"""Benchmark: training-step throughput on one chip (BERT-base + ResNet-50).

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", ...extras}.
vs_baseline = achieved BERT MFU / 0.45 (BASELINE.json north-star of >=45% MFU
on TPU; the reference publishes no training throughput numbers, SURVEY.md §6).
The same line carries the ResNet-50 images/s secondary metric (BASELINE
config 2). See PERF.md for the measured roofline and why each config is
shaped the way it is.

Model FLOPs use the standard 6*N*T transformer estimate (N = matmul-
participating params, embeddings excluded) plus attention terms; ResNet-50
uses 3x the canonical 4.089 GFLOP forward. Peak chip FLOP/s from device kind.
"""
from __future__ import annotations

import json
import time

import jax
import numpy as np


def _peak_flops(device) -> float:
    kind = getattr(device, "device_kind", "cpu").lower()
    table = {
        "v5e": 197e12, "v5litepod": 197e12, "v5 lite": 197e12,
        "v5lite": 197e12, "v5p": 459e12, "v5": 197e12,
        "v4": 275e12, "v3": 123e12, "v2": 45e12, "v6e": 918e12, "v6": 918e12,
    }
    for k, v in table.items():
        if k in kind:
            return v
    return 1e12  # CPU / unknown: nominal


def bench_bert(on_tpu: bool, peak: float):
    import paddle_tpu as pt
    from paddle_tpu.models import transformer

    if on_tpu:
        # best single-chip config from the sweep (PERF.md): seq 128, batch
        # 128 — batch 256 and seq-512/batch-64 exceed the 16G HBM without
        # recompute; flash attention is slower than XLA attention here
        cfg = transformer.TransformerConfig(
            vocab_size=30522, hidden_size=768, num_layers=12, num_heads=12,
            ffn_size=3072, max_position=512, dropout=0.0, use_tp=False)
        # 50 iters: the axon-tunnel host read that ends the timed region
        # costs ~91 ms round-trip (tools/_dispatch.py), so short runs
        # under-report throughput by 91/iters ms per step
        batch, seq_len, iters = 128, 128, 50
    else:  # dev-box sanity run
        cfg = transformer.bert_tiny(use_tp=False)
        batch, seq_len, iters = 8, 32, 5

    main_p, startup = pt.Program(), pt.Program()
    with pt.program_guard(main_p, startup):
        avg_loss, _ = transformer.bert_pretrain(cfg, seq_len=seq_len)
        opt = pt.contrib.mixed_precision.decorate(
            pt.optimizer.Adam(learning_rate=1e-4))  # bf16 matmuls on the MXU
        opt.minimize(avg_loss)

    from __graft_entry__ import _example_feed

    feed = _example_feed(cfg, batch, seq_len)

    exe = pt.Executor()
    with pt.scope_guard(pt.Scope()):
        exe.run(startup)
        # warmup/compile both signatures (with and without fetch)
        exe.run(main_p, feed=feed, fetch_list=[avg_loss])
        exe.run(main_p, feed=feed)
        v = pt.global_scope().find_var("lm_head.b")
        assert v is not None, "drain var lm_head.b missing"
        np.asarray(v)  # drain
        # steady state: async dispatch, drain once at the end — the real
        # trainer pattern (a per-step loss fetch would time the host<->device
        # round trip, not the chip)
        t0 = time.perf_counter()
        for _ in range(iters):
            exe.run(main_p, feed=feed)
        np.asarray(pt.global_scope().find_var("lm_head.b"))
        dt = (time.perf_counter() - t0) / iters
        (loss,) = exe.run(main_p, feed=feed, fetch_list=[avg_loss])
        assert np.isfinite(float(np.asarray(loss)))

    tokens = batch * seq_len
    # matmul-participating parameter count: word/position embedding tables
    # are lookups, not matmuls, so they are EXCLUDED from the 6N term; the
    # lm_head projection (H*V) is a real matmul and stays.
    H, L_, F, V = cfg.hidden_size, cfg.num_layers, cfg.ffn_size, cfg.vocab_size
    n_params = L_ * (4 * H * H + 2 * H * F) + H * V
    step_flops = 6 * n_params * tokens + 12 * L_ * H * seq_len * tokens
    mfu = (step_flops / dt) / peak
    return tokens / dt, mfu


def bench_resnet(on_tpu: bool, peak: float):
    import paddle_tpu as pt
    from paddle_tpu.models import resnet

    batch, iters = (128, 50) if on_tpu else (4, 3)
    size = 224 if on_tpu else 32
    main_p, startup = pt.Program(), pt.Program()
    with pt.program_guard(main_p, startup):
        from paddle_tpu import layers as L

        img = L.data(name="img", shape=[3, size, size], dtype="float32")
        label = L.data(name="label", shape=[1], dtype="int64")
        if on_tpu:
            loss, acc, _ = resnet.resnet50(img, label)
        else:
            loss, acc, _ = resnet.resnet18(img, label, num_classes=10)
        # AMP bf16 with batch_norm GRAY (not blacklisted): the BN kernel
        # keeps its statistics in fp32 internally, so bf16 in/out is safe and
        # halves the HBM traffic of the activation chain. Blacklisted-BN AMP
        # measured 2.7x SLOWER than fp32 (cast walls); gray-BN AMP measures
        # 1.7x FASTER (PERF.md round 3).
        opt = pt.contrib.mixed_precision.decorate(
            pt.optimizer.Momentum(learning_rate=0.1, momentum=0.9))
        opt.minimize(loss)

    rng = np.random.default_rng(0)
    # device-resident feed: re-feeding 77MB of host images per step would
    # time the host link, not the chip (the input pipeline overlaps in a
    # real trainer)
    feed = {
        "img": jax.device_put(
            rng.standard_normal((batch, 3, size, size), dtype=np.float32)),
        "label": jax.device_put(
            rng.integers(0, 1000 if on_tpu else 10,
                         (batch, 1)).astype(np.int32)),
    }
    # drain on a parameter the optimizer writes: its scope value after N
    # steps depends on all N, so one asarray synchronizes the whole run.
    # Derived from the program (a hardcoded name that misses find_var would
    # silently time dispatch only).
    drain = main_p.all_parameters()[-1].name
    exe = pt.Executor()
    with pt.scope_guard(pt.Scope()):
        exe.run(startup)
        exe.run(main_p, feed=feed, fetch_list=[loss])
        exe.run(main_p, feed=feed)
        v = pt.global_scope().find_var(drain)
        assert v is not None, drain
        np.asarray(v)
        t0 = time.perf_counter()
        for _ in range(iters):
            exe.run(main_p, feed=feed)
        np.asarray(pt.global_scope().find_var(drain))
        dt = (time.perf_counter() - t0) / iters
        (lv,) = exe.run(main_p, feed=feed, fetch_list=[loss])
        assert np.isfinite(float(np.asarray(lv)))
    img_s = batch / dt
    mfu = (3 * 4.089e9 * img_s) / peak  # fwd 4.089 GF/img @224, train ~3x
    return img_s, mfu


def main():
    dev = jax.devices()[0]
    on_tpu = dev.platform == "tpu"
    peak = _peak_flops(dev)

    tok_s, bert_mfu = bench_bert(on_tpu, peak)
    img_s, rn_mfu = bench_resnet(on_tpu, peak)

    print(json.dumps({
        "metric": "bert_train_tokens_per_sec_per_chip",
        "value": round(tok_s, 2),
        "unit": "tokens/s",
        "vs_baseline": round(bert_mfu / 0.45, 4),
        "bert_mfu": round(bert_mfu, 4),
        "resnet50_images_per_sec_per_chip": round(img_s, 2),
        "resnet50_mfu": round(rn_mfu, 4),
    }))


if __name__ == "__main__":
    main()
