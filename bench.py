"""Benchmark: BERT-style transformer training-step throughput on one chip.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
vs_baseline = achieved MFU / 0.45 (the BASELINE.json north-star of >=45% MFU
on TPU; the reference publishes no training throughput numbers, SURVEY.md §6).

Model FLOPs use the standard 6*N*T transformer estimate plus attention terms
(12*L*H*S^2*T_layer factor), peak chip FLOP/s from the device kind.
"""
from __future__ import annotations

import json
import time

import jax
import numpy as np


def _peak_flops(device) -> float:
    kind = getattr(device, "device_kind", "cpu").lower()
    table = {
        "v5e": 197e12, "v5litepod": 197e12, "v5 lite": 197e12,
        "v5lite": 197e12, "v5p": 459e12, "v5": 197e12,
        "v4": 275e12, "v3": 123e12, "v2": 45e12, "v6e": 918e12, "v6": 918e12,
    }
    for k, v in table.items():
        if k in kind:
            return v
    return 1e12  # CPU / unknown: nominal


def main():
    import paddle_tpu as pt
    from paddle_tpu.models import transformer

    dev = jax.devices()[0]
    on_tpu = dev.platform == "tpu"

    if on_tpu:
        cfg = transformer.TransformerConfig(
            vocab_size=30522, hidden_size=768, num_layers=12, num_heads=12,
            ffn_size=3072, max_position=512, dropout=0.0, use_tp=False)
        batch, seq_len, iters = 128, 128, 20
    else:  # dev-box sanity run
        cfg = transformer.bert_tiny(use_tp=False)
        batch, seq_len, iters = 8, 32, 5

    main_p, startup = pt.Program(), pt.Program()
    with pt.program_guard(main_p, startup):
        avg_loss, _ = transformer.bert_pretrain(cfg, seq_len=seq_len)
        opt = pt.contrib.mixed_precision.decorate(
            pt.optimizer.Adam(learning_rate=1e-4))  # bf16 matmuls on the MXU
        opt.minimize(avg_loss)

    from __graft_entry__ import _example_feed

    feed = _example_feed(cfg, batch, seq_len)

    exe = pt.Executor()
    with pt.scope_guard(pt.Scope()):
        exe.run(startup)
        # warmup/compile both signatures (with and without fetch)
        exe.run(main_p, feed=feed, fetch_list=[avg_loss])
        exe.run(main_p, feed=feed)
        np.asarray(pt.global_scope().find_var("lm_head.b"))  # drain
        # steady state: async dispatch, drain once at the end — the real
        # trainer pattern (a per-step loss fetch would time the host<->device
        # round trip, not the chip)
        t0 = time.perf_counter()
        for _ in range(iters):
            exe.run(main_p, feed=feed)
        np.asarray(pt.global_scope().find_var("lm_head.b"))
        dt = (time.perf_counter() - t0) / iters
        (loss,) = exe.run(main_p, feed=feed, fetch_list=[avg_loss])
        assert np.isfinite(float(np.asarray(loss)))

    tokens = batch * seq_len
    tok_per_sec = tokens / dt

    # matmul-participating parameter count: word/position embedding tables are
    # lookups, not matmuls, so they are EXCLUDED from the 6N term; the lm_head
    # projection (H*V) is a real matmul and stays.
    H, L_, F, V = cfg.hidden_size, cfg.num_layers, cfg.ffn_size, cfg.vocab_size
    n_params = L_ * (4 * H * H + 2 * H * F) + H * V
    # fwd+bwd matmul flops ~ 6*N*T; attention adds 12*L*H*S^2 per token-pair term
    step_flops = 6 * n_params * tokens + 12 * L_ * H * seq_len * tokens
    mfu = (step_flops / dt) / _peak_flops(dev)

    print(json.dumps({
        "metric": "bert_train_tokens_per_sec_per_chip",
        "value": round(tok_per_sec, 2),
        "unit": "tokens/s",
        "vs_baseline": round(mfu / 0.45, 4),
    }))


if __name__ == "__main__":
    main()
