"""Benchmark: training-step throughput on one chip, all BASELINE workloads.

`--multichip` instead runs the measured multichip scaling campaign
(tools/_mc_ab.py: per-axis dp/tp/pp/sp tokens/s + scaling efficiency with
collective-overlap A/B arms on an 8-device mesh) and prints its artifact
line; see bench_multichip.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", ...extras}.
vs_baseline = MIN over every measured workload's vs_target (BERT / RN50 /
WMT MFU each against the 0.45 north star, DeepFM examples/s against the
declared 60k ex/s floor) — the aggregate moves only when the WORST workload
moves, so no single good number can mask a miss (VERDICT r3 #4). Per-workload
vs_target values ride in the same line. See PERF.md for the measured roofline
and why each config is shaped the way it is.

Model FLOPs use the standard 6*N*T transformer estimate (N = matmul-
participating params, embeddings excluded) plus attention terms; ResNet-50
uses 3x the 8.18 GF forward (2 ops/MAC — the canonical "4.089 GFLOPs" is
GMACs; see PERF.md r4). Peak chip FLOP/s from device kind.
"""
from __future__ import annotations

import json
import time

import jax
import numpy as np

# ResNet-50 @224 forward FLOPs per image at 2 ops/MAC (the canonical
# 4.089e9 figure counts multiply-add as one op). Single source of truth —
# the RN50 tools import this (PERF.md r4 'Finding 0').
RN50_FWD_FLOPS_PER_IMG = 2 * 4.089e9


def _timed_windows(run_once, drain, iters: int, passes: int) -> list:
    """The ONE timing protocol for every bench row: `passes` windows of
    `iters` async-dispatched steps each, ended by a host drain read; the
    per-step seconds of every window are returned so the artifact records
    interference spread and min(windows) is the steady-state estimate."""
    windows = []
    for _ in range(passes):
        t0 = time.perf_counter()
        for _ in range(iters):
            run_once()
        np.asarray(drain())
        windows.append((time.perf_counter() - t0) / iters)
    return windows


def _peak_flops(device) -> float:
    kind = getattr(device, "device_kind", "cpu").lower()
    table = {
        "v5e": 197e12, "v5litepod": 197e12, "v5 lite": 197e12,
        "v5lite": 197e12, "v5p": 459e12, "v5": 197e12,
        "v4": 275e12, "v3": 123e12, "v2": 45e12, "v6e": 918e12, "v6": 918e12,
    }
    for k, v in table.items():
        if k in kind:
            return v
    return 1e12  # CPU / unknown: nominal


def _bert_step_time(cfg, batch, seq_len, iters):
    """Build + time a BERT pretrain step: the ONE timing protocol shared by
    the headline bench and the s512 kernel A/B. 50 iters on TPU: the
    axon-tunnel host read ending the timed region costs ~91 ms round-trip
    (tools/_dispatch.py), so short runs under-report throughput by
    91/iters ms per step. Asserts the final loss is finite — a fast wrong
    kernel must not win a bench row."""
    import paddle_tpu as pt
    from paddle_tpu.models import transformer

    from __graft_entry__ import _example_feed

    main_p, startup = pt.Program(), pt.Program()
    with pt.program_guard(main_p, startup):
        avg_loss, _ = transformer.bert_pretrain(cfg, seq_len=seq_len)
        opt = pt.contrib.mixed_precision.decorate(
            pt.optimizer.Adam(learning_rate=1e-4))  # bf16 matmuls on the MXU
        opt.minimize(avg_loss)
    feed = _example_feed(cfg, batch, seq_len)
    exe = pt.Executor()
    with pt.scope_guard(pt.Scope()):
        exe.run(startup)
        # warmup/compile both signatures (with and without fetch)
        exe.run(main_p, feed=feed, fetch_list=[avg_loss])
        exe.run(main_p, feed=feed)
        v = pt.global_scope().find_var("lm_head.b")
        assert v is not None, "drain var lm_head.b missing"
        np.asarray(v)  # drain
        # steady state: async dispatch, drain once at the end — the real
        # trainer pattern (a per-step loss fetch would time the host<->device
        # round trip, not the chip)
        # best-of-2 passes: machine interference through the shared
        # tunnel is one-sided (observed bimodal WMT throughput, PERF r4),
        # so min-time is the honest steady-state estimate
        windows = _timed_windows(
            lambda: exe.run(main_p, feed=feed),
            lambda: pt.global_scope().find_var("lm_head.b"), iters, 2)
        (loss,) = exe.run(main_p, feed=feed, fetch_list=[avg_loss])
        assert np.isfinite(float(np.asarray(loss)))
    return min(windows), windows


# BERT-base hyperparameters shared by the headline bench and its s512
# kernel-proof row — one source of truth so the two stay comparable
BERT_BASE = dict(vocab_size=30522, hidden_size=768, num_layers=12,
                 num_heads=12, ffn_size=3072, max_position=512,
                 dropout=0.0, use_tp=False)


def bench_bert(on_tpu: bool, peak: float):
    from paddle_tpu.models import transformer

    if on_tpu:
        # throughput-optimal headline config from the sweep (PERF.md): seq
        # 128, batch 128. The s512 regime (fits since r3's bf16 work, and
        # where the Pallas kernel wins) is measured by bench_bert_long.
        cfg = transformer.TransformerConfig(**BERT_BASE)
        batch, seq_len, iters = 128, 128, 50
    else:  # dev-box sanity run
        cfg = transformer.bert_tiny(use_tp=False)
        batch, seq_len, iters = 8, 32, 5

    dt, windows = _bert_step_time(cfg, batch, seq_len, iters)
    tokens = batch * seq_len
    # matmul-participating parameter count: word/position embedding tables
    # are lookups, not matmuls, so they are EXCLUDED from the 6N term; the
    # lm_head projection (H*V) is a real matmul and stays.
    H, L_, F, V = cfg.hidden_size, cfg.num_layers, cfg.ffn_size, cfg.vocab_size
    n_params = L_ * (4 * H * H + 2 * H * F) + H * V
    step_flops = 6 * n_params * tokens + 12 * L_ * H * seq_len * tokens
    mfu = (step_flops / dt) / peak
    return tokens / dt, mfu, [round(tokens / w, 1) for w in windows]


def bench_bert_long(on_tpu: bool):
    """BERT-base at seq 512 — the config class the custom short-seq Pallas
    attention kernel exists for (memory-bound attention: the [B,nh,S,S]
    score residuals dominate). Reports tokens/s with the kernel OFF (XLA
    attention) and ON, proving the kernel earns its keep end-to-end
    (VERDICT r3 #8). Measured r4: ON wins ~9% (125-127k vs 115-116k)."""
    from paddle_tpu.models import transformer

    if on_tpu:
        seq, batch, iters = 512, 64, 50
        base = BERT_BASE
    else:
        # dev-box note: off-TPU the Pallas kernel never engages (the
        # dispatch gate is TPU-only), so both arms measure the reference
        # path — the row is a smoke test there, and main() excludes it
        # from the vs_target gate off-TPU for exactly that reason
        seq, batch, iters = 128, 4, 3
        base = dict(vocab_size=256, hidden_size=64, num_layers=2,
                    num_heads=4, ffn_size=128, max_position=128,
                    dropout=0.0, use_tp=False)

    out = {}
    for flash in (False, True):
        cfg = transformer.TransformerConfig(use_flash_attention=flash,
                                            **base)
        dt, _ = _bert_step_time(cfg, batch, seq, iters)
        out["pallas" if flash else "xla"] = batch * seq / dt
    return out


def bench_bert_short(on_tpu: bool):
    """BERT at the HEADLINE short sequence — the regime where the bundled
    flash kernel measured 42-52% SLOWER than XLA (PERF.md r4/r5) and the
    ISSUE 9 seq<=128 kernel (pallas_kernels/short_attention.py) now fields
    a custom arm. Interleaved end-to-end A/B on the bench step protocol:
    the same config timed with the attention dispatch forced to XLA and
    forced to pallas_short128 (FLAGS_attention_force_backend; a force the
    platform cannot honor degrades to XLA at dispatch, recorded via
    `engaged`). tools/gate.py fails an artifact whose ENGAGED kernel arm
    loses beyond the interference band."""
    from paddle_tpu import flags as pt_flags
    from paddle_tpu.models import transformer
    from paddle_tpu.ops.pallas_kernels import short_attention as _s128
    from paddle_tpu.ops.pallas_kernels import workbench as _wb
    from tools import _timing

    if on_tpu:
        cfg = transformer.TransformerConfig(**BERT_BASE)
        batch, seq, iters = 128, 128, 50
    else:
        cfg = transformer.bert_tiny(use_tp=False)
        batch, seq, iters = 8, 32, 3

    out = {}
    saved = pt_flags.get_flag("attention_force_backend")
    try:
        # interleaved passes (ABAB): sequential per-arm measurement aliases
        # box drift into the margin (the PERF.md r9 lesson)
        tok = {}
        for rep in range(2):
            for arm in ("xla", "pallas_short128"):
                pt_flags.set_flags({"attention_force_backend": arm})
                dt, _ = _bert_step_time(cfg, batch, seq, iters)
                tok.setdefault(arm, []).append(batch * seq / dt)
        out["xla_tok_s"] = round(max(tok["xla"]), 1)
        out["pallas_tok_s"] = round(max(tok["pallas_short128"]), 1)
        out["windows_tok_s"] = {a: [round(v, 1) for v in vs]
                                for a, vs in tok.items()}
    finally:
        pt_flags.set_flags({"attention_force_backend": saved})
    dh = cfg.hidden_size // cfg.num_heads
    q_shape = (batch, cfg.num_heads, seq, dh)
    out["engaged"] = bool(
        _wb.runnable(_s128)
        and _s128.short128_supported(q_shape, q_shape, None))
    band = max(_timing.DEFAULT_BAND,
               _timing.interference_band(tok["xla"]),
               _timing.interference_band(tok["pallas_short128"]))
    out["band"] = round(band, 4)
    out["verdict"] = _timing.ab_verdict(
        1.0 / max(tok["xla"]), 1.0 / max(tok["pallas_short128"]), band)
    out["config"] = (f"base b{batch} s{seq} AMP Adam" if on_tpu
                     else f"tiny b{batch} s{seq}")
    return out


def bench_resnet(on_tpu: bool, peak: float):
    """ResNet-50 row with an in-artifact lever A/B (PERF.md r6/r10): the
    step is timed three ways — conv levers OFF (direct conv + two-pass BN,
    the r5 configuration), ON (FLAGS_conv_implicit_gemm auto + fused
    one-pass BN statistics), and ON + the fused Pallas epilogue forced
    (FLAGS_pallas_epilogue=on: the ISSUE 9 normalize+affine+act+residual
    kernel carries every BN apply tail it can run) — and the headline takes
    the fastest arm, with all recorded so every round re-measures the
    levers end-to-end (the keep-it-honest protocol; chained microbenches
    are poisoned here, PERF.md r5). The epilogue arm also records whether
    its kernel could actually engage (`engaged`: off-TPU without the
    interpreter the dispatch degrades to XLA and the arm measures pure
    rewrite overhead) and its keep/retire verdict vs the levered arm on
    the tools/_timing.py band — tools/gate.py fails an artifact whose
    ENGAGED kernel arm loses beyond the band."""
    from paddle_tpu import flags as pt_flags
    from paddle_tpu.ops.pallas_kernels import epilogue as _ep
    from paddle_tpu.ops.pallas_kernels import workbench as _wb
    from tools import _timing

    arms = {}
    saved = {k: pt_flags.get_flag(k)
             for k in ("conv_implicit_gemm", "bn_fuse_stats",
                       "pallas_epilogue")}
    try:
        for name, (igemm, fuse, epi) in (
                ("baseline", ("off", False, "off")),
                ("levered", ("auto", True, "off")),
                ("epilogue", ("auto", True, "on"))):
            pt_flags.set_flags({"conv_implicit_gemm": igemm,
                                "bn_fuse_stats": fuse,
                                "pallas_epilogue": epi})
            arms[name] = _resnet_arm(on_tpu, peak)
    finally:
        pt_flags.set_flags(saved)
    best = max(arms, key=lambda k: arms[k][0])
    img_s, mfu, windows = arms[best]
    ab = {f"{k}_img_s": round(v[0], 1) for k, v in arms.items()}
    ab["winner"] = best
    # the epilogue kernel's end-to-end verdict vs its own baseline (the
    # levered arm: identical levers, kernel off) — per-step seconds feed
    # the shared band protocol
    eng = _wb.runnable(_ep)
    # interference_band is scale-invariant, so the recorded img/s windows
    # feed it directly
    band = max(_timing.DEFAULT_BAND,
               _timing.interference_band(arms["levered"][2]),
               _timing.interference_band(arms["epilogue"][2]))
    ab["epilogue_engaged"] = eng
    ab["epilogue_band"] = round(band, 4)
    ab["epilogue_verdict"] = _timing.ab_verdict(
        1.0 / arms["levered"][0], 1.0 / arms["epilogue"][0], band)
    return img_s, mfu, windows, ab


def _resnet_arm(on_tpu: bool, peak: float):
    import paddle_tpu as pt
    from paddle_tpu.models import resnet

    batch, iters = (128, 50) if on_tpu else (4, 3)
    size = 224 if on_tpu else 32
    main_p, startup = pt.Program(), pt.Program()
    with pt.program_guard(main_p, startup), pt.unique_name.guard():
        from paddle_tpu import layers as L

        img_shape = [size, size, 3] if on_tpu else [3, size, size]
        img = L.data(name="img", shape=img_shape, dtype="float32")
        label = L.data(name="label", shape=[1], dtype="int64")
        if on_tpu:
            # NHWC + s2d stem: channels-last end-to-end plus the exact
            # space-to-depth refactoring of the 7x7-s2 stem (see
            # models/resnet.py fold_stem_to_s2d) — PERF.md r5
            loss, acc, _ = resnet.resnet50(img, label, s2d_stem=True,
                                           data_format="NHWC")
        else:
            loss, acc, _ = resnet.resnet18(img, label, num_classes=10)
        # AMP bf16 with batch_norm GRAY (not blacklisted): the BN kernel
        # keeps its statistics in fp32 internally, so bf16 in/out is safe and
        # halves the HBM traffic of the activation chain. Blacklisted-BN AMP
        # measured 2.7x SLOWER than fp32 (cast walls); gray-BN AMP measures
        # 1.7x FASTER (PERF.md round 3).
        opt = pt.contrib.mixed_precision.decorate(
            pt.optimizer.Momentum(learning_rate=0.1, momentum=0.9))
        opt.minimize(loss)

    rng = np.random.default_rng(0)
    # device-resident feed: re-feeding 77MB of host images per step would
    # time the host link, not the chip (the input pipeline overlaps in a
    # real trainer)
    feed = {
        "img": jax.device_put(
            rng.standard_normal(
                (batch, size, size, 3) if on_tpu else (batch, 3, size, size),
                dtype=np.float32)),
        "label": jax.device_put(
            rng.integers(0, 1000 if on_tpu else 10,
                         (batch, 1)).astype(np.int32)),
    }
    # drain on a parameter the optimizer writes: its scope value after N
    # steps depends on all N, so one asarray synchronizes the whole run.
    # Derived from the program (a hardcoded name that misses find_var would
    # silently time dispatch only).
    drain = main_p.all_parameters()[-1].name
    exe = pt.Executor()
    with pt.scope_guard(pt.Scope()):
        exe.run(startup)
        exe.run(main_p, feed=feed, fetch_list=[loss])
        exe.run(main_p, feed=feed)
        v = pt.global_scope().find_var(drain)
        assert v is not None, drain
        np.asarray(v)
        # 3 recorded windows: RN50 is the gate row, so its artifact
        # carries the same interference forensics as WMT/DeepFM
        windows = _timed_windows(
            lambda: exe.run(main_p, feed=feed),
            lambda: pt.global_scope().find_var(drain), iters,
            3 if on_tpu else 2)
        dt = min(windows)
        (lv,) = exe.run(main_p, feed=feed, fetch_list=[loss])
        assert np.isfinite(float(np.asarray(lv)))
    img_s = batch / dt
    rn_windows = [round(batch / w, 1) for w in windows]
    # FLOP convention fix (r4): the canonical "4.089 GFLOPs" for RN50@224
    # counts a multiply-add as ONE op (it is 4.089 GMACs — exact per-layer
    # enumeration in tools/_rn_stagecost.py gives 8.17 GF/img at 2 ops/MAC).
    # The 197e12 chip peak and the transformer 6N formula both count 2 ops
    # per MAC, so the model FLOPs must too — r2/r3 reported RN50 MFU at
    # half its true value (PERF.md r4).
    mfu = (3 * RN50_FWD_FLOPS_PER_IMG * img_s) / peak  # train ~3x fwd
    return img_s, mfu, rn_windows


def bench_wmt(on_tpu: bool, peak: float):
    """Transformer-base WMT en-de (BASELINE config 3): tokens/s counts
    src+tgt tokens per sentence pair; MFU from explicit encoder/decoder/proj
    matmul FLOPs (embedd lookups excluded) + attention terms."""
    import paddle_tpu as pt
    from paddle_tpu.models import transformer

    if on_tpu:
        cfg = transformer.TransformerConfig(
            vocab_size=37000, hidden_size=512, num_layers=6, num_heads=8,
            ffn_size=2048, max_position=256, dropout=0.0, use_tp=False)
        batch, src_len, tgt_len, iters = 128, 128, 128, 50
    else:
        cfg = transformer.TransformerConfig(
            vocab_size=256, hidden_size=64, num_layers=2, num_heads=4,
            ffn_size=128, max_position=64, dropout=0.0, use_tp=False)
        batch, src_len, tgt_len, iters = 8, 16, 16, 3

    main_p, startup = pt.Program(), pt.Program()
    with pt.program_guard(main_p, startup):
        avg_loss, _ = transformer.transformer_wmt(
            cfg, src_len=src_len, tgt_len=tgt_len)
        opt = pt.contrib.mixed_precision.decorate(
            pt.optimizer.Adam(learning_rate=1e-4))
        opt.minimize(avg_loss)

    rng = np.random.default_rng(0)
    feed = {
        "src_ids": rng.integers(0, cfg.vocab_size, (batch, src_len)).astype(np.int32),
        "src_pos": np.tile(np.arange(src_len, dtype=np.int32), (batch, 1)),
        "tgt_ids": rng.integers(0, cfg.vocab_size, (batch, tgt_len)).astype(np.int32),
        "tgt_pos": np.tile(np.arange(tgt_len, dtype=np.int32), (batch, 1)),
        "tgt_label": rng.integers(0, cfg.vocab_size, (batch, tgt_len)).astype(np.int32),
        "tgt_weight": np.ones((batch, tgt_len), np.float32),
    }
    drain = "proj.b"
    exe = pt.Executor()
    with pt.scope_guard(pt.Scope()):
        exe.run(startup)
        exe.run(main_p, feed=feed, fetch_list=[avg_loss])
        exe.run(main_p, feed=feed)
        assert pt.global_scope().find_var(drain) is not None, drain
        np.asarray(pt.global_scope().find_var(drain))
        # 3 windows with the spread recorded (VERDICT r4 #9: the WMT margin
        # is one interference burst from red, and its bimodality is
        # documented — more, shorter windows dodge single bursts and the
        # recorded spread distinguishes outliers from regressions)
        windows = _timed_windows(
            lambda: exe.run(main_p, feed=feed),
            lambda: pt.global_scope().find_var(drain), iters,
            3 if on_tpu else 2)
        dt = min(windows)
        (lv,) = exe.run(main_p, feed=feed, fetch_list=[avg_loss])
        assert np.isfinite(float(np.asarray(lv)))

    H, L_, F, V = cfg.hidden_size, cfg.num_layers, cfg.ffn_size, cfg.vocab_size
    t_src, t_tgt = batch * src_len, batch * tgt_len
    enc_params = L_ * (4 * H * H + 2 * H * F)
    dec_params = L_ * (8 * H * H + 2 * H * F)
    step_flops = (6 * enc_params * t_src + 6 * (dec_params + H * V) * t_tgt
                  + 12 * L_ * H * (src_len * t_src          # enc self
                                   + tgt_len * t_tgt        # dec self (causal)
                                   + src_len * t_tgt))      # cross
    mfu = (step_flops / dt) / peak
    wmt_windows = [round((t_src + t_tgt) / w, 1) for w in windows]
    return (t_src + t_tgt) / dt, mfu, wmt_windows


def bench_deepfm(on_tpu: bool):
    """DeepFM CTR through exe.train_from_dataset (BASELINE config 5): the
    trainer-runtime path — QueueDataset file parsing (native C MultiSlot
    parser) feeding sparse-embedding training. Metric: examples/s end-to-end
    including the host data pipeline (that IS the workload for CTR)."""
    import os
    import tempfile

    import paddle_tpu as pt
    from paddle_tpu.models import deepfm

    n_fields, n_dense = 26, 13
    if on_tpu:
        vocab, batch, lines_per_file, n_files = 100_000, 2048, 16384, 8
    else:
        vocab, batch, lines_per_file, n_files = 1000, 256, 1024, 2

    main_p, startup = pt.Program(), pt.Program()
    with pt.program_guard(main_p, startup):
        avg_loss, _, feed_names = deepfm.deepfm(
            n_fields=n_fields, n_dense=n_dense, vocab_size=vocab)
        # SGD: the is_sparse embeddings emit SelectedRows grads (the pserver
        # wire format), which the sgd op applies as true row updates
        pt.optimizer.SGD(learning_rate=1e-3).minimize(avg_loss)
        block = main_p.global_block
        use_vars = [block.var(n) for n in feed_names]

    rng = np.random.default_rng(0)
    tmp = tempfile.mkdtemp(prefix="deepfm_bench_")
    files = []
    for fi in range(n_files):
        p = os.path.join(tmp, f"part-{fi}")
        with open(p, "w") as f:
            for _ in range(lines_per_file):
                ids = rng.integers(0, vocab, n_fields)
                dense = rng.random(n_dense).round(4)
                lbl = rng.integers(0, 2)
                f.write(f"{n_fields} {' '.join(map(str, ids))} "
                        f"{n_dense} {' '.join(map(str, dense))} 1 {lbl}\n")
        files.append(p)

    ds = pt.DatasetFactory().create_dataset("QueueDataset")
    ds.set_batch_size(batch)
    # 4 ingest threads (reference MultiSlotDataFeed runs many): at the
    # healthy-box 52 ms/file parse cost, 2 threads leave ~200 ms of an
    # ~1.9 s pass unhidden; 4 halve it
    ds.set_thread(4)
    ds.set_use_var(use_vars)
    ds.set_filelist(files)

    exe = pt.Executor()
    with pt.scope_guard(pt.Scope()):
        exe.run(startup)
        # warmup pass compiles; timed pass measures steady-state. Drain on a
        # trained parameter before AND after the timed pass — exe.run
        # dispatch is async, so the clock must not stop with device work
        # still in flight (same discipline as the other benches)
        drain = main_p.all_parameters()[-1].name
        assert pt.global_scope().find_var(drain) is not None, drain
        exe.train_from_dataset(main_p, ds, print_period=10**9)
        np.asarray(pt.global_scope().find_var(drain))
        # >=5 timed windows with the full spread recorded (VERDICT r4 #2:
        # a single window on a shared box cannot distinguish a regression
        # from an interference outlier). Best window is the steady-state
        # estimate (interference is one-sided); the spread ships in the
        # bench JSON so the artifact itself shows the measurement quality.
        windows = []
        for _ in range(5 if on_tpu else 2):
            t0 = time.perf_counter()
            exe.train_from_dataset(main_p, ds, print_period=10**9)
            np.asarray(pt.global_scope().find_var(drain))
            windows.append(time.perf_counter() - t0)
        dt = min(windows)
        windows_ex_s = [round(n_files * lines_per_file / w, 1)
                        for w in windows]
        # device-path reference: the same compiled step fed one resident
        # batch — no host parse, no transfer. e2e/device is the pipelined-
        # execution efficiency the async feed/dispatch subsystem is
        # accountable for (ISSUE 2 target >= 0.9; tools/gate.py flags it)
        dev_feed = {
            "sparse_ids": jax.device_put(
                rng.integers(0, vocab, (batch, n_fields)).astype(np.int32)),
            "dense_x": jax.device_put(
                rng.random((batch, n_dense)).astype(np.float32)),
            "label": jax.device_put(
                rng.integers(0, 2, (batch, 1)).astype(np.float32)),
        }
        exe.run(main_p, feed=dev_feed)  # compile this signature
        np.asarray(pt.global_scope().find_var(drain))
        dev_windows = _timed_windows(
            lambda: exe.run(main_p, feed=dev_feed),
            lambda: pt.global_scope().find_var(drain),
            50 if on_tpu else 5, 3 if on_tpu else 2)
        device_ex_s = batch / min(dev_windows)
        (lv,) = exe.run(main_p, feed=dev_feed, fetch_list=[avg_loss])
        assert np.isfinite(float(np.asarray(lv)))

    # health-sentinel overhead: the SAME device-path step with the in-graph
    # numeric guard compiled in (FLAGS_guard_numerics). The sentinel rides
    # the step's own outputs (a [4] vector + [2] EMA state), so the measured
    # cost should be noise; tools/gate.py flags > 2% against this baseline
    from paddle_tpu import flags as pt_flags

    old_guard = pt_flags.get_flag("guard_numerics")
    pt_flags.set_flags({"guard_numerics": True})
    try:
        g_main, g_startup = pt.Program(), pt.Program()
        with pt.program_guard(g_main, g_startup):
            with pt.unique_name.guard():
                g_loss, _, _ = deepfm.deepfm(
                    n_fields=n_fields, n_dense=n_dense, vocab_size=vocab)
                pt.optimizer.SGD(learning_rate=1e-3).minimize(g_loss)
        with pt.scope_guard(pt.Scope()):
            exe.run(g_startup)
            g_drain = g_main.all_parameters()[-1].name
            exe.run(g_main, feed=dev_feed)  # compile
            np.asarray(pt.global_scope().find_var(g_drain))
            g_windows = _timed_windows(
                lambda: exe.run(g_main, feed=dev_feed),
                lambda: pt.global_scope().find_var(g_drain),
                50 if on_tpu else 5, 3 if on_tpu else 2)
        guarded_ex_s = batch / min(g_windows)
        guard_overhead_pct = max(0.0,
                                 (1.0 - guarded_ex_s / device_ex_s) * 100.0)
    finally:
        pt_flags.set_flags({"guard_numerics": old_guard})

    for p in files:
        os.unlink(p)
    os.rmdir(tmp)
    return (n_files * lines_per_file / dt, windows_ex_s, device_ex_s,
            guard_overhead_pct)


def _tiered_parity(steps: int = 12):
    """Small-scale parameter-parity oracle for the tiered path (ISSUE 10):
    same model, same inits, same batches — N SGD steps through a 256-slot
    cache over a 512-row table (evictions + write-backs fire constantly)
    vs the dense-lookup program. Returns the max |param| drift; tools/
    gate.py hard-fails above 1e-4 (measured: float associativity only)."""
    import paddle_tpu as pt
    from paddle_tpu import flags as pt_flags
    from paddle_tpu import layers as L
    from paddle_tpu.layers import tensor as T
    from paddle_tpu.param_attr import ParamAttr

    VOCAB, DIM, FIELDS, BATCH = 512, 8, 6, 32

    def build():
        ids = T.data(name="ids", shape=[FIELDS], dtype="int64")
        label = T.data(name="label", shape=[1], dtype="float32")
        emb = L.embedding(ids, size=[VOCAB, DIM], is_sparse=True,
                          param_attr=ParamAttr(name="ptbl"))
        pooled = L.reduce_sum(emb, dim=1)
        logit = L.fc(pooled, size=1, param_attr=ParamAttr(name="pw"),
                     bias_attr=ParamAttr(name="pb"))
        return L.mean(L.sigmoid_cross_entropy_with_logits(logit, label))

    def feed(s):
        rng = np.random.default_rng(500 + s)
        return {"ids": rng.integers(0, VOCAB,
                                    (BATCH, FIELDS)).astype(np.int64),
                "label": rng.integers(0, 2, (BATCH, 1)).astype(np.float32)}

    def minimized(budget, slots):
        m, st = pt.Program(), pt.Program()
        m.random_seed = st.random_seed = 7
        pt_flags.set_flags({"emb_hbm_budget_mb": budget,
                            "emb_cache_slots": slots})
        with pt.program_guard(m, st), pt.unique_name.guard():
            loss = build()
            pt.optimizer.SGD(0.1).minimize(loss)
        return m, st, loss

    saved = {k: pt_flags.get_flag(k)
             for k in ("emb_hbm_budget_mb", "emb_cache_slots")}
    try:
        exe = pt.Executor()
        main_o, startup_o, loss_o = minimized(0.0, 0)
        sc_o = pt.Scope()
        with pt.scope_guard(sc_o):
            exe.run(startup_o)
            init = {n: np.array(np.asarray(sc_o.find_var(n)))
                    for n in ("ptbl", "pw", "pb")}
            for s in range(steps):
                exe.run(main_o, feed=feed(s), fetch_list=[loss_o])
            oracle = {n: np.asarray(sc_o.find_var(n))
                      for n in ("ptbl", "pw", "pb")}

        main_t, startup_t, loss_t = minimized(0.001, 256)
        eng = main_t._tiered_engine
        sc_t = pt.Scope()
        with pt.scope_guard(sc_t):
            exe.run(startup_t)
            eng.tables["ptbl"].host.load_rows(np.arange(VOCAB),
                                              init["ptbl"])
            eng.tables["ptbl"].host.clear_dirty()
            sc_t.set_var("pw", jax.device_put(init["pw"]))
            sc_t.set_var("pb", jax.device_put(init["pb"]))
            for s in range(steps):
                exe.run(main_t, feed=feed(s), fetch_list=[loss_t])
            exe.wait()
            table_t = eng.export_dense("ptbl", sc_t)
            drift = max(
                float(np.abs(table_t - oracle["ptbl"]).max()),
                float(np.abs(np.asarray(sc_t.find_var("pw"))
                             - oracle["pw"]).max()),
                float(np.abs(np.asarray(sc_t.find_var("pb"))
                             - oracle["pb"]).max()))
            st = eng.stats("ptbl")
        assert st["evictions"] > 0, "parity run never evicted — not tiered"
        return drift
    finally:
        pt_flags.set_flags(saved)


def bench_deepfm_giant(on_tpu: bool):
    """DeepFM with an embedding table provably exceeding the configured HBM
    budget (ISSUE 10): the minimize()-time rewrite puts fm_emb on the
    two-tier path — host shards + hot-ID cache — and the feed pipeline
    resolves misses off the step. Metrics: end-to-end examples/s through
    train_from_dataset (zipf-skewed ids, the CTR regime the hot-ID cache
    exists for), cache hit rate / evictions / write-backs, host-tier bytes
    vs the budget, and the small-scale parameter-parity drift vs the
    dense-lookup oracle that tools/gate.py hard-fails on."""
    import os
    import tempfile

    import paddle_tpu as pt
    from paddle_tpu import flags as pt_flags
    from paddle_tpu.models import deepfm

    n_fields, n_dense = 26, 13
    if on_tpu:
        # fm_emb = 10M x 16 fp32 = 640 MB against a 64 MB budget: the table
        # provably exceeds the cache tier by 10x
        vocab, batch, lines_per_file, n_files = 10_000_000, 2048, 16384, 4
        budget_mb = 64.0
    else:
        # CPU: 200k x 16 fp32 = 12.8 MB against a 2 MB budget (6.4x over)
        vocab, batch, lines_per_file, n_files = 200_000, 256, 1024, 2
        budget_mb = 2.0

    saved = {k: pt_flags.get_flag(k)
             for k in ("emb_hbm_budget_mb", "emb_cache_slots")}
    pt_flags.set_flags({"emb_hbm_budget_mb": budget_mb,
                        "emb_cache_slots": 0})
    try:
        main_p, startup = pt.Program(), pt.Program()
        with pt.program_guard(main_p, startup), pt.unique_name.guard():
            avg_loss, _, feed_names = deepfm.deepfm(
                n_fields=n_fields, n_dense=n_dense, vocab_size=vocab)
            pt.optimizer.SGD(learning_rate=1e-3).minimize(avg_loss)
            block = main_p.global_block
            use_vars = [block.var(n) for n in feed_names]
        engine = main_p._tiered_engine
        assert engine is not None and "fm_emb" in engine.tables, \
            "fm_emb did not tier — check FLAGS_emb_hbm_budget_mb"
        ts = engine.tables["fm_emb"]

        rng = np.random.default_rng(0)
        tmp = tempfile.mkdtemp(prefix="deepfm_giant_")
        files = []
        for fi in range(n_files):
            p = os.path.join(tmp, f"part-{fi}")
            with open(p, "w") as f:
                for _ in range(lines_per_file):
                    # zipf-skewed ids: the production CTR distribution the
                    # frequency-based hot-ID admission exists for
                    ids = (rng.zipf(1.5, n_fields) - 1) % vocab
                    dense = rng.random(n_dense).round(4)
                    lbl = rng.integers(0, 2)
                    f.write(f"{n_fields} {' '.join(map(str, ids))} "
                            f"{n_dense} {' '.join(map(str, dense))} "
                            f"1 {lbl}\n")
            files.append(p)

        ds = pt.DatasetFactory().create_dataset("QueueDataset")
        ds.set_batch_size(batch)
        ds.set_thread(4)
        ds.set_use_var(use_vars)
        ds.set_filelist(files)

        exe = pt.Executor()
        with pt.scope_guard(pt.Scope()):
            exe.run(startup)
            drain = main_p.all_parameters()[-1].name
            exe.train_from_dataset(main_p, ds, print_period=10**9)
            np.asarray(pt.global_scope().find_var(drain))
            windows = []
            for _ in range(5 if on_tpu else 2):
                t0 = time.perf_counter()
                exe.train_from_dataset(main_p, ds, print_period=10**9)
                np.asarray(pt.global_scope().find_var(drain))
                windows.append(time.perf_counter() - t0)
            engine.flush_all()
            stats = engine.stats("fm_emb")
            (lv,) = exe.run(main_p, feed={
                "sparse_ids": (rng.zipf(1.5, (batch, n_fields)) - 1)
                % vocab,
                "dense_x": rng.random((batch, n_dense)).astype(np.float32),
                "label": rng.integers(0, 2, (batch, 1)).astype(np.float32),
            }, fetch_list=[avg_loss])
            assert np.isfinite(float(np.asarray(lv)))

        dt = min(windows)
        n_examples = n_files * lines_per_file
        for p in files:
            os.unlink(p)
        os.rmdir(tmp)
    finally:
        pt_flags.set_flags(saved)

    parity = _tiered_parity()
    return {
        "examples_per_sec": round(n_examples / dt, 2),
        "windows_ex_s": [round(n_examples / w, 1) for w in windows],
        "cache_hit_rate": stats["hit_rate"],
        "evictions": stats.get("evictions", 0),
        "writebacks": stats.get("writebacks", 0),
        "cache_slots": stats["slots"],
        "prefetch_rows": stats["prefetch_rows"],
        "host_tier_bytes": int(sum(
            t.host.nbytes for t in engine.tables.values())),
        "table_bytes": int(ts.host.nbytes),
        "hbm_budget_mb": budget_mb,
        "cache_bytes": int((ts.slots + 1) * ts.host.dim
                           * ts.host.dtype.itemsize),
        "parity_max_abs_diff": parity,
        "config": (f"v{vocab // 10**6}M b{batch} f{n_fields} zipf1.5 "
                   f"budget{budget_mb:g}MB" if on_tpu
                   else f"v200k b{batch} f{n_fields} zipf1.5 "
                        f"budget{budget_mb:g}MB"),
    }


def bench_serving(on_tpu: bool):
    """Served-load row (ISSUE 7): synthetic open-loop arrivals against a
    small bert-decoder through the paged-KV continuous-batching engine
    (paddle_tpu/serving/). The metrics ARE the serving SLOs: served
    tokens/s, p50/p99 request latency, first-token latency, KV-pool
    occupancy — and the zero-leak page count tools/gate.py hard-fails on.
    Open-loop (arrivals never wait for the system) because a closed loop
    self-throttles and hides queueing collapse; the workload is seeded so
    every round replays the same arrival trace."""
    from paddle_tpu.serving import DecoderConfig, ServingEngine, decoder_tiny
    from tools import _serve_ab

    if on_tpu:
        cfg = DecoderConfig(vocab_size=30522, hidden_size=512, num_layers=6,
                            num_heads=8, ffn_size=2048, max_position=1024)
        engine = ServingEngine(cfg, page_size=16, pool_pages=2048,
                               max_inflight=16)
        wl = _serve_ab.synth_workload(64, cfg.vocab_size, seed=0,
                                      prompt_lens=(16, 128), max_new=32,
                                      rate=32.0)
    else:
        cfg = decoder_tiny()
        engine = ServingEngine(cfg, page_size=4, pool_pages=64,
                               max_inflight=4)
        wl = _serve_ab.synth_workload(10, cfg.vocab_size, seed=0,
                                      prompt_lens=(4, 16), max_new=4,
                                      rate=16.0)
    out = _serve_ab.run_open_loop(engine, wl)
    out["config"] = ("dec6x512 b16 pool2048x16 open-loop r32" if on_tpu
                     else "tiny pool64x4 open-loop r16")
    out["shared_prefix"] = _bench_shared_prefix(on_tpu)
    # ISSUE 14: overload resilience — the shared-prefix mix at 10x the r8
    # rate against shed floors + the degradation ladder, plus the same
    # trace under a bounded serving fault plan; gate.py enforces goodput
    # >= 0.7x the unloaded arm and zero leaks in every arm
    out["overload"] = _serve_ab.overload_block(on_tpu)
    return out


def _bench_shared_prefix(on_tpu: bool):
    """The ISSUE 11 multi-tenant A/B: a zipf shared-system-prompt mix at
    10x the r8 request rate through three arms over the SAME seeded trace —
    the PR 7 baseline (no cache, no speculation), copy-on-write prefix
    caching, and prefix caching + speculative decoding (draft k=4, exact
    under greedy). Steady-state, compile-free measurement
    (tools/_serve_ab.run_open_loop warmup protocol). tools/gate.py
    hard-fails page/refcount leaks in ANY arm and a prefix-cache hit rate
    below floor."""
    from paddle_tpu.serving import ServingEngine
    from tools import _serve_ab

    cfg, _, user_lens = _serve_ab.ab_config(on_tpu, shared_prefix=True)
    import paddle_tpu as pt

    ps = int(pt.flags.get_flag("serving_page_size"))
    if on_tpu:
        n_req, max_new, rate, sys_len = 64, 16, 640.0, 8 * ps
    else:
        n_req, max_new, rate, sys_len = 32, 4, 320.0, 6 * ps
    wl = _serve_ab.synth_shared_prefix_workload(
        n_req, cfg.vocab_size, seed=0, n_sys_prompts=8, sys_len=sys_len,
        user_lens=user_lens, max_new=max_new, rate=rate)
    arms = {}
    for name, prefix, draft in (("baseline", False, 0),
                                ("prefix", True, 0),
                                ("prefix_spec", True, 4)):
        eng = ServingEngine(cfg, prefix_cache=prefix, draft_k=draft)
        r = _serve_ab.run_open_loop(eng, wl, warmup=True)
        arms[name] = {k: r[k] for k in (
            "served_tokens_per_sec", "prefill_tokens_computed",
            "prefix_cache_hit_rate", "spec_accept_rate",
            "tokens_per_decode_step", "kv_pages_leaked", "refcount_leaks",
            "cow_copies")}
        arms[name]["request_latency_p50_ms"] = r["request_latency"].get(
            "p50_ms")
    base = arms["baseline"]["served_tokens_per_sec"]
    return {
        "arms": arms,
        "rate_req_s": rate,
        "vs_baseline_tok_s": round(
            arms["prefix"]["served_tokens_per_sec"] / max(base, 1e-9), 3),
        "prefill_tokens_saved": (
            arms["baseline"]["prefill_tokens_computed"]
            - arms["prefix"]["prefill_tokens_computed"]),
        "config": (f"shared-prefix zipf1.2 sys{sys_len} r{rate:g} "
                   f"n{n_req}"),
    }


def bench_telemetry(on_tpu: bool):
    """Telemetry-layer overhead A/B (ISSUE 13): the SAME tiny device-path
    async-dispatch step timed with FLAGS_obs_enable on vs off over the
    shared `_timed_windows` protocol. The flag gates exactly what the
    unified registry added over the PR 2 stage accumulators (histograms,
    events, spans, exporter sinks) — counters/gauges stay on in both arms —
    so the delta IS the layer's marginal cost on the hottest instrumented
    loop (run_async dispatch + window drain + per-step latency histogram).
    tools/gate.py --obs fails the artifact above 2%."""
    import paddle_tpu as pt
    from paddle_tpu import flags as pt_flags
    from paddle_tpu import layers as L
    from paddle_tpu.layers import tensor as T

    rng = np.random.default_rng(13)
    batch, dim = (4096, 256) if on_tpu else (256, 64)
    main_p, startup = pt.Program(), pt.Program()
    with pt.program_guard(main_p, startup), pt.unique_name.guard():
        x = T.data(name="obs_x", shape=[dim], dtype="float32")
        label = T.data(name="obs_y", shape=[1], dtype="float32")
        h = L.fc(x, size=dim, act="relu")
        logit = L.fc(h, size=1)
        loss = L.mean(L.sigmoid_cross_entropy_with_logits(logit, label))
        pt.optimizer.SGD(learning_rate=1e-3).minimize(loss)
    feed = {"obs_x": jax.device_put(
                rng.random((batch, dim), dtype=np.float32)),
            "obs_y": jax.device_put(
                rng.integers(0, 2, (batch, 1)).astype(np.float32))}
    exe = pt.Executor()
    iters, passes = (50, 3) if on_tpu else (20, 3)
    steps_per_s = {}
    old = pt_flags.get_flag("obs_enable")
    try:
        with pt.scope_guard(pt.Scope()):
            exe.run(startup)
            drain_name = main_p.all_parameters()[-1].name
            exe.run(main_p, feed=feed)  # compile once; both arms share it
            np.asarray(pt.global_scope().find_var(drain_name))

            def run_once():
                exe.run_async(main_p, feed=feed)

            def drain():
                exe.wait()
                return pt.global_scope().find_var(drain_name)

            for arm, flag_val in (("off", False), ("on", True)):
                pt_flags.set_flags({"obs_enable": flag_val})
                windows = _timed_windows(run_once, drain, iters, passes)
                steps_per_s[arm] = batch / min(windows)
    finally:
        pt_flags.set_flags({"obs_enable": old})
    overhead_pct = max(0.0,
                       (1.0 - steps_per_s["on"] / steps_per_s["off"]) * 100.0)
    return {
        "obs_overhead_pct": round(overhead_pct, 2),
        "examples_per_sec_obs_on": round(steps_per_s["on"], 2),
        "examples_per_sec_obs_off": round(steps_per_s["off"], 2),
        "config": f"fc{dim}x2 b{batch} async-dispatch a/b",
    }


def _tuned(tuner_stats: dict, name: str, fn, *args):
    """Run one workload section with the autotuner's provenance counters
    scoped to it: every decision the build/trace makes (conv lowering,
    attention backend, fusion, AMP lists, buckets) lands in this
    workload's hit-rate row. With FLAGS_tuning_mode=off no decisions
    fire and the row records zero consults — which is exactly what
    gate.py needs to tell 'untuned run' from 'tuned run with misses'."""
    from paddle_tpu import tuning

    tuning.reset_provenance()
    out = fn(*args)
    tuner_stats[name] = tuning.provenance_snapshot()
    return out


def bench_multichip(argv=None):
    """`bench.py --multichip`: the measured multichip scaling campaign
    (ROADMAP item 2 promoted from dryrun) — tokens/s and per-axis scaling
    efficiency for dp/tp/pp/sp on an 8-device mesh, with collective-overlap
    A/B arms (bucketed vs per-grad allreduce, ZeRO-1, 1F1B vs fill-drain)
    on the tools/_timing.py protocol, plus the parameter-trajectory parity
    oracle per axis. Prints ONE JSON line (the MULTICHIP artifact's
    scaling/overlap_ab/parity blocks; tools/gate.py --multichip consumes
    it). Off-TPU the campaign provisions a virtual 8-device CPU mesh in a
    fresh process — platform choice is locked at first backend init, so a
    session that already initialized fewer devices re-execs."""
    import os
    import subprocess
    import sys

    argv = list(argv or [])
    n = 8
    if "--devices" in argv:
        n = int(argv[argv.index("--devices") + 1])
    import jax

    if len(jax.devices()) < n and jax.devices()[0].platform != "tpu":
        repo = os.path.dirname(os.path.abspath(__file__))
        from __graft_entry__ import _FORCE_ENV

        env = dict(os.environ)
        env[_FORCE_ENV] = str(n)
        code = (f"import sys; sys.path.insert(0, {repo!r}); "
                f"import __graft_entry__ as g; g._provision_cpu_mesh({n}); "
                f"from tools import _mc_ab; "
                f"sys.exit(_mc_ab.main({argv!r}))")
        r = subprocess.run([sys.executable, "-c", code], cwd=repo, env=env)
        return r.returncode
    from tools import _mc_ab

    return _mc_ab.main(argv)


def main():
    from paddle_tpu import flags as pt_flags
    from paddle_tpu import tuning
    from paddle_tpu.tuning import learned as tuning_learned

    dev = jax.devices()[0]
    on_tpu = dev.platform == "tpu"
    peak = _peak_flops(dev)

    # learned-tier provenance is per-RUN (gate.py's fallback-rate ceiling
    # reads the artifact's aggregate), unlike the per-workload hit rates
    tuning_learned.reset_counters()

    tuner_stats: dict = {}
    tok_s, bert_mfu, bert_windows = _tuned(
        tuner_stats, "bert", bench_bert, on_tpu, peak)
    img_s, rn_mfu, rn_windows, rn_ab = _tuned(
        tuner_stats, "resnet50", bench_resnet, on_tpu, peak)
    wmt_tok_s, wmt_mfu, wmt_windows = _tuned(
        tuner_stats, "transformer_wmt", bench_wmt, on_tpu, peak)
    ctr_ex_s, ctr_windows, ctr_dev_ex_s, ctr_guard_pct = _tuned(
        tuner_stats, "deepfm", bench_deepfm, on_tpu)
    giant = _tuned(tuner_stats, "deepfm_giant", bench_deepfm_giant, on_tpu)
    long_ctx = _tuned(tuner_stats, "bert_s512", bench_bert_long, on_tpu)
    short_ab = _tuned(tuner_stats, "bert_s128_shortattn", bench_bert_short,
                      on_tpu)
    serving = _tuned(tuner_stats, "serving", bench_serving, on_tpu)
    telemetry = bench_telemetry(on_tpu)

    # bench rounds feed the measurement store too (sweep/explore mode or
    # FLAGS_tuning_record=on): per-window seconds-per-item rows under the
    # run's tuning mode as the arm — A/B material for mode-on-vs-off drift
    def _rec_bench(wl, unit, windows):
        ws = [1.0 / w for w in windows if w and w > 0]
        if ws and tuning_learned.recording_enabled():
            tuning_learned.record(
                "bench", f"workload={wl}", "-", tuning.device_kind(),
                f"mode_{tuning.mode()}", windows_s=ws, source="bench",
                extras={"unit": unit})

    _rec_bench("bert", "s_per_token", bert_windows)
    _rec_bench("resnet50", "s_per_image", rn_windows)
    _rec_bench("transformer_wmt", "s_per_token", wmt_windows)
    _rec_bench("deepfm", "s_per_example", ctr_windows)

    # the registry's end-of-run name inventory rides in the artifact:
    # tools/gate.py --obs lints it against observability/schema.py, so a
    # metric added without a declaration fails the gate, not a dashboard
    from paddle_tpu import observability as obs

    _snap = obs.snapshot()
    telemetry["metric_names"] = sorted(
        {obs.base_name(k) for sect in ("counters", "gauges", "histograms")
         for k in _snap[sect]} | set(_snap["stages"]))
    telemetry["undeclared_metrics"] = _snap["undeclared"]

    # Per-workload targets. MFU workloads: the 0.45 north star
    # (BASELINE.json). DeepFM has no published number, so the declared
    # target is a no-regression floor under the round-3 measured 75k ex/s.
    # The workload is host-pipeline bound and best-of-2 runs across a full
    # day spread 68-93k ex/s on this shared box, so the floor sits at 60k —
    # below the observed noise band, above any real (>25%) regression.
    DEEPFM_TARGET_EX_S = 60_000.0
    vs_target = {
        "bert": bert_mfu / 0.45,
        "resnet50": rn_mfu / 0.45,
        "transformer_wmt": wmt_mfu / 0.45,
        "deepfm": ctr_ex_s / DEEPFM_TARGET_EX_S,
    }
    if on_tpu:
        # the Pallas kernel's proof row gates the aggregate too. Floor at
        # 0.95 (not 1.0): the kernel's margin is ~9% but single interference
        # bursts on this box last longer than one timed pass (PERF r4), so
        # a strict >=1.0 gate would flag machine noise as a regression.
        vs_target["bert_s512_pallas"] = \
            long_ctx["pallas"] / long_ctx["xla"] / 0.95
    vs_baseline = min(vs_target.values())

    print(json.dumps({
        "metric": "worst_workload_vs_target",
        "value": round(vs_baseline, 4),
        "unit": "ratio",
        "vs_baseline": round(vs_baseline, 4),
        "vs_target": {k: round(v, 4) for k, v in vs_target.items()},
        "bert_train_tokens_per_sec_per_chip": round(tok_s, 2),
        "bert_windows_tok_s": bert_windows,
        "bert_mfu": round(bert_mfu, 4),
        "resnet50_images_per_sec_per_chip": round(img_s, 2),
        "resnet50_windows_img_s": rn_windows,
        "resnet50_mfu": round(rn_mfu, 4),
        # the r6 conv-lever A/B, re-measured every round: implicit-GEMM
        # (auto per-shape cost model) + fused one-pass BN statistics vs the
        # r5 direct-conv/two-pass-BN step; headline takes the winner
        "resnet50_lever_ab": rn_ab,
        "transformer_wmt_tokens_per_sec_per_chip": round(wmt_tok_s, 2),
        "transformer_wmt_windows_tok_s": wmt_windows,
        "transformer_wmt_mfu": round(wmt_mfu, 4),
        "deepfm_examples_per_sec": round(ctr_ex_s, 2),
        "deepfm_windows_ex_s": ctr_windows,
        "deepfm_target_examples_per_sec": DEEPFM_TARGET_EX_S,
        # pipelined-execution efficiency: end-to-end train_from_dataset over
        # the pure device step (resident batch). The async feed/dispatch
        # pipeline owns this ratio; tools/gate.py flags < 0.9
        "deepfm_device_path_examples_per_sec": round(ctr_dev_ex_s, 2),
        "deepfm_e2e_device_ratio": round(ctr_ex_s / ctr_dev_ex_s, 4),
        # in-graph health sentinel cost vs the unguarded device path
        # (resilience/guardrails.py); tools/gate.py flags > 2%
        "deepfm_guard_overhead_pct": round(ctr_guard_pct, 2),
        # the custom short-seq Pallas attention kernel's proof row: BERT
        # seq-512 tokens/s with the kernel off vs on (on wins ~9%)
        "bert_s512_tokens_per_sec_xla_attn": round(long_ctx["xla"], 2),
        "bert_s512_tokens_per_sec_pallas_attn": round(long_ctx["pallas"], 2),
        # ISSUE 9: the seq<=128 short-attention kernel's end-to-end A/B
        # (interleaved ABAB, FLAGS_attention_force_backend arms); gate.py
        # fails if the kernel ENGAGED and lost beyond the band
        "bert_s128_shortattn_ab": short_ab,
        # ISSUE 10: DeepFM with fm_emb provably over the HBM budget on the
        # tiered host-shards + hot-ID-cache path (embedding/): end-to-end
        # examples/s, cache hit rate, host-tier bytes vs budget, and the
        # small-scale parameter-parity drift vs the dense-lookup oracle.
        # tools/gate.py hard-fails parity drift > 1e-4; the hit-rate floor
        # warns on the first artifact and gates thereafter
        "deepfm_giant": giant,
        # the serving runtime's open-loop load row (serving/): served
        # tokens/s, p50/p99 request + first-token latency, KV-pool
        # occupancy. tools/gate.py fails on leaked KV pages and on a
        # served-tokens/s drop below the floor vs the previous artifact
        "serving": serving,
        # ISSUE 13: the unified telemetry layer's overhead A/B
        # (FLAGS_obs_enable on vs off on the async dispatch loop) plus the
        # registry's metric-name inventory; tools/gate.py --obs fails
        # overhead > 2%, undeclared metric names, or schema drift
        "telemetry": telemetry,
        # autotuner provenance (paddle_tpu/tuning/): per-workload decision
        # counts and swept-DB hit-rate. tools/gate.py flags a consult-mode
        # workload that resolved mostly off the DB (running untuned)
        "tuning": {
            "mode": tuning.mode(),
            "db": str(pt_flags.get_flag("tuning_db")),
            "model": tuning_learned.model_path() or "",
            # learned-tier aggregate: predictions/fallbacks/promotions +
            # fallback_rate (gate.py --costmodel's consult-mode ceiling)
            "learned": tuning_learned.snapshot(),
            "workloads": tuner_stats,
        },
        "config": {
            "device_kind": getattr(dev, "device_kind", "cpu"),
            "bert": "base b128 s128 AMP Adam" if on_tpu else "tiny b8 s32",
            "resnet": "rn50 b128 i224 AMP Momentum" if on_tpu else "rn18 b4 i32",
            "wmt": "base b128 s128/128 AMP Adam" if on_tpu else "tiny b8 s16/16",
            "deepfm": ("v100k b2048 f26 d13 QueueDataset" if on_tpu
                       else "v1k b256 f26 d13"),
            "deepfm_giant": giant["config"],
            "bert_s512": ("base b64 s512 AMP Adam" if on_tpu
                          else "tiny b4 s128"),
        },
    }))


if __name__ == "__main__":
    import sys as _sys

    if "--multichip" in _sys.argv:
        _argv = [a for a in _sys.argv[1:] if a != "--multichip"]
        _sys.exit(bench_multichip(_argv))
    main()
