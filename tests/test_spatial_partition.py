"""Spatial partitioning of conv models: image H dim sharded over the `sp`
mesh axis (GSPMD inserts the 3x3 halo exchanges). The oracle is numerical
equivalence with the unsharded single-device run of the same program —
same seed, same feed, same loss."""
import numpy as np

import paddle_tpu as pt
from paddle_tpu import layers as L
from paddle_tpu.parallel.mesh import DATA_AXIS, SEQ_AXIS, make_mesh
from paddle_tpu.parallel.sharding import annotate_sharding


def _build(annotate):
    main, startup = pt.Program(), pt.Program()
    with pt.program_guard(main, startup):
        with pt.unique_name.guard():
            img = L.data(name="img", shape=[3, 16, 16], dtype="float32")
            label = L.data(name="label", shape=[1], dtype="int64")
            x = L.conv2d(img, num_filters=8, filter_size=3, padding=1,
                         act="relu", param_attr=pt.ParamAttr(name="c1w"),
                         bias_attr=pt.ParamAttr(name="c1b"))
            x = L.conv2d(x, num_filters=8, filter_size=3, padding=1,
                         stride=2, act="relu",
                         param_attr=pt.ParamAttr(name="c2w"),
                         bias_attr=pt.ParamAttr(name="c2b"))
            x = L.pool2d(x, pool_type="avg", global_pooling=True)
            logits = L.fc(x, size=10, param_attr=pt.ParamAttr(name="fcw"),
                          bias_attr=pt.ParamAttr(name="fcb"))
            loss = L.mean(L.softmax_with_cross_entropy(logits, label))
            if annotate:
                blk = main.global_block
                annotate_sharding(blk.var("img"),
                                  (DATA_AXIS, None, SEQ_AXIS, None))
                annotate_sharding(blk.var("label"), (DATA_AXIS, None))
            pt.optimizer.SGD(learning_rate=0.1).minimize(loss)
    return main, startup, loss


def test_spatial_sharded_step_matches_single_device():
    rng = np.random.default_rng(0)
    feed = {"img": rng.standard_normal((8, 3, 16, 16)).astype(np.float32),
            "label": rng.integers(0, 10, (8, 1)).astype(np.int64)}

    def run(sharded):
        main, startup, loss = _build(annotate=sharded)
        exe = pt.Executor()
        with pt.scope_guard(pt.Scope()) as sc:
            exe.run(startup)
            if sharded:
                mesh = make_mesh({"dp": 2, "sp": 4})
                prog = pt.CompiledProgram(main).with_data_parallel(
                    loss_name=loss.name, mesh=mesh)
            else:
                prog = main
            losses = []
            for _ in range(3):
                (lv,) = exe.run(prog, feed=feed, fetch_list=[loss])
                losses.append(float(np.asarray(lv)))
            w = np.asarray(sc.find_var("c1w"))
        return losses, w

    base_losses, base_w = run(sharded=False)
    sp_losses, sp_w = run(sharded=True)
    # same program, same seed: the spatially-sharded trajectory must match
    np.testing.assert_allclose(sp_losses, base_losses, rtol=2e-5)
    np.testing.assert_allclose(sp_w, base_w, rtol=2e-4, atol=1e-5)
    assert base_losses[2] < base_losses[0]  # and it actually trains


def test_spatial_sharded_resnet_matches_single_device():
    """Strided convs + batch-norm + global pool under the sp split: the
    full ResNet-CIFAR train step must match the unsharded run."""
    from paddle_tpu.models import resnet

    rng = np.random.default_rng(1)
    feed = {"img": rng.standard_normal((8, 3, 32, 32)).astype(np.float32),
            "label": rng.integers(0, 10, (8, 1)).astype(np.int64)}

    def run(sharded):
        main, startup = pt.Program(), pt.Program()
        with pt.program_guard(main, startup):
            with pt.unique_name.guard():
                loss, acc, _ = resnet.resnet_cifar10()
                if sharded:
                    blk = main.global_block
                    annotate_sharding(blk.var("img"),
                                      (DATA_AXIS, None, SEQ_AXIS, None))
                    annotate_sharding(blk.var("label"), (DATA_AXIS, None))
                pt.optimizer.Momentum(learning_rate=0.05,
                                      momentum=0.9).minimize(loss)
        exe = pt.Executor()
        with pt.scope_guard(pt.Scope()) as sc:
            exe.run(startup)
            prog = main
            if sharded:
                mesh = make_mesh({"dp": 2, "sp": 4})
                prog = pt.CompiledProgram(main).with_data_parallel(
                    loss_name=loss.name, mesh=mesh)
            losses = [float(np.asarray(exe.run(prog, feed=feed,
                                               fetch_list=[loss])[0]))
                      for _ in range(6)]
        return losses

    base = run(sharded=False)
    sp = run(sharded=True)
    # step 1 is bitwise-comparable; later steps accumulate cross-device
    # reduction-order drift through the BN statistics (fp32 sums in a
    # different association), amplified by the momentum trajectory. The
    # FULL 6-step trajectory must stay inside the documented band — not
    # just the early steps (VERDICT r4 #10) — and both runs must actually
    # train (monotone-ish descent, same direction).
    np.testing.assert_allclose(sp[0], base[0], rtol=2e-5)
    np.testing.assert_allclose(sp, base, rtol=2e-2)
    assert sp[-1] < sp[0] and base[-1] < base[0], (sp, base)
