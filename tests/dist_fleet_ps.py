"""Fleet parameter-server-mode runner (reference fleet pserver lifecycle over
the TestDistBase subprocess pattern).

usage: dist_fleet_ps.py ROLE EPS TRAINER_ID N_TRAINERS OUT_NPZ [SERVER_ID]
"""
import os
import sys

import jax

jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402

import paddle_tpu as pt  # noqa: E402
from paddle_tpu import layers as L  # noqa: E402
from paddle_tpu.incubate.fleet.parameter_server import fleet  # noqa: E402
from paddle_tpu.incubate.fleet.base import PaddleCloudRoleMaker  # noqa: E402

STEPS = 5
FULL_BATCH = 32


def build():
    x = L.data(name="x", shape=[16], dtype="float32")
    y = L.data(name="y", shape=[1], dtype="float32")
    h = L.fc(x, size=512, act="relu")
    pred = L.fc(h, size=1)
    return L.mean(L.square_error_cost(pred, y))


def full_data():
    rng = np.random.default_rng(0)
    x = rng.standard_normal((FULL_BATCH, 16)).astype(np.float32)
    w = rng.standard_normal((16, 1)).astype(np.float32)
    return x, (x @ w).astype(np.float32)


def main():
    role, eps, tid, n, out = (sys.argv[1], sys.argv[2], int(sys.argv[3]),
                              int(sys.argv[4]), sys.argv[5])
    sid = sys.argv[6] if len(sys.argv) > 6 else "0"
    mode = sys.argv[7] if len(sys.argv) > 7 else "sync"
    os.environ["TRAINING_ROLE"] = "PSERVER" if role == "pserver" else "TRAINER"
    os.environ["PADDLE_PSERVERS_IP_PORT_LIST"] = eps
    os.environ["PADDLE_PSERVER_ID"] = sid
    os.environ["PADDLE_TRAINER_ID"] = str(tid)
    os.environ["PADDLE_TRAINERS_NUM"] = str(n)

    from paddle_tpu.transpiler import DistributeTranspilerConfig

    strategy = None
    steps = STEPS
    lr = 0.1
    if mode == "async":
        strategy = DistributeTranspilerConfig()
        strategy.sync_mode = False
        steps = 120  # async has no exact oracle; assert convergence instead
        # two trainers apply updates independently (effective rate ~2x) with
        # staleness — the classic async trade; lr halves for stability
        lr = 0.03

    main_p, startup = pt.Program(), pt.Program()
    main_p.random_seed = startup.random_seed = 7
    with pt.program_guard(main_p, startup):
        with pt.unique_name.guard():
            loss = build()
            fleet.init(PaddleCloudRoleMaker())
            opt = fleet.distributed_optimizer(pt.optimizer.SGD(lr),
                                              strategy=strategy)
            opt.minimize(loss)

    if fleet.is_server():
        with pt.program_guard(main_p, startup):
            fleet.init_server()
            fleet.run_server()
        return

    exe = pt.Executor()
    with pt.program_guard(main_p, startup):
        exe.run(startup)
        fleet.init_worker()
        x, y = full_data()
        shard = FULL_BATCH // n
        lo = tid * shard
        prog = fleet.main_program
        losses = []
        for _ in range(steps):
            (lv,) = exe.run(prog, feed={"x": x[lo:lo + shard],
                                        "y": y[lo:lo + shard]},
                            fetch_list=[loss.name])
            losses.append(float(np.asarray(lv)))
            if mode == "async":
                # pace the loop like a real CTR reader: async semantics are
                # grads-at-last-recv'd-params; an unthrottled microbenchmark
                # loop would compute all its grads at the initial params
                # before the first merged send even lands
                import time
                time.sleep(0.03)
        fleet.stop_worker()
    vals = {p.name: np.asarray(pt.global_scope().find_var(p.name))
            for p in main_p.all_parameters()}
    vals["__last_loss__"] = np.asarray(lv)
    vals["__losses__"] = np.asarray(losses)
    np.savez(out, **vals)


if __name__ == "__main__":
    main()
