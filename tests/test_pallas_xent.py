"""Pallas fused softmax-xent kernel vs the jnp reference (fwd + grad),
through the interpreter on CPU. The kernel is default-OFF in production
(FLAGS_pallas_xent): it measured 8.5% slower end-to-end than XLA's fused
path at BERT shapes (PERF.md r5) and is kept as a measured-and-retired
lever with this regression coverage."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import layers as L
from paddle_tpu.ops.pallas_kernels import xent as px


@pytest.fixture(autouse=True)
def _interpret():
    px.INTERPRET = True
    pt.flags.set_flags({"pallas_xent": True})
    yield
    px.INTERPRET = False
    pt.flags.set_flags({"pallas_xent": False})


def _ref(logits, labels):
    lsm = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
    return -jnp.take_along_axis(lsm, labels[:, None], axis=1)[:, 0]


@pytest.mark.parametrize("dtype", ["float32", "bfloat16"])
@pytest.mark.parametrize("vocab", [640, 1000])  # lane-aligned and ragged
def test_xent_kernel_matches_reference(dtype, vocab):
    rng = np.random.default_rng(0)
    n = 128
    logits = jnp.asarray(rng.standard_normal((n, vocab)) * 2.0, dtype)
    labels = jnp.asarray(rng.integers(0, vocab, n).astype(np.int32))
    got = px.softmax_xent_rows(logits, labels)
    ref = _ref(logits, labels)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               rtol=2e-2 if dtype == "bfloat16" else 1e-5,
                               atol=1e-3)

    gp = jax.grad(lambda lg: jnp.mean(px.softmax_xent_rows(lg, labels)))(
        logits)
    gr = jax.grad(lambda lg: jnp.mean(_ref(lg, labels)))(logits)
    np.testing.assert_allclose(np.asarray(gp, np.float32),
                               np.asarray(gr, np.float32),
                               rtol=2e-2, atol=2e-3)


def test_xent_op_fast_path_trains_and_matches():
    """The softmax_with_cross_entropy op's Pallas branch (program path with
    the registered in-VMEM-recompute grad) matches the classic path."""
    rng = np.random.default_rng(1)
    x = rng.standard_normal((128, 16)).astype(np.float32)
    y = rng.integers(0, 640, (128, 1)).astype(np.int64)

    def run(flag):
        pt.flags.set_flags({"pallas_xent": flag})
        main, startup = pt.Program(), pt.Program()
        main.random_seed = startup.random_seed = 3
        with pt.program_guard(main, startup):
            with pt.unique_name.guard():
                xv = L.data(name="x", shape=[16], dtype="float32")
                yv = L.data(name="y", shape=[1], dtype="int64")
                logits = L.fc(xv, size=640)
                loss = L.mean(L.softmax_with_cross_entropy(logits, yv))
                pt.optimizer.SGD(0.1).minimize(loss)
        exe = pt.Executor()
        with pt.scope_guard(pt.Scope()):
            exe.run(startup)
            hist = [float(np.asarray(exe.run(
                main, feed={"x": x, "y": y}, fetch_list=[loss])[0]))
                for _ in range(4)]
            params = [np.asarray(pt.global_scope().find_var(p.name))
                      for p in main.all_parameters()]
        return hist, params

    h_p, p_p = run(True)
    h_x, p_x = run(False)
    np.testing.assert_allclose(h_p, h_x, rtol=1e-4, atol=1e-5)
    for a, b in zip(p_p, p_x):
        np.testing.assert_allclose(a, b, rtol=1e-4, atol=1e-5)
