"""Multi-tenant serving tests (ISSUE 11): refcounted pages + page-granular
prefix cache with copy-on-write, speculative draft-verify decoding (exact
under the greedy oracle), the temperature/top-k/top-p sampling suite with
seeded determinism, and TP-sharded decode through per-shard tuner keys."""
import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import tuning
from paddle_tpu.serving import (PagedKVPool, PrefixCache, SamplingParams,
                                ServingEngine, decoder_tiny, ngram_draft,
                                sample_token)


def _prompts(cfg, seed, lens):
    rng = np.random.default_rng(seed)
    return [list(rng.integers(1, cfg.vocab_size, n)) for n in lens]


def _generate(cfg, prompts, max_new=6, **engine_kw):
    eng = ServingEngine(cfg, page_size=4, pool_pages=64, max_inflight=4,
                        **engine_kw)
    rids = [eng.submit(p, max_new_tokens=max_new) for p in prompts]
    eng.run_until_drained()
    return eng, [eng.result(r) for r in rids]


# -- pool refcounts -----------------------------------------------------------

def test_pool_refcount_share_release():
    """share bumps a holder, release drops one; a page returns to the free
    list only when the LAST holder releases it — and over-releasing raises
    before any mutation."""
    pool = PagedKVPool(8, 4)
    got = pool.allocate(2)
    assert [pool.refcount(p) for p in got] == [1, 1]
    pool.share(got)
    assert [pool.refcount(p) for p in got] == [2, 2]
    assert pool.release(got) == 0, "a held page must not free"
    assert pool.free_count == 6
    assert pool.release(got) == 2
    assert pool.free_count == 8
    with pytest.raises(ValueError, match="double-free"):
        pool.release([got[0]])
    with pytest.raises(ValueError, match="sharing free page"):
        pool.share([got[0]])
    # a single release call over-counting a page must raise pre-mutation
    more = pool.allocate(1)
    before = pool.free_count
    with pytest.raises(ValueError, match="double-free"):
        pool.release([more[0], more[0]])
    assert pool.free_count == before and pool.refcount(more[0]) == 1


# -- prefix cache mechanics ---------------------------------------------------

def test_prefix_cache_match_insert_evict_lru():
    """Page-granular trie: full blocks match longest-prefix-wins; eviction
    is LRU over leaves whose page only the cache holds, and never touches
    a page a request still maps."""
    pool = PagedKVPool(16, 4)
    cache = PrefixCache(pool)
    toks = list(range(1, 13))                      # 3 full blocks
    pages = pool.allocate(3)
    assert cache.insert(toks, pages) == 3
    assert [pool.refcount(p) for p in pages] == [2, 2, 2]
    assert cache.match(toks) == pages
    assert cache.match(toks[:7]) == pages[:1], "partial block must not match"
    assert cache.match([99] + toks[1:]) == []
    # the request releases; pages persist under the cache's refcount
    pool.release(pages)
    assert pool.free_count == 16 - 3
    # a second chain, older LRU stamp than the refreshed first chain
    other = pool.allocate(2)
    cache.insert(list(range(50, 58)), other)
    pool.release(other)
    cache.match(toks)                              # refresh chain 1
    freed = cache.evict(1)
    assert freed == 1
    assert cache.match(list(range(50, 58))) == other[:1], (
        "LRU evicts the stale chain's LEAF first")
    # pages shared with a "request" are not evictable
    pool.share([pages[0]])
    cache.evict(16)
    assert cache.match(toks[:4]) == pages[:1], "mapped page was evicted"
    pool.release([pages[0]])
    assert cache.flush() == 1
    assert pool.free_count == 16


# -- shared-prefix serving ----------------------------------------------------

def test_shared_prefix_requests_share_pages_and_match_plain_engine():
    """Concurrent requests sharing a system prompt: the later admissions
    map the earlier request's pages (refcount > 1, prefill computes only
    the suffix) and generation matches the prefix-cache-off engine."""
    cfg = decoder_tiny()
    rng = np.random.default_rng(11)
    sysp = list(rng.integers(1, cfg.vocab_size, 12))
    prompts = [sysp + list(rng.integers(1, cfg.vocab_size, 3))
               for _ in range(3)]
    _, want = _generate(cfg, prompts, prefix_cache=False)
    eng, got = _generate(cfg, prompts, prefix_cache=True)
    assert got == want
    st = eng.stats
    assert st["prefix_hit_tokens"] >= 2 * 12 // 4 * 4, "no pages shared"
    total = sum(len(p) for p in prompts)
    assert st["prefill_tokens_computed"] < total, (
        "prefix hits did not reduce prefill compute")
    assert eng.leaked_pages() == 0
    eng.flush_prefix_cache()
    assert eng.pool.free_count == eng.pool.num_pages


def test_full_prefix_hit_cow_and_isolation():
    """A page-aligned identical prompt full-hits: ZERO prefill compute, the
    first decode write copy-on-writes the shared tail page, and the copy
    leaves the original request's pages (and the cache's) untouched —
    tokens exactly match the cache-off engine for both."""
    cfg = decoder_tiny()
    rng = np.random.default_rng(5)
    prompt = list(rng.integers(1, cfg.vocab_size, 8))   # 2 full pages (ps 4)
    _, want = _generate(cfg, [prompt], max_new=5, prefix_cache=False)

    eng = ServingEngine(cfg, page_size=4, pool_pages=64, max_inflight=4,
                        prefix_cache=True)
    r1 = eng.submit(prompt, max_new_tokens=5)
    eng.run_until_drained()
    computed_before = eng.stats["prefill_tokens_computed"]
    r2 = eng.submit(prompt, max_new_tokens=5)
    eng.run_until_drained()
    assert eng.result(r1) == want[0]
    assert eng.result(r2) == want[0]
    st = eng.stats
    assert st["prefix_full_hits"] == 1
    assert st["prefill_tokens_computed"] == computed_before, (
        "a full hit must not compute any prefill")
    assert st["cow_copies"] >= 1, "the shared-boundary write never COW'd"
    assert eng.leaked_pages() == 0
    eng.flush_prefix_cache()
    assert eng.pool.free_count == eng.pool.num_pages


def test_prefix_cache_evicts_under_pool_pressure():
    """A pool mostly full of cached prompts still admits new work: unshared
    cache entries evict LRU-first instead of backpressuring live requests."""
    cfg = decoder_tiny()
    rng = np.random.default_rng(9)
    eng = ServingEngine(cfg, page_size=4, pool_pages=12, max_inflight=2,
                        prefix_cache=True)
    for _ in range(4):  # leaves ~8 cached pages in a 12-page pool
        eng.submit(list(rng.integers(1, 97, 8)), max_new_tokens=2)
        eng.run_until_drained()
    held = eng.prefix_cache.pages_held
    assert held >= 6
    eng.submit(list(rng.integers(1, 97, 20)), max_new_tokens=3)
    eng.run_until_drained()
    assert eng.prefix_cache.evicted_pages > 0, "pressure never evicted"
    assert eng.leaked_pages() == 0


def test_admit_pins_matched_prefix_pages_before_allocation():
    """Pool-pressure admission with a prefix hit: _allocate's eviction
    relief must never reclaim the pages match() just returned (the cache's
    own ref may be their only holder). Without the share-before-allocate
    pin, the evicted page comes straight back off the LIFO free list as one
    of the SAME request's private pages — one physical page mapped at two
    ordinals, silent KV corruption."""
    cfg = decoder_tiny()
    rng = np.random.default_rng(17)
    hot = list(rng.integers(1, cfg.vocab_size, 8))    # kept running
    cold = list(rng.integers(1, cfg.vocab_size, 8))   # cached, idle
    tail = list(rng.integers(1, cfg.vocab_size, 12))

    def run(prefix_cache):
        eng = ServingEngine(cfg, page_size=4, pool_pages=8, max_inflight=4,
                            prefix_cache=prefix_cache)
        r1 = eng.submit(hot, max_new_tokens=8)
        eng.step()
        r2 = eng.submit(cold, max_new_tokens=1)
        steps = 0
        while eng.requests[r2].state != "finished":
            eng.step()
            steps += 1
            assert steps < 100
        # let r1 grow to its 4th page: free pages drop to 2, so admitting
        # cold+tail (6 pages, 2 matched) must reclaim BOTH matched pages
        # through the eviction-relief path
        while len(eng.requests[r1].pages) < 4:
            eng.step()
            steps += 1
            assert steps < 100
        # cold's prompt pages sit in the cache at refcount 1 (the only
        # evictable entries — hot's pages are pinned by the running r1);
        # without the pin, eviction frees them and the LIFO free list hands
        # one back inside a prefill-written ordinal of the same request
        r3 = eng.submit(cold + tail, max_new_tokens=4)
        while eng.has_work():
            eng.step()
            steps += 1
            assert steps < 200
            for r in eng.requests.values():
                assert len(set(r.pages)) == len(r.pages), (
                    f"request {r.rid} maps a physical page at two "
                    f"ordinals: {r.pages}")
            assert eng.leaked_pages() == 0
        return eng, [eng.result(r) for r in (r1, r2, r3)]

    _, want = run(prefix_cache=False)
    eng, got = run(prefix_cache=True)
    assert got == want, "pressure admission diverged from the cache-off run"
    eng.flush_prefix_cache()
    assert eng.pool.free_count == eng.pool.num_pages


# -- speculative decoding -----------------------------------------------------

def test_ngram_draft_proposes_history_continuation():
    toks = [1, 2, 3, 9, 1, 2, 3]
    assert ngram_draft(toks, 3) == [9, 1, 2]
    assert ngram_draft([7], 2) == [7, 7], "no history: repeat-last fallback"
    assert ngram_draft(toks, 0) == []


def test_spec_decode_exact_vs_plain_greedy():
    """draft_k in {1..3} generates BITWISE the plain greedy sequence (the
    verify accepts only tokens the target model itself emits) — across
    mixed prompt lengths batched together."""
    cfg = decoder_tiny()
    prompts = _prompts(cfg, 7, (3, 9, 17))
    _, want = _generate(cfg, prompts, prefix_cache=False, draft_k=0)
    for k in (1, 3):
        eng, got = _generate(cfg, prompts, prefix_cache=True, draft_k=k)
        assert got == want, f"draft_k={k} diverged from plain greedy"
        assert eng.stats["spec_steps"] > 0
        assert eng.leaked_pages() == 0


def test_spec_decode_accepts_on_repetitive_sequences():
    """Greedy decoding of the tiny model settles into a loop (as real LLM
    decode settles into templated spans): the n-gram self-draft picks the
    cycle up, so accepted tokens > 0 and FEWER decode steps than tokens
    generated — the whole point of the draft-verify window — while the
    output stays bitwise the plain greedy sequence."""
    cfg = decoder_tiny()
    prompt = list(np.random.default_rng(3).integers(1, cfg.vocab_size, 5))
    _, want = _generate(cfg, [prompt], max_new=16, prefix_cache=False,
                        draft_k=0)
    eng, got = _generate(cfg, [prompt], max_new=16, prefix_cache=False,
                         draft_k=3)
    assert got == want
    st = eng.stats
    assert st["spec_accepted"] > 0, "no draft ever accepted"
    assert st["decode_steps"] < 16, (
        f"{st['decode_steps']} steps for 16 tokens — speculation never "
        f"batched an acceptance")


# -- sampling suite -----------------------------------------------------------

def test_sampling_filters_reduce_to_greedy():
    rng = np.random.default_rng(0)
    logits = rng.standard_normal(32).astype(np.float32)
    top = int(np.argmax(logits))
    assert sample_token(logits, SamplingParams(), rng) == top
    assert sample_token(logits, SamplingParams(temperature=0.7, top_k=1),
                        rng) == top
    assert sample_token(logits, SamplingParams(temperature=0.7,
                                               top_p=1e-6), rng) == top
    # top-k filter really restricts support
    p = SamplingParams(temperature=1.5, top_k=4)
    keep = set(np.argsort(-logits)[:4])
    draws = {sample_token(logits, p, np.random.default_rng(i))
             for i in range(64)}
    assert draws <= keep and len(draws) > 1
    with pytest.raises(ValueError, match="top_p"):
        SamplingParams(top_p=0.0)


def test_sampling_seeded_determinism_across_batch_buckets():
    """Same engine seed => same sampled tokens, run-to-run AND across
    engines whose max_inflight (hence batch-bucket packing + recompiles)
    differs; a different seed diverges."""
    cfg = decoder_tiny()
    prompts = _prompts(cfg, 21, (5, 9, 6, 12))
    samp = {"temperature": 0.9, "top_k": 8, "top_p": 0.9}

    def run(seed, inflight):
        eng = ServingEngine(cfg, page_size=4, pool_pages=64,
                            max_inflight=inflight, seed=seed)
        rids = [eng.submit(p, max_new_tokens=5, sampling=samp)
                for p in prompts]
        eng.run_until_drained()
        return [eng.result(r) for r in rids]

    a = run(0, 4)
    assert run(0, 4) == a, "same seed, same packing: must replay"
    assert run(0, 2) == a, (
        "determinism must not depend on batch-bucket packing")
    assert run(1, 4) != a, "different seed never diverged (rng unused?)"


def test_sampling_rows_mix_with_greedy_and_spec_rows():
    """A sampling request batched with greedy rows under speculative
    decoding: whatever the sampler draws can never leak into the greedy
    rows (row-independent compute), and the sampling row itself is
    deterministic per its seed stream."""
    cfg = decoder_tiny()
    prompts = _prompts(cfg, 31, (6, 10))

    def run(top_k):
        eng = ServingEngine(cfg, page_size=4, pool_pages=64, max_inflight=4,
                            draft_k=2, seed=3)
        g = [eng.submit(p, max_new_tokens=5) for p in prompts]
        s = eng.submit(prompts[0], max_new_tokens=5,
                       sampling=SamplingParams(temperature=1.1, top_k=top_k))
        eng.run_until_drained()
        return [eng.result(r) for r in g], eng.result(s)

    greedy1, sampled1 = run(top_k=6)
    greedy2, sampled2 = run(top_k=6)
    greedy3, sampled3 = run(top_k=48)
    assert greedy1 == greedy2 and sampled1 == sampled2, "replay broke"
    assert greedy3 == greedy1, (
        "the sampling row's draws leaked into greedy rows")
    assert sampled3 != sampled1, "top_k filter had no effect on support"


# -- tensor-parallel serving --------------------------------------------------

def test_tp_engine_matches_single_shard():
    """tp=2 over the host-device mesh: head-sharded prefill+decode emits
    exactly the tp=1 tokens (GSPMD correctness), with the KV pools
    annotated on their heads dim."""
    cfg = decoder_tiny()
    prompts = _prompts(cfg, 13, (5, 11))
    _, want = _generate(cfg, prompts, prefix_cache=False)
    eng, got = _generate(cfg, prompts, prefix_cache=False, tp=2)
    assert got == want
    pool_var = eng._decode_prog.global_block.var("kv_cache.k0")
    assert pool_var.sharding == (None, None, "tp", None)


def test_tp_decode_consults_per_shard_tuner_key(tmp_path):
    """The per-shard contract: under tp the decode-attention lever keys the
    DB on nh/tp — a swept entry for the SHARD shape drives (and hits) the
    dispatch, exactly what tools/tune.py's TP candidates upgrade into."""
    from paddle_tpu.ops import attention_ops as ao

    snap = pt.flags.all_flags()
    db_path = str(tmp_path / "db.json")
    try:
        pt.flags.set_flags({"tuning_mode": "consult", "tuning_db": db_path})
        tuning.invalidate_db_cache()
        db = tuning.TuningDB(db_path)
        key = tuning.canonical_key(
            "attention", tuning.attention_key(4, 6, 1, 256, 64, True),
            "float32", tuning.device_kind())
        db.put(key, {"backend": "xla"}, source="swept")
        db.save(db_path)
        tuning.invalidate_db_cache()
        backend, tier = ao.paged_attention_backend(
            4, 12, 256, 64, np.dtype("float32"), tp=2)
        assert (backend, tier) == ("xla", "db"), (
            "tp=2 dispatch must consult the nh/tp shard key")
        _, tier_full = ao.paged_attention_backend(
            4, 12, 256, 64, np.dtype("float32"), tp=1)
        assert tier_full != "db", "tp=1 must NOT hit the shard key"
    finally:
        pt.flags.set_flags(snap)
        tuning.invalidate_db_cache()


def test_tune_records_tp_decode_candidates(tmp_path):
    """tools/tune.py records the head-sharded decode shapes as candidate
    entries under their per-shard keys (and never clobbers a swept one)."""
    from tools import tune

    db = tuning.TuningDB(str(tmp_path / "db.json"))
    shapes = [("d", 8, 12, 512, 64)]
    swept_key = tuning.canonical_key(
        "attention", tuning.attention_key(8, 6, 1, 512, 64, True),
        "float32", tuning.device_kind())
    db.put(swept_key, {"backend": "pallas_paged"}, source="swept")
    added = tune.record_tp_decode_candidates(db, shapes, "float32",
                                             tp_degrees=(2, 4))
    assert added == 1, "tp=2 key is swept already; only tp=4 should land"
    cand_key = tuning.canonical_key(
        "attention", tuning.attention_key(8, 3, 1, 512, 64, True),
        "float32", tuning.device_kind())
    assert db.lookup(cand_key)["source"] == "candidate"
    assert db.lookup(swept_key)["source"] == "swept"


# -- chaos: abort + speculation + sharing ------------------------------------

@pytest.mark.chaos
def test_abort_under_speculation_keeps_refcounts_balanced():
    """Aborts injected while speculative windows are in flight over shared
    prefixes: lookahead pages, COW copies and shared mappings all release
    exactly once — the accounting balances every cycle."""
    from paddle_tpu.resilience.faults import fault_scope

    cfg = decoder_tiny()
    eng = ServingEngine(cfg, page_size=4, pool_pages=32, max_inflight=4,
                        prefix_cache=True, draft_k=3)
    rng = np.random.default_rng(17)
    sysp = list(rng.integers(1, 97, 8))
    for cycle in range(3):
        with fault_scope("serving_abort:1,3") as plan:
            rids = [eng.submit(sysp + list(rng.integers(1, 97, n)),
                               max_new_tokens=6) for n in (0, 4, 9)]
            eng.run_until_drained()
            assert plan.stats()["fired"]
        assert {eng.requests[r].state for r in rids} <= {"finished",
                                                         "aborted"}
        assert eng.leaked_pages() == 0, f"cycle {cycle} orphaned pages"
    eng.flush_prefix_cache()
    assert eng.pool.free_count == eng.pool.num_pages
