"""Dygraph (imperative) mode: eager ops, tape backward, Layer system, and
the static-vs-imperative equivalence oracle (reference
unittests/test_imperative_mnist.py pattern: same params + same data =>
same loss trajectory)."""
import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import dygraph as dg
from paddle_tpu import layers as L
from paddle_tpu.dygraph import _dy_op


def test_eager_op_and_gradient():
    with dg.guard(seed=3):
        x = dg.to_variable(np.array([[1.0, 2.0], [3.0, 4.0]], np.float32))
        w = dg.VarBase(np.array([[1.0], [1.0]], np.float32),
                       persistable=True)
        y = _dy_op("mul", {"X": [x], "Y": [w]})["Out"]
        loss = _dy_op("mean", {"X": [y]})["Out"]
        loss.backward()
        # dL/dW = X^T @ (0.5 * ones): [[ (1+3)/2 ], [ (2+4)/2 ]]
        np.testing.assert_allclose(
            w.gradient(), np.array([[2.0], [3.0]]), rtol=1e-6)
        np.testing.assert_allclose(float(loss.numpy()), (3 + 7) / 2, rtol=1e-6)


def test_stop_gradient_and_no_grad():
    with dg.guard():
        x = dg.to_variable(np.ones((2, 2), np.float32))
        w = dg.VarBase(np.ones((2, 2), np.float32), persistable=True)
        with dg.no_grad():
            frozen = _dy_op("elementwise_mul", {"X": [x], "Y": [w]})["Out"]
        assert frozen.stop_gradient
        y = _dy_op("elementwise_add", {"X": [frozen], "Y": [w]})["Out"]
        loss = _dy_op("mean", {"X": [y]})["Out"]
        loss.backward()
        # only the add contributes: dL/dw = 1/4
        np.testing.assert_allclose(w.gradient(), np.full((2, 2), 0.25),
                                   rtol=1e-6)


def test_layer_registry_and_state_dict():
    with dg.guard(seed=5):
        class Net(dg.Layer):
            def __init__(self):
                super().__init__()
                self.fc1 = dg.Linear(4, 8, act="relu")
                self.fc2 = dg.Linear(8, 2)

            def forward(self, x):
                return self.fc2(self.fc1(x))

        net = Net()
        assert len(net.parameters()) == 4
        sd = net.state_dict()
        assert set(sd) == {"fc1.weight", "fc1.bias", "fc2.weight", "fc2.bias"}

        net2 = Net()
        net2.set_dict(sd)
        x = dg.to_variable(np.ones((3, 4), np.float32))
        np.testing.assert_allclose(net(x).numpy(), net2(x).numpy(), rtol=1e-6)


def test_conv_pool_batchnorm_forward_backward():
    with dg.guard(seed=7):
        conv = dg.Conv2D(3, 8, 3, padding=1, act="relu")
        bn = dg.BatchNorm(8)
        pool = dg.Pool2D(pool_size=2, pool_type="max", pool_stride=2)
        x = dg.to_variable(
            np.random.default_rng(0).standard_normal((2, 3, 8, 8))
            .astype(np.float32))
        out = pool(bn(conv(x)))
        assert out.shape == (2, 8, 4, 4)
        loss = _dy_op("mean", {"X": [out]})["Out"]
        loss.backward()
        assert conv.weight.gradient() is not None
        assert np.isfinite(conv.weight.gradient()).all()


def test_imperative_mnist_matches_static_graph():
    """Same init + same data: dygraph SGD trajectory == static-graph SGD
    trajectory (reference test_imperative_mnist.py equivalence)."""
    rng = np.random.default_rng(0)
    xs = rng.standard_normal((5, 16, 10)).astype(np.float32)
    w_true = rng.standard_normal((10, 1)).astype(np.float32)
    ys = np.stack([x @ w_true for x in xs])

    # static graph
    x = L.data(name="x", shape=[10], dtype="float32")
    yv = L.data(name="y", shape=[1], dtype="float32")
    h = L.fc(x, size=8, act="tanh", name="h")
    pred = L.fc(h, size=1, name="p")
    loss = L.mean(L.square_error_cost(pred, yv))
    pt.optimizer.SGD(0.1).minimize(loss)
    exe = pt.Executor()
    exe.run(pt.default_startup_program())
    scope = pt.global_scope()
    init = {n: np.asarray(scope.find_var(n))
            for n in ("h.w_0", "h.b_0", "p.w_0", "p.b_0")}
    static_losses = []
    for i in range(5):
        (lv,) = exe.run(pt.default_main_program(),
                        feed={"x": xs[i], "y": ys[i]}, fetch_list=[loss])
        static_losses.append(float(np.asarray(lv)))

    # imperative, seeded with the SAME initial params
    with dg.guard():
        fc1 = dg.Linear(10, 8, act="tanh")
        fc2 = dg.Linear(8, 1)
        fc1.set_dict({"weight": init["h.w_0"], "bias": init["h.b_0"]})
        fc2.set_dict({"weight": init["p.w_0"], "bias": init["p.b_0"]})
        sgd = pt.optimizer.SGD(0.1)
        dy_losses = []
        for i in range(5):
            xb = dg.to_variable(xs[i])
            yb = dg.to_variable(ys[i])
            pred = fc2(fc1(xb))
            diff = _dy_op("elementwise_sub", {"X": [pred], "Y": [yb]})["Out"]
            sq = _dy_op("square", {"X": [diff]})["Out"]
            lv = _dy_op("mean", {"X": [sq]})["Out"]
            lv.backward()
            sgd.minimize(lv, parameter_list=fc1.parameters() + fc2.parameters())
            for p in fc1.parameters() + fc2.parameters():
                p.clear_gradient()
            dy_losses.append(float(lv.numpy()))
    np.testing.assert_allclose(static_losses, dy_losses, rtol=1e-4)


def test_dygraph_adam_and_embedding():
    with dg.guard(seed=11):
        emb = dg.Embedding([20, 6])
        fc = dg.Linear(6, 1)
        adam = pt.optimizer.Adam(learning_rate=0.05)
        rng = np.random.default_rng(0)
        first = last = None
        for step in range(30):
            ids = dg.to_variable(rng.integers(0, 20, (8, 1)))
            target = dg.to_variable(
                (ids.numpy().astype(np.float32) / 20.0))
            e = emb(ids)
            p = fc(e)
            d = _dy_op("elementwise_sub", {"X": [p], "Y": [target]})["Out"]
            lv = _dy_op("mean", {"X": [_dy_op("square", {"X": [d]})["Out"]]})["Out"]
            lv.backward()
            adam.minimize(lv, parameter_list=emb.parameters() + fc.parameters())
            for prm in emb.parameters() + fc.parameters():
                prm.clear_gradient()
            if first is None:
                first = float(lv.numpy())
            last = float(lv.numpy())
        assert last < first * 0.5, (first, last)


def test_dygraph_op_outside_guard_raises():
    with pytest.raises(RuntimeError):
        _dy_op("mean", {"X": [dg.VarBase(np.ones(3))]})
