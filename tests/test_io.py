"""Checkpoint / inference-model round-trip tests (reference book tests'
save/load round-trip pattern + unittests/test_inference_model_io.py)."""
import os

import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import layers as L


def _build_and_train(exe, steps=3):
    x = L.data(name="x", shape=[8], dtype="float32")
    y = L.data(name="y", shape=[1], dtype="float32")
    h = L.fc(x, size=4, act="relu")
    pred = L.fc(h, size=1)
    loss = L.mean(L.square_error_cost(pred, y))
    eval_prog = pt.default_main_program().clone(for_test=True)
    pt.optimizer.Adam(learning_rate=0.01).minimize(loss)
    exe.run(pt.default_startup_program())
    rng = np.random.default_rng(0)
    xv = rng.standard_normal((16, 8)).astype(np.float32)
    w = rng.standard_normal((8, 1)).astype(np.float32)
    for _ in range(steps):
        exe.run(pt.default_main_program(), feed={"x": xv, "y": xv @ w},
                fetch_list=[loss])
    return pred, loss, xv, eval_prog


def test_save_load_persistables_roundtrip(tmp_path):
    exe = pt.Executor()
    pred, loss, xv, eval_prog = _build_and_train(exe)
    scope = pt.global_scope()
    main = pt.default_main_program()

    (before,) = exe.run(eval_prog, feed={"x": xv, "y": np.zeros((16, 1), np.float32)}, fetch_list=[pred.name])
    saved = pt.io.save_persistables(exe, str(tmp_path / "ckpt"))
    assert any(".w" in n or "fc" in n for n in saved)

    # corrupt every param, then load back and check restoration
    for name in saved:
        v = scope.find_var(name)
        scope.set_var(name, np.zeros_like(np.asarray(v)))
    pt.io.load_persistables(exe, str(tmp_path / "ckpt"))
    (after,) = exe.run(eval_prog, feed={"x": xv, "y": np.zeros((16, 1), np.float32)}, fetch_list=[pred.name])
    np.testing.assert_allclose(before, after, rtol=1e-6)


def test_save_load_combined_file(tmp_path):
    exe = pt.Executor()
    pred, loss, xv, eval_prog = _build_and_train(exe)
    pt.io.save_params(exe, str(tmp_path / "ckpt"), filename="params.npz")
    scope = pt.global_scope()
    names = [p.name for p in pt.default_main_program().all_parameters()]
    orig = {n: np.asarray(scope.find_var(n)).copy() for n in names}
    for n in names:
        scope.set_var(n, np.zeros_like(orig[n]))
    pt.io.load_params(exe, str(tmp_path / "ckpt"), filename="params.npz")
    for n in names:
        np.testing.assert_allclose(np.asarray(scope.find_var(n)), orig[n])


def test_inference_model_roundtrip(tmp_path):
    exe = pt.Executor()
    pred, loss, xv, eval_prog = _build_and_train(exe)
    main = pt.default_main_program()
    (want,) = exe.run(eval_prog, feed={"x": xv, "y": np.zeros((16, 1), np.float32)}, fetch_list=[pred.name])

    pt.io.save_inference_model(str(tmp_path / "model"), ["x"], [pred], exe,
                               main_program=main)

    # load into a FRESH scope: inference must not depend on training state
    with pt.scope_guard(pt.Scope()):
        prog, feeds, fetches = pt.io.load_inference_model(
            str(tmp_path / "model"), exe)
        assert feeds == ["x"]
        # pruned program must not contain optimizer/backward ops
        types = {op.type for op in prog.global_block.ops}
        assert not any(t.endswith("_grad") or t == "adam" for t in types)
        (got,) = exe.run(prog, feed={"x": xv}, fetch_list=fetches)
    np.testing.assert_allclose(want, got, rtol=1e-6)


def test_load_missing_var_errors(tmp_path):
    exe = pt.Executor()
    _build_and_train(exe)
    with pytest.raises(FileNotFoundError):
        pt.io.load_params(exe, str(tmp_path / "nonexistent"))


def test_save_before_startup_errors(tmp_path):
    x = L.data(name="x", shape=[4], dtype="float32")
    L.fc(x, size=2)
    exe = pt.Executor()
    with pytest.raises(RuntimeError, match="startup"):
        pt.io.save_params(exe, str(tmp_path / "ckpt"))


def test_inference_model_mid_graph_feed(tmp_path):
    """Feeding an intermediate var: pruning must stop at the feed boundary
    (ops computing the fed var are dropped, not kept)."""
    exe = pt.Executor()
    x = L.data(name="x", shape=[8], dtype="float32")
    h = L.fc(x, size=4, act="relu", name="hlayer")
    pred = L.fc(h, size=1, name="olayer")
    exe.run(pt.default_startup_program())
    pt.io.save_inference_model(str(tmp_path / "m"), [h.name], [pred], exe,
                               main_program=pt.default_main_program())
    with pt.scope_guard(pt.Scope()):
        prog, feeds, fetches = pt.io.load_inference_model(str(tmp_path / "m"), exe)
        # the op computing h from x must be gone
        out_names = {n for op in prog.global_block.ops for n in op.output_names}
        assert h.name not in out_names
        hv = np.abs(np.random.default_rng(0).standard_normal((3, 4))).astype(np.float32)
        (got,) = exe.run(prog, feed={h.name: hv}, fetch_list=fetches)
    assert got.shape == (3, 1)


def test_sharded_checkpoint_roundtrip_on_mesh(tmp_path):
    """save_sharded/load_sharded round-trips params + ZeRO-sharded optimizer
    state over the 8-device mesh without a host-0 gather (SURVEY §5)."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    import paddle_tpu as pt
    from paddle_tpu import layers as L
    from paddle_tpu.parallel.mesh import make_mesh

    x = L.data(name="x", shape=[16], dtype="float32")
    y = L.data(name="y", shape=[1], dtype="float32")
    loss = L.mean(L.square_error_cost(L.fc(x, size=8, name="s"), y))
    pt.optimizer.Adam(0.01).minimize(loss)
    exe = pt.Executor()
    exe.run(pt.default_startup_program())
    rng = np.random.default_rng(0)
    xb = rng.standard_normal((16, 16)).astype(np.float32)
    yb = rng.standard_normal((16, 1)).astype(np.float32)
    exe.run(pt.default_main_program(), feed={"x": xb, "y": yb},
            fetch_list=[loss])

    # shard one var over the mesh to prove sharded arrays round-trip
    mesh = make_mesh({"dp": 8})
    scope = pt.global_scope()
    w = np.asarray(scope.find_var("s.w_0"))
    sharded = jax.device_put(w, NamedSharding(mesh, P("dp", None)))
    scope.set_var("s.w_0", sharded)

    before = {n: np.asarray(scope.find_var(n))
              for n in scope.var_names()}
    pt.io.save_sharded(exe, str(tmp_path / "ckpt"))

    for n in list(scope.var_names()):
        scope.set_var(n, np.zeros_like(before[n]))
    pt.io.load_sharded(exe, str(tmp_path / "ckpt"))
    for n, v in before.items():
        np.testing.assert_allclose(
            np.asarray(scope.find_var(n)), v, rtol=1e-6,
            err_msg=f"var {n} did not round-trip")

    # resharding-on-load: place the weight over a different axis layout
    pt.io.load_sharded(
        exe, str(tmp_path / "ckpt"),
        shardings={"s.w_0": NamedSharding(mesh, P(None, "dp"))})
    got = scope.find_var("s.w_0")
    assert got.sharding.spec == P(None, "dp")
    np.testing.assert_allclose(np.asarray(got), before["s.w_0"], rtol=1e-6)


def test_load_sharded_restores_program_grown_since_save(tmp_path):
    """A program that grew new persistables (EMA shadows, slow weights)
    after the save must still restore: the saved key set from the orbax
    metadata prunes the restore targets, and the new var keeps its current
    value instead of aborting the whole load."""
    exe = pt.Executor()
    _build_and_train(exe)
    scope = pt.global_scope()
    pt.io.save_sharded(exe, str(tmp_path / "ckpt"))

    saved = {n: np.asarray(scope.find_var(n)).copy()
             for n in scope.var_names()}
    blk = pt.default_main_program().global_block
    blk.create_var(name="ema_shadow_0", shape=[4], dtype="float32",
                   persistable=True)
    shadow = np.full((4,), 7.0, np.float32)
    scope.set_var("ema_shadow_0", shadow)
    for n in saved:
        scope.set_var(n, np.zeros_like(saved[n]))

    pt.io.load_sharded(exe, str(tmp_path / "ckpt"))
    for n, v in saved.items():
        np.testing.assert_allclose(np.asarray(scope.find_var(n)), v,
                                   rtol=1e-6, err_msg=n)
    np.testing.assert_array_equal(np.asarray(scope.find_var("ema_shadow_0")),
                                  shadow)


def test_load_sharded_metadata_unreadable_falls_back(tmp_path, monkeypatch):
    """A checkpoint whose metadata can't be read (corrupt/ancient layout)
    falls back to the full program tree — which still restores when the
    trees match."""
    import orbax.checkpoint as ocp

    exe = pt.Executor()
    _build_and_train(exe)
    scope = pt.global_scope()
    pt.io.save_sharded(exe, str(tmp_path / "ckpt"))
    saved = {n: np.asarray(scope.find_var(n)).copy()
             for n in scope.var_names()}
    for n in saved:
        scope.set_var(n, np.zeros_like(saved[n]))

    def broken_metadata(self, path):
        raise ValueError("metadata store corrupted")

    monkeypatch.setattr(ocp.StandardCheckpointer, "metadata",
                        broken_metadata)
    pt.io.load_sharded(exe, str(tmp_path / "ckpt"))
    for n, v in saved.items():
        np.testing.assert_allclose(np.asarray(scope.find_var(n)), v,
                                   rtol=1e-6, err_msg=n)


def test_save_sharded_interrupted_write_leaves_target_loadable(tmp_path):
    """Atomic save: a save that dies on every write attempt must leave the
    previous checkpoint at the target path untouched and loadable."""
    from paddle_tpu.resilience import fault_scope

    exe = pt.Executor()
    _build_and_train(exe)
    scope = pt.global_scope()
    path = str(tmp_path / "ckpt")
    pt.io.save_sharded(exe, path)
    saved = {n: np.asarray(scope.find_var(n)).copy()
             for n in scope.var_names()}

    # poison the scope, then fail the save on every retry attempt
    exe.run(pt.default_main_program(),
            feed={"x": np.ones((4, 8), np.float32),
                  "y": np.ones((4, 1), np.float32)}, fetch_list=[])
    with fault_scope("ckpt.write:" + ",".join(map(str, range(1, 20)))):
        import pytest as _pytest

        with _pytest.raises(ConnectionError):
            pt.io.save_sharded(exe, path)
    assert not [n for n in os.listdir(str(tmp_path))
                if ".tmp" in n or ".old" in n]

    # the ORIGINAL checkpoint still loads in full
    for n in saved:
        scope.set_var(n, np.zeros_like(saved[n]))
    pt.io.load_sharded(exe, path)
    for n, v in saved.items():
        np.testing.assert_allclose(np.asarray(scope.find_var(n)), v,
                                   rtol=1e-6, err_msg=n)
