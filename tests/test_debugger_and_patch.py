"""Graphviz program dump (reference fluid/debugger.py) and dygraph VarBase
operator sugar (reference dygraph/math_op_patch.py)."""
import numpy as np

import paddle_tpu as pt
from paddle_tpu import dygraph as dg
from paddle_tpu import layers as L


def test_program_to_dot(tmp_path):
    main, startup = pt.Program(), pt.Program()
    with pt.program_guard(main, startup):
        x = L.data(name="x", shape=[4], dtype="float32")
        h = L.fc(x, size=2, name="head")
        L.mean(h)
    dot = pt.debugger.draw_block_graphviz(
        main, highlights=["x"], path=str(tmp_path / "g.dot"))
    assert dot.startswith("digraph")
    assert "mul" in dot and "mean" in dot       # op nodes
    assert "head.w_0" in dot                     # parameter node
    assert "#ffe6cc" in dot                      # highlight applied
    assert (tmp_path / "g.dot").read_text() == dot
    # every edge references a declared node
    import re
    declared = set(re.findall(r"^\s+(\w+) \[", dot, re.M))
    for a, b in re.findall(r"^\s+(\w+) -> (\w+);", dot, re.M):
        assert a in declared and b in declared


def test_varbase_operator_sugar():
    with dg.guard(seed=1):
        a = dg.to_variable(np.array([[1.0, 2.0], [3.0, 4.0]], np.float32))
        b = dg.to_variable(np.array([[2.0, 2.0], [2.0, 2.0]], np.float32))
        np.testing.assert_allclose((a / b).numpy(), a.numpy() / 2)
        np.testing.assert_allclose((a ** b).numpy(), a.numpy() ** 2)
        np.testing.assert_allclose((-a).numpy(), -a.numpy())
        np.testing.assert_allclose((a @ b).numpy(), a.numpy() @ b.numpy())
        np.testing.assert_allclose((3.0 - a).numpy(), 3.0 - a.numpy())
        np.testing.assert_allclose((8.0 / b).numpy(), 4.0)
        assert (a > 2.5).numpy().astype(bool).tolist() == [[False, False],
                                                           [True, True]]
        assert (a <= 1.0).numpy().astype(bool).tolist() == [[True, False],
                                                            [False, False]]


def test_varbase_sugar_backward():
    """Gradients flow through the patched operators."""
    with dg.guard(seed=2):
        w = dg.VarBase(np.array([[2.0, 3.0]], np.float32), persistable=True)
        loss_parts = (w * w) / 2.0 - w
        from paddle_tpu.dygraph import _dy_op
        loss = _dy_op("mean", {"X": [loss_parts]})["Out"]
        loss.backward()
        # d/dw mean(w^2/2 - w) = (w - 1) / n
        np.testing.assert_allclose(w.gradient(), (np.array([[2.0, 3.0]]) - 1) / 2,
                                   rtol=1e-6)
