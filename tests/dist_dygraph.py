"""Dygraph DataParallel runner (reference parallel_dygraph_mnist.py driven by
TestDistBase): under the launcher each process trains its batch shard with
scale_loss + apply_collective_grads; with one process it is the local
baseline. usage: dist_dygraph.py OUT_NPZ"""
import sys

from paddle_tpu.distributed import init_parallel_env

penv = init_parallel_env(backend="cpu", local_device_count=1)

import numpy as np  # noqa: E402

import paddle_tpu as pt  # noqa: E402
from paddle_tpu import dygraph as dg  # noqa: E402
from paddle_tpu.dygraph import _dy_op  # noqa: E402

STEPS = 5
FULL_BATCH = 32


class Net(dg.Layer):
    def __init__(self):
        super().__init__()
        self.fc1 = dg.Linear(16, 32, act="relu")
        self.fc2 = dg.Linear(32, 1)

    def forward(self, x):
        return self.fc2(self.fc1(x))


def full_data():
    rng = np.random.default_rng(0)
    x = rng.standard_normal((FULL_BATCH, 16)).astype(np.float32)
    w = rng.standard_normal((16, 1)).astype(np.float32)
    return x, (x @ w).astype(np.float32)


def main():
    out = sys.argv[1]
    if penv.world_size > 1:
        out = f"{out}.r{penv.rank}.npz"

    with dg.guard(seed=11):
        model = dg.DataParallel(Net())
        sgd = pt.optimizer.SGD(0.1)
        x, y = full_data()
        shard = FULL_BATCH // penv.world_size
        lo = penv.rank * shard
        xs, ys = x[lo:lo + shard], y[lo:lo + shard]
        for _ in range(STEPS):
            pred = model(dg.to_variable(xs))
            diff = _dy_op("elementwise_sub",
                          {"X": [pred], "Y": [dg.to_variable(ys)]})["Out"]
            loss = _dy_op("mean", {"X": [_dy_op("square",
                                               {"X": [diff]})["Out"]]})["Out"]
            loss = model.scale_loss(loss)
            loss.backward()
            model.apply_collective_grads()
            sgd.minimize(loss, parameter_list=model.parameters())
            for p in model.parameters():
                p.clear_gradient()

        vals = {f"p{i}": np.asarray(p.numpy())
                for i, p in enumerate(model.parameters())}
        vals["__last_loss__"] = np.asarray(loss.numpy())
        np.savez(out, **vals)


if __name__ == "__main__":
    main()
