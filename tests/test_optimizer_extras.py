"""ModelAverage + LookaheadOptimizer (reference optimizer.py:2263, :2976)."""
import numpy as np

import paddle_tpu as pt
from paddle_tpu import layers as L


def test_model_average_and_lookahead():
    x = L.data(name="x", shape=[6], dtype="float32")
    y = L.data(name="y", shape=[1], dtype="float32")
    loss = L.mean(L.square_error_cost(L.fc(x, size=1, name="f"), y))
    pt.optimizer.LookaheadOptimizer(
        pt.optimizer.SGD(0.05), alpha=0.5, k=4).minimize(loss)
    ma = pt.optimizer.ModelAverage(0.15, max_average_window=20)
    exe = pt.Executor()
    exe.run(pt.default_startup_program())
    rng = np.random.default_rng(0)
    w = rng.standard_normal((6, 1)).astype(np.float32)
    first = last = None
    for i in range(40):
        xb = rng.standard_normal((16, 6)).astype(np.float32)
        (lv,) = exe.run(pt.default_main_program(),
                        feed={"x": xb, "y": xb @ w}, fetch_list=[loss])
        if first is None:
            first = float(lv)
        last = float(lv)
    assert last < first * 0.5
    cur = np.asarray(pt.global_scope().find_var("f.w_0")).copy()
    with ma.apply(exe):
        avg = np.asarray(pt.global_scope().find_var("f.w_0")).copy()
    back = np.asarray(pt.global_scope().find_var("f.w_0"))
    assert not np.allclose(avg, cur)      # averaged weights differ
    np.testing.assert_allclose(back, cur)  # restored on exit


def test_sparse_adam_lazy_mode():
    """Adam with SelectedRows grads (reference adam_op.h SparseAdamFunctor,
    lazy_mode): touched rows update exactly like dense Adam on those rows;
    untouched rows keep params AND moments frozen (no decay)."""
    import paddle_tpu as pt
    from paddle_tpu import layers as L

    def run(sparse):
        main, startup = pt.Program(), pt.Program()
        main.random_seed = startup.random_seed = 5
        with pt.program_guard(main, startup):
            with pt.unique_name.guard():
                ids = L.data(name="ids", shape=[3], dtype="int64")
                y = L.data(name="y", shape=[1], dtype="float32")
                emb = L.embedding(ids, size=[20, 4], is_sparse=sparse,
                                  param_attr=pt.ParamAttr(name="tbl"))
                pred = L.fc(L.reduce_sum(emb, dim=1), size=1)
                loss = L.mean(L.square_error_cost(pred, y))
                pt.optimizer.Adam(0.05).minimize(loss)
        scope = pt.Scope()
        exe = pt.Executor()
        rng = np.random.default_rng(0)
        idv = rng.integers(0, 10, (8, 3)).astype(np.int64)  # rows 10+ untouched
        yv = rng.standard_normal((8, 1)).astype(np.float32)
        with pt.scope_guard(scope):
            exe.run(startup)
            t0 = np.asarray(scope.find_var("tbl")).copy()
            for _ in range(5):
                exe.run(main, feed={"ids": idv, "y": yv}, fetch_list=[loss])
            t1 = np.asarray(scope.find_var("tbl"))
            m1 = np.asarray(scope.find_var(
                next(n for n in scope.var_names()
                     if n.startswith("tbl") and "moment1" in n)))
        return t0, t1, m1, idv

    t0s, t1s, m1s, idv = run(sparse=True)
    t0d, t1d, m1d, _ = run(sparse=False)
    touched = np.zeros(20, bool)
    touched[np.unique(idv)] = True
    # dense and lazy-sparse agree on touched rows (same math there)
    np.testing.assert_allclose(t1s[touched], t1d[touched], rtol=1e-5,
                               atol=1e-6)
    # lazy mode: untouched rows completely frozen
    np.testing.assert_array_equal(t1s[~touched], t0s[~touched])
    np.testing.assert_array_equal(m1s[~touched], 0.0)
    # dense mode moved nothing either on untouched rows (zero grads), but
    # the sparse path must have moved touched rows off init
    assert not np.allclose(t1s[touched], t0s[touched])
