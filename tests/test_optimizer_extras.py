"""ModelAverage + LookaheadOptimizer (reference optimizer.py:2263, :2976)."""
import numpy as np

import paddle_tpu as pt
from paddle_tpu import layers as L


def test_model_average_and_lookahead():
    x = L.data(name="x", shape=[6], dtype="float32")
    y = L.data(name="y", shape=[1], dtype="float32")
    loss = L.mean(L.square_error_cost(L.fc(x, size=1, name="f"), y))
    pt.optimizer.LookaheadOptimizer(
        pt.optimizer.SGD(0.05), alpha=0.5, k=4).minimize(loss)
    ma = pt.optimizer.ModelAverage(0.15, max_average_window=20)
    exe = pt.Executor()
    exe.run(pt.default_startup_program())
    rng = np.random.default_rng(0)
    w = rng.standard_normal((6, 1)).astype(np.float32)
    first = last = None
    for i in range(40):
        xb = rng.standard_normal((16, 6)).astype(np.float32)
        (lv,) = exe.run(pt.default_main_program(),
                        feed={"x": xb, "y": xb @ w}, fetch_list=[loss])
        if first is None:
            first = float(lv)
        last = float(lv)
    assert last < first * 0.5
    cur = np.asarray(pt.global_scope().find_var("f.w_0")).copy()
    with ma.apply(exe):
        avg = np.asarray(pt.global_scope().find_var("f.w_0")).copy()
    back = np.asarray(pt.global_scope().find_var("f.w_0"))
    assert not np.allclose(avg, cur)      # averaged weights differ
    np.testing.assert_allclose(back, cur)  # restored on exit
