"""Liveness-scenario trainer/pserver worker (dist_*.py launcher pattern).

Exercises the distributed liveness layer end to end: trainers checkpoint
every step with a CheckpointManager; a trainer whose environment carries
FLAGS_fault_plan="trainer_crash:K" dies via os._exit(137) at its K-th sync
barrier — the in-process stand-in for a mid-round SIGKILL (no cleanup, no
`complete`, heartbeats die with it). The pserver's liveness monitor must
evict it within the FLAGS_rpc_deadline and release the surviving trainers'
barrier; a fresh invocation on the same checkpoint root rejoins the server
and resumes from latest_step().

usage: dist_liveness.py ROLE EPS TRAINER_ID N_TRAINERS OUT_NPZ CKPT_ROOT \
       [CURRENT_EP]
"""
import sys
import time

import jax

jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402

import paddle_tpu as pt  # noqa: E402
from paddle_tpu import layers as L  # noqa: E402
from paddle_tpu.resilience import CheckpointManager  # noqa: E402

STEPS = 5
FULL_BATCH = 32


def build():
    x = L.data(name="x", shape=[16], dtype="float32")
    y = L.data(name="y", shape=[1], dtype="float32")
    h = L.fc(x, size=64, act="relu")
    pred = L.fc(h, size=1)
    loss = L.mean(L.square_error_cost(pred, y))
    return loss


def full_data():
    rng = np.random.default_rng(0)
    x = rng.standard_normal((FULL_BATCH, 16)).astype(np.float32)
    w = rng.standard_normal((16, 1)).astype(np.float32)
    return x, (x @ w).astype(np.float32)


def main():
    role, eps, trainer_id, n_trainers, out, ckpt_root = (
        sys.argv[1], sys.argv[2], int(sys.argv[3]), int(sys.argv[4]),
        sys.argv[5], sys.argv[6])
    current_ep = sys.argv[7] if len(sys.argv) > 7 else None

    main_p, startup = pt.Program(), pt.Program()
    main_p.random_seed = 7
    startup.random_seed = 7
    with pt.program_guard(main_p, startup):
        with pt.unique_name.guard():
            loss = build()
            pt.optimizer.SGD(0.1).minimize(loss)

    exe = pt.Executor()
    t = pt.DistributeTranspiler()
    t.transpile(trainer_id, program=main_p, pservers=eps,
                trainers=n_trainers, sync_mode=True, startup_program=startup)

    if role == "pserver":
        exe.run(t.get_startup_program())
        exe.run(t.get_pserver_program(current_ep))  # blocks until complete
        return

    # trainer: checkpoint every step; resume + rejoin if a root exists
    exe.run(startup)
    mgr = CheckpointManager(ckpt_root, keep_last_k=3, main_program=main_p)
    latest = mgr.latest_step()
    start = 0
    if latest is not None:
        mgr.restore(executor=exe, main_program=main_p)
        start = latest + 1
        from paddle_tpu.distributed.ps_rpc import PSClient

        client = PSClient.get(tuple(e for e in eps.split(",") if e),
                              trainer_id)
        server_step = client.rejoin()
        print(f"rejoined start={start} server_step={server_step}",
              flush=True)

    prog = t.get_trainer_program()
    x, y = full_data()
    shard = FULL_BATCH // n_trainers
    lo = trainer_id * shard
    xs, ys = x[lo:lo + shard], y[lo:lo + shard]

    losses, step_times = [], []
    for step in range(start, STEPS):
        t0 = time.monotonic()
        (lv,) = exe.run(prog, feed={"x": xs, "y": ys},
                        fetch_list=[loss.name])
        step_times.append(time.monotonic() - t0)
        losses.append(float(np.asarray(lv).reshape(-1)[0]))
        mgr.save(step, executor=exe, main_program=main_p)
    exe.close()
    np.savez(out, losses=np.asarray(losses),
             step_times=np.asarray(step_times),
             start_step=np.asarray(start))
    print(f"done start={start} max_step_s={max(step_times):.2f}", flush=True)


if __name__ == "__main__":
    main()
