"""Python misc tail parity (VERDICT r5 #9 / ISSUE 5 satellite): average.py
WeightedAverage, evaluator.py in-program accumulators, net_drawer.py DOT
emission, install_check.run_check — the last four reference
python/paddle/fluid modules without an analogue here."""
import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import layers as L
from paddle_tpu.average import WeightedAverage


def test_weighted_average_math_and_errors():
    wa = WeightedAverage()
    with pytest.raises(ValueError):
        wa.eval()  # nothing accumulated
    wa.add(2.0, 1.0)
    wa.add(np.array([4.0, 8.0]), 3.0)  # ndarray value averages first
    np.testing.assert_allclose(wa.eval(), (2.0 * 1 + 6.0 * 3) / 4.0)
    wa.reset()
    with pytest.raises(ValueError):
        wa.eval()
    with pytest.raises(ValueError):
        wa.add("nan", 1.0)


def test_chunk_evaluator_accumulates_across_batches():
    # the known IOB case from test_layers_tail_r4: per batch 2 inferred
    # chunks, 3 labeled, 1 correct
    inf = np.array([[0, 1, 4, 2, 3, 4]], np.int64)
    lab = np.array([[0, 1, 4, 2, 1, 4]], np.int64)
    iv = L.data(name="i", shape=[6], dtype="int64")
    lv = L.data(name="l", shape=[6], dtype="int64")
    ev = pt.evaluator.ChunkEvaluator(iv, lv, "IOB", 2)
    exe = pt.Executor()
    exe.run(pt.default_startup_program())
    for _ in range(2):
        exe.run(pt.default_main_program(), feed={"i": inf, "l": lab},
                fetch_list=ev.metrics)
    p, r, f1 = ev.eval(exe)
    np.testing.assert_allclose(p, [0.5], atol=1e-6)
    np.testing.assert_allclose(r, [1.0 / 3.0], atol=1e-6)
    np.testing.assert_allclose(f1, [0.4], atol=1e-6)
    # reset() zeroes the running counts
    ev.reset(exe)
    p, r, f1 = ev.eval(exe)
    assert float(p[0]) == 0.0 and float(r[0]) == 0.0 and float(f1[0]) == 0.0


def test_edit_distance_evaluator_rates():
    hv = L.data(name="h", shape=[3], dtype="int64")
    rv = L.data(name="r", shape=[3], dtype="int64")
    ev = pt.evaluator.EditDistance(hv, rv)
    exe = pt.Executor()
    exe.run(pt.default_startup_program())
    main = pt.default_main_program()
    # batch 1: one substitution -> distance 1; batch 2: exact -> distance 0
    exe.run(main, feed={"h": np.array([[1, 2, 3]], np.int64),
                        "r": np.array([[1, 2, 4]], np.int64)},
            fetch_list=ev.metrics)
    exe.run(main, feed={"h": np.array([[1, 2, 3]], np.int64),
                        "r": np.array([[1, 2, 3]], np.int64)},
            fetch_list=ev.metrics)
    avg, err_rate = ev.eval(exe)
    # layers.edit_distance default normalizes by label length: (1/3 + 0)/2
    np.testing.assert_allclose(avg, [1.0 / 6.0], atol=1e-6)
    np.testing.assert_allclose(err_rate, [0.5], atol=1e-6)  # 1 of 2 wrong


def test_net_drawer_emits_dot(tmp_path):
    x = L.data(name="x", shape=[4], dtype="float32")
    loss = L.mean(L.fc(x, size=2))
    main = pt.default_main_program()
    dot = pt.net_drawer.parse_graph(main)
    assert dot.startswith("digraph") and "mul" in dot and "mean" in dot
    out = tmp_path / "g.dot"
    pt.net_drawer.draw_graph(pt.default_startup_program(), main,
                             graph_path=str(out))
    assert out.read_text() == dot


def test_install_check_single_and_parallel(capsys):
    # conftest pins an 8-device virtual CPU mesh, so this drives BOTH the
    # single-device and the CompiledProgram data-parallel arm
    pt.install_check.run_check()
    out = capsys.readouterr().out
    assert "installed successfully" in out
    assert "MUTIPLE" in out  # the reference's own spelling, kept verbatim
