"""Fused/ring attention tests. On the CPU test mesh the fused op runs the
jnp reference path — numerics vs hand-built attention; ring attention runs
under a real 8-way shard_map and must match full-sequence attention."""
import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import layers as L


def _np_attention(q, k, v, causal=False, scale=None):
    scale = scale or q.shape[-1] ** -0.5
    s = np.einsum("bhqd,bhkd->bhqk", q, k) * scale
    if causal:
        sq, sk = s.shape[-2], s.shape[-1]
        mask = np.tril(np.ones((sq, sk), bool), sk - sq)
        s = np.where(mask, s, -1e9)
    e = np.exp(s - s.max(-1, keepdims=True))
    p = e / e.sum(-1, keepdims=True)
    return np.einsum("bhqk,bhkd->bhqd", p, v)


def test_fused_attention_matches_reference():
    B, nh, S, dh = 2, 3, 16, 8
    rng = np.random.default_rng(0)
    qv = rng.standard_normal((B, nh, S, dh)).astype(np.float32)
    kv = rng.standard_normal((B, nh, S, dh)).astype(np.float32)
    vv = rng.standard_normal((B, nh, S, dh)).astype(np.float32)
    q = L.data(name="q", shape=[nh, S, dh], dtype="float32")
    k = L.data(name="k", shape=[nh, S, dh], dtype="float32")
    v = L.data(name="v", shape=[nh, S, dh], dtype="float32")
    out = L.fused_attention(q, k, v)
    out_c = L.fused_attention(q, k, v, causal=True)
    exe = pt.Executor()
    got, got_c = exe.run(pt.default_main_program(),
                         feed={"q": qv, "k": kv, "v": vv},
                         fetch_list=[out, out_c])
    np.testing.assert_allclose(got, _np_attention(qv, kv, vv), rtol=2e-4,
                               atol=2e-5)
    np.testing.assert_allclose(got_c, _np_attention(qv, kv, vv, causal=True),
                               rtol=2e-4, atol=2e-5)


def test_fused_attention_grads_flow():
    B, nh, S, dh = 2, 2, 8, 4
    q = L.data(name="q", shape=[nh, S, dh], dtype="float32")
    k = L.data(name="k", shape=[nh, S, dh], dtype="float32")
    v = L.data(name="v", shape=[nh, S, dh], dtype="float32")
    h = L.fc(L.reshape(L.fused_attention(q, k, v), shape=[0, nh * S * dh]),
             size=1)
    loss = L.mean(h)
    pt.optimizer.SGD(0.1).minimize(loss)
    exe = pt.Executor()
    exe.run(pt.default_startup_program())
    rng = np.random.default_rng(1)
    feed = {n: rng.standard_normal((B, nh, S, dh)).astype(np.float32)
            for n in ("q", "k", "v")}
    (lv,) = exe.run(pt.default_main_program(), feed=feed, fetch_list=[loss])
    assert np.isfinite(float(lv))


def test_ring_attention_matches_full_attention():
    """shard_map over sp=8: ring attention on sequence shards == full attn."""
    import jax
    from jax.sharding import PartitionSpec as P

    from paddle_tpu.ops.attention_ops import ring_attention_local
    from paddle_tpu.parallel import make_mesh

    mesh = make_mesh({"sp": 8})
    B, nh, S, dh = 2, 2, 64, 8  # S/p = 8 per device
    rng = np.random.default_rng(2)
    qv = rng.standard_normal((B, nh, S, dh)).astype(np.float32)
    kv = rng.standard_normal((B, nh, S, dh)).astype(np.float32)
    vv = rng.standard_normal((B, nh, S, dh)).astype(np.float32)

    from paddle_tpu.ops.collective_ops import compat_shard_map as shard_map_fn

    fn = shard_map_fn(
        lambda q, k, v: ring_attention_local(q, k, v, "sp", sm_scale=dh ** -0.5),
        mesh=mesh,
        in_specs=(P(None, None, "sp", None),) * 3,
        out_specs=P(None, None, "sp", None),
    )
    got = np.asarray(jax.jit(fn)(qv, kv, vv))
    want = _np_attention(qv, kv, vv)
    np.testing.assert_allclose(got, want, rtol=2e-4, atol=2e-5)


def test_ring_attention_causal_matches():
    import jax
    from jax.sharding import PartitionSpec as P

    from paddle_tpu.ops.attention_ops import ring_attention_local
    from paddle_tpu.parallel import make_mesh

    mesh = make_mesh({"sp": 4})
    B, nh, S, dh = 1, 2, 32, 8
    rng = np.random.default_rng(3)
    qv = rng.standard_normal((B, nh, S, dh)).astype(np.float32)
    kv = rng.standard_normal((B, nh, S, dh)).astype(np.float32)
    vv = rng.standard_normal((B, nh, S, dh)).astype(np.float32)
    from paddle_tpu.ops.collective_ops import compat_shard_map as shard_map_fn

    fn = shard_map_fn(
        lambda q, k, v: ring_attention_local(q, k, v, "sp", causal=True, sm_scale=dh ** -0.5),
        mesh=mesh,
        in_specs=(P(None, None, "sp", None),) * 3,
        out_specs=P(None, None, "sp", None),
    )
    got = np.asarray(jax.jit(fn)(qv, kv, vv))
    want = _np_attention(qv, kv, vv, causal=True)
    np.testing.assert_allclose(got, want, rtol=2e-4, atol=2e-5)


def test_ring_attention_grads():
    """BPTT through the ring: grads of a loss over ring attention are finite
    and match the full-attention grads."""
    import jax
    from jax.sharding import PartitionSpec as P

    from paddle_tpu.ops.attention_ops import (
        _reference_attention, ring_attention_local)
    from paddle_tpu.parallel import make_mesh

    mesh = make_mesh({"sp": 4})
    B, nh, S, dh = 1, 1, 16, 4
    rng = np.random.default_rng(4)
    qv = rng.standard_normal((B, nh, S, dh)).astype(np.float32)
    kv = rng.standard_normal((B, nh, S, dh)).astype(np.float32)
    vv = rng.standard_normal((B, nh, S, dh)).astype(np.float32)
    from paddle_tpu.ops.collective_ops import compat_shard_map as shard_map_fn

    ring = shard_map_fn(
        lambda q, k, v: ring_attention_local(q, k, v, "sp", sm_scale=dh ** -0.5),
        mesh=mesh,
        in_specs=(P(None, None, "sp", None),) * 3,
        out_specs=P(None, None, "sp", None),
    )
    g_ring = jax.grad(lambda q: jax.jit(ring)(q, kv, vv).sum())(qv)
    g_full = jax.grad(
        lambda q: _reference_attention(q, kv, vv, sm_scale=dh ** -0.5).sum()
    )(qv)
    np.testing.assert_allclose(np.asarray(g_ring), np.asarray(g_full),
                               rtol=5e-4, atol=5e-5)


def test_transformer_uses_fused_attention():
    from paddle_tpu.models import transformer

    cfg = transformer.bert_tiny(use_tp=False)
    cfg.use_flash_attention = True
    main, startup = pt.Program(), pt.Program()
    with pt.program_guard(main, startup):
        transformer.bert_pretrain(cfg, seq_len=16)
    types = [op.type for op in main.global_block.ops]
    assert "fused_attention" in types


def test_ring_attention_program_trains_under_collective():
    """The full program path VERDICT asked for: L.ring_attention inside an
    executor program, append_backward through the ring op, executed under
    with_collective on an sp mesh — parameter trajectory matches the same
    program run single-device (where the ring op is plain attention)."""
    from paddle_tpu.incubate.fleet import UserDefinedRoleMaker, fleet
    from paddle_tpu.parallel import make_mesh
    from paddle_tpu.parallel.mesh import get_comm_context
    from paddle_tpu.parallel.sharding import annotate_sharding

    B, nh, S, dh = 2, 2, 16, 4
    rng = np.random.default_rng(0)
    qkv_in = rng.standard_normal((B, nh, S, dh)).astype(np.float32)
    tgt = rng.standard_normal((B, nh, S, dh)).astype(np.float32)

    def build(sp):
        x = L.data(name="x", shape=[nh, S, dh], dtype="float32")
        t = L.data(name="t", shape=[nh, S, dh], dtype="float32")
        if sp:
            # sequence-parallel feeds: dim 2 (seq) shards over the sp axis
            annotate_sharding(x, (None, None, "sp", None))
            annotate_sharding(t, (None, None, "sp", None))
        q = L.fc(x, size=dh, num_flatten_dims=3, name="q")
        out = L.ring_attention(q, x, x, sm_scale=dh ** -0.5, ring_id=5)
        loss = L.mean(L.square_error_cost(out, t))
        return loss

    def run_single():
        main, startup = pt.Program(), pt.Program()
        main.random_seed = startup.random_seed = 9
        with pt.program_guard(main, startup):
            with pt.unique_name.guard():
                loss = build(sp=False)
                pt.optimizer.SGD(0.1).minimize(loss)
        exe = pt.Executor()
        scope = pt.Scope()
        with pt.scope_guard(scope):
            exe.run(startup)
            for _ in range(4):
                exe.run(main, feed={"x": qkv_in, "t": tgt},
                        fetch_list=[loss.name])
            return np.asarray(scope.find_var("q.w_0"))

    def run_ring():
        mesh = make_mesh({"sp": 8})
        get_comm_context().register_ring(5, "sp")
        try:
            main, startup = pt.Program(), pt.Program()
            main.random_seed = startup.random_seed = 9
            with pt.program_guard(main, startup):
                with pt.unique_name.guard():
                    loss = build(sp=True)
                    # fleet grad-allreduce: local (per-seq-shard) grads
                    # average over sp, reproducing the full-sequence grad
                    fleet.init(UserDefinedRoleMaker(worker_num=8), mesh=mesh)
                    opt = fleet.distributed_optimizer(pt.optimizer.SGD(0.1))
                    opt.minimize(loss)
            exe = pt.Executor()
            scope = pt.Scope()
            with pt.scope_guard(scope):
                exe.run(startup)
                compiled = pt.CompiledProgram(main).with_collective(mesh=mesh)
                for _ in range(4):
                    exe.run(compiled, feed={"x": qkv_in, "t": tgt},
                            fetch_list=[loss.name])
                return np.asarray(scope.find_var("q.w_0"))
        finally:
            get_comm_context().unregister_ring(5)

    base_w = run_single()
    ring_w = run_ring()
    np.testing.assert_allclose(base_w, ring_w, rtol=1e-4, atol=1e-5)
