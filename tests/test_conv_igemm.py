"""Numeric-equivalence regression tests for the round-6 conv levers:

  * implicit-GEMM conv lowering (FLAGS_conv_implicit_gemm) vs direct conv —
    forward AND gradients (the trained-weight trajectory captures the vjp),
    NHWC and NCHW, strided + padded (incl. asymmetric 4-element) + dilated +
    1x1-as-matmul cases;
  * fused one-pass BN statistics (FLAGS_bn_fuse_stats -> conv2d_bn) vs the
    two-pass conv2d + batch_norm pair, including running-stat updates and
    the AMP bf16 path;
  * the per-shape cost-model auto gate and the fusion pass's bail-out rules.

Tolerances: 1e-5 for fp32 paths (pure reassociation noise), a bf16 band for
AMP (ISSUE 5 acceptance).
"""
import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import flags
from paddle_tpu import layers as L


@pytest.fixture(autouse=True)
def _restore_lever_flags():
    saved = {k: flags.get_flag(k)
             for k in ("conv_implicit_gemm", "bn_fuse_stats")}
    yield
    flags.set_flags(saved)


def _set(igemm="off", fuse=False):
    flags.set_flags({"conv_implicit_gemm": igemm, "bn_fuse_stats": fuse})


def _train_conv(fmt, k, stride, pad, dil=1, bn=False, act=None, steps=2,
                cin=3, cout=8, hw=12, batch=4, seed=7):
    """Build data->conv2d[->bn]->mean, train `steps` SGD steps; return the
    per-step losses, the updated conv weight, and the program."""
    main, startup = pt.Program(), pt.Program()
    main.random_seed = startup.random_seed = seed
    rng = np.random.default_rng(seed)
    x = rng.standard_normal(
        (batch, cin, hw, hw) if fmt == "NCHW" else (batch, hw, hw, cin)
    ).astype(np.float32)
    with pt.program_guard(main, startup), pt.unique_name.guard():
        shape = [cin, hw, hw] if fmt == "NCHW" else [hw, hw, cin]
        img = L.data(name="img", shape=shape, dtype="float32")
        y = L.conv2d(img, num_filters=cout, filter_size=k, stride=stride,
                     padding=pad, dilation=dil, bias_attr=False, name="c",
                     data_format=fmt)
        if bn:
            y = L.batch_norm(y, act=act, name="c.bn", data_layout=fmt)
        # square the activations so the loss's curvature exercises the
        # gradient beyond a constant cotangent
        loss = L.mean(L.square(y))
        pt.optimizer.SGD(0.05).minimize(loss)
    exe = pt.Executor()
    losses = []
    with pt.scope_guard(pt.Scope()):
        exe.run(startup)
        for _ in range(steps):
            (lv,) = exe.run(main, feed={"img": x}, fetch_list=[loss])
            losses.append(float(np.asarray(lv)))
        w = np.asarray(pt.global_scope().find_var("c.w_0"))
        stats = {}
        if bn:
            for n in ("c.bn.mean", "c.bn.var"):
                v = pt.global_scope().find_var(n)
                if v is not None:
                    stats[n] = np.asarray(v).copy()
    return losses, w, stats, main


CASES = [
    ("NHWC", 3, 1, 1, 1),
    ("NCHW", 3, 1, 1, 1),
    ("NHWC", 3, 2, 1, 1),          # strided
    ("NCHW", 5, 2, 2, 1),          # bigger kernel, strided
    ("NHWC", 4, 1, [2, 1, 2, 1], 1),   # asymmetric 4-element padding
    ("NCHW", 4, 2, [2, 1, 2, 1], 1),
    ("NHWC", 3, 1, 2, 2),          # dilated
    ("NHWC", 1, 1, 0, 1),          # 1x1 as [B*H*W, C] matmul
    ("NCHW", 1, 2, 0, 1),          # strided 1x1
]


@pytest.mark.parametrize("fmt,k,stride,pad,dil", CASES)
def test_igemm_matches_direct_conv_fwd_and_grad(fmt, k, stride, pad, dil):
    _set(igemm="off")
    ref_losses, ref_w, _, _ = _train_conv(fmt, k, stride, pad, dil)
    _set(igemm="on")
    ig_losses, ig_w, _, _ = _train_conv(fmt, k, stride, pad, dil)
    # step-2 loss depends on step-1 gradients: this equality IS the vjp test
    np.testing.assert_allclose(ig_losses, ref_losses, rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(ig_w, ref_w, rtol=1e-5, atol=1e-6)


def test_igemm_grouped_conv_falls_back_to_direct():
    # groups != 1 is ineligible: forced-on must still produce direct-conv
    # numerics (the gate, not the lowering, owns the decision)
    _set(igemm="on")
    main, startup = pt.Program(), pt.Program()
    with pt.program_guard(main, startup), pt.unique_name.guard():
        img = L.data(name="img", shape=[4, 8, 8], dtype="float32")
        y = L.conv2d(img, num_filters=4, filter_size=3, padding=1, groups=2,
                     bias_attr=False)
        loss = L.mean(y)
    exe = pt.Executor()
    with pt.scope_guard(pt.Scope()):
        exe.run(startup)
        (lv,) = exe.run(main, feed={"img": np.ones((2, 4, 8, 8), np.float32)},
                        fetch_list=[loss])
    assert np.isfinite(float(np.asarray(lv)))


def test_auto_cost_model_per_shape():
    from paddle_tpu.ops.nn_ops import _igemm_predict_win

    # RN50 s0 interior 3x3 (b128, 56^2, 64->64, bf16): the 9x patch tensor
    # through HBM costs ~4x the direct conv's MXU time — must NOT take igemm
    assert not _igemm_predict_win(128, 56, 56, 64, 64, 3, 3, 2)
    # the raw 7x7-s2 stem (3->64 @ 112^2 out): K=3 direct fill is ~2% of the
    # MXU lanes; folding to K=147 pays even at 9x traffic
    assert _igemm_predict_win(128, 112, 112, 3, 64, 7, 7, 4)
    # wide-channel stages fill the lanes already — no win to buy
    assert not _igemm_predict_win(128, 14, 14, 256, 256, 3, 3, 2)


def test_auto_gate_respects_mode_flag():
    import jax.numpy as jnp

    from paddle_tpu.ops.nn_ops import _igemm_take

    x = jnp.zeros((128, 112, 112, 3), jnp.float32)
    w = jnp.zeros((7, 7, 3, 64), jnp.float32)
    args = (x, w, (2, 2), [(3, 3), (3, 3)], (1, 1), 1, "NHWC")
    _set(igemm="auto")
    assert _igemm_take(*args)
    _set(igemm="off")
    assert not _igemm_take(*args)
    _set(igemm="on")
    assert _igemm_take(*args)
    # int dtypes never take the GEMM path
    _set(igemm="on")
    assert not _igemm_take(x.astype(jnp.int32), w.astype(jnp.int32), *args[2:])


# ---------------------------------------------------------------------------
# fused one-pass BN statistics
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("fmt,stride", [("NHWC", 1), ("NCHW", 1),
                                        ("NHWC", 2), ("NCHW", 2)])
def test_fused_bn_stats_matches_two_pass(fmt, stride):
    _set(fuse=False)
    ref_losses, ref_w, ref_stats, ref_p = _train_conv(
        fmt, 3, stride, 1, bn=True, act="relu", steps=3)
    _set(fuse=True)
    fu_losses, fu_w, fu_stats, fu_p = _train_conv(
        fmt, 3, stride, 1, bn=True, act="relu", steps=3)
    types = [op.type for op in fu_p.global_block.ops]
    assert "conv2d_bn" in types and "batch_norm" not in types
    assert "batch_norm" in [op.type for op in ref_p.global_block.ops]
    np.testing.assert_allclose(fu_losses, ref_losses, rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(fu_w, ref_w, rtol=1e-5, atol=1e-6)
    # running statistics (the stateful MeanOut/VarianceOut writes) must
    # track the two-pass op exactly, and must have moved off their init
    assert ref_stats and fu_stats.keys() == ref_stats.keys()
    for n in ref_stats:
        np.testing.assert_allclose(fu_stats[n], ref_stats[n],
                                   rtol=1e-5, atol=1e-6)
    assert not np.allclose(fu_stats[[n for n in fu_stats
                                     if n.endswith(".mean")][0]], 0.0)


def test_fused_bn_with_igemm_accumulator():
    # both levers together: stats come from the fp32 GEMM accumulator
    _set(igemm="off", fuse=False)
    ref_losses, ref_w, _, _ = _train_conv("NHWC", 3, 1, 1, bn=True, steps=3)
    _set(igemm="on", fuse=True)
    both_losses, both_w, _, _ = _train_conv("NHWC", 3, 1, 1, bn=True, steps=3)
    np.testing.assert_allclose(both_losses, ref_losses, rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(both_w, ref_w, rtol=1e-5, atol=1e-6)


def test_fuse_pass_bails_on_shared_or_biased_or_test_bn():
    from paddle_tpu.passes import fuse_conv_bn_stats

    # (a) conv output consumed twice -> no fusion
    main, startup = pt.Program(), pt.Program()
    with pt.program_guard(main, startup), pt.unique_name.guard():
        img = L.data(name="img", shape=[8, 8, 3], dtype="float32")
        y = L.conv2d(img, num_filters=4, filter_size=3, padding=1,
                     bias_attr=False, data_format="NHWC")
        z = L.batch_norm(y, data_layout="NHWC")
        out = L.elementwise_add(z, y)  # second consumer of the conv output
    assert fuse_conv_bn_stats(main) == 0
    # (b) conv with bias: elementwise_add owns the conv output, BN reads the
    # add's output -> pattern must not match
    main2, startup2 = pt.Program(), pt.Program()
    with pt.program_guard(main2, startup2), pt.unique_name.guard():
        img = L.data(name="img", shape=[8, 8, 3], dtype="float32")
        y = L.conv2d(img, num_filters=4, filter_size=3, padding=1,
                     data_format="NHWC")  # bias on
        z = L.batch_norm(y, data_layout="NHWC")
    assert fuse_conv_bn_stats(main2) == 0
    # (c) inference-mode BN has no statistics pass to fuse
    main3, startup3 = pt.Program(), pt.Program()
    with pt.program_guard(main3, startup3), pt.unique_name.guard():
        img = L.data(name="img", shape=[8, 8, 3], dtype="float32")
        y = L.conv2d(img, num_filters=4, filter_size=3, padding=1,
                     bias_attr=False, data_format="NHWC")
        z = L.batch_norm(y, is_test=True, data_layout="NHWC")
    assert fuse_conv_bn_stats(main3) == 0
    # (d) the eligible pattern DOES fuse
    main4, startup4 = pt.Program(), pt.Program()
    with pt.program_guard(main4, startup4), pt.unique_name.guard():
        img = L.data(name="img", shape=[8, 8, 3], dtype="float32")
        y = L.conv2d(img, num_filters=4, filter_size=3, padding=1,
                     bias_attr=False, data_format="NHWC")
        z = L.batch_norm(y, data_layout="NHWC")
    assert fuse_conv_bn_stats(main4) == 1
    types = [op.type for op in main4.global_block.ops]
    assert "conv2d_bn" in types
    assert "conv2d" not in types and "batch_norm" not in types


def test_fused_bn_under_amp_bf16_band():
    """AMP path: decorate() rewrites to bf16 first, the fusion pass runs at
    minimize underneath it — the fused arm must stay inside bf16 noise of
    the two-pass arm over a short trajectory."""

    def run(fuse):
        _set(fuse=fuse)
        main, startup = pt.Program(), pt.Program()
        main.random_seed = startup.random_seed = 11
        rng = np.random.default_rng(3)
        x = rng.standard_normal((4, 10, 10, 3)).astype(np.float32)
        with pt.program_guard(main, startup), pt.unique_name.guard():
            img = L.data(name="img", shape=[10, 10, 3], dtype="float32")
            y = L.conv2d(img, num_filters=8, filter_size=3, padding=1,
                         bias_attr=False, name="c", data_format="NHWC")
            y = L.batch_norm(y, act="relu", name="c.bn", data_layout="NHWC")
            loss = L.mean(L.square(y))
            opt = pt.contrib.mixed_precision.decorate(pt.optimizer.SGD(0.05))
            opt.minimize(loss)
        if fuse:
            assert "conv2d_bn" in [op.type for op in main.global_block.ops]
        exe = pt.Executor()
        with pt.scope_guard(pt.Scope()):
            exe.run(startup)
            for _ in range(3):
                (lv,) = exe.run(main, feed={"img": x}, fetch_list=[loss])
        return float(np.asarray(lv))

    ref, fused = run(False), run(True)
    assert np.isfinite(ref) and np.isfinite(fused)
    # bf16 has ~3 decimal digits; a 3-step trajectory stays within ~1%
    assert abs(fused - ref) <= 2e-2 * max(abs(ref), 1e-3)


def test_resnet_cifar_end_to_end_levers_match():
    """Whole-model check: resnet_cifar10 trained 2 steps with both levers on
    matches the baseline step-for-step (the model wiring — shortcuts,
    stride-2 blocks, global pool — picked the fused ops up unchanged)."""
    from paddle_tpu.models import resnet

    def run(igemm, fuse):
        _set(igemm=igemm, fuse=fuse)
        main, startup = pt.Program(), pt.Program()
        main.random_seed = startup.random_seed = 9
        rng = np.random.default_rng(5)
        img = rng.standard_normal((4, 3, 32, 32)).astype(np.float32)
        lbl = rng.integers(0, 10, (4, 1)).astype(np.int64)
        with pt.program_guard(main, startup), pt.unique_name.guard():
            loss, acc, _ = resnet.resnet_cifar10()
            pt.optimizer.Momentum(0.05, 0.9).minimize(loss)
        n_fused = sum(op.type == "conv2d_bn"
                      for op in main.global_block.ops)
        exe = pt.Executor()
        out = []
        with pt.scope_guard(pt.Scope()):
            exe.run(startup)
            for _ in range(2):
                (lv,) = exe.run(main, feed={"img": img, "label": lbl},
                                fetch_list=[loss])
                out.append(float(np.asarray(lv)))
        return out, n_fused

    ref, n0 = run("off", False)
    lev, n1 = run("on", True)
    assert n0 == 0
    # every conv in the cifar net feeds a training BN directly -> all fuse
    assert n1 > 10
    np.testing.assert_allclose(lev, ref, rtol=2e-5, atol=1e-6)
