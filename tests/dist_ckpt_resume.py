"""Kill-and-resume trainer (pattern of the dist_*.py launchers): trains the
MNIST-style MLP under a CheckpointedRunner, appending one "step loss" line
per step to a trajectory file. With KILL_AT >= 0 the process SIGKILLs
ITSELF right after recording that step — a real uncatchable preemption mid-
run, after the step's loss is durable but (with save cadence 1) within one
checkpoint of the crash. A fresh invocation on the same checkpoint root
resumes from latest_step() and must reproduce the remaining trajectory
bit-for-bit.

usage: dist_ckpt_resume.py CKPT_ROOT LOSSES_FILE TOTAL_STEPS KILL_AT
       (KILL_AT = -1: run to completion)
"""
import os
import signal
import sys

import jax

jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402

import paddle_tpu as pt  # noqa: E402
from paddle_tpu import layers as L  # noqa: E402
from paddle_tpu.resilience import CheckpointManager, CheckpointedRunner  # noqa: E402


def build():
    img = L.data(name="img", shape=[64], dtype="float32")
    label = L.data(name="label", shape=[1], dtype="int64")
    h = L.fc(img, size=32, act="relu")
    logits = L.fc(h, size=10)
    loss = L.mean(L.softmax_with_cross_entropy(logits, label))
    return loss


def feed_fn(step):
    # pure in the step index: a resumed process regenerates exactly the
    # batches the dead one saw
    rng = np.random.default_rng(1000 + step)
    x = rng.standard_normal((32, 64)).astype(np.float32)
    w = np.random.default_rng(77).standard_normal((64, 10)).astype(np.float32)
    y = np.argmax(x @ w, axis=1).astype(np.int64)[:, None]
    return {"img": x, "label": y}


def main():
    root, losses_path, total_steps, kill_at = (
        sys.argv[1], sys.argv[2], int(sys.argv[3]), int(sys.argv[4]))

    main_p, startup = pt.Program(), pt.Program()
    main_p.random_seed = 7
    startup.random_seed = 7
    with pt.program_guard(main_p, startup):
        with pt.unique_name.guard():
            loss = build()
            pt.optimizer.SGD(0.1).minimize(loss)

    exe = pt.Executor()
    exe.run(startup)  # a later resume() overwrites this init from the ckpt
    runner = CheckpointedRunner(
        exe, CheckpointManager(root, keep_last_k=3, main_program=main_p),
        main_program=main_p, save_every=1, max_retries=5)

    f = open(losses_path, "a")

    def on_step(step, outs):
        f.write(f"{step} {float(np.asarray(outs[0]).reshape(-1)[0]):.17g}\n")
        f.flush()
        os.fsync(f.fileno())
        if step == kill_at:
            os.kill(os.getpid(), signal.SIGKILL)  # preemption, uncatchable

    out = runner.run(feed_fn, total_steps, fetch_list=[loss],
                     on_step=on_step)
    f.close()
    print(f"done start={out['start_step']} retries={out['retries']}")


if __name__ == "__main__":
    main()
