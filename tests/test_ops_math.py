"""Per-op correctness + gradient tests (reference pattern: test_*_op.py)."""
import numpy as np
import pytest

from op_test import OpTest

rng = np.random.default_rng(42)


@pytest.fixture(autouse=True)
def _reseed():
    """Each test draws from a freshly-seeded rng so results don't depend on
    which tests ran before (and failures reproduce in isolation)."""
    global rng
    rng = np.random.default_rng(42)
    yield


def _r(*shape):
    return rng.standard_normal(shape).astype(np.float32)


class TestMul(OpTest):
    def test_output_and_grad(self):
        x, y = _r(4, 5), _r(5, 3)
        self.setup("mul", {"X": x, "Y": y}, {"Out": x @ y},
                   {"x_num_col_dims": 1, "y_num_col_dims": 1})
        self.check_output()
        self.check_grad(["X_in", "Y_in"], "Out")

    def test_4d_flatten(self):
        x, y = _r(2, 3, 2, 2), _r(12, 4)
        self.setup("mul", {"X": x, "Y": y}, {"Out": (x.reshape(2, 12) @ y).reshape(2, 4)},
                   {"x_num_col_dims": 1, "y_num_col_dims": 1})
        self.check_output()


class TestMatmul(OpTest):
    def test_batched(self):
        x, y = _r(3, 4, 5), _r(3, 5, 6)
        self.setup("matmul", {"X": x, "Y": y}, {"Out": np.matmul(x, y)}, {})
        self.check_output(atol=1e-4, rtol=1e-4)
        self.check_grad(["X_in", "Y_in"], "Out")

    def test_transpose(self):
        x, y = _r(5, 4), _r(5, 6)
        self.setup("matmul", {"X": x, "Y": y}, {"Out": x.T @ y}, {"transpose_X": True})
        self.check_output(atol=1e-4, rtol=1e-4)


class TestElementwise(OpTest):
    def test_add_broadcast_axis(self):
        x, y = _r(2, 3, 4), _r(3)
        self.setup("elementwise_add", {"X": x, "Y": y},
                   {"Out": x + y.reshape(1, 3, 1)}, {"axis": 1})
        self.check_output()
        self.check_grad(["X_in", "Y_in"], "Out")

    def test_mul_same_shape(self):
        x, y = _r(3, 4), _r(3, 4)
        self.setup("elementwise_mul", {"X": x, "Y": y}, {"Out": x * y}, {})
        self.check_output()
        self.check_grad(["X_in", "Y_in"], "Out")

    def test_div(self):
        x = _r(3, 4)
        y = np.abs(_r(3, 4)) + 1.0
        self.setup("elementwise_div", {"X": x, "Y": y}, {"Out": x / y}, {})
        self.check_output()
        self.check_grad(["X_in", "Y_in"], "Out", max_relative_error=1e-2)


class TestReduce(OpTest):
    def test_sum_axis(self):
        x = _r(3, 4, 5)
        self.setup("reduce_sum", {"X": x}, {"Out": x.sum(1)}, {"dim": [1], "keep_dim": False})
        self.check_output()
        self.check_grad(["X_in"], "Out")

    def test_mean_all(self):
        x = _r(3, 4)
        self.setup("reduce_mean", {"X": x}, {"Out": np.asarray(x.mean())},
                   {"dim": [0], "reduce_all": True, "keep_dim": False})
        self.check_output()

    def test_max(self):
        x = _r(4, 5)
        self.setup("reduce_max", {"X": x}, {"Out": x.max(1)}, {"dim": [1], "keep_dim": False})
        self.check_output()


class TestActivations(OpTest):
    def test_relu(self):
        x = _r(3, 4)
        x[np.abs(x) < 0.05] += 0.2  # keep away from the kink
        self.setup("relu", {"X": x}, {"Out": np.maximum(x, 0)}, {})
        self.check_output()
        self.check_grad(["X_in"], "Out")

    def test_sigmoid(self):
        x = _r(3, 4)
        self.setup("sigmoid", {"X": x}, {"Out": 1 / (1 + np.exp(-x))}, {})
        self.check_output()
        self.check_grad(["X_in"], "Out")

    def test_tanh_gelu(self):
        x = _r(3, 4)
        self.setup("tanh", {"X": x}, {"Out": np.tanh(x)}, {})
        self.check_output()
        self.check_grad(["X_in"], "Out")

    def test_leaky_relu(self):
        x = _r(3, 4)
        x[np.abs(x) < 0.05] += 0.2
        self.setup("leaky_relu", {"X": x}, {"Out": np.where(x >= 0, x, 0.1 * x)}, {"alpha": 0.1})
        self.check_output()


class TestSoftmaxXent(OpTest):
    def test_softmax(self):
        x = _r(4, 7)
        e = np.exp(x - x.max(-1, keepdims=True))
        self.setup("softmax", {"X": x}, {"Out": e / e.sum(-1, keepdims=True)}, {"axis": -1})
        self.check_output()
        # weighted target: sum(softmax) is identically n_rows, so the plain
        # sum's true gradient is ZERO everywhere and the unweighted check
        # compared nothing but fp32 evaluation noise against the 1e-3
        # denominator floor (the pre-existing tier-1 failure)
        self.check_grad(["X_in"], "Out", weighted=True)

    def test_softmax_with_cross_entropy(self):
        logits = _r(5, 10)
        label = rng.integers(0, 10, (5, 1)).astype(np.int64)
        e = np.exp(logits - logits.max(-1, keepdims=True))
        sm = e / e.sum(-1, keepdims=True)
        loss = -np.log(sm[np.arange(5), label[:, 0]])[:, None]
        self.setup(
            "softmax_with_cross_entropy",
            {"Logits": logits, "Label": label},
            {"Softmax": sm, "Loss": loss},
            {},
        )
        self.check_output(atol=1e-4)
        self.check_grad(["Logits_in"], "Loss", max_relative_error=3e-2)

    def test_cross_entropy_soft(self):
        probs = np.abs(_r(4, 6)) + 0.1
        probs /= probs.sum(-1, keepdims=True)
        soft = np.abs(_r(4, 6))
        soft /= soft.sum(-1, keepdims=True)
        expected = -(soft * np.log(probs + 1e-12)).sum(-1, keepdims=True)
        self.setup(
            "cross_entropy",
            {"X": probs.astype(np.float32), "Label": soft.astype(np.float32)},
            {"Y": expected},
            {"soft_label": True},
        )
        self.check_output(atol=1e-4)


class TestConvPool(OpTest):
    def test_conv2d(self):
        import jax
        x, w = _r(2, 3, 8, 8), _r(4, 3, 3, 3)
        ref = np.asarray(
            jax.lax.conv_general_dilated(
                x, w, (1, 1), [(1, 1), (1, 1)], dimension_numbers=("NCHW", "OIHW", "NCHW")
            )
        )
        self.setup(
            "conv2d",
            {"Input": x, "Filter": w},
            {"Output": ref},
            {"strides": [1, 1], "paddings": [1, 1], "dilations": [1, 1], "groups": 1},
        )
        self.check_output(atol=1e-4, rtol=1e-4)
        self.check_grad(["Input_in", "Filter_in"], "Output", max_relative_error=2e-2)

    def test_pool2d_max(self):
        x = _r(2, 3, 4, 4)
        ref = x.reshape(2, 3, 2, 2, 2, 2).max(axis=(3, 5))
        self.setup(
            "pool2d",
            {"X": x},
            {"Out": ref},
            {"pooling_type": "max", "ksize": [2, 2], "strides": [2, 2], "paddings": [0, 0]},
        )
        self.check_output()

    def test_pool2d_avg(self):
        x = _r(2, 3, 4, 4)
        ref = x.reshape(2, 3, 2, 2, 2, 2).mean(axis=(3, 5))
        self.setup(
            "pool2d",
            {"X": x},
            {"Out": ref},
            {"pooling_type": "avg", "ksize": [2, 2], "strides": [2, 2], "paddings": [0, 0]},
        )
        self.check_output()


class TestNorms(OpTest):
    def test_layer_norm(self):
        x = _r(4, 10)
        scale = np.abs(_r(10)) + 0.5
        bias = _r(10)
        mean = x.mean(-1, keepdims=True)
        var = x.var(-1, keepdims=True)
        y = (x - mean) / np.sqrt(var + 1e-5) * scale + bias
        self.setup(
            "layer_norm",
            {"X": x, "Scale": scale, "Bias": bias},
            {
                "Y": y,
                "Mean": mean.reshape(4),
                "Variance": var.reshape(4),
            },
            {"begin_norm_axis": 1, "epsilon": 1e-5},
        )
        self.check_output(atol=1e-4)
        self.check_grad(["X_in", "Scale_in", "Bias_in"], "Y", max_relative_error=2e-2)

    def test_batch_norm_infer(self):
        x = _r(4, 3, 2, 2)
        scale, bias = np.abs(_r(3)) + 0.5, _r(3)
        mean, var = _r(3) * 0.1, np.abs(_r(3)) + 1.0
        y = (x - mean.reshape(1, 3, 1, 1)) / np.sqrt(var.reshape(1, 3, 1, 1) + 1e-5)
        y = y * scale.reshape(1, 3, 1, 1) + bias.reshape(1, 3, 1, 1)
        self.setup(
            "batch_norm",
            {"X": x, "Scale": scale, "Bias": bias, "Mean": mean, "Variance": var},
            {"Y": y},
            {"is_test": True, "epsilon": 1e-5},
        )
        self.check_output(atol=1e-4)


class TestLookupTable(OpTest):
    def test_lookup_and_grad(self):
        w = _r(10, 4)
        ids = rng.integers(0, 10, (5, 1)).astype(np.int64)
        self.setup("lookup_table", {"W": w, "Ids": ids}, {"Out": w[ids[:, 0]]}, {})
        self.check_output()
        self.check_grad(["W_in"], "Out")


class TestTensorOps(OpTest):
    def test_concat_grad(self):
        xs = [("a", _r(2, 3)), ("b", _r(2, 5))]
        self.setup(
            "concat",
            {"X": xs},
            {"Out": np.concatenate([xs[0][1], xs[1][1]], axis=1)},
            {"axis": 1},
        )
        self.check_output()
        self.check_grad(["a", "b"], "Out")

    def test_split(self):
        x = _r(4, 6)
        parts = np.split(x, 3, axis=1)
        self.setup(
            "split",
            {"X": x},
            {"Out": [("o0", parts[0]), ("o1", parts[1]), ("o2", parts[2])]},
            {"axis": 1, "num": 3},
        )
        self.check_output()

    def test_transpose_reshape(self):
        x = _r(2, 3, 4)
        self.setup("transpose2", {"X": x}, {"Out": x.transpose(2, 0, 1)}, {"axis": [2, 0, 1]})
        self.check_output()
        self.check_grad(["X_in"], "Out")

    def test_slice(self):
        x = _r(4, 5, 6)
        self.setup(
            "slice",
            {"Input": x},
            {"Out": x[1:3, :, 2:5]},
            {"axes": [0, 2], "starts": [1, 2], "ends": [3, 5]},
        )
        self.check_output()
        self.check_grad(["Input_in"], "Out")

    def test_gather(self):
        x = _r(8, 3)
        idx = np.array([0, 3, 5], np.int64)
        self.setup("gather", {"X": x, "Index": idx}, {"Out": x[idx]}, {})
        self.check_output()
        self.check_grad(["X_in"], "Out")

    def test_scale_bias(self):
        x = _r(3, 4)
        self.setup("scale", {"X": x}, {"Out": x * 2.5 + 1.0}, {"scale": 2.5, "bias": 1.0})
        self.check_output()
        self.check_grad(["X_in"], "Out")
