"""Linear-chain CRF (reference linear_chain_crf_op + crf_decoding_op):
brute-force enumeration oracle for the partition function and Viterbi path,
numeric gradient check, and an end-to-end tagging train that beats the
emission-only argmax on transition-dependent data."""
import itertools

import numpy as np

import paddle_tpu as pt
from paddle_tpu import layers as L

from op_test import OpTest


def _brute_nll(em, label, w):
    """Enumerate all tag paths: nll = logZ - score(gold)."""
    T, N = em.shape
    start, stop, trans = w[0], w[1], w[2:]

    def score(path):
        s = start[path[0]] + em[0, path[0]]
        for t in range(1, T):
            s += trans[path[t - 1], path[t]] + em[t, path[t]]
        return s + stop[path[-1]]

    scores = [score(p) for p in itertools.product(range(N), repeat=T)]
    log_z = np.log(np.sum(np.exp(np.array(scores) - max(scores)))) + max(scores)
    return log_z - score(list(label)), scores


class TestLinearChainCrf(OpTest):
    def _setup(self):
        rng = np.random.default_rng(0)
        B, T, N = 2, 4, 3
        em = rng.standard_normal((B, T, N)).astype(np.float32)
        w = (rng.standard_normal((N + 2, N)) * 0.5).astype(np.float32)
        label = rng.integers(0, N, (B, T)).astype(np.int64)
        expect = np.array([[_brute_nll(em[b], label[b], w)[0]]
                           for b in range(B)], np.float32)
        self.setup("linear_chain_crf",
                   {"Emission": em, "Transition": w, "Label": label},
                   {"LogLikelihood": expect}, {})

    def test_output(self):
        self._setup()
        self.check_output(atol=1e-4)

    def test_grad(self):
        self._setup()
        self.check_grad(["Emission_in", "Transition_in"], "LogLikelihood",
                        max_relative_error=2e-2, no_grad_set={"Label_in"})


def test_crf_decoding_matches_bruteforce():
    rng = np.random.default_rng(1)
    B, T, N = 3, 4, 3
    em = rng.standard_normal((B, T, N)).astype(np.float32)
    w = (rng.standard_normal((N + 2, N)) * 0.5).astype(np.float32)
    main, startup = pt.Program(), pt.Program()
    with pt.program_guard(main, startup):
        blk = main.global_block
        blk.create_var(name="em", shape=em.shape, dtype="float32",
                       is_data=True)
        blk.create_var(name="w", shape=w.shape, dtype="float32",
                       is_data=True)
        blk.create_var(name="path", shape=(), dtype="int64")
        blk.append_op("crf_decoding", {"Emission": ["em"],
                                       "Transition": ["w"]},
                      {"ViterbiPath": ["path"]}, {})
    exe = pt.Executor()
    exe.run(startup)
    (path,) = exe.run(main, feed={"em": em, "w": w}, fetch_list=["path"])
    path = np.asarray(path)
    start, stop, trans = w[0], w[1], w[2:]
    for b in range(B):
        best, best_s = None, -np.inf
        for p in itertools.product(range(N), repeat=T):
            s = start[p[0]] + em[b, 0, p[0]]
            for t in range(1, T):
                s += trans[p[t - 1], p[t]] + em[b, t, p[t]]
            s += stop[p[-1]]
            if s > best_s:
                best, best_s = p, s
        np.testing.assert_array_equal(path[b], best)


def test_crf_tagging_end_to_end():
    """Sequence tagging where the LABEL DEPENDS ON THE PREVIOUS TAG (parity
    chain): CRF training must learn the transition structure, beating the
    emission-only decoder. Also exercises the Length-masked path."""
    rng = np.random.default_rng(2)
    B, T, N, D = 64, 6, 2, 5
    # observations weakly indicate the tag; tags alternate with prob 0.9
    tags = np.zeros((B, T), np.int64)
    for b in range(B):
        t0 = rng.integers(0, N)
        tags[b, 0] = t0
        for t in range(1, T):
            tags[b, t] = (tags[b, t - 1] + 1) % N if rng.random() < 0.9 \
                else tags[b, t - 1]
    obs = (np.eye(N)[tags] @ rng.standard_normal((N, D)) * 0.3
           + rng.standard_normal((B, T, D)) * 0.5).astype(np.float32)
    lens = np.full((B,), T, np.int64)

    main, startup = pt.Program(), pt.Program()
    main.random_seed = startup.random_seed = 7
    with pt.program_guard(main, startup):
        with pt.unique_name.guard():
            x = L.data(name="x", shape=[T, D], dtype="float32")
            y = L.data(name="y", shape=[T], dtype="int64")
            ln = L.data(name="ln", shape=[1], dtype="int64")
            em = L.fc(x, size=N, num_flatten_dims=2)
            nll = L.linear_chain_crf(
                em, y, param_attr=pt.ParamAttr(name="crfw"), length=ln)
            loss = L.mean(nll)
            pt.optimizer.Adam(0.05).minimize(loss)
    exe = pt.Executor()
    with pt.scope_guard(pt.Scope()):
        exe.run(startup)
        losses = []
        for _ in range(60):
            (lv,) = exe.run(main, feed={"x": obs, "y": tags, "ln": lens},
                            fetch_list=[loss])
            losses.append(float(np.asarray(lv)))
        assert losses[-1] < losses[0] * 0.7, (losses[0], losses[-1])
        # the learned transition must favor the +1 alternation
        w = np.asarray(pt.global_scope().find_var("crfw"))
        trans = w[2:]
        assert trans[0, 1] > trans[0, 0] and trans[1, 0] > trans[1, 1], trans
