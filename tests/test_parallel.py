"""Parallelism tests on the virtual 8-device CPU mesh.

Mirrors the reference's equivalence oracle (SURVEY.md §4: train the same model
single-device vs ParallelExecutor and compare losses —
unittests/parallel_executor_test_base.py): here single-device vs GSPMD
data-parallel vs fleet shard_map-collective must match numerically.
"""
import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import layers as L
from paddle_tpu.parallel import make_mesh


def _build(seed=0):
    x = L.data(name="x", shape=[16], dtype="float32")
    y = L.data(name="y", shape=[1], dtype="float32")
    h = L.fc(x, size=8, act="relu")
    pred = L.fc(h, size=1)
    loss = L.mean(L.square_error_cost(pred, y))
    return loss


def _batch(rng, bs=32):
    x = rng.standard_normal((bs, 16)).astype(np.float32)
    w = rng.standard_normal((16, 1)).astype(np.float32)
    return x, (x @ w).astype(np.float32)


def _train(run_target, steps=5, seed=0):
    """Build + train in a fresh program/scope; return (loss history, params)."""
    main, startup = pt.Program(), pt.Program()
    main.random_seed = 7
    startup.random_seed = 7
    with pt.program_guard(main, startup):
        with pt.unique_name.guard():
            loss = _build()
            pt.optimizer.SGD(0.05).minimize(loss)
    scope = pt.Scope()
    exe = pt.Executor()
    rng = np.random.default_rng(seed)
    x, y = _batch(rng)
    with pt.scope_guard(scope):
        exe.run(startup)
        target = run_target(main, loss)
        hist = []
        for _ in range(steps):
            (lv,) = exe.run(target, feed={"x": x, "y": y}, fetch_list=[loss.name])
            hist.append(float(np.asarray(lv).reshape(-1)[0]))
        params = {
            p.name: np.asarray(scope.find_var(p.name)) for p in main.all_parameters()
        }
    return hist, params


def test_gspmd_dp_matches_single_device():
    single, single_params = _train(lambda main, loss: main)

    mesh = make_mesh({"dp": 8})
    dp, dp_params = _train(
        lambda main, loss: pt.CompiledProgram(main).with_data_parallel(
            loss_name=loss.name, mesh=mesh
        )
    )
    np.testing.assert_allclose(single, dp, rtol=1e-4)
    for name, ref in single_params.items():
        np.testing.assert_allclose(ref, dp_params[name], rtol=1e-4, atol=1e-5)


def test_fleet_collective_matches_single_device():
    from paddle_tpu.incubate.fleet import UserDefinedRoleMaker, fleet

    single, single_params = _train(lambda main, loss: main)

    mesh = make_mesh({"dp": 8})

    # fleet transpile: wrap minimize
    main, startup = pt.Program(), pt.Program()
    main.random_seed = 7
    startup.random_seed = 7
    with pt.program_guard(main, startup):
        with pt.unique_name.guard():
            loss = _build()
            fleet.init(UserDefinedRoleMaker(worker_num=8), mesh=mesh)
            opt = fleet.distributed_optimizer(pt.optimizer.SGD(0.05))
            opt.minimize(loss)
    types = [op.type for op in main.global_block.ops]
    # bucketed regime (ISSUE 8): grads coalesce into c_allreduce_coalesced
    # buckets; a single-member bucket keeps the classic c_allreduce_sum
    assert "c_allreduce_sum" in types or "c_allreduce_coalesced" in types

    scope = pt.Scope()
    exe = pt.Executor()
    rng = np.random.default_rng(0)
    x, y = _batch(rng)
    with pt.scope_guard(scope):
        exe.run(startup)
        compiled = pt.CompiledProgram(main).with_collective(mesh=mesh)
        hist = []
        for _ in range(5):
            (lv,) = exe.run(compiled, feed={"x": x, "y": y}, fetch_list=[loss.name])
            hist.append(float(np.asarray(lv).reshape(-1)[0]))
        fleet_params = {
            p.name: np.asarray(scope.find_var(p.name)) for p in main.all_parameters()
        }
    assert hist[-1] < hist[0]
    # equivalence oracle: mean-allreduced grads over the same global batch
    # must produce the same parameter trajectory as the single-device run
    for name, ref in single_params.items():
        np.testing.assert_allclose(ref, fleet_params[name], rtol=1e-4, atol=1e-5)


def test_local_sgd_syncs_every_k_steps():
    """LocalSGD: params diverge per-rank... on a shared-batch setup they stay
    identical, so verify the mechanics instead: snapshots exist, step counts,
    and after k steps params still track the single-device trajectory (delta
    averaging of identical ranks is a no-op)."""
    from paddle_tpu.parallel.collective import LocalSGD

    mesh = make_mesh({"dp": 8})
    main, startup = pt.Program(), pt.Program()
    main.random_seed = 7
    startup.random_seed = 7
    with pt.program_guard(main, startup):
        with pt.unique_name.guard():
            loss = _build()
            pt.optimizer.SGD(0.05).minimize(loss)
            t = LocalSGD(k_steps=2)
            t.transpile(startup, main, rank=0, nranks=8)
    types = [op.type for op in main.global_block.ops]
    assert "local_sgd_sync" in types

    scope = pt.Scope()
    exe = pt.Executor()
    rng = np.random.default_rng(0)
    x, y = _batch(rng)
    with pt.scope_guard(scope):
        exe.run(startup)
        compiled = pt.CompiledProgram(main).with_collective(mesh=mesh)
        hist = []
        for _ in range(6):
            (lv,) = exe.run(compiled, feed={"x": x, "y": y}, fetch_list=[loss.name])
            hist.append(float(np.asarray(lv).reshape(-1)[0]))
        step = np.asarray(scope.find_var("@LOCAL_SGD_STEP@"))
    assert hist[-1] < hist[0]
    assert int(step) == 6


def test_collective_ops_shard_map_semantics():
    """c_allreduce_sum under with_collective really sums across the axis."""
    mesh = make_mesh({"dp": 8})
    main, startup = pt.Program(), pt.Program()
    with pt.program_guard(main, startup):
        x = L.data(name="x", shape=[4], dtype="float32")
        s = L.reduce_sum(x)  # per-device partial sum
        block = main.global_block
        block.append_op(
            "c_allreduce_sum", {"X": [s.name]}, {"Out": [s.name]}, {"ring_id": 0}
        )
    exe = pt.Executor()
    xv = np.arange(32, dtype=np.float32).reshape(8, 4)
    compiled = pt.CompiledProgram(main).with_collective(mesh=mesh)
    (out,) = exe.run(compiled, feed={"x": xv}, fetch_list=[s.name])
    np.testing.assert_allclose(np.asarray(out).reshape(()), xv.sum(), rtol=1e-6)


def test_sharded_optimizer_states_zero1():
    """BuildStrategy.sharded_optimizer_states: Adam moments must live dp-
    sharded in the scope (ZeRO-1) while the parameter trajectory still matches
    the unsharded single-device run."""
    from jax.sharding import NamedSharding

    def _train_adam(run_target, steps=4):
        main, startup = pt.Program(), pt.Program()
        main.random_seed = 7
        startup.random_seed = 7
        with pt.program_guard(main, startup):
            with pt.unique_name.guard():
                loss = _build()
                pt.optimizer.Adam(learning_rate=0.01).minimize(loss)
        scope = pt.Scope()
        exe = pt.Executor()
        rng = np.random.default_rng(0)
        x, y = _batch(rng)
        with pt.scope_guard(scope):
            exe.run(startup)
            target = run_target(main, loss)
            for _ in range(steps):
                exe.run(target, feed={"x": x, "y": y}, fetch_list=[loss.name])
            params = {
                p.name: np.asarray(scope.find_var(p.name))
                for p in main.all_parameters()
            }
            moments = {
                n: scope.find_var(n)
                for n in scope.var_names()
                if "_moment" in n and main.global_block.has_var(n)
            }
        return params, moments

    single_params, _ = _train_adam(lambda main, loss: main)

    mesh = make_mesh({"dp": 8})
    bs = pt.BuildStrategy()
    bs.sharded_optimizer_states = True
    zero_params, zero_moments = _train_adam(
        lambda main, loss: pt.CompiledProgram(main, build_strategy=bs)
        .with_data_parallel(loss_name=loss.name, mesh=mesh)
    )
    # at least the 16-row fc weight moments must be dp-sharded on dim 0
    sharded = [
        n for n, v in zero_moments.items()
        if isinstance(getattr(v, "sharding", None), NamedSharding)
        and v.sharding.spec and v.sharding.spec[0] == "dp"
    ]
    assert sharded, f"no dp-sharded moments found in {list(zero_moments)}"
    for name, ref in single_params.items():
        np.testing.assert_allclose(ref, zero_params[name], rtol=1e-4, atol=1e-5)


def test_allreduce_inside_static_rnn_body():
    """ADVICE r1 (medium): __axis_env__ must propagate into control-flow
    sub-blocks — a c_allreduce_sum inside a StaticRNN body under
    with_collective must really sum across the dp axis, not lower to
    identity/local compute."""
    from paddle_tpu.layers.control_flow import StaticRNN

    mesh = make_mesh({"dp": 8})
    T = 4
    main, startup = pt.Program(), pt.Program()
    with pt.program_guard(main, startup):
        x = L.data(name="x", shape=[T], dtype="float32")  # [B, T]
        xt = L.transpose(x, perm=[1, 0])  # [T, B_local]
        h0 = L.fill_constant(shape=[1], dtype="float32", value=0.0)
        rnn = StaticRNN()
        with rnn.step():
            word = rnn.step_input(xt)  # [B_local]
            prev = rnn.memory(init=h0)  # [1]
            s = L.reduce_sum(word, keep_dim=True)  # local partial sum, [1]
            blk = main.current_block()
            blk.append_op(
                "c_allreduce_sum", {"X": [s.name]}, {"Out": [s.name]}, {"ring_id": 0}
            )
            h = L.elementwise_add(prev, s)
            rnn.update_memory(prev, h)
            rnn.step_output(h)
        out = rnn()
    exe = pt.Executor()
    xv = np.arange(32, dtype=np.float32).reshape(8, T)
    compiled = pt.CompiledProgram(main).with_collective(mesh=mesh)
    (hist,) = exe.run(compiled, feed={"x": xv}, fetch_list=[out.name])
    hist = np.asarray(hist)  # [T, 1] running sums of global per-step sums
    np.testing.assert_allclose(hist[-1].reshape(()), xv.sum(), rtol=1e-6)
    np.testing.assert_allclose(hist[0].reshape(()), xv[:, 0].sum(), rtol=1e-6)


def test_tp_sharding_annotation_compiles():
    """Megatron-style TP: shard fc weights over 'tp'; program must compile and
    match the unsharded result."""
    from paddle_tpu.parallel import annotate_sharding

    mesh = make_mesh({"dp": 2, "tp": 4})
    x = L.data(name="x", shape=[16], dtype="float32")
    h = L.fc(x, size=32, act="relu")
    out = L.fc(h, size=8)
    prog = pt.default_main_program()
    params = prog.all_parameters()
    # column-parallel then row-parallel
    annotate_sharding(params[0], (None, "tp"))
    annotate_sharding(params[2], ("tp", None))
    exe = pt.Executor()
    exe.run(pt.default_startup_program())
    xv = np.random.default_rng(0).standard_normal((8, 16)).astype(np.float32)
    (ref,) = exe.run(prog, feed={"x": xv}, fetch_list=[out])
    compiled = pt.CompiledProgram(prog).with_data_parallel(mesh=mesh)
    (sharded,) = exe.run(compiled, feed={"x": xv}, fetch_list=[out])
    np.testing.assert_allclose(ref, sharded, rtol=1e-4, atol=1e-5)
