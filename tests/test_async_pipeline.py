"""Async feed/dispatch pipeline tests: DeviceLoader prefetch contract
(producer errors propagate, no leaked threads), PyReader use_double_buffer
routing, bucketed-padding numerics (masked loss is exact on real rows),
async-window determinism (same trajectory for window 1 and 4), and the
ragged-tail recompile regression (exactly one compile under
FLAGS_feed_bucketing)."""
import threading
import time

import jax
import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import layers as L
from paddle_tpu import profiler
from paddle_tpu.data_feeder import ROW_MASK_NAME, pad_feed_to_bucket
from paddle_tpu.pipeline import DeviceLoader, jit_compile_counter


@pytest.fixture
def restore_flags():
    snap = pt.flags.all_flags()
    yield
    pt.flags.set_flags(snap)


def _threads_settle(base, deadline_s=5.0):
    deadline = time.monotonic() + deadline_s
    while time.monotonic() < deadline:
        if threading.active_count() <= base:
            return True
        time.sleep(0.05)
    return threading.active_count() <= base


# -- DeviceLoader contract ---------------------------------------------------

def test_device_loader_stages_to_device_in_order():
    def src():
        for i in range(5):
            yield {"x": np.full((2, 3), i, np.float32)}

    out = list(DeviceLoader(src, depth=2))
    assert len(out) == 5
    for i, d in enumerate(out):
        assert isinstance(d["x"], jax.Array)
        np.testing.assert_array_equal(np.asarray(d["x"]), np.full((2, 3), i))


def test_device_loader_casts_to_feed_var_dtypes():
    x = L.data(name="dl_x", shape=[3], dtype="float32")

    def src():
        yield {"dl_x": np.ones((2, 3), np.float64), "extra": np.arange(2)}

    (d,) = list(DeviceLoader(src, depth=1, feed_vars=[x]))
    assert d["dl_x"].dtype == np.float32  # declared var dtype, not float64
    assert isinstance(d["extra"], jax.Array)  # unknown keys still staged


def test_device_loader_propagates_producer_errors_no_leaked_threads():
    base = threading.active_count()

    def bad():
        yield {"x": np.zeros(4, np.float32)}
        raise ValueError("boom")

    with pytest.raises(ValueError, match="boom"):
        list(DeviceLoader(lambda: bad(), depth=2))
    assert _threads_settle(base), "DeviceLoader left its stage thread running"


def test_device_loader_abandoned_iteration_stops_thread():
    base = threading.active_count()

    def src():
        for i in range(1000):
            yield {"x": np.full(4, i, np.float32)}

    it = iter(DeviceLoader(src, depth=2))
    next(it)
    it.close()  # consumer abandons mid-stream
    assert _threads_settle(base), "abandoned DeviceLoader leaked its thread"


def test_device_loader_records_stage_counters():
    profiler.stage_counters(reset=True)
    list(DeviceLoader(lambda: iter([{"x": np.zeros(4, np.float32)}] * 3),
                      depth=1))
    snap = profiler.stage_counters()
    assert snap["pipeline.host_ingest"]["events"] == 3
    assert snap["pipeline.device_put"]["events"] == 3


# -- PyReader use_double_buffer ----------------------------------------------

def _pyreader(double_buffer):
    x = L.data(name="px", shape=[4], dtype="float32")
    r = pt.PyReader(feed_list=[x], capacity=4,
                    use_double_buffer=double_buffer)
    r.decorate_sample_list_generator(
        lambda: iter([[(np.full(4, i, np.float32),)] * 2 for i in range(4)]))
    return r


def test_pyreader_double_buffer_yields_device_arrays():
    feeds = list(_pyreader(True)())
    assert len(feeds) == 4
    assert all(isinstance(d["px"], jax.Array) for d in feeds)


def test_pyreader_without_double_buffer_yields_host_arrays():
    feeds = list(_pyreader(False)())
    assert all(isinstance(d["px"], np.ndarray) for d in feeds)


def test_pyreader_double_buffer_still_propagates_errors():
    x = L.data(name="pe", shape=[4], dtype="float32")
    r = pt.PyReader(feed_list=[x], capacity=2, use_double_buffer=True)

    def bad():
        yield [(np.zeros(4, np.float32),)]
        raise ValueError("boom")

    r.decorate_sample_list_generator(lambda: bad())
    with pytest.raises(ValueError, match="boom"):
        for _ in r():
            pass


# -- bucketed padding --------------------------------------------------------

def test_pad_feed_to_bucket_shapes_and_mask():
    feed = pad_feed_to_bucket(
        {"a": np.ones((3, 2), np.float32), "b": np.ones((3, 1), np.int64)}, 5)
    assert feed["a"].shape == (5, 2) and feed["b"].shape == (5, 1)
    np.testing.assert_array_equal(feed["a"][3:], 0)
    np.testing.assert_array_equal(
        feed[ROW_MASK_NAME].ravel(), [1, 1, 1, 0, 0])


def _masked_regression_program():
    """Loss that honors the row-mask convention:
    sum(per_row * mask) / sum(mask)."""
    x = L.data(name="x", shape=[4], dtype="float32")
    y = L.data(name="y", shape=[1], dtype="float32")
    m = L.data(name=ROW_MASK_NAME, shape=[1], dtype="float32")
    per_row = L.square_error_cost(L.fc(x, size=1), y)
    loss = L.elementwise_div(L.reduce_sum(L.elementwise_mul(per_row, m)),
                             L.reduce_sum(m))
    pt.optimizer.SGD(0.1).minimize(loss)
    return x, y, loss


def test_bucketed_padding_numerics_match_unpadded():
    x, y, loss = _masked_regression_program()
    main, startup = pt.default_main_program(), pt.default_startup_program()
    rng = np.random.default_rng(0)
    samples = [(rng.standard_normal(4, dtype=np.float32),
                rng.standard_normal(1, dtype=np.float32)) for _ in range(3)]
    w_name = main.all_parameters()[0].name
    exe = pt.Executor()

    results = []
    for bucket in (3, 4):  # 3 = no padding; 4 = one zero row + mask
        feeder = pt.DataFeeder([x, y], bucket_size=bucket)
        feed = feeder.feed(samples)
        assert feed["x"].shape[0] == bucket
        with pt.scope_guard(pt.Scope()) as scope:
            exe.run(startup)
            (lv,) = exe.run(main, feed=feed, fetch_list=[loss])
            results.append((float(np.asarray(lv)),
                            np.asarray(scope.find_var(w_name))))
    (loss_a, w_a), (loss_b, w_b) = results
    np.testing.assert_allclose(loss_a, loss_b, rtol=1e-6)
    np.testing.assert_allclose(w_a, w_b, rtol=1e-6)


def test_dataset_split_batch_buckets_tail(restore_flags):
    ds = pt.DatasetFactory().create_dataset("QueueDataset")
    v = L.data(name="slot0", shape=[2], dtype="float32")
    ds.set_use_var([v])
    ds.set_batch_size(4)
    pt.flags.set_flags({"feed_bucketing": True})
    feed = ds._split_batch(np.arange(6, dtype=np.float64).reshape(3, 2))
    assert feed["slot0"].shape == (4, 2)
    np.testing.assert_array_equal(feed[ROW_MASK_NAME].ravel(), [1, 1, 1, 0])


# -- recompile regression (jax compile-count hook) ---------------------------

def test_ragged_tail_epoch_compiles_once_under_bucketing():
    x, y, loss = _masked_regression_program()
    main, startup = pt.default_main_program(), pt.default_startup_program()
    rng = np.random.default_rng(1)

    def batches(sizes):
        return [[(rng.standard_normal(4, dtype=np.float32),
                  rng.standard_normal(1, dtype=np.float32))
                 for _ in range(n)] for n in sizes]

    exe = pt.Executor()
    exe.run(startup)
    feeder = pt.DataFeeder([x, y], bucket_size=4)
    with jit_compile_counter() as c:
        for b in batches([4, 4, 2]):  # epoch with a ragged tail
            exe.run(main, feed=feeder.feed(b), fetch_list=[loss])
    assert c.count == 1, f"expected 1 whole-block compile, saw {c.events}"

    # control: without bucketing the tail's exact shape forces a fresh
    # compile (the full-batch signature is already cached from above, so the
    # tail is the only new one — and its logged shapes say batch 2)
    plain = pt.DataFeeder([x, y])
    with jit_compile_counter() as c2:
        for b in batches([4, 2]):
            feed = plain.feed(b)
            feed[ROW_MASK_NAME] = np.ones((len(b), 1), np.float32)
            exe.run(main, feed=feed, fetch_list=[loss])
    assert c2.count == 1, f"hook missed the tail recompile: {c2.events}"
    assert "float32[2," in c2.events[0]


# -- async dispatch window ---------------------------------------------------

def _dropout_program():
    x = L.data(name="dx", shape=[8], dtype="float32")
    h = L.dropout(L.fc(x, size=8, act="relu"), dropout_prob=0.5)
    loss = L.reduce_mean(L.square(h))
    pt.optimizer.SGD(0.1).minimize(loss)
    return loss


def test_async_window_determinism_across_sizes(restore_flags):
    """Window 1 (fully synchronous) and window 4 (async runahead) must walk
    the identical trajectory: rng_counter pins the per-step PRNG keys, so
    dropout masks do not depend on dispatch timing."""
    loss = _dropout_program()
    main, startup = pt.default_main_program(), pt.default_startup_program()
    w_name = main.all_parameters()[0].name
    feed = {"dx": np.linspace(-1, 1, 16, dtype=np.float32).reshape(2, 8)}
    exe = pt.Executor()

    trajectories = []
    for window in (1, 4):
        pt.flags.set_flags({"max_inflight_steps": window})
        with pt.scope_guard(pt.Scope()) as scope:
            exe.run(startup)
            for i in range(6):
                outs = exe.run_async(main, feed=feed, fetch_list=[loss],
                                     rng_counter=100 + i)
                assert isinstance(outs[0], jax.Array)  # deferred fetch handle
            assert len(exe._inflight) <= window
            exe.wait()
            assert not exe._inflight
            trajectories.append(np.asarray(scope.find_var(w_name)))
    np.testing.assert_array_equal(trajectories[0], trajectories[1])


def test_run_async_handles_materialize_to_fetch_values():
    x = L.data(name="ax", shape=[2], dtype="float32")
    out = L.reduce_sum(x)
    exe = pt.Executor()
    (h,) = exe.run_async(pt.default_main_program(),
                         feed={"ax": np.ones((3, 2), np.float32)},
                         fetch_list=[out])
    exe.wait()
    assert float(np.asarray(h)) == pytest.approx(6.0)


# -- train_from_dataset async path -------------------------------------------

def _slot_file(tmp_path, rows, seed=0):
    rng = np.random.default_rng(seed)
    p = tmp_path / "part-0"
    with open(p, "w") as f:
        for _ in range(rows):
            vals = " ".join(f"{v:.4f}" for v in rng.random(4))
            f.write(f"4 {vals} 1 {rng.integers(0, 2)}\n")
    return str(p)


def _dataset_program():
    x = L.data(name="x", shape=[4], dtype="float32")
    y = L.data(name="y", shape=[1], dtype="float32")
    loss = L.reduce_mean(L.square_error_cost(L.fc(x, size=1), y))
    pt.optimizer.SGD(0.1).minimize(loss)
    return [x, y], loss


def test_train_from_dataset_async_matches_sync(tmp_path, restore_flags):
    use_vars, loss = _dataset_program()
    main, startup = pt.default_main_program(), pt.default_startup_program()
    w_name = main.all_parameters()[0].name
    path = _slot_file(tmp_path, rows=10)  # batches of 4, 4, 2
    exe = pt.Executor()

    finals = []
    for window, depth in ((1, 0), (4, 2)):  # sync reference vs full pipeline
        pt.flags.set_flags({"max_inflight_steps": window,
                            "device_prefetch_depth": depth})
        ds = pt.DatasetFactory().create_dataset("QueueDataset")
        ds.set_batch_size(4)
        ds.set_use_var(use_vars)
        ds.set_filelist([path])
        with pt.scope_guard(pt.Scope()) as scope:
            exe.run(startup)
            exe.train_from_dataset(main, ds, fetch_list=[loss],
                                   print_period=10**9)
            finals.append(np.asarray(scope.find_var(w_name)))
    np.testing.assert_array_equal(finals[0], finals[1])


def test_train_from_dataset_throughput_print_excludes_first_batch(
        tmp_path, capsys, restore_flags):
    """Satellite fix: the printed batch/s window opens after batch 1 (the
    compile), and the rate divides by the batches inside the window."""
    use_vars, loss = _dataset_program()
    main, startup = pt.default_main_program(), pt.default_startup_program()
    path = _slot_file(tmp_path, rows=16)  # 4 full batches
    ds = pt.DatasetFactory().create_dataset("QueueDataset")
    ds.set_batch_size(4)
    ds.set_use_var(use_vars)
    ds.set_filelist([path])
    exe = pt.Executor()
    exe.run(startup)
    exe.train_from_dataset(main, ds, fetch_list=[loss], print_period=3)
    printed = capsys.readouterr().out
    assert "batch 3 (" in printed and "batch/s" in printed
    # first batch is never inside a printed window
    assert "batch 1 (" not in printed


def test_train_from_dataset_no_leaked_threads(tmp_path, restore_flags):
    base = threading.active_count()
    use_vars, loss = _dataset_program()
    main, startup = pt.default_main_program(), pt.default_startup_program()
    ds = pt.DatasetFactory().create_dataset("QueueDataset")
    ds.set_batch_size(4)
    ds.set_use_var(use_vars)
    ds.set_filelist([_slot_file(tmp_path, rows=12)])
    pt.flags.set_flags({"device_prefetch_depth": 2})
    exe = pt.Executor()
    exe.run(startup)
    exe.train_from_dataset(main, ds, print_period=10**9)
    assert _threads_settle(base), "prefetch stack leaked threads"


# -- bucket-boundary regressions (ISSUE 6: the tuner records these) ----------

def _ragged_sum_program():
    """Ragged-dim-tolerant program honoring the row mask: the ragged x is
    reduced over its padded dim (zero padding is sum-neutral) before the
    static-width fc."""
    x = L.data(name="rx", shape=[-1], dtype="float32")
    y = L.data(name="ry", shape=[1], dtype="float32")
    m = L.data(name=ROW_MASK_NAME, shape=[1], dtype="float32")
    h = L.reduce_sum(x, dim=1, keep_dim=True)
    per_row = L.square_error_cost(L.fc(h, size=1), y)
    loss = L.elementwise_div(L.reduce_sum(L.elementwise_mul(per_row, m)),
                             L.reduce_sum(m))
    pt.optimizer.SGD(0.1).minimize(loss)
    return x, y, loss


def test_batch_exactly_on_bucket_size_compiles_once():
    """A batch landing EXACTLY on bucket_size must share the bucketed
    signature (no pad rows, mask all ones — and critically no rounding past
    the bucket), so a full-then-ragged epoch is one compile. Guards the
    boundary the tuner records as a feed_bucket decision."""
    x, y, loss = _masked_regression_program()
    main, startup = pt.default_main_program(), pt.default_startup_program()
    rng = np.random.default_rng(2)

    def batch(n):
        return [(rng.standard_normal(4, dtype=np.float32),
                 rng.standard_normal(1, dtype=np.float32))
                for _ in range(n)]

    exe = pt.Executor()
    exe.run(startup)
    feeder = pt.DataFeeder([x, y], bucket_size=4)
    exact = feeder.feed(batch(4))  # lands exactly on the bucket
    assert exact["x"].shape[0] == 4
    np.testing.assert_array_equal(exact[ROW_MASK_NAME].ravel(), [1, 1, 1, 1])
    with jit_compile_counter() as c:
        exe.run(main, feed=exact, fetch_list=[loss])
        exe.run(main, feed=feeder.feed(batch(4)), fetch_list=[loss])
        exe.run(main, feed=feeder.feed(batch(2)), fetch_list=[loss])
    assert c.count == 1, f"boundary batch broke the signature: {c.events}"


def test_one_past_pow2_ragged_boundary_compiles_once():
    """Ragged-dim rounding boundaries: max extent 8 (a power of two) stays
    8; max extent 9 (one past the boundary) rounds to 16 — ONE fresh
    compile that every later batch up to 16 then reuses. Guards the pow2
    decisions the tuner starts recording (data_feeder._tuned_extent)."""
    x, y, loss = _ragged_sum_program()
    main, startup = pt.default_main_program(), pt.default_startup_program()
    rng = np.random.default_rng(3)

    def ragged(lens):
        return [(rng.standard_normal(n, dtype=np.float32),
                 rng.standard_normal(1, dtype=np.float32)) for n in lens]

    exe = pt.Executor()
    exe.run(startup)
    feeder = pt.DataFeeder([x, y], bucket_size=2)
    at8 = feeder.feed(ragged([8, 5]))
    assert at8["rx"].shape == (2, 8)  # exactly-pow2 max does NOT round up
    with jit_compile_counter() as c:
        exe.run(main, feed=at8, fetch_list=[loss])
        exe.run(main, feed=feeder.feed(ragged([6, 8])), fetch_list=[loss])
    assert c.count == 1, f"pow2-exact extent recompiled: {c.events}"

    past = feeder.feed(ragged([9, 4]))
    assert past["rx"].shape == (2, 16)  # one past the boundary: next pow2
    with jit_compile_counter() as c2:
        exe.run(main, feed=past, fetch_list=[loss])
        exe.run(main, feed=feeder.feed(ragged([13, 11])), fetch_list=[loss])
        exe.run(main, feed=feeder.feed(ragged([16, 2])), fetch_list=[loss])
    assert c2.count == 1, f"16-bucket shapes fragmented: {c2.events}"
