"""Pipeline parallelism tests (reference unittests/test_pipeline.py pattern +
the ParallelExecutor equivalence oracle): a 2-stage GPipe split with >=4
microbatches must reproduce the single-device parameter trajectory exactly
(SGD, mean loss)."""
import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import layers as L


def _build():
    x = L.data(name="x", shape=[16], dtype="float32")
    y = L.data(name="y", shape=[1], dtype="float32")
    h = L.fc(x, size=8, act="relu")
    pred = L.fc(h, size=1)
    loss = L.mean(L.square_error_cost(pred, y))
    return h, loss


def _batch(rng, bs=32):
    x = rng.standard_normal((bs, 16)).astype(np.float32)
    w = rng.standard_normal((16, 1)).astype(np.float32)
    return x, (x @ w).astype(np.float32)


def _run(pipeline: bool, steps=5, num_micro=4, devices=None, raw_params=False):
    main, startup = pt.Program(), pt.Program()
    main.random_seed = 7
    startup.random_seed = 7
    with pt.program_guard(main, startup):
        with pt.unique_name.guard():
            h, loss = _build()
            if pipeline:
                opt = pt.optimizer.PipelineOptimizer(
                    pt.optimizer.SGD(0.05), cut_list=[[h]],
                    place_list=devices, num_microbatches=num_micro)
            else:
                opt = pt.optimizer.SGD(0.05)
            opt.minimize(loss)
    scope = pt.Scope()
    exe = pt.Executor()
    rng = np.random.default_rng(0)
    x, y = _batch(rng)
    with pt.scope_guard(scope):
        exe.run(startup)
        hist = []
        for _ in range(steps):
            (lv,) = exe.run(main, feed={"x": x, "y": y}, fetch_list=[loss.name])
            hist.append(float(np.asarray(lv).reshape(-1)[0]))
        params = {
            p.name: (scope.find_var(p.name) if raw_params
                     else np.asarray(scope.find_var(p.name)))
            for p in main.all_parameters()
        }
    return hist, params, main


def test_two_stage_pipeline_matches_single_device():
    single, single_params, _ = _run(pipeline=False)
    piped, piped_params, main = _run(pipeline=True, num_micro=4)
    assert len(main._pipeline.stages) == 2
    np.testing.assert_allclose(single, piped, rtol=1e-5)
    for name, ref in single_params.items():
        np.testing.assert_allclose(ref, piped_params[name], rtol=1e-5,
                                   atol=1e-6)


def test_pipeline_eight_microbatches():
    single, single_params, _ = _run(pipeline=False)
    piped, piped_params, _ = _run(pipeline=True, num_micro=8)
    np.testing.assert_allclose(single, piped, rtol=1e-5)
    for name, ref in single_params.items():
        np.testing.assert_allclose(ref, piped_params[name], rtol=1e-5,
                                   atol=1e-6)


def test_pipeline_stage_structure():
    _, _, main = _run(pipeline=True, steps=1)
    plan = main._pipeline
    s0, s1 = plan.stages
    # stage 0 produces the cut activation, owns fc_0 params
    assert any(n.startswith("fc_0") for n in s0.param_names)
    assert s0.out_names and s0.update is not None
    # stage 1 consumes the cut + the label feed, owns fc_1 params
    assert any(n.startswith("fc_1") for n in s1.param_names)
    assert any("y" == n for n in s1.ext_inputs)
    assert set(s0.out_names) <= set(s1.ext_inputs)


def test_pipeline_backward_replay_shields_bn_stats():
    """The rematerialized backward must NOT update batch-norm moving stats a
    second time: after K steps the moving mean equals the plain-topology
    count (M fwd updates per step), not 2M."""
    def build_bn():
        x = L.data(name="x", shape=[16], dtype="float32")
        y = L.data(name="y", shape=[1], dtype="float32")
        h = L.batch_norm(L.fc(x, size=8))
        pred = L.fc(h, size=1)
        return h, L.mean(L.square_error_cost(pred, y))

    def run(pipeline):
        main, startup = pt.Program(), pt.Program()
        main.random_seed = 7
        startup.random_seed = 7
        with pt.program_guard(main, startup):
            with pt.unique_name.guard():
                h, loss = build_bn()
                if pipeline:
                    pt.optimizer.PipelineOptimizer(
                        pt.optimizer.SGD(0.0), cut_list=[[h]],
                        num_microbatches=2).minimize(loss)
                else:
                    pt.optimizer.SGD(0.0).minimize(loss)
        scope = pt.Scope()
        exe = pt.Executor()
        rng = np.random.default_rng(0)
        x, y = _batch(rng, bs=8)
        with pt.scope_guard(scope):
            exe.run(startup)
            exe.run(main, feed={"x": x, "y": y}, fetch_list=[loss.name])
            mean_name = next(n for n in scope.var_names() if "mean" in n)
            return np.asarray(scope.find_var(mean_name))

    single_mean = run(False)
    piped_mean = run(True)
    # lr=0 so params identical; with 2 microbatches the fwd stats update twice
    # (inherent to microbatching) but the bwd replay must add nothing: the
    # moving mean must stay strictly between 1 and 2 plain updates' worth.
    # a doubled (2M=4) update count would overshoot 2x.
    assert np.abs(piped_mean).sum() < 2.1 * np.abs(single_mean).sum() + 1e-6


def test_pipeline_batch_fetch_concatenates():
    exe = pt.Executor()
    main, startup = pt.Program(), pt.Program()
    with pt.program_guard(main, startup):
        with pt.unique_name.guard():
            h, loss = _build()
            pt.optimizer.PipelineOptimizer(
                pt.optimizer.SGD(0.01), cut_list=[[h]],
                num_microbatches=4).minimize(loss)
    with pt.scope_guard(pt.Scope()):
        exe.run(startup)
        rng = np.random.default_rng(0)
        x, y = _batch(rng, bs=8)  # microbatch size 2
        pred = next(v for s in main._pipeline.stages
                    for v in [s.fwd.global_block.vars.get("fc_1.tmp_1")] if v)
        (out,) = exe.run(main, feed={"x": x, "y": y}, fetch_list=[pred.name])
    assert out.shape[0] == 8  # concatenated, not averaged


def test_pipeline_rejects_scheduler_lr():
    with pt.program_guard(pt.Program(), pt.Program()):
        x = L.data(name="x", shape=[4], dtype="float32")
        a = L.fc(x, size=4)
        loss = L.mean(L.fc(a, size=1))
        lr = L.exponential_decay(0.1, 100, 0.9)
        with pytest.raises(NotImplementedError, match="scheduler"):
            pt.optimizer.PipelineOptimizer(
                pt.optimizer.SGD(lr), cut_list=[[a]]).minimize(loss)


def test_pipeline_rejects_bad_batch_split():
    _, _, main = _run(pipeline=True, steps=1, num_micro=4)
    exe = pt.Executor()
    with pt.scope_guard(pt.Scope()):
        with pytest.raises(ValueError, match="divisible"):
            main._pipeline.run_step(
                exe, pt.global_scope(),
                {"x": np.zeros((30, 16), np.float32),
                 "y": np.zeros((30, 1), np.float32)}, [])


def test_pipeline_rejects_unordered_cuts():
    with pt.program_guard(pt.Program(), pt.Program()):
        x = L.data(name="x", shape=[4], dtype="float32")
        a = L.fc(x, size=4)
        b = L.fc(a, size=4)
        loss = L.mean(b)
        with pytest.raises(ValueError, match="order"):
            pt.optimizer.PipelineOptimizer(
                pt.optimizer.SGD(0.1), cut_list=[[b], [a]]).minimize(loss)


def test_pipeline_device_placement_matches_single_device():
    """Stages placed on two devices of the virtual mesh reproduce the
    single-device trajectory exactly, stage state lives on its stage's
    device, and the schedule interleaves (reference SectionWorker
    concurrency, trainer.h:110 / pipeline_trainer.cc)."""
    import jax

    devs = jax.devices()
    if len(devs) < 2:
        pytest.skip("needs >= 2 devices")
    single, single_params, _ = _run(pipeline=False)
    hist, params, main = _run(pipeline=True, devices=[devs[0], devs[1]], raw_params=True)
    np.testing.assert_allclose(single, hist, rtol=1e-5)
    for name, ref in single_params.items():
        np.testing.assert_allclose(ref, np.asarray(params[name]),
                                   rtol=1e-5, atol=1e-6)
    # per-stage device residency: each stage's params (and their SGD-updated
    # values) are committed to that stage's device
    plan = main._pipeline
    for stage, dev in zip(plan.stages, plan.devices):
        for pname in stage.param_names:
            v = params[pname]
            assert isinstance(v, jax.Array) and v.devices() == {dev}, (
                pname, v.devices(), dev)


def test_pipeline_clock_cycle_interleave():
    """The dispatch order must interleave stages: stage 1's first microbatch
    is dispatched BEFORE stage 0's last (GPipe fill), and symmetrically in
    the backward drain — wall-clock overlap on real devices follows from
    async dispatch; the order is the deterministic observable."""
    import jax

    devs = jax.devices()
    if len(devs) < 2:
        pytest.skip("needs >= 2 devices")
    M = 4
    _, _, main = _run(pipeline=True, num_micro=M, steps=1,
                      devices=[devs[0], devs[1]])
    trace = main._pipeline.last_dispatch
    fwd = [e for e in trace if e[0] == "f"]
    bwd = [e for e in trace if e[0] == "b"]
    # forward fill: ("f",1,0) strictly before ("f",0,M-1)
    assert fwd.index(("f", 1, 0)) < fwd.index(("f", 0, M - 1))
    # backward drain: last stage leads — ("b",0,0) before ("b",1,M-1)
    assert bwd.index(("b", 0, 0)) < bwd.index(("b", 1, M - 1))
    # every (stage, microbatch) pair ran exactly once in each direction
    assert sorted(fwd) == sorted(("f", s, m) for s in range(2) for m in range(M))
    assert sorted(bwd) == sorted(("b", s, m) for s in range(2) for m in range(M))


def test_pipeline_placement_rejects_tied_weights():
    import jax

    devs = jax.devices()
    if len(devs) < 2:
        pytest.skip("needs >= 2 devices")
    main, startup = pt.Program(), pt.Program()
    with pt.program_guard(main, startup):
        with pt.unique_name.guard():
            x = L.data(name="x", shape=[8], dtype="float32")
            from paddle_tpu.layer_helper import LayerHelper
            helper = LayerHelper("tied", name="tied")
            w = helper.create_parameter(
                attr=pt.ParamAttr(name="tied_w"), shape=[8, 8],
                dtype="float32")
            a = L.mul(x, w)
            b = L.relu(a)
            c = L.mul(b, w)  # the same parameter read in stage 1
            loss = L.mean(c)
            with pytest.raises(NotImplementedError, match="tied"):
                pt.optimizer.PipelineOptimizer(
                    pt.optimizer.SGD(0.1), cut_list=[[b]],
                    place_list=[devs[0], devs[1]]).minimize(loss)


def _build_3stage(num_micro, schedule):
    main, startup = pt.Program(), pt.Program()
    main.random_seed = 11
    startup.random_seed = 11
    with pt.program_guard(main, startup):
        with pt.unique_name.guard():
            x = L.data(name="x", shape=[16], dtype="float32")
            y = L.data(name="y", shape=[1], dtype="float32")
            h1 = L.fc(x, size=12, act="relu")
            h2 = L.fc(h1, size=8, act="relu")
            pred = L.fc(h2, size=1)
            loss = L.mean(L.square_error_cost(pred, y))
            from paddle_tpu.parallel.pipeline import build_pipeline_plan
            main._pipeline = build_pipeline_plan(
                main, loss, [h1, h2], pt.optimizer.SGD(0.05), num_micro,
                startup, schedule=schedule)
    return main, startup, loss


def test_1f1b_schedule_order_and_stash_bound():
    """1F1B: stage s runs min(S-1-s, M) warmup forwards then strictly
    alternates F/B then drains; the boundary stash never holds more than
    ~n_stages microbatches (vs num_microbatches for gpipe) — the
    PipeDream-flush memory bound (reference trainer.h:110 SectionWorker
    steady state)."""
    rng = np.random.default_rng(0)
    x = rng.standard_normal((32, 16)).astype(np.float32)
    yv = rng.standard_normal((32, 1)).astype(np.float32)
    M, S = 8, 3
    peaks = {}
    for schedule in ("1f1b", "gpipe"):
        main, startup, loss = _build_3stage(M, schedule)
        scope = pt.Scope()
        exe = pt.Executor()
        with pt.scope_guard(scope):
            exe.run(startup)
            exe.run(main, feed={"x": x, "y": yv}, fetch_list=[loss.name])
        plan = main._pipeline
        peaks[schedule] = plan.last_peak_stash
        if schedule != "1f1b":
            continue
        for s in range(S):
            seq = [k for (k, ss, _) in plan.last_dispatch if ss == s]
            w = min(S - 1 - s, M)
            expect = ["f"] * w + ["f", "b"] * (M - w) + ["b"] * w
            assert seq == expect, (s, seq)
        # microbatch order within each stage is sequential
        for s in range(S):
            fs = [m for (k, ss, m) in plan.last_dispatch
                  if ss == s and k == "f"]
            assert fs == list(range(M))
    assert peaks["gpipe"] == M, peaks
    assert peaks["1f1b"] <= S + 1, peaks


def test_1f1b_matches_gpipe_and_single_device():
    single, single_params, _ = _run(pipeline=False)
    rng = np.random.default_rng(0)
    x = rng.standard_normal((32, 16)).astype(np.float32)
    yv = rng.standard_normal((32, 1)).astype(np.float32)
    results = {}
    for schedule in ("1f1b", "gpipe"):
        main, startup, loss = _build_3stage(8, schedule)
        scope = pt.Scope()
        exe = pt.Executor()
        with pt.scope_guard(scope):
            exe.run(startup)
            hist = []
            for _ in range(5):
                (lv,) = exe.run(main, feed={"x": x, "y": yv},
                                fetch_list=[loss.name])
                hist.append(float(np.asarray(lv).reshape(-1)[0]))
            results[schedule] = (
                hist, {p.name: np.asarray(scope.find_var(p.name))
                       for p in main.all_parameters()})
    h1, p1 = results["1f1b"]
    h2, p2 = results["gpipe"]
    np.testing.assert_allclose(h1, h2, rtol=1e-5, atol=1e-6)
    for n in p1:
        np.testing.assert_allclose(p1[n], p2[n], rtol=1e-5, atol=1e-6)


def test_pipeline_dropout_backward_replays_forward_masks():
    """The backward replay must apply the SAME dropout masks the forward
    drew (r4 weak #5: re-drawn masks make pipeline+dropout a biased
    estimator). Oracle: loss = mean(dropout(x @ W)); the realized mask is
    recoverable from the fetched dropout output, so the exact analytic
    dW is computable and must equal the pipeline's applied update."""
    lr, M = 0.05, 4
    main, startup = pt.Program(), pt.Program()
    main.random_seed = 13
    startup.random_seed = 13
    with pt.program_guard(main, startup):
        with pt.unique_name.guard():
            x = L.data(name="x", shape=[16], dtype="float32")
            h = L.fc(x, size=8, bias_attr=False)   # stage 0
            d = L.dropout(h, dropout_prob=0.5)     # stage 1
            loss = L.mean(d)
            from paddle_tpu.parallel.pipeline import build_pipeline_plan
            main._pipeline = build_pipeline_plan(
                main, loss, [h], pt.optimizer.SGD(lr), M, startup,
                schedule="1f1b")
    scope = pt.Scope()
    exe = pt.Executor()
    rng = np.random.default_rng(5)
    xv = rng.standard_normal((32, 16)).astype(np.float32)
    with pt.scope_guard(scope):
        exe.run(startup)
        wname = main.all_parameters()[0].name
        w0 = np.asarray(scope.find_var(wname)).copy()
        outs = exe.run(main, feed={"x": xv},
                       fetch_list=[loss.name, d.name])
        w1 = np.asarray(scope.find_var(wname))
    dv = np.asarray(outs[1])            # realized dropout output [32, 8]
    assert dv.shape == (32, 8)
    # fluid default downgrade_in_infer: train out = h * mask (no upscale)
    mask = (dv != 0).astype(np.float32)
    # some units must actually have dropped for the test to mean anything
    assert 0 < mask.mean() < 1
    dW = xv.T @ (mask / dv.size)        # d mean(h*mask) / dW
    np.testing.assert_allclose(w1, w0 - lr * dW, rtol=1e-4, atol=1e-5)


def test_pipeline_nondiff_boundary_var():
    """A non-differentiable (int) boundary var crossing a cut must not
    crash the backward: the zero-cotangent fallback reads its shape from
    the forward-recorded table, which survives the 1F1B stash freeing
    (r5 review regression). The int mask is built BEFORE the cut producer
    so it lands in stage 0 and crosses to stage 1 as a boundary var."""
    main, startup = pt.Program(), pt.Program()
    main.random_seed = startup.random_seed = 5
    with pt.program_guard(main, startup):
        with pt.unique_name.guard():
            x = L.data(name="x", shape=[16], dtype="float32")
            y = L.data(name="y", shape=[1], dtype="float32")
            # stage 0: int mask from x, then the cut var h
            mask_i = L.cast(L.greater_than(x, L.zeros_like(x)), "int64")
            h = L.fc(x, size=16, act="relu")
            # stage 1 consumes BOTH h and the int mask
            gate = L.reduce_mean(L.cast(mask_i, "float32"), dim=[1],
                                 keep_dim=True)
            pred = L.fc(L.elementwise_mul(h, L.cast(mask_i, "float32")),
                        size=1)
            loss = L.mean(L.square_error_cost(
                L.elementwise_mul(pred, gate), y))
            from paddle_tpu.parallel.pipeline import build_pipeline_plan
            main._pipeline = build_pipeline_plan(
                main, loss, [h], pt.optimizer.SGD(0.05), 4, startup,
                schedule="1f1b")
    # the int mask really is a stage-0 boundary output
    assert mask_i.name in main._pipeline.stages[0].out_names
    rng = np.random.default_rng(0)
    feed = {"x": rng.standard_normal((16, 16)).astype(np.float32),
            "y": rng.standard_normal((16, 1)).astype(np.float32)}
    exe = pt.Executor()
    with pt.scope_guard(pt.Scope()):
        exe.run(startup)
        for schedule in ("1f1b", "gpipe"):
            main._pipeline.schedule = schedule
            (lv,) = exe.run(main, feed=feed, fetch_list=[loss.name])
            assert np.isfinite(float(np.asarray(lv).reshape(-1)[0]))
