"""Detection ops: prior_box geometry, box_coder round trip, IoU values,
multiclass NMS suppression (reference detection/ op family semantics on
fixed shapes)."""
import numpy as np

import paddle_tpu as pt
from paddle_tpu import layers as L


def test_prior_box_count_and_geometry():
    feat = L.data(name="feat", shape=[8, 2, 2], dtype="float32")
    img = L.data(name="img", shape=[3, 32, 32], dtype="float32")
    boxes, var = L.prior_box(feat, img, min_sizes=[8.0], max_sizes=[16.0],
                             aspect_ratios=[2.0], flip=True)
    exe = pt.Executor()
    (b, v) = exe.run(
        pt.default_main_program(),
        feed={"feat": np.zeros((1, 8, 2, 2), np.float32),
              "img": np.zeros((1, 3, 32, 32), np.float32)},
        fetch_list=[boxes, var])
    # priors per cell: min(ratio 1) + sqrt(min*max) + ratio 2 + ratio 1/2
    assert b.shape == (2, 2, 4, 4)
    assert v.shape == b.shape
    # first prior at cell (0,0): center (0.5*16, 0.5*16)=(8,8), 8x8 box
    np.testing.assert_allclose(
        b[0, 0, 0], [4 / 32, 4 / 32, 12 / 32, 12 / 32], atol=1e-6)
    # sqrt box: sqrt(8*16) ~ 11.31
    s = np.sqrt(8.0 * 16.0) / 2
    np.testing.assert_allclose(
        b[0, 0, 1], [(8 - s) / 32, (8 - s) / 32, (8 + s) / 32, (8 + s) / 32],
        atol=1e-5)


def test_box_coder_encode_decode_roundtrip():
    rng = np.random.default_rng(0)
    priors = np.array([[0.1, 0.1, 0.4, 0.5], [0.3, 0.2, 0.9, 0.8]],
                      np.float32)
    gts = np.array([[0.15, 0.12, 0.45, 0.47]], np.float32)
    pb = L.data(name="pb", shape=[2, 4], dtype="float32",
                append_batch_size=False)
    gt = L.data(name="gt", shape=[1, 4], dtype="float32",
                append_batch_size=False)
    enc = L.box_coder(pb, None, gt, code_type="encode_center_size")
    dec = L.box_coder(pb, None, enc, code_type="decode_center_size")
    exe = pt.Executor()
    e, d = exe.run(pt.default_main_program(),
                   feed={"pb": priors, "gt": gts}, fetch_list=[enc, dec])
    assert e.shape == (1, 2, 4)
    # decoding the encoding against the same priors returns the gt box
    np.testing.assert_allclose(d[0, 0], gts[0], atol=1e-5)
    np.testing.assert_allclose(d[0, 1], gts[0], atol=1e-5)


def test_iou_similarity_values():
    a = np.array([[0, 0, 2, 2]], np.float32)
    b = np.array([[0, 0, 2, 2], [1, 1, 3, 3], [5, 5, 6, 6]], np.float32)
    x = L.data(name="x", shape=[1, 4], dtype="float32",
               append_batch_size=False)
    y = L.data(name="y", shape=[3, 4], dtype="float32",
               append_batch_size=False)
    out = L.iou_similarity(x, y)
    exe = pt.Executor()
    (got,) = exe.run(pt.default_main_program(), feed={"x": a, "y": b},
                     fetch_list=[out])
    np.testing.assert_allclose(got[0], [1.0, 1.0 / 7.0, 0.0], rtol=1e-5)


def test_multiclass_nms_suppresses_overlaps():
    # two near-identical boxes + one distant; NMS keeps the best of the
    # pair and the distant one
    boxes = np.array([[[0.1, 0.1, 0.4, 0.4],
                       [0.11, 0.11, 0.41, 0.41],
                       [0.6, 0.6, 0.9, 0.9]]], np.float32)
    scores = np.array([[[0.0, 0.0, 0.0],        # background
                        [0.9, 0.8, 0.7]]], np.float32)  # class 1
    bb = L.data(name="bb", shape=[3, 4], dtype="float32")
    sc = L.data(name="sc", shape=[2, 3], dtype="float32")
    out = L.multiclass_nms(bb, sc, score_threshold=0.1, nms_top_k=10,
                           keep_top_k=3, nms_threshold=0.5)
    exe = pt.Executor()
    (got,) = exe.run(pt.default_main_program(),
                     feed={"bb": boxes, "sc": scores}, fetch_list=[out])
    labels = got[0, :, 0]
    kept = labels >= 0
    assert kept.sum() == 2, got[0]
    kept_scores = sorted(got[0, kept, 1].tolist(), reverse=True)
    np.testing.assert_allclose(kept_scores, [0.9, 0.7], rtol=1e-5)


def test_ssd_loss_trains_toy_detector():
    """SSD loss end to end: a linear head over fixed priors learns to
    classify/locate a synthetic box (book SSD pattern on padded gt)."""
    M, C, G = 8, 3, 2
    priors = np.stack(np.meshgrid(np.linspace(0.1, 0.7, 4),
                                  [0.2, 0.6]), -1).reshape(-1, 2)
    priors = np.concatenate([priors, priors + 0.25], 1).astype(np.float32)

    feat = L.data(name="feat", shape=[16], dtype="float32")
    loc = L.reshape(L.fc(feat, size=M * 4, name="loc"), [-1, M, 4])
    conf = L.reshape(L.fc(feat, size=M * C, name="conf"), [-1, M, C])
    pb = L.data(name="pb", shape=[M, 4], dtype="float32",
                append_batch_size=False)
    gtb = L.data(name="gtb", shape=[G, 4], dtype="float32")
    gtl = L.data(name="gtl", shape=[G, 1], dtype="int64")
    gtc = L.data(name="gtc", shape=[], dtype="int64")
    loss = L.mean(L.ssd_loss(loc, conf, gtb, gtl, pb, gt_count=gtc))
    pt.optimizer.Adam(0.01).minimize(loss)

    exe = pt.Executor()
    exe.run(pt.default_startup_program())
    rng = np.random.default_rng(0)
    first = last = None
    for i in range(40):
        B = 8
        featv = rng.standard_normal((B, 16)).astype(np.float32)
        # gt box near the first prior, one valid gt per image
        gt = np.tile(priors[0], (B, G, 1)).astype(np.float32)
        gt += rng.uniform(-0.02, 0.02, gt.shape).astype(np.float32)
        lbl = np.full((B, G, 1), 1, np.int64)
        cnt = np.full((B,), 1, np.int64)
        (lv,) = exe.run(pt.default_main_program(),
                        feed={"feat": featv, "pb": priors, "gtb": gt,
                              "gtl": lbl, "gtc": cnt},
                        fetch_list=[loss])
        if first is None:
            first = float(lv)
        last = float(lv)
    assert np.isfinite(last) and last < first * 0.8, (first, last)
