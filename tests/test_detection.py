"""Detection ops: prior_box geometry, box_coder round trip, IoU values,
multiclass NMS suppression (reference detection/ op family semantics on
fixed shapes)."""
import numpy as np

import paddle_tpu as pt
from paddle_tpu import layers as L


def _run(build, feeds, n_fetch=1):
    main, startup = pt.Program(), pt.Program()
    with pt.program_guard(main, startup):
        with pt.unique_name.guard():
            outs = build()
    outs = outs if isinstance(outs, (list, tuple)) else [outs]
    exe = pt.Executor()
    with pt.scope_guard(pt.Scope()):
        exe.run(startup)
        vals = exe.run(main, feed=feeds, fetch_list=list(outs)[:n_fetch])
    return [np.asarray(v) for v in vals]


def test_prior_box_count_and_geometry():
    feat = L.data(name="feat", shape=[8, 2, 2], dtype="float32")
    img = L.data(name="img", shape=[3, 32, 32], dtype="float32")
    boxes, var = L.prior_box(feat, img, min_sizes=[8.0], max_sizes=[16.0],
                             aspect_ratios=[2.0], flip=True)
    exe = pt.Executor()
    (b, v) = exe.run(
        pt.default_main_program(),
        feed={"feat": np.zeros((1, 8, 2, 2), np.float32),
              "img": np.zeros((1, 3, 32, 32), np.float32)},
        fetch_list=[boxes, var])
    # priors per cell: min(ratio 1) + sqrt(min*max) + ratio 2 + ratio 1/2
    assert b.shape == (2, 2, 4, 4)
    assert v.shape == b.shape
    # first prior at cell (0,0): center (0.5*16, 0.5*16)=(8,8), 8x8 box
    np.testing.assert_allclose(
        b[0, 0, 0], [4 / 32, 4 / 32, 12 / 32, 12 / 32], atol=1e-6)
    # sqrt box: sqrt(8*16) ~ 11.31
    s = np.sqrt(8.0 * 16.0) / 2
    np.testing.assert_allclose(
        b[0, 0, 1], [(8 - s) / 32, (8 - s) / 32, (8 + s) / 32, (8 + s) / 32],
        atol=1e-5)


def test_box_coder_encode_decode_roundtrip():
    rng = np.random.default_rng(0)
    priors = np.array([[0.1, 0.1, 0.4, 0.5], [0.3, 0.2, 0.9, 0.8]],
                      np.float32)
    gts = np.array([[0.15, 0.12, 0.45, 0.47]], np.float32)
    pb = L.data(name="pb", shape=[2, 4], dtype="float32",
                append_batch_size=False)
    gt = L.data(name="gt", shape=[1, 4], dtype="float32",
                append_batch_size=False)
    enc = L.box_coder(pb, None, gt, code_type="encode_center_size")
    dec = L.box_coder(pb, None, enc, code_type="decode_center_size")
    exe = pt.Executor()
    e, d = exe.run(pt.default_main_program(),
                   feed={"pb": priors, "gt": gts}, fetch_list=[enc, dec])
    assert e.shape == (1, 2, 4)
    # decoding the encoding against the same priors returns the gt box
    np.testing.assert_allclose(d[0, 0], gts[0], atol=1e-5)
    np.testing.assert_allclose(d[0, 1], gts[0], atol=1e-5)


def test_iou_similarity_values():
    a = np.array([[0, 0, 2, 2]], np.float32)
    b = np.array([[0, 0, 2, 2], [1, 1, 3, 3], [5, 5, 6, 6]], np.float32)
    x = L.data(name="x", shape=[1, 4], dtype="float32",
               append_batch_size=False)
    y = L.data(name="y", shape=[3, 4], dtype="float32",
               append_batch_size=False)
    out = L.iou_similarity(x, y)
    exe = pt.Executor()
    (got,) = exe.run(pt.default_main_program(), feed={"x": a, "y": b},
                     fetch_list=[out])
    np.testing.assert_allclose(got[0], [1.0, 1.0 / 7.0, 0.0], rtol=1e-5)


def test_multiclass_nms_suppresses_overlaps():
    # two near-identical boxes + one distant; NMS keeps the best of the
    # pair and the distant one
    boxes = np.array([[[0.1, 0.1, 0.4, 0.4],
                       [0.11, 0.11, 0.41, 0.41],
                       [0.6, 0.6, 0.9, 0.9]]], np.float32)
    scores = np.array([[[0.0, 0.0, 0.0],        # background
                        [0.9, 0.8, 0.7]]], np.float32)  # class 1
    bb = L.data(name="bb", shape=[3, 4], dtype="float32")
    sc = L.data(name="sc", shape=[2, 3], dtype="float32")
    out = L.multiclass_nms(bb, sc, score_threshold=0.1, nms_top_k=10,
                           keep_top_k=3, nms_threshold=0.5)
    exe = pt.Executor()
    (got,) = exe.run(pt.default_main_program(),
                     feed={"bb": boxes, "sc": scores}, fetch_list=[out])
    labels = got[0, :, 0]
    kept = labels >= 0
    assert kept.sum() == 2, got[0]
    kept_scores = sorted(got[0, kept, 1].tolist(), reverse=True)
    np.testing.assert_allclose(kept_scores, [0.9, 0.7], rtol=1e-5)


def test_ssd_loss_trains_toy_detector():
    """SSD loss end to end: a linear head over fixed priors learns to
    classify/locate a synthetic box (book SSD pattern on padded gt)."""
    M, C, G = 8, 3, 2
    priors = np.stack(np.meshgrid(np.linspace(0.1, 0.7, 4),
                                  [0.2, 0.6]), -1).reshape(-1, 2)
    priors = np.concatenate([priors, priors + 0.25], 1).astype(np.float32)

    feat = L.data(name="feat", shape=[16], dtype="float32")
    loc = L.reshape(L.fc(feat, size=M * 4, name="loc"), [-1, M, 4])
    conf = L.reshape(L.fc(feat, size=M * C, name="conf"), [-1, M, C])
    pb = L.data(name="pb", shape=[M, 4], dtype="float32",
                append_batch_size=False)
    gtb = L.data(name="gtb", shape=[G, 4], dtype="float32")
    gtl = L.data(name="gtl", shape=[G, 1], dtype="int64")
    gtc = L.data(name="gtc", shape=[], dtype="int64")
    loss = L.mean(L.ssd_loss(loc, conf, gtb, gtl, pb, gt_count=gtc))
    pt.optimizer.Adam(0.01).minimize(loss)

    exe = pt.Executor()
    exe.run(pt.default_startup_program())
    rng = np.random.default_rng(0)
    first = last = None
    for i in range(40):
        B = 8
        featv = rng.standard_normal((B, 16)).astype(np.float32)
        # gt box near the first prior, one valid gt per image
        gt = np.tile(priors[0], (B, G, 1)).astype(np.float32)
        gt += rng.uniform(-0.02, 0.02, gt.shape).astype(np.float32)
        lbl = np.full((B, G, 1), 1, np.int64)
        cnt = np.full((B,), 1, np.int64)
        (lv,) = exe.run(pt.default_main_program(),
                        feed={"feat": featv, "pb": priors, "gtb": gt,
                              "gtl": lbl, "gtc": cnt},
                        fetch_list=[loss])
        if first is None:
            first = float(lv)
        last = float(lv)
    assert np.isfinite(last) and last < first * 0.8, (first, last)


# -- round-4 tail: yolo family, anchors, proposals, psroi ------------------


def _np_yolov3_loss(x, gt_box, gt_label, anchors, mask, class_num,
                    ignore_thresh, downsample, use_smooth=True):
    """Direct numpy port of reference yolov3_loss_op.h (the oracle)."""
    def sce(v, t):
        return max(v, 0.0) - v * t + np.log1p(np.exp(-abs(v)))

    N, _, H, W = x.shape
    an_num = len(anchors) // 2
    mask_num = len(mask)
    B = gt_box.shape[1]
    input_size = downsample * H
    pos_l, neg_l = 1.0, 0.0
    if use_smooth:
        d = min(1.0 / class_num, 1.0 / 40)
        pos_l, neg_l = 1.0 - d, d
    xr = x.reshape(N, mask_num, 5 + class_num, H, W)
    loss = np.zeros(N)
    obj_mask = np.zeros((N, mask_num, H, W))

    def iou(b1, b2):
        ow = min(b1[0] + b1[2] / 2, b2[0] + b2[2] / 2) - \
            max(b1[0] - b1[2] / 2, b2[0] - b2[2] / 2)
        oh = min(b1[1] + b1[3] / 2, b2[1] + b2[3] / 2) - \
            max(b1[1] - b1[3] / 2, b2[1] - b2[3] / 2)
        inter = 0.0 if (ow < 0 or oh < 0) else ow * oh
        return inter / (b1[2] * b1[3] + b2[2] * b2[3] - inter + 1e-10)

    for i in range(N):
        for j in range(mask_num):
            for k in range(H):
                for l in range(W):
                    px = (l + 1 / (1 + np.exp(-xr[i, j, 0, k, l]))) / W
                    py = (k + 1 / (1 + np.exp(-xr[i, j, 1, k, l]))) / H
                    pw = np.exp(xr[i, j, 2, k, l]) * anchors[2 * mask[j]] / input_size
                    ph = np.exp(xr[i, j, 3, k, l]) * anchors[2 * mask[j] + 1] / input_size
                    best = 0.0
                    for t in range(B):
                        if gt_box[i, t, 2] <= 1e-6 or gt_box[i, t, 3] <= 1e-6:
                            continue
                        best = max(best, iou((px, py, pw, ph), gt_box[i, t]))
                    if best > ignore_thresh:
                        obj_mask[i, j, k, l] = -1
        for t in range(B):
            if gt_box[i, t, 2] <= 1e-6 or gt_box[i, t, 3] <= 1e-6:
                continue
            gi = int(gt_box[i, t, 0] * W)
            gj = int(gt_box[i, t, 1] * H)
            best_iou, best_n = 0.0, 0
            for a in range(an_num):
                ab = (0, 0, anchors[2 * a] / input_size,
                      anchors[2 * a + 1] / input_size)
                v = iou(ab, (0, 0, gt_box[i, t, 2], gt_box[i, t, 3]))
                if v > best_iou:
                    best_iou, best_n = v, a
            if best_n not in mask:
                continue
            mj = mask.index(best_n)
            tx = gt_box[i, t, 0] * W - gi
            ty = gt_box[i, t, 1] * H - gj
            tw = np.log(gt_box[i, t, 2] * input_size / anchors[2 * best_n])
            th = np.log(gt_box[i, t, 3] * input_size / anchors[2 * best_n + 1])
            s = 2.0 - gt_box[i, t, 2] * gt_box[i, t, 3]
            loss[i] += (sce(xr[i, mj, 0, gj, gi], tx)
                        + sce(xr[i, mj, 1, gj, gi], ty)
                        + abs(xr[i, mj, 2, gj, gi] - tw)
                        + abs(xr[i, mj, 3, gj, gi] - th)) * s
            obj_mask[i, mj, gj, gi] = 1.0
            for c in range(class_num):
                tgt = pos_l if c == gt_label[i, t] else neg_l
                loss[i] += sce(xr[i, mj, 5 + c, gj, gi], tgt)
        for j in range(mask_num):
            for k in range(H):
                for l in range(W):
                    o = obj_mask[i, j, k, l]
                    if o > 1e-5:
                        loss[i] += sce(xr[i, j, 4, k, l], 1.0) * o
                    elif o > -0.5:
                        loss[i] += sce(xr[i, j, 4, k, l], 0.0)
    return loss


def test_yolov3_loss_matches_reference_port():
    rng = np.random.default_rng(0)
    N, H, W, class_num = 2, 4, 4, 3
    anchors = [10, 13, 16, 30, 33, 23]
    mask = [0, 1]
    x = rng.standard_normal((N, len(mask) * (5 + class_num), H, W)) \
        .astype(np.float32)
    gt_box = np.array([[[0.3, 0.4, 0.2, 0.3], [0.7, 0.2, 0.1, 0.1],
                        [0.0, 0.0, 0.0, 0.0]],
                       [[0.5, 0.5, 0.4, 0.5], [0.0, 0.0, 0.0, 0.0],
                        [0.0, 0.0, 0.0, 0.0]]], np.float32)
    gt_label = np.array([[1, 2, 0], [0, 0, 0]], np.int64)

    def build():
        xv = L.data(name="x", shape=list(x.shape[1:]), dtype="float32")
        gb = L.data(name="gb", shape=[3, 4], dtype="float32")
        gl = L.data(name="gl", shape=[3], dtype="int64")
        return L.yolov3_loss(xv, gb, gl, anchors, mask, class_num,
                             ignore_thresh=0.7, downsample_ratio=32)

    out, = _run(build, {"x": x, "gb": gt_box, "gl": gt_label})
    expect = _np_yolov3_loss(x.astype(np.float64), gt_box, gt_label,
                             anchors, mask, class_num, 0.7, 32)
    np.testing.assert_allclose(out, expect, rtol=2e-4, atol=2e-4)


def test_yolov3_loss_trains():
    """YOLO-head forward/backward smoke: conv head -> yolov3_loss -> SGD
    step decreases the loss on a fixed batch."""
    rng = np.random.default_rng(1)
    anchors = [10, 13, 16, 30]
    mask = [0, 1]
    class_num = 2
    img = rng.standard_normal((2, 3, 32, 32)).astype(np.float32)
    gt_box = np.array([[[0.4, 0.4, 0.3, 0.3]], [[0.6, 0.6, 0.2, 0.4]]],
                      np.float32)
    gt_label = np.zeros((2, 1), np.int64)

    main, startup = pt.Program(), pt.Program()
    with pt.program_guard(main, startup):
        with pt.unique_name.guard():
            iv = L.data(name="img", shape=[3, 32, 32], dtype="float32")
            gb = L.data(name="gb", shape=[1, 4], dtype="float32")
            gl = L.data(name="gl", shape=[1], dtype="int64")
            feat = L.conv2d(iv, num_filters=len(mask) * (5 + class_num),
                            filter_size=3, stride=32, padding=1, act=None)
            loss = L.reduce_mean(L.yolov3_loss(
                feat, gb, gl, anchors, mask, class_num, 0.7, 32))
            pt.optimizer.SGD(0.01).minimize(loss)
    exe = pt.Executor()
    with pt.scope_guard(pt.Scope()):
        exe.run(startup)
        feed = {"img": img, "gb": gt_box, "gl": gt_label}
        first = float(np.asarray(
            exe.run(main, feed=feed, fetch_list=[loss])[0]))
        for _ in range(10):
            lv, = exe.run(main, feed=feed, fetch_list=[loss])
    assert float(np.asarray(lv)) < first


def test_yolo_box_decodes_center_box():
    anchors = [32, 32]
    N, H, W, cls = 1, 2, 2, 1
    x = np.zeros((N, 5 + cls, H, W), np.float32)
    x[0, 4] = 5.0   # high conf everywhere
    x[0, 5] = 5.0

    def build():
        xv = L.data(name="x", shape=[5 + cls, H, W], dtype="float32")
        sz = L.data(name="sz", shape=[2], dtype="int64")
        b, s = L.yolo_box(xv, sz, anchors, cls, 0.01, 32)
        return [b, s]

    boxes, scores = _run(lambda: build(),
                         {"x": x, "sz": np.array([[64, 64]], np.int64)},
                         n_fetch=2)
    # cell (0,0): cx = 0.5/2 -> 16 px; box w = 32/64 -> 32 px
    np.testing.assert_allclose(boxes[0, 0], [0.0, 0.0, 31.0, 31.0],
                               atol=1.5)
    assert scores[0, 0, 0] > 0.9


def test_psroi_pool_average_bins():
    # X: 8 channels = 2 out channels * 2x2 bins; one roi covering all 4x4
    O, ph, pw = 2, 2, 2
    x = np.zeros((1, O * ph * pw, 4, 4), np.float32)
    for c in range(O * ph * pw):
        x[0, c] = c  # constant planes
    rois = np.array([[0.0, 0.0, 3.0, 3.0]], np.float32)

    def build():
        xv = L.data(name="x", shape=[O * ph * pw, 4, 4], dtype="float32")
        rv = L.data(name="r", shape=[4], dtype="float32",
                    append_batch_size=False)
        return L.psroi_pool(xv, rv, O, 1.0, ph, pw)

    out, = _run(build, {"x": x, "r": rois})
    assert out.shape == (1, O, ph, pw)
    # out channel o bin (i,j) pools plane o*4 + i*2 + j (constant = its id)
    for o in range(O):
        for i in range(ph):
            for j in range(pw):
                assert out[0, o, i, j] == o * 4 + i * 2 + j


def test_anchor_generator_and_density_prior_box_run():
    def build():
        f = L.data(name="f", shape=[8, 4, 4], dtype="float32")
        img = L.data(name="img", shape=[3, 64, 64], dtype="float32")
        a, av = L.anchor_generator(f, anchor_sizes=[64.0],
                                   aspect_ratios=[1.0], stride=[16.0, 16.0])
        b, bv = L.density_prior_box(
            f, img, densities=[2], fixed_sizes=[32.0], fixed_ratios=[1.0])
        return [a, b]

    a, b = _run(lambda: build(),
                {"f": np.zeros((1, 8, 4, 4), np.float32),
                 "img": np.zeros((1, 3, 64, 64), np.float32)}, n_fetch=2)
    assert a.shape == (4, 4, 1, 4)
    # density 2 -> 4 boxes per cell
    assert b.shape[:2] == (4, 4) and b.shape[-1] == 4
    # reference: x_ctr = 0.5*(16-1) = 7.5, corners +-0.5*(64-1)
    np.testing.assert_allclose(a[0, 0, 0], [-24.0, -24.0, 39.0, 39.0],
                               atol=1e-4)


def test_generate_proposals_runs():
    rng = np.random.default_rng(2)
    N, A, H, W = 1, 3, 4, 4

    def build():
        s = L.data(name="s", shape=[A, H, W], dtype="float32")
        d = L.data(name="d", shape=[A * 4, H, W], dtype="float32")
        info = L.data(name="info", shape=[3], dtype="float32")
        f = L.data(name="f", shape=[8, H, W], dtype="float32")
        anc, var = L.anchor_generator(f, anchor_sizes=[32.0],
                                      aspect_ratios=[0.5, 1.0, 2.0],
                                      stride=[16.0, 16.0])
        rois, probs = L.generate_proposals(
            s, d, info, anc, var, pre_nms_top_n=12, post_nms_top_n=5,
            nms_thresh=0.7, min_size=4.0)
        return [rois, probs]

    rois, probs = _run(
        lambda: build(),
        {"s": rng.standard_normal((N, A, H, W)).astype(np.float32),
         "d": 0.1 * rng.standard_normal((N, A * 4, H, W)).astype(np.float32),
         "info": np.array([[64.0, 64.0, 1.0]], np.float32),
         "f": np.zeros((N, 8, H, W), np.float32)}, n_fetch=2)
    assert rois.shape[-1] == 4
    assert np.isfinite(rois).all() and np.isfinite(probs).all()
