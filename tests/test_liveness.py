"""Distributed liveness layer: rpc_deadline plumbing, heartbeats,
dead-trainer eviction/rejoin, and the hang watchdogs.

In-process tests drive PServerRuntime/PSClient directly with shrunken
deadlines; the chaos-marked scenario SIGKILLs a real subprocess trainer
mid-sync-round (reference test_dist_base.py:442 kill/retry pattern) and
asserts the server unblocks within FLAGS_rpc_deadline — not the old fixed
30 s — and that a restarted trainer rejoins and resumes from its latest
checkpoint."""
import os
import socket
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

_DIR = os.path.dirname(os.path.abspath(__file__))
_REPO = os.path.dirname(_DIR)


def _free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


@pytest.fixture
def liveness_flags():
    """Shrink every liveness deadline to test scale; restore on exit."""
    from paddle_tpu import flags

    saved = {k: flags.get_flag(k) for k in
             ("rpc_deadline", "heartbeat_interval_ms",
              "heartbeat_timeout_ms", "watchdog_stall_s")}
    flags.set_flags({"rpc_deadline": 1200, "heartbeat_interval_ms": 100,
                     "heartbeat_timeout_ms": 0})
    yield flags
    flags.set_flags(saved)


def _start_server(n_trainers):
    import paddle_tpu as pt
    from paddle_tpu.distributed.ps_rpc import PServerRuntime

    ep = f"127.0.0.1:{_free_port()}"
    srv = PServerRuntime(ep, n_trainers=n_trainers, sync_mode=True,
                         blocks=[], scope=pt.Scope(), executor=pt.Executor())
    t = threading.Thread(target=srv.serve, daemon=True)
    t.start()
    return ep, srv, t


# -- deadline plumbing --------------------------------------------------------

def test_no_hardcoded_deadline_left_in_ps_rpc():
    """Every timeout in the PS RPC layer must come from FLAGS_rpc_deadline
    (the old fixed 30.0 s constants are the regression this guards)."""
    src_path = os.path.join(_REPO, "paddle_tpu", "distributed", "ps_rpc.py")
    with open(src_path) as f:
        src = f.read()
    assert "30.0" not in src
    assert "rpc_deadline_s()" in src


def test_rpc_deadline_flag_registered_with_reference_default():
    from paddle_tpu import flags

    assert flags.all_flags()["rpc_deadline"] == 180000  # ms, reference
    assert "heartbeat_interval_ms" in flags.all_flags()
    assert "heartbeat_timeout_ms" in flags.all_flags()
    assert "watchdog_stall_s" in flags.all_flags()


def test_connect_bounded_by_rpc_deadline(liveness_flags):
    from paddle_tpu.distributed.ps_rpc import PSClient

    liveness_flags.set_flags({"rpc_deadline": 400})
    client = PSClient([f"127.0.0.1:{_free_port()}"], 0)  # nothing listening
    t0 = time.monotonic()
    with pytest.raises(ConnectionRefusedError):
        client.send_barrier()
    assert time.monotonic() - t0 < 5.0
    client.close()


def test_reply_wait_bounded_by_rpc_deadline(liveness_flags):
    """A server that accepts but never replies must yield TimeoutError
    within the (doubled, for barriers) deadline — never an infinite wait."""
    from multiprocessing.connection import Listener

    from paddle_tpu.distributed.ps_rpc import PSClient, _authkey

    liveness_flags.set_flags({"rpc_deadline": 400})
    ep = f"127.0.0.1:{_free_port()}"
    host, port = ep.rsplit(":", 1)
    listener = Listener((host, int(port)), authkey=_authkey())
    conns = []

    def mute_server():
        while True:
            try:
                c = listener.accept()
            except OSError:
                return
            conns.append(c)  # read nothing, reply to nothing

    threading.Thread(target=mute_server, daemon=True).start()
    client = PSClient([ep], 0)
    client.stop_heartbeat()
    t0 = time.monotonic()
    with pytest.raises(TimeoutError, match="FLAGS_rpc_deadline"):
        client.send_barrier()
    assert time.monotonic() - t0 < 5.0
    client.close()
    listener.close()


# -- heartbeats, eviction, rejoin ---------------------------------------------

def test_dead_trainer_evicted_and_survivor_unblocked(liveness_flags):
    """Trainer 1 never shows up for the round: the monitor evicts it within
    the liveness deadline, the survivor's barrier releases, the eviction is
    logged, and a rejoin re-admits it for the next round."""
    from paddle_tpu.distributed.ps_rpc import PSClient

    ep, srv, _ = _start_server(n_trainers=2)
    c0 = PSClient([ep], 0)
    t0 = time.monotonic()
    c0.send_barrier()  # would block forever without eviction
    elapsed = time.monotonic() - t0
    assert elapsed < 4.0, f"survivor blocked {elapsed:.1f}s"
    evicts = [e for e in srv.liveness_log if e["event"] == "evict"]
    assert evicts and evicts[0]["trainer"] == 1

    # rejoin: trainer 1 comes back and the next round needs both again
    c1 = PSClient([ep], 1)
    c1.rejoin()
    assert 1 not in srv._evicted
    assert [e["event"] for e in srv.liveness_log][-1] == "rejoin"

    released = []
    th = threading.Thread(
        target=lambda: (c0.send_barrier(), released.append(0)), daemon=True)
    th.start()
    time.sleep(0.3)
    assert not released, "round ran without the rejoined trainer"
    c1.send_barrier()
    th.join(5.0)
    assert released == [0]
    c0.send_complete()
    c1.send_complete()
    c0.close()
    c1.close()


def test_eviction_drops_dead_trainers_half_round_grads(liveness_flags):
    """Gradients the dead trainer posted before dying must not leak into
    the survivors' renormalized average."""
    from paddle_tpu.distributed.ps_rpc import PSClient

    ep, srv, _ = _start_server(n_trainers=2)
    c1 = PSClient([ep], 1)
    c1.send_var(ep, "w@GRAD", np.ones((2, 2), np.float32))  # then dies
    c0 = PSClient([ep], 0)
    c0.send_var(ep, "w@GRAD", np.full((2, 2), 3.0, np.float32))
    c0.send_barrier()
    assert 1 in srv._evicted
    assert 1 not in srv._grad_buf.get("w@GRAD", {})
    c0.send_complete()
    c0.close()
    c1.close()


def test_heartbeat_keeps_slow_trainer_admitted(liveness_flags):
    """The positive case: a trainer that is SLOW but heartbeating must not
    be evicted even when the round stalls past the deadline — liveness is
    heartbeats, not round latency."""
    from paddle_tpu.distributed.ps_rpc import PSClient

    liveness_flags.set_flags({"rpc_deadline": 1000})
    ep, srv, _ = _start_server(n_trainers=2)
    c0, c1 = PSClient([ep], 0), PSClient([ep], 1)
    c1.start_heartbeat()  # alive and beating, just slow to compute
    released = []
    th = threading.Thread(
        target=lambda: (c0.send_barrier(), released.append(0)), daemon=True)
    th.start()
    # past the 1.0s eviction deadline, but inside the survivor's 2x-deadline
    # barrier wait — only heartbeats keep this window open
    time.sleep(1.5)
    assert not srv._evicted, "heartbeating trainer was evicted"
    assert not released
    c1.send_barrier()
    th.join(5.0)
    assert released == [0]
    c0.send_complete()
    c1.send_complete()
    c0.close()
    c1.close()


def test_heartbeat_loss_site_starves_monitor_into_eviction(liveness_flags):
    """The heartbeat_loss fault site: the beacon thread runs but every beat
    is injected away, so the server's monitor must treat the trainer as
    dead once the round stalls."""
    from paddle_tpu.distributed.ps_rpc import PSClient
    from paddle_tpu.resilience import fault_scope

    ep, srv, _ = _start_server(n_trainers=2)
    with fault_scope("rand:p=1.0,seed=0,sites=heartbeat_loss"):
        c1 = PSClient([ep], 1)
        c1.start_heartbeat()  # every tick hits the fault site
        c0 = PSClient([ep], 0)
        t0 = time.monotonic()
        c0.send_barrier()
        assert time.monotonic() - t0 < 4.0
    assert 1 in srv._evicted
    c1.stop_heartbeat()
    c0.send_complete()
    c0.close()
    c1.close()


# -- hang watchdogs -----------------------------------------------------------

def _tiny_train_program():
    import paddle_tpu as pt
    from paddle_tpu import layers as L

    main, startup = pt.Program(), pt.Program()
    with pt.program_guard(main, startup):
        with pt.unique_name.guard():
            x = L.data(name="x", shape=[4], dtype="float32")
            loss = L.mean(L.fc(x, size=3))
            pt.optimizer.SGD(0.1).minimize(loss)
    return main, startup, loss


def test_watchdog_raises_stall_error_on_injected_pipeline_stall(
        liveness_flags):
    """An injected pipeline_stall must turn Executor.wait into a StallError
    carrying the in-flight state dump — never an indefinite hang."""
    import paddle_tpu as pt
    from paddle_tpu.resilience import StallError, fault_scope

    liveness_flags.set_flags({"watchdog_stall_s": 0.3})
    main, startup, loss = _tiny_train_program()
    with pt.scope_guard(pt.Scope()):
        exe = pt.Executor()
        exe.run(startup)
        feed = {"x": np.ones((2, 4), np.float32)}
        with fault_scope("pipeline_stall:1"):
            exe.run_async(main, feed=feed, fetch_list=[loss.name])
            with pytest.raises(StallError) as exc:
                exe.wait()
        state = exc.value.state
        assert state["inflight_step_ids"] == [1]
        assert state["inflight_depth"] == 1
        assert "profiler_stages" in state
        assert "FLAGS_watchdog_stall_s" in str(exc.value)
        exe._inflight.clear()  # forensics done; drop the wedged token


def test_watchdog_clean_async_run_unaffected(liveness_flags):
    """With the watchdog armed but no stall, run_async/wait behave exactly
    as before (the bounded wait is semantics-free on the happy path)."""
    import paddle_tpu as pt

    liveness_flags.set_flags({"watchdog_stall_s": 30.0})
    main, startup, loss = _tiny_train_program()
    with pt.scope_guard(pt.Scope()):
        exe = pt.Executor()
        exe.run(startup)
        feed = {"x": np.ones((2, 4), np.float32)}
        for _ in range(3):
            (lv,) = exe.run_async(main, feed=feed, fetch_list=[loss.name])
        exe.wait()
        assert not exe._inflight
        assert np.isfinite(float(np.asarray(lv).reshape(-1)[0]))


def test_watchdog_device_loader_producer_wedge(liveness_flags):
    """A wedged feed producer (simulated by pipeline_stall in the
    DeviceLoader's staging thread) raises StallError with queue state."""
    from paddle_tpu.pipeline.device_loader import DeviceLoader
    from paddle_tpu.resilience import StallError, fault_scope

    liveness_flags.set_flags({"watchdog_stall_s": 0.3})

    def src():
        for _ in range(3):
            yield {"x": np.ones((2, 4), np.float32)}

    with fault_scope("pipeline_stall:2"):
        it = iter(DeviceLoader(lambda: src(), depth=1))
        next(it)  # batch 1 stages fine
        with pytest.raises(StallError) as exc:
            next(it)  # producer is parked: the consumer wait must bound
    assert exc.value.state["queue_depth"] == 0
    assert "producer_alive" in exc.value.state


# -- the SIGKILL-mid-round chaos scenario -------------------------------------

@pytest.mark.chaos
def test_sigkill_trainer_mid_round_evicted_then_rejoins(tmp_path):
    """Reference test_dist_base.py:442 kill/retry, liveness edition: one of
    two sync trainers dies (os._exit(137) via the trainer_crash fault site
    — a SIGKILL stand-in) at its 3rd barrier. The server must evict it
    within FLAGS_rpc_deadline (3 s here, NOT the old fixed 30 s) so the
    survivor finishes all rounds; a restarted trainer must rejoin and
    resume from its latest checkpoint."""
    script = os.path.join(_DIR, "dist_liveness.py")
    ep = f"127.0.0.1:{_free_port()}"
    deadline_ms = 3000

    def env(extra=None):
        e = dict(os.environ)
        e["PYTHONPATH"] = _REPO + os.pathsep + e.get("PYTHONPATH", "")
        e.pop("FLAGS_fault_plan", None)
        e["FLAGS_rpc_deadline"] = str(deadline_ms)
        e["FLAGS_heartbeat_interval_ms"] = "200"
        e.update(extra or {})
        return e

    def spawn(args, extra_env=None):
        return subprocess.Popen(
            [sys.executable, script, *args], env=env(extra_env),
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT)

    ps = spawn(["pserver", ep, "0", "2", str(tmp_path / "ps.npz"),
                str(tmp_path / "ps_ck"), ep])
    t0 = spawn(["trainer", ep, "0", "2", str(tmp_path / "t0.npz"),
                str(tmp_path / "ck0")])
    # trainer 1 dies at its 3rd sync barrier (step 2), mid-round
    t1 = spawn(["trainer", ep, "1", "2", str(tmp_path / "t1.npz"),
                str(tmp_path / "ck1")],
               extra_env={"FLAGS_fault_plan": "trainer_crash:3"})
    try:
        out1, _ = t1.communicate(timeout=240)
        assert t1.returncode == 137, (t1.returncode, out1.decode()[-2000:])

        # the survivor must complete every round, with the blocked round
        # bounded by the eviction deadline, not the old fixed 30 s
        out0, _ = t0.communicate(timeout=240)
        assert t0.returncode == 0, out0.decode()[-3000:]
        d0 = np.load(str(tmp_path / "t0.npz"))
        assert d0["losses"].shape[0] == 5
        max_step = float(d0["step_times"].max())
        assert max_step < 20.0, (
            f"survivor's blocked round took {max_step:.1f}s — eviction did "
            f"not honor the {deadline_ms}ms deadline")
        assert max_step >= deadline_ms / 1000.0 * 0.5, (
            "no round ever blocked — the crash missed the sync round")

        # restart trainer 1 on the same checkpoint root: rejoin + resume
        t1b = spawn(["trainer", ep, "1", "2", str(tmp_path / "t1.npz"),
                     str(tmp_path / "ck1")])
        out1b, _ = t1b.communicate(timeout=240)
        assert t1b.returncode == 0, out1b.decode()[-3000:]
        assert b"rejoined start=2" in out1b, out1b.decode()[-2000:]
        d1 = np.load(str(tmp_path / "t1.npz"))
        assert int(d1["start_step"]) == 2  # latest ckpt was step 1
        assert d1["losses"].shape[0] == 3  # steps 2..4 only

        # the pserver observed the full evict -> rejoin lifecycle and shut
        # down cleanly once both trainers completed
        outp, _ = ps.communicate(timeout=60)
        assert ps.returncode == 0, outp.decode()[-3000:]
        assert b"evicted trainer 1" in outp, outp.decode()[-2000:]
        assert b"trainer 1 rejoined" in outp, outp.decode()[-2000:]
    finally:
        for p in (ps, t0, t1):
            if p.poll() is None:
                p.kill()
        if "t1b" in dir() and t1b.poll() is None:
            t1b.kill()
