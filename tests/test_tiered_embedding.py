"""Tiered giant-embedding engine (ISSUE 10): the minimize()-time rewrite,
host-tier/cache parity against the dense-lookup oracle, the async feed-
pipeline miss prefetch, frequency-based admission/eviction, delta
checkpoints, the emb_host_stall chaos drill — plus the lookup_table
padding_idx contract (satellite: forward zeros, no gradient)."""
import os

import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import flags
from paddle_tpu import layers as L
from paddle_tpu.layers import tensor as T
from paddle_tpu.param_attr import ParamAttr

VOCAB, DIM, FIELDS, BATCH = 512, 8, 6, 32


@pytest.fixture
def emb_flags():
    saved = {k: flags.get_flag(k) for k in (
        "emb_hbm_budget_mb", "emb_cache_slots", "emb_prefetch_rows",
        "emb_admit_min_freq", "emb_host_shards", "emb_ckpt_base_every",
        "device_prefetch_depth", "watchdog_stall_s", "tuning_mode",
        "tuning_db")}
    yield flags
    flags.set_flags(saved)


def _build(vocab=VOCAB, dim=DIM, name="tbl"):
    ids = T.data(name="ids", shape=[FIELDS], dtype="int64")
    label = T.data(name="label", shape=[1], dtype="float32")
    emb = L.embedding(ids, size=[vocab, dim], is_sparse=True,
                      param_attr=ParamAttr(name=name))
    s = L.reduce_sum(emb, dim=1)
    logit = L.fc(s, size=1, param_attr=ParamAttr(name="w_out"),
                 bias_attr=ParamAttr(name="b_out"))
    loss = L.mean(L.sigmoid_cross_entropy_with_logits(logit, label))
    return loss


def _feed(step, vocab=VOCAB, zipf=False):
    rng = np.random.default_rng(100 + step)
    if zipf:
        ids = (rng.zipf(1.5, (BATCH, FIELDS)) - 1) % vocab
    else:
        ids = rng.integers(0, vocab, (BATCH, FIELDS))
    return {"ids": ids.astype(np.int64),
            "label": rng.integers(0, 2, (BATCH, 1)).astype(np.float32)}


def _minimized(budget_mb, slots=0, seed=7):
    main, startup = pt.Program(), pt.Program()
    main.random_seed = startup.random_seed = seed
    flags.set_flags({"emb_hbm_budget_mb": budget_mb,
                     "emb_cache_slots": slots})
    with pt.program_guard(main, startup), pt.unique_name.guard():
        loss = _build()
        pt.optimizer.SGD(0.1).minimize(loss)
    return main, startup, loss


# -- satellite: lookup_table padding_idx contract ----------------------------

def test_lookup_table_padding_idx_forward_zeros_and_no_grad(emb_flags):
    """padding_idx rows read as zeros AND receive no gradient — the attr is
    plumbed end-to-end, so a training step must leave the padding row's
    parameters untouched while real rows move."""
    pad = 3
    main, startup = pt.Program(), pt.Program()
    with pt.program_guard(main, startup), pt.unique_name.guard():
        ids = T.data(name="ids", shape=[4], dtype="int64")
        emb = L.embedding(ids, size=[16, DIM], padding_idx=pad,
                          param_attr=ParamAttr(name="ptbl"))
        loss = L.mean(emb)
        pt.optimizer.SGD(1.0).minimize(loss)
    exe = pt.Executor()
    with pt.scope_guard(pt.Scope()):
        exe.run(startup)
        w0 = np.array(np.asarray(pt.global_scope().find_var("ptbl")))
        feed = {"ids": np.array([[pad, 1, 2, pad], [5, pad, 6, 7]],
                                np.int64)}
        (out,) = exe.run(main, feed=feed, fetch_list=[emb])
        out = np.asarray(out)
        # forward: padding positions are exactly zero even though the row's
        # parameter values are not
        assert np.abs(w0[pad]).max() > 0
        np.testing.assert_array_equal(out[0, 0], np.zeros(DIM))
        np.testing.assert_array_equal(out[0, 3], np.zeros(DIM))
        np.testing.assert_array_equal(out[1, 1], np.zeros(DIM))
        w1 = np.asarray(pt.global_scope().find_var("ptbl"))
        # backward: the padding row took no gradient; touched rows did
        np.testing.assert_array_equal(w1[pad], w0[pad])
        assert np.abs(w1[1] - w0[1]).max() > 0
        assert np.abs(w1[5] - w0[5]).max() > 0
        # untouched non-padding rows also unchanged (sanity on the scatter)
        np.testing.assert_array_equal(w1[9], w0[9])


# -- the opt-in-by-budget contract -------------------------------------------

def test_under_budget_table_compiles_bitwise_unchanged(emb_flags):
    """Acceptance: with tiering enabled but every table under the budget,
    the built program is IDENTICAL to the tiering-off build — the rewrite
    is opt-in-by-budget, not a global behavior change."""
    import json

    progs = {}
    for label, budget in (("off", 0.0), ("on_big_budget", 1024.0)):
        main, startup, _ = _minimized(budget)
        progs[label] = (json.dumps(main.to_dict(), sort_keys=True),
                        json.dumps(startup.to_dict(), sort_keys=True))
        assert getattr(main, "_tiered_engine", None) is None
    assert progs["off"] == progs["on_big_budget"]


def test_over_budget_table_rewrites_to_tiered_ops(emb_flags):
    main, startup, _ = _minimized(0.001, slots=256)
    ops = [op.type for op in main.global_block.ops]
    assert "lookup_table" not in ops
    assert "tiered_lookup" in ops and "emb_cache_install" in ops
    assert ops.index("emb_cache_install") < ops.index("tiered_lookup")
    eng = main._tiered_engine
    assert eng.tables["tbl"].slots == 256
    # the giant table's device init op is GONE from startup (the host tier
    # owns those bytes); the cache fill replaced it
    sops = [(op.type, op.output_names) for op in startup.global_block.ops]
    assert not any("tbl" in outs and t != "fill_constant"
                   for t, outs in sops if "tbl@CACHE" not in outs)
    assert any("tbl@CACHE" in outs for _, outs in sops)
    # host tier re-drew the SAME distribution the removed init op declared
    host = eng.tables["tbl"].host
    assert host.init[0] == "uniform"
    dense = host.to_dense()
    assert dense.shape == (VOCAB, DIM)
    assert np.abs(dense).max() <= host.init[2] + 1e-6


# -- parity vs the dense-lookup oracle ---------------------------------------

def test_tiered_training_matches_dense_oracle(emb_flags):
    """The acceptance oracle: same model, same inits, same batches — N SGD
    steps through the tiered path (256-slot cache over a 512-row table, so
    eviction + write-back fire constantly) must land on the dense-lookup
    run's parameters within 1e-4 (measured: float-associativity only)."""
    steps = 12
    main_t, startup_t, loss_t = _minimized(0.001, slots=256)
    eng = main_t._tiered_engine

    # oracle program + its init values
    main_o, startup_o, loss_o = _minimized(0.0)
    exe = pt.Executor()
    sc_o = pt.Scope()
    with pt.scope_guard(sc_o):
        exe.run(startup_o)
        init = {n: np.array(np.asarray(sc_o.find_var(n)))
                for n in ("tbl", "w_out", "b_out")}

    import jax

    sc_t = pt.Scope()
    with pt.scope_guard(sc_t):
        exe.run(startup_t)
        eng.tables["tbl"].host.load_rows(np.arange(VOCAB), init["tbl"])
        eng.tables["tbl"].host.clear_dirty()
        sc_t.set_var("w_out", jax.device_put(init["w_out"]))
        sc_t.set_var("b_out", jax.device_put(init["b_out"]))
        losses_t = []
        for s in range(steps):
            (lv,) = exe.run(main_t, feed=_feed(s), fetch_list=[loss_t])
            losses_t.append(float(np.asarray(lv)))
        exe.wait()
        table_t = eng.export_dense("tbl", sc_t)
        out_t = {n: np.asarray(sc_t.find_var(n))
                 for n in ("w_out", "b_out")}
        stats = eng.stats("tbl")

    with pt.scope_guard(sc_o):
        sc_o.set_var("tbl", jax.device_put(init["tbl"]))
        losses_o = []
        for s in range(steps):
            (lv,) = exe.run(main_o, feed=_feed(s), fetch_list=[loss_o])
            losses_o.append(float(np.asarray(lv)))
        table_o = np.asarray(sc_o.find_var("tbl"))
        out_o = {n: np.asarray(sc_o.find_var(n))
                 for n in ("w_out", "b_out")}

    np.testing.assert_allclose(losses_t, losses_o, rtol=0, atol=1e-6)
    assert np.abs(table_t - table_o).max() < 1e-4
    assert np.abs(out_t["w_out"] - out_o["w_out"]).max() < 1e-4
    assert np.abs(out_t["b_out"] - out_o["b_out"]).max() < 1e-4
    # the run genuinely exercised the tiers
    assert stats["evictions"] > 0 and stats["writebacks"] > 0
    assert stats["hit_rate"] is not None


def test_tiered_async_pipeline_with_device_loader(emb_flags):
    """The miss prefetch runs OFF the step: feeds flow through the
    DeviceLoader (background-thread resolution + staging, run_async window)
    and the trained table still matches the synchronous path exactly."""
    from paddle_tpu.pipeline import DeviceLoader

    steps = 10
    flags.set_flags({"device_prefetch_depth": 2})
    main, startup, loss = _minimized(0.001, slots=256)
    eng = main._tiered_engine
    exe = pt.Executor()
    with pt.scope_guard(pt.Scope()) as sc:
        exe.run(startup)

        def src():
            for s in range(steps):
                yield _feed(s)

        loader = DeviceLoader(lambda: src(), depth=2,
                              placement=exe.feed_placer(main))
        for feed in loader:
            exe.run_async(main, feed=feed, fetch_list=[loss])
        exe.wait()
        eng.flush_all()
        stats = eng.stats("tbl")
        assert stats["batches"] == steps
        assert stats["evictions"] > 0
        # every eviction's write-back landed (none dropped/stuck)
        assert stats["writebacks"] == stats["evictions"]
        table_async = eng.export_dense("tbl", sc)

    # synchronous reference over the same batches, same inits (host tier is
    # re-drawn deterministically from the same seed)
    main2, startup2, loss2 = _minimized(0.001, slots=256)
    eng2 = main2._tiered_engine
    with pt.scope_guard(pt.Scope()) as sc2:
        exe2 = pt.Executor()
        exe2.run(startup2)
        for s in range(steps):
            exe2.run(main2, feed=_feed(s), fetch_list=[loss2])
        exe2.wait()
        table_sync = eng2.export_dense("tbl", sc2)
    np.testing.assert_allclose(table_async, table_sync, rtol=0, atol=1e-6)


# -- admission / eviction policy ---------------------------------------------

def test_frequency_admission_probation_and_lru_fallback(emb_flags):
    """Under FLAGS_emb_admit_min_freq, a first-seen id enters on probation
    (zero accumulated frequency) and is evicted before established hot rows;
    ties break LRU. Driven through the raw engine API."""
    from paddle_tpu.embedding import HostShardedTable, TieredEmbeddingEngine

    flags.set_flags({"emb_admit_min_freq": 3, "emb_prefetch_rows": 4})
    host = HostShardedTable("t", 64, 4, init=("uniform", -1, 1), seed=1)
    eng = TieredEmbeddingEngine()
    eng.add_table("t", host, slots=4, cache_var="t@CACHE",
                  rows_var="t@PREFETCH_ROWS", slots_var="t@PREFETCH_SLOTS",
                  evict_var="t@EVICTED", prefetch_rows=4)
    eng.add_lookup("t", "ids", "t@SLOTS@ids", None)
    ts = eng.tables["t"]

    def resolve(ids):
        feed = eng.resolve_feed({"ids": np.asarray(ids, np.int64)})
        return feed

    # fill: ids 0,1 seen repeatedly (hot, above threshold), 2,3 once
    resolve([[0, 1, 0, 1]])
    resolve([[0, 1, 2, 3]])
    assert set(ts.row2slot) == {0, 1, 2, 3}
    # rows 2 and 3 are on probation (seen < 3): a new id must evict one of
    # THEM (LRU tie-break -> row 2, the older slot), never hot rows 0/1
    resolve([[4, 4, 4, 4]])
    assert 0 in ts.row2slot and 1 in ts.row2slot and 4 in ts.row2slot
    assert 2 not in ts.row2slot
    # slots referenced by the CURRENT batch are pinned: resolving a batch
    # that uses 3 and introduces 5 must evict... not 3
    resolve([[3, 5, 3, 5]])
    assert 3 in ts.row2slot and 5 in ts.row2slot


def test_prefetch_buffer_grows_on_overflow(emb_flags):
    from paddle_tpu.embedding import HostShardedTable, TieredEmbeddingEngine

    flags.set_flags({"emb_admit_min_freq": 1})
    host = HostShardedTable("t", 256, 4, init=("constant", 0.5))
    eng = TieredEmbeddingEngine()
    eng.add_table("t", host, slots=128, cache_var="c", rows_var="r",
                  slots_var="s", evict_var="e", prefetch_rows=2)
    eng.add_lookup("t", "ids", "slots", None)
    out = eng.resolve_feed({"ids": np.arange(10, dtype=np.int64)[None]})
    # 10 misses overflow the configured width 2: pow2 growth, padded with
    # the scratch slot
    assert out["r"].shape == (16, 4)
    assert (out["s"][10:] == eng.tables["t"].scratch).all()
    assert eng.tables["t"].prefetch_rows == 16


def test_cache_smaller_than_batch_working_set_raises(emb_flags):
    main, startup, loss = _minimized(0.001, slots=8)
    exe = pt.Executor()
    with pt.scope_guard(pt.Scope()):
        exe.run(startup)
        with pytest.raises(RuntimeError, match="working set"):
            exe.run(main, feed=_feed(0), fetch_list=[loss])


# -- tuning integration -------------------------------------------------------

def test_cache_geometry_resolves_through_tuning_db(emb_flags, tmp_path):
    from paddle_tpu import tuning

    db_path = str(tmp_path / "db.json")
    key = tuning.canonical_key(
        "embedding", tuning.embedding_key("tbl", VOCAB, DIM), "float32",
        tuning.device_kind())
    db = tuning.TuningDB(db_path)
    db.put(key, {"slots": 192, "prefetch_rows": 64}, source="swept")
    db.save(db_path)
    flags.set_flags({"tuning_mode": "consult", "tuning_db": db_path})
    tuning.invalidate_db_cache()
    try:
        main, _, _ = _minimized(0.001, slots=0)
        ts = main._tiered_engine.tables["tbl"]
        assert ts.slots == 192 and ts.prefetch_rows == 64
    finally:
        tuning.invalidate_db_cache()


def test_sweep_mode_records_embedding_candidate(emb_flags, tmp_path):
    from paddle_tpu import tuning

    db_path = str(tmp_path / "db.json")
    flags.set_flags({"tuning_mode": "sweep", "tuning_db": db_path})
    tuning.invalidate_db_cache()
    try:
        _minimized(0.001, slots=0)
        db = tuning.TuningDB(db_path)
        keys = [k for k in db.entries if k.startswith("embedding|")]
        assert keys, sorted(db.entries)
        assert db.entries[keys[0]]["source"] == "candidate"
        assert db.entries[keys[0]]["decision"]["slots"] > 0
    finally:
        tuning.invalidate_db_cache()


# -- chaos: the stalled host tier --------------------------------------------

@pytest.mark.chaos
def test_emb_host_stall_surfaces_via_watchdog(emb_flags):
    """A wedged host-tier prefetch (emb_host_stall on the DeviceLoader's
    producer thread) must raise StallError with queue depths — never hang
    the trainer on an empty staging queue."""
    from paddle_tpu.pipeline import DeviceLoader
    from paddle_tpu.resilience import StallError, fault_scope

    flags.set_flags({"watchdog_stall_s": 0.3})
    main, startup, loss = _minimized(0.001, slots=256)
    exe = pt.Executor()
    with pt.scope_guard(pt.Scope()):
        exe.run(startup)

        def src():
            for s in range(4):
                yield _feed(s)

        with fault_scope("emb_host_stall:2"):
            loader = DeviceLoader(lambda: src(), depth=1,
                                  placement=exe.feed_placer(main))
            it = iter(loader)
            exe.run_async(main, feed=next(it), fetch_list=[loss])
            with pytest.raises(StallError) as exc:
                for feed in it:
                    exe.run_async(main, feed=feed, fetch_list=[loss])
            exe.wait()
        assert "queue_depth" in exc.value.state
        assert "DeviceLoader" in exc.value.what


# -- streaming delta checkpoints ---------------------------------------------

def test_delta_checkpoint_roundtrip_base_plus_delta(emb_flags, tmp_path):
    """Host-tier shards checkpoint as base + cumulative dirty-row deltas
    through the CheckpointManager manifest; restore = base + delta, and the
    device cache restarts cold with the host tier authoritative."""
    import glob

    from paddle_tpu.resilience import CheckpointManager

    flags.set_flags({"emb_ckpt_base_every": 2})
    main, startup, loss = _minimized(0.001, slots=256)
    eng = main._tiered_engine
    exe = pt.Executor()
    root = str(tmp_path / "ck")
    with pt.scope_guard(pt.Scope()) as sc:
        exe.run(startup)
        mgr = CheckpointManager(root, main_program=main, scope=sc)
        for s in range(4):
            exe.run(main, feed=_feed(s), fetch_list=[loss])
            mgr.save(s, executor=exe)
        snap = eng.export_dense("tbl", sc)
        # base rotation happened: step 0 base + step 2 base, deltas between
        bases = sorted(glob.glob(os.path.join(root, "emb_tbl.base_*.npz")))
        assert len(bases) == 2, bases
        man = mgr.read_manifest(3)
        frag = man["extra"]["tiered_embedding"]["tables"]["tbl"]
        assert frag["base_step"] == 2
        # poison the host tier + keep training state, then restore: the
        # table must come back exactly as of the step-3 save
        eng.tables["tbl"].host.load_rows(
            np.arange(VOCAB), np.zeros((VOCAB, DIM), np.float32))
        restored = mgr.restore(executor=exe)
        assert restored == 3
        back = eng.tables["tbl"].host.to_dense()
        np.testing.assert_allclose(back, snap, rtol=0, atol=1e-7)
        # cache restarted cold
        assert eng.tables["tbl"].row2slot == {}
        # and training continues from the restored state
        (lv,) = exe.run(main, feed=_feed(4), fetch_list=[loss])
        assert np.isfinite(float(np.asarray(lv)))


def test_delta_checkpoint_kill_and_resume_bit_identical(emb_flags, tmp_path):
    """SIGKILL a tiered trainer mid-run; a fresh process resumes from
    base + delta and reproduces the undisturbed loss trajectory bit for
    bit (the PR 1 contract extended to the host tier)."""
    import subprocess
    import sys

    script = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                          "dist_emb_resume.py")
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ)
    env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")
    env.pop("FLAGS_fault_plan", None)

    def run(root, losses, kill_at, check=True):
        p = subprocess.run(
            [sys.executable, script, root, losses, "10", str(kill_at)],
            env=env, capture_output=True, timeout=240)
        if check:
            assert p.returncode == 0, p.stderr.decode()[-3000:]
        return p

    def traj(path):
        out = {}
        with open(path) as f:
            for line in f:
                step, val = line.split()
                out[int(step)] = val
        return out

    base = str(tmp_path / "base.txt")
    run(str(tmp_path / "base_ck"), base, -1)
    baseline = traj(base)
    assert sorted(baseline) == list(range(10))

    root, losses = str(tmp_path / "ck"), str(tmp_path / "resumed.txt")
    p = run(root, losses, 4, check=False)
    assert p.returncode == -9, (p.returncode, p.stderr.decode()[-2000:])
    run(root, losses, -1)
    assert traj(losses) == baseline
