"""Diagnostics: op creation-stack attribution, check_nan_inf debug mode,
flags registry, profiler traces, name_scope.

Reference analogues: op_call_stack.cc + enforce.h (attribution),
operator.cc:949 FLAGS_check_nan_inf, platform/flags.cc + read_env_flags,
fluid/profiler.py:225.
"""
import os

import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import flags
from paddle_tpu import layers as L


def test_failing_op_error_names_creation_site():
    """A trace-time failure must name the op and the user line that built it."""
    x = L.data(name="x", shape=[4], dtype="float32")
    y = L.data(name="y", shape=[7], dtype="float32")
    blk = pt.default_main_program().current_block()
    out = blk.create_var(name="bad_out", shape=[4], dtype="float32")
    # bypass the layer API's shape checking: matmul on incompatible shapes
    blk.append_op("matmul", {"X": [x.name], "Y": [y.name]}, {"Out": [out.name]})
    MARKER_LINE = "bad_matmul_marker"  # noqa: F841 (appears in the stack text)
    exe = pt.Executor()
    with pytest.raises(pt.OpError) as ei:
        exe.run(feed={"x": np.ones((2, 4), np.float32),
                      "y": np.ones((2, 7), np.float32)},
                fetch_list=[out])
    msg = str(ei.value)
    assert "Operator 'matmul'" in msg
    assert "test_diagnostics.py" in msg  # creation stack points at user code
    assert "append_op" in msg


def test_infer_error_is_recorded_not_swallowed():
    x = L.data(name="x", shape=[4], dtype="float32")
    blk = pt.default_main_program().current_block()
    out = blk.create_var(name="o", shape=[4], dtype="float32")
    op = blk.append_op("matmul", {"X": [x.name], "Y": [x.name]}, {"Out": [out.name]})
    assert op._infer_error is not None  # [B,4]x[B,4] doesn't contract


def test_check_nan_inf_names_offending_op():
    """Per-op attribution needs concrete values, so it lives under
    jax.disable_jit() (the guard's blame-replay mode); on the compiled path
    the flag keeps the jit path and warns once (ISSUE 4 satellite —
    test_guardrails.py covers that side)."""
    import jax

    x = L.data(name="x", shape=[4], dtype="float32")
    z = L.scale(x, scale=0.0)
    bad = L.elementwise_div(x, z)  # div by zero -> inf
    out = L.mean(bad)
    exe = pt.Executor()
    flags.set_flags({"check_nan_inf": True})
    try:
        with jax.disable_jit():
            with pytest.raises(pt.OpError) as ei:
                exe.run(feed={"x": np.ones((2, 4), np.float32)},
                        fetch_list=[out])
        assert "elementwise_div" in str(ei.value)
        assert "nan/inf" in str(ei.value)
    finally:
        flags.set_flags({"check_nan_inf": False})


def test_flags_env_and_set():
    assert flags.get_flag("op_callstack") is True
    flags.set_flags({"FLAGS_benchmark": "1"})
    assert flags.get_flag("benchmark") is True
    flags.set_flags({"benchmark": False})
    assert flags.get_flag("benchmark") is False
    with pytest.raises(KeyError):
        flags.get_flag("no_such_flag")
    with pytest.raises(KeyError):
        flags.set_flags({"no_such_flag": 1})


def test_profiler_emits_trace_dir(tmp_path):
    from paddle_tpu import profiler

    x = L.data(name="x", shape=[4], dtype="float32")
    out = L.mean(L.scale(x, 2.0))
    exe = pt.Executor()
    d = str(tmp_path / "trace")
    with profiler.profiler(profile_path=d):
        with profiler.RecordEvent("step"):
            exe.run(feed={"x": np.ones((2, 4), np.float32)}, fetch_list=[out])
    found = [
        os.path.join(r, f) for r, _, fs in os.walk(d) for f in fs
    ]
    assert found, "profiler produced no trace files"


def test_name_scope_tags_ops():
    x = L.data(name="x", shape=[4], dtype="float32")
    with pt.name_scope("encoder"):
        with pt.name_scope("block1"):
            h = L.scale(x, 2.0)
    op = pt.default_main_program().current_block().ops[-1]
    assert op.attrs["op_namescope"] == "encoder/block1"
