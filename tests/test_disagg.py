"""Disaggregated prefill/decode serving (ISSUE 19): the transactional KV
handoff state machine (prepare -> commit happy path, reaper reclaiming an
expired lease under an injected clock, double-commit and commit-after-reap
rejected, abandon/supersede), leases as a first-class holder class in the
pool audit (a forged lease audits DIRTY), role-aware placement (affinity
hashes over the decode universe only; a prefill replica is never a decode
home), and end-to-end byte-exactness of the role-split fleet against the
single-engine oracle — fault-free, under shared-prefix + speculative-decode
arms, and through every disagg fault site (prefill SIGKILL pre-commit,
dropped handoff reaped + replayed, the lease-expiry race at commit, and a
decode SIGKILL holding adopted pages).

Tier-1 keeps the unit tests plus one fault-free exactness pass per fleet
shape and the in-fleet lease-expiry race; the remaining per-site fault
walks are @slow because tests/test_chaos.py's disagg drill already proves
every fault arm byte-exact inside the tier-1 budget."""
import numpy as np
import pytest

from paddle_tpu.resilience.faults import fault_scope
from paddle_tpu.serving import (FleetRouter, PagedKVPool, ServingEngine,
                                decoder_tiny, disagg_fleet_factory)
from paddle_tpu.serving.fleet.handoff import (COMMITTED, PREPARED, REAPED,
                                              HandoffError, HandoffManager,
                                              LeaseExpired)


def _prompts(n: int, seed: int = 7) -> list[list[int]]:
    rng = np.random.default_rng(seed)
    return [rng.integers(1, 97, size=4 + i % 3).tolist() for i in range(n)]


_ORACLE_ENGINE = None


def _oracle(prompts, max_new: int) -> list[list[int]]:
    """Greedy single-engine reference outputs. Greedy decode is a pure
    function of (weights, prompt), so one module-wide engine serves every
    test's oracle wave — building a fresh ServingEngine per test is the
    dominant cost of this file."""
    global _ORACLE_ENGINE
    if _ORACLE_ENGINE is None:
        _ORACLE_ENGINE = ServingEngine(decoder_tiny(), page_size=4,
                                       pool_pages=64, max_inflight=4,
                                       draft_k=0, seed=0)
    eng = _ORACLE_ENGINE
    rids = [eng.submit(p, max_new) for p in prompts]
    eng.run_until_drained()
    out = [eng.result(r) for r in rids]
    eng.prune_finished()
    assert eng.leaked_pages() == 0
    return out


def _fleet(roles, heartbeat_s: float = 30.0, lease_ttl_s=None,
           affinity: bool = False, **factory_kw) -> FleetRouter:
    factory_kw.setdefault("page_size", 4)
    factory_kw.setdefault("pool_pages", 64)
    factory_kw.setdefault("max_inflight", 4)
    factory_kw.setdefault("draft_k", 0)
    factory_kw.setdefault("seed", 0)
    factory = disagg_fleet_factory(decoder_tiny(), **factory_kw)
    return FleetRouter(factory, len(roles), roles=list(roles),
                       heartbeat_s=heartbeat_s, affinity=affinity,
                       lease_ttl_s=lease_ttl_s)


def _serve(fr: FleetRouter, prompts, max_new: int, plan=None):
    fids = [fr.submit(p, max_new) for p in prompts]
    if plan is not None:
        with fault_scope(plan):
            fr.run_until_idle()
    else:
        fr.run_until_idle()
    assert all(fr.state(f) == "finished" for f in fids), \
        {f: fr.state(f) for f in fids}
    return [fr.result(f) for f in fids]


def _assert_clean(fr: FleetRouter) -> None:
    """The zero-leak postcondition every disagg test ends on: no lease
    left PREPARED, a clean shared-pool audit, zero leaked pages on every
    surviving engine, and zero replay divergence."""
    assert fr.handoff.active() == 0
    assert fr.handoff.pool.check_consistency(None) == []
    for rep in fr.replicas:
        if rep.alive:
            assert rep.engine.leaked_pages() == 0, f"replica {rep.rid}"
    assert fr.stats["replay_divergence"] == 0


class _Clock:
    """Injectable monotonic clock for deterministic reaper tests."""

    def __init__(self):
        self.t = 0.0

    def __call__(self) -> float:
        return self.t


# -- leases as a holder class in the pool audit (satellite 1) ----------------

def test_lease_is_first_class_audit_holder_and_forged_lease_is_dirty():
    pool = PagedKVPool(8, 4)
    pages = pool.allocate(2)
    holders = {p: 1 for p in pages}
    assert pool.check_consistency(holders) == []

    # grant: one extra pin per page; the lease pin counts as a holder
    pool.lease_grant("l0", pages)
    assert pool.check_consistency(holders) == []
    assert pool.leased_page_count == 2
    with pytest.raises(ValueError):
        pool.lease_grant("l0", pages)  # ids are single-use

    # mid-handoff: the granting table dropped its pin, NO request maps the
    # pages, only the lease keeps them alive -> still a clean audit
    pool.release(pages)
    assert pool.check_consistency({}) == []
    assert all(p not in pool._free for p in pages)

    # transfer: the record drops, the refcount does NOT — the pin now
    # belongs to the adopter's table (no release/share window)
    moved = pool.lease_transfer("l0")
    assert sorted(moved) == sorted(pages)
    assert pool.leased_page_count == 0
    assert pool.check_consistency({p: 1 for p in pages}) == []
    assert pool.release(pages) == 2  # adopter done -> pages actually free
    assert pool.check_consistency({}) == []

    # a forged lease record — a pin the refcount never backed — is DIRTY
    held = pool.allocate(1)
    pool._leases["forged"] = [held[0], held[0]]
    problems = pool.check_consistency({held[0]: 1})
    assert any("forged or duplicate lease" in p for p in problems)
    del pool._leases["forged"]
    pool.release(held)


def test_lease_release_reclaims_the_orphaned_pin():
    pool = PagedKVPool(8, 4)
    pages = pool.allocate(3)
    pool.lease_grant("l0", pages)
    pool.release(pages)          # granting side is gone
    assert pool.lease_release("l0") == 3  # reap frees for real
    assert pool.check_consistency({}) == []
    assert len(pool._free) == 8
    with pytest.raises(KeyError):
        pool.lease_release("l0")


# -- the handoff state machine (satellite 4, unit level) ---------------------

def _manager(pool, ttl_s=5.0):
    clk = _Clock()
    return HandoffManager(pool, ttl_s=ttl_s, clock=clk), clk


def test_handoff_prepare_then_commit_happy_path():
    pool = PagedKVPool(8, 4)
    pages = pool.allocate(2)
    hm, clk = _manager(pool)
    lid = hm.prepare(7, {"pages": pages})
    assert hm.active() == 1
    assert hm.is_current(hm.leases[lid])
    assert pool.leased_page_count == 2

    clk.t = 1.0  # well inside the TTL
    lease = hm.commit(lid)
    assert lease.state == COMMITTED
    assert lease.fid == 7 and lease.pages == pages
    assert hm.active() == 0
    assert pool.leased_page_count == 0       # pin moved, not released
    assert pool.check_consistency({p: 2 for p in pages}) == []
    assert hm.stats["granted"] == 1 and hm.stats["committed"] == 1
    assert hm.stats["reaped"] == 0 and hm.stats["commit_failed"] == 0


def test_handoff_reaper_reclaims_expired_lease():
    pool = PagedKVPool(8, 4)
    pages = pool.allocate(2)
    hm, clk = _manager(pool, ttl_s=5.0)
    lid = hm.prepare(1, {"pages": pages})
    pool.release(pages)  # prefill side already dropped its pin

    clk.t = 4.0
    assert hm.reap_expired() == []           # not yet
    clk.t = 5.5
    reaped = hm.reap_expired()
    assert [l.lease_id for l in reaped] == [lid]
    assert reaped[0].state == REAPED
    assert len(pool._free) == 8              # the orphaned pin came back
    assert hm.reap_expired() == []           # reaping is exactly-once
    assert hm.stats["reaped"] == 1


def test_handoff_double_commit_rejected():
    pool = PagedKVPool(8, 4)
    pages = pool.allocate(1)
    hm, _clk = _manager(pool)
    lid = hm.prepare(1, {"pages": pages})
    hm.commit(lid)
    with pytest.raises(HandoffError, match="double commit"):
        hm.commit(lid)
    with pytest.raises(HandoffError, match="unknown lease"):
        hm.commit("lease-404")
    assert hm.stats["commit_failed"] == 2
    assert pool.check_consistency({pages[0]: 2}) == []  # pin undisturbed


def test_handoff_commit_after_reap_and_expiry_race_reclaim_exactly_once():
    pool = PagedKVPool(8, 4)
    hm, clk = _manager(pool, ttl_s=5.0)

    # commit-after-reap: the reaper won long ago; the commit must lose
    a = pool.allocate(1)
    lid = hm.prepare(1, {"pages": a})
    pool.release(a)
    clk.t = 6.0
    hm.reap_expired()
    with pytest.raises(LeaseExpired, match="after reap"):
        hm.commit(lid)
    assert len(pool._free) == 8  # reclaimed once, by the reap, not twice

    # expiry discovered AT commit: the commit itself reaps, then rejects
    b = pool.allocate(1)
    lid2 = hm.prepare(2, {"pages": b})
    pool.release(b)
    clk.t = 20.0
    with pytest.raises(LeaseExpired, match="expired before commit"):
        hm.commit(lid2)
    assert hm.leases[lid2].state == REAPED
    assert len(pool._free) == 8
    assert hm.stats["expired_at_commit"] == 1
    assert hm.stats["reaped"] == 2


def test_handoff_abandon_and_supersede():
    pool = PagedKVPool(8, 4)
    hm, _clk = _manager(pool)
    pages = pool.allocate(1)
    lid = hm.prepare(1, {"pages": pages})
    hm.supersede(1)  # the router replayed fid 1 from scratch
    assert not hm.is_current(hm.leases[lid])
    assert hm.abandon(lid)          # reap NOW, TTL notwithstanding
    assert not hm.abandon(lid)      # idempotent: only PREPARED reaps
    assert hm.leases[lid].state == REAPED
    assert pool.leased_page_count == 0

    lid2 = hm.prepare(2, {"pages": pages})
    hm.commit(lid2)
    assert not hm.abandon(lid2)     # committed leases are out of reach


# -- role-aware placement (satellite 2) --------------------------------------

def test_roles_are_validated_at_construction():
    fac = disagg_fleet_factory(decoder_tiny(), page_size=4, pool_pages=64,
                               max_inflight=2, draft_k=0, seed=0)
    with pytest.raises(ValueError, match="at least one"):
        FleetRouter(fac, 2, roles=["prefill", "prefill"])
    with pytest.raises(ValueError, match="roles"):
        FleetRouter(fac, 3, roles=["prefill", "decode"])
    with pytest.raises(ValueError, match="inline"):
        FleetRouter(fac, 2, roles=["prefill", "decode"], pump="threads")
    # a role-split fleet REQUIRES the shared pool: plain per-engine
    # factories cannot hand off tables
    def plain(role="mixed"):
        return ServingEngine(decoder_tiny(), page_size=4, pool_pages=64,
                             max_inflight=2, draft_k=0, seed=0)
    with pytest.raises(ValueError, match="disagg_fleet_factory"):
        FleetRouter(plain, 2, roles=["prefill", "decode"])


# -- end-to-end byte-exactness (tentpole + satellites 2 + 4) -----------------
#
# One 1-prefill + 2-decode fleet carries three waves: the fault-free
# greedy-exactness pass, the resubmission/affinity pass, and the
# lease-expiry race at commit. Sharing the fleet keeps tier-1 wall time
# down without dropping an assertion.

def test_disagg_affinity_greedy_exactness_and_lease_expiry_race():
    prompts = _prompts(5)
    want = _oracle(prompts, 7)
    with _fleet(["prefill", "decode", "decode"], affinity=True) as fr:
        # affinity hashes over the decode universe only
        decode_rids = {r.rid for r in fr.replicas if r.role == "decode"}
        for p in _prompts(12, seed=3):
            assert fr._affinity_rid(p) in decode_rids

        # wave 1+2: fault-free exactness, same home on resubmission
        got = _serve(fr, prompts, 7)
        again = _serve(fr, prompts, 7)
        assert fr.handoff.stats["committed"] >= 1
        assert fr.stats["handoff.replays"] == 0
        assert fr.stats["affinity_hits"] == 10
        assert fr.stats["prefill_dispatches"] == 10

        # the prefill replica only ever prefills + extracts; every decode
        # token was produced by an adopter
        pre = next(r for r in fr.replicas if r.role == "prefill")
        assert pre.engine.stats["handoff_extracts"] >= 1
        assert pre.engine.stats["adopts"] == 0
        assert sum(r.engine.stats["adopts"] for r in fr.replicas
                   if r.role == "decode") >= 1
        _assert_clean(fr)

        # wave 3: the lease expires UNDER the commit; the reaper inside
        # commit reclaims once and the router replays byte-exact
        fr.reset_stats()
        race = _prompts(3)
        want_race = _oracle(race, 5)
        got_race = _serve(fr, race, 5, plan="disagg_lease_expire_race:1")
        assert fr.handoff.stats["expired_at_commit"] >= 1
        assert fr.stats["handoff.replays"] >= 1
        _assert_clean(fr)
    assert got == want and again == want
    assert got_race == want_race


def test_disagg_shared_prefix_and_spec_decode_stay_exact():
    # shared system prompt -> the PREFILL stage absorbs the prefix reuse;
    # draft_k>0 on the decode engines must stay exact under greedy
    base = [5, 6, 7, 8, 9, 10, 11, 12]
    prompts = [base + [t] for t in (20, 30, 40, 50)]
    want = _oracle(prompts, 6)
    with _fleet(["prefill", "decode", "decode"], draft_k=2) as fr:
        got = _serve(fr, prompts, 6)
        _assert_clean(fr)
    assert got == want


@pytest.mark.slow
def test_disagg_prefill_kill_pre_commit_replays_exactly():
    prompts = _prompts(4)
    want = _oracle(prompts, 6)
    with _fleet(["prefill", "prefill", "decode", "decode"],
                heartbeat_s=0.3) as fr:
        warm = [fr.submit([9, 8, 7], 2) for _ in range(2)]
        fr.run_until_idle()
        assert all(fr.state(f) == "finished" for f in warm)
        fr.reset_stats()
        got = _serve(fr, prompts, 6, plan="disagg_prefill_kill:2")
        assert fr.stats["deaths"] >= 1
        _assert_clean(fr)
    assert got == want


@pytest.mark.slow
def test_disagg_dropped_handoff_is_reaped_and_replayed():
    prompts = _prompts(3)
    want = _oracle(prompts, 5)
    with _fleet(["prefill", "decode", "decode"], heartbeat_s=30.0,
                lease_ttl_s=0.2) as fr:
        warm = [fr.submit([9, 8, 7], 2)]
        fr.run_until_idle()
        assert all(fr.state(f) == "finished" for f in warm)
        fr.reset_stats()
        got = _serve(fr, prompts, 5, plan="disagg_handoff_drop:1")
        assert fr.stats["handoff.dropped"] >= 1
        assert fr.handoff.stats["reaped"] >= 1
        assert fr.stats["handoff.replays"] >= 1
        _assert_clean(fr)
    assert got == want


@pytest.mark.slow
def test_disagg_decode_kill_holding_adopted_pages_dedups_and_forfeits():
    prompts = _prompts(4)
    want = _oracle(prompts, 10)
    with _fleet(["prefill", "decode", "decode"], heartbeat_s=0.3) as fr:
        warm = [fr.submit([9, 8, 7], 2)]
        fr.run_until_idle()
        assert all(fr.state(f) == "finished" for f in warm)
        fr.reset_stats()
        fids = [fr.submit(p, 10) for p in prompts]
        victim = None
        for _ in range(3000):
            fr.step()
            victim = next(
                (r for r in fr.replicas
                 if r.alive and r.role == "decode"
                 and r.engine.stats["adopts"] > 0
                 and any(q.state == "running"
                         for q in r.engine.requests.values())), None)
            if victim is not None:
                break
        assert victim is not None, "no decode replica ever held a request"
        fr.kill(victim.rid)
        fr.run_until_idle()
        assert all(fr.state(f) == "finished" for f in fids), \
            {f: fr.state(f) for f in fids}
        got = [fr.result(f) for f in fids]
        assert fr.stats["deaths"] == 1
        _assert_clean(fr)
    assert got == want
