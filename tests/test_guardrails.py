"""Numeric guardrails (resilience/guardrails.py, ISSUE 4): in-graph health
sentinel + branchless bad-step skip, StepGuard budget/rewind/LR-backoff
ladder, eager blame replay, reader corrupt-record skipping, fleet hygiene
(non-finite send drops + pserver renormalization), and the numeric fault
sites/chaos drill.
"""
import numpy as np
import pytest

import jax

import paddle_tpu as pt
from paddle_tpu import flags
from paddle_tpu import layers as L
from paddle_tpu.resilience import (
    GUARD_HEALTH_NAME,
    CheckpointManager,
    GuardRewind,
    StepGuard,
    fault_scope,
)
from paddle_tpu.resilience.guardrails import H_BAD, H_GNORM, H_NONFINITE


@pytest.fixture()
def restore_flags():
    snap = pt.flags.all_flags()
    yield
    pt.flags.set_flags(snap)


def _sgd_program(lr=0.1):
    x = L.data(name="x", shape=[4], dtype="float32")
    y = L.data(name="y", shape=[1], dtype="float32")
    loss = L.mean(L.square_error_cost(L.fc(x, size=1), y))
    pt.optimizer.SGD(lr).minimize(loss)
    return loss


def _feed(seed=0):
    rng = np.random.default_rng(seed)
    return {"x": rng.standard_normal((8, 4)).astype(np.float32),
            "y": np.ones((8, 1), np.float32)}


def _nan_feed(seed=0):
    f = _feed(seed)
    bx = f["x"].copy()
    bx[0, 0] = np.nan
    f["x"] = bx
    return f


# -- in-graph sentinel: skip semantics ----------------------------------------

def test_nan_step_skipped_bit_exact(restore_flags):
    """The acceptance contract: an injected NaN leaves parameters BIT
    identical (SGD sees zeroed grads), health records the verdict, and the
    next healthy step trains normally — no interpreter fallback anywhere."""
    flags.set_flags({"guard_numerics": True})
    loss = _sgd_program()
    main, startup = pt.default_main_program(), pt.default_startup_program()
    assert main.global_block.var(GUARD_HEALTH_NAME) is not None
    exe = pt.Executor()
    scope = pt.global_scope()
    exe.run(startup)
    w = main.all_parameters()[0].name

    exe.run(main, feed=_feed(), fetch_list=[loss])
    w1 = np.asarray(scope.find_var(w)).copy()
    h1 = np.asarray(scope.find_var(GUARD_HEALTH_NAME))
    assert h1[H_BAD] == 0 and np.isfinite(h1[H_GNORM])

    exe.run(main, feed=_nan_feed(), fetch_list=[loss])
    w2 = np.asarray(scope.find_var(w))
    h2 = np.asarray(scope.find_var(GUARD_HEALTH_NAME))
    assert h2[H_NONFINITE] == 1 and h2[H_BAD] == 1
    np.testing.assert_array_equal(w1, w2)  # bit-exact skip

    exe.run(main, feed=_feed(1), fetch_list=[loss])
    assert not np.array_equal(w2, np.asarray(scope.find_var(w)))
    assert np.isfinite(np.asarray(scope.find_var(w))).all()


def test_spike_step_skipped_by_ema_gate(restore_flags):
    flags.set_flags({"guard_numerics": True, "guard_spike_factor": 10.0})
    loss = _sgd_program(lr=0.01)
    main, startup = pt.default_main_program(), pt.default_startup_program()
    exe = pt.Executor()
    scope = pt.global_scope()
    exe.run(startup)
    w = main.all_parameters()[0].name
    for i in range(3):  # establish the loss EMA
        exe.run(main, feed=_feed(i), fetch_list=[loss])
    w_before = np.asarray(scope.find_var(w)).copy()
    spike = _feed(0)
    spike["x"] = spike["x"] * 1e4
    exe.run(main, feed=spike, fetch_list=[loss])
    h = np.asarray(scope.find_var(GUARD_HEALTH_NAME))
    assert h[H_BAD] == 1 and h[H_NONFINITE] == 0  # finite, but a spike
    np.testing.assert_array_equal(w_before, np.asarray(scope.find_var(w)))


def test_guard_off_appends_nothing(restore_flags):
    flags.set_flags({"guard_numerics": False})
    _sgd_program()
    main = pt.default_main_program()
    assert GUARD_HEALTH_NAME not in main.global_block.vars
    assert not any(op.type == "health_sentinel"
                   for op in main.global_block.ops)


# -- StepGuard: budget, rewind, blame -----------------------------------------

def test_budget_exhausted_rewinds_and_attributes_blame(tmp_path,
                                                       restore_flags):
    flags.set_flags({"guard_numerics": True, "max_inflight_steps": 1})
    loss = _sgd_program()
    main, startup = pt.default_main_program(), pt.default_startup_program()
    exe = pt.Executor()
    scope = pt.global_scope()
    exe.run(startup)
    mgr = CheckpointManager(str(tmp_path / "ckpt"), main_program=main,
                            scope=scope)
    exe.run(main, feed=_feed(), fetch_list=[loss])
    mgr.save(0, executor=exe)
    w = main.all_parameters()[0].name
    w_ckpt = np.asarray(scope.find_var(w)).copy()
    lr_name = main._guard_lr_name
    lr0 = float(np.asarray(scope.find_var(lr_name)).reshape(-1)[0])

    guard = StepGuard(mgr, budget=1, program=main, scope=scope)
    exe.set_step_guard(guard)
    report = None
    for _ in range(4):
        try:
            exe.run_async(main, feed=_nan_feed(), fetch_list=[loss])
        except GuardRewind as gr:
            report = guard.rewind(exe, gr)
            break
    exe.wait()
    assert report is not None, "consecutive bad steps never tripped the guard"
    # replay reproduced the fault eagerly with op attribution
    assert report["op_type"] is not None
    assert "nan/inf" in report["detail"]
    assert report["var"] is not None
    # state rewound to the checkpoint, LR backed off
    np.testing.assert_array_equal(w_ckpt, np.asarray(scope.find_var(w)))
    lr1 = float(np.asarray(scope.find_var(lr_name)).reshape(-1)[0])
    assert lr1 == pytest.approx(lr0 * 0.5)
    # forensics: skips then a rewind, durably recorded
    actions = [e["action"] for e in mgr.guard_events()]
    assert actions.count("skip") == 2 and actions[-1] == "rewind"
    mgr.save(1, executor=exe)
    assert len(mgr.read_manifest(1)["guard_events"]) == len(actions)
    assert mgr.latest_step() == 1


def test_guard_events_survive_restart(tmp_path):
    root = str(tmp_path / "ckpt")
    mgr = CheckpointManager(root)
    mgr.record_guard_event(7, "nonfinite", "skip", {"loss": float("nan")})
    fresh = CheckpointManager(root)  # a restarted process
    evts = fresh.guard_events()
    assert len(evts) == 1 and evts[0]["step"] == 7
    assert fresh.latest_step() is None  # events never masquerade as steps


# -- AMP composition ----------------------------------------------------------

def test_amp_dynamic_loss_scaling_composes(restore_flags):
    """AMP's own found_inf machinery must keep working under the guard: the
    scale still decrements on overflow, and the sentinel sees AMP's verdict
    (health reports the bad step) without double-updating anything."""
    from paddle_tpu.contrib import mixed_precision as amp

    flags.set_flags({"guard_numerics": True})
    x = L.data(name="x", shape=[8], dtype="float32")
    y = L.data(name="y", shape=[1], dtype="float32")
    loss = L.mean(L.square_error_cost(L.fc(x, size=1), y))
    opt = amp.decorate(pt.optimizer.SGD(0.01), init_loss_scaling=2.0 ** 15,
                       use_dynamic_loss_scaling=True,
                       decr_every_n_nan_or_inf=1)
    opt.minimize(loss)
    main, startup = pt.default_main_program(), pt.default_startup_program()
    exe = pt.Executor()
    scope = pt.global_scope()
    exe.run(startup)
    rng = np.random.default_rng(1)
    xv = rng.standard_normal((8, 8)).astype(np.float32)
    yv = np.ones((8, 1), np.float32)

    exe.run(main, feed={"x": xv, "y": yv}, fetch_list=[loss])
    s1 = float(np.asarray(scope.find_var("@LOSS_SCALING@")).reshape(-1)[0])
    exe.run(main, feed={"x": np.full((8, 8), 1e30, np.float32), "y": yv},
            fetch_list=[loss])
    s2 = float(np.asarray(scope.find_var("@LOSS_SCALING@")).reshape(-1)[0])
    assert s2 < s1  # AMP state machine untouched by the sentinel
    h = np.asarray(scope.find_var(GUARD_HEALTH_NAME))
    assert h[H_BAD] == 1  # and the sentinel heard AMP's verdict
    (lv,) = exe.run(main, feed={"x": xv, "y": yv}, fetch_list=[loss])
    assert np.isfinite(float(lv))


# -- FLAGS_check_nan_inf compiled-path fix ------------------------------------

def test_check_nan_inf_keeps_jit_and_warns_once(restore_flags):
    loss = _sgd_program()
    main, startup = pt.default_main_program(), pt.default_startup_program()
    exe = pt.Executor()
    exe.run(startup)
    flags.set_flags({"check_nan_inf": True})
    import paddle_tpu.executor as executor_mod
    executor_mod._nan_inf_jit_warned = False
    with pytest.warns(UserWarning, match="health sentinel|guard_numerics"):
        (lv,) = exe.run(main, feed=_nan_feed(), fetch_list=[loss])
    assert not np.isfinite(float(lv))  # jit path kept: NaN flows, no raise
    # eager mode still gives per-op attribution (the blame-replay contract)
    with jax.disable_jit():
        with pytest.raises(pt.OpError, match="nan/inf"):
            exe.run(main, feed=_nan_feed(), fetch_list=[loss])


# -- numeric fault sites ------------------------------------------------------

def test_numeric_fault_sites_poison_deterministically(restore_flags):
    flags.set_flags({"guard_numerics": True})
    loss = _sgd_program()
    main, startup = pt.default_main_program(), pt.default_startup_program()
    exe = pt.Executor()
    scope = pt.global_scope()
    exe.run(startup)
    with fault_scope("numeric_nan:2") as plan:
        exe.run(main, feed=_feed(), fetch_list=[loss])
        assert np.asarray(scope.find_var(GUARD_HEALTH_NAME))[H_BAD] == 0
        exe.run(main, feed=_feed(), fetch_list=[loss])  # hit 2: poisoned
        h = np.asarray(scope.find_var(GUARD_HEALTH_NAME))
        assert h[H_NONFINITE] == 1  # the planted NaN reached the sentinel
    assert ("numeric_nan", 2) in plan.stats()["fired"]
    assert np.isfinite(
        np.asarray(scope.find_var(main.all_parameters()[0].name))).all()


@pytest.mark.chaos
def test_numeric_chaos_drill(restore_flags):
    """The kill-free gate.py --chaos drill: seeded NaN + spike faults under
    the guard; epoch completes finite with both skips recorded."""
    import os
    import sys
    sys.path.insert(0, os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "tools"))
    import chaos

    out = chaos.run_numeric_smoke(steps=6)
    assert out["rewinds"] == 0 and out["skips"] >= 2


# -- reader robustness --------------------------------------------------------

def test_datafeeder_skips_corrupt_sample(restore_flags):
    from paddle_tpu import profiler

    x = L.data(name="x", shape=[3], dtype="float32")
    y = L.data(name="y", shape=[1], dtype="float32")
    good = ([1.0, 2.0, 3.0], [1.0])
    corrupt = (["not", "a", "float"], [0.0])
    feeder = pt.DataFeeder([x, y])
    with pytest.raises(ValueError):
        feeder.feed([good, corrupt])  # default: corrupt record raises

    flags.set_flags({"feed_skip_corrupt": True})
    profiler.stage_counters(reset=True)
    out = feeder.feed([good, corrupt, good])
    assert out["x"].shape == (2, 3)  # the corrupt sample is gone
    counters = profiler.stage_counters()
    assert counters["feed.skip_corrupt"]["events"] == 1
    with pytest.raises(ValueError, match="every sample"):
        feeder.feed([corrupt])  # an all-corrupt batch still surfaces


def test_device_loader_skips_corrupt_batch(restore_flags):
    from paddle_tpu import profiler
    from paddle_tpu.pipeline import DeviceLoader
    from paddle_tpu.pipeline.device_loader import default_placement

    x = L.data(name="x", shape=[2], dtype="float32")
    feeds = [{"x": np.ones((2, 2), np.float32)},
             {"x": np.array([["bad", "row"]], dtype=object)},
             {"x": np.full((2, 2), 2.0, np.float32)}]
    flags.set_flags({"feed_skip_corrupt": True})
    profiler.stage_counters(reset=True)
    loader = DeviceLoader(lambda: iter(feeds), depth=2,
                          placement=default_placement([x]))
    seen = [np.asarray(f["x"])[0, 0] for f in loader]
    assert seen == [1.0, 2.0]
    assert profiler.stage_counters()["feed.skip_corrupt"]["events"] == 1


def test_train_from_dataset_survives_guard(tmp_path, restore_flags):
    """End-to-end: numeric_nan injected mid-epoch through the async
    train_from_dataset path — the epoch completes, state stays finite, the
    guard logs the skip."""
    flags.set_flags({"guard_numerics": True, "max_inflight_steps": 2,
                     "device_prefetch_depth": 2})
    x = L.data(name="x", shape=[4], dtype="float32")
    y = L.data(name="y", shape=[1], dtype="float32")
    loss = L.mean(L.square_error_cost(L.fc(x, size=1), y))
    pt.optimizer.SGD(0.1).minimize(loss)
    main, startup = pt.default_main_program(), pt.default_startup_program()
    rng = np.random.default_rng(0)
    path = tmp_path / "part-0"
    with open(path, "w") as f:
        for _ in range(24):  # 6 batches of 4
            vals = " ".join(f"{v:.4f}" for v in rng.random(4))
            f.write(f"4 {vals} 1 {rng.integers(0, 2)}\n")
    ds = pt.DatasetFactory().create_dataset("QueueDataset")
    ds.set_batch_size(4)
    ds.set_use_var([x, y])
    ds.set_filelist([str(path)])
    exe = pt.Executor()
    scope = pt.global_scope()
    exe.run(startup)
    guard = StepGuard(CheckpointManager(str(tmp_path / "ckpt"),
                                        main_program=main, scope=scope),
                      program=main, scope=scope)
    with fault_scope("numeric_nan:3"):
        exe.train_from_dataset(main, ds, print_period=10 ** 9, guard=guard)
    assert guard.skips == 1 and guard.rewinds == 0
    w = np.asarray(scope.find_var(main.all_parameters()[0].name))
    assert np.isfinite(w).all()


# -- fleet hygiene ------------------------------------------------------------

class _RecordingClient:
    trainer_id = 0

    def __init__(self):
        self.sent = []

    def send_var(self, ep, name, value):
        self.sent.append((ep, name))


def test_sync_send_drops_nonfinite(restore_flags):
    from paddle_tpu.distributed.ps_rpc import send_sections

    client = _RecordingClient()
    bad = np.array([1.0, np.nan], np.float32)
    good = np.array([1.0, 2.0], np.float32)
    flags.set_flags({"guard_numerics": True})
    send_sections(client, "w@GRAD", bad, ["ep0"], [])
    assert client.sent == []  # poison never reached the wire
    send_sections(client, "w@GRAD", good, ["ep0"], [])
    assert client.sent == [("ep0", "w@GRAD")]
    # hygiene is opt-in with the guard: off means ship as before
    flags.set_flags({"guard_numerics": False})
    send_sections(client, "w@GRAD", bad, ["ep0"], [])
    assert len(client.sent) == 2


def test_communicator_drops_nonfinite_merged_send(restore_flags):
    from paddle_tpu.distributed.communicator import Communicator

    flags.set_flags({"guard_numerics": True})
    client = _RecordingClient()
    comm = Communicator(
        {"w@GRAD": {"epmap": ["ep0"], "sections": []}}, {}, client,
        pt.global_scope())
    ctx = comm.send_ctx["w@GRAD"]
    comm._send_merged("w@GRAD", ctx,
                      [np.array([1.0, np.nan], np.float32),
                       np.array([1.0, 1.0], np.float32)])
    assert client.sent == []  # one poisoned grad poisons the merge: dropped
    comm._send_merged("w@GRAD", ctx, [np.array([1.0, 1.0], np.float32)])
    assert client.sent == [("ep0", "w@GRAD")]


def test_pserver_round_renormalizes_to_posting_trainers(restore_flags):
    """The survivors' round stays a true mean when a trainer dropped its
    poisoned dense send: scale is 1/len(posted), not 1/n_active (sparse
    keeps 1/n_active — partial posting is legitimate there)."""
    from paddle_tpu.distributed.ps_rpc import PServerRuntime

    ps = PServerRuntime("127.0.0.1:0", n_trainers=2, sync_mode=True,
                        blocks=[], scope=pt.Scope(), executor=None)
    applied = []
    ps._apply_update = lambda name, vals, scale, trainer=None: applied.append(
        (name, len(vals), scale))
    ps._grad_buf = {
        "dense@GRAD": {1: ("dense", np.ones(2, np.float32))},  # trainer 0
                                                               # dropped
        "table@GRAD": {0: ("sparse", np.zeros(1, np.int64),
                           np.ones((1, 2), np.float32), 10)},
    }
    ps._run_round()
    by_name = {n: (k, s) for n, k, s in applied}
    assert by_name["dense@GRAD"] == (1, 1.0)   # renormalized to survivors
    assert by_name["table@GRAD"] == (1, 0.5)   # sparse: still 1/n_active
