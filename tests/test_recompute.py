"""RecomputeOptimizer: segment rewrite + jax.checkpoint remat backward.
Oracle: identical loss trajectory to plain training (the rewrite must be
semantics-preserving); structure checks on the rewritten program."""
import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import layers as L


def _build(use_rc, dropout=0.0):
    main, startup = pt.Program(), pt.Program()
    main.random_seed = startup.random_seed = 11
    with pt.program_guard(main, startup):
        with pt.unique_name.guard():
            x = L.data(name="x", shape=[16], dtype="float32")
            y = L.data(name="y", shape=[1], dtype="float32")
            h1 = L.fc(x, size=32, act="relu", name="h1")
            if dropout:
                h1 = L.dropout(h1, dropout_prob=dropout)
            h2 = L.fc(h1, size=32, act="relu", name="h2")
            h3 = L.fc(h2, size=32, act="relu", name="h3")
            pred = L.fc(h3, size=1, name="p")
            loss = L.mean(L.square_error_cost(pred, y))
            if use_rc:
                opt = pt.optimizer.RecomputeOptimizer(pt.optimizer.Adam(0.01))
                opt._set_checkpoints([h1, h2, h3])
            else:
                opt = pt.optimizer.Adam(0.01)
            opt.minimize(loss)
    return main, startup, loss


def test_recompute_matches_plain_training():
    rng = np.random.default_rng(0)
    xs = rng.standard_normal((6, 32, 16)).astype(np.float32)
    w = rng.standard_normal((16, 1)).astype(np.float32)
    results = []
    for use_rc in (False, True):
        main, startup, loss = _build(use_rc)
        exe = pt.Executor()
        with pt.scope_guard(pt.Scope()):
            exe.run(startup)
            losses = []
            for i in range(6):
                (lv,) = exe.run(main, feed={"x": xs[i], "y": xs[i] @ w},
                                fetch_list=[loss])
                losses.append(float(lv))
        results.append(losses)
    np.testing.assert_allclose(results[0], results[1], rtol=1e-5)


def test_recompute_program_structure():
    main, _, _ = _build(True)
    blk = main.global_block
    n_rec = sum(op.type == "recompute" for op in blk.ops)
    assert n_rec >= 2, [op.type for op in blk.ops]
    # segments moved out of block 0: no fc mul ops before the first
    # recompute's position remain from wrapped segments
    rec = next(op for op in blk.ops if op.type == "recompute")
    sub = main.blocks[rec.attrs["sub_block"]]
    assert any(op.type == "mul" for op in sub.ops)
    # grad side: a recompute_grad op consumes the segment output cotangents
    assert any(op.type == "recompute_grad" for op in blk.ops)


def test_recompute_rejects_rng_ops_in_segment():
    with pytest.raises(ValueError, match="RNG"):
        _build(True, dropout=0.5)


def test_recompute_transformer_layer_checkpoints():
    """The model-zoo hook: per-layer outputs feed _set_checkpoints and the
    rewritten BERT still trains with finite decreasing loss."""
    from paddle_tpu.models import transformer

    cfg = transformer.bert_tiny(use_tp=False)
    main, startup = pt.Program(), pt.Program()
    main.random_seed = startup.random_seed = 3
    with pt.program_guard(main, startup):
        with pt.unique_name.guard():
            avg_loss, _ = transformer.bert_pretrain(cfg, seq_len=16)
            opt = pt.optimizer.RecomputeOptimizer(pt.optimizer.Adam(1e-3))
            opt._set_checkpoints(list(transformer.last_layer_outputs))
            opt.minimize(avg_loss)
    assert sum(op.type == "recompute"
               for op in main.global_block.ops) == cfg.num_layers
    from __graft_entry__ import _example_feed

    feed = _example_feed(cfg, 4, 16)
    exe = pt.Executor()
    with pt.scope_guard(pt.Scope()):
        exe.run(startup)
        first = last = None
        for _ in range(8):
            (lv,) = exe.run(main, feed=feed, fetch_list=[avg_loss])
            if first is None:
                first = float(lv)
            last = float(lv)
        assert np.isfinite(last) and last < first
