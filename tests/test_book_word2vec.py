"""Word2vec book test (reference tests/book/test_word2vec.py): n-gram model
over imikolov data — full-softmax variant from the model zoo, plus the
large-vocab NCE and hsigmoid variants the reference builds this model to
motivate."""
import numpy as np

import paddle_tpu as pt
from paddle_tpu import layers as L
from paddle_tpu.dataset import imikolov
from paddle_tpu.models import word2vec


def _batches(word_idx, n, batch, count):
    gen = imikolov.train(word_idx, n)()
    grams = []
    for g in gen:
        grams.append(g)
        if len(grams) >= batch * count:
            break
    arr = np.array(grams, np.int64)
    for i in range(0, len(arr) - batch + 1, batch):
        chunk = arr[i:i + batch]
        yield {**{f"w{j}": chunk[:, j:j + 1] for j in range(n - 1)},
               "next_word": chunk[:, -1:]}


def test_word2vec_book_full_softmax():
    word_idx = imikolov.build_dict()
    V = len(word_idx)
    avg_loss, predict, feeds = word2vec.word2vec(dict_size=V, embed_dim=16,
                                                 hidden_size=64, context=4)
    pt.optimizer.Adam(0.01).minimize(avg_loss)
    exe = pt.Executor()
    exe.run(pt.default_startup_program())
    fixed = list(_batches(word_idx, 5, 64, 20))
    epoch_means = []
    for _ in range(2):  # same batches twice: epoch means are comparable
        losses = [float(exe.run(pt.default_main_program(), feed=f,
                                fetch_list=[avg_loss])[0])
                  for f in fixed]
        epoch_means.append(np.mean(losses))
    assert np.isfinite(epoch_means[1])
    assert epoch_means[1] < epoch_means[0], epoch_means


def test_word2vec_nce_variant():
    """The same n-gram tower trained with NCE instead of full softmax —
    the reference nce/hsigmoid docs' motivating setup."""
    word_idx = imikolov.build_dict()
    V = len(word_idx)
    ctx = 4
    embeds = []
    for i in range(ctx):
        w = L.data(name=f"w{i}", shape=[1], dtype="int64")
        embeds.append(L.embedding(
            w, size=[V, 16], param_attr=pt.ParamAttr(name="nce_shared_w")))
    concat = L.concat([L.reshape(e, [-1, 16]) for e in embeds], axis=1)
    hidden = L.fc(concat, size=64, act="sigmoid")
    nw = L.data(name="next_word", shape=[1], dtype="int64")
    nce_cost = L.mean(L.nce(hidden, nw, num_total_classes=V,
                            num_neg_samples=16, sampler="log_uniform"))
    hs_cost = L.mean(L.hsigmoid(hidden, nw, num_classes=V))
    total = L.elementwise_add(nce_cost, hs_cost)
    pt.optimizer.Adam(0.01).minimize(total)
    exe = pt.Executor()
    exe.run(pt.default_startup_program())
    fixed = list(_batches(word_idx, 5, 64, 15))
    epoch_means = []
    for _ in range(2):
        losses = [float(exe.run(pt.default_main_program(), feed=f,
                                fetch_list=[total])[0])
                  for f in fixed]
        epoch_means.append(np.mean(losses))
    assert np.isfinite(epoch_means[1])
    assert epoch_means[1] < epoch_means[0], epoch_means
