"""End-to-end pserver training on localhost subprocesses (reference
unittests/test_dist_base.py:442 TestDistBase._run_cluster): 2 trainers over
batch shards + 2 pservers (row-sliced fc weight) must reproduce the
single-process full-batch parameter trajectory."""
import os
import socket
import subprocess
import sys

import numpy as np
import pytest

_DIR = os.path.dirname(os.path.abspath(__file__))
_REPO = os.path.dirname(_DIR)
_SCRIPT = os.path.join(_DIR, "dist_simple.py")


def _free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _env():
    env = dict(os.environ)
    env["PYTHONPATH"] = _REPO + os.pathsep + env.get("PYTHONPATH", "")
    return env


def _spawn(args):
    return subprocess.Popen(
        [sys.executable, _SCRIPT, *args], env=_env(),
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT)


def test_pserver_cluster_matches_local(tmp_path):
    eps = f"127.0.0.1:{_free_port()},127.0.0.1:{_free_port()}"
    ep_list = eps.split(",")

    local_out = str(tmp_path / "local.npz")
    p = _spawn(["local", eps, "0", "2", local_out])
    out, _ = p.communicate(timeout=240)
    assert p.returncode == 0, out.decode()[-2000:]

    pservers = [
        _spawn(["pserver", eps, "0", "2", str(tmp_path / f"ps{i}.npz"), ep])
        for i, ep in enumerate(ep_list)
    ]
    trainers = [
        _spawn(["trainer", eps, str(i), "2", str(tmp_path / f"tr{i}.npz")])
        for i in range(2)
    ]
    try:
        for i, t in enumerate(trainers):
            out, _ = t.communicate(timeout=240)
            assert t.returncode == 0, f"trainer {i}: {out.decode()[-3000:]}"
        for i, ps in enumerate(pservers):
            out, _ = ps.communicate(timeout=60)
            assert ps.returncode == 0, f"pserver {i}: {out.decode()[-3000:]}"
    finally:
        for pr in trainers + pservers:
            if pr.poll() is None:
                pr.kill()

    local = np.load(local_out)
    tr0 = np.load(str(tmp_path / "tr0.npz"))
    tr1 = np.load(str(tmp_path / "tr1.npz"))
    for k in local.files:
        if k == "__last_loss__":
            continue
        np.testing.assert_allclose(
            local[k], tr0[k], rtol=1e-4, atol=1e-5,
            err_msg=f"trainer0 param {k} diverged from local")
        np.testing.assert_allclose(
            tr0[k], tr1[k], rtol=1e-6, atol=1e-7,
            err_msg=f"trainers disagree on param {k}")
    assert float(local["__last_loss__"]) < 10.0


def test_fleet_pserver_mode_matches_local(tmp_path):
    """The fleet pserver lifecycle (init/distributed_optimizer/init_server/
    run_server/init_worker/stop_worker) reproduces the plain-transpiler
    cluster result (which itself matches local training, asserted above)."""
    script = os.path.join(_DIR, "dist_fleet_ps.py")
    eps = f"127.0.0.1:{_free_port()},127.0.0.1:{_free_port()}"
    ep_list = eps.split(",")

    local_out = str(tmp_path / "local.npz")
    p = subprocess.Popen(
        [sys.executable, _SCRIPT, "local", eps, "0", "2", local_out],
        env=_env(), stdout=subprocess.PIPE, stderr=subprocess.STDOUT)
    out, _ = p.communicate(timeout=240)
    assert p.returncode == 0, out.decode()[-2000:]

    def spawn(args):
        return subprocess.Popen(
            [sys.executable, script, *args], env=_env(),
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT)

    pservers = [spawn(["pserver", eps, "0", "2",
                       str(tmp_path / f"ps{i}.npz"), str(i)])
                for i in range(len(ep_list))]
    trainers = [spawn(["trainer", eps, str(i), "2",
                       str(tmp_path / f"tr{i}.npz")]) for i in range(2)]
    try:
        for i, t in enumerate(trainers):
            out, _ = t.communicate(timeout=240)
            assert t.returncode == 0, f"trainer {i}: {out.decode()[-3000:]}"
        for i, ps in enumerate(pservers):
            out, _ = ps.communicate(timeout=60)
            assert ps.returncode == 0, f"pserver {i}: {out.decode()[-3000:]}"
    finally:
        for pr in trainers + pservers:
            if pr.poll() is None:
                pr.kill()

    local = np.load(local_out)
    tr0 = np.load(str(tmp_path / "tr0.npz"))
    for k in local.files:
        if k == "__last_loss__":
            continue
        np.testing.assert_allclose(
            local[k], tr0[k], rtol=1e-4, atol=1e-5,
            err_msg=f"fleet-ps param {k} diverged from local")


def test_checkpoint_notify_saves_pserver_slices(tmp_path):
    """checkpoint_notify (reference checkpoint_notify_op / the pserver-side
    save in listen_and_serv): trainer asks, the SERVER persists its slices —
    nothing travels back."""
    import threading

    from paddle_tpu.distributed.ps_rpc import PSClient, PServerRuntime
    from paddle_tpu.executor import Executor, Scope

    ep = f"127.0.0.1:{_free_port()}"
    scope = Scope()
    scope.set_var("w.block0", np.arange(12, dtype=np.float32).reshape(3, 4))
    srv = PServerRuntime(ep, n_trainers=1, sync_mode=True, blocks=[],
                         scope=scope, executor=Executor())
    t = threading.Thread(target=srv.serve, daemon=True)
    t.start()

    client = PSClient([ep], trainer_id=0)
    ckdir = str(tmp_path / "ps_ck")
    client.checkpoint_notify(ckdir)
    client.send_complete()
    client.close()
    t.join(timeout=10)

    files = os.listdir(ckdir)
    assert len(files) == 1 and files[0].startswith("pserver-")
    data = np.load(os.path.join(ckdir, files[0]))
    np.testing.assert_allclose(
        data["w.block0"], np.arange(12, dtype=np.float32).reshape(3, 4))


def test_pserver_checkpoint_resume_roundtrip(tmp_path):
    """init_server(model_dir) restores what checkpoint_notify saved."""
    import threading

    from paddle_tpu.distributed.ps_rpc import PSClient, PServerRuntime
    from paddle_tpu.executor import Executor, Scope

    ep = f"127.0.0.1:{_free_port()}"
    scope = Scope()
    val = np.arange(8, dtype=np.float32).reshape(2, 4) * 3
    scope.set_var("p.block0", val)
    srv = PServerRuntime(ep, 1, True, [], scope, Executor())
    t = threading.Thread(target=srv.serve, daemon=True)
    t.start()
    client = PSClient([ep], 0)
    ckdir = str(tmp_path / "ck")
    client.checkpoint_notify(ckdir)
    client.send_complete()
    client.close()
    t.join(timeout=10)

    # resume: load the slice back the way fleet.init_server does
    safe_ep = ep.replace(":", "_")
    data = np.load(os.path.join(ckdir, f"pserver-{safe_ep}.npz"))
    np.testing.assert_allclose(data["p.block0"], val)


def test_fleet_async_mode_converges(tmp_path):
    """sync_mode=False: the Communicator path — per-grad send queues with
    merge-before-send, no barriers, an independent recv thread pulling
    params (reference communicator.h:162). Async has no exact single-process
    oracle (server state keeps moving while trainers stop at different
    times), so the contract is convergence: every trainer's loss-trajectory
    tail must fall by >10x and its params must have moved off init."""
    script = os.path.join(_DIR, "dist_fleet_ps.py")
    eps = f"127.0.0.1:{_free_port()},127.0.0.1:{_free_port()}"
    ep_list = eps.split(",")

    def spawn(args):
        env = _env()
        # recv quickly so the loss trajectory reflects server progress
        env["FLAGS_communicator_min_send_grad_num_before_recv"] = "2"
        return subprocess.Popen(
            [sys.executable, script, *args], env=env,
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT)

    pservers = [spawn(["pserver", eps, "0", "2",
                       str(tmp_path / f"ps{i}.npz"), str(i), "async"])
                for i in range(len(ep_list))]
    trainers = [spawn(["trainer", eps, str(i), "2",
                       str(tmp_path / f"tr{i}.npz"), "0", "async"])
                for i in range(2)]
    try:
        for i, t in enumerate(trainers):
            out, _ = t.communicate(timeout=240)
            assert t.returncode == 0, f"trainer {i}: {out.decode()[-3000:]}"
        for i, ps in enumerate(pservers):
            out, _ = ps.communicate(timeout=60)
            assert ps.returncode == 0, f"pserver {i}: {out.decode()[-3000:]}"
    finally:
        for pr in trainers + pservers:
            if pr.poll() is None:
                pr.kill()

    for i in range(2):
        tr = np.load(str(tmp_path / f"tr{i}.npz"))
        losses = tr["__losses__"]
        tail = float(np.mean(losses[-5:]))  # async oscillates; judge the tail
        assert tail < losses[0] / 10, (
            f"trainer {i} did not converge: {losses[0]} -> tail {tail} "
            f"({[round(float(v), 2) for v in losses[-5:]]})")


def test_distributed_lookup_table_matches_local_dense(tmp_path):
    """embedding(is_distributed=True): the table is row-sharded over the
    pservers, trainers prefetch only the batch's rows (the full table never
    enters a trainer scope — asserted inside the worker), SelectedRows grads
    route per slice, and the sync trajectory equals single-process DENSE
    training (reference distribute_transpiler.py:1503 distributed lookup
    table + parameter_prefetch.cc)."""
    script = os.path.join(_DIR, "dist_lookup.py")
    eps = f"127.0.0.1:{_free_port()},127.0.0.1:{_free_port()}"
    ep_list = eps.split(",")

    def spawn(args):
        return subprocess.Popen(
            [sys.executable, script, *args], env=_env(),
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT)

    local_out = str(tmp_path / "local.npz")
    p = spawn(["local", eps, "0", "2", local_out])
    out, _ = p.communicate(timeout=240)
    assert p.returncode == 0, out.decode()[-2000:]

    pservers = [spawn(["pserver", eps, "0", "2",
                       str(tmp_path / f"ps{i}.npz"), ep])
                for i, ep in enumerate(ep_list)]
    trainers = [spawn(["trainer", eps, str(i), "2",
                       str(tmp_path / f"tr{i}.npz")]) for i in range(2)]
    try:
        for i, t in enumerate(trainers):
            out, _ = t.communicate(timeout=240)
            assert t.returncode == 0, f"trainer {i}: {out.decode()[-3000:]}"
        for i, ps in enumerate(pservers):
            out, _ = ps.communicate(timeout=60)
            assert ps.returncode == 0, f"pserver {i}: {out.decode()[-3000:]}"
    finally:
        for pr in trainers + pservers:
            if pr.poll() is None:
                pr.kill()

    local = np.load(local_out)
    tr0 = np.load(str(tmp_path / "tr0.npz"))
    for k in local.files:
        if k == "__last_loss__":
            continue
        np.testing.assert_allclose(
            local[k], tr0[k], rtol=1e-4, atol=1e-5,
            err_msg=f"dist-lookup param {k} diverged from local dense")


def test_wire_frame_roundtrip_and_auth_refusal(monkeypatch):
    """The PS wire format is a length-prefixed raw-tensor frame (JSON meta +
    raw blocks), not pickle: roundtrip preserves dtype/shape/values with
    zero-copy views, and a pserver refuses to bind a routable address with
    the default authkey (r4 weak #4)."""
    import pytest
    from paddle_tpu.distributed.ps_rpc import PServerRuntime, _pack, _unpack

    rng = np.random.default_rng(0)
    tensors = [rng.standard_normal((3, 5)).astype(np.float32),
               rng.integers(0, 9, 7).astype(np.int64),
               np.float32(2.5).reshape(())]  # 0-d
    meta = {"op": "send", "name": "w.block0", "trainer": 3, "kind": "sparse",
            "height": 100}
    buf = _pack(meta, tensors)
    assert isinstance(buf, bytes)
    assert b"cnumpy" not in buf and b"pickle" not in buf  # no pickle opcodes
    out_meta, out = _unpack(buf)
    assert out_meta == meta
    for a, b in zip(tensors, out):
        assert a.dtype == b.dtype and a.shape == b.shape
        np.testing.assert_array_equal(a, b)

    import paddle_tpu as pt
    srv = PServerRuntime("0.0.0.0:29599", n_trainers=1, sync_mode=True,
                         blocks=[], scope=pt.Scope(), executor=pt.Executor())
    # machines that export a real key must still exercise the refusal path
    monkeypatch.delenv("PADDLE_PS_AUTHKEY", raising=False)
    with pytest.raises(RuntimeError, match="non-loopback"):
        srv.serve()
