"""Control-flow tests: While -> lax.while_loop, cond -> lax.cond,
StaticRNN -> lax.scan incl. backward-through-time (reference
unittests/test_while_op.py, test_cond.py-era, test_recurrent_op.py)."""
import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import layers as L
from paddle_tpu.layers import tensor as T


def test_while_accumulates():
    """sum 0..9 with a While loop."""
    i = T.fill_constant(shape=[1], dtype="int64", value=0)
    n = T.fill_constant(shape=[1], dtype="int64", value=10)
    acc = T.fill_constant(shape=[1], dtype="int64", value=0)
    cond = L.less_than(i, n)
    w = L.While(cond)
    with w.block():
        tmp = L.elementwise_add(acc, i)
        L.assign(tmp, acc)
        L.increment(i, value=1, in_place=True)
        L.less_than(i, n, cond=cond)
    exe = pt.Executor()
    (out,) = exe.run(pt.default_main_program(), feed={}, fetch_list=[acc])
    assert int(np.asarray(out).reshape(-1)[0]) == 45


def test_while_reads_outer_var():
    x = L.data(name="x", shape=[4], dtype="float32")
    i = T.fill_constant(shape=[1], dtype="int64", value=0)
    n = T.fill_constant(shape=[1], dtype="int64", value=3)
    acc = T.fill_constant(shape=[1, 4], dtype="float32", value=0.0)
    cond = L.less_than(i, n)
    w = L.While(cond)
    with w.block():
        s = L.reduce_sum(x, dim=0, keep_dim=True)  # outer read, not carried
        L.assign(L.elementwise_add(acc, s), acc)
        L.increment(i, value=1, in_place=True)
        L.less_than(i, n, cond=cond)
    exe = pt.Executor()
    xv = np.arange(8, dtype=np.float32).reshape(2, 4)
    (out,) = exe.run(pt.default_main_program(), feed={"x": xv},
                     fetch_list=[acc])
    np.testing.assert_allclose(np.asarray(out), 3 * xv.sum(0, keepdims=True))


def test_cond_selects_branch():
    x = L.data(name="x", shape=[4], dtype="float32")
    pred_in = L.data(name="p", shape=[], dtype="bool")
    out = L.cond(pred_in,
                 lambda: L.scale(x, scale=2.0),
                 lambda: L.scale(x, scale=-1.0))
    exe = pt.Executor()
    xv = np.ones((2, 4), np.float32)
    (got_t,) = exe.run(pt.default_main_program(),
                       feed={"x": xv, "p": np.asarray(True)}, fetch_list=[out])
    (got_f,) = exe.run(pt.default_main_program(),
                       feed={"x": xv, "p": np.asarray(False)}, fetch_list=[out])
    np.testing.assert_allclose(got_t, 2 * xv)
    np.testing.assert_allclose(got_f, -xv)


def test_cond_branch_may_return_outer_var_directly():
    """A branch fn that returns an outer-scope var (zero ops in the branch
    before the bridge assign) must still wire that var into Deps."""
    x = L.data(name="x", shape=[1], dtype="float32")
    yv = L.fc(x, size=1)
    p = L.fill_constant([1], "bool", False)
    out = L.cond(p, lambda: L.scale(x, 2.0), lambda: yv)
    exe = pt.Executor()
    exe.run(pt.default_startup_program())
    xv = np.ones((2, 1), np.float32)
    (got,) = exe.run(pt.default_main_program(), feed={"x": xv}, fetch_list=[out])
    (ref,) = exe.run(pt.default_main_program(), feed={"x": xv}, fetch_list=[yv])
    np.testing.assert_allclose(got, ref)


def test_cond_outer_scope_write_raises():
    """ADVICE r1: a branch assigning to an outer-scope var would be silently
    discarded under functional tracing — must raise instead."""
    x = L.data(name="x", shape=[1], dtype="float32")
    a = L.scale(x, 1.0)
    p = L.fill_constant([1], "bool", True)
    with pytest.raises(ValueError, match="outer-scope"):
        L.cond(p,
               lambda: L.assign(L.scale(x, 2.0), a),
               lambda: a)


def test_static_rnn_forward_matches_numpy():
    T_, B, D, H = 5, 2, 3, 4
    x = L.data(name="x", shape=[B, D], dtype="float32")  # time-major [T,B,D]
    h0 = L.data(name="h0", shape=[H], dtype="float32")

    rnn = L.StaticRNN()
    with rnn.step():
        x_t = rnn.step_input(x)
        prev = rnn.memory(init=h0)
        h = L.tanh(L.elementwise_add(
            L.matmul(x_t, T.fill_constant([D, H], "float32", 0.1)),
            prev))
        rnn.update_memory(prev, h)
        rnn.step_output(h)
    out = rnn()

    exe = pt.Executor()
    rng = np.random.default_rng(0)
    xv = rng.standard_normal((T_, B, D)).astype(np.float32)
    h0v = np.zeros((B, H), np.float32)
    (got,) = exe.run(pt.default_main_program(), feed={"x": xv, "h0": h0v},
                     fetch_list=[out])
    want = []
    h = h0v
    for t in range(T_):
        h = np.tanh(xv[t] @ np.full((D, H), 0.1, np.float32) + h)
        want.append(h)
    np.testing.assert_allclose(np.asarray(got), np.stack(want), rtol=1e-5)


def test_static_rnn_trains_bptt():
    """Gradient flows through lax.scan: train a tiny RNN to fit a target."""
    T_, B, D, H = 4, 8, 3, 5
    x = L.data(name="x", shape=[B, D], dtype="float32")
    y = L.data(name="y", shape=[H], dtype="float32")
    h0 = T.fill_constant([B, H], "float32", 0.0)

    rnn = L.StaticRNN()
    with rnn.step():
        x_t = rnn.step_input(x)
        prev = rnn.memory(init=h0)
        h = L.fc([x_t, prev], size=H, act="tanh")
        rnn.update_memory(prev, h)
        rnn.step_output(h)
    outs = rnn()  # [T, B, H]
    last = L.squeeze(L.slice(outs, axes=[0], starts=[T_ - 1], ends=[T_]),
                     axes=[0])
    loss = L.mean(L.square_error_cost(last, y))
    pt.optimizer.Adam(0.01).minimize(loss)

    exe = pt.Executor()
    exe.run(pt.default_startup_program())
    rng = np.random.default_rng(1)
    xv = rng.standard_normal((T_, B, D)).astype(np.float32)
    yv = rng.standard_normal((B, H)).astype(np.float32) * 0.5
    hist = []
    for _ in range(40):
        (lv,) = exe.run(pt.default_main_program(), feed={"x": xv, "y": yv},
                        fetch_list=[loss])
        hist.append(float(lv))
    assert hist[-1] < hist[0] * 0.5, (hist[0], hist[-1])


def test_while_requires_bool_cond():
    i = T.fill_constant(shape=[1], dtype="int64", value=0)
    with pytest.raises(TypeError):
        L.While(i)


def test_while_on_grad_path_raises():
    """A While between params and loss must raise, not silently freeze."""
    x = L.data(name="xg", shape=[4], dtype="float32")
    h = L.fc(x, size=4)
    i = T.fill_constant(shape=[1], dtype="int64", value=0)
    n = T.fill_constant(shape=[1], dtype="int64", value=2)
    acc = T.fill_constant(shape=[1, 4], dtype="float32", value=0.0)
    cnd = L.less_than(i, n)
    w = L.While(cnd)
    with w.block():
        L.assign(L.elementwise_add(acc, h), acc)
        L.increment(i, value=1, in_place=True)
        L.less_than(i, n, cond=cnd)
    loss = L.mean(acc)
    with pytest.raises(RuntimeError, match="gradient path"):
        pt.optimizer.SGD(0.1).minimize(loss)


def test_static_rnn_dropout_varies_per_step():
    """Per-timestep RNG: dropout masks must differ across scan steps."""
    T_, B, D = 6, 2, 64
    x = L.data(name="xr", shape=[B, D], dtype="float32")
    m0 = T.fill_constant([B, D], "float32", 0.0)
    rnn = L.StaticRNN()
    with rnn.step():
        x_t = rnn.step_input(x)
        d = L.dropout(x_t, dropout_prob=0.5,
                      dropout_implementation="upscale_in_train")
        mem = rnn.memory(init=m0)
        rnn.update_memory(mem, d)
        rnn.step_output(d)
    outs = rnn()
    exe = pt.Executor()
    xv = np.ones((T_, B, D), np.float32)
    (got,) = exe.run(pt.default_main_program(), feed={"xr": xv},
                     fetch_list=[outs])
    got = np.asarray(got)
    masks = (got != 0).reshape(T_, -1)
    # adjacent steps must not share the identical mask
    assert not all((masks[t] == masks[0]).all() for t in range(1, T_))


def test_switch_first_true_case_wins():
    """Switch executes the first matching case (reference Switch:1622)."""
    import numpy as np

    from paddle_tpu.layers import tensor as T

    step = L.data(name="step", shape=[], dtype="float32")
    lr = T.create_global_var([1], 0.0, "float32", name="sw_lr")
    c1 = L.less_than(step, T.fill_constant([], "float32", 10.0))
    c2 = L.less_than(step, T.fill_constant([], "float32", 100.0))
    with L.Switch() as sw:
        with sw.case(c1):
            T.assign(T.fill_constant([1], "float32", 0.001), lr)
        with sw.case(c2):
            T.assign(T.fill_constant([1], "float32", 0.01), lr)
        with sw.default():
            T.assign(T.fill_constant([1], "float32", 0.1), lr)
    exe = pt.Executor()
    vals = [float(exe.run(pt.default_main_program(),
                          feed={"step": np.float32(s)},
                          fetch_list=[lr])[0][0])
            for s in (5.0, 50.0, 500.0)]
    np.testing.assert_allclose(vals, [0.001, 0.01, 0.1], rtol=1e-6)


def test_ifelse_rowwise_merge():
    """IfElse merges per-row branch results (reference IfElse:1897; the
    batch split becomes a row-wise select on the padded layout)."""
    import numpy as np

    x = L.data(name="x", shape=[3], dtype="float32")
    c = L.data(name="c", shape=[1], dtype="bool")
    ie = L.IfElse(c)
    with ie.true_block():
        ie.output(L.scale(ie.input(x), scale=10.0))
    with ie.false_block():
        ie.output(L.scale(ie.input(x), scale=0.0, bias=-1.0))
    out = ie()
    exe = pt.Executor()
    xv = np.arange(6, dtype=np.float32).reshape(2, 3)
    cv = np.array([[True], [False]])
    (got,) = exe.run(pt.default_main_program(),
                     feed={"x": xv, "c": cv}, fetch_list=[out])
    np.testing.assert_allclose(got[0], xv[0] * 10.0)
    np.testing.assert_allclose(got[1], [-1.0, -1.0, -1.0])
