"""Quantization deployment paths (reference slim QuantizationFreezePass /
ConvertToInt8Pass / post-training calibration): QAT -> freeze ->
save_inference_model round trip, int8 weight storage, and PTQ calibration."""
import numpy as np

import paddle_tpu as pt
from paddle_tpu import layers as L
from paddle_tpu.contrib.slim.quantization import (
    ConvertToInt8Pass,
    PostTrainingQuantization,
    QuantizationFreezePass,
    QuantizationTransformPass,
)


def _build_and_train(qat: bool, steps=60, seed=3):
    main, startup = pt.Program(), pt.Program()
    main.random_seed = startup.random_seed = 7
    with pt.program_guard(main, startup):
        with pt.unique_name.guard():
            x = L.data(name="x", shape=[8], dtype="float32")
            y = L.data(name="y", shape=[1], dtype="float32")
            pred = L.fc(L.fc(x, size=16, act="relu"), size=1)
            loss = L.mean(L.square_error_cost(pred, y))
            if qat:
                QuantizationTransformPass().apply(main, startup)
            # inference program BEFORE minimize (reference clone(for_test))
            test_prog = main.clone(for_test=True)
            pt.optimizer.SGD(0.05).minimize(loss)
    scope = pt.Scope()
    exe = pt.Executor()
    rng = np.random.default_rng(seed)
    w = rng.standard_normal((8, 1)).astype(np.float32)
    with pt.scope_guard(scope):
        exe.run(startup)
        for _ in range(steps):
            xb = rng.standard_normal((32, 8)).astype(np.float32)
            exe.run(main, feed={"x": xb, "y": xb @ w}, fetch_list=[loss])
    return main, test_prog, scope, exe, pred, w


def test_qat_freeze_save_load_roundtrip(tmp_path):
    main, test_prog, scope, exe, pred, w = _build_and_train(qat=True)
    rng = np.random.default_rng(11)
    xq = rng.standard_normal((16, 8)).astype(np.float32)
    with pt.scope_guard(scope):
        # QAT-mode reference output from the TEST program (the training
        # program would apply an SGD step as a side effect of the fetch)
        (ref,) = exe.run(test_prog, feed={"x": xq, "y": np.zeros((16, 1), np.float32)},
                         fetch_list=[pred.name])
        ref = np.asarray(ref)

        infer = test_prog.clone(for_test=True)
        QuantizationFreezePass(scope).apply(infer)
        types = [op.type for op in infer.global_block.ops]
        assert not any("fake_quantize" in t for t in types), types
        # quantization metadata survives on the consumer ops
        assert any("in_scales" in op.attrs for op in infer.global_block.ops)
        # frozen weights are quantized levels: <= 2^8 distinct values
        fcw = np.asarray(scope.find_var("fc_0.w_0"))
        assert len(np.unique(fcw)) <= 255
        (frozen_out,) = exe.run(infer, feed={"x": xq, "y": np.zeros((16, 1), np.float32)},
                                fetch_list=[pred.name])
        # freeze keeps the qdq'd weights but drops activation fakes: close,
        # not identical
        np.testing.assert_allclose(np.asarray(frozen_out), ref,
                                   rtol=0.15, atol=0.05)

        d = str(tmp_path / "qmodel")
        pt.io.save_inference_model(d, ["x"], [infer.global_block.var(pred.name)],
                                   exe, main_program=infer, scope=scope)
    scope2 = pt.Scope()
    with pt.scope_guard(scope2):
        prog2, feeds2, fetches2 = pt.io.load_inference_model(d, exe)
        (out2,) = exe.run(prog2, feed={"x": xq}, fetch_list=fetches2)
    np.testing.assert_allclose(np.asarray(out2),
                               np.asarray(frozen_out), rtol=1e-5)


def test_convert_to_int8_stores_int8_weights(tmp_path):
    main, test_prog, scope, exe, pred, w = _build_and_train(qat=True)
    rng = np.random.default_rng(11)
    xq = rng.standard_normal((16, 8)).astype(np.float32)
    with pt.scope_guard(scope):
        infer = test_prog.clone(for_test=True)
        QuantizationFreezePass(scope).apply(infer)
        (frozen_out,) = exe.run(infer, feed={"x": xq, "y": np.zeros((16, 1), np.float32)},
                                fetch_list=[pred.name])
        ConvertToInt8Pass(scope).apply(infer)
        # weights now int8 in scope + program; dequantize ops present
        fcw = np.asarray(scope.find_var("fc_0.w_0"))
        assert fcw.dtype == np.int8
        assert any(op.type == "dequantize_abs_max"
                   for op in infer.global_block.ops)
        (int8_out,) = exe.run(infer, feed={"x": xq, "y": np.zeros((16, 1), np.float32)},
                              fetch_list=[pred.name])
        # int8 storage must be numerically identical to the frozen fp sim
        np.testing.assert_allclose(np.asarray(int8_out),
                                   np.asarray(frozen_out), rtol=1e-5)
        d = str(tmp_path / "int8model")
        pt.io.save_inference_model(d, ["x"], [infer.global_block.var(pred.name)],
                                   exe, main_program=infer, scope=scope)
    scope2 = pt.Scope()
    with pt.scope_guard(scope2):
        prog2, feeds2, fetches2 = pt.io.load_inference_model(d, exe)
        fcw2 = np.asarray(pt.global_scope().find_var("fc_0.w_0"))
        assert fcw2.dtype == np.int8, "saved weights are not int8"
        (out2,) = exe.run(prog2, feed={"x": xq}, fetch_list=fetches2)
    np.testing.assert_allclose(np.asarray(out2), np.asarray(int8_out),
                               rtol=1e-5)


def test_post_training_quantization_accuracy(tmp_path):
    # train FP32, calibrate on samples, quantize, compare predictions
    main, test_prog, scope, exe, pred, w = _build_and_train(qat=False)
    rng = np.random.default_rng(5)
    calib = [{"x": rng.standard_normal((32, 8)).astype(np.float32),
              "y": np.zeros((32, 1), np.float32)} for _ in range(4)]
    xq = rng.standard_normal((64, 8)).astype(np.float32)
    with pt.scope_guard(scope):
        (fp_out,) = exe.run(test_prog, feed={"x": xq, "y": np.zeros((64, 1), np.float32)},
                            fetch_list=[pred.name])
        infer = test_prog.clone(for_test=True)
        ptq = PostTrainingQuantization(exe, infer, calib, scope=scope)
        qprog = ptq.quantize()
        types = [op.type for op in qprog.global_block.ops]
        assert types.count("fake_quantize_dequantize_static") >= 4, types
        (q_out,) = exe.run(qprog, feed={"x": xq, "y": np.zeros((64, 1), np.float32)},
                           fetch_list=[pred.name])
        # 8-bit PTQ on a small regression head: small accuracy delta
        err = np.abs(np.asarray(q_out) - np.asarray(fp_out)).mean()
        ref = np.abs(np.asarray(fp_out)).mean() + 1e-6
        assert err / ref < 0.1, (err, ref)
        # full deploy chain: freeze + int8 + save
        QuantizationFreezePass(scope).apply(qprog)
        ConvertToInt8Pass(scope).apply(qprog)
        d = str(tmp_path / "ptqmodel")
        pt.io.save_inference_model(d, ["x"], [qprog.global_block.var(pred.name)],
                                   exe, main_program=qprog, scope=scope)
    scope2 = pt.Scope()
    with pt.scope_guard(scope2):
        prog2, _, fetches2 = pt.io.load_inference_model(d, exe)
        (out2,) = exe.run(prog2, feed={"x": xq}, fetch_list=fetches2)
    err2 = np.abs(np.asarray(out2) - np.asarray(fp_out)).mean()
    assert err2 / ref < 0.1, (err2, ref)
