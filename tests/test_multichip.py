"""Multichip collective-overlap tests (ISSUE 8) on the virtual 8-device mesh.

The exactness contracts behind the measured scaling campaign
(tools/_mc_ab.py, bench.py --multichip): bucketed allreduce is BITWISE
payload-layout-invariant, ZeRO-1 sharding lands on the single-device
parameter trajectory, the 1F1B schedule's bubble accounting is explicit and
its numerics equal fill-drain's, and the PR 3 watchdog surfaces a hung
allreduce with step ids and queue depths.
"""
import json
import warnings

import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import layers as L
from paddle_tpu.parallel import make_mesh
from paddle_tpu.parallel.collective import (GradAllReduce, build_buckets,
                                            resolve_bucket_mb)

N_DEV = 8


def _build_mlp(opt=None, sizes=(8, 8)):
    x = L.data(name="x", shape=[16], dtype="float32")
    y = L.data(name="y", shape=[1], dtype="float32")
    h = x
    for s in sizes:
        h = L.fc(h, size=s, act="relu")
    pred = L.fc(h, size=1)
    loss = L.mean(L.square_error_cost(pred, y))
    (opt or pt.optimizer.Momentum(0.05, 0.9)).minimize(loss)
    return loss


def _batch(seed=0, bs=32):
    rng = np.random.default_rng(seed)
    x = rng.standard_normal((bs, 16)).astype(np.float32)
    w = rng.standard_normal((16, 1)).astype(np.float32)
    return x, (x @ w).astype(np.float32)


def _train(transpile=None, target_of=None, steps=5, opt=None, fetch=True):
    """Build+train in fresh program/scope; return (loss history, params)."""
    main, startup = pt.Program(), pt.Program()
    main.random_seed = 7
    startup.random_seed = 7
    with pt.program_guard(main, startup):
        with pt.unique_name.guard():
            loss = _build_mlp(opt() if opt else None)
    if transpile is not None:
        transpile(main, startup)
    scope = pt.Scope()
    exe = pt.Executor()
    x, y = _batch()
    with pt.scope_guard(scope):
        exe.run(startup)
        target = target_of(main) if target_of else main
        hist = []
        for _ in range(steps):
            (lv,) = exe.run(target, feed={"x": x, "y": y},
                            fetch_list=[loss.name])
            hist.append(float(np.asarray(lv).reshape(-1)[0]))
        params = {p.name: np.asarray(scope.find_var(p.name))
                  for p in main.all_parameters()}
    return hist, params, main


def _collective(main):
    return pt.CompiledProgram(main).with_collective(
        mesh=make_mesh({"dp": N_DEV}))


def _transpiler(bucket_mb=None, zero1=None):
    t = GradAllReduce(bucket_mb=bucket_mb, zero1=zero1)

    def run(main, startup):
        t.transpile(startup, main, rank=0, nranks=N_DEV)

    return t, run


# -- bucketed allreduce exactness -------------------------------------------

def test_bucketed_allreduce_bitwise_loss_parity():
    """Per-grad vs one-big-bucket vs a boundary that SPLITS one layer's
    (w, b) pair: identical bitwise loss trajectories (psum per element is
    the same sum regardless of payload grouping), and all land on the
    single-device parameter trajectory (mean-allreduce oracle)."""
    single_h, single_p, _ = _train()

    arms = {}
    for name, mb in (("pergrad", 0.0), ("bucketed", 4.0),
                     ("split", 0.0001)):
        t, tr = _transpiler(bucket_mb=mb)
        arms[name] = _train(tr, _collective)
        if name == "split":
            # the tiny bucket really did split a layer: some consecutive
            # bucket pair separates one fc layer's w from its b
            assert len(t.last_buckets) > 1, t.last_buckets
            stems = [{g.split(".")[0] for g in names}
                     for _, names in t.last_buckets]
            assert any(a & b for a, b in zip(stems, stems[1:])), \
                t.last_buckets

    assert arms["pergrad"][0] == arms["bucketed"][0] == arms["split"][0], \
        {k: v[0] for k, v in arms.items()}
    for name, ref in single_p.items():
        for _, params, _ in arms.values():
            np.testing.assert_allclose(ref, params[name], rtol=1e-4,
                                       atol=1e-5)


def test_bucket_overlap_placement_below_guardrails():
    """Buckets sit at grad-READINESS points: interleaved with the backward
    ops rather than parked at the optimizer boundary — and under
    FLAGS_guard_numerics strictly below the health sentinel (a reduce above
    it would ship pre-gated gradients)."""
    from paddle_tpu import flags as pt_flags

    t, tr = _transpiler(bucket_mb=0.00005)  # ~50B buckets: one per grad
    _, _, main = _train(tr, _collective, steps=1)
    block = main.global_block
    first_opt = min(i for i, op in enumerate(block.ops)
                    if op.type == "momentum")
    positions = [p for p, _ in t.last_buckets]
    assert len(positions) > 1
    # at least one bucket reduce runs BEFORE the last backward grad op —
    # the overlap regime (per-grad baseline parks all of them at first_opt)
    last_grad = max(i for i, op in enumerate(block.ops)
                    if op.type.endswith("_grad"))
    assert min(positions) <= last_grad < first_opt, \
        (positions, last_grad, first_opt)

    saved = pt_flags.get_flag("guard_numerics")
    pt_flags.set_flags({"guard_numerics": True})
    try:
        t2, tr2 = _transpiler(bucket_mb=4.0)
        _, _, main2 = _train(tr2, _collective, steps=1)
        block2 = main2.global_block
        sentinel = [i for i, op in enumerate(block2.ops)
                    if op.type == "health_sentinel"]
        assert sentinel, [op.type for op in block2.ops]
        assert all(p > sentinel[-1] for p, _ in t2.last_buckets), \
            (t2.last_buckets, sentinel)
    finally:
        pt_flags.set_flags({"guard_numerics": saved})


def test_bucketed_allreduce_bitwise_under_amp():
    """'Below AMP': with the mixed-precision decorator the readiness points
    sit after the unscale/check ops (the last grad writers), and bucketed
    still equals per-grad BITWISE — the reduce ships post-unscale fp32
    master grads either way."""
    def amp_opt():
        return pt.contrib.mixed_precision.decorate(
            pt.optimizer.Momentum(0.05, 0.9))

    arms = {}
    for name, mb in (("pergrad", 0.0), ("bucketed", 4.0)):
        _, tr = _transpiler(bucket_mb=mb)
        arms[name] = _train(tr, _collective, steps=4, opt=amp_opt)
    assert arms["pergrad"][0] == arms["bucketed"][0]
    for n, ref in arms["pergrad"][1].items():
        assert np.array_equal(ref, arms["bucketed"][1][n]), n


def test_build_buckets_cuts_and_order():
    items = [(3, "g_late", 100), (1, "g_mid", 100), (0, "g_early", 250)]
    buckets = build_buckets(items, 300)
    assert [[n for _, n, _ in b] for b in buckets] == \
        [["g_early"], ["g_mid", "g_late"]]
    assert [[n for _, n, _ in b] for b in build_buckets(items, 0)] == \
        [["g_early"], ["g_mid"], ["g_late"]]


def test_bucket_size_resolved_through_tuning_db(tmp_path):
    """The `collective|mesh=..|payload=..` tuner wiring: a swept DB verdict
    overrides FLAGS_allreduce_bucket_mb in consult mode; off mode keeps the
    flag; and the transpiler records provenance either way."""
    from paddle_tpu import flags as pt_flags
    from paddle_tpu import tuning

    # discover this model's quantized payload key from a throwaway transpile
    probe, tr = _transpiler()
    main, startup = pt.Program(), pt.Program()
    with pt.program_guard(main, startup):
        with pt.unique_name.guard():
            _build_mlp()
    tr(main, startup)
    assert probe.bucket_source == "flag"  # tuning off by default
    assert probe.resolved_bucket_mb == float(
        pt_flags.get_flag("allreduce_bucket_mb"))

    key = tuning.canonical_key(
        "collective",
        tuning.collective_key(f"dp{N_DEV}", probe.last_payload_bytes),
        "float32", tuning.device_kind())
    db_path = str(tmp_path / "tuning.json")
    db = tuning.TuningDB()
    db.put(key, {"bucket_mb": 0.0001}, source="swept", note="test sweep")
    db.save(db_path)

    saved = {k: pt_flags.get_flag(k) for k in ("tuning_mode", "tuning_db")}
    pt_flags.set_flags({"tuning_mode": "consult", "tuning_db": db_path})
    tuning.invalidate_db_cache()
    try:
        t2, tr2 = _transpiler()
        main2, startup2 = pt.Program(), pt.Program()
        with pt.program_guard(main2, startup2):
            with pt.unique_name.guard():
                _build_mlp()
        tr2(main2, startup2)
        assert t2.bucket_source == "db", (t2.bucket_source, key)
        assert t2.resolved_bucket_mb == 0.0001
        assert len(t2.last_buckets) > 1  # the swept size actually applied
    finally:
        pt_flags.set_flags(saved)
        tuning.invalidate_db_cache()


# -- ZeRO-1 ------------------------------------------------------------------

def test_zero1_structure_and_parity():
    """ZeRO-1 with Adam: reduce-scatter/shard/allgather ops present, the
    rewritten update consumes shard vars, moments shard with the param,
    the indivisible bias falls back to the allreduce path — and the
    parameter trajectory still equals single-device (loss parity)."""
    single_h, single_p, _ = _train(opt=lambda: pt.optimizer.Adam(1e-2))

    t, tr = _transpiler(zero1=True)
    _, params, main = _train(tr, _collective,
                             opt=lambda: pt.optimizer.Adam(1e-2))
    types = [op.type for op in main.global_block.ops]
    assert "c_reducescatter" in types
    assert "zero1_shard" in types
    assert "c_allgather" in types
    assert t.zero1_params, "no parameter took the ZeRO-1 path"
    # the final fc bias [1] cannot shard 8 ways -> classic allreduce
    assert "c_allreduce_sum" in types
    adam_ops = [op for op in main.global_block.ops if op.type == "adam"]
    sharded = [op for op in adam_ops
               if op.input("Param")[0].endswith("@ZERO1_SHARD")]
    assert sharded, [op.input("Param") for op in adam_ops]
    for op in sharded:
        assert op.input("Moment1")[0].endswith("@ZERO1_SHARD")
        assert op.input("Grad")[0].endswith("@ZERO1_GRAD")
        # scalar beta-pow state stays replicated
        assert not op.input("Beta1Pow")[0].endswith("@ZERO1_SHARD")
    for name, ref in single_p.items():
        np.testing.assert_allclose(ref, params[name], rtol=1e-4, atol=1e-5)


def test_zero1_gspmd_degrade_is_identity():
    """The same ZeRO-1-rewritten program run WITHOUT a bound axis (GSPMD/
    single device): every inserted collective lowers to identity and the
    step equals the untranspiled program bitwise."""
    plain_h, _, _ = _train(steps=3)
    _, tr = _transpiler(zero1=True)
    z_h, _, _ = _train(tr, None, steps=3)  # no mesh: axis env unbound
    assert plain_h == z_h, (plain_h, z_h)


# -- 1F1B bubble accounting --------------------------------------------------

def _pipeline_program(schedule, M=8):
    from paddle_tpu.parallel.pipeline import build_pipeline_plan

    main, startup = pt.Program(), pt.Program()
    main.random_seed = 11
    startup.random_seed = 11
    with pt.program_guard(main, startup):
        with pt.unique_name.guard():
            x = L.data(name="x", shape=[16], dtype="float32")
            y = L.data(name="y", shape=[1], dtype="float32")
            h1 = L.fc(x, size=16, act="relu")
            h2 = L.fc(h1, size=16, act="relu")
            pred = L.fc(h2, size=1)
            loss = L.mean(L.square_error_cost(pred, y))
            main._pipeline = build_pipeline_plan(
                main, loss, [h1, h2], pt.optimizer.SGD(0.05), M, startup,
                schedule=schedule)
    return main, startup, loss


def test_1f1b_bubble_accounting_and_loss_equivalence():
    """Explicit bubble accounting: both schedules report the analytic
    (S-1)/(M+S-1), GPipe's observed stalls are exactly the fill/drain
    2*(S-1) slots per stage, 1F1B's steady state stalls no more than GPipe
    and bounds the stash — while producing the IDENTICAL loss (fill-drain
    equivalence, the satellite oracle)."""
    from paddle_tpu.parallel.pipeline import bubble_fraction

    M, S = 8, 3
    x, y = _batch(bs=32)
    out = {}
    for schedule in ("gpipe", "1f1b"):
        main, startup, loss = _pipeline_program(schedule, M)
        scope = pt.Scope()
        exe = pt.Executor()
        with pt.scope_guard(scope):
            exe.run(startup)
            (lv,) = exe.run(main, feed={"x": x, "y": y},
                            fetch_list=[loss.name])
        plan = main._pipeline
        b = plan.last_bubble
        assert b["schedule"] == schedule
        assert b["analytic_frac"] == round(bubble_fraction(S, M), 4)
        assert b["num_microbatches"] == M and b["n_stages"] == S
        out[schedule] = (float(np.asarray(lv)), b, plan.last_peak_stash)
    g_loss, g_b, g_peak = out["gpipe"]
    f_loss, f_b, f_peak = out["1f1b"]
    assert g_loss == f_loss, (g_loss, f_loss)
    # gpipe: every stage idles exactly 2*(S-1) fill/drain slots
    assert g_b["stall_rounds_per_stage"] == [2 * (S - 1)] * S, g_b
    assert g_b["observed_frac"] == round(bubble_fraction(S, M), 4)
    # 1f1b: dependency stalls exist but the stash is the win
    assert sum(f_b["stall_rounds_per_stage"]) > 0
    assert f_peak <= S + 1 < M <= g_peak, (f_peak, g_peak)


def test_pipeline_schedule_flag_default():
    from paddle_tpu import flags as pt_flags

    saved = pt_flags.get_flag("pipeline_schedule")
    pt_flags.set_flags({"pipeline_schedule": "gpipe"})
    try:
        main, _, _ = _pipeline_program(schedule=None)
        assert main._pipeline.schedule == "gpipe"
    finally:
        pt_flags.set_flags({"pipeline_schedule": saved})
    main2, _, _ = _pipeline_program(schedule=None)
    assert main2._pipeline.schedule == "1f1b"


def test_pipeline_int64_feed_no_truncation_warning():
    """MULTICHIP dryrun-tail hygiene (ISSUE 8 satellite): an int64 host feed
    through the pipeline microbatch splitter is narrowed on the HOST
    (np_feed_dtype), so jax never sees an int64 astype request."""
    main, startup, loss = _pipeline_program("1f1b", M=4)
    x, y = _batch(bs=16)
    scope = pt.Scope()
    exe = pt.Executor()
    with pt.scope_guard(scope):
        exe.run(startup)
        with warnings.catch_warnings(record=True) as caught:
            warnings.simplefilter("always")
            exe.run(main, feed={"x": x.astype(np.float64),
                                "y": y.astype(np.float64)},
                    fetch_list=[loss.name])
    bad = [w for w in caught if "truncated" in str(w.message)]
    assert not bad, [str(w.message) for w in bad]


# -- collective_stall watchdog ----------------------------------------------

@pytest.mark.chaos
def test_collective_stall_surfaces_hung_allreduce():
    """The PR 3 watchdog must turn a hung allreduce into a StallError
    carrying step ids and queue depths — driven by the `collective_stall`
    fault site, which fires only for steps dispatched under the
    shard_map/with_collective regime."""
    from paddle_tpu import flags as pt_flags
    from paddle_tpu.resilience.faults import fault_scope
    from paddle_tpu.resilience.watchdog import StallError

    _, tr = _transpiler(bucket_mb=4.0)
    main, startup = pt.Program(), pt.Program()
    with pt.program_guard(main, startup):
        with pt.unique_name.guard():
            _build_mlp()
    tr(main, startup)
    x, y = _batch()
    scope = pt.Scope()
    exe = pt.Executor()
    saved = pt_flags.get_flag("watchdog_stall_s")
    pt_flags.set_flags({"watchdog_stall_s": 0.25})
    try:
        with pt.scope_guard(scope):
            exe.run(startup)
            compiled = _collective(main)
            exe.run(compiled, feed={"x": x, "y": y})  # warm compile
            with fault_scope("collective_stall:1") as plan:
                # a plain (gspmd) async step must NOT trip the site
                exe.run_async(main, feed={"x": x, "y": y}, scope=scope)
                exe.wait()
                assert plan.stats()["hits"].get("collective_stall", 0) == 0
                exe.run_async(compiled, feed={"x": x, "y": y}, scope=scope)
                with pytest.raises(StallError) as ei:
                    exe.wait()
            err = ei.value
            assert "collective allreduce" in str(err)
            assert err.state["inflight_step_ids"], err.state
            assert err.state["inflight_depth"] >= 1
            assert err.state["spmd_mode"] == "shard_map"
            exe.drain_quiet()
    finally:
        pt_flags.set_flags({"watchdog_stall_s": saved})


# -- campaign artifact + gate ------------------------------------------------

def _artifact(**overrides):
    base = {
        "metric": "multichip_scaling", "value": 0.4, "unit": "ratio",
        "n_devices": 8, "platform": "cpu",
        "scaling": {
            "dp": {"tokens_per_sec": 14000.0, "n_devices": 8,
                   "speedup_vs_single": 1.2, "efficiency": 0.15,
                   "band": 0.02},
            "pp": {"tokens_per_sec": 8000.0, "n_devices": 4,
                   "speedup_vs_single": 0.64, "efficiency": 0.16,
                   "band": 0.02},
        },
        "overlap_ab": {
            "dp_bucketed": {"off_tok_s": 13800.0, "on_tok_s": 14000.0,
                            "band": 0.05, "verdict": "keep"},
            "dp_zero1": {"off_tok_s": 14000.0, "on_tok_s": 13000.0,
                         "band": 0.05, "verdict": "retire"},
            "pp_1f1b": {"off_tok_s": 8000.0, "on_tok_s": 8100.0,
                        "band": 0.05, "verdict": "tie"},
        },
        "parity": {"dp": 0.0002, "pp": 0.0003},
    }
    base.update(overrides)
    return base


def test_gate_multichip_checks(tmp_path):
    import importlib.util
    import os

    spec = importlib.util.spec_from_file_location(
        "mc_gate", os.path.join(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))), "tools", "gate.py"))
    gate = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(gate)

    def check(art):
        p = tmp_path / "MULTICHIP_test.json"
        # the driver wrapper shape: metrics line rides in the tail
        p.write_text(json.dumps({"n_devices": 8, "rc": 0, "ok": True,
                                 "tail": "noise\n" + json.dumps(art)}))
        return gate.check_multichip(str(p))

    assert check(_artifact()) == 0  # zero1 retire is WARN-only (memory lever)
    bad_parity = _artifact(parity={"dp": 0.02, "pp": 0.0003})
    assert check(bad_parity) == 1
    slow = _artifact()
    slow["scaling"]["dp"]["speedup_vs_single"] = 0.01
    assert check(slow) == 1
    regressed = _artifact()
    regressed["overlap_ab"]["dp_bucketed"]["verdict"] = "retire"
    assert check(regressed) == 1
    # pre-campaign artifact (parity dryrun only): skipped, green
    p = tmp_path / "MULTICHIP_old.json"
    p.write_text(json.dumps({"n_devices": 8, "rc": 0, "ok": True,
                             "tail": "dryrun_multichip ok: ..."}))
    assert gate.check_multichip(str(p)) == 0


def test_mc_ab_record_verdict_roundtrip(tmp_path):
    """A sweep winner beating the per-grad baseline beyond the band lands
    in the tuning DB as a swept `collective|...` verdict the transpiler's
    consult path can resolve (and a tie would be rejected — the
    _timing.ab_verdict contract, exercised by the CLI run)."""
    from paddle_tpu import tuning
    from tools import _mc_ab

    class _T:
        last_payload_bytes = 2 << 20

    rows = {"4.0": {"tok_s": 100.0, "median_s": 0.8, "band": 0.01}}
    off = {"median_s": 1.0, "band": 0.01}
    db_path = str(tmp_path / "db.json")
    _mc_ab._record_verdict(db_path, 8, _T(), rows, 4.0, off)
    key = tuning.canonical_key(
        "collective", tuning.collective_key("dp8", 2 << 20),
        "float32", tuning.device_kind())
    entry = tuning.TuningDB(db_path).lookup(key)
    assert entry is not None, key
    assert entry["decision"]["bucket_mb"] == 4.0
    assert entry["source"] == "swept"


def test_mc_ab_param_drift():
    from tools._mc_ab import _param_drift

    a = {"w": np.ones((4, 4), np.float32)}
    assert _param_drift(a, {"w": np.ones((4, 4), np.float32)}) == 0.0
    b = {"w": np.ones((4, 4), np.float32) * 1.01}
    assert 0.005 < _param_drift(a, b) < 0.02
    assert _param_drift(a, {}) == float("inf")


def test_fleet_strategy_bucket_and_zero1_knobs():
    """DistributedStrategy.allreduce_bucket_mb / zero1 flow through the
    fleet CollectiveOptimizer into the transpiler."""
    from paddle_tpu.incubate.fleet import UserDefinedRoleMaker, fleet
    from paddle_tpu.incubate.fleet.base import DistributedStrategy

    mesh = make_mesh({"dp": N_DEV})
    strat = DistributedStrategy()
    strat.allreduce_bucket_mb = 0.0001
    strat.zero1 = True
    main, startup = pt.Program(), pt.Program()
    with pt.program_guard(main, startup):
        with pt.unique_name.guard():
            x = L.data(name="x", shape=[16], dtype="float32")
            y = L.data(name="y", shape=[1], dtype="float32")
            loss = L.mean(L.square_error_cost(L.fc(x, size=8), y))
            fleet.init(UserDefinedRoleMaker(worker_num=N_DEV), mesh=mesh)
            opt = fleet.distributed_optimizer(
                pt.optimizer.Adam(1e-2), strategy=strat)
            opt.minimize(loss)
    types = [op.type for op in main.global_block.ops]
    assert "c_reducescatter" in types  # zero1 took the eligible params
    assert "zero1_shard" in types
