"""Kill-and-resume integration: a subprocess trainer is SIGKILLed mid-run
and a fresh process resumes from latest_step() with an identical loss
trajectory (tests/dist_*.py launcher pattern, reference TestDistBase)."""
import os
import subprocess
import sys

import numpy as np

_DIR = os.path.dirname(os.path.abspath(__file__))
_REPO = os.path.dirname(_DIR)
_SCRIPT = os.path.join(_DIR, "dist_ckpt_resume.py")

TOTAL_STEPS = 10
KILL_AT = 4


def _env():
    env = dict(os.environ)
    env["PYTHONPATH"] = _REPO + os.pathsep + env.get("PYTHONPATH", "")
    env.pop("FLAGS_fault_plan", None)
    return env


def _run(root, losses, kill_at, check=True):
    p = subprocess.run(
        [sys.executable, _SCRIPT, root, losses, str(TOTAL_STEPS),
         str(kill_at)],
        env=_env(), capture_output=True, timeout=240)
    if check:
        assert p.returncode == 0, p.stderr.decode()[-3000:]
    return p


def _trajectory(path):
    """step -> loss; on duplicate steps the LAST line wins (a resumed run
    legitimately re-records the crash step it replays)."""
    out = {}
    with open(path) as f:
        for line in f:
            step, loss = line.split()
            out[int(step)] = loss  # compare the exact printed repr
    return out


def test_sigkill_mid_run_then_resume_bit_identical(tmp_path):
    base_losses = str(tmp_path / "base.txt")
    _run(str(tmp_path / "base_ck"), base_losses, -1)
    baseline = _trajectory(base_losses)
    assert sorted(baseline) == list(range(TOTAL_STEPS))

    # crashed run: the trainer SIGKILLs itself right after step KILL_AT
    root = str(tmp_path / "ck")
    losses = str(tmp_path / "resumed.txt")
    p = _run(root, losses, KILL_AT, check=False)
    assert p.returncode == -9, (p.returncode, p.stderr.decode()[-2000:])
    crashed = _trajectory(losses)
    assert sorted(crashed) == list(range(KILL_AT + 1))

    # the checkpoint root survived the kill with a loadable latest step
    # within one step of the crash (save cadence 1: crash during step 4's
    # post-step bookkeeping -> last durable checkpoint is step 3 or 4)
    from paddle_tpu.resilience import CheckpointManager

    latest = CheckpointManager(root).latest_step()
    assert latest is not None and KILL_AT - 1 <= latest <= KILL_AT, latest

    # fresh process, same root: resumes and completes
    p = _run(root, losses, -1)
    assert f"start={latest + 1}".encode() in p.stdout, p.stdout
    combined = _trajectory(losses)
    assert sorted(combined) == list(range(TOTAL_STEPS))

    # bit-identical: every step's printed loss matches the undisturbed run,
    # including the overlap step the resume replayed
    assert combined == baseline
