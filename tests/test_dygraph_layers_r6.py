"""Dygraph zoo completion (VERDICT r5 #2 / ISSUE 5 satellite): `FC` (the
lazy-weight, num_flatten_dims eager dense layer, reference dygraph/nn.py:773)
and `Conv2DTranspose` (reference dygraph/nn.py:1964) as tape Layers, each
checked against the static-graph layer with the same parameters, plus
gradient flow through the tape."""
import numpy as np

import paddle_tpu as pt
from paddle_tpu import dygraph as dg
from paddle_tpu import layers as L
from paddle_tpu.dygraph import _dy_op


def _static_eval(build_fn, feeds, params_by_shape):
    """Run a static program, injecting params positionally by shape (the
    test_dygraph_layers_r5 oracle helper)."""
    main, startup = pt.Program(), pt.Program()
    with pt.program_guard(main, startup):
        with pt.unique_name.guard():
            out = build_fn()
    exe = pt.Executor()
    with pt.scope_guard(pt.Scope()):
        exe.run(startup)
        remaining = list(params_by_shape)
        for p in main.all_parameters():
            for i, v in enumerate(remaining):
                if tuple(v.shape) == tuple(p.shape):
                    pt.global_scope().set_var(p.name, v)
                    remaining.pop(i)
                    break
            else:
                raise AssertionError(
                    f"no injected value of shape {p.shape} for {p.name}")
        assert not remaining, [v.shape for v in remaining]
        return np.asarray(exe.run(main, feed=feeds, fetch_list=[out])[0])


def test_dygraph_fc_lazy_weight_matches_static():
    rng = np.random.default_rng(0)
    x = rng.standard_normal((4, 2, 3, 5)).astype(np.float32)
    with dg.guard():
        layer = dg.FC(size=7, num_flatten_dims=2, act="relu")
        assert layer.weight is None  # lazy until the first forward
        got = layer(dg.to_variable(x)).numpy()
        # weight materialized from the trailing dims: [3*5, 7]
        assert tuple(layer.weight.shape) == (15, 7)
        w, b = layer.weight.numpy(), layer.bias.numpy()
        # second call reuses the same parameter (no re-create)
        again = layer(dg.to_variable(x)).numpy()
    np.testing.assert_allclose(again, got, rtol=1e-6)
    assert got.shape == (4, 2, 7)

    def build():
        xv = L.data(name="x", shape=[2, 3, 5], dtype="float32")
        return L.fc(xv, size=7, num_flatten_dims=2, act="relu")

    ref = _static_eval(build, {"x": x}, [w, b])
    np.testing.assert_allclose(got, ref, rtol=1e-5, atol=1e-6)


def test_dygraph_fc_gradient_flows():
    rng = np.random.default_rng(1)
    x = rng.standard_normal((8, 6)).astype(np.float32)
    with dg.guard():
        layer = dg.FC(size=3)
        out = layer(dg.to_variable(x))
        loss = _dy_op("mean", {"X": [out]})["Out"]
        loss.backward()
        g = layer.weight.gradient()
    assert g is not None and g.shape == (6, 3)
    assert np.abs(np.asarray(g)).sum() > 0


def test_dygraph_conv2d_transpose_matches_static():
    rng = np.random.default_rng(2)
    x = rng.standard_normal((2, 3, 5, 5)).astype(np.float32)
    with dg.guard():
        layer = dg.Conv2DTranspose(num_channels=3, num_filters=4,
                                   filter_size=3, stride=2, padding=1)
        got = layer(dg.to_variable(x)).numpy()
        w, b = layer.weight.numpy(), layer.bias.numpy()
    assert tuple(w.shape) == (3, 4, 3, 3)

    def build():
        xv = L.data(name="x", shape=[3, 5, 5], dtype="float32")
        return L.conv2d_transpose(xv, num_filters=4, filter_size=3,
                                  stride=2, padding=1)

    ref = _static_eval(build, {"x": x}, [w, b])
    np.testing.assert_allclose(got, ref, rtol=1e-5, atol=1e-6)


def test_dygraph_conv2d_transpose_gradient_and_act():
    rng = np.random.default_rng(3)
    x = rng.standard_normal((2, 3, 4, 4)).astype(np.float32)
    with dg.guard():
        layer = dg.Conv2DTranspose(num_channels=3, num_filters=2,
                                   filter_size=2, stride=2, act="relu")
        out = layer(dg.to_variable(x))
        assert out.shape == (2, 2, 8, 8)
        assert (out.numpy() >= 0).all()  # act applied
        _dy_op("mean", {"X": [out]})["Out"].backward()
        g = layer.weight.gradient()
    assert g is not None and np.isfinite(np.asarray(g)).all()


def test_dygraph_zoo_superset_of_reference_nn():
    """The reference dygraph/nn.py class list is now a subset of ours."""
    reference_zoo = {
        "Conv2D", "Conv3D", "Pool2D", "FC", "BatchNorm", "Embedding",
        "LayerNorm", "GRUUnit", "NCE", "PRelu", "BilinearTensorProduct",
        "Conv2DTranspose", "Conv3DTranspose", "GroupNorm", "SpectralNorm",
        "TreeConv",
    }
    missing = reference_zoo - set(dir(dg))
    assert not missing, missing
