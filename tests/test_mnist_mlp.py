"""End-to-end slice: MNIST-style MLP trains and loss decreases.

Mirrors the reference's book test (python/paddle/fluid/tests/book/
test_recognize_digits.py) — build via layers, run startup, train a few
iterations on synthetic data, assert the loss drops.
"""
import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import layers as L


def _build_mlp():
    img = L.data(name="img", shape=[784], dtype="float32")
    label = L.data(name="label", shape=[1], dtype="int64")
    h = L.fc(img, size=128, act="relu")
    h = L.fc(h, size=64, act="relu")
    logits = L.fc(h, size=10)
    loss = L.softmax_with_cross_entropy(logits, label)
    avg_loss = L.mean(loss)
    acc = L.accuracy(logits, label)
    return avg_loss, acc


def _synthetic_batch(rng, bs=64):
    x = rng.standard_normal((bs, 784)).astype(np.float32)
    w = rng.standard_normal((784, 10)).astype(np.float32)
    y = np.argmax(x @ w, axis=1).astype(np.int64)[:, None]
    return x, y, w


def test_mnist_mlp_sgd_loss_decreases():
    rng = np.random.default_rng(0)
    avg_loss, acc = _build_mlp()
    opt = pt.optimizer.SGD(learning_rate=0.1)
    opt.minimize(avg_loss)

    exe = pt.Executor()
    exe.run(pt.default_startup_program())

    # fixed teacher so the task is learnable
    x, y, w = _synthetic_batch(rng, bs=128)
    losses = []
    for i in range(30):
        (loss_val,) = exe.run(
            pt.default_main_program(), feed={"img": x, "label": y}, fetch_list=[avg_loss]
        )
        losses.append(float(loss_val))
    assert losses[-1] < losses[0] * 0.7, losses


def test_mnist_mlp_adam_and_accuracy():
    rng = np.random.default_rng(1)
    avg_loss, acc = _build_mlp()
    opt = pt.optimizer.Adam(learning_rate=1e-3)
    opt.minimize(avg_loss)

    exe = pt.Executor()
    exe.run(pt.default_startup_program())
    x, y, _ = _synthetic_batch(rng, bs=128)
    first_acc = last = None
    for i in range(40):
        loss_val, acc_val = exe.run(
            pt.default_main_program(),
            feed={"img": x, "label": y},
            fetch_list=[avg_loss, acc],
        )
        if first_acc is None:
            first_acc = float(acc_val)
        last = (float(loss_val), float(acc_val))
    assert last[1] > max(first_acc, 0.3), (first_acc, last)


def test_eval_program_clone_for_test():
    avg_loss, acc = _build_mlp()
    test_prog = pt.default_main_program().clone(for_test=True)
    opt = pt.optimizer.SGD(learning_rate=0.1)
    opt.minimize(avg_loss)

    exe = pt.Executor()
    exe.run(pt.default_startup_program())
    rng = np.random.default_rng(2)
    x, y, _ = _synthetic_batch(rng, bs=32)
    (train_loss,) = exe.run(
        pt.default_main_program(), feed={"img": x, "label": y}, fetch_list=[avg_loss]
    )
    (test_loss,) = exe.run(test_prog, feed={"img": x, "label": y}, fetch_list=[avg_loss.name])
    assert np.isfinite(test_loss)
