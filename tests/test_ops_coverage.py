"""OpTest coverage for ops previously riding on vjp faith (VERDICT weak #8):
conv2d_transpose, group/instance_norm, scatter/gather_nd, strided_slice,
sequence ops' gradients, and numpy-trajectory checks for the long-tail
optimizers (Ftrl, Adadelta, DecayedAdagrad, RMSProp)."""
import numpy as np

import paddle_tpu as pt
from paddle_tpu import layers as L

from op_test import OpTest


class TestConv2DTranspose(OpTest):
    def test_output_and_grad(self):
        rng = np.random.default_rng(0)
        x = rng.standard_normal((2, 3, 4, 4)).astype(np.float32)
        w = rng.standard_normal((3, 5, 3, 3)).astype(np.float32)  # I, O, kh, kw

        # numpy reference: scatter each input pixel times the kernel
        N, C, H, W = x.shape
        _, O, kh, kw = w.shape
        stride, pad = 2, 1
        OH = (H - 1) * stride - 2 * pad + kh
        OW = (W - 1) * stride - 2 * pad + kw
        full = np.zeros((N, O, OH + 2 * pad, OW + 2 * pad), np.float32)
        for n in range(N):
            for c in range(C):
                for i in range(H):
                    for j in range(W):
                        full[n, :, i * stride:i * stride + kh,
                             j * stride:j * stride + kw] += (
                            x[n, c, i, j] * w[c])
        expect = full[:, :, pad:pad + OH, pad:pad + OW]

        self.setup("conv2d_transpose",
                   {"Input": [("x", x)], "Filter": [("w", w)]},
                   {"Output": expect},
                   {"strides": [stride, stride], "paddings": [pad, pad]})
        self.check_output(atol=1e-4, rtol=1e-4)
        self.check_grad(["x", "w"], "Output", max_relative_error=1e-2)


class TestGroupNorm(OpTest):
    def test_output_and_grad(self):
        rng = np.random.default_rng(1)
        x = rng.standard_normal((2, 6, 3, 3)).astype(np.float32)
        scale = rng.standard_normal(6).astype(np.float32)
        bias = rng.standard_normal(6).astype(np.float32)
        G, eps = 3, 1e-5
        xr = x.reshape(2, G, 2, 3, 3)
        mean = xr.mean(axis=(2, 3, 4), keepdims=True)
        var = xr.var(axis=(2, 3, 4), keepdims=True)
        norm = ((xr - mean) / np.sqrt(var + eps)).reshape(x.shape)
        expect = norm * scale[None, :, None, None] + bias[None, :, None, None]
        self.setup("group_norm",
                   {"X": [("x", x)], "Scale": [("scale", scale)],
                    "Bias": [("bias", bias)]},
                   {"Y": expect}, {"groups": G, "epsilon": eps})
        self.check_output(atol=1e-4, rtol=1e-4)
        self.check_grad(["x", "scale", "bias"], "Y",
                        max_relative_error=1e-2)


class TestInstanceNorm(OpTest):
    def test_output(self):
        rng = np.random.default_rng(2)
        x = rng.standard_normal((2, 4, 5, 5)).astype(np.float32)
        scale = np.abs(rng.standard_normal(4)).astype(np.float32)
        bias = rng.standard_normal(4).astype(np.float32)
        eps = 1e-5
        mean = x.mean(axis=(2, 3), keepdims=True)
        var = x.var(axis=(2, 3), keepdims=True)
        expect = ((x - mean) / np.sqrt(var + eps)
                  * scale[None, :, None, None] + bias[None, :, None, None])
        self.setup("instance_norm",
                   {"X": [("x", x)], "Scale": [("scale", scale)],
                    "Bias": [("bias", bias)]},
                   {"Y": expect}, {"epsilon": eps})
        self.check_output(atol=1e-4, rtol=1e-4)
        # no numeric grad check: sum(instance_norm(x)) is constant in x
        # (each channel's normalized values sum to 0), so the harness's
        # sum-reduced target has an identically-zero, degenerate gradient


class TestScatter(OpTest):
    def test_overwrite_and_grad(self):
        x = np.arange(20, dtype=np.float32).reshape(5, 4)
        idx = np.array([1, 3], np.int64)
        upd = -np.ones((2, 4), np.float32)
        expect = x.copy()
        expect[idx] = upd
        self.setup("scatter",
                   {"X": [("x", x)], "Ids": [("ids", idx)],
                    "Updates": [("upd", upd)]},
                   {"Out": expect}, {"overwrite": True})
        self.check_output()
        self.check_grad(["x", "upd"], "Out", no_grad_set={"ids"})


class TestGatherNd(OpTest):
    def test_output_and_grad(self):
        rng = np.random.default_rng(3)
        x = rng.standard_normal((3, 4, 5)).astype(np.float32)
        idx = np.array([[0, 1], [2, 3]], np.int64)
        expect = x[idx[:, 0], idx[:, 1]]
        self.setup("gather_nd",
                   {"X": [("x", x)], "Index": [("idx", idx)]},
                   {"Out": expect}, {})
        self.check_output()
        self.check_grad(["x"], "Out", no_grad_set={"idx"})


class TestStridedSlice(OpTest):
    def test_output_and_grad(self):
        rng = np.random.default_rng(4)
        x = rng.standard_normal((4, 8)).astype(np.float32)
        expect = x[1:4:2, 6:0:-2]
        self.setup("strided_slice", {"Input": [("x", x)]},
                   {"Out": expect},
                   {"axes": [0, 1], "starts": [1, 6], "ends": [4, 0],
                    "strides": [2, -2]})
        self.check_output()
        self.check_grad(["x"], "Out")


class TestSequencePoolGrad(OpTest):
    def test_average_grad(self):
        rng = np.random.default_rng(5)
        x = rng.standard_normal((3, 4, 2)).astype(np.float32)
        lens = np.array([2, 4, 3], np.int64)
        mask = (np.arange(4)[None, :] < lens[:, None]).astype(np.float32)
        expect = (x * mask[..., None]).sum(1) / lens[:, None]
        self.setup("sequence_pool",
                   {"X": [("x", x)], "Length": [("len", lens)]},
                   {"Out": expect}, {"pooltype": "AVERAGE"})
        self.check_output()
        self.check_grad(["x"], "Out", no_grad_set={"len"})


def _run_optimizer_trajectory(make_opt, np_update, steps=5):
    """Train one fc param; compare against a numpy re-implementation."""
    x = L.data(name="x", shape=[4], dtype="float32")
    y = L.data(name="y", shape=[1], dtype="float32")
    pred = L.fc(x, size=1, name="t", bias_attr=False)
    loss = L.mean(L.square_error_cost(pred, y))
    make_opt().minimize(loss)
    exe = pt.Executor()
    exe.run(pt.default_startup_program())
    rng = np.random.default_rng(0)
    w = np.asarray(pt.global_scope().find_var("t.w_0")).astype(np.float64)
    state = {}
    for _ in range(steps):
        xb = rng.standard_normal((8, 4)).astype(np.float32)
        yb = rng.standard_normal((8, 1)).astype(np.float32)
        # analytic grad of mean((xw - y)^2): 2/B * x^T (xw - y)
        g = (2.0 / len(xb)) * xb.T.astype(np.float64) @ (
            xb.astype(np.float64) @ w - yb.astype(np.float64)) / 1.0
        w = np_update(w, g, state)
        exe.run(pt.default_main_program(), feed={"x": xb, "y": yb},
                fetch_list=[loss])
    got = np.asarray(pt.global_scope().find_var("t.w_0"))
    np.testing.assert_allclose(got, w, rtol=2e-4, atol=2e-5)


def test_ftrl_matches_numpy():
    lr, l1, l2, power = 0.05, 0.01, 0.02, -0.5

    def update(w, g, s):
        sq = s.setdefault("sq", np.zeros_like(w))
        lin = s.setdefault("lin", np.zeros_like(w))
        new_sq = sq + g * g
        sigma = (new_sq ** -power - sq ** -power) / lr
        lin += g - sigma * w
        s["sq"] = new_sq
        pre = new_sq ** -power / lr + 2 * l2
        w_new = np.where(np.abs(lin) > l1,
                         (np.sign(lin) * l1 - lin) / pre, 0.0)
        return w_new

    _run_optimizer_trajectory(
        lambda: pt.optimizer.Ftrl(lr, l1=l1, l2=l2, lr_power=power), update)


def test_adadelta_matches_numpy():
    lr, rho, eps = 1.0, 0.95, 1e-6

    def update(w, g, s):
        ag = s.setdefault("ag", np.zeros_like(w))
        ax = s.setdefault("ax", np.zeros_like(w))
        ag = rho * ag + (1 - rho) * g * g
        dx = -np.sqrt((ax + eps) / (ag + eps)) * g
        ax = rho * ax + (1 - rho) * dx * dx
        s["ag"], s["ax"] = ag, ax
        return w + lr * dx

    _run_optimizer_trajectory(
        lambda: pt.optimizer.Adadelta(lr, epsilon=eps, rho=rho), update)


def test_decayed_adagrad_matches_numpy():
    lr, decay, eps = 0.05, 0.9, 1e-6

    def update(w, g, s):
        m = s.setdefault("m", np.zeros_like(w))
        m = decay * m + (1 - decay) * g * g
        s["m"] = m
        return w - lr * g / (np.sqrt(m) + eps)

    _run_optimizer_trajectory(
        lambda: pt.optimizer.DecayedAdagrad(lr, decay=decay, epsilon=eps),
        update)


def test_rmsprop_matches_numpy():
    lr, rho, eps, mom = 0.01, 0.95, 1e-6, 0.9

    def update(w, g, s):
        ms = s.setdefault("ms", np.zeros_like(w))
        v = s.setdefault("v", np.zeros_like(w))
        ms = rho * ms + (1 - rho) * g * g
        v = mom * v + lr * g / np.sqrt(ms + eps)
        s["ms"], s["v"] = ms, v
        return w - v

    _run_optimizer_trajectory(
        lambda: pt.optimizer.RMSProp(lr, rho=rho, epsilon=eps, momentum=mom),
        update)
