"""Learned cost-model subsystem tests (ISSUE 15): the measurement store's
append/fail-open discipline, the hand features, deterministic training, the
NEW policy tier (exact DB hit > learned > analytic prior > default), the
confidence gate (holdout accuracy + feature-envelope extrapolation), the
corrupt/missing-model fail-open (warn ONCE, like the DB), cross-device
transfer, bounded online exploration (promotion evidence schema, pacing,
the executor hook), and the gate.py --costmodel check on the committed
artifacts."""
import json
import os
import warnings

import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import layers as L
from paddle_tpu import tuning
from paddle_tpu.tuning import learned
from paddle_tpu.tuning import policy as _policy
from paddle_tpu.tuning.learned import explore, features, model, store


@pytest.fixture
def lenv(tmp_path):
    """Scratch tuner environment: DB + measurement store + model paths all
    under tmp, consult mode, every cache/counter reset on both sides."""
    snap = pt.flags.all_flags()
    paths = {
        "db": str(tmp_path / "db.json"),
        "meas": str(tmp_path / "meas.jsonl"),
        "model": str(tmp_path / "model.json"),
    }
    pt.flags.set_flags({"tuning_mode": "consult", "tuning_db": paths["db"],
                        "tuning_measurements": paths["meas"],
                        "tuning_model": paths["model"]})
    _reset()
    yield paths
    pt.flags.set_flags(snap)
    _reset()


def _reset():
    tuning.invalidate_db_cache()
    tuning.reset_provenance()
    learned.invalidate_model_cache()
    learned.reset_counters()
    explore.reset_state()
    _policy._seen_candidates.clear()


def _conv_sk(n, hw, cin, cout, k=3):
    return tuning.conv_key(n, hw, hw, cin, cout, k, k, (1, 1), (1, 1),
                           "NHWC")


def _synthetic_records(dk):
    """A conv grid whose arm times are EXACT log-linear functions of the
    hand features: direct = flops * 1e-10, igemm = direct * (K/288)^-0.5
    (K = cin*kh*kw), so igemm wins iff cin > 32 and the ridge can fit the
    surface perfectly. The (hw=8, cin=3, cout=4) key is deliberately left
    OUT so e2e tests can query it as a genuinely unseen, in-envelope
    shape."""
    recs = []
    for hw in (8, 16):
        for cin in (3, 8, 16, 32, 64, 128):
            for cout in (4, 16, 64):
                if (hw, cin, cout) == (8, 3, 4):
                    continue
                sk = _conv_sk(4, hw, cin, cout)
                flops = 2.0 * (4 * hw * hw) * (cin * 9) * cout
                direct = flops * 1e-10
                igemm = direct * (cin * 9 / 288.0) ** -0.5
                for arm, t in (("direct", direct), ("igemm", igemm)):
                    recs.append({"schema": store.STORE_SCHEMA,
                                 "op": "conv2d", "shape_key": sk,
                                 "dtype": "float32", "device_kind": dk,
                                 "arm": arm, "median_s": t})
    return recs


def _trained(lenv, dk=None):
    dk = dk or tuning.device_kind()
    m = learned.train_model(_synthetic_records(dk), seed=0)
    learned.save_model(m, lenv["model"])
    learned.invalidate_model_cache()
    return m


# -- the measurement store ---------------------------------------------------

def test_store_roundtrip_and_median_from_windows(lenv):
    assert store.record("conv2d", "sk", "float32", "cpu", "direct",
                        windows_s=[0.003, 0.001, 0.002], source="test")
    assert store.record("conv2d", "sk", "float32", "cpu", "igemm",
                        windows_s=[0.004], median_s=0.004, band=0.01,
                        source="test")
    recs = list(store.iter_records(lenv["meas"]))
    assert len(recs) == 2
    r = recs[0]
    assert r["schema"] == store.STORE_SCHEMA
    assert r["median_s"] == pytest.approx(0.002)  # computed from windows
    assert r["min_s"] == pytest.approx(0.001)
    assert r["source"] == "test"
    assert "host" in r and r["host"]["cpus"] >= 1


def test_store_corrupt_lines_fail_open(lenv):
    store.record("conv2d", "sk", "float32", "cpu", "direct",
                 windows_s=[0.001], source="test")
    with open(lenv["meas"], "a") as f:
        f.write("{not json\n")
        f.write(json.dumps({"schema": 999, "op": "x"}) + "\n")
        f.write(json.dumps(["a", "list"]) + "\n")
    store.record("conv2d", "sk2", "float32", "cpu", "igemm",
                 windows_s=[0.002], source="test")
    recs = list(store.iter_records(lenv["meas"]))
    assert [r["shape_key"] for r in recs] == ["sk", "sk2"]


def test_store_missing_file_and_unwritable_never_raise(lenv):
    assert list(store.iter_records(str("/nonexistent/meas.jsonl"))) == []
    assert store.record("conv2d", "sk", "float32", "cpu", "direct",
                        windows_s=[0.001], source="test",
                        path="/proc/definitely/not/writable.jsonl") is False


def test_store_flag_gating(lenv):
    # auto (default): tools record, runtime only in sweep/explore
    assert store.recording_enabled(tool=True)
    assert not store.recording_enabled()           # consult-mode runtime
    pt.flags.set_flags({"tuning_mode": "sweep"})
    assert store.recording_enabled()
    pt.flags.set_flags({"tuning_mode": "explore"})
    assert store.recording_enabled()
    pt.flags.set_flags({"tuning_mode": "consult", "tuning_record": "on"})
    assert store.recording_enabled()
    pt.flags.set_flags({"tuning_record": "off"})
    assert not store.recording_enabled(tool=True)  # off is absolute
    pt.flags.set_flags({"tuning_record": "auto", "tuning_measurements": "",
                        "tuning_db": ""})
    assert not store.recording_enabled(tool=True)  # no path resolves


def test_store_record_measured_splits_canonical_key(lenv):
    key = f"conv2d|{_conv_sk(4, 8, 3, 4)}|float32|cpu"
    store.record_measured(key, {
        "direct": {"median_s": 1.0, "min_s": 0.9, "windows_s": [1.0],
                   "band": 0.02},
        "igemm": {"median_s": 0.5, "min_s": 0.5, "windows_s": [0.5],
                  "band": 0.01}}, source="explore")
    recs = list(store.iter_records(lenv["meas"]))
    assert sorted(r["arm"] for r in recs) == ["direct", "igemm"]
    assert all(r["op"] == "conv2d" and r["source"] == "explore"
               and r["device_kind"] == "cpu" for r in recs)


# -- features ----------------------------------------------------------------

def test_featurize_sanity():
    for op, sk in [("conv2d", _conv_sk(4, 8, 3, 4)),
                   ("attention", tuning.attention_key(2, 12, 128, 128, 64,
                                                      False)),
                   ("epilogue", "kind=bn rows=128 c=64 ch=last act=relu "
                                "res=0"),
                   ("xent", "rows=128 v=32000")]:
        v = features.featurize(op, sk, "float32")
        assert isinstance(v, list) and len(v) >= 5
        assert all(np.isfinite(x) for x in v)
    assert features.featurize("collective", "whatever", "float32") is None
    assert features.featurize("conv2d", "un parseable garbage",
                              "float32") is None
    assert features.decision_field("conv2d") == "lowering"
    assert features.decision_field("attention") == "backend"


# -- training + prediction ---------------------------------------------------

def test_training_deterministic_byte_identical(lenv, tmp_path):
    recs = _synthetic_records("cpu")
    m1 = learned.train_model(recs, seed=0)
    m2 = learned.train_model(recs, seed=0)
    p1, p2 = str(tmp_path / "m1.json"), str(tmp_path / "m2.json")
    learned.save_model(m1, p1)
    learned.save_model(m2, p2)
    assert open(p1, "rb").read() == open(p2, "rb").read()
    assert m1["schema"] == model.MODEL_SCHEMA
    # no stray temp files after the atomic write
    assert sorted(os.listdir(tmp_path)) >= ["m1.json", "m2.json"]


def test_model_learns_the_arm_surface(lenv):
    m = _trained(lenv, dk="cpu")
    grp = m["groups"]["conv2d|cpu"]
    assert grp["holdout"]["rank_acc"] >= model.RANK_ACC_FLOOR
    # unseen in-envelope keys on both sides of the igemm/direct boundary
    t_lo, _ = learned.predict_times(m, "conv2d", _conv_sk(4, 16, 8, 32),
                                    "float32", "cpu")
    t_hi, _ = learned.predict_times(m, "conv2d", _conv_sk(4, 16, 128, 32),
                                    "float32", "cpu")
    assert t_lo is not None and t_lo["direct"] < t_lo["igemm"]
    assert t_hi is not None and t_hi["igemm"] < t_hi["direct"]


def test_confidence_gate_rejects_10x_beyond_envelope(lenv):
    m = _trained(lenv, dk="cpu")
    # cin 10x past the widest trained channel count: extrapolation territory
    times, info = learned.predict_times(m, "conv2d",
                                        _conv_sk(4, 16, 1280, 64),
                                        "float32", "cpu")
    assert times is None
    assert info["reason"] == "envelope"


def test_cross_device_transfer_reuses_cpu_ranking(lenv):
    m = _trained(lenv, dk="cpu")
    times, info = learned.predict_times(m, "conv2d", _conv_sk(4, 16, 128, 64),
                                        "float32", "TPU v99")
    assert times is not None
    assert info.get("transfer_from") == "conv2d|cpu"
    assert times["igemm"] < times["direct"]  # ranking carried over


def test_eval_model_rescores_recorded_holdout(lenv):
    recs = _synthetic_records("cpu")
    m = learned.train_model(recs, seed=0)
    ev = learned.eval_model(m, recs)
    g = ev["groups"]["conv2d|cpu"]
    assert g["n"] == len(m["groups"]["conv2d|cpu"]["holdout_keys"])
    assert g["rank_acc"] == m["groups"]["conv2d|cpu"]["holdout"]["rank_acc"]
    assert g["analytic_rank_acc"] is not None


# -- the policy tier ---------------------------------------------------------

def test_tier_ordering_db_beats_learned_beats_analytic(lenv):
    dk = tuning.device_kind()
    _trained(lenv)
    sk = _conv_sk(4, 16, 128, 64)  # unseen, in envelope; model says igemm
    key = tuning.canonical_key("conv2d", sk, "float32", dk)
    # 1) no DB entry: the learned tier answers
    d, tier = tuning.decide("conv2d", key,
                            prior=lambda: {"lowering": "direct"},
                            default={"lowering": "direct"})
    assert (d, tier) == ({"lowering": "igemm"}, "learned")
    # 2) a swept DB entry outranks the model
    db = tuning.TuningDB(lenv["db"])
    db.put(key, {"lowering": "direct"}, source="swept")
    db.save(lenv["db"])
    tuning.invalidate_db_cache()
    d, tier = tuning.decide("conv2d", key,
                            prior=lambda: {"lowering": "direct"},
                            default={"lowering": "direct"})
    assert (d, tier) == ({"lowering": "direct"}, "db")
    # 3) out-of-envelope key falls through to the analytic prior
    far = tuning.canonical_key("conv2d", _conv_sk(4, 16, 1280, 64),
                               "float32", dk)
    d, tier = tuning.decide("conv2d", far,
                            prior=lambda: {"lowering": "direct"},
                            default={"lowering": "direct"})
    assert tier == "analytic"
    # 4) no prior either: conservative default
    d, tier = tuning.decide("conv2d", far, prior=lambda: None,
                            default={"lowering": "direct"})
    assert tier == "default"
    snap = tuning.provenance_snapshot()
    assert snap["per_op"]["conv2d"] == {"db": 1, "learned": 1,
                                        "analytic": 1, "default": 1}
    assert snap["learned"] == 1
    assert snap["tuned_rate"] == pytest.approx(0.5)  # (db+learned)/4
    ls = learned.snapshot()
    assert ls["predictions"] == 1
    assert ls["fallback_reasons"].get("envelope", 0) >= 1


def test_learned_validate_rejection_falls_through(lenv):
    dk = tuning.device_kind()
    _trained(lenv)
    key = tuning.canonical_key("conv2d", _conv_sk(4, 16, 128, 64),
                               "float32", dk)
    d, tier = tuning.decide("conv2d", key,
                            prior=lambda: {"lowering": "direct"},
                            default={"lowering": "direct"},
                            validate=lambda dec: dec == {"lowering":
                                                         "direct"})
    assert tier == "analytic"
    assert learned.snapshot()["fallback_reasons"].get("validate") == 1


def test_missing_model_is_silent_analytic(lenv):
    # lenv points tuning_model at a path that was never written
    key = tuning.canonical_key("conv2d", _conv_sk(4, 16, 128, 64),
                               "float32", tuning.device_kind())
    with warnings.catch_warnings(record=True) as w:
        warnings.simplefilter("always")
        d, tier = tuning.decide("conv2d", key,
                                prior=lambda: {"lowering": "direct"},
                                default={"lowering": "direct"})
    assert tier == "analytic"
    assert [x for x in w if "cost model" in str(x.message)] == []
    assert learned.snapshot()["attempts"] == 0  # a miss is not an attempt


def test_corrupt_model_warns_once_then_fails_open(lenv):
    with open(lenv["model"], "w") as f:
        f.write("{definitely not json")
    key = tuning.canonical_key("conv2d", _conv_sk(4, 16, 128, 64),
                               "float32", tuning.device_kind())
    with warnings.catch_warnings(record=True) as w:
        warnings.simplefilter("always")
        for _ in range(3):
            d, tier = tuning.decide("conv2d", key,
                                    prior=lambda: {"lowering": "direct"},
                                    default={"lowering": "direct"})
            assert tier == "analytic"
    msgs = [x for x in w if "cost model" in str(x.message)]
    assert len(msgs) == 1
    assert "falling back to the analytic" in str(msgs[0].message)


def test_model_removal_mid_session_fails_open(lenv):
    dk = tuning.device_kind()
    _trained(lenv)
    key = tuning.canonical_key("conv2d", _conv_sk(4, 16, 128, 64),
                               "float32", dk)
    _, tier = tuning.decide("conv2d", key,
                            prior=lambda: {"lowering": "direct"},
                            default={"lowering": "direct"})
    assert tier == "learned"
    os.remove(lenv["model"])
    learned.invalidate_model_cache()
    with warnings.catch_warnings(record=True) as w:
        warnings.simplefilter("always")
        _, tier = tuning.decide("conv2d", key,
                                prior=lambda: {"lowering": "direct"},
                                default={"lowering": "direct"})
    assert tier == "analytic"
    assert [x for x in w if "cost model" in str(x.message)] == []


# -- bounded online exploration ----------------------------------------------

def _fake_measured(times):
    def _m(fn):
        t = times[fn]
        return {"median_s": t, "min_s": t, "windows_s": [t], "band": 0.0}
    return _m


def _put_candidate(lenv, key, decision):
    db = tuning.TuningDB(lenv["db"])
    db.put(key, decision, source="candidate")
    db.save(lenv["db"])
    tuning.invalidate_db_cache()
    return key


def test_explore_promotes_winner_with_sweep_evidence(lenv, monkeypatch):
    pt.flags.set_flags({"tuning_mode": "explore"})
    dk = tuning.device_kind()
    key = _put_candidate(
        lenv, tuning.canonical_key("conv2d", _conv_sk(4, 8, 3, 4),
                                   "float32", dk),
        {"lowering": "direct"})
    monkeypatch.setattr(explore, "_build_arms",
                        lambda op, sk, dt: {"direct": "d", "igemm": "g"})
    monkeypatch.setattr(explore, "_measure",
                        _fake_measured({"d": 1.0, "g": 0.5}))
    out = explore.explore_one()
    assert out is not None and out["verdict"] == "keep"
    assert out["decision"] == "igemm"
    entry = tuning.TuningDB(lenv["db"]).lookup(key)
    assert entry["source"] == "swept"
    assert entry["decision"] == {"lowering": "igemm"}
    # the promotion carries the SAME evidence schema offline sweeps write
    assert entry["measured"] == {"direct": {"median_s": 1.0, "band": 0.0},
                                 "igemm": {"median_s": 0.5, "band": 0.0}}
    assert learned.snapshot()["promotions"] == 1
    # the raw windows landed in the measurement store too
    srcs = {r["source"] for r in store.iter_records(lenv["meas"])}
    assert srcs == {"explore"}
    # a probed key is never re-probed in-process
    assert explore.explore_one() is None


def test_explore_tie_keeps_candidate_with_evidence(lenv, monkeypatch):
    pt.flags.set_flags({"tuning_mode": "explore"})
    dk = tuning.device_kind()
    key = _put_candidate(
        lenv, tuning.canonical_key("conv2d", _conv_sk(4, 8, 3, 4),
                                   "float32", dk),
        {"lowering": "direct"})
    monkeypatch.setattr(explore, "_build_arms",
                        lambda op, sk, dt: {"direct": "d", "igemm": "g"})
    monkeypatch.setattr(explore, "_measure",
                        _fake_measured({"d": 1.0, "g": 0.98}))  # inside 5%
    out = explore.explore_one()
    assert out["verdict"] == "tie"
    entry = tuning.TuningDB(lenv["db"]).lookup(key)
    assert entry["source"] == "candidate"          # the candidate stands
    assert entry["decision"] == {"lowering": "direct"}
    assert entry["measured"]["igemm"]["median_s"] == 0.98  # ...with data
    assert learned.snapshot()["promotions"] == 0


def test_explore_retires_slower_candidate(lenv, monkeypatch):
    pt.flags.set_flags({"tuning_mode": "explore"})
    dk = tuning.device_kind()
    key = _put_candidate(
        lenv, tuning.canonical_key("conv2d", _conv_sk(4, 8, 3, 4),
                                   "float32", dk),
        {"lowering": "igemm"})
    monkeypatch.setattr(explore, "_build_arms",
                        lambda op, sk, dt: {"direct": "d", "igemm": "g"})
    monkeypatch.setattr(explore, "_measure",
                        _fake_measured({"d": 0.5, "g": 1.0}))
    out = explore.explore_one()
    assert out["verdict"] == "keep"  # direct beats the igemm base
    entry = tuning.TuningDB(lenv["db"]).lookup(key)
    assert entry["source"] == "swept"
    assert entry["decision"] == {"lowering": "direct"}


def test_maybe_explore_pacing_and_mode_gate(lenv, monkeypatch):
    calls = []
    monkeypatch.setattr(explore, "explore_one",
                        lambda: calls.append(1) or None)
    # consult mode: a no-op, no step counting
    for _ in range(10):
        assert explore.maybe_explore() is None
    assert calls == []
    pt.flags.set_flags({"tuning_mode": "explore",
                        "tuning_explore_every": 3})
    for _ in range(9):
        explore.maybe_explore()
    assert len(calls) == 3  # steps 3, 6, 9
    pt.flags.set_flags({"tuning_explore_every": 0})
    explore.maybe_explore()
    assert len(calls) == 3  # every<=0 disables


def test_explore_real_probe_end_to_end(lenv):
    """No monkeypatching: a real candidate conv key is rebuilt, timed and
    resolved on this box; whatever the verdict, the entry carries measured
    evidence and the store grew explore rows."""
    pt.flags.set_flags({"tuning_mode": "explore"})
    dk = tuning.device_kind()
    key = _put_candidate(
        lenv, tuning.canonical_key(
            "conv2d", tuning.conv_key(2, 8, 8, 3, 4, 3, 3, (1, 1), (1, 1),
                                      "NHWC"), "float32", dk),
        {"lowering": "direct"})
    out = explore.explore_one()
    assert out is not None
    assert out["verdict"] in ("keep", "retire", "tie")
    entry = tuning.TuningDB(lenv["db"]).lookup(key)
    assert set(entry["measured"]) == {"direct", "igemm"}
    for ev in entry["measured"].values():
        assert ev["median_s"] > 0 and ev["band"] >= 0
    recs = list(store.iter_records(lenv["meas"]))
    assert {r["source"] for r in recs} == {"explore"}
    assert {r["arm"] for r in recs} == {"direct", "igemm"}


def test_executor_step_drives_explore_hook(lenv, monkeypatch):
    pt.flags.set_flags({"tuning_mode": "explore",
                        "tuning_explore_every": 1})
    calls = []
    monkeypatch.setattr(explore, "explore_one",
                        lambda: calls.append(1) or None)
    x = L.data(name="x", shape=[4], dtype="float32")
    y = L.scale(x, scale=2.0)
    exe = pt.Executor()
    exe.run(pt.default_startup_program())
    # the probe rides the ASYNC dispatch path's idle gap (run_async), not
    # the synchronous run()
    exe.run_async(pt.default_main_program(),
                  feed={"x": np.ones((2, 4), np.float32)}, fetch_list=[y])
    exe.wait()
    assert len(calls) >= 1


# -- candidate evidence (the db.py satellite) --------------------------------

def test_db_evidence_schema_and_candidate_measured(lenv):
    measured = {"direct": {"median_s": 1.0, "min_s": 0.9,
                           "windows_s": [1.0, 0.9], "band": 0.11},
                "igemm": {"median_s": 0.5, "band": 0.02},
                "broken": "not a dict", "empty": {"median_s": None}}
    ev = tuning.evidence(measured)
    assert ev == {"direct": {"median_s": 1.0, "band": 0.11},
                  "igemm": {"median_s": 0.5, "band": 0.02}}
    db = tuning.TuningDB(lenv["db"])
    db.put("k", {"lowering": "direct"}, source="candidate", measured=ev)
    db.save(lenv["db"])
    assert tuning.TuningDB(lenv["db"]).lookup("k")["measured"] == ev


# -- end to end + observability + gate ---------------------------------------

def test_e2e_consult_unseen_shape_uses_learned_tier(lenv):
    """The acceptance run: a consult-mode model whose conv key is NOT in
    the DB resolves from the learned tier at trace time and trains
    finite; removing the model mid-session falls back to analytic with
    zero crashes (covered per-decide by test_model_removal...)."""
    _trained(lenv)
    img = L.data(name="img", shape=[8, 8, 3], dtype="float32")
    label = L.data(name="label", shape=[1], dtype="int64")
    c = L.conv2d(img, num_filters=4, filter_size=3, padding=1,
                 data_format="NHWC")
    p = L.pool2d(c, global_pooling=True, pool_type="avg",
                 data_format="NHWC")
    loss = L.reduce_mean(
        L.softmax_with_cross_entropy(L.fc(p, size=10), label))
    pt.optimizer.SGD(0.1).minimize(loss)
    exe = pt.Executor()
    exe.run(pt.default_startup_program())
    tuning.reset_provenance()
    rng = np.random.default_rng(0)
    feed = {"img": rng.standard_normal((4, 8, 8, 3)).astype(np.float32),
            "label": rng.integers(0, 10, (4, 1)).astype(np.int64)}
    (lv,) = exe.run(pt.default_main_program(), feed=feed, fetch_list=[loss])
    assert np.isfinite(float(np.asarray(lv)))
    snap = tuning.provenance_snapshot()
    assert snap["per_op"].get("conv2d", {}).get("learned", 0) >= 1


def test_schema_declares_learned_metrics():
    from paddle_tpu.observability import schema
    for name in ("tuning.learned.predictions", "tuning.learned.fallbacks",
                 "tuning.learned.explore_promotions"):
        assert name in schema.DECLARED_NAMES


def test_sweep_conv_feeds_the_store_with_evidence(lenv, tmp_path):
    from tools import tune
    db = tuning.TuningDB(str(tmp_path / "swept.json"))
    shapes = [("tiny", 2, 8, 8, 3, 4, 3, 3, (1, 1), [(1, 1), (1, 1)],
               (1, 1))]
    tune.sweep_conv(db, shapes, "float32", iters=1, passes=2, band=0.05)
    key = tuning.canonical_key(
        "conv2d", tuning.conv_key(2, 8, 8, 3, 4, 3, 3, (1, 1), (1, 1),
                                  "NHWC"), "float32", tuning.device_kind())
    entry = db.lookup(key)
    assert entry["source"] == "swept"
    # swept entries carry the shared evidence schema...
    for ev in entry["measured"].values():
        assert set(ev) == {"median_s", "band"}
    # ...and the raw windows landed in the measurement store
    recs = [r for r in store.iter_records(lenv["meas"])
            if r["op"] == "conv2d"]
    assert {r["arm"] for r in recs} >= {"direct", "igemm"}
    assert all(r["windows_s"] for r in recs)


def test_gate_costmodel_on_committed_artifacts():
    """The committed COSTMODEL_cpu.json must beat the analytic prior on
    the committed dataset's recorded holdout keys — the PR's acceptance
    line, enforced exactly as `python tools/gate.py --costmodel` runs
    it."""
    from tools import gate
    data = os.path.join(gate.REPO, gate.COSTMODEL_DATA)
    mdl = os.path.join(gate.REPO, gate.COSTMODEL_MODEL)
    if not (os.path.exists(data) and os.path.exists(mdl)):
        pytest.skip("committed costmodel artifacts absent")
    assert gate.check_costmodel() == 0
    ev = learned.eval_model(learned.load_model(mdl),
                            list(learned.iter_records(data)))
    for g in ev["groups"].values():
        assert g["rank_acc"] >= g["analytic_rank_acc"]


def test_gate_costmodel_fails_on_corrupt_model(tmp_path):
    from tools import gate
    data = os.path.join(gate.REPO, gate.COSTMODEL_DATA)
    if not os.path.exists(data):
        pytest.skip("committed costmodel dataset absent")
    bad = str(tmp_path / "bad_model.json")
    with open(bad, "w") as f:
        f.write("{nope")
    assert gate.check_costmodel(data_path=data, model_path=bad) == 1
