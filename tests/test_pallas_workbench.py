"""Pallas kernel workbench (ISSUE 9): substrate, fused epilogue, short-seq
attention, tuner wiring, and the registry lint.

The kernels run through the Pallas interpreter on CPU (module INTERPRET
flags), pinned against the XLA references that define their numerics —
fp32 at rtol 1e-5, a bf16 arm at bf16-rounding tolerance, masked/ragged
rows, both layouts. The dispatch tests prove the r5 contract: kernels ship
off by default, a swept DB verdict turns them on per shape, and a verdict
the platform cannot honor degrades to the reference at dispatch instead of
erroring.
"""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

import paddle_tpu as pt
from paddle_tpu import flags as pt_flags
from paddle_tpu import layers as L
from paddle_tpu import tuning
from paddle_tpu.ops.pallas_kernels import epilogue as ep
from paddle_tpu.ops.pallas_kernels import short_attention as sa
from paddle_tpu.ops.pallas_kernels import workbench as wb

rng = np.random.default_rng(0)


@pytest.fixture
def interpret(monkeypatch):
    monkeypatch.setattr(ep, "INTERPRET", True)
    monkeypatch.setattr(sa, "INTERPRET", True)
    yield


def _f32(*shape):
    return jnp.asarray(rng.standard_normal(shape).astype(np.float32))


# ---------------------------------------------------------------------------
# workbench substrate
# ---------------------------------------------------------------------------


def test_workbench_helpers():
    # compiler_params resolves on this jax version (the shim IS the fix for
    # the pre-existing test_pallas_attention env failures)
    assert wb.compiler_params(("parallel",)) is not None
    assert wb.sublanes(jnp.float32) == 8 and wb.sublanes(jnp.bfloat16) == 16
    assert wb.round_up(129, 128) == 256
    # pick_block: largest fitting divisor, sublane multiples preferred
    assert wb.pick_block(1024, 1024) == 1024  # 1024 rows * 1024 B fits 3 MB
    tr = wb.pick_block(4096, 4096)
    assert 4096 % tr == 0 and tr * 4096 <= wb.VMEM_BUDGET
    assert wb.pick_block(7, 10) == 7              # whole extent fits
    assert wb.pick_block(7, wb.VMEM_BUDGET) == 1  # prime, over budget
    gh = wb.fit_heads(12, wb.VMEM_BUDGET // 3)
    assert 12 % gh == 0


def test_kernel_registry_lint():
    """The tier-1 spelling of `tools/gate.py --kernels`: every registered
    kernel carries an XLA reference, a shape gate, a wired tuning decision
    op, and an equivalence test that exists."""
    import tools.gate as gate

    assert gate.check_kernel_registry() == 0


# ---------------------------------------------------------------------------
# fused epilogue kernels
# ---------------------------------------------------------------------------


def test_bn_apply_act_matches_reference(interpret):
    """fp32 rtol 1e-5 equivalence vs the XLA reference: both layouts, with
    and without residual, identity and relu."""
    C = 16
    s, b, m = _f32(C), _f32(C), _f32(C)
    v = jnp.asarray((np.abs(rng.standard_normal(C)) + 0.5)
                    .astype(np.float32))
    for channel_last, shape in ((True, (6, 4, 4, C)), (False, (4, C, 3, 5))):
        x = _f32(*shape)
        res = _f32(*shape)
        for act in ("identity", "relu"):
            for r in (None, res):
                got = ep.bn_apply_act(x, s, b, m, v, act=act, residual=r,
                                      channel_last=channel_last)
                ref = ep.bn_apply_act_reference(
                    x, s, b, m, v, act=act, residual=r,
                    channel_last=channel_last)
                np.testing.assert_allclose(got, ref, rtol=1e-5, atol=1e-5)


def test_bn_apply_act_bf16_arm(interpret):
    """The AMP arm: bf16 operands, fp32 kernel math, bf16-rounding
    tolerance vs the reference (which follows the same cast discipline)."""
    C = 16
    x = _f32(4, 8, C).astype(jnp.bfloat16)
    res = _f32(4, 8, C).astype(jnp.bfloat16)
    s, b, m = _f32(C), _f32(C), _f32(C)
    v = jnp.asarray((np.abs(rng.standard_normal(C)) + 0.5)
                    .astype(np.float32))
    got = ep.bn_apply_act(x, s, b, m, v, act="relu", residual=res)
    ref = ep.bn_apply_act_reference(x, s, b, m, v, act="relu", residual=res)
    assert got.dtype == jnp.bfloat16
    np.testing.assert_allclose(np.asarray(got, np.float32),
                               np.asarray(ref, np.float32),
                               rtol=2e-2, atol=2e-2)


def test_bn_apply_act_grads_match(interpret):
    """The fused Pallas backward (dx + two partial-sum planes) matches the
    XLA reference's derived grads for every differentiable input."""
    C = 8
    x, res = _f32(3, C, 4, 4), _f32(3, C, 4, 4)
    s, b, m = _f32(C), _f32(C), _f32(C)
    v = jnp.asarray((np.abs(rng.standard_normal(C)) + 0.5)
                    .astype(np.float32))

    def loss(fn):
        def f(x, s, b, m, v, r):
            return jnp.sum(jnp.square(fn(x, s, b, m, v, act="relu",
                                         residual=r, channel_last=False)))
        return jax.grad(f, argnums=(0, 1, 2, 3, 4, 5))(x, s, b, m, v, res)

    for gk, gr, name in zip(loss(ep.bn_apply_act),
                            loss(ep.bn_apply_act_reference),
                            "x scale bias mean inv residual".split()):
        np.testing.assert_allclose(gk, gr, rtol=1e-4, atol=1e-4,
                                   err_msg=name)


def test_layer_norm_act_matches_reference(interpret):
    x2 = _f32(24, 64)
    s, b = _f32(64), _f32(64)
    for act in ("identity", "relu"):
        got = ep.layer_norm_act(x2, s, b, eps=1e-5, act=act)
        ref = ep.layer_norm_act_reference(x2, s, b, eps=1e-5, act=act)
        np.testing.assert_allclose(got, ref, rtol=1e-5, atol=1e-5)
    # no-affine form (scale/bias default 1/0)
    got = ep.layer_norm_act(x2)
    ref = ep.layer_norm_act_reference(x2, None, None)
    np.testing.assert_allclose(got, ref, rtol=1e-5, atol=1e-5)


def test_layer_norm_act_grads_match(interpret):
    x2, s, b = _f32(16, 32), _f32(32), _f32(32)

    def g(fn):
        return jax.grad(lambda x, s, b: jnp.sum(jnp.square(
            fn(x, s, b))), argnums=(0, 1, 2))(x2, s, b)

    gk = g(lambda x, s, b: ep.layer_norm_act(x, s, b, act="relu"))
    gr = g(lambda x, s, b: ep.layer_norm_act_reference(x, s, b, act="relu"))
    np.testing.assert_allclose(gk[0], gr[0], rtol=1e-4, atol=1e-4)
    np.testing.assert_allclose(np.asarray(gk[1]).reshape(-1), gr[1],
                               rtol=1e-4, atol=1e-4)
    np.testing.assert_allclose(np.asarray(gk[2]).reshape(-1), gr[2],
                               rtol=1e-4, atol=1e-4)


# ---------------------------------------------------------------------------
# short-sequence (<=128) attention kernel
# ---------------------------------------------------------------------------


def test_short128_attention_matches_reference(interpret):
    """fp32 rtol 1e-5 vs the XLA reference at S = 128, 96 (non-lane-
    multiple) and 17, causal and not."""
    for S in (128, 96, 17):
        for causal in (False, True):
            q, k, v = (_f32(3, 4, S, 16) for _ in range(3))
            got = sa.short128_attention(q, k, v, causal=causal,
                                        sm_scale=0.25)
            ref = sa._reference(q, k, v, causal=causal, sm_scale=0.25)
            np.testing.assert_allclose(got, ref, rtol=1e-5, atol=1e-5)


def test_short128_attention_ragged_rows(interpret):
    """kv_lens masking: partial rows match the masked reference, a fully
    masked row (len 0 — scheduler padding) emits zeros, not NaN."""
    q, k, v = (_f32(4, 2, 64, 16) for _ in range(3))
    lens = jnp.asarray(np.array([64, 13, 1, 0], np.int32))
    got = sa.short128_attention(q, k, v, sm_scale=0.25, kv_lens=lens)
    ref = sa._reference(q, k, v, sm_scale=0.25, kv_lens=lens)
    np.testing.assert_allclose(got, ref, rtol=1e-5, atol=1e-5)
    assert np.all(np.isfinite(np.asarray(got, np.float32)))
    assert np.all(np.asarray(got)[3] == 0.0)


def test_short128_attention_grads_match(interpret):
    q, k, v = (_f32(2, 2, 48, 16) for _ in range(3))
    lens = jnp.asarray(np.array([48, 20], np.int32))

    def g(fn):
        return jax.grad(lambda q, k, v: jnp.sum(jnp.square(
            fn(q, k, v))), argnums=(0, 1, 2))(q, k, v)

    gk = g(lambda q, k, v: sa.short128_attention(
        q, k, v, causal=True, sm_scale=0.25, kv_lens=lens))
    gr = g(lambda q, k, v: sa._reference(
        q, k, v, causal=True, sm_scale=0.25, kv_lens=lens))
    for a, b in zip(gk, gr):
        np.testing.assert_allclose(a, b, rtol=1e-4, atol=1e-4)


def test_short128_attention_bf16_arm(interpret):
    q, k, v = (_f32(2, 2, 32, 16).astype(jnp.bfloat16) for _ in range(3))
    got = sa.short128_attention(q, k, v, sm_scale=0.25)
    ref = sa._reference(q, k, v, sm_scale=0.25)
    np.testing.assert_allclose(np.asarray(got, np.float32),
                               np.asarray(ref, np.float32),
                               rtol=2e-2, atol=2e-2)


def test_short128_supported_gate():
    ok = sa.short128_supported
    assert ok((2, 4, 128, 64), (2, 4, 128, 64))
    assert ok((2, 4, 17, 8), (2, 4, 17, 8))
    assert not ok((2, 4, 129, 64), (2, 4, 129, 64))   # past the VMEM row
    assert not ok((2, 4, 64, 64), (2, 4, 128, 64))    # cross-attention
    assert not ok((2, 4, 64, 12), (2, 4, 64, 12))     # dh not sublane-mult
    assert not ok((2, 4, 64, 64), (2, 4, 64, 64), bias=object())


# ---------------------------------------------------------------------------
# tuner wiring: default-off, swept keep, dispatch-time degradation
# ---------------------------------------------------------------------------


def _seed_db(tmp_path, key, decision):
    db = tuning.TuningDB(str(tmp_path / "db.json"))
    db.put(key, decision, source="swept", note="test")
    path = db.save()
    pt_flags.set_flags({"tuning_mode": "consult", "tuning_db": path})
    tuning.invalidate_db_cache()
    return path


@pytest.fixture
def tuner_cleanup():
    saved = {k: pt_flags.get_flag(k) for k in
             ("tuning_mode", "tuning_db", "pallas_epilogue",
              "attention_force_backend")}
    yield
    pt_flags.set_flags(saved)
    tuning.invalidate_db_cache()


def test_attention_swept_keep_engages_short128(tmp_path, interpret,
                                               tuner_cleanup):
    """A swept pallas_short128 keep routes flash_attention through the
    kernel for exactly that shape; the numbers match the XLA composition."""
    from paddle_tpu.ops.attention_ops import (_reference_attention,
                                              attention_backend,
                                              flash_attention)

    q, k, v = (_f32(2, 2, 48, 16) for _ in range(3))
    key = tuning.canonical_key(
        "attention", tuning.attention_key(2, 2, 48, 48, 16, False),
        "float32", tuning.device_kind())
    _seed_db(tmp_path, key, {"backend": "pallas_short128"})
    backend, tier = attention_backend(q.shape, k.shape, q.dtype)
    assert (backend, tier) == ("pallas_short128", "db")
    got = flash_attention(q, k, v, sm_scale=0.25)
    ref = _reference_attention(q, k, v, None, False, 0.25)
    np.testing.assert_allclose(got, ref, rtol=2e-5, atol=2e-5)


def test_swept_unrunnable_kernel_degrades_at_dispatch(tmp_path, monkeypatch,
                                                     tuner_cleanup):
    """The ISSUE 9 degradation clause: a swept verdict naming a kernel this
    platform cannot run (INTERPRET off, no TPU) is not obeyed blindly —
    dispatch falls back to the XLA reference without error."""
    from paddle_tpu.ops.attention_ops import (_reference_attention,
                                              attention_backend,
                                              flash_attention)

    monkeypatch.setattr(sa, "INTERPRET", False)
    q, k, v = (_f32(2, 2, 48, 16) for _ in range(3))
    key = tuning.canonical_key(
        "attention", tuning.attention_key(2, 2, 48, 48, 16, False),
        "float32", tuning.device_kind())
    _seed_db(tmp_path, key, {"backend": "pallas_short128"})
    backend, _tier = attention_backend(q.shape, k.shape, q.dtype)
    assert backend == "pallas_short128"  # the DB entry IS consulted...
    got = flash_attention(q, k, v, sm_scale=0.25)  # ...but degrades here
    ref = _reference_attention(q, k, v, None, False, 0.25)
    np.testing.assert_allclose(got, ref, rtol=0, atol=0)


def test_epilogue_swept_unrunnable_degrades(tmp_path, monkeypatch,
                                            tuner_cleanup):
    """Same clause for the epilogue lever: a swept pallas keep for a shape
    the platform cannot run falls back to the XLA composition inside the
    batch_norm lowering — bit-identical output, no error."""
    from paddle_tpu.ops.nn_ops import _bn_epilogue

    monkeypatch.setattr(ep, "INTERPRET", False)
    C = 8
    x = _f32(4, 6, C)
    s, b, m = _f32(C), _f32(C), _f32(C)
    v = jnp.asarray((np.abs(rng.standard_normal(C)) + 0.5)
                    .astype(np.float32))
    key = tuning.canonical_key(
        "epilogue", tuning.epilogue_key("bn", 24, C, "last", "relu", False),
        "float32", tuning.device_kind())
    _seed_db(tmp_path, key, {"backend": "pallas"})
    pt_flags.set_flags({"pallas_epilogue": "auto"})
    got = _bn_epilogue(x, s, b, m, v, "relu", None, channel_last=True,
                       bshape=[1, 1, C])
    ref = ep.bn_apply_act_reference(x, s, b, m, v, act="relu")
    # last-bit association difference only ((x-m)*inv*s vs (x-m)*(inv*s))
    np.testing.assert_allclose(got, ref, rtol=1e-5, atol=1e-6)


def test_epilogue_swept_keep_engages(tmp_path, interpret, tuner_cleanup):
    """A swept pallas keep routes the batch_norm epilogue through the
    kernel (r5 contract: the DB, not a flag, turns kernels on)."""
    from paddle_tpu.ops.nn_ops import _bn_epilogue

    C = 8
    x = _f32(4, 6, C)
    s, b, m = _f32(C), _f32(C), _f32(C)
    v = jnp.asarray((np.abs(rng.standard_normal(C)) + 0.5)
                    .astype(np.float32))
    key = tuning.canonical_key(
        "epilogue", tuning.epilogue_key("bn", 24, C, "last", "relu", False),
        "float32", tuning.device_kind())
    _seed_db(tmp_path, key, {"backend": "pallas"})
    pt_flags.set_flags({"pallas_epilogue": "auto"})
    got = _bn_epilogue(x, s, b, m, v, "relu", None, channel_last=True,
                       bshape=[1, 1, C])
    ref = ep.bn_apply_act_reference(x, s, b, m, v, act="relu")
    np.testing.assert_allclose(got, ref, rtol=1e-5, atol=1e-5)


def test_epilogue_candidate_recorded_in_sweep_mode(tmp_path, tuner_cleanup):
    """FLAGS_tuning_mode=sweep records the epilogue decision surface as
    candidate keys for tools/tune.py --what candidates to upgrade."""
    from paddle_tpu.ops.nn_ops import _epilogue_backend

    path = str(tmp_path / "db.json")
    pt_flags.set_flags({"tuning_mode": "sweep", "tuning_db": path,
                        "pallas_epilogue": "auto"})
    tuning.invalidate_db_cache()
    assert _epilogue_backend("bn", 96, 8, "last", "relu", True,
                             jnp.float32) == "xla"
    tuning.invalidate_db_cache()
    db = tuning.TuningDB(path)
    keys = [k for k in db.entries if k.startswith("epilogue|")]
    assert keys and db.entries[keys[0]]["source"] == "candidate"
    import re

    from tools.tune import _EPI_KEY_RE

    assert _EPI_KEY_RE.match(keys[0]), keys[0]


# ---------------------------------------------------------------------------
# minimize()-time epilogue fusion pass
# ---------------------------------------------------------------------------


def _bn_relu_program():
    main, startup = pt.Program(), pt.Program()
    with pt.program_guard(main, startup), pt.unique_name.guard():
        img = L.data(name="img", shape=[8, 6, 6], dtype="float32")
        y = L.conv2d(img, num_filters=8, filter_size=3, padding=1,
                     bias_attr=False, name="c1")
        y = L.batch_norm(y, act="relu", name="bn1")
        s = L.conv2d(img, num_filters=8, filter_size=1, bias_attr=False,
                     name="sc")
        s = L.batch_norm(s, name="bnsc")
        out = L.relu(L.elementwise_add(y, s))
        loss = L.reduce_mean(out)
        pt.optimizer.Momentum(learning_rate=0.1, momentum=0.9).minimize(loss)
    return main, startup, loss


def test_epilogue_pass_fuses_chains(tuner_cleanup):
    """FLAGS_pallas_epilogue=on: bn->relu folds to an act attr, the
    bn->add->relu residual block folds the add and relu into the norm op
    (attr act + input Residual), and no standalone relu survives."""
    pt_flags.set_flags({"pallas_epilogue": "on"})
    main, _, _ = _bn_relu_program()
    types = [op.type for op in main.global_block.ops]
    assert "relu" not in types and "elementwise_add" not in types
    fused = [op for op in main.global_block.ops
             if op.type in ("batch_norm", "conv2d_bn")]
    assert sorted(op.attr("act", "") for op in fused) == ["relu", "relu"]
    assert sum(1 for op in fused if op.input("Residual")) == 1


def test_epilogue_pass_training_equivalence(tuner_cleanup):
    """The fused program trains bit-identically to the unfused one on the
    XLA backend (the rewrite must be a pure structure change)."""
    exe = pt.Executor()
    x = rng.standard_normal((4, 8, 6, 6)).astype(np.float32)
    losses, params = {}, None
    for arm, flag in (("off", "off"), ("fused", "on")):
        pt_flags.set_flags({"pallas_epilogue": flag})
        main, startup, loss = _bn_relu_program()
        with pt.scope_guard(pt.Scope()):
            exe.run(startup)
            if params is None:
                params = [np.array(pt.global_scope().find_var(p.name))
                          for p in main.all_parameters()]
            else:
                for p, val in zip(main.all_parameters(), params):
                    pt.global_scope().set_var(p.name, val)
            losses[arm] = [float(np.asarray(exe.run(
                main, feed={"img": x}, fetch_list=[loss])[0]))
                for _ in range(3)]
    np.testing.assert_allclose(losses["off"], losses["fused"],
                               rtol=1e-6, atol=1e-6)


def test_epilogue_pass_off_leaves_program_alone(tuner_cleanup):
    """Default tier-1 state (tuning off, flag auto): zero structural
    change — the rewrite only runs when a DB could ever keep the kernel."""
    pt_flags.set_flags({"pallas_epilogue": "auto", "tuning_mode": "off"})
    main, _, _ = _bn_relu_program()
    types = [op.type for op in main.global_block.ops]
    assert "relu" in types and "elementwise_add" in types


def test_epilogue_pass_respects_multi_reader(tuner_cleanup):
    """A norm output with a second reader must NOT fuse (the var would
    vanish while still being read)."""
    pt_flags.set_flags({"pallas_epilogue": "on"})
    main, startup = pt.Program(), pt.Program()
    with pt.program_guard(main, startup), pt.unique_name.guard():
        img = L.data(name="img", shape=[4, 6, 6], dtype="float32")
        y = L.batch_norm(img, name="bn")
        a = L.relu(y)
        loss = L.reduce_mean(a) + L.reduce_mean(y)  # second reader of y
        pt.optimizer.SGD(learning_rate=0.1).minimize(loss)
    types = [op.type for op in main.global_block.ops]
    assert "relu" in types  # fusion declined


def test_layer_norm_act_fuses_and_dispatches(interpret, tuner_cleanup):
    """layer_norm -> relu folds to the act attr and, with a swept keep for
    the exact row shape, lowers through the LN kernel with matching
    numerics end to end."""
    x = rng.standard_normal((6, 32)).astype(np.float32)

    def build():
        main, startup = pt.Program(), pt.Program()
        with pt.program_guard(main, startup), pt.unique_name.guard():
            d = L.data(name="x", shape=[32], dtype="float32")
            y = L.layer_norm(d, act="relu", name="ln")
            loss = L.reduce_mean(y)
            pt.optimizer.SGD(learning_rate=0.1).minimize(loss)
        return main, startup, loss

    exe = pt.Executor()
    out = {}
    for arm in ("off", "on"):
        pt_flags.set_flags({"pallas_epilogue": arm, "tuning_mode": "off"})
        main, startup, loss = build()
        if arm == "on":
            assert "relu" not in [op.type for op in main.global_block.ops]
        with pt.scope_guard(pt.Scope()):
            exe.run(startup)
            for p in main.all_parameters():
                base = np.ones(p.shape, np.float32) * (
                    0.5 if "scale" in p.name or "_w" in p.name else 0.1)
                pt.global_scope().set_var(p.name, base)
            (out[arm],) = exe.run(main, feed={"x": x}, fetch_list=[loss])
    np.testing.assert_allclose(float(out["off"]), float(out["on"]),
                               rtol=1e-5, atol=1e-6)
