"""Serving-fleet tests (ISSUE 16): heartbeat health checking, failover
replay exactness (greedy output byte-identical with and without a
mid-stream replica kill — including a kill during the speculative-decode
accept window and a kill of a DRAINING replica), exactly-once token
delivery through the router ledger, drain-and-retire with zero shed,
affinity placement with graceful degradation, the per-request failover
budget, fleet-wide shedding, and the FLAGS_watchdog_scale margin knob."""
import time

import numpy as np
import pytest

from paddle_tpu import flags
from paddle_tpu import observability as obs
from paddle_tpu.resilience.faults import fault_scope
from paddle_tpu.resilience.watchdog import HeartbeatMonitor, watchdog_scale
from paddle_tpu.serving import (AdmissionRejected, FleetRouter, ServingEngine,
                                decoder_tiny)
from paddle_tpu.serving.fleet import DEAD, DRAINING, HEALTHY, RETIRED


def _factory(**kw):
    kw.setdefault("page_size", 4)
    kw.setdefault("pool_pages", 64)
    kw.setdefault("max_inflight", 4)
    kw.setdefault("draft_k", 0)
    kw.setdefault("seed", 0)
    return lambda: ServingEngine(decoder_tiny(), **kw)


def _prompts(n: int) -> list[list[int]]:
    rng = np.random.default_rng(7)
    return [rng.integers(1, 97, size=4 + i % 3).tolist() for i in range(n)]


def _oracle(prompts, max_new: int, **engine_kw) -> list[list[int]]:
    """Fault-free single-engine greedy outputs for the same seed/config —
    the byte-exactness reference every failover test pins against."""
    eng = _factory(**engine_kw)()
    rids = [eng.submit(p, max_new) for p in prompts]
    eng.run_until_drained()
    return [eng.result(r) for r in rids]


def _serve(fr: FleetRouter, prompts, max_new: int, plan: str | None = None):
    """Submit + drive to idle (optionally under a fault plan); returns
    per-prompt delivered streams."""
    fids = [fr.submit(p, max_new) for p in prompts]
    if plan is not None:
        with fault_scope(plan):
            fr.run_until_idle()
    else:
        fr.run_until_idle()
    assert all(fr.state(f) == "finished" for f in fids), \
        {f: fr.state(f) for f in fids}
    return [fr.result(f) for f in fids]


def _warm(fr: FleetRouter) -> None:
    """Compile every replica's programs before any timing-sensitive phase
    (first steps are seconds of XLA compile; heartbeats must not race
    that)."""
    fids = [fr.submit([9, 8, 7], 2) for _ in fr.replicas]
    fr.run_until_idle()
    assert all(fr.state(f) == "finished" for f in fids)
    fr.reset_stats()


# -- watchdog generalization (satellite 2) -----------------------------------

def test_watchdog_scale_clamps_and_widens():
    assert watchdog_scale() == 1.0
    old = flags.get_flag("watchdog_scale")
    try:
        flags.set_flags({"watchdog_scale": 0.25})
        assert watchdog_scale() == 1.0  # values < 1 clamp up, never tighten
        flags.set_flags({"watchdog_scale": 3.0})
        assert watchdog_scale() == 3.0
        assert HeartbeatMonitor(2.0).deadline_s == pytest.approx(6.0)
    finally:
        flags.set_flags({"watchdog_scale": old})
    assert HeartbeatMonitor(2.0).deadline_s == pytest.approx(2.0)


def test_heartbeat_monitor_overdue_and_lifecycle():
    mon = HeartbeatMonitor(0.05, scale=1.0)
    mon.register("a", now=0.0)
    mon.register("b", now=0.0)
    assert mon.overdue(now=0.04) == []
    mon.beat("a", now=0.04)
    assert mon.overdue(now=0.08) == ["b"]  # a beat, b went silent
    mon.deregister("b")
    assert mon.overdue(now=10.0) == ["a"]
    mon.beat("zombie", now=0.0)  # beats from unregistered names are ignored
    assert mon.age("a", now=0.1) == pytest.approx(0.06)
    disabled = HeartbeatMonitor(0.0)
    assert not disabled.enabled
    disabled.register("x", now=0.0)
    assert disabled.overdue(now=1e9) == []


# -- basic fleet serving -----------------------------------------------------

def test_fleet_matches_single_engine_and_affinity_routes():
    prompts = _prompts(4)
    want = _oracle(prompts, 6)
    with FleetRouter(_factory(), n_replicas=2, heartbeat_s=30.0) as fr:
        got = _serve(fr, prompts, 6)
        # identical resubmission must route to the same (healthy) home
        again = _serve(fr, prompts, 6)
    assert got == want
    assert again == want
    assert fr.stats["affinity_hits"] == 8
    assert fr.stats["affinity_misses"] == 0
    assert fr.stats["deaths"] == 0


def test_fleet_wide_shed_vs_single_replica_reject():
    # one running + one waiting per replica (max_inflight=1) trips the
    # queue-depth floor on BOTH replicas -> fleet-wide AdmissionRejected
    fac = _factory(shed_queue_depth=1, max_inflight=1)
    with FleetRouter(fac, n_replicas=2, heartbeat_s=30.0,
                     affinity=False) as fr:
        for p in ([1, 2, 3], [4, 5, 6], [7, 8, 9], [2, 4, 6]):
            fr.submit(list(p), 12)
            fr.step()  # admission is async: let the job reach the engine
        with pytest.raises(AdmissionRejected):
            fr.submit([7, 7, 7], 4)
        assert fr.stats["sheds"] == 1
        fr.run_until_idle()
    # one overloaded replica only loses the placement: the reject bounces
    # back and the request re-places on the free replica under the budget
    with FleetRouter(_factory(shed_queue_depth=1), n_replicas=2,
                     heartbeat_s=30.0, affinity=False) as fr2:
        fr2.replicas[0].engine.submit([9, 9, 9, 9], 3)  # pre-load replica0
        fid = fr2.submit([1, 2, 3], 4)
        fr2.run_until_idle()
        assert fr2.state(fid) == "finished"
        assert fr2.stats["rejects"] >= 1
        assert fr2.stats["failovers"] >= 1


# -- failover determinism (satellite 3) --------------------------------------

def test_failover_mid_stream_kill_is_byte_identical():
    prompts = _prompts(4)
    want = _oracle(prompts, 8)
    with FleetRouter(_factory(), n_replicas=2, heartbeat_s=0.3,
                     affinity=False) as fr:
        _warm(fr)
        # the kill site fires once, a few pumps in — mid-decode for
        # whichever replica draws it; nothing is announced
        got = _serve(fr, prompts, 8, plan="fleet_replica_kill:6")
    assert got == want, "failover replay must be bitwise-exact under greedy"
    assert fr.stats["deaths"] == 1
    assert fr.stats["failovers"] >= 1
    assert fr.stats["replayed_tokens"] >= 1
    assert fr.stats["dedup_tokens"] == fr.stats["replayed_tokens"]
    assert fr.stats["replay_divergence"] == 0


def test_failover_kill_in_spec_accept_window_is_byte_identical():
    # long greedy generations settle into loops the n-gram self-draft picks
    # up (the test_spec_decode_accepts_on_repetitive_sequences mechanism),
    # so decode emits multi-token accept windows — and the kill lands while
    # those windows are mid-flight
    prompts = _prompts(4)
    want = _oracle(prompts, 16, draft_k=3)
    oracle_eng = _factory(draft_k=3)()
    for p in prompts:
        oracle_eng.submit(p, 16)
    oracle_eng.run_until_drained()
    assert oracle_eng.stats["spec_accepted"] > 0, \
        "workload must actually exercise the accept window"
    with FleetRouter(_factory(draft_k=3), n_replicas=2, heartbeat_s=0.3,
                     affinity=False) as fr:
        _warm(fr)
        got = _serve(fr, prompts, 16, plan="fleet_replica_kill:6")
    assert got == want
    assert fr.stats["deaths"] == 1
    assert fr.stats["replay_divergence"] == 0


def test_failover_kill_of_draining_replica_is_byte_identical():
    prompts = _prompts(4)
    want = _oracle(prompts, 8)
    with FleetRouter(_factory(), n_replicas=2, heartbeat_s=0.3,
                     affinity=False) as fr:
        _warm(fr)
        fids = [fr.submit(p, 8) for p in prompts]
        for _ in range(3):  # get decodes moving on both replicas
            fr.step()
        fr.drain(0)
        fr.kill(0)  # the drain never finishes: replica dies mid-drain
        fr.run_until_idle()
        got = [fr.result(f) for f in fids]
        assert all(fr.state(f) == "finished" for f in fids)
    assert got == want
    assert fr.replicas[0].state == DEAD
    assert fr.stats["deaths"] == 1
    assert fr.stats["replay_divergence"] == 0


def test_hang_is_discovered_and_failed_over_exactly():
    prompts = _prompts(3)
    want = _oracle(prompts, 8)
    with FleetRouter(_factory(), n_replicas=2, heartbeat_s=0.3,
                     affinity=False) as fr:
        _warm(fr)
        got = _serve(fr, prompts, 8, plan="fleet_replica_hang:6")
    assert got == want
    assert fr.stats["deaths"] == 1, \
        "a wedged replica must be declared dead exactly like a killed one"
    assert fr.stats["replay_divergence"] == 0


def test_one_slow_heartbeat_does_not_kill_a_margined_replica():
    prompts = _prompts(3)
    with FleetRouter(_factory(), n_replicas=2, heartbeat_s=30.0,
                     affinity=False) as fr:
        _warm(fr)
        # one dropped beat against a wide deadline: a loaded host, not a
        # dead one — the health checker must NOT declare death
        got = _serve(fr, prompts, 6, plan="fleet_heartbeat_slow:3")
    assert fr.stats["deaths"] == 0
    assert fr.stats["failovers"] == 0
    assert got == _oracle(prompts, 6)
    # ...while a sustained beat starve against a tight deadline IS death
    with FleetRouter(_factory(), n_replicas=2, heartbeat_s=0.15,
                     affinity=False) as fr2:
        _warm(fr2)
        with fault_scope("rand:p=1.0,seed=0,sites=fleet_heartbeat_slow"):
            deadline = time.monotonic() + 60.0
            while (fr2.stats["deaths"] < len(fr2.replicas)
                   and time.monotonic() < deadline):
                fr2.step()
                time.sleep(0.002)
        assert fr2.stats["deaths"] == len(fr2.replicas), \
            "starving every beat must eventually read as death"


# -- drain-and-retire (tentpole c) -------------------------------------------

def test_drain_and_retire_sheds_nothing_and_stamps_duration():
    prompts = _prompts(6)
    want = _oracle(prompts, 8)
    obs.reset("fleet.")
    with FleetRouter(_factory(), n_replicas=2, heartbeat_s=30.0,
                     affinity=False) as fr:
        _warm(fr)
        fids = [fr.submit(p, 8) for p in prompts]
        for _ in range(2):
            fr.step()
        fr.drain(0)
        assert fr.replicas[0].state == DRAINING
        fr.run_until_idle()
        got = [fr.result(f) for f in fids]
        # the drained replica retired clean; every request finished on the
        # survivor or in place — zero shed, zero failed, byte-exact output
        assert fr.replicas[0].state == RETIRED
        assert fr.replicas[1].state == HEALTHY
        assert got == want
        assert fr.stats["retires"] == 1
        assert fr.stats["failed"] == 0
        assert fr.stats["sheds"] == 0
        assert fr.stats["deaths"] == 0
        snap = obs.snapshot()
        assert snap["histograms"]["fleet.drain_s"]["count"] == 1
        # draining replicas admit nothing: a new submit lands on replica 1
        fid = fr.submit([3, 1, 4, 1], 4)
        fr.run_until_idle()
        assert fr.requests[fid].replica == 1


def test_failover_budget_exhaustion_fails_the_request():
    with FleetRouter(_factory(), n_replicas=3, heartbeat_s=30.0,
                     affinity=False, failover_budget=1) as fr:
        _warm(fr)
        fid = fr.submit([5, 6, 7, 8], 16)
        fr.step()
        first = fr.requests[fid].replica
        fr.kill(first)  # consumes the whole budget of 1
        fr.step()
        second = fr.requests[fid].replica
        assert second is not None and second != first
        fr.kill(second)  # past the budget: fail, do NOT hop again
        fr.run_until_idle()
        assert fr.state(fid) == "failed"
        assert fr.stats["failed"] == 1
        assert fr.stats["failovers"] == 1
        # an untouched replica remains healthy — failure was budget policy
        assert any(r.state == HEALTHY for r in fr.replicas)


# -- threaded pump topology --------------------------------------------------

def test_threaded_pump_serves_and_survives_kill():
    prompts = _prompts(4)
    want = _oracle(prompts, 6)
    # wide heartbeat: a worker's first pump blocks seconds in XLA compile,
    # which must not read as death
    with FleetRouter(_factory(), n_replicas=2, heartbeat_s=60.0,
                     affinity=False, pump="threads") as fr:
        fids = [fr.submit(p, 6) for p in prompts]
        deadline = time.monotonic() + 120.0
        while (any(fr.state(f) != "finished" for f in fids)
               and time.monotonic() < deadline):
            fr.poll()
            time.sleep(0.005)
        got = [fr.result(f) for f in fids]
        assert got == want
        # administrative kill of a live worker: survivors keep serving
        fr.kill(0)
        fid = fr.submit([2, 7, 1, 8], 4)
        deadline = time.monotonic() + 60.0
        while (fr.state(fid) != "finished"
               and time.monotonic() < deadline):
            fr.poll()
            time.sleep(0.005)
        assert fr.state(fid) == "finished"
        assert fr.requests[fid].replica == 1
