"""Collective-mode distributed runner (reference unittests/dist_mnist.py with
DistributedStrategy collective, driven by TestDistBase._run_cluster:442): run
under `python -m paddle_tpu.distributed.launch`, each process trains on its
batch shard over a global mesh; with one process it is the local baseline.

usage: dist_collective.py OUT_NPZ
"""
import sys

from paddle_tpu.distributed import init_parallel_env

# join the coordination service BEFORE any jax compute (multi-process CPU
# needs the gloo collectives client wired into backend creation)
penv = init_parallel_env(backend="cpu", local_device_count=1)

import numpy as np  # noqa: E402

import paddle_tpu as pt  # noqa: E402
from paddle_tpu import layers as L  # noqa: E402
from paddle_tpu.incubate.fleet.base import PaddleCloudRoleMaker, fleet  # noqa: E402

STEPS = 5
FULL_BATCH = 32


def build():
    x = L.data(name="x", shape=[16], dtype="float32")
    y = L.data(name="y", shape=[1], dtype="float32")
    h = L.fc(x, size=64, act="relu")
    pred = L.fc(h, size=1)
    return L.mean(L.square_error_cost(pred, y))


def full_data():
    rng = np.random.default_rng(0)
    x = rng.standard_normal((FULL_BATCH, 16)).astype(np.float32)
    w = rng.standard_normal((16, 1)).astype(np.float32)
    return x, (x @ w).astype(np.float32)


def main():
    out = sys.argv[1]
    if penv.world_size > 1:
        out = f"{out}.r{penv.rank}.npz"
    fleet.init(PaddleCloudRoleMaker())

    main_p, startup = pt.Program(), pt.Program()
    main_p.random_seed = 7
    startup.random_seed = 7
    with pt.program_guard(main_p, startup):
        with pt.unique_name.guard():
            loss = build()
            opt = fleet.distributed_optimizer(pt.optimizer.SGD(0.1))
            opt.minimize(loss)

    exe = pt.Executor()
    exe.run(startup)

    compiled = fleet.compiled_program(main_p)
    x, y = full_data()
    shard = FULL_BATCH // penv.world_size
    lo = penv.rank * shard
    xs, ys = x[lo:lo + shard], y[lo:lo + shard]
    for _ in range(STEPS):
        (lv,) = exe.run(compiled, feed={"x": xs, "y": ys},
                        fetch_list=[loss.name])

    vals = {
        p.name: np.asarray(pt.global_scope().find_var(p.name))
        for p in main_p.all_parameters()
    }
    vals["__last_loss__"] = np.asarray(lv)
    np.savez(out, **vals)


if __name__ == "__main__":
    main()
