"""OpTest harness: declarative per-op correctness + gradient checking.

TPU-native clone of the reference's backbone test infrastructure
(/root/reference/python/paddle/fluid/tests/unittests/op_test.py: OpTest:135,
check_output:544, check_grad:736, get_numeric_gradient:46). Same contract:
a test declares op_type / inputs / outputs / attrs as numpy; `check_output`
runs the single op through a real Program+Executor; `check_grad` compares the
framework's program-transformation gradients against numeric central
differences.
"""
from __future__ import annotations

import numpy as np

import paddle_tpu as pt
from paddle_tpu.framework import grad_var_name


class OpTest:
    """Subclass and call setup() then check_output()/check_grad()."""

    op_type: str = ""

    def setup(self, op_type, inputs, outputs, attrs=None):
        self.op_type = op_type
        self.inputs = inputs  # slot -> np array | list[(name, np array)]
        self.expected = outputs  # slot -> np array | list
        self.attrs = attrs or {}

    # -- helpers ------------------------------------------------------------
    def _flat_inputs(self):
        flat = []
        for slot, v in self.inputs.items():
            if isinstance(v, list):
                for name, arr in v:
                    flat.append((slot, name, np.asarray(arr)))
            else:
                flat.append((slot, f"{slot}_in", np.asarray(v)))
        return flat

    def _build(self):
        """Build a fresh program containing just this op; returns fetch names."""
        main, startup = pt.Program(), pt.Program()
        with pt.program_guard(main, startup):
            block = main.global_block
            in_names = {}
            feed = {}
            for slot, name, arr in self._flat_inputs():
                block.create_var(
                    name=name, shape=arr.shape, dtype=str(arr.dtype), is_data=True,
                    stop_gradient=False,
                )
                in_names.setdefault(slot, []).append(name)
                feed[name] = arr
            out_names = {}
            for slot, v in self.expected.items():
                if isinstance(v, list):
                    out_names[slot] = [n for n, _ in v]
                else:
                    out_names[slot] = [f"{slot}_out"]
                for n in out_names[slot]:
                    block.create_var(name=n, shape=(), dtype="float32")
            block.append_op(self.op_type, in_names, out_names, self.attrs)
        return main, startup, feed, out_names

    # -- checks -------------------------------------------------------------
    def check_output(self, atol=1e-5, rtol=1e-5):
        main, startup, feed, out_names = self._build()
        exe = pt.Executor()
        exe.run(startup)
        fetch = []
        expected = []
        for slot, v in self.expected.items():
            if isinstance(v, list):
                for n, arr in v:
                    fetch.append(n)
                    expected.append(np.asarray(arr))
            else:
                fetch.append(out_names[slot][0])
                expected.append(np.asarray(v))
        got = exe.run(main, feed=feed, fetch_list=fetch)
        for g, e, name in zip(got, expected, fetch):
            np.testing.assert_allclose(
                np.asarray(g, np.float64),
                np.asarray(e, np.float64),
                atol=atol,
                rtol=rtol,
                err_msg=f"{self.op_type} output {name}",
            )

    def _out_array(self, output_name):
        for slot, v in self.expected.items():
            if isinstance(v, list):
                for n, arr in v:
                    if n == output_name or slot == output_name:
                        return np.asarray(arr)
            elif slot == output_name or f"{slot}_out" == output_name:
                return np.asarray(v)
        raise KeyError(output_name)

    def _reduce_target(self, block, out_var, feed, weighted):
        """The scalar the gradient check differentiates: sum(out), or —
        with `weighted` — sum(W (.) out) for a fixed seeded W. The weighted
        form exists for ops whose plain sum is a DEGENERATE functional
        (sum(softmax) == n_rows identically, so its true gradient is zero
        everywhere and the check compares nothing but fp32 noise against
        the 1e-3 denominator floor); weighting makes the checked gradient
        non-trivial while both the analytic and numeric sides see the same
        scalar."""
        from paddle_tpu import layers as L

        if not weighted:
            return L.reduce_sum(out_var)
        wname = "__grad_check_w"
        arr = self._out_array(self._weight_ref_name)
        block.create_var(name=wname, shape=arr.shape, dtype="float32",
                         is_data=True, stop_gradient=True)
        wrng = np.random.default_rng(1234)
        feed[wname] = wrng.standard_normal(arr.shape).astype(np.float32)
        return L.reduce_sum(L.elementwise_mul(out_var, block.var(wname)))

    def check_grad(
        self,
        inputs_to_check: list[str],
        output_name: str,
        numeric_delta=5e-3,
        max_relative_error=5e-3,
        no_grad_set=None,
        weighted=False,
    ):
        """Analytic grads (append_backward over a sum-reduced output) vs
        numeric central differences of the same scalar. `weighted=True`
        reduces with a fixed seeded weighting instead of a plain sum (see
        _reduce_target) — required for ops like softmax whose row sums are
        constant."""
        self._weight_ref_name = output_name
        main, startup, feed, out_names = self._build()
        with pt.program_guard(main, startup):
            block = main.global_block
            out_var = block.var(self._out_name(output_name, out_names))
            target = self._reduce_target(block, out_var, feed, weighted)
            pt.append_backward(target, parameter_list=[], no_grad_set=no_grad_set or set())
        exe = pt.Executor()
        exe.run(startup)
        grad_names = [grad_var_name(n) for n in inputs_to_check]
        analytic = exe.run(main, feed=feed, fetch_list=grad_names)

        # numeric: d target / d in via central differences
        fetch_scalar_main, fetch_startup, feed2, o2 = self._build()
        with pt.program_guard(fetch_scalar_main, fetch_startup):
            block = fetch_scalar_main.global_block
            out_var = block.var(self._out_name(output_name, o2))
            target2 = self._reduce_target(block, out_var, feed2, weighted)
        if weighted:
            feed["__grad_check_w"] = feed2["__grad_check_w"]
        exe2 = pt.Executor()
        exe2.run(fetch_startup)

        def f(feed_dict):
            (v,) = exe2.run(fetch_scalar_main, feed=feed_dict, fetch_list=[target2])
            return float(np.asarray(v))

        for name, a_grad in zip(inputs_to_check, analytic):
            base = {k: np.array(v, np.float64) for k, v in feed.items()}
            x = base[name].astype(np.float64)
            num = np.zeros_like(x)
            it = np.nditer(x, flags=["multi_index"])
            while not it.finished:
                idx = it.multi_index
                orig = x[idx]
                x[idx] = orig + numeric_delta
                base[name] = x.astype(feed[name].dtype)
                fp = f(base)
                x[idx] = orig - numeric_delta
                base[name] = x.astype(feed[name].dtype)
                fm = f(base)
                x[idx] = orig
                base[name] = x.astype(feed[name].dtype)
                num[idx] = (fp - fm) / (2 * numeric_delta)
                it.iternext()
            a = np.asarray(a_grad, np.float64)
            denom = np.maximum(np.maximum(np.abs(a), np.abs(num)), 1e-3)
            rel = np.max(np.abs(a - num) / denom)
            assert rel <= max_relative_error, (
                f"{self.op_type} grad of {name}: max rel err {rel}\n"
                f"analytic={a}\nnumeric={num}"
            )

    def _out_name(self, output_name, out_names):
        for slot, names in out_names.items():
            if slot == output_name or output_name in names:
                return names[0] if slot == output_name else output_name
        raise KeyError(output_name)
