"""Chaos marker: the tools/chaos.py harness, sized for tier-1.

A seeded random fault plan fires at the executor + checkpoint sites while a
CheckpointedRunner trains; the run must complete and the loss trajectory
must be bit-identical to the fault-free baseline. Seeded = deterministic: a
failure here replays exactly with the printed plan string."""
import os
import sys

import pytest

sys.path.insert(0, os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "tools"))

import chaos  # noqa: E402


@pytest.mark.chaos
def test_chaos_random_plan_survives_and_matches_baseline(tmp_path):
    out = chaos.run_chaos(
        "rand:p=0.2,seed=4,max=5,"
        "sites=collective.step|executor.compile|ckpt.write",
        steps=6, seed=4, root=str(tmp_path), verbose=False)
    assert out["fired"], "plan injected nothing — raise p or steps"
    assert out["retries"] >= 1


@pytest.mark.chaos
def test_chaos_explicit_plan_every_local_site(tmp_path):
    out = chaos.run_chaos(
        "collective.step:3,4;executor.compile:1;ckpt.write:1",
        steps=6, seed=0, root=str(tmp_path), verbose=False)
    assert {s for s, _ in out["fired"]} == {
        "collective.step", "executor.compile", "ckpt.write"}
