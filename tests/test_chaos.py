"""Chaos marker: the tools/chaos.py harness, sized for tier-1.

A seeded random fault plan fires at the executor + checkpoint sites while a
CheckpointedRunner trains; the run must complete and the loss trajectory
must be bit-identical to the fault-free baseline. Seeded = deterministic: a
failure here replays exactly with the printed plan string."""
import os
import sys

import pytest

sys.path.insert(0, os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "tools"))

import chaos  # noqa: E402


@pytest.mark.chaos
def test_chaos_random_plan_survives_and_matches_baseline(tmp_path):
    out = chaos.run_chaos(
        "rand:p=0.2,seed=4,max=5,"
        "sites=collective.step|executor.compile|ckpt.write",
        steps=6, seed=4, root=str(tmp_path), verbose=False)
    assert out["fired"], "plan injected nothing — raise p or steps"
    assert out["retries"] >= 1


@pytest.mark.chaos
def test_chaos_explicit_plan_every_local_site(tmp_path):
    out = chaos.run_chaos(
        "collective.step:3,4;executor.compile:1;ckpt.write:1",
        steps=6, seed=0, root=str(tmp_path), verbose=False)
    assert {s for s, _ in out["fired"]} == {
        "collective.step", "executor.compile", "ckpt.write"}


@pytest.mark.chaos
def test_chaos_fleet_drill_kill_hang_slowbeat_and_drain():
    """ISSUE 16 fleet scenarios, sized for tier-1: one kill wave, one hang
    wave, one heartbeat-starve wave, then a drain-and-retire wave — zero
    lost requests, zero duplicate tokens, outputs byte-identical to the
    fault-free oracle, zero leaks on every surviving engine (all asserted
    inside the drill)."""
    out = chaos.run_fleet_drill(cycles=3, n_req=3, seed=2, n_replicas=2,
                                verbose=False)
    assert len(out["cycles"]) == 3
    sites = {c["site"] for c in out["cycles"]}
    assert sites == {"fleet_replica_kill", "fleet_replica_hang",
                     "fleet_heartbeat_slow"}
    assert any(c["fired"] for c in out["cycles"]), "no fault ever fired"
    assert out["stats"]["deaths"] >= 1
    assert out["stats"]["replay_divergence"] == 0
    assert out["retired"] == 1


@pytest.mark.chaos
def test_chaos_disagg_drill_kill_drop_expiry_and_decode_kill():
    """ISSUE 19 disaggregation scenarios, sized for tier-1: a prefill
    SIGKILL mid-wave, a dropped handoff (the lease reaper must reclaim and
    replay it), the lease-expiry race at commit, then a decode SIGKILL
    holding adopted pages — zero lost requests, outputs byte-identical to
    the fault-free single-engine oracle, zero leaked pages, a clean
    shared-pool audit and no lease left PREPARED (all asserted inside the
    drill)."""
    out = chaos.run_disagg_drill(cycles=3, n_req=3, seed=1, verbose=False)
    assert len(out["cycles"]) == 3
    sites = {c["site"] for c in out["cycles"]}
    assert sites == {"disagg_prefill_kill", "disagg_handoff_drop",
                     "disagg_lease_expire_race"}
    assert any(c["fired"] for c in out["cycles"]), "no fault ever fired"
    assert out["deaths"] >= 2  # a prefill kill + the decode-kill finale
    assert out["handoff"]["granted"] >= out["handoff"]["committed"]
    assert out["handoff"]["reaped"] >= 1
    assert out["stats"]["replay_divergence"] == 0
