"""Sequence ops, beam search, and the machine-translation book test
equivalent (reference tests/book/test_machine_translation.py): train an
attention seq2seq on a copy task, then beam-search decode."""
import numpy as np

import paddle_tpu as pt
from paddle_tpu import layers as L
from paddle_tpu.models import seq2seq

BOS, EOS = 0, 1
VOCAB = 20
S = 6  # padded src len (includes no specials)
T = 8  # padded tgt len


def _batch(rng, batch):
    """Copy task: tgt = src; tgt_in = <s> + tgt[:-1]."""
    lens = rng.integers(2, S + 1, batch)
    src = rng.integers(2, VOCAB, (batch, S))
    for i, ln in enumerate(lens):
        src[i, ln:] = EOS
    tgt_out = np.full((batch, T), EOS)
    tgt_out[:, :S] = src
    tgt_in = np.roll(tgt_out, 1, axis=1)
    tgt_in[:, 0] = BOS
    tgt_lens = lens + 1  # content + EOS
    return (src.astype(np.int64), lens.astype(np.int64),
            tgt_in.astype(np.int64), tgt_out.astype(np.int64),
            tgt_lens.astype(np.int64))


def test_beam_search_step_math():
    """Hand-checked single step (reference beam_search_op_test.cc spirit)."""
    beam, K = 2, 3
    pre_ids = np.array([[3], [4]], np.int64)           # B=1, BW=2
    pre_scores = np.array([[-1.0], [-2.0]], np.float32)
    ids = np.array([[5, 6, 7], [8, 9, 10]], np.int64)
    scores = np.log(np.array([[0.5, 0.3, 0.2], [0.6, 0.3, 0.1]], np.float32))

    p_ids = L.data(name="pid", shape=[1], dtype="int64")
    p_sc = L.data(name="psc", shape=[1], dtype="float32")
    c_ids = L.data(name="cid", shape=[K], dtype="int64")
    c_sc = L.data(name="csc", shape=[K], dtype="float32")
    s_ids, s_sc, par = L.beam_search(p_ids, p_sc, c_ids, c_sc,
                                     beam_size=beam, end_id=EOS)
    exe = pt.Executor()
    outs = exe.run(pt.default_main_program(),
                   feed={"pid": pre_ids, "psc": pre_scores,
                         "cid": ids, "csc": scores},
                   fetch_list=[s_ids, s_sc, par])
    got_ids, got_sc, got_par = outs
    # candidates: beam0 -1+log(.5/.3/.2); beam1 -2+log(.6/.3/.1)
    # best two: beam0 id5 (-1.693), beam0 id6 (-2.204)
    np.testing.assert_array_equal(got_ids.reshape(-1), [5, 6])
    np.testing.assert_array_equal(got_par, [0, 0])
    np.testing.assert_allclose(
        got_sc.reshape(-1), [-1 + np.log(0.5), -1 + np.log(0.3)], rtol=1e-5)


def test_beam_search_frozen_beam_keeps_score():
    """A finished beam (pre_id == end_id) continues only as end_id with an
    unchanged cumulative score."""
    beam, K = 2, 2
    pre_ids = np.array([[EOS], [3]], np.int64)
    pre_scores = np.array([[-0.5], [-3.0]], np.float32)
    ids = np.array([[EOS, 5], [6, 7]], np.int64)
    scores = np.array([[-0.1, -0.2], [-0.3, -0.4]], np.float32)
    p_ids = L.data(name="pid", shape=[1], dtype="int64")
    p_sc = L.data(name="psc", shape=[1], dtype="float32")
    c_ids = L.data(name="cid", shape=[K], dtype="int64")
    c_sc = L.data(name="csc", shape=[K], dtype="float32")
    s_ids, s_sc, par = L.beam_search(p_ids, p_sc, c_ids, c_sc,
                                     beam_size=beam, end_id=EOS)
    exe = pt.Executor()
    got_ids, got_sc, got_par = exe.run(
        pt.default_main_program(),
        feed={"pid": pre_ids, "psc": pre_scores, "cid": ids, "csc": scores},
        fetch_list=[s_ids, s_sc, par])
    # frozen beam 0 survives at -0.5; live beam 1 continues with id6 at -3.3
    np.testing.assert_array_equal(got_ids.reshape(-1), [EOS, 6])
    np.testing.assert_allclose(got_sc.reshape(-1), [-0.5, -3.3], rtol=1e-5)
    np.testing.assert_array_equal(got_par, [0, 1])


def test_beam_search_frozen_beam_survives_without_eos_candidate():
    """A finished hypothesis must survive even when end_id is NOT in the
    frozen beam's top-K candidates (it gets an implicit end_id candidate)."""
    beam, K = 2, 2
    pre_ids = np.array([[EOS], [5]], np.int64)
    pre_scores = np.array([[-1.0], [-5.0]], np.float32)
    ids = np.array([[7, 8], [9, 10]], np.int64)   # no EOS anywhere
    scores = np.array([[-0.5, -0.7], [-0.5, -0.7]], np.float32)
    p_ids = L.data(name="pid", shape=[1], dtype="int64")
    p_sc = L.data(name="psc", shape=[1], dtype="float32")
    c_ids = L.data(name="cid", shape=[K], dtype="int64")
    c_sc = L.data(name="csc", shape=[K], dtype="float32")
    s_ids, s_sc, par = L.beam_search(p_ids, p_sc, c_ids, c_sc,
                                     beam_size=beam, end_id=EOS)
    exe = pt.Executor()
    got_ids, got_sc, got_par = exe.run(
        pt.default_main_program(),
        feed={"pid": pre_ids, "psc": pre_scores, "cid": ids, "csc": scores},
        fetch_list=[s_ids, s_sc, par])
    # the finished -1.0 hypothesis survives as an implicit end_id candidate
    np.testing.assert_array_equal(got_ids.reshape(-1), [EOS, 9])
    np.testing.assert_allclose(got_sc.reshape(-1), [-1.0, -5.5], rtol=1e-5)
    np.testing.assert_array_equal(got_par, [0, 1])


def test_beam_search_decode_backtracks():
    """Parent-pointer backtrack reconstructs the path (decode_op_test)."""
    # T=3, BW=2; step ids/parents crafted so beam 0's final path = [7, 9, 11]
    ids = np.array([[7, 8], [9, 10], [11, 12]], np.int64)
    parents = np.array([[0, 0], [0, 0], [0, 1]], np.int32)
    scores = np.array([[0.0, 0.0], [0.0, 0.0], [-1.0, -2.0]], np.float32)
    i = L.data(name="i", shape=[3, 2], dtype="int64")
    i.shape = (3, 2)
    p = L.data(name="p", shape=[3, 2], dtype="int32")
    p.shape = (3, 2)
    s = L.data(name="s", shape=[3, 2], dtype="float32")
    s.shape = (3, 2)
    sent, sc = L.beam_search_decode(i, p, s, end_id=EOS)
    exe = pt.Executor()
    got, gsc = exe.run(pt.default_main_program(),
                       feed={"i": ids, "p": parents, "s": scores},
                       fetch_list=[sent, sc])
    np.testing.assert_array_equal(got[0], [7, 9, 11])
    # final beam 1 came from step-1 beam 1 (token 10), then step-0 beam 0
    np.testing.assert_array_equal(got[1], [7, 10, 12])
    np.testing.assert_allclose(gsc, [-1.0, -2.0])


def test_machine_translation_trains_and_decodes():
    batch = 16
    src = L.data(name="src", shape=[S], dtype="int64")
    slen = L.data(name="slen", shape=[], dtype="int64")
    tin = L.data(name="tin", shape=[T], dtype="int64")
    tout = L.data(name="tout", shape=[T], dtype="int64")
    tlen = L.data(name="tlen", shape=[], dtype="int64")
    loss = seq2seq.train_model(src, slen, tin, tout, tlen, VOCAB,
                               word_dim=32, hidden_dim=32)
    pt.optimizer.Adam(learning_rate=0.01).minimize(loss)

    exe = pt.Executor()
    exe.run(pt.default_startup_program())
    rng = np.random.default_rng(0)
    first = last = None
    for i in range(60):
        s_, sl, ti, to, tl = _batch(rng, batch)
        (lv,) = exe.run(pt.default_main_program(),
                        feed={"src": s_, "slen": sl, "tin": ti,
                              "tout": to, "tlen": tl},
                        fetch_list=[loss])
        lv = float(np.asarray(lv))
        if first is None:
            first = lv
        last = lv
    assert np.isfinite(last)
    assert last < first * 0.7, (first, last)

    # decode program shares trained params via the global scope
    infer_prog = pt.Program()
    with pt.program_guard(infer_prog, pt.Program()):
        isrc = L.data(name="src", shape=[S], dtype="int64")
        isrc.shape = (4, S)  # static batch for the beam layout
        islen = L.data(name="slen", shape=[], dtype="int64")
        sent, scores = seq2seq.infer_model(
            isrc, islen, VOCAB, word_dim=32, hidden_dim=32,
            beam_size=3, max_len=T, bos_id=BOS, eos_id=EOS)
    s_, sl, *_ = _batch(rng, 4)
    got, gsc = exe.run(infer_prog, feed={"src": s_, "slen": sl},
                       fetch_list=[sent, scores])
    assert got.shape == (4 * 3, T)
    assert np.isfinite(np.asarray(gsc)).all()
    assert ((got >= 0) & (got < VOCAB)).all()


def test_dynamic_rnn_masks_by_length():
    """DynamicRNN freezes state and zeroes outputs beyond each row's length
    (padding-based equivalent of reference DynamicRNN LoD iteration)."""
    B, Tn, D, H = 3, 5, 4, 6
    x = L.data(name="x", shape=[Tn, D], dtype="float32")
    x.shape = (B, Tn, D)
    lens = L.data(name="lens", shape=[], dtype="int64")
    h0 = L.fill_constant([B, H], "float32", 0.0)
    drnn = L.DynamicRNN()
    with drnn.block():
        w = drnn.step_input(x, length=lens)
        h = drnn.memory(init=h0)
        h2 = L.fc(L.concat([w, h], axis=1), size=H, act="tanh")
        drnn.update_memory(h, h2)
        drnn.output(h2)
    out = drnn()
    exe = pt.Executor()
    exe.run(pt.default_startup_program())
    rng = np.random.default_rng(0)
    xv = rng.standard_normal((B, Tn, D)).astype(np.float32)
    lv = np.array([2, 5, 3])
    (o,) = exe.run(pt.default_main_program(),
                   feed={"x": xv, "lens": lv}, fetch_list=[out])
    assert o.shape == (B, Tn, H)
    assert np.abs(o[0, 2:]).max() == 0.0          # tail zeroed
    assert np.abs(o[0, :2]).max() > 0             # valid region computed
    assert np.abs(o[1]).min() >= 0 and np.abs(o[1, 4]).max() > 0
    # frozen rows: row 2's state stops evolving after t=3, so a second run
    # with garbage in the padded tail must give identical valid outputs
    xv2 = xv.copy()
    xv2[0, 2:] = 1e6
    (o2,) = exe.run(pt.default_main_program(),
                    feed={"x": xv2, "lens": lv}, fetch_list=[out])
    np.testing.assert_allclose(o[0, :2], o2[0, :2], rtol=1e-6)
    assert np.abs(o2[0, 2:]).max() == 0.0
