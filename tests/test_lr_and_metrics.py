"""LR scheduler + metrics tests (reference unittests/
test_learning_rate_scheduler.py pattern: run N steps, compare the fetched LR
against the python formula)."""
import math

import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import layers as L


def _run_schedule(build_fn, steps=6):
    """Build `lr = build_fn()` plus a dummy train step; fetch LR per run."""
    lr = build_fn()
    x = L.data(name="x", shape=[4], dtype="float32")
    loss = L.mean(L.fc(x, size=2))
    opt = pt.optimizer.SGD(learning_rate=lr)
    opt.minimize(loss)
    exe = pt.Executor()
    exe.run(pt.default_startup_program())
    xv = np.ones((2, 4), np.float32)
    out = []
    for _ in range(steps):
        (lv,) = exe.run(pt.default_main_program(), feed={"x": xv},
                        fetch_list=[lr])
        out.append(float(np.asarray(lv).reshape(-1)[0]))
    return out


def test_exponential_decay():
    got = _run_schedule(lambda: L.exponential_decay(0.1, decay_steps=2,
                                                    decay_rate=0.5))
    want = [0.1 * 0.5 ** (s / 2) for s in range(6)]
    np.testing.assert_allclose(got, want, rtol=1e-5)


def test_exponential_decay_staircase():
    got = _run_schedule(lambda: L.exponential_decay(0.1, 2, 0.5, staircase=True))
    want = [0.1 * 0.5 ** (s // 2) for s in range(6)]
    np.testing.assert_allclose(got, want, rtol=1e-5)


def test_natural_exp_decay():
    got = _run_schedule(lambda: L.natural_exp_decay(0.1, 2, 0.5))
    want = [0.1 * math.exp(-0.5 * s / 2) for s in range(6)]
    np.testing.assert_allclose(got, want, rtol=1e-5)


def test_inverse_time_decay():
    got = _run_schedule(lambda: L.inverse_time_decay(0.1, 2, 0.5))
    want = [0.1 / (1 + 0.5 * s / 2) for s in range(6)]
    np.testing.assert_allclose(got, want, rtol=1e-5)


def test_polynomial_decay():
    got = _run_schedule(lambda: L.polynomial_decay(0.1, decay_steps=4,
                                                   end_learning_rate=0.01,
                                                   power=2.0))
    want = [(0.1 - 0.01) * (1 - min(s, 4) / 4) ** 2 + 0.01 for s in range(6)]
    np.testing.assert_allclose(got, want, rtol=1e-5)


def test_piecewise_decay():
    got = _run_schedule(lambda: L.piecewise_decay([2, 4], [0.1, 0.05, 0.01]))
    def ref(s):
        if s < 2:
            return 0.1
        if s < 4:
            return 0.05
        return 0.01
    want = [ref(s) for s in range(6)]
    np.testing.assert_allclose(got, want, rtol=1e-6)


def test_noam_decay():
    got = _run_schedule(lambda: L.noam_decay(64, warmup_steps=4))
    want = [64 ** -0.5 * min((s + 1) ** -0.5, (s + 1) * 4 ** -1.5)
            for s in range(6)]
    np.testing.assert_allclose(got, want, rtol=1e-5)


def test_cosine_decay():
    got = _run_schedule(lambda: L.cosine_decay(0.1, step_each_epoch=2,
                                               epochs=3))
    want = [0.05 * (math.cos(math.pi * (s // 2) / 3) + 1) for s in range(6)]
    np.testing.assert_allclose(got, want, rtol=1e-5)


def test_linear_lr_warmup():
    got = _run_schedule(lambda: L.linear_lr_warmup(0.1, warmup_steps=3,
                                                   start_lr=0.01, end_lr=0.1))
    def ref(s):
        return 0.01 + (0.1 - 0.01) * s / 3 if s < 3 else 0.1
    want = [ref(s) for s in range(6)]
    np.testing.assert_allclose(got, want, rtol=1e-5)


def test_training_with_schedule_converges():
    lr = L.piecewise_decay([20], [0.1, 0.01])
    x = L.data(name="x", shape=[8], dtype="float32")
    y = L.data(name="y", shape=[1], dtype="float32")
    loss = L.mean(L.square_error_cost(L.fc(x, size=1), y))
    pt.optimizer.SGD(learning_rate=lr).minimize(loss)
    exe = pt.Executor()
    exe.run(pt.default_startup_program())
    rng = np.random.default_rng(0)
    xv = rng.standard_normal((32, 8)).astype(np.float32)
    w = rng.standard_normal((8, 1)).astype(np.float32)
    hist = []
    for _ in range(30):
        (lv,) = exe.run(pt.default_main_program(),
                        feed={"x": xv, "y": xv @ w}, fetch_list=[loss])
        hist.append(float(lv))
    assert hist[-1] < hist[0] * 0.2


# --- metrics ---------------------------------------------------------------

def test_accuracy_metric():
    m = pt.metrics.Accuracy()
    m.update(0.5, weight=10)
    m.update(1.0, weight=10)
    assert abs(m.eval() - 0.75) < 1e-9


def test_precision_recall():
    p, r = pt.metrics.Precision(), pt.metrics.Recall()
    preds = np.array([1, 1, 0, 0, 1])
    labels = np.array([1, 0, 1, 0, 1])
    p.update(preds, labels)
    r.update(preds, labels)
    assert abs(p.eval() - 2 / 3) < 1e-9
    assert abs(r.eval() - 2 / 3) < 1e-9


def test_auc_perfect_classifier():
    m = pt.metrics.Auc()
    probs = np.array([[0.9, 0.1], [0.8, 0.2], [0.2, 0.8], [0.1, 0.9]])
    labels = np.array([0, 0, 1, 1])
    m.update(probs, labels)
    assert m.eval() > 0.99


def test_composite_metric():
    c = pt.metrics.CompositeMetric()
    c.add_metric(pt.metrics.Precision())
    c.add_metric(pt.metrics.Recall())
    preds = np.array([1, 0, 1])
    labels = np.array([1, 1, 1])
    c.update(preds, labels)
    prec, rec = c.eval()
    assert prec == 1.0 and abs(rec - 2 / 3) < 1e-9
