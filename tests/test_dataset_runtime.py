"""Dataset runtime: MultiSlot parsing (native C + Python fallback),
QueueDataset / InMemoryDataset, exe.train_from_dataset (reference
test_dataset.py + dist_ctr.py CTR pattern)."""
import os

import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import layers as L
from paddle_tpu.native import _parse_multislot_py, parse_multislot_file


def _write_ctr_files(tmp_path, n_files=2, lines_per_file=40, seed=0):
    """MultiSlot CTR lines: 4 sparse ids, 3 dense floats, 1 label."""
    rng = np.random.default_rng(seed)
    paths = []
    for fi in range(n_files):
        p = tmp_path / f"slot{fi}.txt"
        with open(p, "w") as f:
            for _ in range(lines_per_file):
                ids = rng.integers(0, 50, 4)
                dense = rng.random(3).round(4)
                label = rng.integers(0, 2)
                f.write(f"4 {' '.join(map(str, ids))} "
                        f"3 {' '.join(map(str, dense))} "
                        f"1 {label}\n")
        paths.append(str(p))
    return paths


def test_multislot_parser_native_matches_python(tmp_path):
    (path,) = _write_ctr_files(tmp_path, n_files=1, lines_per_file=10)
    widths = [4, 3, 1]
    got = parse_multislot_file(path, widths)
    ref = _parse_multislot_py(path, widths)
    assert got.shape == (10, 8)
    np.testing.assert_allclose(got, ref)


def test_multislot_parser_pads_and_truncates(tmp_path):
    p = tmp_path / "x.txt"
    p.write_text("2 7 8 1 0.5\n4 1 2 3 4 1 0.25\n")
    out = parse_multislot_file(str(p), [3, 1])
    np.testing.assert_allclose(out[0], [7, 8, 0, 0.5])   # padded
    np.testing.assert_allclose(out[1], [1, 2, 3, 0.25])  # truncated


def test_multislot_parser_malformed(tmp_path):
    p = tmp_path / "bad.txt"
    p.write_text("2 7\n")  # declares 2 values, provides 1
    with pytest.raises((ValueError, Exception)):
        parse_multislot_file(str(p), [2])


def _build_ctr():
    ids = L.data(name="ids", shape=[4], dtype="int64")
    dense = L.data(name="dense", shape=[3], dtype="float32")
    label = L.data(name="label", shape=[1], dtype="float32")
    emb = L.embedding(ids, size=[50, 8])
    feat = L.concat([L.reshape(emb, [-1, 32]), dense], axis=1)
    h = L.fc(feat, size=16, act="relu")
    logit = L.fc(h, size=1)
    loss = L.mean(L.sigmoid_cross_entropy_with_logits(logit, label))
    return ids, dense, label, loss


def test_train_from_dataset_queue(tmp_path, capsys):
    files = _write_ctr_files(tmp_path)
    ids, dense, label, loss = _build_ctr()
    pt.optimizer.SGD(0.1).minimize(loss)

    ds = pt.DatasetFactory().create_dataset("QueueDataset")
    ds.set_batch_size(8)
    ds.set_thread(2)
    ds.set_use_var([ids, dense, label])
    ds.set_filelist(files)

    exe = pt.Executor()
    exe.run(pt.default_startup_program())
    w0 = np.asarray(pt.global_scope().find_var("fc_0.w_0")).copy()
    exe.train_from_dataset(
        pt.default_main_program(), ds,
        fetch_list=[loss], fetch_info=["loss"], print_period=5)
    w1 = np.asarray(pt.global_scope().find_var("fc_0.w_0"))
    assert not np.allclose(w0, w1), "training moved no parameters"
    assert "loss" in capsys.readouterr().out


def test_inmemory_dataset_shuffles_and_trains(tmp_path):
    files = _write_ctr_files(tmp_path)
    ids, dense, label, loss = _build_ctr()
    pt.optimizer.SGD(0.1).minimize(loss)

    ds = pt.DatasetFactory().create_dataset("InMemoryDataset")
    ds.set_batch_size(16)
    ds.set_thread(2)
    ds.set_use_var([ids, dense, label])
    ds.set_filelist(files)
    ds.load_into_memory()
    assert ds.get_memory_data_size() == 80
    before = ds._data.copy()
    ds.local_shuffle()
    assert not np.array_equal(before, ds._data)
    np.testing.assert_allclose(np.sort(before.ravel()),
                               np.sort(ds._data.ravel()))

    exe = pt.Executor()
    exe.run(pt.default_startup_program())
    exe.train_from_dataset(pt.default_main_program(), ds)
    lv = exe.run(pt.default_main_program(),
                 feed=next(iter(ds._iter_batches())), fetch_list=[loss])[0]
    assert np.isfinite(float(np.asarray(lv)))
    ds.release_memory()
    assert ds.get_memory_data_size() == 0


def test_global_shuffle_partitions_by_rank(tmp_path):
    files = _write_ctr_files(tmp_path, n_files=1, lines_per_file=30)
    ids, dense, label, _ = _build_ctr()

    class _FakeFleet:
        def __init__(self, rank):
            self._rank = rank

        def worker_index(self):
            return self._rank

        def worker_num(self):
            return 2

    seen = []
    for rank in range(2):
        ds = pt.DatasetFactory().create_dataset("InMemoryDataset")
        ds.set_batch_size(8)
        ds.set_use_var([ids, dense, label])
        ds.set_filelist(files)
        ds.load_into_memory()
        ds.global_shuffle(_FakeFleet(rank))
        seen.append(ds._data)
    total = sum(len(s) for s in seen)
    assert total == 30  # every sample on exactly one trainer
    # partitions are disjoint: re-sorting the union reproduces the full set
    ds_full = pt.DatasetFactory().create_dataset("InMemoryDataset")
    ds_full.set_use_var([ids, dense, label])
    ds_full.set_filelist(files)
    ds_full.load_into_memory()
    union = np.concatenate(seen)
    np.testing.assert_allclose(
        np.sort(union.ravel()), np.sort(ds_full._data.ravel()))


def test_queue_dataset_assembly_runs_on_worker_threads(tmp_path):
    """ISSUE 5 satellite (VERDICT r5 #3): batch ASSEMBLY (_split_batch) must
    run on the parser workers, overlapped with the consumer's dispatch loop,
    and the generator must yield feed-ready dicts."""
    import threading

    files = _write_ctr_files(tmp_path)
    ids, dense, label, _ = _build_ctr()
    ds = pt.DatasetFactory().create_dataset("QueueDataset")
    ds.set_batch_size(8)
    ds.set_thread(2)
    ds.set_use_var([ids, dense, label])
    ds.set_filelist(files)

    assembly_threads = set()
    orig = ds._split_batch

    def spying_split(flat):
        assembly_threads.add(threading.get_ident())
        return orig(flat)

    ds._split_batch = spying_split
    batches = list(ds._iter_batches())
    assert batches and all(isinstance(b, dict) for b in batches)
    assert set(batches[0]) == {ids.name, dense.name, label.name}
    assert threading.get_ident() not in assembly_threads
    assert assembly_threads  # the workers actually assembled


def test_inmemory_dataset_double_buffers_assembly(tmp_path):
    import threading

    files = _write_ctr_files(tmp_path)
    ids, dense, label, _ = _build_ctr()
    ds = pt.DatasetFactory().create_dataset("InMemoryDataset")
    ds.set_batch_size(8)
    ds.set_use_var([ids, dense, label])
    ds.set_filelist(files)
    ds.load_into_memory()

    assembly_threads = set()
    orig = ds._split_batch

    def spying_split(flat):
        assembly_threads.add(threading.get_ident())
        return orig(flat)

    ds._split_batch = spying_split
    n = sum(1 for _ in ds._iter_batches())
    assert n == 10  # 80 rows / batch 8
    assert threading.get_ident() not in assembly_threads


def test_queue_dataset_worker_skips_corrupt_batch(tmp_path):
    """A batch whose assembly raises dies OFF-thread now: under
    FLAGS_feed_skip_corrupt it must be counted and skipped, not kill the
    epoch; without the flag the error must still surface to the consumer."""
    from paddle_tpu import flags, profiler

    files = _write_ctr_files(tmp_path, n_files=1, lines_per_file=24)
    ids, dense, label, _ = _build_ctr()

    def make_ds():
        ds = pt.DatasetFactory().create_dataset("QueueDataset")
        ds.set_batch_size(8)
        ds.set_use_var([ids, dense, label])
        ds.set_filelist(files)
        orig = ds._split_batch
        calls = {"n": 0}

        def poisoned(flat):
            calls["n"] += 1
            if calls["n"] == 2:
                raise ValueError("corrupt batch (injected)")
            return orig(flat)

        ds._split_batch = poisoned
        return ds

    saved = flags.get_flag("feed_skip_corrupt")
    try:
        flags.set_flags({"feed_skip_corrupt": True})
        profiler.stage_counters(reset=True)
        got = list(make_ds()._iter_batches())
        assert len(got) == 2  # 3 batches, one poisoned
        assert profiler.stage_counters()["feed.skip_corrupt"]["events"] == 1
        flags.set_flags({"feed_skip_corrupt": False})
        with pytest.raises(ValueError, match="corrupt batch"):
            list(make_ds()._iter_batches())
    finally:
        flags.set_flags({"feed_skip_corrupt": saved})
