"""Long-tail op coverage (VERDICT r2 #9): detection (roi_align/roi_pool/
yolo_box/anchor_generator/bipartite_match/density_prior_box/
generate_proposals), sequence (slice/erase/expand_as/scatter), print, and
OpTest numeric-grad checks for previously vjp-faith ops (gru_unit/lstm_unit,
prior_box, multiclass_nms outputs)."""
import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import layers as L

from op_test import OpTest


# -- detection ---------------------------------------------------------------


class TestRoiAlign(OpTest):
    def _setup(self):
        rng = np.random.default_rng(0)
        x = rng.standard_normal((2, 3, 8, 8)).astype(np.float32)
        rois = np.array([[0.5, 0.5, 6.0, 6.0],
                         [1.0, 2.0, 7.0, 7.5],
                         [0.0, 0.0, 4.0, 4.0]], np.float32)
        bid = np.array([0, 1, 1], np.int64)
        self.setup("roi_align",
                   {"X": x, "ROIs": rois, "RoisBatchId": bid},
                   {"Out": self._ref(x, rois, bid)},
                   {"pooled_height": 2, "pooled_width": 2,
                    "spatial_scale": 1.0, "sampling_ratio": 2})

    @staticmethod
    def _ref(x, rois, bid, ph=2, pw=2, sr=2):
        R = rois.shape[0]
        C, H, W = x.shape[1:]
        out = np.zeros((R, C, ph, pw), np.float32)
        for r in range(R):
            img = x[bid[r]]
            x1, y1, x2, y2 = rois[r]
            rw, rh = max(x2 - x1, 1.0), max(y2 - y1, 1.0)
            bw, bh = rw / pw, rh / ph
            for i in range(ph):
                for j in range(pw):
                    acc = np.zeros(C)
                    for iy in range(sr):
                        for ix in range(sr):
                            yy = y1 + (i + (iy + 0.5) / sr) * bh
                            xx = x1 + (j + (ix + 0.5) / sr) * bw
                            y0 = int(np.clip(np.floor(yy), 0, H - 1))
                            x0 = int(np.clip(np.floor(xx), 0, W - 1))
                            y1i = min(y0 + 1, H - 1)
                            x1i = min(x0 + 1, W - 1)
                            wy = np.clip(yy, 0, H - 1) - y0
                            wx = np.clip(xx, 0, W - 1) - x0
                            acc += (img[:, y0, x0] * (1 - wy) * (1 - wx)
                                    + img[:, y1i, x0] * wy * (1 - wx)
                                    + img[:, y0, x1i] * (1 - wy) * wx
                                    + img[:, y1i, x1i] * wy * wx)
                    out[r, :, i, j] = acc / (sr * sr)
        return out

    def test_output(self):
        self._setup()
        self.check_output(atol=1e-4)

    def test_grad(self):
        self._setup()
        self.check_grad(["X_in"], "Out", max_relative_error=2e-2,
                        no_grad_set={"ROIs_in", "RoisBatchId_in"})


class TestRoiPool(OpTest):
    def test_output_and_grad(self):
        rng = np.random.default_rng(1)
        x = rng.standard_normal((1, 2, 6, 6)).astype(np.float32)
        rois = np.array([[0.0, 0.0, 5.0, 5.0]], np.float32)
        # numpy oracle: exact reference binning
        ph = pw = 2
        r = np.round(rois[0])
        rw = max(r[2] - r[0] + 1, 1.0)
        rh = max(r[3] - r[1] + 1, 1.0)
        ref = np.zeros((1, 2, ph, pw), np.float32)
        for i in range(ph):
            for j in range(pw):
                hs = int(np.floor(i * rh / ph) + r[1])
                he = int(np.ceil((i + 1) * rh / ph) + r[1])
                ws = int(np.floor(j * rw / pw) + r[0])
                we = int(np.ceil((j + 1) * rw / pw) + r[0])
                ref[0, :, i, j] = x[0, :, hs:he, ws:we].max(axis=(1, 2))
        self.setup("roi_pool", {"X": x, "ROIs": rois}, {"Out": ref},
                   {"pooled_height": ph, "pooled_width": pw,
                    "spatial_scale": 1.0})
        self.check_output(atol=1e-5)
        self.check_grad(["X_in"], "Out", max_relative_error=2e-2,
                        no_grad_set={"ROIs_in"})


class TestYoloBox(OpTest):
    def test_output(self):
        rng = np.random.default_rng(2)
        an, cls, H, W = 2, 3, 2, 2
        x = rng.standard_normal((1, an * (5 + cls), H, W)).astype(np.float32)
        img_size = np.array([[64, 64]], np.int64)
        anchors = [10, 13, 16, 30]
        down = 32

        xr = x.reshape(1, an, 5 + cls, H, W)
        sig = lambda v: 1 / (1 + np.exp(-v))
        boxes = np.zeros((1, an * H * W, 4), np.float32)
        scores = np.zeros((1, an * H * W, cls), np.float32)
        k = 0
        # op layout: [an, H, W] flattened row-major
        for a in range(an):
            for i in range(H):
                for j in range(W):
                    cx = (sig(xr[0, a, 0, i, j]) + j) / W
                    cy = (sig(xr[0, a, 1, i, j]) + i) / H
                    bw = np.exp(xr[0, a, 2, i, j]) * anchors[2 * a] / (W * down)
                    bh = np.exp(xr[0, a, 3, i, j]) * anchors[2 * a + 1] / (H * down)
                    conf = sig(xr[0, a, 4, i, j])
                    p = sig(xr[0, a, 5:, i, j]) * conf
                    if conf < 0.01:
                        p = np.zeros_like(p)
                    idx = a * H * W + i * W + j
                    boxes[0, idx] = [np.clip((cx - bw / 2) * 64, 0, 63),
                                     np.clip((cy - bh / 2) * 64, 0, 63),
                                     np.clip((cx + bw / 2) * 64, 0, 63),
                                     np.clip((cy + bh / 2) * 64, 0, 63)]
                    scores[0, idx] = p
        self.setup("yolo_box", {"X": x, "ImgSize": img_size},
                   {"Boxes": boxes, "Scores": scores},
                   {"anchors": anchors, "class_num": cls,
                    "conf_thresh": 0.01, "downsample_ratio": down})
        self.check_output(atol=1e-4)


def test_anchor_generator_shapes_and_values():
    main, startup = pt.Program(), pt.Program()
    with pt.program_guard(main, startup):
        blk = main.global_block
        blk.create_var(name="feat", shape=(1, 8, 2, 3), dtype="float32",
                       is_data=True)
        blk.create_var(name="A", shape=(), dtype="float32")
        blk.create_var(name="V", shape=(), dtype="float32")
        blk.append_op("anchor_generator", {"Input": ["feat"]},
                      {"Anchors": ["A"], "Variances": ["V"]},
                      {"anchor_sizes": [32.0, 64.0], "aspect_ratios": [1.0],
                       "stride": [16.0, 16.0], "offset": 0.5})
    exe = pt.Executor()
    exe.run(startup)
    a, v = exe.run(main, feed={"feat": np.zeros((1, 8, 2, 3), np.float32)},
                   fetch_list=["A", "V"])
    a = np.asarray(a)
    assert a.shape == (2, 3, 2, 4) and np.asarray(v).shape == a.shape
    # reference math (anchor_generator_op.h:55-81): center = 0*16+0.5*15 =
    # 7.5, size-32 square spans ±(32-1)/2 -> [-8, -8, 23, 23]
    np.testing.assert_allclose(a[0, 0, 0], [-8, -8, 23, 23])


def test_bipartite_match_greedy():
    dist = np.array([[[0.9, 0.1, 0.3],
                      [0.8, 0.7, 0.2]]], np.float32)  # [1, 2 gt, 3 priors]
    main, startup = pt.Program(), pt.Program()
    with pt.program_guard(main, startup):
        blk = main.global_block
        blk.create_var(name="d", shape=dist.shape, dtype="float32",
                       is_data=True)
        blk.create_var(name="idx", shape=(), dtype="int32")
        blk.create_var(name="md", shape=(), dtype="float32")
        blk.append_op("bipartite_match", {"DistMat": ["d"]},
                      {"ColToRowMatchIndices": ["idx"],
                       "ColToRowMatchDist": ["md"]}, {})
    exe = pt.Executor()
    exe.run(startup)
    idx, md = exe.run(main, feed={"d": dist}, fetch_list=["idx", "md"])
    # greedy: (r0,c0)=0.9 first, then (r1,c1)=0.7; c2 unmatched
    np.testing.assert_array_equal(np.asarray(idx)[0], [0, 1, -1])
    np.testing.assert_allclose(np.asarray(md)[0], [0.9, 0.7, 0.0])


def test_density_prior_box_count_and_range():
    main, startup = pt.Program(), pt.Program()
    with pt.program_guard(main, startup):
        blk = main.global_block
        blk.create_var(name="feat", shape=(1, 8, 4, 4), dtype="float32",
                       is_data=True)
        blk.create_var(name="img", shape=(1, 3, 32, 32), dtype="float32",
                       is_data=True)
        blk.create_var(name="B", shape=(), dtype="float32")
        blk.create_var(name="V", shape=(), dtype="float32")
        blk.append_op("density_prior_box",
                      {"Input": ["feat"], "Image": ["img"]},
                      {"Boxes": ["B"], "Variances": ["V"]},
                      {"fixed_sizes": [8.0], "fixed_ratios": [1.0],
                       "densities": [2], "clip": True})
    exe = pt.Executor()
    exe.run(startup)
    b, _ = exe.run(main, feed={"feat": np.zeros((1, 8, 4, 4), np.float32),
                               "img": np.zeros((1, 3, 32, 32), np.float32)},
                   fetch_list=["B", "V"])
    b = np.asarray(b)
    assert b.shape == (4, 4, 4, 4)  # density^2 = 4 boxes per cell
    assert (b >= 0).all() and (b <= 1).all()
    # boxes are (x1, y1) < (x2, y2)
    assert (b[..., 2] > b[..., 0]).all() and (b[..., 3] > b[..., 1]).all()


def test_generate_proposals_smoke():
    rng = np.random.default_rng(3)
    N, A, H, W = 1, 3, 4, 4
    scores = rng.random((N, A, H, W)).astype(np.float32)
    deltas = (rng.standard_normal((N, A * 4, H, W)) * 0.1).astype(np.float32)
    anchors = np.zeros((H, W, A, 4), np.float32)
    for i in range(H):
        for j in range(W):
            for a in range(A):
                s = 8.0 * (a + 1)
                cx, cy = j * 8 + 4, i * 8 + 4
                anchors[i, j, a] = [cx - s / 2, cy - s / 2,
                                    cx + s / 2, cy + s / 2]
    variances = np.full((H, W, A, 4), 1.0, np.float32)
    im_info = np.array([[32, 32, 1.0]], np.float32)
    main, startup = pt.Program(), pt.Program()
    with pt.program_guard(main, startup):
        blk = main.global_block
        for n, v in (("s", scores), ("d", deltas), ("ii", im_info),
                     ("an", anchors), ("va", variances)):
            blk.create_var(name=n, shape=v.shape, dtype="float32",
                           is_data=True)
        for n in ("rois", "probs", "num"):
            blk.create_var(name=n, shape=(), dtype="float32")
        blk.append_op("generate_proposals",
                      {"Scores": ["s"], "BboxDeltas": ["d"], "ImInfo": ["ii"],
                       "Anchors": ["an"], "Variances": ["va"]},
                      {"RpnRois": ["rois"], "RpnRoiProbs": ["probs"],
                       "RpnRoisNum": ["num"]},
                      {"pre_nms_topN": 12, "post_nms_topN": 5,
                       "nms_thresh": 0.7, "min_size": 1.0})
    exe = pt.Executor()
    exe.run(startup)
    rois, probs, num = exe.run(
        main, feed={"s": scores, "d": deltas, "ii": im_info,
                    "an": anchors, "va": variances},
        fetch_list=["rois", "probs", "num"])
    rois, probs, num = map(np.asarray, (rois, probs, num))
    assert rois.shape == (1, 5, 4) and probs.shape == (1, 5, 1)
    n = int(num[0])
    assert 1 <= n <= 5
    valid = rois[0, :n]
    assert (valid[:, 2] >= valid[:, 0]).all()
    assert (valid >= 0).all() and (valid <= 31).all()
    # scores sorted descending among kept
    assert (np.diff(probs[0, :n, 0]) <= 1e-6).all()


# -- sequence ----------------------------------------------------------------


class TestSequenceSlice(OpTest):
    def test_output_and_grad(self):
        rng = np.random.default_rng(4)
        x = rng.standard_normal((2, 5, 3)).astype(np.float32)
        off = np.array([1, 0], np.int64)
        ln = np.array([2, 4], np.int64)
        ref = np.zeros_like(x)
        ref[0, :2] = x[0, 1:3]
        ref[1, :4] = x[1, 0:4]
        self.setup("sequence_slice",
                   {"X": x, "Offset": off, "Length": ln},
                   {"Out": ref, "OutLength": ln}, {})
        self.check_output()
        self.check_grad(["X_in"], "Out",
                        no_grad_set={"Offset_in", "Length_in"})


def test_sequence_erase():
    x = np.array([[2, 7, 2, 5, 0], [9, 2, 9, 0, 0]], np.int64)
    ln = np.array([4, 3], np.int64)
    main, startup = pt.Program(), pt.Program()
    with pt.program_guard(main, startup):
        blk = main.global_block
        blk.create_var(name="x", shape=x.shape, dtype="int64", is_data=True)
        blk.create_var(name="l", shape=ln.shape, dtype="int64", is_data=True)
        blk.create_var(name="o", shape=(), dtype="int64")
        blk.create_var(name="ol", shape=(), dtype="int64")
        blk.append_op("sequence_erase", {"X": ["x"], "Length": ["l"]},
                      {"Out": ["o"], "OutLength": ["ol"]}, {"tokens": [2, 0]})
    exe = pt.Executor()
    exe.run(startup)
    o, ol = exe.run(main, feed={"x": x, "l": ln}, fetch_list=["o", "ol"])
    np.testing.assert_array_equal(np.asarray(o), [[7, 5, 0, 0, 0],
                                                  [9, 9, 0, 0, 0]])
    np.testing.assert_array_equal(np.asarray(ol), [2, 2])


class TestSequenceExpandAs(OpTest):
    def test_output_and_grad(self):
        rng = np.random.default_rng(5)
        x = rng.standard_normal((2, 3)).astype(np.float32)
        y = np.zeros((6, 3), np.float32)
        self.setup("sequence_expand_as", {"X": x, "Y": y},
                   {"Out": np.repeat(x, 3, axis=0)}, {})
        self.check_output()
        self.check_grad(["X_in"], "Out", no_grad_set={"Y_in"})


class TestSequenceScatter(OpTest):
    def test_output_and_grad(self):
        rng = np.random.default_rng(6)
        x = rng.standard_normal((2, 6)).astype(np.float32)
        ids = np.array([[1, 3], [0, 5]], np.int64)
        upd = rng.standard_normal((2, 2)).astype(np.float32)
        ref = x.copy()
        for b in range(2):
            for s in range(2):
                ref[b, ids[b, s]] += upd[b, s]
        self.setup("sequence_scatter",
                   {"X": x, "Ids": ids, "Updates": upd}, {"Out": ref}, {})
        self.check_output()
        self.check_grad(["X_in", "Updates_in"], "Out",
                        no_grad_set={"Ids_in"})


# -- print -------------------------------------------------------------------


def test_print_op_passthrough_and_first_n(capsys):
    main, startup = pt.Program(), pt.Program()
    with pt.program_guard(main, startup):
        x = L.data(name="x", shape=[3], dtype="float32")
        out = L.Print(x, first_n=2, message="dbg")
        out2 = L.scale(out, scale=2.0)
    exe = pt.Executor()
    exe.run(startup)
    xb = np.arange(6, dtype=np.float32).reshape(2, 3)
    for _ in range(4):
        (o,) = exe.run(main, feed={"x": xb}, fetch_list=[out2])
    np.testing.assert_allclose(np.asarray(o), xb * 2)  # pass-through intact
    logs = capsys.readouterr().out
    assert logs.count("dbg") == 2  # first_n honored


# -- previously vjp-faith ops get numeric-grad coverage ----------------------


class TestGruUnitGrad(OpTest):
    def test_grad(self):
        rng = np.random.default_rng(7)
        B, D = 2, 4
        self.setup("gru_unit",
                   {"Input": rng.standard_normal((B, 3 * D)).astype(np.float32),
                    "HiddenPrev": rng.standard_normal((B, D)).astype(np.float32),
                    "Weight": (rng.standard_normal((D, 3 * D)) * 0.3).astype(np.float32),
                    "Bias": (rng.standard_normal((1, 3 * D)) * 0.1).astype(np.float32)},
                   {"Hidden": np.zeros((B, D), np.float32)}, {})
        # output oracle unavailable (gate math); numeric grad IS the check.
        # fp32 forward + 5e-3 central differences through two sigmoids cap
        # the attainable agreement near 5e-2 (reference gru tests run fp64)
        self.check_grad(["Input_in", "HiddenPrev_in", "Weight_in"], "Hidden",
                        max_relative_error=6e-2)


class TestLstmUnitGrad(OpTest):
    def test_grad(self):
        rng = np.random.default_rng(8)
        B, D = 2, 3
        self.setup("lstm_unit",
                   {"X": rng.standard_normal((B, 4 * D)).astype(np.float32),
                    "C_prev": rng.standard_normal((B, D)).astype(np.float32)},
                   {"C": np.zeros((B, D), np.float32),
                    "H": np.zeros((B, D), np.float32)}, {})
        self.check_grad(["X_in", "C_prev_in"], "H", max_relative_error=6e-2)


def test_prior_box_reference_values():
    """Direct OpTest for prior_box (previously only via layers)."""
    main, startup = pt.Program(), pt.Program()
    with pt.program_guard(main, startup):
        blk = main.global_block
        blk.create_var(name="feat", shape=(1, 4, 2, 2), dtype="float32",
                       is_data=True)
        blk.create_var(name="img", shape=(1, 3, 16, 16), dtype="float32",
                       is_data=True)
        blk.create_var(name="B", shape=(), dtype="float32")
        blk.create_var(name="V", shape=(), dtype="float32")
        blk.append_op("prior_box", {"Input": ["feat"], "Image": ["img"]},
                      {"Boxes": ["B"], "Variances": ["V"]},
                      {"min_sizes": [4.0], "aspect_ratios": [1.0],
                       "clip": True})
    exe = pt.Executor()
    exe.run(startup)
    b, v = exe.run(main, feed={"feat": np.zeros((1, 4, 2, 2), np.float32),
                               "img": np.zeros((1, 3, 16, 16), np.float32)},
                   fetch_list=["B", "V"])
    b = np.asarray(b)
    # cell (0,0): center (4,4) step 8; size-4 box -> (2,2,6,6)/16
    np.testing.assert_allclose(b[0, 0, 0], [2 / 16, 2 / 16, 6 / 16, 6 / 16])
    np.testing.assert_allclose(np.asarray(v).reshape(-1)[:4],
                               [0.1, 0.1, 0.2, 0.2])


def test_multiclass_nms_suppression():
    """Direct OpTest for multiclass_nms: overlapping boxes suppressed,
    highest score kept (previously only exercised via layers/ssd_loss)."""
    boxes = np.array([[[0, 0, 10, 10], [1, 1, 11, 11], [50, 50, 60, 60]]],
                     np.float32)
    scores = np.array([[[0.9, 0.8, 0.7]]], np.float32)  # [N, cls, M]
    main, startup = pt.Program(), pt.Program()
    with pt.program_guard(main, startup):
        blk = main.global_block
        blk.create_var(name="b", shape=boxes.shape, dtype="float32",
                       is_data=True)
        blk.create_var(name="s", shape=scores.shape, dtype="float32",
                       is_data=True)
        blk.create_var(name="o", shape=(), dtype="float32")
        blk.append_op("multiclass_nms", {"BBoxes": ["b"], "Scores": ["s"]},
                      {"Out": ["o"]},
                      {"score_threshold": 0.1, "nms_threshold": 0.5,
                       "keep_top_k": 3, "nms_top_k": 3,
                       "background_label": -1})
    exe = pt.Executor()
    exe.run(startup)
    (o,) = exe.run(main, feed={"b": boxes, "s": scores}, fetch_list=["o"])
    o = np.asarray(o)
    kept = o[o[..., 0] >= 0].reshape(-1, 6)
    # box 1 (IoU ~0.68 with box 0) suppressed; boxes 0 and 2 kept
    assert kept.shape[0] == 2
    np.testing.assert_allclose(sorted(kept[:, 1].tolist(), reverse=True),
                               [0.9, 0.7], rtol=1e-5)


def test_print_on_gradient_path_trains():
    """Print's grad is identity (reference PrintOpGradientMaker) — a debug
    print on a training tensor must not break append_backward."""
    main, startup = pt.Program(), pt.Program()
    with pt.program_guard(main, startup):
        x = L.data(name="x", shape=[3], dtype="float32")
        h = L.fc(x, size=4)
        h = L.Print(h, message="dbg", first_n=0)
        loss = L.mean(h)
        pt.optimizer.SGD(0.1).minimize(loss)
    exe = pt.Executor()
    with pt.scope_guard(pt.Scope()):
        exe.run(startup)
        w0 = np.asarray(pt.global_scope().find_var("fc_0.w_0")).copy()
        exe.run(main, feed={"x": np.ones((2, 3), np.float32)},
                fetch_list=[loss])
        w1 = np.asarray(pt.global_scope().find_var("fc_0.w_0"))
    assert not np.allclose(w0, w1), "gradient did not flow through Print"


def test_print_preserves_shape_metadata():
    with pt.program_guard(pt.Program(), pt.Program()):
        x = L.data(name="x", shape=[3], dtype="float32")
        y = L.Print(x)
        assert tuple(y.shape) == tuple(x.shape)
        # downstream fc sees the true fan-in
        out = L.fc(y, size=4)
        w = out.block.program.all_parameters()[0]
        assert w.shape[0] == 3


def test_sequence_erase_keeps_negative_values():
    main, startup = pt.Program(), pt.Program()
    with pt.program_guard(main, startup):
        blk = main.global_block
        blk.create_var(name="x", shape=(1, 5), dtype="int64", is_data=True)
        blk.create_var(name="l", shape=(1,), dtype="int64", is_data=True)
        blk.create_var(name="o", shape=(), dtype="int64")
        blk.create_var(name="ol", shape=(), dtype="int64")
        blk.append_op("sequence_erase", {"X": ["x"], "Length": ["l"]},
                      {"Out": ["o"], "OutLength": ["ol"]}, {"tokens": [2]})
    exe = pt.Executor()
    exe.run(startup)
    o, _ = exe.run(main, feed={"x": np.array([[-5, 2, -7, 0, 0]], np.int64),
                               "l": np.array([3], np.int64)},
                   fetch_list=["o", "ol"])
    np.testing.assert_array_equal(np.asarray(o), [[-5, -7, 0, 0, 0]])
