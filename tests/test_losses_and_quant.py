"""NCE / hierarchical sigmoid / CTC losses, distributions, QAT pass, and the
DGC optimizer (reference: nce_op.h, hierarchical_sigmoid_op.h, warpctc_op.h,
layers/distributions.py, slim QuantizationTransformPass, optimizer.py DGC)."""
import itertools

import numpy as np

import paddle_tpu as pt
from paddle_tpu import layers as L


def test_warpctc_matches_bruteforce():
    """Alpha recursion equals explicit path enumeration on a tiny case."""
    T, V = 4, 3
    blank = 0
    label = [1, 2]
    rng = np.random.default_rng(0)
    logits = rng.standard_normal((1, T, V)).astype(np.float32)
    logp = logits - np.log(np.exp(logits).sum(-1, keepdims=True))

    def collapses_to(path, target):
        out, prev = [], None
        for p in path:
            if p != blank and p != prev:
                out.append(p)
            prev = p
        return out == target

    total = -np.inf
    for path in itertools.product(range(V), repeat=T):
        if collapses_to(list(path), label):
            lp = sum(logp[0, t, c] for t, c in enumerate(path))
            total = np.logaddexp(total, lp)
    expect = -total

    lg = L.data(name="lg", shape=[T, V], dtype="float32")
    lab = L.data(name="lab", shape=[len(label)], dtype="int64")
    loss = L.warpctc(lg, lab, blank=blank)
    exe = pt.Executor()
    (got,) = exe.run(pt.default_main_program(),
                     feed={"lg": logits,
                           "lab": np.array([label], np.int64)},
                     fetch_list=[loss])
    np.testing.assert_allclose(float(got.reshape(-1)[0]), expect, rtol=1e-4)


def test_hsigmoid_path_consistency():
    """hsigmoid loss equals a numpy replay of the SimpleCode path."""
    rng = np.random.default_rng(1)
    D, C, B = 6, 10, 4
    xv = rng.standard_normal((B, D)).astype(np.float32)
    lbl = rng.integers(0, C, (B, 1)).astype(np.int64)

    x = L.data(name="x", shape=[D], dtype="float32")
    y = L.data(name="y", shape=[1], dtype="int64")
    out = L.hsigmoid(x, y, num_classes=C,
                     param_attr=pt.ParamAttr(name="hs.w"),
                     bias_attr=pt.ParamAttr(name="hs.b"))
    exe = pt.Executor()
    exe.run(pt.default_startup_program())
    (got,) = exe.run(pt.default_main_program(),
                     feed={"x": xv, "y": lbl}, fetch_list=[out])
    w = np.asarray(pt.global_scope().find_var("hs.w"))
    b = np.asarray(pt.global_scope().find_var("hs.b"))

    def ref_loss(x_row, c):
        code = c + C
        length = int(np.floor(np.log2(code)))
        loss = 0.0
        for d in range(length):
            idx = (code >> (d + 1)) - 1
            bit = (code >> d) & 1
            pre = x_row @ w[idx] + b[idx]
            loss += np.log1p(np.exp(pre)) - bit * pre
        return loss

    expect = np.array([ref_loss(xv[i], int(lbl[i, 0])) for i in range(B)])
    np.testing.assert_allclose(got.reshape(-1), expect, rtol=1e-4)


def test_nce_trains_and_uses_saved_samples():
    rng = np.random.default_rng(2)
    x = L.data(name="x", shape=[16], dtype="float32")
    lbl = L.data(name="lbl", shape=[1], dtype="int64")
    cost = L.mean(L.nce(x, lbl, num_total_classes=40, num_neg_samples=8,
                        sampler="log_uniform"))
    pt.optimizer.SGD(0.1).minimize(cost)
    exe = pt.Executor()
    exe.run(pt.default_startup_program())
    first = last = None
    for i in range(40):
        xb = rng.standard_normal((16, 16)).astype(np.float32)
        yb = (np.abs(xb[:, :1]).round().astype(np.int64) % 40)
        (lv,) = exe.run(pt.default_main_program(),
                        feed={"x": xb, "lbl": yb}, fetch_list=[cost])
        if first is None:
            first = float(lv)
        last = float(lv)
    assert np.isfinite(last) and last < first, (first, last)


def test_distributions_math():
    from paddle_tpu.layers.distributions import Categorical, Normal, Uniform

    n1 = Normal(0.0, 1.0)
    n2 = Normal(1.0, 2.0)
    u = Uniform(0.0, 2.0)
    x = L.data(name="x", shape=[3], dtype="float32")
    cat = Categorical(x)
    fetches = [n1.entropy(), n1.kl_divergence(n2), u.entropy(),
               n1.log_prob(L.fill_constant([1], "float32", 0.0)),
               cat.entropy(), cat.sample(seed=5)]
    exe = pt.Executor()
    outs = exe.run(pt.default_main_program(),
                   feed={"x": np.log(np.array([[0.5, 0.25, 0.25]],
                                              np.float32))},
                   fetch_list=fetches)
    np.testing.assert_allclose(float(np.asarray(outs[0]).reshape(-1)[0]),
                               0.5 + 0.5 * np.log(2 * np.pi), rtol=1e-5)
    # KL(N(0,1)||N(1,2)) = log(2) + (1+1)/8 - 1/2
    np.testing.assert_allclose(float(np.asarray(outs[1]).reshape(-1)[0]),
                               np.log(2.0) + 2.0 / 8 - 0.5, rtol=1e-5)
    np.testing.assert_allclose(float(np.asarray(outs[2]).reshape(-1)[0]),
                               np.log(2.0), rtol=1e-5)
    np.testing.assert_allclose(float(np.asarray(outs[3]).reshape(-1)[0]),
                               -0.5 * np.log(2 * np.pi), rtol=1e-5)
    expect_ent = -(0.5 * np.log(0.5) + 2 * 0.25 * np.log(0.25))
    np.testing.assert_allclose(float(np.asarray(outs[4]).reshape(-1)[0]),
                               expect_ent, rtol=1e-4)
    assert 0 <= int(np.asarray(outs[5]).reshape(-1)[0]) < 3


def test_quantization_pass_qat():
    from paddle_tpu.contrib.slim.quantization import QuantizationTransformPass

    x = L.data(name="x", shape=[8], dtype="float32")
    y = L.data(name="y", shape=[1], dtype="float32")
    pred = L.fc(L.fc(x, size=16, act="relu"), size=1)
    loss = L.mean(L.square_error_cost(pred, y))
    QuantizationTransformPass().apply()
    types = [op.type for op in pt.default_main_program().global_block.ops]
    assert sum("fake_quantize" in t for t in types) >= 4
    pt.optimizer.SGD(0.05).minimize(loss)

    exe = pt.Executor()
    exe.run(pt.default_startup_program())
    rng = np.random.default_rng(3)
    w = rng.standard_normal((8, 1)).astype(np.float32)
    first = last = None
    for i in range(60):
        xb = rng.standard_normal((32, 8)).astype(np.float32)
        (lv,) = exe.run(pt.default_main_program(),
                        feed={"x": xb, "y": xb @ w}, fetch_list=[loss])
        if first is None:
            first = float(lv)
        last = float(lv)
    assert last < first * 0.2, (first, last)


def test_fake_quant_levels():
    """Quantized values land on <= 2^bits distinct levels."""
    from paddle_tpu.layer_helper import LayerHelper

    x = L.data(name="x", shape=[64], dtype="float32")
    helper = LayerHelper("fq")
    out = helper.create_variable_for_type_inference("float32")
    scale = helper.create_variable_for_type_inference("float32")
    helper.append_op("fake_quantize_dequantize_abs_max", {"X": [x]},
                     {"Out": [out], "OutScale": [scale]}, {"bit_length": 4})
    exe = pt.Executor()
    xv = np.random.default_rng(4).standard_normal((2, 64)).astype(np.float32)
    (got, sc) = exe.run(pt.default_main_program(), feed={"x": xv},
                        fetch_list=[out, scale])
    assert len(np.unique(got.round(6))) <= 2 ** 4
    np.testing.assert_allclose(float(sc[0]), np.abs(xv).max(), rtol=1e-6)


def test_dgc_momentum_converges_and_sparsifies():
    x = L.data(name="x", shape=[12], dtype="float32")
    y = L.data(name="y", shape=[1], dtype="float32")
    loss = L.mean(L.square_error_cost(L.fc(x, size=1), y))
    pt.optimizer.DGCMomentumOptimizer(
        0.05, momentum=0.9, sparsity=[0.9]).minimize(loss)
    types = [op.type for op in pt.default_main_program().global_block.ops]
    assert "dgc" in types
    exe = pt.Executor()
    exe.run(pt.default_startup_program())
    rng = np.random.default_rng(5)
    w = rng.standard_normal((12, 1)).astype(np.float32)
    first = last = None
    for i in range(80):
        xb = rng.standard_normal((32, 12)).astype(np.float32)
        (lv,) = exe.run(pt.default_main_program(),
                        feed={"x": xb, "y": xb @ w}, fetch_list=[loss])
        if first is None:
            first = float(lv)
        last = float(lv)
    assert last < first * 0.1, (first, last)


def test_dgc_rampup_schedule_oracle():
    """The in-graph warmup schedule must follow the reference get_sparsity
    formula step for step (VERDICT r5 #6): sparsity 0 before
    rampup_begin_step, then the sparsity list section-by-section across
    rampup_step steps, held at the final value — and the allreduce payload
    (nonzeros in the dgc GradOut) must shrink as the schedule ramps."""
    x = L.data(name="x", shape=[64], dtype="float32")
    y = L.data(name="y", shape=[1], dtype="float32")
    loss = L.mean(L.square_error_cost(L.fc(x, size=16, act=None), y))
    ramp = [0.5, 0.75, 0.9]
    begin, width = 5, 12
    pt.optimizer.DGCMomentumOptimizer(
        0.01, momentum=0.9, rampup_begin_step=begin, rampup_step=width,
        sparsity=ramp).minimize(loss)
    main = pt.default_main_program()
    dgc_ops = [op for op in main.global_block.ops if op.type == "dgc"]
    assert dgc_ops and all("CurrentStep" in op.inputs for op in dgc_ops)
    # the fc weight's dgc op: its GradOut is the [64,16] allreduce payload
    big = next(op for op in dgc_ops
               if main.global_block.var(op.output("GradOut")[0]).shape[0] == 64)
    gout, sp_name = big.output("GradOut")[0], big.output("Sparsity")[0]

    def expected(step):
        if step < begin:
            return 0.0
        i = min(int((step - begin) * len(ramp) / width), len(ramp) - 1)
        return ramp[i]

    exe = pt.Executor()
    exe.run(pt.default_startup_program())
    rng = np.random.default_rng(7)
    w = rng.standard_normal((64, 1)).astype(np.float32)
    nnz_frac = []
    for step in range(begin + width + 5):
        xb = rng.standard_normal((32, 64)).astype(np.float32)
        (g, sp) = exe.run(main, feed={"x": xb, "y": xb @ w},
                          fetch_list=[gout, sp_name])
        np.testing.assert_allclose(float(np.asarray(sp)[0]), expected(step),
                                   atol=1e-6, err_msg=f"step {step}")
        nnz_frac.append(float(np.mean(np.asarray(g) != 0.0)))
    # payload shrinks as the schedule ramps: dense before begin, ~top-10%
    # at the final sparsity (ties can nudge the exact count)
    assert nnz_frac[begin - 1] == 1.0, nnz_frac[:begin]
    assert nnz_frac[begin] <= 0.55
    assert nnz_frac[-1] <= 0.15
    assert nnz_frac[-1] < nnz_frac[begin] < nnz_frac[0]
