"""Serving runtime tests (ISSUE 7): paged KV cache, ragged paged decode
attention (XLA reference + Pallas interpret kernel) equivalence against
dense attention, continuous-batching scheduling (backpressure, preemption,
abort reclamation), and the compile-once-per-bucket contract."""
import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import tuning, unique_name
from paddle_tpu.framework import Program, program_guard
from paddle_tpu.ops import attention_ops as ao
from paddle_tpu.serving import (PagedKVPool, ServingEngine, decoder_tiny,
                                build_full_forward_program)
from paddle_tpu.serving import model as sv_model


def _rand(shape, seed):
    return np.random.default_rng(seed).standard_normal(shape).astype(
        np.float32)


def _scattered_pool(lens, ps, nh, dh, num_pages, seed=0):
    """Contiguous per-row K/V plus its paged scatter: returns
    (k_dense, v_dense, k_pool, v_pool, page_table)."""
    import jax.numpy as jnp

    B = len(lens)
    S = max(lens)
    P = max(-(-l // ps) for l in lens)
    k = _rand((B, nh, S, dh), seed)
    v = _rand((B, nh, S, dh), seed + 1)
    rng = np.random.default_rng(seed + 2)
    perm = iter(rng.permutation(num_pages))
    pt_ = np.zeros((B, P), np.int32)
    for b in range(B):
        for p in range(-(-lens[b] // ps)):
            pt_[b, p] = next(perm)
    kp = jnp.zeros((num_pages, ps, nh, dh), jnp.float32)
    vp = jnp.zeros((num_pages, ps, nh, dh), jnp.float32)
    kp, vp = ao.kv_cache_prefill_write_fn(
        kp, vp, jnp.asarray(k), jnp.asarray(v), jnp.asarray(pt_),
        jnp.asarray(lens, np.int32))
    return k, v, kp, vp, jnp.asarray(pt_)


# -- op level: paged attention vs dense --------------------------------------

def test_paged_attention_matches_dense_ragged_rows():
    """XLA gather-based paged decode attention over a shuffled page table
    == dense attention per row, at three different context lengths."""
    import jax.numpy as jnp

    ps, nh, dh = 4, 2, 8
    lens = [5, 9, 1]
    k, v, kp, vp, pt_ = _scattered_pool(lens, ps, nh, dh, num_pages=16)
    q = _rand((3, nh, dh), 9)
    out = ao._paged_attention_reference(
        jnp.asarray(q), kp, vp, pt_, jnp.asarray(lens, np.int32),
        sm_scale=dh ** -0.5)
    for b, L_ in enumerate(lens):
        ref = ao._reference_attention(
            jnp.asarray(q[b:b + 1, :, None, :]),
            jnp.asarray(k[b:b + 1, :, :L_]), jnp.asarray(v[b:b + 1, :, :L_]),
            sm_scale=dh ** -0.5)
        np.testing.assert_allclose(np.asarray(out)[b],
                                   np.asarray(ref)[0, :, 0, :],
                                   rtol=2e-5, atol=2e-5)


def test_paged_attention_pallas_matches_reference():
    """The Pallas page-DMA kernel (interpret mode on the CPU mesh) ==
    the XLA gather reference, ragged lengths included."""
    import jax.numpy as jnp

    from paddle_tpu.ops.pallas_kernels import paged_attention as ppa

    ps, nh, dh = 4, 2, 8
    lens = [7, 12, 3, 1]
    _, _, kp, vp, pt_ = _scattered_pool(lens, ps, nh, dh, num_pages=16,
                                        seed=3)
    q = jnp.asarray(_rand((4, nh, dh), 4))
    ref = ao._paged_attention_reference(q, kp, vp, pt_,
                                        jnp.asarray(lens, np.int32),
                                        sm_scale=dh ** -0.5)
    old = ppa.INTERPRET
    ppa.INTERPRET = True
    try:
        out = ppa.paged_decode_attention(q, kp, vp, pt_,
                                         jnp.asarray(lens, np.int32),
                                         sm_scale=dh ** -0.5)
    finally:
        ppa.INTERPRET = old
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def test_kv_append_page_boundary_and_mask():
    """Appends that land on a page boundary go to the next page's slot 0;
    masked (padded) rows write nothing."""
    import jax.numpy as jnp

    ps, nh, dh = 4, 2, 8
    kp = jnp.zeros((8, ps, nh, dh), jnp.float32)
    vp = jnp.zeros((8, ps, nh, dh), jnp.float32)
    pt_ = jnp.asarray([[5, 2], [3, 6]], np.int32)
    k = jnp.asarray(_rand((2, nh, dh), 0))
    v = jnp.asarray(_rand((2, nh, dh), 1))
    # row 0 writes slot 3 (last of page 5); row 1 is masked out
    live = jnp.asarray([[1.0], [0.0]], np.float32)
    kp1, vp1 = ao.kv_cache_append_fn(kp, vp, k, v, pt_,
                                     jnp.asarray([3, 3], np.int32), live)
    np.testing.assert_allclose(np.asarray(kp1)[5, 3], np.asarray(k)[0])
    assert np.all(np.asarray(kp1)[3] == 0), "masked row wrote to its page"
    # row 0's next append (slot 4 == page boundary) lands in page 2 slot 0
    kp2, _ = ao.kv_cache_append_fn(kp1, vp1, k, v, pt_,
                                   jnp.asarray([4, 4], np.int32), live)
    np.testing.assert_allclose(np.asarray(kp2)[2, 0], np.asarray(k)[0])
    np.testing.assert_allclose(np.asarray(kp2)[5, 3], np.asarray(k)[0])


def test_paged_backend_tuner_lever(tmp_path):
    """A swept DB entry drives the decode-attention backend for its exact
    (b, nh, 1, sk, dh) key; an un-runnable pallas verdict (off-TPU, no
    interpreter) degrades to the reference at dispatch — numerics exact."""
    import jax.numpy as jnp

    snap = pt.flags.all_flags()
    db_path = str(tmp_path / "db.json")
    try:
        pt.flags.set_flags({"tuning_mode": "consult", "tuning_db": db_path})
        tuning.invalidate_db_cache()
        ps, nh, dh = 4, 2, 8
        lens = [6, 2]
        _, _, kp, vp, pt_ = _scattered_pool(lens, ps, nh, dh, num_pages=8)
        P = pt_.shape[1]
        key = tuning.canonical_key(
            "attention", tuning.attention_key(2, nh, 1, P * ps, dh, True),
            "float32", tuning.device_kind())
        db = tuning.TuningDB(db_path)
        db.put(key, {"backend": "pallas_paged"}, source="swept")
        db.save(db_path)
        tuning.invalidate_db_cache()
        backend, tier = ao.paged_attention_backend(2, nh, P * ps, dh,
                                                   np.dtype("float32"),
                                                   pool_shape=kp.shape)
        assert (backend, tier) == ("pallas_paged", "db")
        q = jnp.asarray(_rand((2, nh, dh), 5))
        out = ao.paged_decode_attention_fn(q, kp, vp, pt_,
                                           jnp.asarray(lens, np.int32),
                                           sm_scale=dh ** -0.5)
        ref = ao._paged_attention_reference(q, kp, vp, pt_,
                                            jnp.asarray(lens, np.int32),
                                            sm_scale=dh ** -0.5)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=1e-6)
    finally:
        pt.flags.set_flags(snap)
        tuning.invalidate_db_cache()


def test_decode_candidate_upgrades_via_tune(tmp_path, monkeypatch):
    """The PR 6 candidates workflow extended to decode attention: a
    sq=1 candidate key recorded by a sweep-mode run is measured and
    upgraded to a swept verdict by tools/tune.py."""
    from paddle_tpu.ops.pallas_kernels import paged_attention as ppa
    from tools import tune

    monkeypatch.setattr(ppa, "INTERPRET", True)  # both arms runnable on CPU
    pt.flags.set_flags({"serving_page_size": 8})
    try:
        db_path = str(tmp_path / "db.json")
        db = tuning.TuningDB(db_path)
        key = tuning.canonical_key(
            "attention", tuning.attention_key(2, 2, 1, 16, 8, True),
            "float32", tuning.device_kind())
        db.put(key, {"backend": "xla"}, source="candidate")
        tune.sweep_candidates(db, iters=1, passes=2, band=0.05)
        entry = db.lookup(key)
        assert entry["source"] == "swept"
        assert entry["decision"]["backend"] in ("xla", "pallas_paged")
        assert {"xla", "pallas_paged"} <= set(entry["measured"])
    finally:
        pt.flags.set_flags({"serving_page_size": 16})


# -- pool allocator ----------------------------------------------------------

def test_pool_allocator_edges():
    pool = PagedKVPool(4, 8)
    assert pool.pages_for(1) == 1 and pool.pages_for(8) == 1
    assert pool.pages_for(9) == 2
    got = pool.allocate(3)
    assert len(got) == 3 and pool.free_count == 1
    assert pool.allocate(2) is None, "partial grabs must not happen"
    assert pool.free_count == 1
    pool.free(got)
    assert pool.free_count == 4
    with pytest.raises(ValueError, match="double-free"):
        pool.free([got[0], got[0]])
    with pytest.raises(ValueError, match="outside pool"):
        pool.free([99])


def _assert_no_leaks(eng):
    """The ISSUE 11 leak contract: every in-use page is accounted for by a
    live request or a prefix-cache entry, and flushing the cache returns
    the WHOLE pool to the free list."""
    assert eng.leaked_pages() == 0, f"{eng.leaked_pages()} orphaned pages"
    eng.flush_prefix_cache()
    assert eng.pool.free_count == eng.pool.num_pages, (
        f"{eng.pool.num_pages - eng.pool.free_count} pages still held "
        f"after drain + cache flush")


# -- engine: equivalence against dense attention -----------------------------

def test_engine_generation_matches_dense_oracle():
    """The whole serving path (bucketed prefill -> paged ragged decode over
    scattered pages, with requests of different lengths batched together)
    greedy-generates EXACTLY what a dense full-context forward does."""
    cfg = decoder_tiny()
    eng = ServingEngine(cfg, page_size=4, pool_pages=64, max_inflight=4)
    rng = np.random.default_rng(7)
    prompts = [list(rng.integers(1, cfg.vocab_size, n)) for n in (3, 9, 17)]
    rids = [eng.submit(p, max_new_tokens=6) for p in prompts]
    eng.run_until_drained()

    full = Program()
    with program_guard(full, Program()), unique_name.guard():
        io = build_full_forward_program(cfg)
    for p, rid in zip(prompts, rids):
        seq = list(p)
        for _ in range(6):
            feed = {sv_model.TOK_FEED: np.asarray(seq, np.int32)[None, :],
                    sv_model.POS_FEED:
                        np.arange(len(seq), dtype=np.int32)[None, :]}
            (lg,) = eng._exe.run(full, feed=feed,
                                 fetch_list=[io["logits"]],
                                 scope=eng._scope)
            seq.append(int(np.argmax(lg[0, -1])))
        assert eng.result(rid) == seq[len(p):], f"request {rid} diverged"
    _assert_no_leaks(eng)


# -- engine: scheduling edge cases -------------------------------------------

def test_pool_exhaustion_backpressures_admission():
    """More requests than the pool can hold at once: admission queues them
    (never crashes, never oversubscribes) and every request still
    finishes once earlier ones release pages."""
    cfg = decoder_tiny()
    # 6 pages of 4 slots: one 9-token prompt + decode needs 3 pages, so at
    # most two requests fit concurrently
    eng = ServingEngine(cfg, page_size=4, pool_pages=6, max_inflight=8)
    rng = np.random.default_rng(1)
    rids = [eng.submit(list(rng.integers(1, cfg.vocab_size, 9)),
                       max_new_tokens=3) for _ in range(5)]
    eng.run_until_drained()
    assert all(eng.requests[r].state == "finished" for r in rids)
    assert eng.stats["peak_pages_in_use"] <= eng.pool.num_pages
    _assert_no_leaks(eng)


def test_oversize_request_raises_cleanly():
    cfg = decoder_tiny()
    eng = ServingEngine(cfg, page_size=4, pool_pages=2, max_inflight=2)
    with pytest.raises(ValueError, match="max_position"):
        eng.submit(list(range(1, 80)), max_new_tokens=60)
    # fits max_position but can never fit the 2-page pool: surfaced, not hung
    eng.submit(list(np.random.default_rng(0).integers(1, 97, 20)),
               max_new_tokens=2)
    with pytest.raises(RuntimeError, match="pool"):
        eng.run_until_drained()


def test_preemption_recomputes_exactly():
    """Mid-decode pool exhaustion preempts the youngest request; its
    re-prefilled continuation produces the SAME tokens a pressure-free pool
    yields (greedy decode + recompute preemption is exact). Prefix caching
    off: the PR 7 bitwise-recompute contract is for the plain engine —
    with the cache, a re-admission reuses its own cached prompt pages
    through the suffix path, whose last-bit drift is the same class the
    dense-oracle test tolerates but not bitwise the cold prefill."""
    cfg = decoder_tiny()
    rng = np.random.default_rng(3)
    prompts = [list(rng.integers(1, cfg.vocab_size, n)) for n in (7, 7)]

    big = ServingEngine(cfg, page_size=2, pool_pages=64, max_inflight=2,
                        prefix_cache=False)
    want = []
    for p in prompts:
        rid = big.submit(p, max_new_tokens=8)
        big.run_until_drained()
        want.append(big.result(rid))

    # 9 pages of 2 slots: both requests admit (4 pages each for 7+1 slots),
    # but growing to 15 slots each needs 16 pages total -> preemption
    small = ServingEngine(cfg, page_size=2, pool_pages=9, max_inflight=2,
                          prefix_cache=False)
    rids = [small.submit(p, max_new_tokens=8) for p in prompts]
    small.run_until_drained()
    assert small.stats["preemptions"] >= 1, "pool pressure never triggered"
    assert [small.result(r) for r in rids] == want
    assert small.pool.free_count == small.pool.num_pages


def test_sjf_policy_admits_shortest_first():
    cfg = decoder_tiny()
    eng = ServingEngine(cfg, page_size=4, pool_pages=64, max_inflight=1,
                        policy="sjf")
    rng = np.random.default_rng(5)
    long_rid = eng.submit(list(rng.integers(1, 97, 20)), max_new_tokens=2)
    short_rid = eng.submit(list(rng.integers(1, 97, 3)), max_new_tokens=2)
    eng.step()  # max_inflight=1: exactly one admission — sjf picks short
    assert eng.requests[short_rid].state in ("running", "finished")
    assert eng.requests[long_rid].state == "waiting"
    eng.run_until_drained()
    assert eng.requests[long_rid].state == "finished"


# -- compile discipline ------------------------------------------------------

def test_decode_compiles_once_per_bucket():
    """The compile-count contract (reusing the PR 2 jit_compile_counter
    hook): a full run compiles decode exactly once per (batch-bucket,
    page-bucket) signature, and a second identical wave through the same
    engine compiles NOTHING."""
    from paddle_tpu.pipeline import jit_compile_counter

    cfg = decoder_tiny()
    eng = ServingEngine(cfg, page_size=4, pool_pages=64, max_inflight=4)
    rng = np.random.default_rng(11)

    def wave():
        rids = [eng.submit(list(rng.integers(1, 97, n)), max_new_tokens=4)
                for n in (3, 5, 9, 12)]
        eng.run_until_drained()
        return rids

    with jit_compile_counter() as c1:
        wave()
    n_sigs = (len(eng.stats["prefill_signatures"])
              + len(eng.stats["decode_signatures"]))
    assert c1.count == n_sigs, (
        f"{c1.count} XLA compiles for {n_sigs} distinct bucket signatures "
        f"(prefill {eng.stats['prefill_signatures']}, decode "
        f"{eng.stats['decode_signatures']})")
    with jit_compile_counter() as c2:
        wave()
    assert c2.count == 0, (
        f"second wave recompiled {c2.count}x — bucketing failed to hit "
        f"the compile cache")


# -- chaos: aborted requests leak nothing ------------------------------------

@pytest.mark.chaos
def test_abort_mid_decode_returns_pages_over_cycles():
    """`serving_abort` fault site extended to SHARED-PREFIX requests
    (ISSUE 11): every cycle submits requests sharing a system prompt, so
    aborts hit requests whose page tables map refcounted shared pages.
    An abort must decrement refcounts — never free a page another request
    (or the prefix cache) still maps — and after every drain the zero-leak
    accounting must balance; at the end, flushing the cache returns the
    WHOLE pool."""
    from paddle_tpu.resilience.faults import fault_scope

    cfg = decoder_tiny()
    eng = ServingEngine(cfg, page_size=4, pool_pages=32, max_inflight=4)
    rng = np.random.default_rng(13)
    sys_prompt = list(rng.integers(1, 97, 8))  # page-aligned: COW territory
    total_aborts = 0
    for cycle in range(3):
        with fault_scope("serving_abort:2,4") as plan:
            rids = [eng.submit(sys_prompt + list(rng.integers(1, 97, n)),
                               max_new_tokens=6) for n in (0, 5, 10)]
            eng.run_until_drained()
            assert plan.stats()["fired"], "abort plan never fired"
        states = {eng.requests[r].state for r in rids}
        assert states <= {"finished", "aborted"}
        assert "aborted" in states, f"cycle {cycle}: nothing was aborted"
        total_aborts += sum(1 for r in rids
                            if eng.requests[r].state == "aborted")
        assert eng.leaked_pages() == 0, (
            f"cycle {cycle} orphaned {eng.leaked_pages()} pages")
        # cached shared pages survive the cycle with exactly the cache's ref
        for node in eng.prefix_cache._nodes.values():
            assert eng.pool.refcount(node.page) >= 1
    assert eng.stats["aborts"] == total_aborts
    assert eng.stats["prefix_hit_tokens"] > 0, "no prefix sharing exercised"
    eng.flush_prefix_cache()
    assert eng.pool.free_count == eng.pool.num_pages
