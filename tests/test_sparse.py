"""SelectedRows sparse embedding-gradient path (reference selected_rows.h:32 +
lookup_table_op sparse grad + sgd_op SelectedRows kernel)."""
import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import layers as L
from paddle_tpu.core.selected_rows import SelectedRows


def test_selected_rows_to_dense_merges_duplicates():
    sr = SelectedRows(
        rows=np.array([1, 3, 1], np.int32),
        values=np.array([[1.0, 2.0], [3.0, 4.0], [10.0, 20.0]], np.float32),
        height=5,
    )
    dense = np.asarray(sr.to_dense())
    expect = np.zeros((5, 2), np.float32)
    expect[1] = [11.0, 22.0]
    expect[3] = [3.0, 4.0]
    np.testing.assert_allclose(dense, expect)
    uniq, merged = sr.merged()
    np.testing.assert_array_equal(uniq, [1, 3])
    np.testing.assert_allclose(merged, [[11.0, 22.0], [3.0, 4.0]])


def _train_embedding(is_sparse, steps=5):
    main, startup = pt.Program(), pt.Program()
    main.random_seed = 7
    startup.random_seed = 7
    with pt.program_guard(main, startup):
        with pt.unique_name.guard():
            ids = L.data(name="ids", shape=[4], dtype="int64")
            y = L.data(name="y", shape=[1], dtype="float32")
            emb = L.embedding(ids, size=[50, 8], is_sparse=is_sparse,
                              param_attr=pt.ParamAttr(name="emb_w"))
            pooled = L.reduce_sum(emb, dim=1)
            pred = L.fc(pooled, size=1)
            loss = L.mean(L.square_error_cost(pred, y))
            pt.optimizer.SGD(0.1).minimize(loss)
    scope = pt.Scope()
    exe = pt.Executor()
    rng = np.random.default_rng(0)
    idv = rng.integers(0, 50, (16, 4)).astype(np.int64)
    yv = rng.standard_normal((16, 1)).astype(np.float32)
    with pt.scope_guard(scope):
        exe.run(startup)
        hist = []
        for _ in range(steps):
            (lv,) = exe.run(main, feed={"ids": idv, "y": yv},
                            fetch_list=[loss.name])
            hist.append(float(np.asarray(lv).reshape(-1)[0]))
        w = np.asarray(scope.find_var("emb_w"))
    return hist, w


def test_sparse_embedding_grad_matches_dense():
    """is_sparse=True (SelectedRows grad + sparse sgd scatter) must produce
    the exact same trajectory as the dense scatter-add path."""
    dense_hist, dense_w = _train_embedding(False)
    sparse_hist, sparse_w = _train_embedding(True)
    np.testing.assert_allclose(dense_hist, sparse_hist, rtol=1e-5)
    np.testing.assert_allclose(dense_w, sparse_w, rtol=1e-5, atol=1e-6)
    assert dense_hist[-1] < dense_hist[0]


def test_sparse_grad_with_momentum_raises():
    with pt.program_guard(pt.Program(), pt.Program()):
        ids = L.data(name="ids", shape=[4], dtype="int64")
        emb = L.embedding(ids, size=[20, 4], is_sparse=True)
        loss = L.mean(L.reduce_sum(emb, dim=1))
        pt.optimizer.Momentum(0.1, momentum=0.9).minimize(loss)
        exe = pt.Executor()
        exe.run(pt.default_startup_program())
        with pytest.raises(pt.OpError, match="SelectedRows"):
            exe.run(pt.default_main_program(),
                    feed={"ids": np.zeros((8, 4), np.int64)},
                    fetch_list=[loss.name])
