"""Flags-documentation lint (tier-1): every FLAGS_* declared in
paddle_tpu/flags.py must be mentioned in README.md.

The drift this catches is real: by PR 6 ten flags (pallas_xent, the
communicator knobs, profiler/debug toggles) had accumulated with README
silence, and the new tuning flags would have joined them. A flag the README
does not name is a lever operators cannot find — and the lint makes adding
one a documentation act, not just a _define call.
"""
import os
import re

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _declared_flags() -> list[str]:
    src = open(os.path.join(REPO, "paddle_tpu", "flags.py")).read()
    return re.findall(r'^_define\(\s*"(\w+)"', src, flags=re.MULTILINE)


def test_every_flag_is_documented_in_readme():
    readme = open(os.path.join(REPO, "README.md")).read()
    declared = _declared_flags()
    assert declared, "flags.py parse found no _define declarations"
    missing = [f"FLAGS_{name}" for name in declared
               if f"FLAGS_{name}" not in readme]
    assert not missing, (
        f"flags declared in paddle_tpu/flags.py but absent from README.md: "
        f"{missing} — document what each does (and its default) in the "
        f"relevant README section")


def test_readme_names_no_phantom_flags():
    """The inverse drift: README mentioning a FLAGS_* that no longer exists
    sends operators to a KeyError."""
    readme = open(os.path.join(REPO, "README.md")).read()
    declared = set(_declared_flags())
    mentioned = set(re.findall(r"FLAGS_(\w+)", readme))
    phantom = sorted(m for m in mentioned if m not in declared)
    assert not phantom, (
        f"README.md documents flags that paddle_tpu/flags.py no longer "
        f"declares: {phantom}")
