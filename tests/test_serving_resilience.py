"""Serving-resilience tests (ISSUE 14): request deadlines, overload
shedding + priority classes, the graceful-degradation ladder, supervised
dispatch (retry absorption), and exact crash recovery (quarantine + pool
rebuild + prompt replay, bitwise-equal to a fault-free run under greedy).
Plus the PagedKVPool invariant audit and the WAITING-abort admission-pin
regression."""
import time

import numpy as np
import pytest

from paddle_tpu import observability as obs
from paddle_tpu.resilience.faults import fault_scope
from paddle_tpu.serving import (AdmissionRejected, PagedKVPool,
                                ServingEngine, decoder_tiny)


def _prompt(seed: int, n: int) -> list:
    return np.random.default_rng(seed).integers(1, 97, n).tolist()


def _engine(**kw):
    kw.setdefault("page_size", 4)
    kw.setdefault("pool_pages", 64)
    kw.setdefault("max_inflight", 4)
    kw.setdefault("draft_k", 0)
    return ServingEngine(decoder_tiny(), **kw)


# -- pool invariant audit (PagedKVPool.check_consistency / reset) ------------

def test_check_consistency_clean_and_each_corruption_kind():
    pool = PagedKVPool(num_pages=8, page_size=4)
    pages = pool.allocate(3)
    assert pool.check_consistency() == []
    assert pool.check_consistency(holders={p: 1 for p in pages}) == []

    # phantom holder: refcount says 2, the holder map says 1
    pool._refs[pages[0]] += 1
    assert pool.check_consistency(holders={p: 1 for p in pages})
    pool._refs[pages[0]] -= 1

    # live page pushed back on the free list
    pool._free.append(pages[1])
    assert pool.check_consistency()
    pool._free.pop()

    # duplicate free-list entry
    pool._free.append(pool._free[-1])
    assert pool.check_consistency()
    pool._free.pop()

    assert pool.check_consistency() == []
    pool.reset()
    assert pool.free_count == pool.num_pages
    assert pool.check_consistency() == []


# -- deadlines: WAITING / mid-decode / crossing the first step ---------------

def test_deadline_expires_while_waiting():
    obs.reset("serving.")
    eng = _engine()
    rid = eng.submit(_prompt(0, 5), 4, deadline_s=1e-4)
    time.sleep(0.01)
    eng.step()  # top-of-step expiry fires before admission
    req = eng.requests[rid]
    assert req.state == "deadline_exceeded"
    assert req.pages == [] and req.n_generated == 0
    assert eng.stats["deadline_exceeded"] == 1
    assert obs.snapshot()["counters"].get("serving.deadline_exceeded") == 1
    assert not eng.has_work()
    assert eng.leaked_pages() == 0
    assert eng.pop_result(rid) == []


def test_deadline_expires_mid_decode_keeps_partial_tokens():
    eng = _engine()
    rid = eng.submit(_prompt(1, 5), 8)
    eng.step()  # admit + prefill + first decode
    req = eng.requests[rid]
    assert req.state == "running" and req.n_generated >= 1
    req.deadline_t = time.perf_counter() - 1.0
    eng.step()
    assert req.state == "deadline_exceeded"
    assert req.pages == [], "expiry must return every page"
    assert 1 <= req.n_generated < 8, "partial output is kept"
    assert eng.stats["deadline_exceeded"] == 1
    assert eng.leaked_pages() == 0
    eng.flush_prefix_cache()
    assert eng.pool.free_count == eng.pool.num_pages


def test_deadline_crossing_inside_first_step_caught_same_step():
    """A TTL that expires DURING the admission/prefill/decode span is
    caught by the post-decode sweep in the same scheduler step — pages
    release immediately, not one iteration later."""
    eng = _engine()
    rid = eng.submit(_prompt(2, 6), 4)
    # generous vs the pre-admission check, tiny vs the first step's XLA
    # compile (hundreds of ms on CPU)
    eng.requests[rid].deadline_t = time.perf_counter() + 0.02
    eng.step()
    req = eng.requests[rid]
    assert req.state == "deadline_exceeded"
    assert req.pages == []
    assert eng.stats["deadline_exceeded"] == 1
    assert eng.leaked_pages() == 0


# -- satellite: aborting a WAITING request releases its admission pin --------

def test_abort_waiting_request_releases_prefix_pin():
    """A failed admission attempt leaves the matched prefix-cache pages
    PINNED on the waiting request (so eviction relief cannot free the
    match). abort() of that WAITING request must release the pin — the
    leak the pre-ISSUE-14 abort (waiting-queue removal only) had."""
    eng = _engine(pool_pages=16, prefix_cache=True)
    sysp = _prompt(3, 8)  # two full pages: prefix-cache territory
    a = eng.submit(sysp, 2)
    eng.run_until_drained()
    assert eng.requests[a].state == "finished"
    cache_pages = [n.page for n in eng.prefix_cache._nodes.values()]
    assert len(cache_pages) == 2

    # r outlives the next step (prefill emits token 1, one decode per
    # step) and its admission grant of pages_for(5+1)=2 pages covers all
    # 8 final slots, so it never needs the starved pool again
    r = eng.submit(_prompt(4, 5), 3)
    eng.step()  # admit + prefill + first decode: r keeps running
    hold = eng.pool.allocate(eng.pool.free_count)  # starve the pool
    assert hold is not None

    b = eng.submit(sysp + _prompt(5, 4), 2)
    eng.step()  # admission matches the cached prefix, private alloc fails
    breq = eng.requests[b]
    assert breq.state == "waiting"
    assert sorted(breq.pages) == sorted(cache_pages), "pin not recorded"
    assert all(eng.pool.refcount(p) == 2 for p in cache_pages)

    eng.abort(b)
    assert breq.state == "aborted" and breq.pages == []
    assert all(eng.pool.refcount(p) == 1 for p in cache_pages), (
        "abort of a WAITING request must release its admission pin")

    eng.pool.release(hold)
    eng.run_until_drained()
    assert eng.requests[r].state == "finished"
    assert eng.leaked_pages() == 0
    eng.flush_prefix_cache()
    assert eng.pool.free_count == eng.pool.num_pages


# -- admission control: priority shedding + reject-with-retry-after ----------

def test_admission_rejects_and_sheds_by_priority():
    eng = _engine(max_inflight=1, shed_queue_depth=2)
    a = eng.submit(_prompt(6, 4), 2, priority=0)
    b = eng.submit(_prompt(7, 4), 2, priority=0)

    # same class: nothing strictly lower to shed -> explicit refusal
    with pytest.raises(AdmissionRejected) as ei:
        eng.submit(_prompt(8, 4), 2, priority=0)
    assert "queue_depth" in ei.value.signals
    assert ei.value.retry_after_s > 0
    assert eng.stats["rejects"] == 1

    # higher class: sheds the youngest lowest-priority waiter (b) instead
    d = eng.submit(_prompt(9, 4), 2, priority=5)
    assert eng.requests[b].state == "shed"
    assert eng.stats["shed"] == 1
    assert eng.pop_result(b) == []

    eng.run_until_drained()
    assert eng.requests[a].state == "finished"
    assert eng.requests[d].state == "finished"
    assert eng.leaked_pages() == 0


# -- the graceful-degradation ladder -----------------------------------------

def test_ladder_climbs_under_pressure_and_descends_calm():
    """Occupancy pressure climbs the ladder one rung per `degrade_after`
    pressured steps (each rung counted), rung 4 sheds waiters; a calm
    streak of the same length walks it back down to nominal."""
    eng = _engine(pool_pages=8, max_inflight=2, prefix_cache=False,
                  shed_occupancy=0.3, degrade_after=1)
    # long enough to span several steps: two running requests hold 4-6 of
    # the 8 pages, so the occupancy floor stays tripped between steps
    rids = [eng.submit(_prompt(10 + i, 3), 6) for i in range(6)]
    eng.run_until_drained()
    for rung in ("spec_off", "lookahead_shrink", "cache_evict", "shed"):
        assert eng.stats["ladder." + rung] >= 1, f"rung {rung} never hit"
    assert eng.stats["shed"] >= 1, "rung 4 shed no waiter"
    states = {eng.requests[r].state for r in rids}
    assert states <= {"finished", "shed"}
    assert "finished" in states
    assert eng.leaked_pages() == 0
    # idle steps: occupancy is back to zero, the ladder walks down
    for _ in range(8):
        eng.step()
    assert eng._ladder_rung == 0


# -- supervision: retry absorption + exact recovery --------------------------

def _drain_outputs(eng, seeds, max_new=4):
    rids = [eng.submit(_prompt(s, 5), max_new) for s in seeds]
    eng.run_until_drained()
    return {i: eng.requests[r].out_tokens for i, r in enumerate(rids)}, rids


def test_transient_step_faults_absorbed_by_retry():
    """Isolated dispatch faults (hits 3 and 7 — different dispatches) are
    absorbed by the retry policy: outputs bitwise-equal to fault-free, no
    recovery pass."""
    seeds = (20, 21, 22)
    want, _ = _drain_outputs(_engine(prefix_cache=False, seed=0), seeds)
    eng = _engine(prefix_cache=False, seed=0, step_retries=3)
    with fault_scope("serving_step_fail:3,7") as plan:
        got, _ = _drain_outputs(eng, seeds)
        assert plan.stats()["fired"]
    assert got == want
    assert eng.stats["step_retries"] == 2
    assert eng.stats["recovery.passes"] == 0
    assert eng.leaked_pages() == 0


def test_recovery_oracle_step_fail_exhaustion():
    """Hits 5,6,7 burn every attempt of ONE dispatch: the supervisor runs
    a recovery pass (pool rebuild + prompt replay) and the final outputs
    are STILL bitwise-equal to the fault-free run — greedy decode is
    deterministic, so replay-from-prompt is exact."""
    seeds = (30, 31, 32)
    want, _ = _drain_outputs(_engine(prefix_cache=False, seed=0), seeds)
    eng = _engine(prefix_cache=False, seed=0, step_retries=3)
    with fault_scope("serving_step_fail:5,6,7") as plan:
        got, _ = _drain_outputs(eng, seeds)
        assert plan.stats()["fired"]
    assert got == want, "recovery replay diverged from the fault-free run"
    assert eng.stats["recovery.passes"] == 1
    assert eng.stats["recovery.replayed"] >= 1
    assert eng.stats["recovery.quarantined"] == 0
    assert eng.leaked_pages() == 0
    assert eng.pool.free_count == eng.pool.num_pages


def test_recovery_quarantines_poisoned_request():
    """Corruption kind 2 (duplicate ordinal in the newest running page
    table) poisons that request: the per-step audit catches it, recovery
    quarantines it (aborted, pages forfeited) and replays the others to
    fault-free-identical outputs over a rebuilt pool."""
    seeds = (40, 41, 42)
    want, _ = _drain_outputs(_engine(prefix_cache=False, seed=0), seeds)
    eng = _engine(prefix_cache=False, seed=0, audit_every=1)
    with fault_scope("serving_pool_corrupt:2") as plan:
        got, rids = _drain_outputs(eng, seeds)
        assert plan.stats()["fired"]
    assert eng.stats["recovery.passes"] == 1
    assert eng.stats["recovery.quarantined"] == 1
    quarantined = [i for i, r in enumerate(rids)
                   if eng.requests[r].state == "aborted"]
    assert len(quarantined) == 1
    for i, r in enumerate(rids):
        if i in quarantined:
            continue
        assert eng.requests[r].state == "finished"
        assert got[i] == want[i], f"survivor {i} diverged after recovery"
    problems, _ = eng.audit_pool()
    assert problems == []
    assert eng.leaked_pages() == 0
    assert eng.pool.free_count == eng.pool.num_pages


def test_recovery_from_refcount_corruption_replays_all():
    """Corruption kind 0 (phantom refcount holder) dirties the pool audit
    without poisoning any page table: recovery replays EVERY live request
    and quarantines none."""
    seeds = (50, 51)
    want, _ = _drain_outputs(_engine(prefix_cache=False, seed=0), seeds)
    eng = _engine(prefix_cache=False, seed=0, audit_every=1)
    with fault_scope("serving_pool_corrupt:3") as plan:  # hit 3 -> kind 0
        got, rids = _drain_outputs(eng, seeds)
        assert plan.stats()["fired"]
    assert eng.stats["recovery.passes"] == 1
    assert eng.stats["recovery.quarantined"] == 0
    assert all(eng.requests[r].state == "finished" for r in rids)
    assert got == want
    assert eng.leaked_pages() == 0


# -- chaos: the serving drill (tools/chaos.py --serve) ------------------------

@pytest.mark.chaos
def test_serve_drill_survives_random_fault_plans():
    """The tools/chaos.py --serve drill, small: random plans over all
    three serving fault sites; the drill itself asserts clean terminal
    states, a clean pool audit and zero leaks every cycle."""
    from tools.chaos import run_serve_drill

    out = run_serve_drill(cycles=2, n_req=4, p=0.12, seed=3)
    fired = [f for c in out["cycles"] for f in c["fired"]]
    assert fired, "the random plans never fired a fault"
    assert out["leaked_pages"] == 0
