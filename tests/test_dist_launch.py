"""Launcher + multi-process collective training (reference
unittests/test_dist_base.py:442 TestDistBase pattern, collective/NCCL2 mode):
`python -m paddle_tpu.distributed.launch` over 2 localhost CPU processes must
reproduce the single-process full-batch parameter trajectory."""
import os
import subprocess
import sys

import numpy as np

_DIR = os.path.dirname(os.path.abspath(__file__))
_REPO = os.path.dirname(_DIR)
_SCRIPT = os.path.join(_DIR, "dist_collective.py")


def _env():
    env = dict(os.environ)
    env["PYTHONPATH"] = _REPO + os.pathsep + env.get("PYTHONPATH", "")
    # the conftest pins XLA_FLAGS for the in-process suite; workers provision
    # their own device count via init_parallel_env
    env.pop("PADDLE_TRAINERS_NUM", None)
    env.pop("PADDLE_TRAINER_ID", None)
    return env


def test_launch_two_process_collective_matches_local(tmp_path):
    local_out = str(tmp_path / "local.npz")
    p = subprocess.run(
        [sys.executable, _SCRIPT, local_out],
        env=_env(), capture_output=True, timeout=300)
    assert p.returncode == 0, p.stderr.decode()[-3000:]

    log_dir = str(tmp_path / "log")
    dist_out = str(tmp_path / "dist")  # each rank writes dist.r{rank}.npz
    p = subprocess.run(
        [sys.executable, "-m", "paddle_tpu.distributed.launch",
         "--nproc_per_node", "2", "--backend", "cpu",
         "--local_devices_per_proc", "1", "--log_dir", log_dir,
         _SCRIPT, dist_out],
        env=_env(), cwd=_REPO, capture_output=True, timeout=300)
    logs = ""
    for i in range(2):
        f = os.path.join(log_dir, f"workerlog.{i}")
        if os.path.exists(f):
            with open(f) as fh:
                logs += f"--- workerlog.{i}\n" + fh.read()[-3000:]
    assert p.returncode == 0, logs + p.stderr.decode()[-2000:]

    local = np.load(local_out)
    r0 = np.load(dist_out + ".r0.npz")
    r1 = np.load(dist_out + ".r1.npz")
    for k in local.files:
        if k == "__last_loss__":
            continue
        np.testing.assert_allclose(
            local[k], r0[k], rtol=1e-4, atol=1e-5,
            err_msg=f"param {k} diverged from local baseline")
        np.testing.assert_allclose(
            r0[k], r1[k], rtol=1e-6, atol=1e-7,
            err_msg=f"ranks disagree on param {k}")


def test_launch_ps_spawns_servers_and_workers(tmp_path):
    """`launch --server_num --worker_num` drives a real 2-server/2-trainer
    fleet job end-to-end: roles arrive via the exported PADDLE_* envs
    (reference launch_ps.py:55-82), trainers converge and agree (sync)."""
    script = os.path.join(_DIR, "dist_ps_launched.py")
    p = subprocess.run(
        [sys.executable, "-m", "paddle_tpu.distributed.launch",
         "--server_num=2", "--worker_num=2",
         f"--log_dir={tmp_path / 'logs'}", script, str(tmp_path)],
        env=_env(), capture_output=True, timeout=300)
    logs = ""
    logdir = tmp_path / "logs"
    if logdir.exists():
        for f in sorted(logdir.iterdir()):
            logs += f"\n--- {f.name} ---\n" + f.read_text()[-2000:]
    assert p.returncode == 0, (p.stdout.decode()[-1000:],
                               p.stderr.decode()[-1000:], logs[-6000:])
    t0 = np.load(tmp_path / "trainer0.npz")
    t1 = np.load(tmp_path / "trainer1.npz")
    losses = t0["__losses__"]
    assert losses[-1] < losses[0], losses
    for k in t0.files:
        if k.startswith("__"):
            continue
        np.testing.assert_allclose(t0[k], t1[k], rtol=1e-5, atol=1e-6)
