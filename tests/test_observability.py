"""Unified telemetry layer tests (ISSUE 13): registry semantics under
concurrency, the legacy-shim contracts (profiler stage counters, serving
stats), exporter round-trips (JSONL bytes, Prometheus text), SLO
escalation, and the gate/CLI tooling on top."""
import json
import os
import re
import subprocess
import sys
import threading

import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import observability as obs
from paddle_tpu import profiler
from paddle_tpu.observability import (JsonlWriter, MetricsRegistry,
                                      SloMonitor, jsonl_line,
                                      parse_prometheus, prometheus_text,
                                      schema, write_prometheus)
from paddle_tpu.observability.slo import gauge_above

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# -- registry core ------------------------------------------------------------

def test_counters_gauges_and_labeled_series():
    reg = MetricsRegistry(schema.DECLARED)
    reg.counter_inc("serving.prefills")
    reg.counter_inc("serving.prefills", 4)
    reg.gauge_set("serving.pool_occupancy", 0.25)
    reg.counter_inc("emb.hit_ids", 7, labels={"table": "emb_a"})
    reg.counter_inc("emb.hit_ids", 1, labels={"table": "emb_b"})
    snap = reg.snapshot()
    assert snap["counters"]["serving.prefills"] == 5
    assert snap["gauges"]["serving.pool_occupancy"] == 0.25
    # a (name, labels) pair is one series, rendered Prometheus-style
    assert snap["counters"]['emb.hit_ids{table="emb_a"}'] == 7
    assert snap["counters"]['emb.hit_ids{table="emb_b"}'] == 1
    assert obs.base_name('emb.hit_ids{table="emb_a"}') == "emb.hit_ids"


def test_histogram_percentiles_within_bucket_tolerance():
    reg = MetricsRegistry(schema.DECLARED)
    vals = [0.001 * (i + 1) for i in range(100)]  # 1ms .. 100ms uniform
    for v in vals:
        reg.histogram_observe("serving.ttft_s", v)
    h = reg.snapshot()["histograms"]["serving.ttft_s"]
    assert h["count"] == 100
    assert h["min"] == pytest.approx(0.001)
    assert h["max"] == pytest.approx(0.100)
    assert h["sum"] == pytest.approx(sum(vals))
    # log buckets are 10^(1/8) wide, so a quantile is within ~15% true
    assert h["p50"] == pytest.approx(0.050, rel=0.20)
    assert h["p99"] == pytest.approx(0.099, rel=0.20)
    # quantiles never escape the observed range
    assert h["min"] <= h["p50"] <= h["p99"] <= h["max"]


def test_undeclared_names_record_but_are_flagged():
    reg = MetricsRegistry(schema.DECLARED)
    reg.counter_inc("serving.prefills")      # declared: clean
    reg.counter_inc("rogue.metric")          # undeclared: lands AND flags
    snap = reg.snapshot()
    assert snap["counters"]["rogue.metric"] == 1
    assert snap["undeclared"] == ["rogue.metric"]
    reg.declare("rogue.metric", schema.COUNTER, "now blessed")
    assert reg.snapshot()["undeclared"] == []


def test_snapshot_reset_is_atomic_under_8_threads():
    """8 writers hammer one counter + one histogram while a reader does
    snapshot(reset=True) concurrently; nothing is lost or double-counted
    across the reset boundaries."""
    reg = MetricsRegistry(schema.DECLARED)
    N, THREADS = 500, 8
    stop = threading.Event()
    seen = {"count": 0.0, "hist": 0}

    def writer():
        for _ in range(N):
            reg.counter_inc("train.steps")
            reg.histogram_observe("train.step_latency_s", 0.01)

    def reader():
        while not stop.is_set():
            snap = reg.snapshot(reset=True)
            seen["count"] += snap["counters"].get("train.steps", 0)
            seen["hist"] += snap["histograms"].get(
                "train.step_latency_s", {}).get("count", 0)

    ws = [threading.Thread(target=writer) for _ in range(THREADS)]
    r = threading.Thread(target=reader)
    r.start()
    for w in ws:
        w.start()
    for w in ws:
        w.join()
    stop.set()
    r.join()
    final = reg.snapshot()
    seen["count"] += final["counters"].get("train.steps", 0)
    seen["hist"] += final["histograms"].get(
        "train.step_latency_s", {}).get("count", 0)
    assert seen["count"] == N * THREADS
    assert seen["hist"] == N * THREADS


def test_reset_prefix_scopes_the_clear():
    reg = MetricsRegistry(schema.DECLARED)
    reg.counter_inc("serving.prefills")
    reg.counter_inc("train.steps")
    reg.stage_record("pipeline.dispatch", 0.1)
    reg.reset("serving.")
    snap = reg.snapshot()
    assert "serving.prefills" not in snap["counters"]
    assert snap["counters"]["train.steps"] == 1
    assert snap["stages"]["pipeline.dispatch"]["events"] == 1


# -- legacy shim contracts ----------------------------------------------------

def test_profiler_stage_shims_keep_pr2_semantics():
    profiler.stage_counters(reset=True)  # scope: drop whatever ran before
    profiler.record_stage("pipeline.dispatch", 0.25, events=2)
    profiler.bump("feed.skip_corrupt", 3)
    c = profiler.stage_counters()
    assert c["pipeline.dispatch"] == {"events": 2, "seconds": 0.25}
    assert c["feed.skip_corrupt"] == {"events": 3, "seconds": 0.0}
    # the same accumulators are visible through the unified snapshot
    snap = obs.snapshot()
    assert snap["stages"]["pipeline.dispatch"]["seconds"] == 0.25
    # reset=True zeroes (epoch-scoped reads), as PR 2 call sites expect
    assert profiler.stage_counters(reset=True)["pipeline.dispatch"][
        "events"] == 2
    assert profiler.stage_counters() == {}


def test_every_legacy_stage_literal_is_declared():
    """Source-scan regression: every bump("x")/record_stage("x") literal in
    the tree must name a declared stage — adding a stage is a schema act."""
    pat = re.compile(r'(?:\bbump|\brecord_stage|\bstage_timer)\(\s*"([^"]+)"')
    used = set()
    pkg = os.path.join(REPO, "paddle_tpu")
    for dirpath, _, files in os.walk(pkg):
        if os.path.basename(dirpath) == "observability":
            continue  # the layer's own docs show `bump("...")` examples
        for fn in files:
            if fn.endswith(".py"):
                with open(os.path.join(dirpath, fn)) as f:
                    used |= set(pat.findall(f.read()))
    assert used, "source scan found no stage call sites"
    undeclared = sorted(used - schema.STAGE_NAMES)
    assert not undeclared, (
        f"stage literals not declared in observability/schema.py: "
        f"{undeclared}")


def test_stats_snapshot_spec_rate_guard():
    """Speculation configured but no spec step run yet: the derived rates
    must read 0.0 — never ZeroDivisionError, never NaN."""
    from paddle_tpu.serving import ServingEngine, decoder_tiny

    eng = ServingEngine(decoder_tiny(), page_size=4, pool_pages=16,
                        max_inflight=2, draft_k=2)
    ss = eng.stats_snapshot()
    assert ss["spec_accept_rate"] == 0.0
    assert ss["tokens_per_decode_step"] == 0.0
    assert ss["prefix_cache_hit_rate"] == 0.0
    assert ss["occupancy_mean"] == 0.0
    assert all(np.isfinite(v) for v in ss.values()
               if isinstance(v, (int, float)))


def test_serving_engine_mirrors_stats_into_registry():
    """A live run: every registry serving.* counter equals the engine's
    stats dict entry, and the occupancy gauges match the pool."""
    from paddle_tpu.serving import ServingEngine, decoder_tiny

    obs.reset("serving.")  # scope: earlier tests share the process registry
    cfg = decoder_tiny()
    eng = ServingEngine(cfg, page_size=4, pool_pages=32, max_inflight=4)
    rng = np.random.default_rng(3)
    for n in (3, 9):
        eng.submit(list(rng.integers(1, cfg.vocab_size, n)),
                   max_new_tokens=4)
    eng.run_until_drained()
    snap = obs.snapshot()
    for key in ("prefills", "decode_steps", "decode_tokens",
                "prefill_tokens_computed", "prefix_lookups"):
        assert snap["counters"].get("serving." + key, 0) == eng.stats[key], key
    assert snap["gauges"]["serving.pages_in_use"] == (
        eng.pool.num_pages - eng.pool.free_count)
    # histograms + request events rode along (flag default: enabled)
    assert snap["histograms"]["serving.ttft_s"]["count"] == 2
    assert snap["histograms"]["serving.request_s"]["count"] == 2
    phases = [e["payload"]["phase"] for e in snap["events"]
              if e["name"] == "serving.request"]
    for ph in ("queued", "admitted", "first_token", "finished"):
        assert ph in phases, f"missing lifecycle phase {ph}"

    # Prometheus round-trip on the live snapshot: render -> strict-parse
    text = prometheus_text(snap)
    parsed = parse_prometheus(text)
    assert parsed["serving_prefills"] == eng.stats["prefills"]
    assert parsed['serving_ttft_s_count'] == 2


# -- profiler trace-lifecycle guards ------------------------------------------

def test_stop_profiler_without_start_names_the_fix():
    with pytest.raises(RuntimeError, match="start_profiler"):
        pt.profiler.stop_profiler()


def test_failed_trace_start_leaves_no_half_open_state(tmp_path, monkeypatch):
    def boom(path, exist_ok=False):
        raise OSError("read-only filesystem")

    monkeypatch.setattr(profiler.os, "makedirs", boom)
    with pytest.raises(OSError, match="read-only"):
        with profiler.profiler(profile_path=str(tmp_path / "trace")):
            pass  # pragma: no cover — begin fails before the body
    monkeypatch.undo()
    # nothing half-open: the lifecycle flag is clean and stop still gives
    # the instructive error, not a raw jax one
    assert profiler._trace_active is False
    with pytest.raises(RuntimeError, match="start_profiler"):
        profiler.stop_profiler()


# -- exporters ----------------------------------------------------------------

def test_jsonl_writer_rotation_and_byte_roundtrip(tmp_path):
    path = str(tmp_path / "obs.jsonl")
    w = JsonlWriter(path, rotate_bytes=4096)
    for i in range(120):
        w.write({"ts": float(i), "type": "event", "name": "serving.request",
                 "level": "info", "payload": {"rid": i, "pad": "x" * 40}})
    w.close()
    assert os.path.exists(path + ".1"), "size rotation never triggered"
    rids = []
    for p in (path + ".1", path):
        with open(p, "rb") as f:
            for line in f:
                rec = json.loads(line)
                assert jsonl_line(rec) == line  # byte-for-byte contract
                rids.append(rec["payload"]["rid"])
    # the two retained files hold a contiguous, complete tail of the
    # stream ending at the newest record (older generations were rotated
    # away, never torn mid-line)
    assert rids == list(range(rids[0], 120))


def test_prometheus_file_roundtrip_and_strict_parse(tmp_path):
    reg = MetricsRegistry(schema.DECLARED)
    reg.counter_inc("train.steps", 17)
    reg.gauge_set("serving.pool_occupancy", 0.5)
    reg.counter_inc("tuning.decisions", labels={"op": "fc", "tier": "db"})
    reg.stage_record("pipeline.dispatch", 1.5, events=3)
    reg.histogram_observe("serving.ttft_s", 0.02)
    path = str(tmp_path / "metrics.prom")
    text = write_prometheus(path, reg.snapshot())
    with open(path) as f:
        assert f.read() == text  # temp+rename wrote exactly the render
    parsed = parse_prometheus(text)
    assert parsed["train_steps"] == 17
    assert parsed['tuning_decisions{op="fc",tier="db"}'] == 1
    assert parsed["pipeline_dispatch_events"] == 3
    assert parsed["pipeline_dispatch_seconds_total"] == 1.5
    assert parsed["serving_ttft_s_count"] == 1
    with pytest.raises(ValueError, match="unparseable"):
        parse_prometheus("this is not exposition format\n")


def test_http_exporter_serves_live_snapshot():
    import urllib.request

    reg = MetricsRegistry(schema.DECLARED)
    reg.counter_inc("train.steps", 5)
    try:
        server = obs.start_http_exporter(reg, port=0)
    except OSError as e:  # sandboxed runner without loopback bind
        pytest.skip(f"cannot bind loopback: {e}")
    try:
        port = server.server_address[1]
        body = urllib.request.urlopen(
            f"http://127.0.0.1:{port}/metrics", timeout=5).read().decode()
        assert parse_prometheus(body)["train_steps"] == 5
    finally:
        server.shutdown()


# -- SLO monitor --------------------------------------------------------------

def test_slo_monitor_escalates_warn_to_alert():
    reg = MetricsRegistry(schema.DECLARED)
    hits = []
    mon = SloMonitor(registry=reg, window_s=60.0, alert_after=2,
                     on_warn=lambda b: hits.append(("warn", b)),
                     on_alert=lambda b: hits.append(("alert", b)))
    mon.add_rule("leak", gauge_above("serving.leaked_pages", 0.0), 0)
    reg.gauge_set("serving.leaked_pages", 0.0)
    assert mon.observe(now=0.0) == []          # healthy: no breach
    reg.gauge_set("serving.leaked_pages", 3.0)
    mon.observe(now=1.0)
    mon.observe(now=2.0)
    assert [s for s, _ in hits] == ["warn", "alert"]
    assert hits[1][1]["value"] == 3.0
    snap = reg.snapshot()
    assert snap["counters"]['slo.breaches{rule="leak",severity="warn"}'] == 1
    assert snap["counters"]['slo.breaches{rule="leak",severity="alert"}'] == 1
    levels = [e["level"] for e in snap["events"] if e["name"] == "slo.breach"]
    assert levels == ["warning", "error"]


def test_slo_breaches_age_out_of_the_window():
    reg = MetricsRegistry(schema.DECLARED)
    sev = []
    mon = SloMonitor(registry=reg, window_s=10.0, alert_after=2,
                     on_warn=lambda b: sev.append("warn"),
                     on_alert=lambda b: sev.append("alert"))
    mon.add_rule("leak", gauge_above("serving.leaked_pages", 0.0), 0)
    reg.gauge_set("serving.leaked_pages", 1.0)
    mon.observe(now=0.0)
    mon.observe(now=20.0)  # first breach aged out: still a warn
    assert sev == ["warn", "warn"]


# -- gate + CLI tooling -------------------------------------------------------

def _load_gate():
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "gate_obs_test", os.path.join(REPO, "tools", "gate.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_gate_obs_checks(capsys):
    gate = _load_gate()
    good = {"telemetry": {
        "obs_overhead_pct": 0.4, "examples_per_sec_obs_on": 100.0,
        "examples_per_sec_obs_off": 100.4, "undeclared_metrics": [],
        "metric_names": ["serving.prefills", "train.steps",
                         "pipeline.dispatch"]}}
    assert gate._check_obs(good, "t") == 0
    # artifacts predating the layer: green unless --obs demands the block
    assert gate._check_obs({}, "t") == 0
    assert gate._check_obs({}, "t", require=True) == 1
    over = {"telemetry": dict(good["telemetry"], obs_overhead_pct=3.1)}
    assert gate._check_obs(over, "t") == 1
    rogue = {"telemetry": dict(good["telemetry"],
                               undeclared_metrics=["rogue.metric"])}
    assert gate._check_obs(rogue, "t") == 1
    drift = {"telemetry": dict(good["telemetry"],
                               metric_names=["serving.prefills",
                                             "not.in.schema"])}
    assert gate._check_obs(drift, "t") == 1
    out = capsys.readouterr().out
    assert "not.in.schema" in out and "rogue.metric" in out


def test_obs_cli_tail_summarize_diff_prom(tmp_path):
    stream = tmp_path / "obs.jsonl"
    with open(stream, "wb") as f:
        for i in range(5):
            f.write(jsonl_line({"ts": float(i), "type": "event",
                                "name": "serving.request", "level": "info",
                                "payload": {"rid": i, "phase": "queued"}}))
        for d in (0.01, 0.02, 0.03):
            f.write(jsonl_line({"ts": 9.0, "type": "span",
                                "name": "serving.decode", "dur_s": d}))

    def run(*args):
        return subprocess.run(
            [sys.executable, os.path.join(REPO, "tools", "obs.py"), *args],
            capture_output=True, text=True, timeout=60)

    r = run("tail", str(stream), "-n", "2")
    assert r.returncode == 0, r.stderr
    assert len(r.stdout.strip().splitlines()) == 2

    r = run("summarize", str(stream))
    assert r.returncode == 0, r.stderr
    assert "serving.request" in r.stdout and "serving.decode" in r.stdout
    assert "8 records" in r.stdout

    old = tmp_path / "old.json"
    new = tmp_path / "new.json"
    old.write_text(json.dumps({"counters": {"train.steps": 5},
                               "gauges": {}, "histograms": {}}))
    new.write_text(json.dumps({"counters": {"train.steps": 9},
                               "gauges": {"serving.pool_occupancy": 0.5},
                               "histograms": {}}))
    r = run("diff", str(old), str(new))
    assert r.returncode == 0, r.stderr
    assert "+4" in r.stdout and "serving.pool_occupancy" in r.stdout

    prom = tmp_path / "m.prom"
    reg = MetricsRegistry(schema.DECLARED)
    reg.counter_inc("train.steps", 2)
    write_prometheus(str(prom), reg.snapshot())
    r = run("prom", str(prom))
    assert r.returncode == 0, r.stderr
    assert json.loads(r.stdout)["train_steps"] == 2
    prom.write_text("garbage line here\n")
    assert run("prom", str(prom)).returncode == 1
    assert run("nosuchcmd").returncode == 2
