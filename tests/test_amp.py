"""AMP tests: program rewriting, bf16 training parity, dynamic loss scaling
state machine (reference unittests/test_image_classification_fp16.py idea +
update_loss_scaling op tests)."""
import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import layers as L
from paddle_tpu.contrib import mixed_precision as amp


def _build(seed=3):
    x = L.data(name="x", shape=[16], dtype="float32")
    y = L.data(name="y", shape=[1], dtype="int64")
    h = L.fc(x, size=32, act="relu")
    logits = L.fc(h, size=4)
    loss = L.mean(L.softmax_with_cross_entropy(logits, y))
    return loss


def _batch(rng, bs=64):
    x = rng.standard_normal((bs, 16)).astype(np.float32)
    w = rng.standard_normal((16, 4)).astype(np.float32)
    y = np.argmax(x @ w, 1).astype(np.int64)[:, None]
    return x, y


def test_rewrite_inserts_bf16_casts():
    loss = _build()
    main = pt.default_main_program()
    n = amp.rewrite_program(main, amp.AutoMixedPrecisionLists(), "bfloat16")
    assert n > 0
    types = [op.type for op in main.global_block.ops]
    assert "cast" in types
    # mul (fc matmul) inputs must now be the bf16 views
    mul_ops = [op for op in main.global_block.ops if op.type == "mul"]
    assert all(any(n.endswith("@BF16") for n in op.input_names)
               for op in mul_ops)


def test_bf16_training_tracks_fp32():
    rng = np.random.default_rng(0)
    x, y = _batch(rng)

    def train(use_amp):
        main, startup = pt.Program(), pt.Program()
        main.random_seed = startup.random_seed = 5
        with pt.program_guard(main, startup):
            with pt.unique_name.guard():
                loss = _build()
                opt = pt.optimizer.Momentum(0.05, 0.9)
                if use_amp:
                    opt = amp.decorate(opt)
                opt.minimize(loss)
        exe = pt.Executor()
        with pt.scope_guard(pt.Scope()):
            exe.run(startup)
            hist = []
            for _ in range(15):
                (lv,) = exe.run(main, feed={"x": x, "y": y},
                                fetch_list=[loss.name])
                hist.append(float(np.asarray(lv).reshape(-1)[0]))
        return hist

    fp32 = train(False)
    bf16 = train(True)
    assert bf16[-1] < bf16[0] * 0.7
    # bf16 should track fp32 loosely (same trajectory, lower precision)
    assert abs(bf16[-1] - fp32[-1]) < 0.35, (fp32[-1], bf16[-1])


def test_dynamic_loss_scaling_recovers_from_overflow():
    """Feed an input that overflows fp16-style scaled grads: scale must drop
    and params must survive (no nans)."""
    x = L.data(name="x", shape=[8], dtype="float32")
    y = L.data(name="y", shape=[1], dtype="float32")
    loss = L.mean(L.square_error_cost(L.fc(x, size=1), y))
    opt = amp.decorate(pt.optimizer.SGD(0.01), init_loss_scaling=2.0 ** 15,
                       use_dynamic_loss_scaling=True,
                       decr_every_n_nan_or_inf=1)
    opt.minimize(loss)
    exe = pt.Executor()
    exe.run(pt.default_startup_program())
    scope = pt.global_scope()
    rng = np.random.default_rng(1)

    # normal step
    xv = rng.standard_normal((8, 8)).astype(np.float32)
    yv = np.ones((8, 1), np.float32)
    exe.run(pt.default_main_program(), feed={"x": xv, "y": yv},
            fetch_list=[loss])
    s1 = float(np.asarray(scope.find_var("@LOSS_SCALING@")).reshape(-1)[0])

    # overflow step: gigantic input -> inf grads after scaling
    exe.run(pt.default_main_program(),
            feed={"x": np.full((8, 8), 1e30, np.float32), "y": yv},
            fetch_list=[loss])
    s2 = float(np.asarray(scope.find_var("@LOSS_SCALING@")).reshape(-1)[0])
    assert s2 < s1  # scale halved

    # params stayed finite and training continues
    (lv,) = exe.run(pt.default_main_program(), feed={"x": xv, "y": yv},
                    fetch_list=[loss])
    assert np.isfinite(float(lv))


def test_custom_lists_override():
    lists = amp.AutoMixedPrecisionLists(custom_black_list={"mul"})
    assert "mul" not in lists.white_list
    with pytest.raises(ValueError):
        amp.AutoMixedPrecisionLists(custom_white_list={"softmax"},
                                    custom_black_list={"softmax"})


def test_amp_rewrites_control_flow_sub_blocks():
    """White ops inside a StaticRNN scan body must get bf16 casts too."""
    from paddle_tpu.layers import tensor as T
    T_, B, D, H = 3, 2, 4, 5
    x = L.data(name="xs", shape=[B, D], dtype="float32")
    h0 = T.fill_constant([B, H], "float32", 0.0)
    rnn = L.StaticRNN()
    with rnn.step():
        x_t = rnn.step_input(x)
        prev = rnn.memory(init=h0)
        h = L.fc([x_t, prev], size=H, act="tanh")
        rnn.update_memory(prev, h)
        rnn.step_output(h)
    loss = L.mean(rnn())
    main = pt.default_main_program()
    amp.rewrite_program(main, amp.AutoMixedPrecisionLists(), "bfloat16")
    sub_blocks = main.blocks[1:]
    assert any(op.type == "cast" for b in sub_blocks for op in b.ops)
    # and the rewritten program still runs
    exe = pt.Executor()
    exe.run(pt.default_startup_program())
    (lv,) = exe.run(main, feed={"xs": np.ones((T_, B, D), np.float32)},
                    fetch_list=[loss])
    assert np.isfinite(float(lv))


def test_amp_cast_hoist_through_layout_ops():
    """Down-casts below layout-only ops (reshape/transpose) are hoisted above
    them so data movement happens at low precision — and the hoist must NOT
    create a second producer of an existing @BF16 var when the same fp32
    source also feeds a white op directly (r5: double-producer made
    append_backward sum both cast_grads -> 1.5x gradients)."""
    def build():
        x = L.data(name="x", shape=[4, 6], dtype="float32")
        h = L.fc(x, size=24, name="shared")
        z = L.exp(h)            # black op: z is genuinely float32
        a = L.fc(z, size=3)     # white op consumes z directly (z@BF16)
        r = L.reshape(z, [-1, 4, 6])
        r2 = L.transpose(r, [0, 2, 1])
        b = L.fc(r2, size=3)
        return z, r2, L.mean(a) + L.mean(b)

    z, r2, loss = build()
    main = pt.default_main_program()
    amp.rewrite_program(main, amp.AutoMixedPrecisionLists(), "bfloat16")
    block = main.global_block
    # every var has at most one producer
    producers = {}
    for op in block.ops:
        for n in op.output_names:
            assert n not in producers, f"two producers for {n}: " \
                f"{producers[n].type} and {op.type}"
            producers[n] = op
    # the hoist actually fired: the reshape now consumes a bf16 view of z,
    # not the fp32 z itself
    (reshape_op,) = [op for op in block.ops if op.type == "reshape2"]
    (rin,) = reshape_op.input("X")
    assert rin != z.name, "cast was not hoisted above the layout chain"
    assert "bf16" in str(block.var(rin).dtype.value).replace("loat", ""), rin
    pt.backward.append_backward(loss)
    w_shared = main.all_parameters()[0].name
    exe = pt.Executor()
    exe.run(pt.default_startup_program())
    rng = np.random.default_rng(0)
    feed = {"x": rng.standard_normal((2, 4, 6)).astype(np.float32)}
    params = [np.array(pt.global_scope().find_var(p.name))
              for p in main.all_parameters()]
    # the layout op's ORIGINAL fp32 output must stay fetchable post-hoist
    # (a repair upcast keeps it producible; DCE'd when unfetched)
    gw, r_val = exe.run(main, feed=feed,
                        fetch_list=[w_shared + "@GRAD", r2.name])
    assert np.asarray(r_val).dtype == np.float32
    # gradient oracle: same graph, no AMP rewrite, same params
    with pt.program_guard(pt.Program(), pt.Program()):
        _, _, loss2 = build()
        main2 = pt.default_main_program()
        pt.backward.append_backward(loss2)
        w2 = main2.all_parameters()[0].name
        exe.run(pt.default_startup_program())
        for p2, val in zip(main2.all_parameters(), params):
            pt.global_scope().set_var(p2.name, val)
        (gw_ref,) = exe.run(main2, feed=feed, fetch_list=[w2 + "@GRAD"])
    np.testing.assert_allclose(np.asarray(gw), np.asarray(gw_ref),
                               rtol=3e-2, atol=3e-2)
