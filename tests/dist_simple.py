"""Distributed-test model runner (reference unittests/dist_mnist.py pattern):
one script, three roles — `local`, `pserver`, `trainer` — so the pserver path
can be exercised with real subprocesses on localhost (TestDistBase :442).

usage: dist_simple.py ROLE EPS TRAINER_ID N_TRAINERS OUT_NPZ [CURRENT_EP]
"""
import sys

import jax

jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402

import paddle_tpu as pt  # noqa: E402
from paddle_tpu import layers as L  # noqa: E402

STEPS = 5
FULL_BATCH = 32


def build():
    x = L.data(name="x", shape=[16], dtype="float32")
    y = L.data(name="y", shape=[1], dtype="float32")
    h = L.fc(x, size=512, act="relu")  # big enough to row-slice over pservers
    pred = L.fc(h, size=1)
    loss = L.mean(L.square_error_cost(pred, y))
    return loss


def full_data():
    rng = np.random.default_rng(0)
    x = rng.standard_normal((FULL_BATCH, 16)).astype(np.float32)
    w = rng.standard_normal((16, 1)).astype(np.float32)
    return x, (x @ w).astype(np.float32)


def main():
    role, eps, trainer_id, n_trainers, out = (
        sys.argv[1], sys.argv[2], int(sys.argv[3]), int(sys.argv[4]),
        sys.argv[5])
    current_ep = sys.argv[6] if len(sys.argv) > 6 else None

    main_p, startup = pt.Program(), pt.Program()
    main_p.random_seed = 7
    startup.random_seed = 7
    with pt.program_guard(main_p, startup):
        with pt.unique_name.guard():
            loss = build()
            pt.optimizer.SGD(0.1).minimize(loss)

    exe = pt.Executor()
    x, y = full_data()

    if role == "local":
        exe.run(startup)
        for _ in range(STEPS):
            (lv,) = exe.run(main_p, feed={"x": x, "y": y},
                            fetch_list=[loss.name])
        _dump(out, main_p, float(np.asarray(lv).reshape(-1)[0]))
        return

    t = pt.DistributeTranspiler()
    t.transpile(trainer_id, program=main_p, pservers=eps,
                trainers=n_trainers, sync_mode=True, startup_program=startup)

    if role == "pserver":
        exe.run(t.get_startup_program())
        exe.run(t.get_pserver_program(current_ep))  # blocks until complete
        return

    # trainer
    exe.run(startup)
    prog = t.get_trainer_program()
    shard = FULL_BATCH // n_trainers
    lo = trainer_id * shard
    xs, ys = x[lo:lo + shard], y[lo:lo + shard]
    for _ in range(STEPS):
        (lv,) = exe.run(prog, feed={"x": xs, "y": ys}, fetch_list=[loss.name])
    exe.close()
    _dump(out, main_p, float(np.asarray(lv).reshape(-1)[0]))


def _dump(out, program, last_loss):
    vals = {
        p.name: np.asarray(pt.global_scope().find_var(p.name))
        for p in program.all_parameters()
    }
    vals["__last_loss__"] = np.asarray(last_loss)
    np.savez(out, **vals)


if __name__ == "__main__":
    main()
