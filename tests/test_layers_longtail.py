"""Layer-DSL long tail (reference nn.py parity batch): every new wrapper
builds + executes; differentiable ones train through append_backward."""
import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import layers as L


def _run(build, feeds, n_fetch=1):
    main, startup = pt.Program(), pt.Program()
    with pt.program_guard(main, startup):
        with pt.unique_name.guard():
            outs = build()
    outs = outs if isinstance(outs, (list, tuple)) else [outs]
    exe = pt.Executor()
    with pt.scope_guard(pt.Scope()):
        exe.run(startup)
        vals = exe.run(main, feed=feeds, fetch_list=list(outs)[:n_fetch])
    return [np.asarray(v) for v in vals]


def test_activation_wrappers():
    x = np.linspace(-3, 3, 12).astype(np.float32).reshape(2, 6)
    def build():
        v = L.data(name="x", shape=[6], dtype="float32")
        return [L.elu(v), L.relu6(v), L.hard_sigmoid(v), L.swish(v),
                L.selu(v), L.sign(v), L.brelu(v), L.soft_relu(v),
                L.stanh(v), L.hard_swish(v)]
    outs = _run(build, {"x": x}, n_fetch=10)
    np.testing.assert_allclose(
        outs[0], np.where(x >= 0, x, np.exp(x) - 1), rtol=1e-5)  # elu
    np.testing.assert_allclose(outs[1], np.clip(x, 0, 6), rtol=1e-6)
    np.testing.assert_allclose(outs[5], np.sign(x))
    s = 1.0507009873554805; a = 1.6732632423543772
    np.testing.assert_allclose(
        outs[4], s * np.where(x >= 0, x, a * (np.exp(x) - 1)), rtol=1e-5)


def test_elementwise_and_reduce_wrappers():
    x = np.array([[7.0, -3.0], [5.0, 2.0]], np.float32)
    y = np.array([[2.0, 2.0], [3.0, 2.0]], np.float32)
    def build():
        a = L.data(name="x", shape=[2], dtype="float32")
        b = L.data(name="y", shape=[2], dtype="float32")
        m = L.elementwise_mod(a, b)
        f = L.elementwise_floordiv(a, b)
        anyv = L.reduce_any(L.greater_than(a, b))
        allv = L.reduce_all(L.greater_than(a, b))
        return [m, f, anyv, allv]
    m, f, anyv, allv = _run(build, {"x": x, "y": y}, n_fetch=4)
    np.testing.assert_allclose(m, np.mod(x, y))
    np.testing.assert_allclose(f, np.floor_divide(x, y))
    assert bool(anyv) is True and bool(allv) is False


def test_loss_wrappers():
    rng = np.random.default_rng(0)
    logp = np.log(rng.dirichlet(np.ones(4), 6)).astype(np.float32)
    tgt = rng.dirichlet(np.ones(4), 6).astype(np.float32)
    pred = rng.random((6, 1)).astype(np.float32) * 0.8 + 0.1
    lbl = rng.integers(0, 2, (6, 1)).astype(np.float32)
    def build():
        lp = L.data(name="lp", shape=[4], dtype="float32")
        t = L.data(name="t", shape=[4], dtype="float32")
        p = L.data(name="p", shape=[1], dtype="float32")
        y = L.data(name="y", shape=[1], dtype="float32")
        return [L.kldiv_loss(lp, t, reduction="mean"),
                L.log_loss(p, y),
                L.huber_loss(p, y, delta=0.5),
                L.rank_loss(y, p, p)]
    kld, ll, hub, rl = _run(build, {"lp": logp, "t": tgt, "p": pred,
                                    "y": lbl}, n_fetch=4)
    ref_kld = (tgt * (np.log(tgt) - logp)).mean()
    np.testing.assert_allclose(kld, ref_kld, rtol=1e-4)
    ref_ll = -lbl * np.log(pred + 1e-4) - (1 - lbl) * np.log(1 - pred + 1e-4)
    np.testing.assert_allclose(ll, ref_ll, rtol=1e-3)
    np.testing.assert_allclose(rl, np.log1p(np.exp(0.0)) - lbl * 0.0,
                               rtol=1e-5)
    assert np.isfinite(hub).all()


def test_vision_layout_wrappers():
    rng = np.random.default_rng(1)
    x = rng.standard_normal((2, 8, 4, 4)).astype(np.float32)
    def build():
        v = L.data(name="x", shape=[8, 4, 4], dtype="float32")
        return [L.pixel_shuffle(v, 2), L.shuffle_channel(v, 4),
                L.space_to_depth(v, 2), L.maxout(v, 2),
                L.adaptive_pool2d(v, [2, 2], "avg"),
                L.resize_bilinear(v, out_shape=(8, 8)),
                L.resize_nearest(v, out_shape=(2, 2)),
                L.lrn(v), L.temporal_shift(v, seg_num=2)]
    outs = _run(build, {"x": x}, n_fetch=9)
    assert outs[0].shape == (2, 2, 8, 8)    # pixel_shuffle
    assert outs[1].shape == x.shape         # shuffle_channel
    assert outs[2].shape == (2, 32, 2, 2)   # space_to_depth
    assert outs[3].shape == (2, 4, 4, 4)    # maxout
    np.testing.assert_allclose(
        outs[4], x.reshape(2, 8, 2, 2, 2, 2).mean(axis=(3, 5)), rtol=1e-5)
    assert outs[5].shape == (2, 8, 8, 8)
    np.testing.assert_allclose(outs[6], x[:, :, ::3, ::3])  # nearest align_corners (reference default)
    assert outs[7].shape == x.shape
    assert outs[8].shape == x.shape


def test_tensor_wrappers():
    rng = np.random.default_rng(2)
    x = rng.standard_normal((3, 4)).astype(np.float32)
    idx = np.array([[0, 1], [2, 3]], np.int64)
    upd = np.array([10.0, 20.0], np.float32)
    def build():
        v = L.data(name="x", shape=[4], dtype="float32")
        i = L.data(name="i", shape=[2], dtype="int64")
        u = L.data(name="u", shape=[], dtype="float32")
        return [L.gather_nd(v, i), L.scatter_nd_add(v, i, u),
                L.rank(v), L.size(v), L.sum([v, v]),
                L.crop(v, shape=[2, 2], offsets=[1, 1]),
                L.shard_index(i, index_num=8, nshards=2, shard_id=0)]
    g, sc, rk, sz, sm, cr, sh = _run(
        build, {"x": x, "i": idx, "u": upd}, n_fetch=7)
    np.testing.assert_allclose(g, x[idx[:, 0], idx[:, 1]])
    ref = x.copy(); ref[0, 1] += 10; ref[2, 3] += 20
    np.testing.assert_allclose(sc, ref)
    assert int(rk) == 2 and int(sz) == 12
    np.testing.assert_allclose(sm, 2 * x)
    np.testing.assert_allclose(cr, x[1:3, 1:3])
    np.testing.assert_array_equal(sh, np.where(idx < 4, idx, -1))


def test_conv3d_and_pool3d_train():
    rng = np.random.default_rng(3)
    x = rng.standard_normal((2, 3, 4, 6, 6)).astype(np.float32)
    main, startup = pt.Program(), pt.Program()
    with pt.program_guard(main, startup):
        with pt.unique_name.guard():
            v = L.data(name="x", shape=[3, 4, 6, 6], dtype="float32")
            c = L.conv3d(v, num_filters=4, filter_size=3, padding=1,
                         act="relu")
            p = L.pool3d(c, pool_size=2, pool_type="avg", pool_stride=2)
            loss = L.mean(p)
            pt.optimizer.SGD(0.1).minimize(loss)
    exe = pt.Executor()
    with pt.scope_guard(pt.Scope()):
        exe.run(startup)
        w0 = np.asarray(pt.global_scope().find_var(
            main.all_parameters()[0].name)).copy()
        (lv,) = exe.run(main, feed={"x": x}, fetch_list=[loss])
        w1 = np.asarray(pt.global_scope().find_var(
            main.all_parameters()[0].name))
    assert np.isfinite(float(np.asarray(lv)))
    assert not np.allclose(w0, w1)


def test_grid_sampler_identity():
    rng = np.random.default_rng(4)
    x = rng.standard_normal((1, 2, 4, 4)).astype(np.float32)
    ys, xs = np.meshgrid(np.linspace(-1, 1, 4), np.linspace(-1, 1, 4),
                         indexing="ij")
    grid = np.stack([xs, ys], axis=-1)[None].astype(np.float32)
    def build():
        v = L.data(name="x", shape=[2, 4, 4], dtype="float32")
        g = L.data(name="g", shape=[4, 4, 2], dtype="float32")
        return L.grid_sampler(v, g)
    (out,) = _run(build, {"x": x, "g": grid})
    np.testing.assert_allclose(out, x, rtol=1e-4, atol=1e-5)  # identity grid


def test_misc_wrappers():
    rng = np.random.default_rng(5)
    x = rng.standard_normal((2, 3)).astype(np.float32)
    y = rng.standard_normal((2, 4)).astype(np.float32)
    sel = np.array([[1], [0]], np.int64)
    def build():
        a = L.data(name="x", shape=[3], dtype="float32")
        b = L.data(name="y", shape=[4], dtype="float32")
        i = L.data(name="i", shape=[1], dtype="int64")
        btp = L.bilinear_tensor_product(a, b, size=5)
        mux = L.multiplex([a, L.scale(a, scale=2.0)], i)
        seq = L.data(name="s", shape=[4, 8], dtype="float32")
        pe = L.add_position_encoding(seq)
        return [btp, mux, pe]
    s = rng.standard_normal((2, 4, 8)).astype(np.float32)
    btp, mux, pe = _run(build, {"x": x, "y": y, "i": sel, "s": s}, n_fetch=3)
    assert btp.shape == (2, 5)
    np.testing.assert_allclose(mux, np.stack([x[0] * 2, x[1]]), rtol=1e-6)
    assert pe.shape == s.shape and not np.allclose(pe, s)


def test_unfold_matches_manual_im2col():
    rng = np.random.default_rng(6)
    x = rng.standard_normal((1, 2, 4, 4)).astype(np.float32)
    def build():
        v = L.data(name="x", shape=[2, 4, 4], dtype="float32")
        return L.unfold(v, kernel_sizes=[2, 2], strides=2)
    (out,) = _run(build, {"x": x})
    assert out.shape == (1, 8, 4)
    # first output column = top-left 2x2 patch, channel-major kh-kw order
    ref0 = np.stack([x[0, :, 0, 0], x[0, :, 0, 1],
                     x[0, :, 1, 0], x[0, :, 1, 1]], axis=1).reshape(-1)
    np.testing.assert_allclose(out[0, :, 0], ref0)


def test_resize_align_corners_conventions():
    """interpolate_op.h coordinate conventions: align_corners=True maps
    d*(in-1)/(out-1); False+mode0 is half-pixel; False+mode1 is d*in/out."""
    x = np.arange(16, dtype=np.float32).reshape(1, 1, 4, 4)

    def build():
        v = L.data(name="x", shape=[1, 4, 4], dtype="float32")
        return [L.resize_bilinear(v, out_shape=(7, 7), align_corners=True),
                L.resize_bilinear(v, out_shape=(7, 7), align_corners=False,
                                  align_mode=0),
                L.resize_bilinear(v, out_shape=(7, 7), align_corners=False,
                                  align_mode=1),
                L.resize_nearest(v, out_shape=(2, 2), align_corners=True),
                L.resize_nearest(v, out_shape=(2, 2), align_corners=False),
                L.resize_nearest(v, out_shape=(3, 3), align_corners=True)]

    a_true, a_m0, a_m1, near, near_f, near_half = _run(
        build, {"x": x}, n_fetch=6)

    def bilinear(coords):
        out = np.zeros((7, 7), np.float32)
        img = x[0, 0]
        for i, sy in enumerate(coords):
            for j, sx in enumerate(coords):
                y0, x0 = min(int(sy), 3), min(int(sx), 3)
                y1, x1 = min(y0 + 1, 3), min(x0 + 1, 3)
                wy, wx = sy - y0, sx - x0
                out[i, j] = (img[y0, x0] * (1 - wy) * (1 - wx)
                             + img[y0, x1] * (1 - wy) * wx
                             + img[y1, x0] * wy * (1 - wx)
                             + img[y1, x1] * wy * wx)
        return out

    d = np.arange(7, dtype=np.float64)
    np.testing.assert_allclose(a_true[0, 0], bilinear(d * 3 / 6), rtol=1e-5)
    np.testing.assert_allclose(
        a_m0[0, 0], bilinear(np.maximum((d + 0.5) * 4 / 7 - 0.5, 0)),
        rtol=1e-5)
    np.testing.assert_allclose(a_m1[0, 0], bilinear(d * 4 / 7), rtol=1e-5)
    # nearest align_corners: round(d * 3 / 1) -> rows/cols {0, 3}
    np.testing.assert_allclose(near[0, 0], x[0, 0][::3, ::3])
    # nearest NOT aligned: floor(d * in/out) -> {0, 2}, never half-pixel
    np.testing.assert_allclose(near_f[0, 0], x[0, 0][::2, ::2])
    # aligned 4->3: coords d*3/2 = [0, 1.5, 3]; half-up rounds 1.5 -> 2
    np.testing.assert_allclose(
        near_half[0, 0], x[0, 0][[0, 2, 3]][:, [0, 2, 3]])


def test_rank_loss_stable_at_large_margin():
    """logaddexp form must not overflow where log1p(exp(d)) would (d>88)."""
    left = np.array([[200.0]], np.float32)
    right = np.array([[0.0]], np.float32)
    lab = np.array([[1.0]], np.float32)

    def build():
        l = L.data(name="l", shape=[1], dtype="float32")
        r = L.data(name="r", shape=[1], dtype="float32")
        y = L.data(name="y", shape=[1], dtype="float32")
        return L.rank_loss(y, l, r)

    out, = _run(build, {"l": left, "r": right, "y": lab})
    assert np.isfinite(out).all()
    np.testing.assert_allclose(out, 0.0, atol=1e-4)  # log(1+e^200)-200 ~ 0
