"""Dygraph layer-zoo tail (VERDICT r4 #6): GRUUnit, NCE, PRelu,
BilinearTensorProduct, GroupNorm, SpectralNorm, Conv3D, Conv3DTranspose as
tape Layers over the registry ops — each checked against the repo's
established oracle (static-graph layer with the same parameters), except
the stochastic NCE (finite loss + gradient flow) and SpectralNorm
(spectral property: top singular value of the output is ~1)."""
import numpy as np

import paddle_tpu as pt
from paddle_tpu import dygraph as dg
from paddle_tpu import layers as L


def _static_eval(build_fn, feeds, params_by_shape):
    """Run a static program, injecting params positionally by shape."""
    main, startup = pt.Program(), pt.Program()
    with pt.program_guard(main, startup):
        with pt.unique_name.guard():
            out = build_fn()
    exe = pt.Executor()
    with pt.scope_guard(pt.Scope()):
        exe.run(startup)
        remaining = list(params_by_shape)
        for p in main.all_parameters():
            for i, v in enumerate(remaining):
                if tuple(v.shape) == tuple(p.shape):
                    pt.global_scope().set_var(p.name, v)
                    remaining.pop(i)
                    break
            else:
                raise AssertionError(
                    f"no injected value of shape {p.shape} for {p.name}")
        assert not remaining, [v.shape for v in remaining]
        return np.asarray(exe.run(main, feed=feeds, fetch_list=[out])[0])


def test_dygraph_prelu_matches_static():
    rng = np.random.default_rng(0)
    x = rng.standard_normal((4, 6, 5, 5)).astype(np.float32)
    with dg.guard():
        layer = dg.PRelu(mode="channel", channel_or_shape=6)
        got = layer(dg.to_variable(x)).numpy()
        alpha = layer.weight.numpy()

    def build():
        xv = L.data(name="x", shape=[6, 5, 5], dtype="float32")
        return L.prelu(xv, mode="channel")

    ref = _static_eval(build, {"x": x}, [alpha])
    np.testing.assert_allclose(got, ref, rtol=1e-5, atol=1e-6)


def test_dygraph_group_norm_matches_static():
    rng = np.random.default_rng(1)
    x = rng.standard_normal((3, 8, 4, 4)).astype(np.float32)
    with dg.guard():
        layer = dg.GroupNorm(channels=8, groups=4)
        got = layer(dg.to_variable(x)).numpy()
        w, b = layer.weight.numpy(), layer.bias.numpy()

    def build():
        xv = L.data(name="x", shape=[8, 4, 4], dtype="float32")
        return L.group_norm(xv, groups=4)

    ref = _static_eval(build, {"x": x}, [w, b])
    np.testing.assert_allclose(got, ref, rtol=1e-4, atol=1e-5)


def test_dygraph_bilinear_tensor_product_matches_static():
    rng = np.random.default_rng(2)
    x = rng.standard_normal((5, 3)).astype(np.float32)
    y = rng.standard_normal((5, 4)).astype(np.float32)
    with dg.guard():
        layer = dg.BilinearTensorProduct(3, 4, 6)
        got = layer(dg.to_variable(x), dg.to_variable(y)).numpy()
        w, b = layer.weight.numpy(), layer.bias.numpy()

    def build():
        xv = L.data(name="x", shape=[3], dtype="float32")
        yv = L.data(name="y", shape=[4], dtype="float32")
        return L.bilinear_tensor_product(xv, yv, size=6)

    ref = _static_eval(build, {"x": x, "y": y}, [w, b])
    np.testing.assert_allclose(got, ref, rtol=1e-4, atol=1e-5)


def test_dygraph_gru_unit_matches_static():
    rng = np.random.default_rng(3)
    B, H = 4, 5
    xin = rng.standard_normal((B, 3 * H)).astype(np.float32)
    h0 = rng.standard_normal((B, H)).astype(np.float32)
    with dg.guard():
        layer = dg.GRUUnit(size=3 * H)
        h, r, g = layer(dg.to_variable(xin), dg.to_variable(h0))
        got = h.numpy()
        w, b = layer.weight.numpy(), layer.bias.numpy()

    def build():
        xv = L.data(name="x", shape=[3 * H], dtype="float32")
        hv = L.data(name="h", shape=[H], dtype="float32")
        hid, _, _ = L.gru_unit(xv, hv, size=3 * H)
        return hid

    ref = _static_eval(build, {"x": xin, "h": h0}, [w, b])
    np.testing.assert_allclose(got, ref, rtol=1e-4, atol=1e-5)


def test_dygraph_conv3d_matches_static():
    rng = np.random.default_rng(4)
    x = rng.standard_normal((2, 3, 6, 6, 6)).astype(np.float32)
    with dg.guard():
        layer = dg.Conv3D(num_channels=3, num_filters=4, filter_size=3,
                          padding=1)
        got = layer(dg.to_variable(x)).numpy()
        w, b = layer.weight.numpy(), layer.bias.numpy()

    def build():
        xv = L.data(name="x", shape=[3, 6, 6, 6], dtype="float32")
        return L.conv3d(xv, num_filters=4, filter_size=3, padding=1)

    ref = _static_eval(build, {"x": x}, [w, b])
    np.testing.assert_allclose(got, ref, rtol=1e-4, atol=1e-5)


def test_dygraph_conv3d_transpose_matches_static():
    rng = np.random.default_rng(5)
    x = rng.standard_normal((2, 4, 5, 5, 5)).astype(np.float32)
    with dg.guard():
        layer = dg.Conv3DTranspose(num_channels=4, num_filters=3,
                                   filter_size=3, stride=2, padding=1)
        got = layer(dg.to_variable(x)).numpy()
        w, b = layer.weight.numpy(), layer.bias.numpy()

    def build():
        xv = L.data(name="x", shape=[4, 5, 5, 5], dtype="float32")
        return L.conv3d_transpose(xv, num_filters=3, filter_size=3,
                                  stride=2, padding=1)

    ref = _static_eval(build, {"x": x}, [w, b])
    np.testing.assert_allclose(got, ref, rtol=1e-4, atol=1e-5)


def test_dygraph_spectral_norm_property():
    """W/sigma_max has top singular value ~1 after enough power iters, and
    the layer's U/V state persists across calls (reference SpectralNorm)."""
    rng = np.random.default_rng(6)
    w = rng.standard_normal((6, 8)).astype(np.float32)
    with dg.guard():
        layer = dg.SpectralNorm(weight_shape=[6, 8], power_iters=30)
        out = layer(dg.to_variable(w)).numpy()
        u_after_1 = layer._u.numpy().copy()
        out2 = layer(dg.to_variable(w)).numpy()
        u_after_2 = layer._u.numpy()
    s = np.linalg.svd(out, compute_uv=False)
    np.testing.assert_allclose(s[0], 1.0, rtol=1e-3)
    np.testing.assert_allclose(out2, out, rtol=1e-4, atol=1e-5)
    assert u_after_1.shape == u_after_2.shape == (6,)


def test_dygraph_nce_trains():
    """NCE is sampled (fresh negatives per step): assert finite cost and
    that gradients flow into the class embedding via the tape."""
    rng = np.random.default_rng(7)
    B, D, C = 16, 8, 50
    x = rng.standard_normal((B, D)).astype(np.float32)
    label = rng.integers(0, C, (B, 1)).astype(np.int64)
    with dg.guard():
        layer = dg.NCE(num_total_classes=C, dim=D, num_neg_samples=5)
        cost = layer(dg.to_variable(x), dg.to_variable(label))
        assert cost.shape == (B, 1)
        loss = dg._dy_op("reduce_mean", {"X": [cost]},
                         attrs={"dim": [0, 1], "keep_dim": False,
                                "reduce_all": True})["Out"]
        assert np.isfinite(float(loss.numpy()))
        dg.backward(loss)
        gw = layer.weight.gradient()
        assert gw is not None and np.isfinite(np.asarray(gw)).all()
        assert np.abs(np.asarray(gw)).sum() > 0


def test_dygraph_tree_conv_matches_static():
    rng = np.random.default_rng(8)
    B, N, F, O, M = 2, 6, 5, 4, 3
    nodes = rng.standard_normal((B, N, F)).astype(np.float32)
    # simple chains: 1-indexed (parent, child); 0 pads
    edges = np.zeros((B, 5, 2), np.int64)
    edges[:, 0] = [1, 2]
    edges[:, 1] = [2, 3]
    edges[:, 2] = [1, 4]
    with dg.guard():
        # act=None isolates the linear part; default act is tanh like the
        # reference. Set a NONZERO bias so the bias-add path is exercised.
        layer = dg.TreeConv(feature_size=F, output_size=O, num_filters=M,
                            act=None)
        layer.bias._value = layer.bias._value + np.arange(
            M, dtype=np.float32)
        got = layer(dg.to_variable(nodes), dg.to_variable(edges)).numpy()
        w, b = layer.weight.numpy(), layer.bias.numpy()
        assert np.abs(b).sum() > 0

    def build():
        nv = L.data(name="nodes", shape=[N, F], dtype="float32")
        ev = L.data(name="edges", shape=[5, 2], dtype="int64")
        return L.tree_conv(nv, ev, output_size=O, num_filters=M, act=None,
                           bias_attr=False)

    ref = _static_eval(build, {"nodes": nodes, "edges": edges}, [w])
    np.testing.assert_allclose(got - b.reshape(1, 1, 1, -1), ref,
                               rtol=1e-4, atol=1e-5)
