"""Slim pruning + distillation (reference contrib/slim prune/ and
distillation/): mask sparsity, mask persistence through training,
sensitivity probe, and teacher->student distillation convergence."""
import numpy as np

import paddle_tpu as pt
from paddle_tpu import layers as L
from paddle_tpu.contrib.slim.distillation import (
    fsp_matrix,
    l2_distill_loss,
    soft_label_loss,
)
from paddle_tpu.contrib.slim.prune import MagnitudePruner, sensitivity


def test_magnitude_prune_sparsity_and_training_persistence():
    main, startup = pt.Program(), pt.Program()
    main.random_seed = startup.random_seed = 5
    with pt.program_guard(main, startup):
        with pt.unique_name.guard():
            x = L.data(name="x", shape=[16], dtype="float32")
            y = L.data(name="y", shape=[1], dtype="float32")
            h = L.fc(x, size=32, act="relu", name="h")
            pred = L.fc(h, size=1, name="p")
            loss = L.mean(L.square_error_cost(pred, y))
            pt.optimizer.SGD(0.05).minimize(loss)
    scope = pt.Scope()
    exe = pt.Executor()
    rng = np.random.default_rng(0)
    w_true = rng.standard_normal((16, 1)).astype(np.float32)
    with pt.scope_guard(scope):
        exe.run(startup)
        MagnitudePruner().apply(["h.w_0"], 0.5, scope=scope, program=main)
        w = np.asarray(scope.find_var("h.w_0"))
        sparsity = float((w == 0).mean())
        assert 0.45 <= sparsity <= 0.55, sparsity
        mask = np.asarray(scope.find_var("h.w_0@prune_mask"))
        for _ in range(20):
            xb = rng.standard_normal((32, 16)).astype(np.float32)
            (lv,) = exe.run(main, feed={"x": xb, "y": xb @ w_true},
                            fetch_list=[loss])
        w_after = np.asarray(scope.find_var("h.w_0"))
        # pruned entries stay EXACTLY zero through 20 SGD steps
        assert np.all(w_after[mask == 0] == 0.0)
        # surviving entries trained
        assert not np.allclose(w_after[mask == 1], w[mask == 1])
        assert np.isfinite(float(np.asarray(lv)))


def test_structured_prune_removes_whole_columns():
    rng = np.random.default_rng(1)
    w = rng.standard_normal((8, 10)).astype(np.float32)
    scope = pt.Scope()
    scope.set_var("w", w)
    MagnitudePruner(structured=True).prune_weights(scope, ["w"], 0.3)
    out = np.asarray(scope.find_var("w"))
    col_zero = (out == 0).all(axis=0)
    assert col_zero.sum() == 3  # floor(0.3 * 10) whole columns
    # the removed columns are the smallest-norm ones
    norms = np.sqrt((w ** 2).sum(axis=0))
    assert set(np.nonzero(col_zero)[0]) == set(np.argsort(norms)[:3])


def test_sensitivity_probe_restores_and_ranks():
    scope = pt.Scope()
    rng = np.random.default_rng(2)
    w = rng.standard_normal((6, 6)).astype(np.float32)
    scope.set_var("w", w.copy())

    def eval_fn():
        # toy metric = remaining weight magnitude: pruning strictly lowers it
        return float(np.abs(np.asarray(scope.find_var("w"))).sum())

    out = sensitivity(None, scope, None, ["w"], eval_fn, ratios=(0.2, 0.6))
    # restored after probing
    np.testing.assert_array_equal(np.asarray(scope.find_var("w")), w)
    # heavier pruning loses more metric
    assert out["w"][0.6] < out["w"][0.2]


def test_distillation_soft_label_student_learns_teacher():
    main, startup = pt.Program(), pt.Program()
    main.random_seed = startup.random_seed = 9
    with pt.program_guard(main, startup):
        with pt.unique_name.guard():
            x = L.data(name="x", shape=[8], dtype="float32")
            # frozen teacher tower
            t_logits = L.fc(x, size=4, name="teacher")
            t_logits.stop_gradient = True
            # student tower
            s_logits = L.fc(x, size=4, name="student")
            loss = soft_label_loss(t_logits, s_logits,
                                   teacher_temperature=2.0,
                                   student_temperature=2.0)
            opt = pt.optimizer.Adam(5e-2)
            params = [p for p in main.all_parameters()
                      if p.name.startswith("student")]
            opt.minimize(loss, parameter_list=params)
    scope = pt.Scope()
    exe = pt.Executor()
    rng = np.random.default_rng(3)
    xb = rng.standard_normal((64, 8)).astype(np.float32)  # fixed batch
    with pt.scope_guard(scope):
        exe.run(startup)
        t0 = np.asarray(scope.find_var("teacher.w_0")).copy()
        tb = np.asarray(scope.find_var("teacher.b_0"))
        losses = []
        for _ in range(80):
            (lv,) = exe.run(main, feed={"x": xb}, fetch_list=[loss])
            losses.append(float(np.asarray(lv)))
        # teacher untouched
        np.testing.assert_array_equal(
            np.asarray(scope.find_var("teacher.w_0")), t0)
        # cross-entropy against soft targets bottoms out at the TEACHER's
        # entropy, not 0 — assert the KL component (loss - H) collapsed
        z = (xb @ t0 + tb) / 2.0
        p_t = np.exp(z - z.max(1, keepdims=True))
        p_t /= p_t.sum(1, keepdims=True)
        floor = float(-(p_t * np.log(p_t)).sum(1).mean())
        kl0, kl1 = losses[0] - floor, losses[-1] - floor
        assert kl1 < 0.1 * kl0, (losses[0], losses[-1], floor)


def test_fsp_matrix_matches_numpy():
    main, startup = pt.Program(), pt.Program()
    with pt.program_guard(main, startup):
        a = L.data(name="a", shape=[3, 4, 5], dtype="float32")
        b = L.data(name="b", shape=[2, 4, 5], dtype="float32")
        m = fsp_matrix(a, b)
        l2 = l2_distill_loss(m, m)
    exe = pt.Executor()
    rng = np.random.default_rng(4)
    av = rng.standard_normal((2, 3, 4, 5)).astype(np.float32)
    bv = rng.standard_normal((2, 2, 4, 5)).astype(np.float32)
    with pt.scope_guard(pt.Scope()):
        exe.run(startup)
        mv, lv = exe.run(main, feed={"a": av, "b": bv}, fetch_list=[m, l2])
    ref = np.einsum("bchw,bdhw->bcd", av, bv) / 20.0
    np.testing.assert_allclose(np.asarray(mv), ref, rtol=1e-5, atol=1e-6)
    assert float(np.asarray(lv)) == 0.0
