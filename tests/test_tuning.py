"""Autotuner tests (ISSUE 6): the persistent decision DB, the three-tier
resolution (exact hit -> analytic prior -> conservative default), the lever
wirings (conv lowering, attention backend, conv+BN fusion, AMP lists,
bucket boundaries), corrupt/missing-DB fallback, sweep-mode candidate
recording, and the acceptance equivalences:

  * FLAGS_tuning_mode=consult with a swept DB reproduces the PR 5 per-shape
    igemm decisions on the PERF.md r6 cost-table shapes (and can beat them
    with a measured override);
  * the swept BENCH_r05 attention split — XLA at seq<=128, the Pallas
    kernel at s512 — resolves from the DB, and an un-runnable backend
    degrades at dispatch instead of breaking numerics.
"""
import json
import os
import warnings

import jax
import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import layers as L
from paddle_tpu import tuning
from paddle_tpu.ops.nn_ops import _igemm_take

def _sds(shape, dtype="float32"):
    import jax.numpy as jnp

    return jax.ShapeDtypeStruct(tuple(shape), jnp.dtype(dtype))


@pytest.fixture
def tuned(tmp_path):
    """Point the tuner at a scratch DB path (not yet written), yield it,
    and restore flags + caches afterwards."""
    snap = pt.flags.all_flags()
    db_path = str(tmp_path / "tuning_db.json")
    pt.flags.set_flags({"tuning_mode": "consult", "tuning_db": db_path})
    tuning.invalidate_db_cache()
    tuning.reset_provenance()
    yield db_path
    pt.flags.set_flags(snap)
    tuning.invalidate_db_cache()
    tuning.reset_provenance()


def _write_db(path, entries):
    db = tuning.TuningDB(path)
    for key, decision, src in entries:
        db.put(key, decision, source=src)
    db.save(path)
    tuning.invalidate_db_cache()
    return db


# -- DB mechanics ------------------------------------------------------------

def test_db_roundtrip_and_atomic_write(tmp_path):
    p = str(tmp_path / "sub" / "db.json")  # directory is created
    db = tuning.TuningDB(p)
    db.put("conv2d|k|float32|cpu", {"lowering": "igemm"},
           measured={"direct": 1.0}, note="n")
    db.save()
    raw = json.load(open(p))
    assert raw["schema"] == tuning.DB_SCHEMA
    re = tuning.TuningDB(p)
    assert re.lookup("conv2d|k|float32|cpu")["decision"] == \
        {"lowering": "igemm"}
    assert re.lookup("conv2d|k|float32|cpu")["measured"] == {"direct": 1.0}
    # no stray temp files after the atomic replace
    assert os.listdir(os.path.dirname(p)) == ["db.json"]


def test_candidate_put_never_clobbers_swept(tmp_path):
    p = str(tmp_path / "db.json")
    db = tuning.TuningDB(p)
    db.put("k", {"lowering": "igemm"}, source="swept")
    assert not db.put("k", {"lowering": "direct"}, source="candidate",
                      overwrite=False)
    assert db.lookup("k")["decision"] == {"lowering": "igemm"}


@pytest.mark.parametrize("payload", [
    "{corrupt json",                       # unparseable
    json.dumps({"schema": 999, "entries": {}}),   # wrong schema
    json.dumps(["not", "an", "object"]),   # wrong top-level type
])
def test_bad_db_warns_and_degrades_to_empty(tmp_path, payload):
    p = str(tmp_path / "bad.json")
    open(p, "w").write(payload)
    with warnings.catch_warnings(record=True) as w:
        warnings.simplefilter("always")
        db = tuning.TuningDB(p)
    assert len(db) == 0
    assert any("falling back to the analytic" in str(x.message) for x in w)


def test_missing_db_is_silently_empty(tmp_path):
    with warnings.catch_warnings(record=True) as w:
        warnings.simplefilter("always")
        db = tuning.TuningDB(str(tmp_path / "nope.json"))
    assert len(db) == 0 and not w


# -- three-tier resolution ---------------------------------------------------

def test_decide_tiers_and_provenance(tuned):
    key = tuning.canonical_key("demo", "shape", "float32", "cpu")
    # tier 3: no DB entry, no prior
    d, tier = tuning.decide("demo", key, default={"x": 1})
    assert (d, tier) == ({"x": 1}, "default")
    # tier 2: analytic prior
    d, tier = tuning.decide("demo", key, prior=lambda: {"x": 2})
    assert (d, tier) == ({"x": 2}, "analytic")
    # tier 1: exact hit
    _write_db(tuned, [(key, {"x": 3}, "swept")])
    d, tier = tuning.decide("demo", key, prior=lambda: {"x": 2})
    assert (d, tier) == ({"x": 3}, "db")
    snap = tuning.provenance_snapshot()
    assert snap["per_op"]["demo"] == {"db": 1, "analytic": 1, "default": 1}
    assert snap["decisions"] == 3 and snap["db_hits"] == 1


def test_candidate_entries_do_not_count_as_hits(tuned):
    key = tuning.canonical_key("demo", "s", "float32", "cpu")
    _write_db(tuned, [(key, {"x": 9}, "candidate")])
    d, tier = tuning.decide("demo", key, prior=lambda: {"x": 2})
    assert (d, tier) == ({"x": 2}, "analytic")


def test_validate_rejects_unusable_db_decision(tuned):
    key = tuning.canonical_key("demo", "s", "float32", "cpu")
    _write_db(tuned, [(key, {"x": "bogus"}, "swept")])
    d, tier = tuning.decide("demo", key, prior=lambda: {"x": 2},
                            validate=lambda dd: isinstance(dd.get("x"), int))
    assert (d, tier) == ({"x": 2}, "analytic")


def test_sweep_mode_records_candidates(tuned):
    pt.flags.set_flags({"tuning_mode": "sweep"})
    key = tuning.canonical_key("demo", "swept-shape", "float32", "cpu")
    d, tier = tuning.decide("demo", key, prior=lambda: {"x": 5})
    assert (d, tier) == ({"x": 5}, "analytic")
    raw = json.load(open(tuned))
    assert raw["entries"][key] == {
        "decision": {"x": 5}, "source": "candidate",
        "note": "analytic resolution tier=analytic"}


# -- conv lowering: the PR 5 equivalence (acceptance) ------------------------

# the PERF.md r6 cost-table shapes (b128 NHWC bf16, bench configuration):
# (name, n, h, w, cin, cout, kh, kw, strides, pads, dil, table_verdict)
# table_verdict None = borderline row (the A/B decides, not the model)
PERF_COST_TABLE = [
    ("stem_7x7_s2_3ch", 128, 224, 224, 3, 64, 7, 7, (2, 2),
     [(3, 3), (3, 3)], (1, 1), True),
    ("stem_s2d_4x4_12ch", 128, 112, 112, 12, 64, 4, 4, (1, 1),
     [(2, 1), (2, 1)], (1, 1), None),
    ("s0_3x3_64ch", 128, 56, 56, 64, 64, 3, 3, (1, 1),
     [(1, 1), (1, 1)], (1, 1), False),
    ("s1_3x3_128ch", 128, 28, 28, 128, 128, 3, 3, (1, 1),
     [(1, 1), (1, 1)], (1, 1), False),
]


def _take(row, dtype="bfloat16"):
    _, n, h, w, cin, cout, kh, kw, s, pads, d, _ = row
    return _igemm_take(_sds((n, h, w, cin), dtype),
                       _sds((kh, kw, cin, cout), dtype),
                       s, pads, d, 1, "NHWC")


def _conv_db_key(row, dtype="bfloat16"):
    _, n, h, w, cin, cout, kh, kw, s, pads, d, _ = row
    hout = (h + sum(pads[0]) - ((kh - 1) * d[0] + 1)) // s[0] + 1
    wout = (w + sum(pads[1]) - ((kw - 1) * d[1] + 1)) // s[1] + 1
    return tuning.canonical_key(
        "conv2d", tuning.conv_key(n, hout, wout, cin, cout, kh, kw, s, d,
                                  "NHWC"), dtype, tuning.device_kind())


def test_analytic_model_matches_perf_cost_table():
    """With tuning off, `auto` is the bare PR 5 cost model — and its
    verdicts on the definite cost-table rows are the documented ones
    (igemm for the 3-channel raw stem, direct for s0/s1)."""
    pt.flags.set_flags({"tuning_mode": "off"})
    for row in PERF_COST_TABLE:
        verdict = row[-1]
        if verdict is not None:
            assert _take(row) is verdict, row[0]


def test_consult_with_swept_db_reproduces_pr5_decisions(tuned):
    """Acceptance: a swept DB whose entries carry the measured verdicts
    reproduces the PR 5 per-shape decisions over the cost-table shapes —
    every resolution an exact DB hit (hit-rate 1.0)."""
    pt.flags.set_flags({"tuning_mode": "off"})
    analytic = {row[0]: _take(row) for row in PERF_COST_TABLE}
    _write_db(tuned, [
        (_conv_db_key(row),
         {"lowering": "igemm" if analytic[row[0]] else "direct"}, "swept")
        for row in PERF_COST_TABLE])
    pt.flags.set_flags({"tuning_mode": "consult"})
    tuning.reset_provenance()
    for row in PERF_COST_TABLE:
        assert _take(row) is analytic[row[0]], row[0]
    snap = tuning.provenance_snapshot()
    assert snap["per_op"]["conv2d"]["db"] == len(PERF_COST_TABLE)
    assert snap["hit_rate"] == 1.0


def test_consult_swept_override_beats_prior(tuned):
    """...or beats them: a measured igemm win on a shape the model prices
    as direct (s0) is honored from the DB, while unswept shapes keep the
    analytic verdict."""
    s0, s1 = PERF_COST_TABLE[2], PERF_COST_TABLE[3]
    _write_db(tuned, [(_conv_db_key(s0), {"lowering": "igemm"}, "swept")])
    assert _take(s0) is True      # DB override
    assert _take(s1) is False     # analytic fallback (no entry)
    snap = tuning.provenance_snapshot()
    assert snap["per_op"]["conv2d"] == {"db": 1, "analytic": 1, "default": 0}


def test_consult_with_corrupt_db_falls_back_to_analytic(tuned):
    """Acceptance: a corrupt DB must not change decisions or raise."""
    open(tuned, "w").write("{definitely not json")
    pt.flags.set_flags({"tuning_mode": "off"})
    analytic = {row[0]: _take(row) for row in PERF_COST_TABLE}
    pt.flags.set_flags({"tuning_mode": "consult"})
    tuning.invalidate_db_cache()
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")  # the one-time unreadable warning
        for row in PERF_COST_TABLE:
            assert _take(row) is analytic[row[0]], row[0]


def test_igemm_force_flags_override_the_db(tuned):
    """'on'/'off' are hard forces (the A/B arms): the DB must not win."""
    s0 = PERF_COST_TABLE[2]
    _write_db(tuned, [(_conv_db_key(s0), {"lowering": "igemm"}, "swept")])
    pt.flags.set_flags({"conv_implicit_gemm": "off"})
    assert _take(s0) is False
    pt.flags.set_flags({"conv_implicit_gemm": "on"})
    assert _take(s0) is True
    pt.flags.set_flags({"conv_implicit_gemm": "auto"})


# -- attention backend: the BENCH_r05 split (acceptance) ---------------------

def _attn_key(b, nh, s, dh, dtype="float32"):
    return tuning.canonical_key(
        "attention", tuning.attention_key(b, nh, s, s, dh, False),
        dtype, tuning.device_kind())


def test_attention_split_matches_bench_r05(tuned):
    """Swept DB carrying the measured split: XLA at seq 128, Pallas at
    s512. Both resolve as exact hits regardless of the use_pallas flag the
    model was built with — the per-model flag becomes a cache entry."""
    from paddle_tpu.ops.attention_ops import attention_backend

    _write_db(tuned, [
        (_attn_key(128, 12, 128, 64), {"backend": "xla"}, "swept"),
        (_attn_key(64, 12, 512, 64), {"backend": "pallas_short"}, "swept"),
    ])
    b128, t = attention_backend((128, 12, 128, 64), (128, 12, 128, 64),
                                np.dtype("float32"), use_pallas=True)
    assert (b128, t) == ("xla", "db")
    b512, t = attention_backend((64, 12, 512, 64), (64, 12, 512, 64),
                                np.dtype("float32"), use_pallas=False)
    assert (b512, t) == ("pallas_short", "db")


def test_attention_backend_analytic_unchanged_when_off():
    pt.flags.set_flags({"tuning_mode": "off"})
    from paddle_tpu.ops.attention_ops import attention_backend

    b, tier = attention_backend((8, 4, 128, 64), (8, 4, 128, 64),
                                np.dtype("float32"))
    assert (b, tier) == ("xla", "analytic")


def test_unrunnable_swept_backend_degrades_at_dispatch(tuned):
    """A Pallas verdict replayed off-TPU must still produce exact
    attention numerics via the reference path."""
    from paddle_tpu.ops.attention_ops import (_reference_attention,
                                              flash_attention)

    b, nh, s, dh = 2, 2, 16, 8
    _write_db(tuned, [(_attn_key(b, nh, s, dh),
                       {"backend": "pallas_short"}, "swept")])
    rng = np.random.default_rng(0)
    q, k, v = (rng.standard_normal((b, nh, s, dh)).astype(np.float32)
               for _ in range(3))
    out = flash_attention(q, k, v, sm_scale=dh ** -0.5)
    ref = _reference_attention(q, k, v, sm_scale=dh ** -0.5)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-6)


# -- conv+BN fusion gating ---------------------------------------------------

def _conv_bn_program():
    img = L.data(name="img", shape=[8, 8, 3], dtype="float32")
    c = L.conv2d(img, num_filters=4, filter_size=3, padding=1,
                 bias_attr=False, data_format="NHWC")
    b = L.batch_norm(c, data_layout="NHWC")
    loss = L.reduce_mean(b)
    pt.optimizer.SGD(0.1).minimize(loss)
    return loss


def _op_types():
    return [op.type for op in pt.default_main_program().global_block.ops]


def test_fusion_db_entry_retires_one_shape(tuned):
    """A swept {"fuse": false} for the conv's shape keeps the pair
    unfused; with no entry the analytic prior fuses as before."""
    db = tuning.TuningDB(tuned)
    # key must match _fusion_wanted's spelling: batch -1 (declared), the
    # declared output tile, fp32
    key = tuning.canonical_key(
        "conv2d_bn_fusion",
        tuning.conv_key(-1, 8, 8, 3, 4, 3, 3, [1, 1], [1, 1], "NHWC"),
        "float32", tuning.device_kind())
    db.put(key, {"fuse": False}, source="swept")
    db.save(tuned)
    tuning.invalidate_db_cache()
    _conv_bn_program()
    types = _op_types()
    assert "conv2d_bn" not in types and "batch_norm" in types


def test_fusion_fuses_without_db_entry(tuned):
    _conv_bn_program()
    types = _op_types()
    assert "conv2d_bn" in types and "batch_norm" not in types


# -- AMP gray-list decisions -------------------------------------------------

def test_amp_gray_entry_promotes_and_demotes(tuned):
    from paddle_tpu.contrib.mixed_precision.fp16_lists import (
        AutoMixedPrecisionLists, apply_tuning_overrides)

    _write_db(tuned, [
        (tuning.canonical_key("amp_list", tuning.amp_key("pool2d"), "-",
                              tuning.device_kind()),
         {"list": "white"}, "swept"),
        (tuning.canonical_key("amp_list", tuning.amp_key("softmax"), "-",
                              tuning.device_kind()),
         {"list": "black"}, "swept"),
    ])
    lists = apply_tuning_overrides(AutoMixedPrecisionLists())
    assert "pool2d" in lists.white_list and "pool2d" not in lists.gray_list
    assert "softmax" in lists.black_list and "softmax" not in lists.gray_list
    assert "relu" in lists.gray_list  # untouched without an entry


def test_amp_custom_lists_win_over_db(tuned):
    """An op the user moved out of gray is no longer tunable."""
    from paddle_tpu.contrib.mixed_precision.fp16_lists import (
        AutoMixedPrecisionLists, apply_tuning_overrides)

    _write_db(tuned, [
        (tuning.canonical_key("amp_list", tuning.amp_key("pool2d"), "-",
                              tuning.device_kind()),
         {"list": "white"}, "swept")])
    lists = AutoMixedPrecisionLists(custom_black_list=["pool2d"])
    lists.gray_list.discard("pool2d")
    lists.black_list.add("pool2d")
    out = apply_tuning_overrides(lists)
    assert "pool2d" in out.black_list and "pool2d" not in out.white_list


# -- bucket boundaries -------------------------------------------------------

def test_bucket_boundary_db_override_and_validation(tuned):
    from paddle_tpu.data_feeder import _tuned_extent

    k = tuning.canonical_key("feed_bucket",
                             tuning.bucket_key("rx", 1, 9), "-",
                             tuning.device_kind())
    _write_db(tuned, [(k, {"pad_to": 12}, "swept")])
    assert _tuned_extent("rx", 1, 9, 16) == 12       # DB refines pow2
    # an override below the raw extent would drop data: rejected
    _write_db(tuned, [(k, {"pad_to": 4}, "swept")])
    assert _tuned_extent("rx", 1, 9, 16) == 16
    # unswept boundary keeps the prior
    assert _tuned_extent("rx", 1, 5, 8) == 8


def test_feeder_bucket_decision_recorded_in_sweep(tuned):
    pt.flags.set_flags({"tuning_mode": "sweep"})
    x = L.data(name="bx", shape=[2], dtype="float32")
    feeder = pt.DataFeeder([x], bucket_size=4)
    feed = feeder.feed([(np.zeros(2, np.float32),)] * 3)
    assert feed["bx"].shape[0] == 4
    raw = json.load(open(tuned))
    keys = [k for k in raw["entries"] if k.startswith("feed_bucket|")]
    assert keys and raw["entries"][keys[0]]["source"] == "candidate"


# -- minimize-time hook + end-to-end -----------------------------------------

def test_on_minimize_stamps_mode_and_loads_db(tuned):
    open(tuned, "w").write("{corrupt")
    tuning.invalidate_db_cache()
    with pytest.warns(UserWarning, match="unreadable"):
        loss = L.reduce_mean(L.fc(
            L.data(name="x", shape=[4], dtype="float32"), size=2))
        pt.optimizer.SGD(0.1).minimize(loss)
    assert pt.default_main_program()._tuning_mode == "consult"


def test_end_to_end_consult_trains_finite(tuned):
    """Full minimize + run under consult with a swept DB forcing the igemm
    lowering for the model's conv: decisions consult the DB at trace time
    and the step stays numerically healthy."""
    key = tuning.canonical_key(
        "conv2d", tuning.conv_key(4, 8, 8, 3, 4, 3, 3, (1, 1), (1, 1),
                                  "NHWC"),
        "float32", tuning.device_kind())
    _write_db(tuned, [(key, {"lowering": "igemm"}, "swept")])
    img = L.data(name="img", shape=[8, 8, 3], dtype="float32")
    label = L.data(name="label", shape=[1], dtype="int64")
    c = L.conv2d(img, num_filters=4, filter_size=3, padding=1,
                 data_format="NHWC")
    b = L.batch_norm(c, act="relu", data_layout="NHWC")
    p = L.pool2d(b, global_pooling=True, pool_type="avg",
                 data_format="NHWC")
    loss = L.reduce_mean(
        L.softmax_with_cross_entropy(L.fc(p, size=10), label))
    pt.optimizer.SGD(0.1).minimize(loss)
    exe = pt.Executor()
    exe.run(pt.default_startup_program())
    tuning.reset_provenance()
    rng = np.random.default_rng(0)
    feed = {"img": rng.standard_normal((4, 8, 8, 3)).astype(np.float32),
            "label": rng.integers(0, 10, (4, 1)).astype(np.int64)}
    (lv,) = exe.run(pt.default_main_program(), feed=feed, fetch_list=[loss])
    assert np.isfinite(float(np.asarray(lv)))
    snap = tuning.provenance_snapshot()
    assert snap["per_op"].get("conv2d", {}).get("db", 0) >= 1


# -- the sweeper + shared timing ---------------------------------------------

def test_timing_stats_and_verdicts():
    from tools import _timing

    assert _timing.median([3.0, 1.0, 2.0]) == 2.0
    assert _timing.interference_band([1.0]) == 0.0
    assert _timing.interference_band([1.0, 1.1]) == pytest.approx(0.0952,
                                                                  abs=1e-3)
    assert _timing.ab_verdict(1.0, 0.9) == "keep"
    assert _timing.ab_verdict(1.0, 1.2) == "retire"
    assert _timing.ab_verdict(1.0, 1.01) == "tie"
    assert _timing.ab_verdict(1.0, 0.97) == "tie"  # inside the 5% band


def test_tune_sweep_conv_writes_swept_entries(tuned, tmp_path):
    from tools import tune

    db = tuning.TuningDB(str(tmp_path / "swept.json"))
    shapes = [("tiny_3ch", 2, 12, 12, 3, 8, 3, 3, (1, 1),
               [(1, 1), (1, 1)], (1, 1))]
    tune.sweep_conv(db, shapes, "float32", iters=1, passes=2, band=0.05)
    db.save()
    raw = json.load(open(str(tmp_path / "swept.json")))
    (key,) = list(raw["entries"])
    entry = raw["entries"][key]
    assert key.startswith("conv2d|n=2 out=12x12 cin=3 cout=8 ")
    assert entry["source"] == "swept"
    assert entry["decision"]["lowering"] in ("direct", "igemm")
    assert {"direct", "igemm"} <= set(entry["measured"])
    assert "median_s" in entry["measured"]["direct"]
