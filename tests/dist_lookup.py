"""Distributed-lookup-table runner (reference dist_ctr.py + the
distributed lookup table rewrite): an embedding too big to replicate is
row-sharded over the pservers; trainers prefetch only the batch's rows and
ship SelectedRows grads routed per slice. Sync mode must reproduce the
single-process DENSE trajectory exactly.

usage: dist_lookup.py ROLE EPS TRAINER_ID N_TRAINERS OUT_NPZ [CURRENT_EP]
"""
import sys

import jax

jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402

import paddle_tpu as pt  # noqa: E402
from paddle_tpu import layers as L  # noqa: E402

import os  # noqa: E402

STEPS = int(os.environ.get("DIST_LOOKUP_STEPS", "5"))
FULL_BATCH = 32
VOCAB = 1000
FIELDS = 4
DIM = 8


def build(distributed: bool):
    ids = L.data(name="ids", shape=[FIELDS], dtype="int64")
    y = L.data(name="y", shape=[1], dtype="float32")
    emb = L.embedding(ids, size=[VOCAB, DIM], is_sparse=distributed,
                      is_distributed=distributed,
                      param_attr=pt.ParamAttr(name="big_emb"))
    pooled = L.reduce_sum(emb, dim=1)
    h = L.fc(pooled, size=16, act="relu")
    pred = L.fc(h, size=1)
    return L.mean(L.square_error_cost(pred, y))


def full_data():
    rng = np.random.default_rng(0)
    ids = rng.integers(0, VOCAB, (FULL_BATCH, FIELDS)).astype(np.int64)
    y = (np.sin(ids.sum(axis=1, keepdims=True) / 100.0)).astype(np.float32)
    return ids, y


def main():
    role, eps, trainer_id, n_trainers, out = (
        sys.argv[1], sys.argv[2], int(sys.argv[3]), int(sys.argv[4]),
        sys.argv[5])
    current_ep = sys.argv[6] if len(sys.argv) > 6 else None

    main_p, startup = pt.Program(), pt.Program()
    main_p.random_seed = startup.random_seed = 7
    with pt.program_guard(main_p, startup):
        with pt.unique_name.guard():
            loss = build(distributed=role != "local")
            pt.optimizer.SGD(0.1).minimize(loss)

    exe = pt.Executor()
    ids, y = full_data()

    if role == "local":
        exe.run(startup)
        for _ in range(STEPS):
            (lv,) = exe.run(main_p, feed={"ids": ids, "y": y},
                            fetch_list=[loss.name])
        _dump(out, main_p, float(np.asarray(lv).reshape(-1)[0]))
        return

    t = pt.DistributeTranspiler()
    t.transpile(trainer_id, program=main_p, pservers=eps,
                trainers=n_trainers, sync_mode=True,
                startup_program=startup)

    if role == "pserver":
        exe.run(t.get_startup_program())
        exe.run(t.get_pserver_program(current_ep))
        return

    # trainer: its startup no longer initializes big_emb — assert that
    exe.run(startup)
    assert pt.global_scope().find_var("big_emb") is None, (
        "distributed table materialized in the trainer scope")
    prog = t.get_trainer_program()
    shard = FULL_BATCH // n_trainers
    lo = trainer_id * shard
    for _ in range(STEPS):
        (lv,) = exe.run(prog, feed={"ids": ids[lo:lo + shard],
                                    "y": y[lo:lo + shard]},
                        fetch_list=[loss.name])
    # pull the final sharded table for the oracle comparison BEFORE closing
    # (close -> send_complete -> the last trainer's close shuts the servers
    # down). Test-only: production uses save_persistables/checkpoint_notify.
    from paddle_tpu.distributed.ps_rpc import PSClient, fetch_sections

    pb = next(p for p in t.param_blocks if p["param"] == "big_emb")
    client = PSClient.get(tuple(t.eps), trainer_id)
    table = fetch_sections(client, "big_emb", pb["eps"], pb["sections"])
    exe.close()
    vals = {p.name: np.asarray(pt.global_scope().find_var(p.name))
            for p in main_p.all_parameters()
            if pt.global_scope().find_var(p.name) is not None}
    vals["big_emb"] = table
    vals["__last_loss__"] = np.asarray(lv)
    np.savez(out, **vals)


def _dump(out, program, last_loss):
    vals = {p.name: np.asarray(pt.global_scope().find_var(p.name))
            for p in program.all_parameters()}
    vals["__last_loss__"] = np.asarray(last_loss)
    np.savez(out, **vals)


if __name__ == "__main__":
    main()
