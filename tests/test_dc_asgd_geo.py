"""DC-ASGD delay compensation + geo-SGD delta protocol.

DC-ASGD (reference distribute_transpiler.py:1979 _append_dc_asgd_ops): on a
staleness-heavy run, async+DC must track the sync-SGD oracle closer than
plain async. The server object is exercised directly (no sockets) — the
trajectory is the contract, the wire is covered by the e2e dist tests.
"""
import numpy as np

import paddle_tpu as pt
from paddle_tpu import layers as L


def _make_server(dc_asgd, lam=1.0, lr=0.1):
    """PServerRuntime over one param 'w' with an SGD optimize program."""
    from paddle_tpu.distributed.ps_rpc import PServerRuntime

    main, startup = pt.Program(), pt.Program()
    with pt.program_guard(main, startup):
        with pt.unique_name.guard():
            g = L.data(name="w@GRAD", shape=[4], dtype="float32",
                       append_batch_size=False)
            w = main.global_block.create_parameter(
                shape=[4], dtype="float32", name="w")
            lrv = L.tensor.fill_constant([1], "float32", lr)
            main.global_block.append_op(
                "sgd",
                {"Param": ["w"], "Grad": ["w@GRAD"],
                 "LearningRate": [lrv.name]},
                {"ParamOut": ["w"]}, {})
    scope = pt.Scope()
    scope.set_var("w", np.zeros(4, np.float32))
    rt = PServerRuntime(
        endpoint="test:0", n_trainers=2, sync_mode=False,
        blocks=[{"grad": "w@GRAD", "param": "w", "origin_param": "w",
                 "sparse": False, "optimize_program": main}],
        scope=scope, executor=pt.Executor(),
        dc_asgd=dc_asgd, dc_asgd_lambda=lam)
    return rt, scope


def _simulate(rt, scope, w_star, steps=30, delay=4, seed=0):
    """Trainer 0 sends fresh grads every step; trainer 1 computes its grad
    at the param it saw `delay` steps ago (the staleness injector).
    Quadratic loss: grad(w) = w - w_star."""
    rng = np.random.default_rng(seed)
    history = [np.asarray(scope.find_var("w"), np.float32).copy()]
    slow_job = None  # (grad, finish_step) — the slow trainer's in-flight work
    for t in range(steps):
        # trainer 0: pull -> compute -> send within the step (fresh grads)
        w_now = np.asarray(rt._handle_get({"name": "w", "trainer": 0}),
                           np.float32).copy()
        noise = rng.standard_normal(4).astype(np.float32) * 0.05
        rt._handle_send({"name": "w@GRAD", "trainer": 0,
                         "value": ("dense", w_now - w_star + noise)})
        # trainer 1: pulls only when it STARTS a computation; the result
        # lands `delay` steps later — the real slow-trainer pattern (it does
        # not pull mid-computation, so the server's get-time snapshot is
        # exactly the params this gradient was computed at)
        if slow_job is None:
            w_seen = np.asarray(rt._handle_get({"name": "w", "trainer": 1}),
                                np.float32).copy()
            slow_job = (w_seen - w_star + noise, t + delay)
        elif t >= slow_job[1]:
            rt._handle_send({"name": "w@GRAD", "trainer": 1,
                             "value": ("dense", slow_job[0])})
            slow_job = None
        history.append(np.asarray(scope.find_var("w"), np.float32).copy())
    return np.stack(history)


def _sync_oracle(w_star, steps=30, lr=0.1, seed=0):
    """Two-trainer synchronous SGD, both grads fresh, averaged."""
    rng = np.random.default_rng(seed)
    w = np.zeros(4, np.float32)
    hist = [w.copy()]
    for t in range(steps):
        noise = rng.standard_normal(4).astype(np.float32) * 0.05
        g = (w - w_star + noise)  # both trainers' fresh grad at w
        w = w - lr * g
        hist.append(w.copy())
    return np.stack(hist)


def test_dc_asgd_tracks_sync_oracle_closer_than_plain_async():
    w_star = np.array([1.0, -2.0, 0.5, 3.0], np.float32)
    oracle = _sync_oracle(w_star)

    def final_gap(dc):
        rt, scope = _make_server(dc_asgd=dc, lam=1.0)
        traj = _simulate(rt, scope, w_star)
        # distance of the whole trajectory tail to the oracle trajectory
        n = min(len(traj), len(oracle))
        return float(np.linalg.norm(traj[n // 2:n] - oracle[n // 2:n]))

    plain = final_gap(dc=False)
    dc = final_gap(dc=True)
    assert dc < plain, (dc, plain)


def test_dc_asgd_snapshot_taken_at_get_time():
    rt, scope = _make_server(dc_asgd=True)
    w_star = np.ones(4, np.float32)
    # no get yet -> no snapshot -> first send applies uncompensated
    rt._handle_send({"name": "w@GRAD", "trainer": 0,
                     "value": ("dense", -w_star)})
    assert ("w@GRAD", 0) not in rt._param_bak
    np.testing.assert_allclose(np.asarray(scope.find_var("w")),
                               0.1 * w_star, rtol=1e-6)
    # the snapshot records what the trainer SAW when it pulled
    seen = rt._handle_get({"name": "w", "trainer": 0})
    np.testing.assert_allclose(rt._param_bak[("w@GRAD", 0)], seen)
    # further applies must not move the snapshot (only the next get does)
    rt._handle_send({"name": "w@GRAD", "trainer": 1,
                     "value": ("dense", -w_star)})
    np.testing.assert_allclose(rt._param_bak[("w@GRAD", 0)], seen)


def test_geo_delta_payload_adds_to_param():
    rt, scope = _make_server(dc_asgd=False)
    rt._handle_send({"name": "w", "trainer": 0,
                     "value": ("delta", np.full(4, 0.25, np.float32))})
    np.testing.assert_allclose(np.asarray(scope.find_var("w")),
                               0.25, rtol=1e-6)


def test_geo_communicator_push_pull_cycle():
    """GeoCommunicator against a fake client backed by a dict 'server':
    local steps accumulate, push ships the delta, pull rebases."""
    from paddle_tpu.distributed.communicator import GeoCommunicator

    server = {"w": np.zeros(4, np.float32)}

    class FakeClient:
        trainer_id = 0

        def _call(self, ep, meta, tensors=()):
            if meta["op"] == "send":
                assert meta["kind"] == "delta"
                (delta,) = tensors
                server["w"] = server["w"] + delta
                return {"s": "ok"}, []
            raise AssertionError(meta)

        def get_var(self, ep, name):
            return server["w"].copy()

    scope = pt.Scope()
    scope.set_var("w", np.zeros(4, np.float32))
    geo = GeoCommunicator({"w": {"epmap": ["ep0"], "sections": []}},
                          FakeClient(), scope, push_nums=3)
    geo.start()
    for step in range(6):
        local = np.asarray(scope.find_var("w"), np.float32)
        scope.set_var("w", local + 0.1)  # a "local optimizer step"
        geo.mark_step()
    # two pushes of +0.3 each; server also reflected back into the scope
    np.testing.assert_allclose(server["w"], 0.6, rtol=1e-5)
    np.testing.assert_allclose(np.asarray(scope.find_var("w")), 0.6,
                               rtol=1e-5)


def test_geo_mode_transpile_keeps_local_optimizer():
    """config.geo_sgd_mode: trainer program keeps its optimizer ops and
    sends NO gradients; get_geo_communicator covers every dense param."""
    from paddle_tpu.transpiler import (DistributeTranspiler,
                                       DistributeTranspilerConfig)

    main, startup = pt.Program(), pt.Program()
    with pt.program_guard(main, startup):
        with pt.unique_name.guard():
            x = L.data(name="x", shape=[4], dtype="float32")
            y = L.data(name="y", shape=[1], dtype="float32")
            loss = L.mean(L.square_error_cost(L.fc(x, size=1), y))
            pt.optimizer.SGD(0.1).minimize(loss)

    cfg = DistributeTranspilerConfig()
    cfg.sync_mode = False
    cfg.geo_sgd_mode = True
    cfg.geo_sgd_need_push_nums = 7
    t = DistributeTranspiler(config=cfg)
    t.transpile(trainer_id=0, program=main, startup_program=startup,
                pservers="127.0.0.1:60901", trainers=2)
    trainer = t.get_trainer_program()
    ops = [op.type for op in trainer.global_block.ops]
    assert "sgd" in ops, ops           # local optimizer retained
    assert "send" not in ops, ops      # no gradient sends
    scope = pt.Scope()

    class NullClient:
        trainer_id = 0

    geo = t.get_geo_communicator(scope, client=NullClient())
    assert geo.push_nums == 7
    assert len(geo.param_ctx) >= 2     # fc weight + bias
