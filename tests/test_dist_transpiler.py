"""DistributeTranspiler unit tests: pure program-transformation assertions,
no networking (reference unittests/test_dist_transpiler.py pattern)."""
import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import layers as L
from paddle_tpu.transpiler import slice_variable
from paddle_tpu.transpiler.distribute_transpiler import VarBlock


class _FakeVar:
    def __init__(self, name, shape):
        self.name = name
        self.shape = tuple(shape)


def test_slice_variable_blocks():
    v = _FakeVar("w", (100, 100))  # 10k elements
    blocks = slice_variable([v], 4, min_block_size=2048)
    assert len(blocks) == 4
    assert sum(b.size for b in blocks) == 100
    assert all(isinstance(b, VarBlock) for b in blocks)
    # small var -> one block
    small = _FakeVar("b", (10,))
    assert len(slice_variable([small], 4, min_block_size=2048)) == 1


def _build_and_transpile(opt, trainers=2, pservers="1.1.1.1:1234,1.1.1.2:1234",
                         sparse=False):
    main, startup = pt.Program(), pt.Program()
    with pt.program_guard(main, startup):
        with pt.unique_name.guard():
            x = L.data(name="x", shape=[64], dtype="float32")
            y = L.data(name="y", shape=[1], dtype="float32")
            h = L.fc(x, size=512, act="relu")  # 64x512 w: big enough to slice
            if sparse:
                ids = L.data(name="ids", shape=[2], dtype="int64")
                emb = L.embedding(ids, size=[1000, 16], is_sparse=True,
                                  param_attr=pt.ParamAttr(name="emb_w"))
                h = L.concat([h, L.reduce_sum(emb, dim=1)], axis=1)
            loss = L.mean(L.square_error_cost(L.fc(h, size=1), y))
            opt.minimize(loss)
    t = pt.DistributeTranspiler()
    t.transpile(trainer_id=0, program=main, pservers=pservers,
                trainers=trainers, sync_mode=True, startup_program=startup)
    return t, main


def test_trainer_program_op_list():
    t, main = _build_and_transpile(pt.optimizer.SGD(0.1))
    types = [op.type for op in main.global_block.ops]
    assert "sgd" not in types  # optimize ops moved to pserver
    n_params = 4  # 2 fc layers x (w, b)
    assert types.count("send") == n_params
    assert types.count("recv") == n_params
    assert types.count("send_barrier") == 1
    assert types.count("fetch_barrier") == 1
    # barriers sit between sends and recvs
    assert types.index("send_barrier") > max(
        i for i, t_ in enumerate(types) if t_ == "send")
    assert types.index("fetch_barrier") > max(
        i for i, t_ in enumerate(types) if t_ == "recv")


def test_large_sgd_param_is_sliced_across_pservers():
    t, main = _build_and_transpile(pt.optimizer.SGD(0.1))
    send_ops = [op for op in main.global_block.ops if op.type == "send"]
    sliced = [op for op in send_ops if op.attr("sections")]
    assert sliced, "the 64x512 fc weight should be row-sliced"
    op = sliced[0]
    assert len(op.attr("epmap")) == len(op.attr("sections")) == 2
    assert sum(op.attr("sections")) in (64, 512)  # rows of a fc weight


def test_adam_params_are_whole_with_accumulator_state():
    t, main = _build_and_transpile(pt.optimizer.Adam(0.001))
    send_ops = [op for op in main.global_block.ops if op.type == "send"]
    assert all(not op.attr("sections") for op in send_ops)
    # each pserver optimize program contains one adam op with moment vars
    specs = [s for eps in t._ep_specs.values() for s in eps]
    prog = pt.Program.from_dict(specs[0]["optimize_program"])
    assert [op.type for op in prog.global_block.ops] == ["adam"]
    assert any("moment" in n for n in prog.global_block.vars)


def test_sparse_embedding_goes_whole_to_one_pserver():
    t, main = _build_and_transpile(pt.optimizer.SGD(0.1), sparse=True)
    emb_sends = [
        op for op in main.global_block.ops
        if op.type == "send" and op.inputs["X"][0].startswith("emb_w")
    ]
    assert len(emb_sends) == 1
    assert emb_sends[0].attr("sparse") is True
    assert not emb_sends[0].attr("sections")
    assert len(emb_sends[0].attr("epmap")) == 1


def test_pserver_program_structure():
    t, _ = _build_and_transpile(pt.optimizer.SGD(0.1))
    prog = t.get_pserver_program("1.1.1.1:1234")
    ops = prog.global_block.ops
    assert len(ops) == 1 and ops[0].type == "listen_and_serv"
    assert ops[0].attr("Fanin") == 2
    assert ops[0].attr("sync_mode") is True
    specs = ops[0].attr("block_specs")
    assert specs, "endpoint must own at least one block"
    with pytest.raises(ValueError, match="unknown pserver"):
        t.get_pserver_program("9.9.9.9:1")


def test_transpile_requires_optimize_ops():
    with pt.program_guard(pt.Program(), pt.Program()):
        x = L.data(name="x", shape=[4], dtype="float32")
        L.fc(x, size=2)
        with pytest.raises(ValueError, match="minimize"):
            pt.DistributeTranspiler().transpile(
                0, program=pt.default_main_program(), trainers=1)


def test_distributed_lookup_table_rewrite():
    """embedding(is_distributed=True) rewrite (reference
    distribute_transpiler.py:1503-1656): forward lookup_table -> prefetch,
    backward -> lookup_table_grad_rows, table row-sharded across every
    pserver, no whole-table recv, trainer startup init neutralized WITHOUT
    shifting the RNG stream of later init ops."""
    import paddle_tpu as pt
    from paddle_tpu import layers as L

    main_p, startup = pt.Program(), pt.Program()
    main_p.random_seed = startup.random_seed = 3
    with pt.program_guard(main_p, startup):
        with pt.unique_name.guard():
            ids = L.data(name="ids", shape=[4], dtype="int64")
            y = L.data(name="y", shape=[1], dtype="float32")
            emb = L.embedding(ids, size=[100, 8], is_sparse=True,
                              is_distributed=True,
                              param_attr=pt.ParamAttr(name="big_emb"))
            pooled = L.reduce_sum(emb, dim=1)
            pred = L.fc(pooled, size=1)
            loss = L.mean(L.square_error_cost(pred, y))
            pt.optimizer.SGD(0.1).minimize(loss)

    n_startup_ops = len(startup.global_block.ops)
    t = pt.DistributeTranspiler()
    t.transpile(0, program=main_p, pservers="ep0:1,ep1:2", trainers=2,
                sync_mode=True, startup_program=startup)

    ops = [op.type for op in main_p.global_block.ops]
    assert "prefetch" in ops and "lookup_table" not in ops
    assert "lookup_table_grad_rows" in ops and "lookup_table_grad" not in ops

    # table sliced evenly: 50 rows per server, sparse optimize blocks
    for ep in ("ep0:1", "ep1:2"):
        tbl = [s for s in t._ep_specs[ep] if s["origin_param"] == "big_emb"]
        assert len(tbl) == 1 and tbl[0]["sparse"] and tbl[0]["rows"] == 50

    # the sparse send carries begins for per-slice row routing
    send = next(op for op in main_p.global_block.ops
                if op.type == "send" and op.inputs["X"][0].startswith("big_emb"))
    assert send.attrs["sections"] == [50, 50]
    assert send.attrs["begins"] == [0, 50]

    # no recv ever pulls the whole table
    recvs = [op.outputs["Out"][0] for op in main_p.global_block.ops
             if op.type == "recv"]
    assert "big_emb" not in recvs

    # trainer startup: table init neutralized, op COUNT preserved (RNG
    # stream alignment with the pserver startup), pserver startup intact
    s_outs = [n for op in startup.global_block.ops for n in op.output_names]
    assert "big_emb" not in s_outs
    assert len(startup.global_block.ops) == n_startup_ops
    ps_outs = [n for op in t.get_startup_program().global_block.ops
               for n in op.output_names]
    assert "big_emb" in ps_outs
