"""Predictor API over saved inference models (reference
inference/api/api_impl_tester.cc + test_inference_model_io.py pattern)."""
import numpy as np

import paddle_tpu as pt
from paddle_tpu import layers as L
from paddle_tpu.inference import (
    AnalysisConfig,
    NativeConfig,
    PaddleTensor,
    create_paddle_predictor,
)


def _save_model(tmp_path):
    x = L.data(name="x", shape=[8], dtype="float32")
    h = L.fc(x, size=16, act="relu")
    out = L.fc(h, size=3, act="softmax")
    exe = pt.Executor()
    exe.run(pt.default_startup_program())
    pt.io.save_inference_model(str(tmp_path / "model"), ["x"], [out], exe)
    xb = np.random.default_rng(0).standard_normal((4, 8)).astype(np.float32)
    (ref,) = exe.run(pt.default_main_program(), feed={"x": xb},
                     fetch_list=[out])
    return str(tmp_path / "model"), xb, ref


def test_native_predictor_matches_direct_run(tmp_path):
    model_dir, xb, ref = _save_model(tmp_path)
    pred = create_paddle_predictor(NativeConfig(model_dir=model_dir))
    assert pred.get_input_names() == ["x"]
    assert len(pred.get_output_names()) == 1
    outs = pred.run([PaddleTensor(name="x", data=xb)])
    np.testing.assert_allclose(np.asarray(outs[0].data), ref, rtol=1e-5)
    # repeated runs (cache hit) and clone both reproduce
    outs2 = pred.run_dict({"x": xb})
    np.testing.assert_allclose(outs2[0], ref, rtol=1e-5)
    clone = pred.clone()
    np.testing.assert_allclose(clone.run_dict({"x": xb})[0], ref, rtol=1e-5)


def test_analysis_predictor_bf16(tmp_path):
    model_dir, xb, ref = _save_model(tmp_path)
    cfg = AnalysisConfig(model_dir=model_dir, enable_bf16=True)
    pred = create_paddle_predictor(cfg)
    # the cast actually happened: loaded params are bf16 in the scope
    w = pred._scope.find_var("fc_0.w_0")
    assert np.asarray(w).dtype == np.dtype("bfloat16"), np.asarray(w).dtype
    (out,) = pred.run_dict({"x": xb})
    np.testing.assert_allclose(
        np.asarray(out, np.float32), ref, rtol=0.05, atol=0.02)


def test_clone_shares_compile_cache(tmp_path):
    """Predictor.clone must NOT re-wrap/recompile the program: the clone's
    first run over an already-compiled signature is a cache hit (the old
    clone paid a full XLA compile per clone)."""
    from paddle_tpu.pipeline import jit_compile_counter

    model_dir, xb, ref = _save_model(tmp_path)
    pred = create_paddle_predictor(NativeConfig(model_dir=model_dir))
    with jit_compile_counter() as c1:
        pred.run_dict({"x": xb})
    assert c1.count == 1
    clone = pred.clone()
    with jit_compile_counter() as c2:
        out = clone.run_dict({"x": xb})
    assert c2.count == 0, "clone recompiled an already-compiled signature"
    np.testing.assert_allclose(out[0], ref, rtol=1e-5)


def test_clone_runs_from_second_thread(tmp_path):
    """A cloned predictor serving from a second thread while the parent
    serves from the main thread: every result exact, no scope-stack
    corruption (run_dict must not touch the global scope stack)."""
    import threading

    model_dir, xb, ref = _save_model(tmp_path)
    pred = create_paddle_predictor(NativeConfig(model_dir=model_dir))
    clone = pred.clone()
    errors = []

    def worker(p):
        try:
            for _ in range(20):
                (out,) = p.run_dict({"x": xb})
                np.testing.assert_allclose(out, ref, rtol=1e-5)
        except Exception as e:  # noqa: BLE001 — surfaced on the main thread
            errors.append(e)

    threads = [threading.Thread(target=worker, args=(p,))
               for p in (pred, clone, clone)]
    for t in threads:
        t.start()
    worker(pred)  # main thread participates too
    for t in threads:
        t.join()
    assert not errors, errors


def test_predictor_missing_feed_raises(tmp_path):
    model_dir, xb, _ = _save_model(tmp_path)
    pred = create_paddle_predictor(NativeConfig(model_dir=model_dir))
    try:
        pred.run_dict({})
    except ValueError as e:
        assert "x" in str(e)
    else:
        raise AssertionError("expected ValueError for missing feed")
