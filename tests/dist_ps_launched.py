"""Env-driven fleet PS runner for the launch_ps launcher test: every role
and endpoint arrives via the PADDLE_* env contract that
`python -m paddle_tpu.distributed.launch --server_num N --worker_num M`
exports (reference launch_ps.py start_procs) — no positional role args.

usage: dist_ps_launched.py OUT_DIR
"""
import os
import sys

import jax

jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402

import paddle_tpu as pt  # noqa: E402
from paddle_tpu import layers as L  # noqa: E402
from paddle_tpu.incubate.fleet.base import PaddleCloudRoleMaker  # noqa: E402
from paddle_tpu.incubate.fleet.parameter_server import fleet  # noqa: E402

STEPS = 5
FULL_BATCH = 32


def main():
    out_dir = sys.argv[1]
    main_p, startup = pt.Program(), pt.Program()
    main_p.random_seed = startup.random_seed = 7
    with pt.program_guard(main_p, startup):
        with pt.unique_name.guard():
            x = L.data(name="x", shape=[16], dtype="float32")
            y = L.data(name="y", shape=[1], dtype="float32")
            h = L.fc(x, size=512, act="relu")
            pred = L.fc(h, size=1)
            loss = L.mean(L.square_error_cost(pred, y))
            fleet.init(PaddleCloudRoleMaker())
            opt = fleet.distributed_optimizer(pt.optimizer.SGD(0.1))
            opt.minimize(loss)

    if fleet.is_server():
        with pt.program_guard(main_p, startup):
            fleet.init_server()
            fleet.run_server()
        return

    tid = int(os.environ["PADDLE_TRAINER_ID"])
    n = int(os.environ["PADDLE_TRAINERS_NUM"])
    exe = pt.Executor()
    with pt.program_guard(main_p, startup):
        exe.run(startup)
        fleet.init_worker()
        rng = np.random.default_rng(0)
        x_all = rng.standard_normal((FULL_BATCH, 16)).astype(np.float32)
        w = rng.standard_normal((16, 1)).astype(np.float32)
        y_all = (x_all @ w).astype(np.float32)
        shard = FULL_BATCH // n
        lo = tid * shard
        losses = []
        for _ in range(STEPS):
            (lv,) = exe.run(fleet.main_program,
                            feed={"x": x_all[lo:lo + shard],
                                  "y": y_all[lo:lo + shard]},
                            fetch_list=[loss.name])
            losses.append(float(np.asarray(lv)))
        fleet.stop_worker()
    vals = {p.name: np.asarray(pt.global_scope().find_var(p.name))
            for p in main_p.all_parameters()}
    vals["__losses__"] = np.asarray(losses)
    np.savez(os.path.join(out_dir, f"trainer{tid}.npz"), **vals)


if __name__ == "__main__":
    main()
