"""Round-4 layers-DSL tail: OpTest-grade numeric oracles for the new
reference-nn.py parity batch (sequence_conv family, RNN variants, norms,
losses, py_func escape hatch, misc tensor ops)."""
import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import layers as L


def _run(build, feeds, n_fetch=1):
    main, startup = pt.Program(), pt.Program()
    with pt.program_guard(main, startup):
        with pt.unique_name.guard():
            outs = build()
    outs = outs if isinstance(outs, (list, tuple)) else [outs]
    exe = pt.Executor()
    with pt.scope_guard(pt.Scope()):
        exe.run(startup)
        vals = exe.run(main, feed=feeds, fetch_list=list(outs)[:n_fetch])
        return [np.asarray(v) for v in vals]


def _run_with_scope(build, feeds, fetch, scope):
    main, startup = pt.Program(), pt.Program()
    with pt.program_guard(main, startup):
        with pt.unique_name.guard():
            res = build()
    exe = pt.Executor()
    with pt.scope_guard(scope):
        exe.run(startup)
        vals = exe.run(main, feed=feeds,
                       fetch_list=[res[i] for i in fetch])
    return [np.asarray(v) for v in vals]


# -- sequence family ---------------------------------------------------------

def test_sequence_conv_matches_manual_window():
    B, T, D, F, K = 2, 5, 3, 4, 3
    rng = np.random.default_rng(0)
    x = rng.standard_normal((B, T, D)).astype(np.float32)

    def build():
        v = L.data(name="x", shape=[T, D], dtype="float32")
        return L.sequence_conv(v, num_filters=F, filter_size=K,
                               bias_attr=False,
                               param_attr=pt.ParamAttr(name="seqconv_w"))

    scope = pt.Scope()
    out, = _run_with_scope(lambda: [build()], {"x": x}, [0], scope)
    w = np.asarray(scope.find_var("seqconv_w"))        # [K*D, F]
    expect = np.zeros((B, T, F), np.float32)
    for b in range(B):
        for t in range(T):
            ctx = []
            for j in range(K):
                s = t - K // 2 + j
                ctx.append(x[b, s] if 0 <= s < T else np.zeros(D, np.float32))
            expect[b, t] = np.concatenate(ctx) @ w
    np.testing.assert_allclose(out, expect, rtol=2e-5, atol=1e-5)


def test_sequence_enumerate_and_reshape():
    x = np.array([[1, 2, 3, 4], [5, 6, 7, 0]], np.int64)
    ln = np.array([4, 3], np.int64)

    def build():
        v = L.data(name="x", shape=[4], dtype="int64")
        lv = L.data(name="ln", shape=[], dtype="int64")
        en = L.sequence_enumerate(v, win_size=2, pad_value=0, length=lv)
        r = L.data(name="r", shape=[2, 6], dtype="float32")
        rs = L.sequence_reshape(r, new_dim=4)
        return en, rs

    en, rs = _run(lambda: list(build()),
                  {"x": x, "ln": ln,
                   "r": np.arange(24, dtype=np.float32).reshape(2, 2, 6)},
                  n_fetch=2)
    np.testing.assert_array_equal(
        en[0], [[1, 2], [2, 3], [3, 4], [4, 0]])
    np.testing.assert_array_equal(
        en[1], [[5, 6], [6, 7], [7, 0], [0, 0]])
    assert rs.shape == (2, 3, 4)
    np.testing.assert_array_equal(rs[0, 0], [0, 1, 2, 3])


def test_sequence_slice_scatter_expand_as():
    x = np.arange(24, dtype=np.float32).reshape(2, 4, 3)

    def build():
        v = L.data(name="x", shape=[4, 3], dtype="float32")
        off = L.data(name="off", shape=[], dtype="int64")
        ln = L.data(name="ln", shape=[], dtype="int64")
        sl = L.sequence_slice(v, off, ln)
        base = L.data(name="base", shape=[5], dtype="float32")
        ids = L.data(name="ids", shape=[2], dtype="int64")
        upd = L.data(name="upd", shape=[2], dtype="float32")
        sc = L.sequence_scatter(base, ids, upd)
        small = L.data(name="small", shape=[3], dtype="float32")
        ex = L.sequence_expand_as(small, v)
        return sl, sc, ex

    sl, sc, ex = _run(
        lambda: list(build()),
        {"x": x, "off": np.array([1, 0], np.int64),
         "ln": np.array([2, 3], np.int64),
         "base": np.zeros((2, 5), np.float32),
         "ids": np.array([[0, 2], [1, 1]], np.int64),
         "upd": np.array([[1.0, 2.0], [3.0, 4.0]], np.float32),
         "small": np.array([[1, 2, 3]], np.float32)},
        n_fetch=3)
    np.testing.assert_allclose(sl[0, :2], x[0, 1:3])
    np.testing.assert_allclose(sl[0, 2:], 0.0)
    np.testing.assert_allclose(sc[0], [1, 0, 2, 0, 0])
    np.testing.assert_allclose(sc[1], [0, 7, 0, 0, 0])  # 3+4 at idx 1
    assert ex.shape == (2, 3)
    np.testing.assert_allclose(ex, [[1, 2, 3], [1, 2, 3]])


def test_sequence_topk_avg_pooling():
    # B=1, C=2, R=2, W=4
    x = np.array([[[[4.0, 1.0, 3.0, 2.0], [1.0, 1.0, 1.0, 1.0]],
                   [[0.0, 10.0, 5.0, 1.0], [2.0, 4.0, 6.0, 8.0]]]],
                 np.float32)

    def build():
        v = L.data(name="x", shape=[2, 2, 4], dtype="float32")
        return L.sequence_topk_avg_pooling(v, topks=[1, 3], channel_num=2)

    out, = _run(build, {"x": x})
    assert out.shape == (1, 2, 4)  # [B, R, C*K]
    # row 0: ch0 top1=4, top3 avg=(4+3+2)/3=3; ch1 top1=10, top3=(10+5+1)/3
    np.testing.assert_allclose(out[0, 0], [4.0, 3.0, 10.0, 16.0 / 3],
                               rtol=1e-6)
    np.testing.assert_allclose(out[0, 1], [1.0, 1.0, 8.0, 6.0], rtol=1e-6)


def test_match_matrix_tensor():
    B, Tx, Ty, H, C = 2, 3, 4, 5, 2
    rng = np.random.default_rng(1)
    x = rng.standard_normal((B, Tx, H)).astype(np.float32)
    y = rng.standard_normal((B, Ty, H)).astype(np.float32)

    def build():
        xv = L.data(name="x", shape=[Tx, H], dtype="float32")
        yv = L.data(name="y", shape=[Ty, H], dtype="float32")
        out, w = L.match_matrix_tensor(
            xv, yv, channel_num=C, param_attr=pt.ParamAttr(name="mmt_w"))
        return [out]

    scope = pt.Scope()
    out, = _run_with_scope(build, {"x": x, "y": y}, [0], scope)
    w = np.asarray(scope.find_var("mmt_w"))
    expect = np.einsum("bih,hcg,bjg->bcij", x, w, y)
    np.testing.assert_allclose(out, expect, rtol=2e-5, atol=1e-5)


# -- RNN variants ------------------------------------------------------------

def test_lstm_cudnn_shapes_and_determinism():
    B, T, D, H, NL = 2, 5, 4, 3, 2

    def build():
        v = L.data(name="x", shape=[T, D], dtype="float32")
        h0 = L.data(name="h0", shape=[NL, B, H], dtype="float32",
                    append_batch_size=False)
        c0 = L.data(name="c0", shape=[NL, B, H], dtype="float32",
                    append_batch_size=False)
        out, lh, lc = L.lstm(v, h0, c0, max_len=T, hidden_size=H,
                             num_layers=NL)
        return [out, lh, lc]

    rng = np.random.default_rng(2)
    feeds = {"x": rng.standard_normal((B, T, D)).astype(np.float32),
             "h0": np.zeros((NL, B, H), np.float32),
             "c0": np.zeros((NL, B, H), np.float32)}
    out, lh, lc = _run(lambda: build(), feeds, n_fetch=3)
    assert out.shape == (B, T, H)
    assert lh.shape == (NL, B, H) and lc.shape == (NL, B, H)
    np.testing.assert_allclose(out[:, -1, :], lh[-1], rtol=1e-5)
    assert np.abs(out).max() > 0


def test_dynamic_lstmp_projection_path():
    B, T, H, P = 2, 4, 3, 2
    rng = np.random.default_rng(3)
    x = rng.standard_normal((B, T, 4 * H)).astype(np.float32)

    def build():
        v = L.data(name="x", shape=[T, 4 * H], dtype="float32")
        proj, cell = L.dynamic_lstmp(v, size=4 * H, proj_size=P,
                                     use_peepholes=False)
        return [proj, cell]

    proj, cell = _run(lambda: build(), {"x": x}, n_fetch=2)
    assert proj.shape == (B, T, P)
    assert cell.shape == (B, T, H)
    assert np.isfinite(proj).all()


def test_lstm_unit_single_step_matches_formula():
    B, D, H = 2, 3, 4
    rng = np.random.default_rng(4)
    x = rng.standard_normal((B, D)).astype(np.float32)
    hp = rng.standard_normal((B, H)).astype(np.float32)
    cp = rng.standard_normal((B, H)).astype(np.float32)

    def build():
        xv = L.data(name="x", shape=[D], dtype="float32")
        hv = L.data(name="h", shape=[H], dtype="float32")
        cv = L.data(name="c", shape=[H], dtype="float32")
        h, c = L.lstm_unit(xv, hv, cv, forget_bias=1.0)
        return [h, c]

    h, c = _run(lambda: build(), {"x": x, "h": hp, "c": cp}, n_fetch=2)
    assert h.shape == (B, H) and c.shape == (B, H)
    assert np.isfinite(h).all()


def test_row_conv_lookahead():
    B, T, D, K = 1, 4, 2, 1
    x = np.arange(8, dtype=np.float32).reshape(B, T, D)

    def build():
        v = L.data(name="x", shape=[T, D], dtype="float32")
        return L.row_conv(v, future_context_size=K,
                          param_attr=pt.ParamAttr(name="rowconv_w"))

    scope = pt.Scope()
    out, = _run_with_scope(lambda: [build()], {"x": x}, [0], scope)
    w = np.asarray(scope.find_var("rowconv_w"))  # [K+1, D]
    expect = np.zeros_like(x)
    for t in range(T):
        for i in range(K + 1):
            if t + i < T:
                expect[0, t] += x[0, t + i] * w[i]
    np.testing.assert_allclose(out, expect, rtol=1e-5, atol=1e-6)


# -- norms -------------------------------------------------------------------

def test_spectral_norm_unit_sigma():
    rng = np.random.default_rng(5)
    w = rng.standard_normal((4, 6)).astype(np.float32)

    def build():
        wv = L.data(name="w", shape=[4, 6], dtype="float32",
                    append_batch_size=False)
        return L.spectral_norm(wv, dim=0, power_iters=20)

    out, = _run(build, {"w": w})
    # after normalization the top singular value is ~1
    s = np.linalg.svd(out, compute_uv=False)
    np.testing.assert_allclose(s[0], 1.0, rtol=1e-3)


def test_data_norm_uses_accumulated_stats():
    B, C = 4, 3
    rng = np.random.default_rng(6)
    x = rng.standard_normal((B, C)).astype(np.float32)

    def build():
        v = L.data(name="x", shape=[C], dtype="float32")
        return L.data_norm(v, name="dn",
                           param_attr={"batch_size": 100.0,
                                       "batch_sum": 50.0,
                                       "batch_square": 400.0})

    out, = _run(build, {"x": x})
    means = 50.0 / 100.0
    scales = np.sqrt(100.0 / 400.0)
    np.testing.assert_allclose(out, (x - means) * scales, rtol=1e-5)


# -- losses ------------------------------------------------------------------

def test_center_loss_distance_and_update():
    B, D, NC = 3, 2, 4
    rng = np.random.default_rng(7)
    x = rng.standard_normal((B, D)).astype(np.float32)
    lab = np.array([[1], [1], [3]], np.int64)

    def build():
        xv = L.data(name="x", shape=[D], dtype="float32")
        lv = L.data(name="y", shape=[1], dtype="int64")
        loss = L.center_loss(xv, lv, NC, alpha=0.5,
                             param_attr=pt.ParamAttr(name="centers"),
                             update_center=True)
        return [loss]

    scope = pt.Scope()
    # capture initial centers by running startup in the same scope first
    main, startup = pt.Program(), pt.Program()
    with pt.program_guard(main, startup):
        with pt.unique_name.guard():
            loss = build()[0]
    exe = pt.Executor()
    with pt.scope_guard(scope):
        exe.run(startup)
        c0 = np.asarray(scope.find_var("centers")).copy()
        lv, = exe.run(main, feed={"x": x, "y": lab}, fetch_list=[loss])
        c1 = np.asarray(scope.find_var("centers"))
    expect = 0.5 * np.sum((x - c0[lab.reshape(-1)]) ** 2, axis=1,
                          keepdims=True)
    np.testing.assert_allclose(np.asarray(lv), expect, rtol=1e-5)
    # class 1 (2 samples): c -= alpha/(1+2) * sum(c - x); class 0 unchanged
    diff = (c0[1] - x[0]) + (c0[1] - x[1])
    np.testing.assert_allclose(c1[1], c0[1] - 0.5 / 3.0 * diff, rtol=1e-5)
    np.testing.assert_allclose(c1[0], c0[0])


def test_cross_entropy2_matches_log():
    x = np.array([[0.2, 0.5, 0.3], [0.9, 0.05, 0.05]], np.float32)
    lab = np.array([[1], [0]], np.int64)

    def build():
        xv = L.data(name="x", shape=[3], dtype="float32")
        lv = L.data(name="y", shape=[1], dtype="int64")
        return L.cross_entropy2(xv, lv)

    out, = _run(build, {"x": x, "y": lab})
    np.testing.assert_allclose(
        out.reshape(-1), -np.log([0.5, 0.9]), rtol=1e-5)


def test_teacher_student_loss_reference_branches():
    """Oracle derived from reference teacher_student_sigmoid_loss_op.h:44-63
    (label < -1 / [-1,0) / [0,1) / >=1 branches, UNCLIPPED forward) and the
    grad kernel :95-111 (sigmoid of the clipped logit, zero at saturation).
    label encoding: {-2: no-q clk0, -1: no-q clk1, q: clk0+q, 1+q: clk1+q}."""
    z = np.array([[2.0], [-3.0], [0.7], [1.4], [-2.0], [40.0]], np.float32)
    lab = np.array([[-2.0], [-1.0], [0.3], [1.6], [0.0], [1.0]], np.float32)

    def build():
        xv = L.data(name="x", shape=[1], dtype="float32")
        lv = L.data(name="y", shape=[1], dtype="float32")
        return L.teacher_student_sigmoid_loss(xv, lv)

    out, = _run(build, {"x": z, "y": lab})
    x = z.reshape(-1).astype(np.float64)
    l = lab.reshape(-1).astype(np.float64)
    sp = np.maximum(x, 0) + np.log1p(np.exp(-np.abs(x)))
    expect = np.where(l < -1.0, sp,
                      np.where(l < 0.0, sp - x, 2.0 * sp - x * l))
    np.testing.assert_allclose(out.reshape(-1), expect, rtol=1e-5, atol=1e-6)

    # gradient: sigmoid of the CLIPPED logit; zero where x saturates the
    # soft_max bounds (the x=40 row)
    def build_grad():
        xv = L.data(name="x", shape=[1], dtype="float32")
        xv.stop_gradient = False
        lv = L.data(name="y", shape=[1], dtype="float32")
        loss = L.reduce_sum(L.teacher_student_sigmoid_loss(xv, lv))
        from paddle_tpu.backward import gradients
        (g,) = gradients([loss], [xv])
        return loss, g.name

    main, startup = pt.Program(), pt.Program()
    with pt.program_guard(main, startup):
        with pt.unique_name.guard():
            loss, gname = build_grad()
    exe = pt.Executor()
    with pt.scope_guard(pt.Scope()):
        exe.run(startup)
        (gx,) = exe.run(main, feed={"x": z, "y": lab}, fetch_list=[gname])
    pred = 1.0 / (1.0 + np.exp(-np.clip(x, -15, 15)))
    expect_g = np.where(l < -1.0, pred,
                        np.where(l < 0.0, pred - 1.0, 2.0 * pred - l))
    expect_g = np.where((x >= 15) | (x <= -15), 0.0, expect_g)
    np.testing.assert_allclose(np.asarray(gx).reshape(-1), expect_g,
                               rtol=1e-5, atol=1e-6)


def test_sampled_softmax_trains():
    B, V = 4, 1000
    rng = np.random.default_rng(8)

    def build():
        xv = L.data(name="x", shape=[16], dtype="float32")
        lv = L.data(name="y", shape=[1], dtype="int64")
        logits = L.fc(xv, size=V)
        loss = L.mean(L.sampled_softmax_with_cross_entropy(
            logits, lv, num_samples=20))
        pt.optimizer.SGD(0.1).minimize(loss)
        return [loss]

    main, startup = pt.Program(), pt.Program()
    with pt.program_guard(main, startup):
        with pt.unique_name.guard():
            loss = build()[0]
    exe = pt.Executor()
    with pt.scope_guard(pt.Scope()):
        exe.run(startup)
        x = rng.standard_normal((B, 16)).astype(np.float32)
        y = rng.integers(0, V, (B, 1)).astype(np.int64)
        first = None
        for i in range(30):
            lv, = exe.run(main, feed={"x": x, "y": y}, fetch_list=[loss])
            if first is None:
                first = float(np.asarray(lv))
    assert np.isfinite(first)
    assert float(np.asarray(lv)) < first  # loss decreases on fixed batch


def test_npair_loss_builds_and_is_finite():
    B, D = 4, 8
    rng = np.random.default_rng(9)

    def build():
        a = L.data(name="a", shape=[D], dtype="float32")
        p = L.data(name="p", shape=[D], dtype="float32")
        lab = L.data(name="lab", shape=[B], dtype="float32",
                     append_batch_size=False)
        return L.npair_loss(a, p, lab)

    out, = _run(build, {
        "a": rng.standard_normal((B, D)).astype(np.float32),
        "p": rng.standard_normal((B, D)).astype(np.float32),
        "lab": np.array([0.0, 0.0, 1.0, 2.0], np.float32)})
    assert np.isfinite(out).all()


# -- decode / metrics --------------------------------------------------------

def test_ctc_greedy_decoder_merges_and_drops():
    # argmax path: tokens [1,1,0,2,2,0,3] -> decode [1,2,3]
    T, V = 7, 4
    probs = np.zeros((1, T, V), np.float32)
    for t, tok in enumerate([1, 1, 0, 2, 2, 0, 3]):
        probs[0, t, tok] = 1.0

    def build():
        v = L.data(name="p", shape=[T, V], dtype="float32")
        ln = L.data(name="ln", shape=[], dtype="int64")
        out, out_len = L.ctc_greedy_decoder(v, blank=0, input_length=ln)
        return [out, out_len]

    out, out_len = _run(lambda: build(),
                        {"p": probs, "ln": np.array([T], np.int64)},
                        n_fetch=2)
    assert out_len[0] == 3
    np.testing.assert_array_equal(out[0, :3], [1, 2, 3])
    assert (out[0, 3:] == -1).all()


def test_edit_distance_known_cases():
    # kitten -> sitting = 3
    def enc(s, T=8):
        v = np.zeros(T, np.int64)
        v[:len(s)] = [ord(c) for c in s]
        return v, len(s)

    h, hl = enc("kitten")
    r, rl = enc("sitting")

    def build():
        hv = L.data(name="h", shape=[8], dtype="int64")
        rv = L.data(name="r", shape=[8], dtype="int64")
        hlv = L.data(name="hl", shape=[], dtype="int64")
        rlv = L.data(name="rl", shape=[], dtype="int64")
        d, n = L.edit_distance(hv, rv, normalized=False,
                               input_length=hlv, label_length=rlv)
        return [d, n]

    d, n = _run(lambda: build(),
                {"h": h[None], "r": r[None],
                 "hl": np.array([hl], np.int64),
                 "rl": np.array([rl], np.int64)}, n_fetch=2)
    assert float(d[0, 0]) == 3.0
    assert int(n[0]) == 1


def test_chunk_eval_iob():
    # 2 types, IOB: tags B-0=0 I-0=1 B-1=2 I-1=3, O = anything out of range
    inf = np.array([[0, 1, 4, 2, 3, 4]], np.int64)
    lab = np.array([[0, 1, 4, 2, 1, 4]], np.int64)

    def build():
        iv = L.data(name="i", shape=[6], dtype="int64")
        lv = L.data(name="l", shape=[6], dtype="int64")
        return list(L.chunk_eval(iv, lv, "IOB", 2))

    p, r, f1 = _run(lambda: build(), {"i": inf, "l": lab}, n_fetch=3)
    # infer chunks: (0,[0,1]), (1,[3,4]); label: (0,[0,1]), (1,[3,3]),(0,[4,4])
    assert abs(float(p[0]) - 0.5) < 1e-6      # 1 correct of 2 inferred
    assert abs(float(r[0]) - 1.0 / 3.0) < 1e-6


# -- escape hatch ------------------------------------------------------------

def test_py_func_forward_and_backward():
    def fwd(a):
        return a * 3.0

    def bwd(a, out, dout):
        return dout * 3.0

    def build():
        v = L.data(name="x", shape=[4], dtype="float32")
        from paddle_tpu.layer_helper import LayerHelper
        helper = LayerHelper("pyf")
        out = helper.create_variable_for_type_inference("float32")
        out.shape = (-1, 4)
        L.py_func(fwd, v, out, backward_func=bwd)
        loss = L.mean(out)
        pt.optimizer.SGD(1.0).minimize(loss)
        return [out, loss]

    main, startup = pt.Program(), pt.Program()
    with pt.program_guard(main, startup):
        with pt.unique_name.guard():
            out, loss = build()
    exe = pt.Executor()
    x = np.ones((2, 4), np.float32)
    with pt.scope_guard(pt.Scope()):
        exe.run(startup)
        ov, = exe.run(main, feed={"x": x}, fetch_list=[out])
    np.testing.assert_allclose(np.asarray(ov), x * 3.0)


# -- misc tensor -------------------------------------------------------------

def test_unique_and_counts_host_ops():
    x = np.array([3, 1, 3, 2, 1, 3], np.int64)

    def build():
        v = L.data(name="x", shape=[6], dtype="int64",
                   append_batch_size=False)
        u, idx = L.unique(v)
        u2, idx2, cnt = L.unique_with_counts(v)
        return [u, idx, cnt]

    u, idx, cnt = _run(lambda: build(), {"x": x}, n_fetch=3)
    np.testing.assert_array_equal(u, [3, 1, 2])   # first-occurrence order
    np.testing.assert_array_equal(idx, [0, 1, 0, 2, 1, 0])
    np.testing.assert_array_equal(cnt, [3, 2, 1])


def test_hash_buckets_and_shape():
    x = np.array([[1], [2], [1]], np.int64)

    def build():
        v = L.data(name="x", shape=[3, 1], dtype="int64",
                   append_batch_size=False)
        return L.hash(v, hash_size=1000, num_hash=2)

    out, = _run(build, {"x": x})
    assert out.shape == (3, 2, 1)
    assert (out >= 0).all() and (out < 1000).all()
    np.testing.assert_array_equal(out[0], out[2])  # same id -> same buckets
    assert (out[0] != out[1]).any()


def test_cvm_transform_and_strip():
    x = np.array([[3.0, 1.0, 5.0, 6.0]], np.float32)
    cvm_feat = np.array([[1.0, 0.5]], np.float32)

    def build():
        v = L.data(name="x", shape=[4], dtype="float32")
        c = L.data(name="c", shape=[2], dtype="float32")
        return [L.continuous_value_model(v, c, use_cvm=True),
                L.continuous_value_model(v, c, use_cvm=False)]

    keep, strip = _run(lambda: build(),
                       {"x": x, "c": cvm_feat}, n_fetch=2)
    np.testing.assert_allclose(
        keep[0], [np.log(4.0), np.log(2.0) - np.log(4.0), 5.0, 6.0],
        rtol=1e-6)
    np.testing.assert_allclose(strip[0], [5.0, 6.0])


def test_tree_conv_root_only_weights():
    """Single-node 'tree' (no edges): patch = root with eta_t=1, eta_l=
    eta_r=0 -> out = f @ W[:, 2] (the t-component)."""
    B, N, F, O, M = 1, 3, 4, 5, 1
    rng = np.random.default_rng(10)
    feat = rng.standard_normal((B, N, F)).astype(np.float32)
    edges = np.zeros((B, 2, 2), np.int64)  # no valid edges

    def build():
        nv = L.data(name="nv", shape=[N, F], dtype="float32")
        ev = L.data(name="ev", shape=[2, 2], dtype="int64")
        return L.tree_conv(nv, ev, O, M, max_depth=2, act=None,
                           bias_attr=False,
                           param_attr=pt.ParamAttr(name="tree_w"))

    scope = pt.Scope()
    out, = _run_with_scope(lambda: [build()], {"nv": feat, "ev": edges},
                           [0], scope)
    w = np.asarray(scope.find_var("tree_w"))  # [F, 3, O, M]
    # only node 1 exists (the implicit root); its patch is itself
    expect = np.einsum("f,fom->om", feat[0, 0], w[:, 2])
    np.testing.assert_allclose(out[0, 0], expect, rtol=2e-5, atol=1e-5)
    np.testing.assert_allclose(out[0, 1:], 0.0, atol=1e-6)


def test_tree_conv_parent_child():
    """Root 1 with children 2, 3 (max_depth 2): root's patch = {1,2,3}."""
    B, N, F, O = 1, 3, 2, 2
    feat = np.array([[[1.0, 0.0], [0.0, 1.0], [1.0, 1.0]]], np.float32)
    edges = np.array([[[1, 2], [1, 3]]], np.int64)

    def build():
        nv = L.data(name="nv", shape=[N, F], dtype="float32")
        ev = L.data(name="ev", shape=[2, 2], dtype="int64")
        return L.tree_conv(nv, ev, O, 1, max_depth=2, act=None,
                           bias_attr=False,
                           param_attr=pt.ParamAttr(name="tree_w2"))

    scope = pt.Scope()
    out, = _run_with_scope(lambda: [build()],
                           {"nv": feat, "ev": edges}, [0], scope)
    w = np.asarray(scope.find_var("tree_w2"))  # [F, 3, O, 1]
    # every patch node contributes ALL THREE eta components (tree2col.cc):
    # root (node 1): eta_t=1, eta_l=eta_r=0
    # child 2: depth 1, index 1, pclen 2 -> eta_t=.5, eta_l=0, eta_r=.5
    # child 3: depth 1, index 2, pclen 2 -> eta_t=.5, eta_l=.5, eta_r=0
    p_l = 0.5 * feat[0, 2]
    p_r = 0.5 * feat[0, 1]
    p_t = feat[0, 0] + 0.5 * feat[0, 1] + 0.5 * feat[0, 2]
    patch = (np.einsum("f,fom->om", p_l, w[:, 0])
             + np.einsum("f,fom->om", p_r, w[:, 1])
             + np.einsum("f,fom->om", p_t, w[:, 2]))
    np.testing.assert_allclose(out[0, 0], patch, rtol=2e-5, atol=1e-5)


# -- vision additions --------------------------------------------------------

def test_resize_trilinear_and_adaptive_pool3d():
    x = np.arange(16, dtype=np.float32).reshape(1, 1, 2, 2, 4)

    def build():
        v = L.data(name="x", shape=[1, 2, 2, 4], dtype="float32")
        r = L.resize_trilinear(v, out_shape=(2, 2, 2), align_corners=True)
        p = L.adaptive_pool3d(v, [1, 1, 2], "avg")
        return [r, p]

    r, p = _run(lambda: build(), {"x": x}, n_fetch=2)
    # align_corners 4->2 on last axis picks cols 0 and 3
    np.testing.assert_allclose(r[0, 0, :, :, 0], x[0, 0, :, :, 0])
    np.testing.assert_allclose(r[0, 0, :, :, 1], x[0, 0, :, :, 3])
    # avg bins: D 2->1, H 2->1, W 4->2 (pairs)
    expect = x[0, 0].mean(axis=(0, 1)).reshape(2, 2).mean(axis=1)
    np.testing.assert_allclose(p[0, 0].reshape(-1), expect, rtol=1e-5)


def test_im2sequence_windows():
    x = np.arange(16, dtype=np.float32).reshape(1, 1, 4, 4)

    def build():
        v = L.data(name="x", shape=[1, 4, 4], dtype="float32")
        return L.im2sequence(v, filter_size=2, stride=2)

    out, = _run(build, {"x": x})
    assert out.shape == (1, 4, 4)
    np.testing.assert_allclose(out[0, 0], [0, 1, 4, 5])
    np.testing.assert_allclose(out[0, 3], [10, 11, 14, 15])


def test_random_crop_shape_and_content():
    x = np.arange(2 * 1 * 6 * 6, dtype=np.float32).reshape(2, 1, 6, 6)

    def build():
        v = L.data(name="x", shape=[1, 6, 6], dtype="float32")
        return L.random_crop(v, shape=[4, 4])

    out, = _run(build, {"x": x})
    assert out.shape == (2, 1, 4, 4)
    # crops are contiguous windows: row deltas of 1 within a row
    assert np.allclose(np.diff(out[0, 0], axis=1), 1.0)


def test_conv3d_transpose_shape():
    x = np.random.default_rng(11).standard_normal(
        (1, 2, 3, 3, 3)).astype(np.float32)

    def build():
        v = L.data(name="x", shape=[2, 3, 3, 3], dtype="float32")
        return L.conv3d_transpose(v, num_filters=4, filter_size=2, stride=2,
                                  bias_attr=False)

    out, = _run(build, {"x": x})
    assert out.shape == (1, 4, 6, 6, 6)


def test_deformable_conv_zero_offset_equals_conv2d():
    """With zero offsets and unit mask, deformable conv IS a plain conv."""
    B, C, H, W, F, K = 1, 2, 5, 5, 3, 3
    rng = np.random.default_rng(12)
    x = rng.standard_normal((B, C, H, W)).astype(np.float32)
    OH = OW = H - K + 1

    def build():
        v = L.data(name="x", shape=[C, H, W], dtype="float32")
        off = L.data(name="off", shape=[2 * K * K, OH, OW], dtype="float32")
        msk = L.data(name="msk", shape=[K * K, OH, OW], dtype="float32")
        out = L.deformable_conv(v, off, msk, F, K, padding=0,
                                bias_attr=False,
                                param_attr=pt.ParamAttr(name="dcn_w"))
        return [out]

    scope = pt.Scope()
    out, = _run_with_scope(
        lambda: build(),
        {"x": x, "off": np.zeros((B, 2 * K * K, OH, OW), np.float32),
         "msk": np.ones((B, K * K, OH, OW), np.float32)}, [0], scope)
    w = np.asarray(scope.find_var("dcn_w"))
    import jax
    expect = np.asarray(jax.lax.conv_general_dilated(
        x, w, (1, 1), "VALID",
        dimension_numbers=("NCHW", "OIHW", "NCHW")))
    np.testing.assert_allclose(out, expect, rtol=2e-4, atol=2e-4)


def test_affine_grid_identity_theta():
    theta = np.tile(np.array([[[1.0, 0, 0], [0, 1.0, 0]]], np.float32),
                    (2, 1, 1))

    def build():
        t = L.data(name="t", shape=[2, 3], dtype="float32")
        return L.affine_grid(t, out_shape=[2, 1, 3, 4])

    out, = _run(build, {"t": theta})
    assert out.shape == (2, 3, 4, 2)
    np.testing.assert_allclose(out[0, 0, :, 0], np.linspace(-1, 1, 4),
                               rtol=1e-6)
    np.testing.assert_allclose(out[0, :, 0, 1], np.linspace(-1, 1, 3),
                               rtol=1e-6)


def test_gaussian_uniform_batch_size_like():
    def build():
        v = L.data(name="x", shape=[7], dtype="float32")
        g = L.gaussian_random_batch_size_like(v, shape=[-1, 5], std=2.0)
        u = L.uniform_random_batch_size_like(v, shape=[-1, 4])
        return [g, u]

    g, u = _run(lambda: build(),
                {"x": np.zeros((6, 7), np.float32)}, n_fetch=2)
    assert g.shape == (6, 5) and u.shape == (6, 4)
    assert (u >= -1).all() and (u <= 1).all()


def test_autoincreased_step_counter():
    def build():
        return [L.autoincreased_step_counter(begin=1)]

    main, startup = pt.Program(), pt.Program()
    with pt.program_guard(main, startup):
        with pt.unique_name.guard():
            ctr = build()[0]
    exe = pt.Executor()
    with pt.scope_guard(pt.Scope()):
        exe.run(startup)
        vals = [int(np.asarray(exe.run(main, feed={}, fetch_list=[ctr])[0]))
                for _ in range(3)]
    assert vals == [1, 2, 3]


def test_ctc_padding_value_and_ce2_ignore_index():
    T, V = 4, 3
    probs = np.zeros((1, T, V), np.float32)
    for t, tok in enumerate([1, 0, 2, 2]):
        probs[0, t, tok] = 1.0

    def build():
        v = L.data(name="p", shape=[T, V], dtype="float32")
        ln = L.data(name="ln", shape=[], dtype="int64")
        out, _ = L.ctc_greedy_decoder(v, blank=0, input_length=ln,
                                      padding_value=0)
        x = L.data(name="x", shape=[3], dtype="float32")
        y = L.data(name="y", shape=[1], dtype="int64")
        ce = L.cross_entropy2(x, y, ignore_index=-100)
        return [out, ce]

    out, ce = _run(lambda: build(),
                   {"p": probs, "ln": np.array([T], np.int64),
                    "x": np.array([[0.2, 0.5, 0.3], [0.1, 0.1, 0.8]],
                                  np.float32),
                    "y": np.array([[1], [-100]], np.int64)}, n_fetch=2)
    np.testing.assert_array_equal(out[0], [1, 2, 0, 0])  # pad 0, not -1
    np.testing.assert_allclose(ce.reshape(-1), [-np.log(0.5), 0.0],
                               rtol=1e-5)


def test_edit_distance_with_ignored_tokens_no_length():
    h = np.array([[1, 0, 2, 0]], np.int64)
    r = np.array([[1, 2, 0, 0]], np.int64)

    def build():
        hv = L.data(name="h", shape=[4], dtype="int64")
        rv = L.data(name="r", shape=[4], dtype="int64")
        d, n = L.edit_distance(hv, rv, normalized=False,
                               ignored_tokens=[0])
        return [d]

    d, = _run(lambda: build(), {"h": h, "r": r})
    assert float(d[0, 0]) == 0.0  # both erase to [1, 2]
