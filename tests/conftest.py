"""Test config: run on an 8-device virtual CPU mesh so sharding/collective
tests work without TPU hardware (SURVEY.md §4 test strategy — the analogue of
the reference's localhost multi-process TestDistBase)."""
import os

# force-override: the session's sitecustomize registers the axon TPU backend
# and programmatically sets jax_platforms="axon,cpu" (env vars alone don't
# win). The unit suite must run on the virtual 8-device CPU mesh, so pin the
# config before any backend initializes.
os.environ["XLA_FLAGS"] = (
    os.environ.get("XLA_FLAGS", "") + " --xla_force_host_platform_device_count=8"
)
os.environ["JAX_PLATFORMS"] = "cpu"

import jax

jax.config.update("jax_platforms", "cpu")
assert jax.devices()[0].platform == "cpu", jax.devices()

# Persistent XLA compile cache: the serving/fleet tests build many engines
# whose programs lower to identical executables, but the executor's
# in-memory cache is per-Program so every engine recompiles from scratch.
# Content-addressed disk caching dedups those compiles within a run and
# across runs (the engine-heavy files drop ~2-3x in wall time). Keep the
# default write thresholds: forcing min-compile-time/min-entry-size to 0
# makes the cache persist every tiny executable, including ones built on
# the checkpoint writer's async thread, and that segfaults this
# jaxlib/tensorstore combination. Honor a caller-provided dir.
if "JAX_COMPILATION_CACHE_DIR" not in os.environ:
    _cache_dir = os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        ".jax_cache")
    jax.config.update("jax_compilation_cache_dir", _cache_dir)

import numpy as np
import pytest


def pytest_configure(config):
    config.addinivalue_line(
        "markers", "slow: excluded from the tier-1 gate (-m 'not slow')")
    config.addinivalue_line(
        "markers",
        "chaos: fault-injection robustness tests (tools/chaos.py smoke "
        "plan; fast enough to stay in tier-1)")


@pytest.fixture(autouse=True)
def fresh_programs():
    """Each test gets fresh default programs + scope + name generator."""
    import paddle_tpu as pt
    from paddle_tpu import unique_name
    from paddle_tpu.executor import _scope_stack, Scope

    main, startup = pt.Program(), pt.Program()
    old_main = pt.framework.switch_main_program(main)
    old_startup = pt.framework.switch_startup_program(startup)
    old_gen = unique_name.switch()
    _scope_stack.append(Scope())
    yield
    _scope_stack.pop()
    unique_name.switch(old_gen)
    pt.framework.switch_main_program(old_main)
    pt.framework.switch_startup_program(old_startup)
