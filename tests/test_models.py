"""Model-zoo smoke tests: build + train a step, loss decreases for the tiny
configs (reference book-test pattern, SURVEY.md §4)."""
import numpy as np

import paddle_tpu as pt
from paddle_tpu.models import deepfm, mlp, resnet, transformer, word2vec


def _fresh_programs():
    main, startup = pt.Program(), pt.Program()
    return pt.program_guard(main, startup), main, startup


def test_bert_tiny_trains():
    guard, main, startup = _fresh_programs()
    with guard:
        cfg = transformer.bert_tiny(use_tp=False)
        avg_loss, feeds = transformer.bert_pretrain(cfg, seq_len=16)
        opt = pt.optimizer.Adam(learning_rate=1e-3)
        opt.minimize(avg_loss)
    exe = pt.Executor()
    with pt.scope_guard(pt.Scope()):
        exe.run(startup)
        rng = np.random.default_rng(0)
        B, S = 4, 16
        feed = {
            "src_ids": rng.integers(0, cfg.vocab_size, (B, S)).astype(np.int64),
            "pos_ids": np.tile(np.arange(S, dtype=np.int64), (B, 1)),
            "lm_label": rng.integers(0, cfg.vocab_size, (B, S)).astype(np.int64),
            "lm_weight": np.ones((B, S), np.float32),
        }
        losses = []
        for _ in range(8):
            (lv,) = exe.run(main, feed=feed, fetch_list=[avg_loss])
            losses.append(float(lv))
    assert np.isfinite(losses).all()
    assert losses[-1] < losses[0], losses


def test_resnet_cifar_forward_backward():
    guard, main, startup = _fresh_programs()
    with guard:
        loss, acc, logits = resnet.resnet_cifar10(num_classes=10)
        pt.optimizer.Momentum(learning_rate=0.01, momentum=0.9).minimize(loss)
    exe = pt.Executor()
    with pt.scope_guard(pt.Scope()):
        exe.run(startup)
        rng = np.random.default_rng(1)
        feed = {
            "img": rng.standard_normal((8, 3, 32, 32)).astype(np.float32),
            "label": rng.integers(0, 10, (8, 1)).astype(np.int64),
        }
        l0 = exe.run(main, feed=feed, fetch_list=[loss])[0]
        for _ in range(4):
            (l1,) = exe.run(main, feed=feed, fetch_list=[loss])
    assert np.isfinite(l0) and np.isfinite(l1)
    assert float(l1) < float(l0)


def test_resnet_s2d_stem_fold_equivalence():
    """The space-to-depth stem (s2d_stem=True) is an exact refactoring of
    the 7x7-s2 stem: fold_stem_to_s2d maps trained 7x7 weights onto the
    4x4 s2d kernel with identical outputs (models/resnet.py)."""
    from paddle_tpu import layers as L

    rng = np.random.default_rng(3)
    x = rng.standard_normal((2, 3, 32, 32)).astype(np.float32)

    guard, main_a, startup_a = _fresh_programs()
    with guard:
        img = L.data(name="img", shape=[3, 32, 32], dtype="float32")
        out_a = L.conv2d(img, num_filters=8, filter_size=7, stride=2,
                         padding=3, bias_attr=False, name="stem")
    exe = pt.Executor()
    with pt.scope_guard(pt.Scope()):
        exe.run(startup_a)
        w_name = main_a.all_parameters()[0].name
        w7 = np.array(pt.global_scope().find_var(w_name))
        (ref,) = exe.run(main_a, feed={"img": x}, fetch_list=[out_a])

    guard, main_b, startup_b = _fresh_programs()
    with guard:
        img = L.data(name="img", shape=[3, 32, 32], dtype="float32")
        y = L.space_to_depth(img, blocksize=2)
        out_b = L.conv2d(y, num_filters=8, filter_size=4, stride=1,
                         padding=[2, 1, 2, 1], bias_attr=False, name="stem")
    with pt.scope_guard(pt.Scope()):
        exe.run(startup_b)
        w_name = main_b.all_parameters()[0].name
        pt.global_scope().set_var(w_name, resnet.fold_stem_to_s2d(w7))
        (got,) = exe.run(main_b, feed={"img": x}, fetch_list=[out_b])

    assert ref.shape == got.shape, (ref.shape, got.shape)
    np.testing.assert_allclose(got, ref, rtol=1e-4, atol=1e-4)


def test_resnet_nhwc_matches_nchw():
    """data_format="NHWC" is a pure layout change: same params (weights
    stay OIHW), same function. Run one small trunk both ways with shared
    initial weights and compare logits.

    Input is 64x64, not 32x32: at 32x32 the depth-18 trunk's deepest stage
    collapses to 1x1 spatial, so each BN normalizes over exactly N=2
    samples per channel — sigma is |x1-x2|/2 and the normalize amplifies
    the conv's layout-dependent last-bit reduction-order differences by
    |x|/sigma (measured blowup 4e-4 -> 3e-2 through stage 4, the
    pre-existing tier-1 failure). At 64x64 the deepest stage keeps 2x2
    spatial and the two layouts match bitwise on this backend."""
    from paddle_tpu import layers as L

    rng = np.random.default_rng(7)
    x_nchw = rng.standard_normal((2, 3, 64, 64)).astype(np.float32)
    x_nhwc = np.ascontiguousarray(x_nchw.transpose(0, 2, 3, 1))
    exe = pt.Executor()
    outs, params = {}, {}
    for fmt in ("NCHW", "NHWC"):
        guard, main, startup = _fresh_programs()
        with guard:
            shape = [3, 64, 64] if fmt == "NCHW" else [64, 64, 3]
            img = L.data(name="img", shape=shape, dtype="float32")
            logits = resnet.resnet(img, depth=18, num_classes=5,
                                   data_format=fmt)
        with pt.scope_guard(pt.Scope()):
            exe.run(startup)
            if fmt == "NCHW":
                params = [np.array(pt.global_scope().find_var(p.name))
                          for p in main.all_parameters()]
            else:
                # same builder order both times; names differ only by
                # unique_name suffixes, so map positionally. NHWC conv
                # weights are stored HWIO (layers/nn.py conv2d) — transpose
                # the NCHW-run OIHW values to match.
                for p, val in zip(main.all_parameters(), params):
                    want = tuple(pt.global_scope().find_var(p.name).shape)
                    if want != tuple(val.shape):
                        val = val.transpose(2, 3, 1, 0)  # OIHW -> HWIO
                    assert want == tuple(val.shape), p.name
                    pt.global_scope().set_var(p.name, val)
            (outs[fmt],) = exe.run(
                main, feed={"img": x_nchw if fmt == "NCHW" else x_nhwc},
                fetch_list=[logits])
    np.testing.assert_allclose(outs["NHWC"], outs["NCHW"],
                               rtol=2e-4, atol=2e-4)


def test_resnet50_s2d_stem_trains():
    guard, main, startup = _fresh_programs()
    with guard:
        img = pt.layers.data(name="img", shape=[3, 64, 64], dtype="float32")
        label = pt.layers.data(name="label", shape=[1], dtype="int64")
        loss, acc, _ = resnet.resnet50(img, label, num_classes=10,
                                       s2d_stem=True)
        pt.optimizer.Momentum(learning_rate=0.01, momentum=0.9).minimize(loss)
    exe = pt.Executor()
    with pt.scope_guard(pt.Scope()):
        exe.run(startup)
        rng = np.random.default_rng(1)
        feed = {
            "img": rng.standard_normal((4, 3, 64, 64)).astype(np.float32),
            "label": rng.integers(0, 10, (4, 1)).astype(np.int64),
        }
        l0 = exe.run(main, feed=feed, fetch_list=[loss])[0]
        (l1,) = exe.run(main, feed=feed, fetch_list=[loss])
    assert np.isfinite(l0) and np.isfinite(l1)


def test_deepfm_trains_with_sparse_grads():
    guard, main, startup = _fresh_programs()
    with guard:
        avg_loss, predict, feeds = deepfm.deepfm(
            n_fields=6, n_dense=4, vocab_size=500, embed_dim=8,
            hidden_sizes=(32, 32), is_sparse=True)
        pt.optimizer.SGD(learning_rate=0.05).minimize(avg_loss)
    exe = pt.Executor()
    with pt.scope_guard(pt.Scope()):
        exe.run(startup)
        rng = np.random.default_rng(0)
        B = 32
        # learnable signal: label depends on one dense feature
        dense = rng.standard_normal((B, 4)).astype(np.float32)
        feed = {
            "sparse_ids": rng.integers(0, 500, (B, 6)).astype(np.int64),
            "dense_x": dense,
            "label": (dense[:, :1] > 0).astype(np.float32),
        }
        hist = []
        for _ in range(30):
            (lv,) = exe.run(main, feed=feed, fetch_list=[avg_loss])
            hist.append(float(np.asarray(lv).reshape(-1)[0]))
    assert hist[-1] < hist[0] * 0.8, hist[::10]


def test_word2vec_trains():
    guard, main, startup = _fresh_programs()
    with guard:
        avg_loss, predict, feeds = word2vec.word2vec(
            dict_size=100, embed_dim=8, hidden_size=32)
        pt.optimizer.Adam(learning_rate=1e-2).minimize(avg_loss)
    exe = pt.Executor()
    with pt.scope_guard(pt.Scope()):
        exe.run(startup)
        rng = np.random.default_rng(0)
        B = 16
        ctx = rng.integers(0, 100, (B, 4)).astype(np.int64)
        feed = {f"w{i}": ctx[:, i:i+1] for i in range(4)}
        feed["next_word"] = ((ctx.sum(1, keepdims=True)) % 100).astype(np.int64)
        hist = []
        for _ in range(20):
            (lv,) = exe.run(main, feed=feed, fetch_list=[avg_loss])
            hist.append(float(np.asarray(lv).reshape(-1)[0]))
    assert hist[-1] < hist[0]


def test_mnist_conv_builds():
    guard, main, startup = _fresh_programs()
    with guard:
        avg_loss, acc_v, _ = mlp.mnist_conv()
        pt.optimizer.SGD(learning_rate=0.1).minimize(avg_loss)
    exe = pt.Executor()
    with pt.scope_guard(pt.Scope()):
        exe.run(startup)
        rng = np.random.default_rng(2)
        feed = {
            "img": rng.standard_normal((4, 1, 28, 28)).astype(np.float32),
            "label": rng.integers(0, 10, (4, 1)).astype(np.int64),
        }
        (lv,) = exe.run(main, feed=feed, fetch_list=[avg_loss])
    assert np.isfinite(lv)


def test_transformer_wmt_trains():
    """Encoder-decoder WMT transformer (BASELINE config 3): tiny config
    overfits a fixed batch; decoder self-attention is causal, source and
    target share the joint word embedding."""
    import paddle_tpu as pt
    from paddle_tpu.models import transformer

    cfg = transformer.TransformerConfig(
        vocab_size=64, hidden_size=32, num_layers=2, num_heads=4,
        ffn_size=64, max_position=32, dropout=0.0, use_tp=False)
    main, startup = pt.Program(), pt.Program()
    with pt.program_guard(main, startup):
        with pt.unique_name.guard():
            avg_loss, feeds = transformer.transformer_wmt(
                cfg, src_len=8, tgt_len=8)
            pt.optimizer.Adam(1e-3).minimize(avg_loss)
    # one shared word embedding table, separate positional tables
    names = [p.name for p in main.all_parameters()]
    assert names.count("word_emb") == 1
    assert "enc.pos_emb" in names and "dec.pos_emb" in names

    rng = np.random.default_rng(0)
    B = 4
    feed = {
        "src_ids": rng.integers(0, 64, (B, 8)).astype(np.int64),
        "src_pos": np.tile(np.arange(8, dtype=np.int64), (B, 1)),
        "tgt_ids": rng.integers(0, 64, (B, 8)).astype(np.int64),
        "tgt_pos": np.tile(np.arange(8, dtype=np.int64), (B, 1)),
        "tgt_label": rng.integers(0, 64, (B, 8)).astype(np.int64),
        "tgt_weight": np.ones((B, 8), np.float32),
    }
    exe = pt.Executor()
    with pt.scope_guard(pt.Scope()):
        exe.run(startup)
        losses = []
        for _ in range(30):
            (lv,) = exe.run(main, feed=feed, fetch_list=[avg_loss])
            losses.append(float(np.asarray(lv)))
    assert losses[-1] < losses[0] - 0.5, (losses[0], losses[-1])


def test_transformer_wmt_decoder_is_causal():
    """Changing a FUTURE target token must not change the loss at earlier
    positions (per-position loss fetched via tgt_weight one-hot)."""
    import paddle_tpu as pt
    from paddle_tpu.models import transformer

    cfg = transformer.TransformerConfig(
        vocab_size=32, hidden_size=16, num_layers=1, num_heads=2,
        ffn_size=32, max_position=16, dropout=0.0, use_tp=False)
    main, startup = pt.Program(), pt.Program()
    with pt.program_guard(main, startup):
        with pt.unique_name.guard():
            avg_loss, _ = transformer.transformer_wmt(
                cfg, src_len=4, tgt_len=4, label_smooth_eps=0.0)
    rng = np.random.default_rng(1)
    B = 2
    base = {
        "src_ids": rng.integers(0, 32, (B, 4)).astype(np.int64),
        "src_pos": np.tile(np.arange(4, dtype=np.int64), (B, 1)),
        "tgt_ids": rng.integers(0, 32, (B, 4)).astype(np.int64),
        "tgt_pos": np.tile(np.arange(4, dtype=np.int64), (B, 1)),
        "tgt_label": rng.integers(0, 32, (B, 4)).astype(np.int64),
        # weight only position 0: avg_loss == loss at position 0
        "tgt_weight": np.array([[1, 0, 0, 0]] * B, np.float32),
    }
    exe = pt.Executor()
    with pt.scope_guard(pt.Scope()):
        exe.run(startup)
        (l0,) = exe.run(main, feed=base, fetch_list=[avg_loss])
        mod = dict(base)
        tgt2 = base["tgt_ids"].copy()
        tgt2[:, 2:] = (tgt2[:, 2:] + 7) % 32  # change future decoder inputs
        mod["tgt_ids"] = tgt2
        (l1,) = exe.run(main, feed=mod, fetch_list=[avg_loss])
    np.testing.assert_allclose(np.asarray(l0), np.asarray(l1), rtol=1e-6)


def test_transformer_wmt_src_mask_blocks_padding():
    """With use_src_mask, changing MASKED source tokens must not change the
    loss (encoder self-attn and decoder cross-attn both honor the mask)."""
    import paddle_tpu as pt
    from paddle_tpu.models import transformer

    cfg = transformer.TransformerConfig(
        vocab_size=32, hidden_size=16, num_layers=1, num_heads=2,
        ffn_size=32, max_position=16, dropout=0.0, use_tp=False)
    main, startup = pt.Program(), pt.Program()
    with pt.program_guard(main, startup):
        with pt.unique_name.guard():
            avg_loss, feeds = transformer.transformer_wmt(
                cfg, src_len=4, tgt_len=4, label_smooth_eps=0.0,
                use_src_mask=True)
    assert "src_mask" in feeds
    rng = np.random.default_rng(0)
    B = 2
    base = {
        "src_ids": rng.integers(0, 32, (B, 4)).astype(np.int64),
        "src_pos": np.tile(np.arange(4, dtype=np.int64), (B, 1)),
        "tgt_ids": rng.integers(0, 32, (B, 4)).astype(np.int64),
        "tgt_pos": np.tile(np.arange(4, dtype=np.int64), (B, 1)),
        "tgt_label": rng.integers(0, 32, (B, 4)).astype(np.int64),
        "tgt_weight": np.ones((B, 4), np.float32),
        "src_mask": np.array([[1, 1, 0, 0]] * B, np.float32),
    }
    exe = pt.Executor()
    with pt.scope_guard(pt.Scope()):
        exe.run(startup)
        (l0,) = exe.run(main, feed=base, fetch_list=[avg_loss])
        mod = dict(base)
        s2 = base["src_ids"].copy()
        s2[:, 2:] = (s2[:, 2:] + 5) % 32
        mod["src_ids"] = s2
        (l1,) = exe.run(main, feed=mod, fetch_list=[avg_loss])
    np.testing.assert_allclose(np.asarray(l0), np.asarray(l1), rtol=1e-6)


def test_wmt_fused_label_smooth_matches_dense_form():
    """The fused label-smooth CE in transformer_wmt is algebraically
    identical to one_hot -> label_smooth -> soft-label CE."""
    from paddle_tpu import layers as L

    rng = np.random.default_rng(9)
    V, B = 50, 6
    x = rng.standard_normal((B, V)).astype(np.float32) * 2.0
    lab = rng.integers(0, V, (B,)).astype(np.int64)
    eps = 0.1

    guard, main, startup = _fresh_programs()
    with guard:
        lg = pt.layers.data(name="lg", shape=[V], dtype="float32")
        lb = pt.layers.data(name="lb", shape=[], dtype="int64")
        # dense reference form
        onehot = L.one_hot(lb, V)
        soft = L.label_smooth(onehot, epsilon=eps)
        dense = L.softmax_with_cross_entropy(lg, soft, soft_label=True)
        # fused form (the transformer_wmt rewrite)
        hard = L.softmax_with_cross_entropy(lg, L.unsqueeze(lb, axes=[1]))
        m = L.reduce_max(lg, dim=[-1], keep_dim=True)
        se = L.reduce_sum(L.exp(L.elementwise_sub(lg, m)), dim=[-1],
                          keep_dim=True)
        lse = L.elementwise_add(m, L.log(se))
        mean_x = L.scale(L.reduce_sum(lg, dim=[-1], keep_dim=True),
                         scale=1.0 / V)
        fused = L.elementwise_add(
            L.scale(hard, scale=1.0 - eps),
            L.scale(L.elementwise_sub(lse, mean_x), scale=eps))
    exe = pt.Executor()
    with pt.scope_guard(pt.Scope()):
        exe.run(startup)
        d, f = exe.run(main, feed={"lg": x, "lb": lab},
                       fetch_list=[dense, fused])
    np.testing.assert_allclose(np.asarray(f), np.asarray(d),
                               rtol=1e-5, atol=1e-6)
