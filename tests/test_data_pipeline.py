"""Data pipeline tests: reader decorators, DataFeeder padding, PyReader
prefetch, dataset loaders, end-to-end training from a PyReader (reference
unittests/test_pyreader*, reader decorator tests)."""
import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import layers as L
from paddle_tpu import reader as R
from paddle_tpu.dataset import imdb, mnist, uci_housing


def test_batch_and_firstn():
    r = R.batch(lambda: iter(range(10)), 3)
    batches = list(r())
    assert batches == [[0, 1, 2], [3, 4, 5], [6, 7, 8], [9]]
    r2 = R.batch(lambda: iter(range(10)), 3, drop_last=True)
    assert list(r2())[-1] == [6, 7, 8]
    assert list(R.firstn(lambda: iter(range(10)), 4)()) == [0, 1, 2, 3]


def test_shuffle_preserves_multiset():
    r = R.shuffle(lambda: iter(range(100)), buf_size=16)
    assert sorted(r()) == list(range(100))


def test_chain_compose_map():
    a = lambda: iter([1, 2])
    b = lambda: iter([3, 4])
    assert list(R.chain(a, b)()) == [1, 2, 3, 4]
    assert list(R.compose(a, b)()) == [(1, 3), (2, 4)]
    assert list(R.map_readers(lambda x, y: x + y, a, b)()) == [4, 6]


def test_compose_misaligned_raises():
    a = lambda: iter([1, 2, 3])
    b = lambda: iter([4])
    with pytest.raises(RuntimeError):
        list(R.compose(a, b)())


def test_buffered_and_xmap():
    r = R.buffered(lambda: iter(range(50)), size=8)
    assert list(r()) == list(range(50))
    xm = R.xmap_readers(lambda x: x * 2, lambda: iter(range(20)),
                        process_num=4, buffer_size=8, order=True)
    assert list(xm()) == [2 * i for i in range(20)]
    xm2 = R.xmap_readers(lambda x: x * 2, lambda: iter(range(20)),
                         process_num=4, buffer_size=8, order=False)
    assert sorted(xm2()) == [2 * i for i in range(20)]


def test_data_feeder_pads_ragged():
    x = L.data(name="ids", shape=[-1], dtype="int64")
    y = L.data(name="lab", shape=[1], dtype="int64")
    feeder = pt.DataFeeder([x, y], emit_lengths=True)
    feed = feeder.feed([([1, 2, 3], 0), ([4], 1)])
    np.testing.assert_array_equal(feed["ids"], [[1, 2, 3], [4, 0, 0]])
    np.testing.assert_array_equal(feed["ids_len"], [3, 1])
    assert feed["lab"].shape == (2, 1)


def test_dataset_loaders_shapes():
    img, lab = next(mnist.train()())
    assert img.shape == (784,) and img.dtype == np.float32
    assert isinstance(lab, int)
    feats, price = next(uci_housing.train()())
    assert feats.shape == (13,) and price.shape == (1,)
    ids, sentiment = next(imdb.train()())
    assert isinstance(ids, list) and sentiment in (0, 1)


def test_pyreader_end_to_end_training():
    img = L.data(name="img", shape=[784], dtype="float32")
    label = L.data(name="label", shape=[1], dtype="int64")
    loss = L.mean(L.softmax_with_cross_entropy(L.fc(img, size=10), label))
    pt.optimizer.SGD(0.1).minimize(loss)

    loader = pt.PyReader(feed_list=[img, label], capacity=4)
    loader.decorate_sample_list_generator(
        R.batch(mnist.train(), batch_size=64, drop_last=True))

    exe = pt.Executor()
    exe.run(pt.default_startup_program())
    losses = []
    for i, feed in enumerate(loader()):
        (lv,) = exe.run(pt.default_main_program(), feed=feed, fetch_list=[loss])
        losses.append(float(lv))
        if i >= 20:
            break
    assert losses[-1] < losses[0], (losses[0], losses[-1])


def test_pyreader_propagates_worker_errors():
    img = L.data(name="im2", shape=[4], dtype="float32")
    loader = pt.PyReader(feed_list=[img], capacity=2)

    def bad_reader():
        yield [(np.zeros(4, np.float32),)]
        raise ValueError("boom")

    loader.decorate_sample_list_generator(lambda: bad_reader())
    with pytest.raises(ValueError, match="boom"):
        for _ in loader():
            pass


def test_xmap_mapper_error_propagates_no_deadlock():
    xm = R.xmap_readers(lambda x: 1 // x, lambda: iter([1, 0, 2]),
                        process_num=2, buffer_size=4)
    with pytest.raises(ZeroDivisionError):
        list(xm())


def test_buffered_propagates_errors():
    def bad():
        yield 1
        raise ValueError("boom")

    with pytest.raises(ValueError, match="boom"):
        list(R.buffered(lambda: bad(), 4)())


def test_cache_partial_first_pass_not_poisoned():
    c = R.cache(lambda: iter(range(5)))
    it = c()
    next(it)  # peek one sample, abandon
    del it
    assert list(c()) == [0, 1, 2, 3, 4]
    assert list(c()) == [0, 1, 2, 3, 4]


def test_wmt16_tuple_order():
    from paddle_tpu.dataset import wmt16
    src, trg_in, trg_next = next(wmt16.train()())
    assert trg_in[0] == wmt16.BOS
    assert trg_next[-1] == wmt16.EOS
    assert trg_in[1:] == trg_next[:-1]


def test_xmap_abandoned_iteration_stops_workers():
    import threading
    import time
    base = threading.active_count()
    xm = R.xmap_readers(lambda x: x, lambda: iter(range(1000)),
                        process_num=3, buffer_size=2)
    it = xm()
    next(it)
    it.close()  # abandon
    deadline = time.monotonic() + 5.0
    while time.monotonic() < deadline:
        if threading.active_count() <= base:
            break
        time.sleep(0.05)
    assert threading.active_count() <= base, "worker threads did not wind down"


def test_imdb_honors_custom_word_idx():
    from paddle_tpu.dataset import imdb
    wd = {f"w{i}": i for i in range(100)}
    ids, label = next(imdb.train(word_idx=wd)())
    assert max(ids) < 100


def test_new_datasets_yield_contract_tuples():
    """movielens/wmt14/conll05/sentiment/flowers/imikolov sample shapes."""
    from paddle_tpu.dataset import (conll05, flowers, imikolov, movielens,
                                    sentiment, wmt14)

    s = next(iter(movielens.train()()))
    assert len(s) == 8 and isinstance(s[5], list) and s[7][0] >= 1.0
    assert movielens.max_user_id() > 0 and len(movielens.age_table) == 7

    src, tin, tnext = next(iter(wmt14.train(1000)()))
    assert tin[0] == wmt14.BOS and tnext[-1] == wmt14.EOS
    assert tin[1:] == tnext[:-1]

    sample = next(iter(conll05.test()()))
    assert len(sample) == 9
    n = len(sample[0])
    assert all(len(f) == n for f in sample[1:])
    assert sum(sample[7]) == 1  # exactly one predicate mark

    ids, label = next(iter(sentiment.train()()))
    assert label in (0, 1) and len(ids) > 0
    assert len(sentiment.get_word_dict()) > 0

    img, lbl = next(iter(flowers.train()()))
    assert img.shape == (3 * 224 * 224,) and 0 <= lbl < 102

    wd = imikolov.build_dict()
    grams = list(imikolov.train(wd, 5)())[:3]
    assert all(len(g) == 5 for g in grams)
    # SEQ mode drops sentences longer than n (reference max-len filter),
    # so use an n above the synthetic max sentence length
    src, trg = next(iter(imikolov.train(wd, 40, imikolov.DataType.SEQ)()))
    assert trg[:-1] == src[1:]


def test_image_preprocessing_utils():
    """paddle.dataset.image parity (reference image.py:197-327): numpy-native
    resize_short/center_crop/random_crop/to_chw/flip/simple_transform."""
    from paddle_tpu.dataset import image as img

    rng = np.random.default_rng(0)
    im = rng.integers(0, 255, (120, 80, 3), dtype=np.uint8)
    r = img.resize_short(im, 64)
    assert min(r.shape[:2]) == 64 and r.shape[0] == 96  # aspect preserved
    assert r.dtype == np.uint8
    # constant image stays constant under bilinear resampling
    const = np.full((50, 100, 3), 77, np.uint8)
    rc = img.resize_short(const, 30)
    assert rc.shape[:2] == (30, 60) and (rc == 77).all()

    c = img.center_crop(r, 48)
    assert c.shape == (48, 48, 3)
    np.testing.assert_array_equal(
        c, r[(96 - 48) // 2:(96 + 48) // 2, (64 - 48) // 2:(64 + 48) // 2])
    rcu = img.random_crop(r, 48)
    assert rcu.shape == (48, 48, 3)
    chw = img.to_chw(c)
    assert chw.shape == (3, 48, 48)
    flipped = img.left_right_flip(c)
    np.testing.assert_array_equal(flipped[:, 0], c[:, -1])

    out = img.simple_transform(im, 64, 48, is_train=True,
                               mean=[1.0, 2.0, 3.0])
    assert out.shape == (3, 48, 48) and out.dtype == np.float32
    out2 = img.simple_transform(im, 64, 48, is_train=False)
    np.testing.assert_allclose(out2, img.to_chw(c).astype(np.float32))


def test_mq2007_readers():
    from paddle_tpu.dataset import mq2007

    f, s = next(iter(mq2007.train("pointwise")()))
    assert f.shape == (46,) and f.dtype == np.float32 and s.shape == (1,)
    hi, lo = next(iter(mq2007.train("pairwise")()))
    assert hi.shape == lo.shape == (46,)
    labels, feats = next(iter(mq2007.test("listwise")()))
    assert len(labels) == len(feats) and feats[0].shape == (46,)
    # LETOR line parsing round-trips
    q = mq2007.Query.parse("2 qid:10 1:0.5 2:0.25 #docid = GX001")
    assert (q.relevance_score, q.query_id) == (2, 10)
    assert q.feature_vector == [0.5, 0.25]


def test_voc2012_reader():
    from paddle_tpu.dataset import voc2012

    img, label = next(iter(voc2012.train()()))
    assert img.ndim == 3 and img.shape[2] == 3 and img.dtype == np.uint8
    assert label.shape == img.shape[:2] and label.max() >= 1
    assert len(list(voc2012.val()())) > 0
