"""Program/Block/Operator IR unit tests (reference test pattern:
python/paddle/fluid/tests/unittests/test_program.py, test_operator_desc.py)."""
import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import layers as L


def test_program_construction_and_shapes():
    x = L.data(name="x", shape=[13], dtype="float32")
    y = L.fc(x, size=7)
    assert y.shape == (-1, 7)
    prog = pt.default_main_program()
    assert [op.type for op in prog.global_block.ops] == ["mul", "elementwise_add"]
    params = prog.all_parameters()
    assert len(params) == 2
    assert sorted(tuple(p.shape) for p in params) == [(7,), (13, 7)]


def test_program_serialization_roundtrip():
    x = L.data(name="x", shape=[4], dtype="float32")
    h = L.fc(x, size=3, act="relu")
    loss = L.mean(h)
    prog = pt.default_main_program()
    d = prog.to_dict()
    prog2 = pt.Program.from_dict(d)
    assert [op.type for op in prog2.global_block.ops] == [
        op.type for op in prog.global_block.ops
    ]
    assert prog2.global_block.var("x").shape == (-1, 4)


def test_clone_independent():
    x = L.data(name="x", shape=[4], dtype="float32")
    h = L.fc(x, size=3)
    prog = pt.default_main_program()
    n_ops = len(prog.global_block.ops)
    clone = prog.clone()
    with pt.program_guard(clone):
        L.relu(h)  # appends to clone only... via default program guard
    assert len(prog.global_block.ops) == n_ops


def test_append_backward_creates_grads():
    x = L.data(name="x", shape=[5], dtype="float32")
    h = L.fc(x, size=3, act="relu")
    loss = L.mean(h)
    pgs = pt.append_backward(loss)
    assert len(pgs) == 2
    block = pt.default_main_program().global_block
    types = [op.type for op in block.ops]
    assert "mul_grad" in types and "relu_grad" in types and "mean_grad" in types
    for p, g in pgs:
        assert g.shape == p.shape


def test_shared_weight_grad_accumulates():
    """Fan-out: one param used twice -> grads summed (reference
    _addup_repetitive_outputs_ backward.py:135)."""
    x = L.data(name="x", shape=[4], dtype="float32")
    w_attr = pt.ParamAttr(name="shared_w")
    h1 = L.fc(x, size=4, param_attr=w_attr, bias_attr=False)
    h2 = L.fc(x, size=4, param_attr=w_attr, bias_attr=False)
    loss = L.mean(h1 + h2)
    pgs = pt.append_backward(loss)
    assert len(pgs) == 1
    types = [op.type for op in pt.default_main_program().global_block.ops]
    assert "sum" in types

    exe = pt.Executor()
    exe.run(pt.default_startup_program())
    xv = np.ones((2, 4), np.float32)
    (g,) = exe.run(
        pt.default_main_program(), feed={"x": xv}, fetch_list=["shared_w@GRAD"]
    )
    # d loss / d w for h1 and h2 paths are identical -> grad is twice one path
    one_path = np.full((4, 4), 1.0 / (2 * 4) * 2, np.float32)  # x=1, mean over 8 elems, 2 rows
    np.testing.assert_allclose(g, 2 * one_path, rtol=1e-5)


def test_stop_gradient_blocks_backward():
    x = L.data(name="x", shape=[4], dtype="float32")
    h1 = L.fc(x, size=4, bias_attr=False)
    h1.stop_gradient = True
    h2 = L.fc(h1, size=2, bias_attr=False)
    loss = L.mean(h2)
    pgs = pt.append_backward(loss)
    names = [p.name for p, _ in pgs]
    # first fc's weight gets no grad because h1 blocks the path
    assert len(pgs) == 1


def test_gradients_multi_target_with_seed_cotangents():
    """gradients() over two targets with explicit target_gradients must match
    the analytic d(w1*t1 + w2*t2)/dx (reference calc_gradient backward.py:820)."""
    x = L.data(name="x", shape=[4], dtype="float32")
    t1 = L.scale(x, 2.0)   # dt1/dx = 2
    t2 = L.scale(x, -3.0)  # dt2/dx = -3
    w1 = L.fill_constant([2, 4], "float32", 0.5)
    w2 = L.fill_constant([2, 4], "float32", 1.0)
    (gx,) = pt.gradients([t1, t2], [x], target_gradients=[w1, w2])
    assert gx is not None
    exe = pt.Executor()
    xv = np.ones((2, 4), np.float32)
    (g,) = exe.run(pt.default_main_program(), feed={"x": xv}, fetch_list=[gx])
    # dx = 2*0.5 + (-3)*1.0 = -2
    np.testing.assert_allclose(g, np.full((2, 4), -2.0, np.float32), rtol=1e-6)


def test_gradients_default_seed_is_ones():
    x = L.data(name="x", shape=[3], dtype="float32")
    t = L.scale(x, 4.0)
    (gx,) = pt.gradients(t, [x])
    exe = pt.Executor()
    (g,) = exe.run(pt.default_main_program(),
                   feed={"x": np.ones((2, 3), np.float32)}, fetch_list=[gx])
    np.testing.assert_allclose(g, np.full((2, 3), 4.0, np.float32), rtol=1e-6)


def test_gradients_target_gradient_shape_mismatch_raises():
    x = L.data(name="x", shape=[3], dtype="float32")
    t = L.scale(x, 4.0)
    bad = L.fill_constant([5], "float32", 1.0)
    with pytest.raises(ValueError, match="shape"):
        pt.gradients(t, [x], target_gradients=[bad])


def test_executor_compile_cache_batch_polymorphism():
    x = L.data(name="x", shape=[4], dtype="float32")
    y = L.fc(x, size=3)
    exe = pt.Executor()
    exe.run(pt.default_startup_program())
    out8 = exe.run(pt.default_main_program(), feed={"x": np.zeros((8, 4), np.float32)}, fetch_list=[y])
    out16 = exe.run(pt.default_main_program(), feed={"x": np.zeros((16, 4), np.float32)}, fetch_list=[y])
    assert out8[0].shape == (8, 3) and out16[0].shape == (16, 3)


def test_square_via_self_mul_grad():
    """Regression: elementwise_mul(x, x) must produce d/dx = 2x (grads from
    both input slots of one grad op summed, not overwritten)."""
    import paddle_tpu.layers.nn as nn

    x = L.data(name="x", shape=[3], dtype="float32")
    x.stop_gradient = False
    y = nn._elementwise_binary("elementwise_mul", x, x)
    loss = L.reduce_sum(y)
    pt.append_backward(loss, parameter_list=[], no_grad_set=set())
    exe = pt.Executor()
    exe.run(pt.default_startup_program())
    xv = np.array([[1.0, 2.0, 3.0]], np.float32)
    (g,) = exe.run(pt.default_main_program(), feed={"x": xv}, fetch_list=["x@GRAD"])
    np.testing.assert_allclose(g, 2 * xv, rtol=1e-6)


def test_scalar_left_operators():
    x = L.data(name="x", shape=[2], dtype="float32")
    a = 1.0 - x
    b = 2.0 / x
    c = -x
    exe = pt.Executor()
    exe.run(pt.default_startup_program())
    xv = np.array([[1.0, 4.0]], np.float32)
    av, bv, cv = exe.run(pt.default_main_program(), feed={"x": xv}, fetch_list=[a, b, c])
    np.testing.assert_allclose(av, 1.0 - xv)
    np.testing.assert_allclose(bv, 2.0 / xv)
    np.testing.assert_allclose(cv, -xv)
