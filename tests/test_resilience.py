"""Fault-tolerant runtime: fault injection, retry, versioned checkpoints,
and the CheckpointedRunner recovery ladder (resilience/).

The core contract under test: with a seeded fault plan firing at the named
runtime sites, training COMPLETES with bounded retries and the loss
trajectory is bit-identical to an undisturbed run — recovery must be
invisible in the numbers, not just in the exit code."""
import os
import threading

import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import layers as L
from paddle_tpu.resilience import (
    CheckpointManager,
    CheckpointedRunner,
    FaultPlan,
    InjectedFault,
    RetryPolicy,
    fault_point,
    fault_scope,
)


# -- fault plans --------------------------------------------------------------


def test_fault_plan_parse_schedule_and_rand():
    p = FaultPlan.parse("ckpt.write:2;ps.send:1,4")
    assert p.schedule == {"ckpt.write": frozenset({2}),
                          "ps.send": frozenset({1, 4})}
    r = FaultPlan.parse("rand:p=0.5,seed=3,sites=ps.send|ps.recv,max=2")
    assert r.p == 0.5 and r.seed == 3 and r.max_faults == 2
    assert r.sites == frozenset({"ps.send", "ps.recv"})
    with pytest.raises(ValueError, match="unknown fault site"):
        FaultPlan.parse("not.a.site:1")
    with pytest.raises(ValueError, match="unknown fault site"):
        FaultPlan.parse("rand:p=0.5,sites=bogus")
    with pytest.raises(ValueError, match="unknown fault-plan key"):
        FaultPlan.parse("rand:q=1")


def test_fault_plan_rand_is_deterministic():
    a = FaultPlan.parse("rand:p=0.4,seed=11")
    b = FaultPlan.parse("rand:p=0.4,seed=11")
    assert [a._draw("ps.send", i) for i in range(64)] == [
        b._draw("ps.send", i) for i in range(64)]
    # different sites draw independent streams
    assert [a._draw("ps.send", i) for i in range(64)] != [
        a._draw("ps.recv", i) for i in range(64)]


def test_fault_scope_fires_on_schedule_and_restores():
    with fault_scope("ckpt.write:2") as plan:
        fault_point("ckpt.write")  # hit 1: passes
        with pytest.raises(InjectedFault) as ei:
            fault_point("ckpt.write")  # hit 2: fires
        assert ei.value.site == "ckpt.write" and ei.value.hit == 2
        assert isinstance(ei.value, ConnectionError)  # travels transport paths
        fault_point("ckpt.write")  # hit 3: passes again
        assert plan.stats()["fired"] == [("ckpt.write", 2)]
    # scope exited: the site is quiet again
    fault_point("ckpt.write")


def test_fault_point_rejects_unknown_site():
    with fault_scope("rand:p=1.0"):
        with pytest.raises(ValueError, match="unknown fault site"):
            fault_point("typo.site")


def test_fault_plan_rand_max_faults_bounds_total():
    with fault_scope("rand:p=1.0,max=3") as plan:
        fired = 0
        for _ in range(10):
            try:
                fault_point("ps.send")
            except InjectedFault:
                fired += 1
        assert fired == 3
        assert len(plan.stats()["fired"]) == 3


# -- retry policy -------------------------------------------------------------


def test_retry_succeeds_after_transient_failures():
    calls = []

    def flaky():
        calls.append(1)
        if len(calls) < 3:
            raise ConnectionError("transient")
        return "ok"

    pol = RetryPolicy(max_attempts=4, base_delay=0.001, max_delay=0.002)
    assert pol.call(flaky) == "ok"
    assert len(calls) == 3


def test_retry_does_not_mask_application_errors():
    pol = RetryPolicy(max_attempts=5, base_delay=0.001)
    calls = []

    def broken():
        calls.append(1)
        raise RuntimeError("pserver: no such var")  # server 'err' reply

    with pytest.raises(RuntimeError):
        pol.call(broken)
    assert len(calls) == 1  # not transient: no retry


def test_retry_exhausts_attempts_and_reraises():
    pol = RetryPolicy(max_attempts=3, base_delay=0.001)
    calls = []

    def always():
        calls.append(1)
        raise EOFError("dead")

    with pytest.raises(EOFError):
        pol.call(always)
    assert len(calls) == 3


def test_retry_deadline_cuts_backoff_short():
    slept = []
    pol = RetryPolicy(max_attempts=50, base_delay=10.0, max_delay=10.0,
                      deadline=0.5, sleep=slept.append)
    with pytest.raises(ConnectionError):
        pol.call(lambda: (_ for _ in ()).throw(ConnectionError("x")))
    assert slept == []  # first 10s backoff already exceeds the 0.5s budget


def test_retry_on_retry_hook_and_deterministic_jitter():
    seen = []
    pol = RetryPolicy(max_attempts=3, base_delay=0.001, jitter=0.5, seed=9,
                      sleep=lambda d: None)
    with pytest.raises(ConnectionError):
        pol.call(lambda: (_ for _ in ()).throw(ConnectionError("x")),
                 on_retry=lambda attempt, exc: seen.append(attempt))
    assert seen == [1, 2]
    assert pol.delay(1) == RetryPolicy(base_delay=0.001, jitter=0.5,
                                       seed=9).delay(1)


def test_injected_fault_is_retryable():
    with fault_scope("ps.send:1"):
        pol = RetryPolicy(max_attempts=2, base_delay=0.001)
        pol.call(fault_point, "ps.send")  # hit 1 fires, hit 2 passes


# -- checkpoint manager -------------------------------------------------------


def _train_setup(steps=0, size=4):
    x = L.data(name="x", shape=[8], dtype="float32")
    y = L.data(name="y", shape=[1], dtype="float32")
    loss = L.mean(L.square_error_cost(L.fc(x, size=size), y))
    pt.optimizer.SGD(learning_rate=0.1).minimize(loss)
    exe = pt.Executor()
    exe.run(pt.default_startup_program())
    rng = np.random.default_rng(0)
    X = rng.standard_normal((8, 8)).astype(np.float32)
    W = rng.standard_normal((8, 1)).astype(np.float32)
    feed = {"x": X, "y": (X @ W).astype(np.float32)}
    for _ in range(steps):
        exe.run(pt.default_main_program(), feed=feed, fetch_list=[loss])
    return exe, loss, feed


def test_checkpoint_manager_roundtrip_and_latest(tmp_path):
    exe, loss, feed = _train_setup(steps=2)
    mgr = CheckpointManager(str(tmp_path / "ck"))
    assert mgr.latest_step() is None
    mgr.save(5, executor=exe)
    mgr.save(9, executor=exe)
    assert mgr.steps() == [5, 9] and mgr.latest_step() == 9

    scope = pt.global_scope()
    before = {n: np.asarray(scope.find_var(n)).copy()
              for n in scope.var_names()}
    for n in scope.var_names():
        scope.set_var(n, np.zeros_like(before[n]))
    assert mgr.restore(executor=exe) == 9
    for n, v in before.items():
        np.testing.assert_array_equal(np.asarray(scope.find_var(n)), v)


def test_checkpoint_manager_keep_last_k_gc(tmp_path):
    exe, loss, feed = _train_setup(steps=1)
    mgr = CheckpointManager(str(tmp_path / "ck"), keep_last_k=2)
    for s in range(5):
        mgr.save(s, executor=exe)
    assert mgr.steps() == [3, 4]


def test_checkpoint_manifest_records_provenance(tmp_path):
    exe, loss, feed = _train_setup(steps=3)
    mgr = CheckpointManager(str(tmp_path / "ck"))
    mgr.save(3, executor=exe)
    m = mgr.read_manifest(3)
    assert m["step"] == 3
    assert m["rng_counter"] == pt.global_scope()._run_counter
    assert m["var_names"]  # persistables present
    # restore puts the RNG run-counter back so counter-derived randomness
    # continues where the save left off
    pt.global_scope()._run_counter = 999
    mgr.restore(executor=exe)
    assert pt.global_scope()._run_counter == m["rng_counter"]


def test_checkpoint_failed_save_leaves_no_half_checkpoint(tmp_path):
    exe, loss, feed = _train_setup(steps=1)
    root = str(tmp_path / "ck")
    mgr = CheckpointManager(root)
    mgr.save(1, executor=exe)
    # fire on every attempt the io retry makes, so the save truly fails
    with fault_scope("ckpt.write:" + ",".join(map(str, range(1, 20)))):
        with pytest.raises(ConnectionError):
            mgr.save(2, executor=exe)
    # target name never appeared; prior checkpoint intact; no tmp orphans
    assert mgr.steps() == [1]
    assert [n for n in os.listdir(root) if n.startswith(".tmp")] == []
    assert mgr.restore(executor=exe) == 1


def test_checkpoint_corrupt_rolls_back_to_last_good(tmp_path):
    exe, loss, feed = _train_setup(steps=2)
    root = str(tmp_path / "ck")
    mgr = CheckpointManager(root)
    scope = pt.global_scope()
    mgr.save(1, executor=exe)
    good = {n: np.asarray(scope.find_var(n)).copy()
            for n in scope.var_names()}
    exe.run(pt.default_main_program(), feed=feed, fetch_list=[loss])
    mgr.save(2, executor=exe)
    # corrupt the newest manifest
    with open(os.path.join(root, "step_00000002", "manifest.json"), "w") as f:
        f.write("{ not json")
    with pytest.warns(UserWarning, match="quarantined"):
        assert mgr.restore(executor=exe) == 1
    for n, v in good.items():
        np.testing.assert_array_equal(np.asarray(scope.find_var(n)), v)
    # the corrupt candidate is out of the rotation now
    assert mgr.steps() == [1]


def test_checkpoint_explicit_step_does_not_substitute(tmp_path):
    exe, loss, feed = _train_setup(steps=1)
    mgr = CheckpointManager(str(tmp_path / "ck"))
    mgr.save(4, executor=exe)
    with pytest.raises(FileNotFoundError):
        mgr.restore(step=7, executor=exe)


def test_checkpoint_program_hash_mismatch_warns(tmp_path):
    exe, loss, feed = _train_setup(steps=1)
    mgr = CheckpointManager(str(tmp_path / "ck"))
    mgr.save(0, executor=exe)
    # a different program resuming from this checkpoint warns loudly
    main2 = pt.Program()
    with pt.program_guard(main2, pt.Program()):
        with pt.unique_name.guard():
            x = L.data(name="x", shape=[8], dtype="float32")
            L.fc(x, size=4)
    with pytest.warns(UserWarning, match="different program"):
        mgr.restore(executor=exe, main_program=main2)


# -- runner: the acceptance contract ------------------------------------------


def _runner_feed():
    rng = np.random.default_rng(0)
    X = rng.standard_normal((8, 8)).astype(np.float32)
    W = rng.standard_normal((8, 1)).astype(np.float32)
    Y = (X @ W).astype(np.float32)
    return lambda step: {"x": X, "y": Y}


def _fresh_model():
    main, startup = pt.Program(), pt.Program()
    with pt.program_guard(main, startup):
        with pt.unique_name.guard():
            x = L.data(name="x", shape=[8], dtype="float32")
            y = L.data(name="y", shape=[1], dtype="float32")
            loss = L.mean(L.square_error_cost(L.fc(x, size=4), y))
            pt.optimizer.SGD(learning_rate=0.1).minimize(loss)
    return main, startup, loss


def _losses(result):
    return [float(np.asarray(v[0]).reshape(-1)[0])
            for _, v in sorted(result["results"].items())]


def test_runner_faulted_trajectory_bit_identical_to_baseline(tmp_path):
    """Faults at executor.compile, collective.step and ckpt.write; the run
    completes with bounded retries and the loss trajectory matches an
    undisturbed run EXACTLY (restore-and-replay + step-keyed RNG)."""
    feed_fn = _runner_feed()
    main, startup, loss = _fresh_model()
    exe = pt.Executor()
    exe.run(startup, scope=pt.global_scope())
    mgr = CheckpointManager(str(tmp_path / "faulted"), keep_last_k=3)
    runner = CheckpointedRunner(exe, mgr, main_program=main, save_every=2,
                                max_retries=5)
    plan_spec = "executor.compile:1;collective.step:4;ckpt.write:2"
    with fault_scope(plan_spec) as plan:
        out = runner.run(feed_fn, 6, fetch_list=[loss])
    fired_sites = {s for s, _ in plan.stats()["fired"]}
    assert fired_sites == {"executor.compile", "collective.step",
                           "ckpt.write"}, plan.stats()
    assert 0 < out["retries"] <= runner.max_retries * 6
    assert mgr.latest_step() == 5

    # baseline: same model in a fresh scope, no faults
    main2, startup2, loss2 = _fresh_model()
    with pt.scope_guard(pt.Scope()):
        exe2 = pt.Executor()
        exe2.run(startup2, scope=pt.global_scope())
        base = CheckpointedRunner(
            exe2, CheckpointManager(str(tmp_path / "base")),
            main_program=main2, save_every=2).run(feed_fn, 6,
                                                  fetch_list=[loss2])
    assert base["retries"] == 0
    assert _losses(out) == _losses(base)


def test_runner_resumes_from_latest_checkpoint(tmp_path):
    feed_fn = _runner_feed()
    main, startup, loss = _fresh_model()
    exe = pt.Executor()
    exe.run(startup)
    root = str(tmp_path / "ck")
    r1 = CheckpointedRunner(exe, root, main_program=main, save_every=1)
    first = r1.run(feed_fn, 3, fetch_list=[loss])
    # "new process": fresh scope, params zeroed — resume must restore
    with pt.scope_guard(pt.Scope()):
        exe2 = pt.Executor()
        exe2.run(startup)
        r2 = CheckpointedRunner(exe2, root, main_program=main, save_every=1)
        second = r2.run(feed_fn, 6, fetch_list=[loss])
    assert second["start_step"] == 3
    assert sorted(second["results"]) == [3, 4, 5]

    # undisturbed 6-step baseline for comparison
    main2, startup2, loss2 = _fresh_model()
    with pt.scope_guard(pt.Scope()):
        exe3 = pt.Executor()
        exe3.run(startup2)
        base = CheckpointedRunner(
            exe3, str(tmp_path / "base"), main_program=main2,
            save_every=1).run(feed_fn, 6, fetch_list=[loss2])
    assert _losses(first) + _losses(second) == _losses(base)


def test_runner_surfaces_persistent_failure_with_bounded_attempts(tmp_path):
    from paddle_tpu.resilience.runner import StepFailure

    feed_fn = _runner_feed()
    main, startup, loss = _fresh_model()
    exe = pt.Executor()
    exe.run(startup)
    runner = CheckpointedRunner(exe, str(tmp_path / "ck"), main_program=main,
                                save_every=1, max_retries=3)
    # collective.step fires on every hit: the step can never succeed
    with fault_scope("collective.step:" + ",".join(map(str, range(1, 60)))):
        with pytest.raises(StepFailure) as ei:
            runner.run(feed_fn, 2, fetch_list=[loss])
    assert ei.value.attempts == 4  # max_retries exceeded by exactly one


def test_runner_invalidates_compile_cache_on_second_failure(tmp_path):
    feed_fn = _runner_feed()
    main, startup, loss = _fresh_model()
    exe = pt.Executor()
    exe.run(startup)
    calls = []
    orig = exe.invalidate_cache
    exe.invalidate_cache = lambda p=None: (calls.append(1), orig(p))[1]
    runner = CheckpointedRunner(exe, str(tmp_path / "ck"), main_program=main,
                                save_every=1, max_retries=5)
    # two consecutive step faults on the same step: rung 2 must invalidate
    with fault_scope("collective.step:2,3"):
        out = runner.run(feed_fn, 3, fetch_list=[loss])
    assert calls  # the second failure reached the invalidation rung
    assert sorted(out["results"]) == [0, 1, 2]


def test_executor_invalidate_cache_recompiles(tmp_path):
    exe, loss, feed = _train_setup(steps=1)
    main = pt.default_main_program()
    assert main in exe._cache
    exe.invalidate_cache(main)
    assert main not in exe._cache
    (lv,) = exe.run(main, feed=feed, fetch_list=[loss])  # recompiles fine
    assert np.isfinite(lv).all()


# -- ps rpc sites: client-level retry absorbs injected wire faults ------------


def _serve_one_param(ep, value):
    from paddle_tpu.distributed.ps_rpc import PServerRuntime
    from paddle_tpu.executor import Executor, Scope

    scope = Scope()
    scope.set_var("w", value)
    srv = PServerRuntime(ep, n_trainers=1, sync_mode=False, blocks=[],
                         scope=scope, executor=Executor())
    t = threading.Thread(target=srv.serve, daemon=True)
    t.start()
    return srv, t


def test_runner_completes_ps_training_under_faults_at_every_site(tmp_path):
    """The acceptance contract: one seeded plan injecting at least one
    failure at EVERY named site; a CheckpointedRunner driving a transpiled
    pserver trainer program completes training with bounded retries.

    The pserver runs as a subprocess (dist_simple.py pattern) so the
    in-process fault counters see only the trainer's hits and the schedule
    stays deterministic."""
    import socket
    import subprocess
    import sys

    import dist_simple as ds

    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    ep = f"127.0.0.1:{s.getsockname()[1]}"
    s.close()

    env = dict(os.environ)
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")
    ps = subprocess.Popen(
        [sys.executable, os.path.join(repo, "tests", "dist_simple.py"),
         "pserver", ep, "0", "1", str(tmp_path / "ps.npz"), ep],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT)

    try:
        main_p, startup = pt.Program(), pt.Program()
        main_p.random_seed = 7
        startup.random_seed = 7
        with pt.program_guard(main_p, startup):
            with pt.unique_name.guard():
                loss = ds.build()
                pt.optimizer.SGD(0.1).minimize(loss)
        t = pt.DistributeTranspiler()
        t.transpile(0, program=main_p, pservers=ep, trainers=1,
                    sync_mode=True, startup_program=startup)
        exe = pt.Executor()
        exe.run(startup)
        prog = t.get_trainer_program()
        x, y = ds.full_data()
        runner = CheckpointedRunner(
            exe, CheckpointManager(str(tmp_path / "ck"), keep_last_k=2),
            main_program=prog, save_every=2, max_retries=5)
        plan_spec = ("ps.send:2;ps.recv:3;collective.step:3;"
                     "executor.compile:1;ckpt.write:1")
        with fault_scope(plan_spec) as plan:
            out = runner.run(lambda step: {"x": x, "y": y}, 5,
                             fetch_list=[loss.name])
        exe.close()
    finally:
        if ps.poll() is None:
            try:
                out_ps, _ = ps.communicate(timeout=30)
            except subprocess.TimeoutExpired:
                ps.kill()
                out_ps, _ = ps.communicate()
        else:
            out_ps, _ = ps.communicate()
    assert ps.returncode == 0, out_ps.decode()[-3000:]

    stats = plan.stats()
    fired = {site for site, _ in stats["fired"]}
    assert fired == {"ps.send", "ps.recv", "collective.step",
                     "executor.compile", "ckpt.write"}, stats
    assert sorted(out["results"]) == [0, 1, 2, 3, 4]
    assert 0 < out["retries"] <= runner.max_retries * 5
    losses = _losses(out)
    assert losses[-1] < losses[0], losses


def test_ps_client_retries_injected_send_and_recv_faults():
    import socket

    from paddle_tpu.distributed.ps_rpc import PSClient

    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    ep = f"127.0.0.1:{s.getsockname()[1]}"
    s.close()
    w0 = np.arange(4, dtype=np.float32)
    srv, t = _serve_one_param(ep, w0)
    client = PSClient([ep], trainer_id=0)
    try:
        with fault_scope("ps.send:1;ps.recv:1") as plan:
            client.send_var(ep, "w", np.ones(4, np.float32))
            got = client.get_var(ep, "w")
        np.testing.assert_array_equal(got, w0)  # no optimize block: unchanged
        stats = plan.stats()
        # both sites fired once and the retry absorbed them
        assert {s for s, _ in stats["fired"]} == {"ps.send", "ps.recv"}
        assert stats["hits"]["ps.send"] >= 2 and stats["hits"]["ps.recv"] >= 2
    finally:
        client.send_complete()
        client.close()
        t.join(timeout=10)
