"""Short-seq fused attention kernel vs the jnp reference (fwd + grads).

Runs the Pallas kernels through the interpreter on the CPU test mesh; TPU
compilation was verified out-of-band (tools/_bert_flash_ab.py trains BERT
end-to-end with use_flash_attention=True). The default bench path keeps the
kernel OFF because XLA attention is faster at the bench config (PERF.md).
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from paddle_tpu.ops.attention_ops import _reference_attention
from paddle_tpu.ops.pallas_kernels import attention as psa


@pytest.fixture(autouse=True)
def _interpret():
    psa.INTERPRET = True
    yield
    psa.INTERPRET = False


def _rand(shape, dtype, seed):
    return jnp.asarray(
        np.random.default_rng(seed).standard_normal(shape), dtype)


@pytest.mark.parametrize("causal", [False, True])
@pytest.mark.parametrize("dtype", ["float32", "bfloat16"])
def test_fwd_matches_reference(causal, dtype):
    B, nh, S, dh = 2, 3, 128, 64
    q, k, v = (_rand((B, nh, S, dh), dtype, i) for i in range(3))
    sm = dh ** -0.5
    out = psa.short_seq_attention(q, k, v, causal=causal, sm_scale=sm)
    ref = _reference_attention(q.astype(jnp.float32), k.astype(jnp.float32),
                               v.astype(jnp.float32), causal=causal,
                               sm_scale=sm)
    tol = 2e-2 if dtype == "bfloat16" else 2e-5
    np.testing.assert_allclose(np.asarray(out, np.float32), np.asarray(ref),
                               atol=tol, rtol=tol)


@pytest.mark.parametrize("causal", [False, True])
def test_grads_match_reference(causal):
    B, nh, S, dh = 1, 2, 128, 32
    q, k, v = (_rand((B, nh, S, dh), "float32", 10 + i) for i in range(3))
    sm = dh ** -0.5
    ct = _rand((B, nh, S, dh), "float32", 99)

    def via_kernel(q, k, v):
        return jnp.sum(psa.short_seq_attention(q, k, v, causal=causal,
                                               sm_scale=sm) * ct)

    def via_ref(q, k, v):
        return jnp.sum(_reference_attention(q, k, v, causal=causal,
                                            sm_scale=sm) * ct)

    g_kernel = jax.grad(via_kernel, argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(via_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b, name in zip(g_kernel, g_ref, "qkv"):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=2e-4, rtol=2e-3, err_msg=name)


def test_head_block_respects_budget_and_divides():
    for nh in (1, 2, 3, 12, 16, 24):
        for s in (128, 256, 512, 1024):
            gh = psa._head_block(nh, s, 64, 2, 9)
            assert nh % gh == 0 and gh >= 1


def test_supported_gate():
    ok = ((2, 12, 128, 64), (2, 12, 128, 64))
    assert psa.short_seq_supported(*ok, bias=None)
    assert not psa.short_seq_supported(*ok, bias=object())
    assert not psa.short_seq_supported((2, 12, 130, 64), (2, 12, 130, 64),
                                       bias=None)
    assert not psa.short_seq_supported((2, 12, 128, 64), (2, 12, 256, 64),
                                       bias=None)
    assert not psa.short_seq_supported((2, 12, 2048, 64), (2, 12, 2048, 64),
                                       bias=None)
    # S=1024 bwd intermediates outgrow VMEM at gh=1 — must be rejected
    assert not psa.short_seq_supported((2, 12, 1024, 64), (2, 12, 1024, 64),
                                       bias=None)
    assert psa.short_seq_supported((2, 12, 512, 64), (2, 12, 512, 64),
                                   bias=None)
