"""Native standalone trainer (reference train/demo/demo_trainer.cc role):
a C binary hosting the runtime in-process loads a saved train model, trains
from a MultiSlot data file, and writes back persistables — no user Python."""
import os
import shutil
import subprocess
import sys

import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import layers as L

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_BIN = os.path.join(_REPO, "paddle_tpu", "native", "standalone_trainer")
_BUILD = os.path.join(_REPO, "tools", "build_standalone_trainer.sh")


def _ensure_built():
    src = os.path.join(_REPO, "paddle_tpu", "native", "standalone_trainer.c")
    if (os.path.exists(_BIN)
            and os.path.getmtime(_BIN) >= os.path.getmtime(src)):
        return True
    r = subprocess.run(["bash", _BUILD], capture_output=True)
    return r.returncode == 0


def test_save_load_train_model_roundtrip(tmp_path):
    main, startup = pt.Program(), pt.Program()
    main.random_seed = startup.random_seed = 3
    with pt.program_guard(main, startup):
        with pt.unique_name.guard():
            x = L.data(name="x", shape=[4], dtype="float32")
            y = L.data(name="y", shape=[1], dtype="float32")
            loss = L.mean(L.square_error_cost(L.fc(x, size=1), y))
            pt.optimizer.SGD(0.1).minimize(loss)
    exe = pt.Executor()
    with pt.scope_guard(pt.Scope()):
        exe.run(startup)
        pt.io.save_train_model(str(tmp_path), [x, y], loss, exe, main,
                               startup)
        w = np.asarray(pt.global_scope().find_var("fc_0.w_0"))
    scope2 = pt.Scope()
    with pt.scope_guard(scope2):
        main2, startup2, meta = pt.io.load_train_model(str(tmp_path), exe)
    assert meta["feed_names"] == ["x", "y"]
    assert meta["loss_name"] == loss.name
    # optimizer ops survived the round trip (it is a TRAIN program)
    assert any(op.type == "sgd" for op in main2.global_block.ops)
    np.testing.assert_array_equal(
        np.asarray(scope2.find_var("fc_0.w_0")), w)


@pytest.mark.skipif(shutil.which("cc") is None, reason="no C compiler")
def test_standalone_trainer_binary_trains(tmp_path):
    if not _ensure_built():
        pytest.skip("standalone trainer build failed (no python3-config?)")
    # build + save a CTR train model
    main, startup = pt.Program(), pt.Program()
    main.random_seed = startup.random_seed = 11
    with pt.program_guard(main, startup):
        with pt.unique_name.guard():
            ids = L.data(name="ids", shape=[4], dtype="int64")
            dense = L.data(name="dense", shape=[3], dtype="float32")
            label = L.data(name="label", shape=[1], dtype="float32")
            emb = L.embedding(ids, size=[50, 8])
            feat = L.concat([L.reshape(emb, [-1, 32]), dense], axis=1)
            h = L.fc(feat, size=16, act="relu")
            logit = L.fc(h, size=1)
            loss = L.mean(
                L.sigmoid_cross_entropy_with_logits(logit, label))
            pt.optimizer.SGD(0.1).minimize(loss)
    exe = pt.Executor()
    model_dir = str(tmp_path / "model")
    with pt.scope_guard(pt.Scope()):
        exe.run(startup)
        pt.io.save_train_model(model_dir, [ids, dense, label], loss, exe,
                               main, startup)
        w0 = np.asarray(pt.global_scope().find_var("fc_0.w_0")).copy()

    rng = np.random.default_rng(0)
    data = str(tmp_path / "data.txt")
    with open(data, "w") as f:
        for _ in range(320):
            i4 = rng.integers(0, 50, 4)
            d3 = rng.random(3).round(4)
            yv = int(i4.sum() % 2)
            f.write(f"4 {' '.join(map(str, i4))} "
                    f"3 {' '.join(map(str, d3))} 1 {yv}\n")

    out_dir = str(tmp_path / "out")
    env = dict(os.environ)
    env["PADDLE_TPU_HOME"] = _REPO
    r = subprocess.run([_BIN, model_dir, data, "32", "2", out_dir],
                       env=env, capture_output=True, timeout=240)
    assert r.returncode == 0, (r.stdout.decode()[-2000:]
                               + r.stderr.decode()[-2000:])
    assert b"saved to" in r.stdout

    # the binary's training moved the parameters it saved
    scope2 = pt.Scope()
    with pt.scope_guard(scope2):
        pt.io.load_vars(exe, out_dir, main,
                        vars=[v for v in main.list_vars()
                              if getattr(v, "persistable", False)])
        w1 = np.asarray(scope2.find_var("fc_0.w_0"))
    assert not np.allclose(w0, w1), "standalone trainer moved no parameters"
