"""Dygraph DataParallel (reference dygraph/parallel.py:84 +
test_parallel_dygraph_mnist pattern) and save/load_dygraph
(dygraph/checkpoint.py): 2-process trajectory == single-process full batch;
checkpoint round-trips through disk."""
import os
import subprocess
import sys

import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import dygraph as dg

_DIR = os.path.dirname(os.path.abspath(__file__))
_REPO = os.path.dirname(_DIR)
_SCRIPT = os.path.join(_DIR, "dist_dygraph.py")


def _env():
    env = dict(os.environ)
    env["PYTHONPATH"] = _REPO + os.pathsep + env.get("PYTHONPATH", "")
    env.pop("PADDLE_TRAINERS_NUM", None)
    env.pop("PADDLE_TRAINER_ID", None)
    return env


def test_dygraph_data_parallel_two_proc_matches_local(tmp_path):
    local_out = str(tmp_path / "local.npz")
    p = subprocess.run([sys.executable, _SCRIPT, local_out],
                       env=_env(), capture_output=True, timeout=300)
    assert p.returncode == 0, p.stderr.decode()[-3000:]

    log_dir = str(tmp_path / "log")
    dist_out = str(tmp_path / "dist")
    p = subprocess.run(
        [sys.executable, "-m", "paddle_tpu.distributed.launch",
         "--nproc_per_node", "2", "--backend", "cpu",
         "--local_devices_per_proc", "1", "--log_dir", log_dir,
         _SCRIPT, dist_out],
        env=_env(), cwd=_REPO, capture_output=True, timeout=300)
    logs = ""
    for i in range(2):
        f = os.path.join(log_dir, f"workerlog.{i}")
        if os.path.exists(f):
            with open(f) as fh:
                logs += f"--- workerlog.{i}\n" + fh.read()[-3000:]
    assert p.returncode == 0, logs + p.stderr.decode()[-2000:]

    local = np.load(local_out)
    r0 = np.load(dist_out + ".r0.npz")
    r1 = np.load(dist_out + ".r1.npz")
    for k in local.files:
        if k == "__last_loss__":
            continue  # dist loss is the scaled shard loss, not comparable
        np.testing.assert_allclose(local[k], r0[k], rtol=1e-5, atol=1e-6)
        np.testing.assert_allclose(r0[k], r1[k], rtol=1e-6, atol=1e-7)


def test_data_parallel_single_process_noop():
    with dg.guard(seed=1):
        model = dg.DataParallel(dg.Linear(4, 2))
        assert model.nranks == 1
        x = dg.to_variable(np.ones((3, 4), np.float32))
        out = model(x)
        loss0 = dg.to_variable(np.array(2.0, np.float32))
        assert model.scale_loss(loss0) is loss0  # identity at nranks=1
        model.apply_collective_grads()  # must not require a mesh
        assert out.numpy().shape == (3, 2)
        # delegation: parameters/state_dict reach the wrapped layer
        assert len(model.parameters()) == 2
        assert set(model.state_dict()) == {"weight", "bias"}


def test_save_load_dygraph_roundtrip(tmp_path):
    path = str(tmp_path / "ckpt" / "model")
    with dg.guard(seed=9):
        net = dg.Linear(6, 3)
        state0 = {k: v.numpy().copy() for k, v in net.state_dict().items()}
        dg.save_dygraph(net.state_dict(), path)
        assert os.path.exists(path + ".pdparams")

        # perturb, reload, verify restoration
        net.set_dict({k: v + 1.0 for k, v in state0.items()})
        params, opt = dg.load_dygraph(path)
        assert opt is None
        net.set_dict(params)
        for k, v in net.state_dict().items():
            np.testing.assert_allclose(v.numpy(), state0[k])


def test_save_load_dygraph_optimizer_state(tmp_path):
    path = str(tmp_path / "model")
    state = {"fc.w_0_moment1_0": np.ones((3,), np.float32),
             "global_step": np.array(7)}
    dg.save_dygraph(state, path)
    assert os.path.exists(path + ".pdopt")
    params, opt = dg.load_dygraph(path)
    assert params is None
    np.testing.assert_allclose(opt["fc.w_0_moment1_0"], 1.0)
    assert int(opt["global_step"]) == 7


def test_load_dygraph_missing_raises(tmp_path):
    with pytest.raises(IOError, match="no checkpoint"):
        dg.load_dygraph(str(tmp_path / "nope"))


def test_load_dygraph_corrupt_names_path(tmp_path):
    """A truncated/garbage container raises IOError naming the file, not a
    bare zipfile/numpy internal error."""
    path = tmp_path / "model.pdparams"
    path.write_bytes(b"PK\x03\x04 this is not a real zip")
    with pytest.raises(IOError, match=str(path)):
        dg.load_dygraph(str(tmp_path / "model"))
