"""Light-NAS search (reference slim/nas/light_nas_strategy.py +
searcher/controller.py): the SA search over MLP layer widths must find a
SMALLER model than the full-width baseline within an accuracy budget."""
import numpy as np

import paddle_tpu as pt
from paddle_tpu import layers as L
from paddle_tpu.contrib.slim.nas import LightNASStrategy, SAController


def _make_data(n=512, dim=12, classes=4, seed=0):
    """Linearly separable clusters — a couple of training epochs reach
    high accuracy at any reasonable width."""
    rng = np.random.default_rng(seed)
    centers = rng.standard_normal((classes, dim)).astype(np.float32) * 3.0
    y = rng.integers(0, classes, n)
    x = centers[y] + rng.standard_normal((n, dim)).astype(np.float32) * 0.5
    return x.astype(np.float32), y.astype(np.int64).reshape(-1, 1)


class MLPWidthSpace:
    """Tokens index into WIDTHS per hidden layer; reward = eval accuracy
    minus a small flops tax so equal-accuracy candidates prefer smaller."""

    WIDTHS = [8, 16, 32, 64]

    def __init__(self, dim=12, classes=4):
        self.dim, self.classes = dim, classes
        self.x, self.y = _make_data(dim=dim, classes=classes)
        self.xe, self.ye = _make_data(dim=dim, classes=classes, seed=1)
        self.evals = 0

    def init_tokens(self):
        return [3, 3]  # start at full width (64, 64)

    def range_table(self):
        return [len(self.WIDTHS)] * 2

    def flops(self, tokens):
        h1, h2 = (self.WIDTHS[t] for t in tokens)
        return 2 * (self.dim * h1 + h1 * h2 + h2 * self.classes)

    def eval_tokens(self, tokens):
        self.evals += 1
        h1, h2 = (self.WIDTHS[t] for t in tokens)
        main, startup = pt.Program(), pt.Program()
        main.random_seed = 5
        startup.random_seed = 5
        with pt.program_guard(main, startup):
            with pt.unique_name.guard():
                xv = L.data(name="x", shape=[self.dim], dtype="float32")
                yv = L.data(name="y", shape=[1], dtype="int64")
                h = L.fc(xv, size=h1, act="relu")
                h = L.fc(h, size=h2, act="relu")
                logits = L.fc(h, size=self.classes)
                loss = L.mean(L.softmax_with_cross_entropy(logits, yv))
                acc = L.accuracy(logits, yv)
                pt.optimizer.Adam(5e-3).minimize(loss)
        exe = pt.Executor()
        with pt.scope_guard(pt.Scope()):
            exe.run(startup)
            for _ in range(25):
                exe.run(main, feed={"x": self.x, "y": self.y})
            (a,) = exe.run(main, feed={"x": self.xe, "y": self.ye},
                           fetch_list=[acc])
        accuracy = float(np.asarray(a).reshape(-1)[0])
        fl = self.flops(tokens)
        return accuracy - 1e-6 * fl, fl


def test_sa_controller_anneals_toward_better_rewards():
    ctrl = SAController(seed=0)
    ctrl.reset([4, 4], [0, 0])
    # reward landscape: higher tokens better
    for _ in range(40):
        t = ctrl.next_tokens()
        ctrl.update(t, sum(t) / 6.0)
    assert ctrl.best_tokens is not None
    assert sum(ctrl.best_tokens) >= 5  # found a high-reward region


def test_sa_controller_honors_constraint():
    ctrl = SAController(seed=1)
    ctrl.reset([4, 4], [0, 0], constrain_func=lambda t: sum(t) <= 3)
    for _ in range(20):
        t = ctrl.next_tokens()
        assert sum(t) <= 3
        ctrl.update(t, 1.0)


def test_light_nas_finds_smaller_model_within_accuracy_budget():
    space = MLPWidthSpace()
    # baseline: the full-width model
    base_reward, base_flops = space.eval_tokens(space.init_tokens())
    base_acc = base_reward + 1e-6 * base_flops

    nas = LightNASStrategy(space, max_flops=base_flops * 0.6,
                           search_steps=8, seed=0)
    best_tokens, best_reward = nas.search()
    best_flops = space.flops(best_tokens)
    best_acc = best_reward + 1e-6 * best_flops

    assert best_flops <= base_flops * 0.6       # genuinely smaller
    assert best_acc >= base_acc - 0.05          # within accuracy budget
    assert space.evals >= 9                     # init + search trials ran
