"""Kill-and-resume trainer for the TIERED embedding path (the
dist_ckpt_resume.py pattern over ISSUE 10's host tier): a 512-row table
behind a 256-slot cache trains under a CheckpointedRunner whose saves
stream base + dirty-row deltas through the CheckpointManager manifest.
With KILL_AT >= 0 the process SIGKILLs itself right after recording that
step; a fresh invocation restores base + delta, cold-starts the cache, and
must reproduce the remaining loss trajectory bit for bit.

usage: dist_emb_resume.py CKPT_ROOT LOSSES_FILE TOTAL_STEPS KILL_AT
"""
import os
import signal
import sys

import jax

jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402

import paddle_tpu as pt  # noqa: E402
from paddle_tpu import flags  # noqa: E402
from paddle_tpu import layers as L  # noqa: E402
from paddle_tpu.layers import tensor as T  # noqa: E402
from paddle_tpu.param_attr import ParamAttr  # noqa: E402
from paddle_tpu.resilience import (CheckpointManager,  # noqa: E402
                                   CheckpointedRunner)

VOCAB, DIM, FIELDS, BATCH = 512, 8, 6, 32

flags.set_flags({"emb_hbm_budget_mb": 0.001, "emb_cache_slots": 256,
                 "emb_ckpt_base_every": 3})


def build():
    ids = T.data(name="ids", shape=[FIELDS], dtype="int64")
    label = T.data(name="label", shape=[1], dtype="float32")
    emb = L.embedding(ids, size=[VOCAB, DIM], is_sparse=True,
                      param_attr=ParamAttr(name="tbl"))
    s = L.reduce_sum(emb, dim=1)
    logit = L.fc(s, size=1, param_attr=ParamAttr(name="w_out"),
                 bias_attr=ParamAttr(name="b_out"))
    return L.mean(L.sigmoid_cross_entropy_with_logits(logit, label))


def feed_fn(step):
    rng = np.random.default_rng(1000 + step)
    return {"ids": rng.integers(0, VOCAB,
                                (BATCH, FIELDS)).astype(np.int64),
            "label": rng.integers(0, 2, (BATCH, 1)).astype(np.float32)}


def main():
    root, losses_path, total_steps, kill_at = (
        sys.argv[1], sys.argv[2], int(sys.argv[3]), int(sys.argv[4]))

    main_p, startup = pt.Program(), pt.Program()
    main_p.random_seed = startup.random_seed = 7
    with pt.program_guard(main_p, startup):
        with pt.unique_name.guard():
            loss = build()
            pt.optimizer.SGD(0.1).minimize(loss)

    exe = pt.Executor()
    exe.run(startup)
    runner = CheckpointedRunner(
        exe, CheckpointManager(root, keep_last_k=3, main_program=main_p),
        main_program=main_p, save_every=1, max_retries=5)

    f = open(losses_path, "a")

    def on_step(step, outs):
        f.write(f"{step} {float(np.asarray(outs[0]).reshape(-1)[0]):.17g}\n")
        f.flush()
        os.fsync(f.fileno())
        if step == kill_at:
            os.kill(os.getpid(), signal.SIGKILL)

    out = runner.run(feed_fn, total_steps, fetch_list=[loss],
                     on_step=on_step)
    f.close()
    print(f"done start={out['start_step']} retries={out['retries']}")


if __name__ == "__main__":
    main()
