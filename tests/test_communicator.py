"""Communicator unit semantics (reference communicator.h:162): per-grad
queues, merge-N-before-send (dense mean / sparse row-concat), progress-gated
recv, error surfacing. A fake client isolates the logic from networking."""
import threading
import time

import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import flags
from paddle_tpu.distributed.communicator import Communicator


class FakeClient:
    def __init__(self):
        self.sent = []          # (ep, name, value)
        self.params = {}        # name -> value served to get_var
        self.lock = threading.Lock()

    def send_var(self, ep, name, value):
        with self.lock:
            self.sent.append((ep, name, value))

    def get_var(self, ep, name):
        with self.lock:
            return self.params[name]


def _wait(pred, timeout=5.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return True
        time.sleep(0.01)
    return False


def test_merge_before_send_dense_mean():
    """N queued dense grads collapse into ONE send carrying their mean."""
    client = FakeClient()
    comm = Communicator({"g": {"epmap": ["ep0"], "sections": []}}, {},
                        client, pt.Scope())
    # enqueue BEFORE starting so the send thread sees a full queue at once
    for i in range(4):
        comm._queues["g"].put(np.full((3,), float(i), np.float32))
    comm.start()
    try:
        assert _wait(lambda: len(client.sent) >= 1)
        time.sleep(0.1)  # no extra sends must trickle out
        assert len(client.sent) == 1, client.sent
        ep, name, val = client.sent[0]
        assert (ep, name) == ("ep0", "g")
        np.testing.assert_allclose(val, np.full((3,), 1.5))  # mean(0..3)
    finally:
        comm.stop()


def test_merge_cap_respects_max_merge_var_num():
    old = flags.get_flag("communicator_max_merge_var_num")
    flags.set_flags({"communicator_max_merge_var_num": 2})
    try:
        client = FakeClient()
        comm = Communicator({"g": {"epmap": ["ep0"], "sections": []}}, {},
                            client, pt.Scope())
        for i in range(4):
            comm._queues["g"].put(np.full((2,), float(i), np.float32))
        comm.start()
        try:
            assert _wait(lambda: len(client.sent) >= 2)
            time.sleep(0.1)
            assert len(client.sent) == 2  # 4 grads / cap 2
            np.testing.assert_allclose(client.sent[0][2], 0.5)  # mean(0,1)
            np.testing.assert_allclose(client.sent[1][2], 2.5)  # mean(2,3)
        finally:
            comm.stop()
    finally:
        flags.set_flags({"communicator_max_merge_var_num": old})


def test_merge_sparse_concatenates_rows():
    from paddle_tpu.core.selected_rows import SelectedRows

    client = FakeClient()
    comm = Communicator({"emb@GRAD": {"epmap": ["ep0"], "sections": []}}, {},
                        client, pt.Scope())
    comm._queues["emb@GRAD"].put(
        SelectedRows(np.array([0, 2]), np.ones((2, 4), np.float32), 10))
    comm._queues["emb@GRAD"].put(
        SelectedRows(np.array([1]), np.full((1, 4), 3.0, np.float32), 10))
    comm.start()
    try:
        assert _wait(lambda: len(client.sent) >= 1)
        _, _, sr = client.sent[0]
        assert hasattr(sr, "rows")
        np.testing.assert_array_equal(np.asarray(sr.rows), [0, 2, 1])
        assert np.asarray(sr.values).shape == (3, 4)
    finally:
        comm.stop()


def test_sectioned_send_slices_rows():
    client = FakeClient()
    comm = Communicator(
        {"g": {"epmap": ["ep0", "ep1"], "sections": [2, 3]}}, {},
        client, pt.Scope())
    comm._queues["g"].put(np.arange(5, dtype=np.float32))
    comm.start()
    try:
        assert _wait(lambda: len(client.sent) >= 2)
        by_name = {n: (ep, v) for ep, n, v in client.sent}
        np.testing.assert_allclose(by_name["g.block0"][1], [0, 1])
        np.testing.assert_allclose(by_name["g.block1"][1], [2, 3, 4])
        assert by_name["g.block0"][0] == "ep0"
        assert by_name["g.block1"][0] == "ep1"
    finally:
        comm.stop()


def test_recv_gated_on_send_progress():
    """No params are pulled before min_send_grad_num_before_recv grads went
    out; after the threshold the scope refreshes."""
    old = flags.get_flag("communicator_min_send_grad_num_before_recv")
    flags.set_flags({"communicator_min_send_grad_num_before_recv": 3})
    try:
        client = FakeClient()
        client.params["w"] = np.full((2,), 7.0, np.float32)
        scope = pt.Scope()
        scope.set_var("w", np.zeros((2,), np.float32))
        comm = Communicator({"g": {"epmap": ["ep0"], "sections": []}},
                            {"w": {"epmap": ["ep0"], "sections": []}},
                            client, scope)
        comm.start()
        try:
            comm.push("g", np.zeros((2,), np.float32))
            time.sleep(0.15)
            np.testing.assert_allclose(np.asarray(scope.find_var("w")), 0.0)
            for _ in range(4):
                comm.push("g", np.zeros((2,), np.float32))
            assert _wait(lambda: float(np.asarray(
                scope.find_var("w"))[0]) == 7.0)
        finally:
            comm.stop()
    finally:
        flags.set_flags(
            {"communicator_min_send_grad_num_before_recv": old})


def test_push_surfaces_send_thread_failure():
    class Exploding(FakeClient):
        def send_var(self, ep, name, value):
            raise ConnectionError("server gone")

    old = flags.get_flag("communicator_send_queue_size")
    flags.set_flags({"communicator_send_queue_size": 1})
    try:
        comm = Communicator({"g": {"epmap": ["ep0"], "sections": []}}, {},
                            Exploding(), pt.Scope())
        comm.start()
        with pytest.raises(RuntimeError, match="send thread.*failed"):
            for _ in range(50):
                comm.push("g", np.zeros((2,), np.float32))
                time.sleep(0.01)
        # stop() must ALSO surface the failure (tail batches with no later
        # push to report through)
        with pytest.raises(RuntimeError, match="send thread.*failed"):
            comm.stop()
        comm._send_errors.clear()
        comm._running = False
    finally:
        flags.set_flags({"communicator_send_queue_size": old})
