"""Learned serving control tests (ISSUE 20): regime/knob spellings and
their round trips, deterministic training + proposals from a fixed store
snapshot, the confidence-gate fallback ladder, the actuator's safety
rails (staged configs adopt only at idle boundaries, geometry changes
re-warm with zero compiles left on the serving path, shadow mode never
applies), and the store-backed disagg role-split prior."""
import os

import pytest

import paddle_tpu as pt
from paddle_tpu.serving import ServingEngine, decoder_tiny
from paddle_tpu.serving import control as sv_control
from paddle_tpu.serving.control import controller as sv_controller
from paddle_tpu.serving.control import policy as sv_policy
from paddle_tpu.tuning import learned
from paddle_tpu.tuning.learned import features


ARM_FAST = {"mi": 8, "dk": 0, "pc": 1, "sp": 0,
            "sq": 8, "so": 95, "da": 2, "pd": 0}
ARM_HAND = {"mi": 4, "dk": 0, "pc": 1, "sp": 0,
            "sq": 8, "so": 95, "da": 2, "pd": 0}
ARM_SLOW = {"mi": 2, "dk": 0, "pc": 1, "sp": 1,
            "sq": 4, "so": 90, "da": 4, "pd": 0}

# eight regimes spanning every feature axis, so live-ish signals land
# INSIDE the trained envelope (the gate kills extrapolations by design)
_REGIMES = [
    dict(rate=2, p50=8, p95=16, out=4, hit=0.0, occ=0.05, q=0, hr=1.0),
    dict(rate=4, p50=8, p95=16, out=8, hit=0.2, occ=0.10, q=1, hr=1.0),
    dict(rate=8, p50=16, p95=32, out=4, hit=0.4, occ=0.20, q=2, hr=0.5),
    dict(rate=16, p50=16, p95=32, out=8, hit=0.6, occ=0.30, q=4, hr=1.0),
    dict(rate=32, p50=32, p95=64, out=16, hit=0.8, occ=0.50, q=8, hr=0.0),
    dict(rate=64, p50=32, p95=64, out=4, hit=0.9, occ=0.70, q=2, hr=0.5),
    dict(rate=128, p50=8, p95=16, out=8, hit=0.5, occ=0.80, q=1, hr=1.0),
    dict(rate=256, p50=16, p95=32, out=16, hit=0.3, occ=0.90, q=0, hr=1.0),
]
_SIG_MID = dict(rate=48, p50=16, p95=32, out=8,
                hit=0.5, occ=0.4, q=2, hr=1.0)

_CTRL_FLAGS = ("serve_control_mode", "serve_control_store",
               "serve_control_model", "serve_control_conf",
               "serve_control_epoch_s", "tuning_record",
               "tuning_measurements", "tuning_model", "tuning_mode",
               "disagg_prefill_replicas")


@pytest.fixture
def ctrl_flags():
    snap = {k: pt.flags.get_flag(k) for k in _CTRL_FLAGS}
    yield pt.flags
    pt.flags.set_flags(snap)
    sv_control.invalidate_model_cache()


def _seed_store(path, flags) -> list:
    """A deterministic store snapshot: goodput = mult * (10 + rate), with
    ARM_FAST always 2x ARM_SLOW — every key ranks the arms identically,
    so the trained group's holdout rank accuracy is exact."""
    flags.set_flags({"tuning_record": "on"})
    for sig in _REGIMES:
        for arm, mult in ((ARM_FAST, 2.0), (ARM_HAND, 1.5), (ARM_SLOW, 1.0)):
            assert sv_control.record_row(
                sig, arm, mult * (10.0 + sig["rate"]),
                source="sweep", tool=True, path=path)
    return list(learned.iter_records(path))


def _engine(**kw):
    kw.setdefault("page_size", 4)
    kw.setdefault("pool_pages", 32)
    kw.setdefault("max_inflight", 2)
    return ServingEngine(decoder_tiny(), seed=0, **kw)


# -- spellings ---------------------------------------------------------------

def test_regime_key_round_trip():
    key = sv_control.regime_key(_SIG_MID)
    sig = sv_control.parse_regime(key)
    assert sig is not None
    assert sv_control.regime_key(sig) == key  # bucketing is idempotent
    assert sv_control.parse_regime("rate=8 p50=16") is None
    assert sv_control.parse_regime("not a regime") is None


def test_regime_key_featurizes():
    key = sv_control.regime_key(_SIG_MID)
    f = features.featurize("serving.control", key, "-")
    assert len(f) == len(features.feature_names("serving.control")) == 8


def test_knob_key_round_trip():
    key = sv_control.knob_key(ARM_FAST)
    assert sv_control.parse_knobs(key) == ARM_FAST
    assert sv_control.parse_knobs("mi=4 dk=0") is None
    assert sv_control.parse_knobs("conv:igemm") is None  # foreign arm


def test_sweep_arms_deterministic_and_hand_first():
    a1 = sv_control.sweep_arms(6, seed=3, include=ARM_HAND)
    a2 = sv_control.sweep_arms(6, seed=3, include=ARM_HAND)
    assert a1 == a2
    assert a1[0] == ARM_HAND
    keys = [sv_control.knob_key(a) for a in a1]
    assert len(set(keys)) == len(keys)
    mis = {a["mi"] for a in a1}
    assert len(mis) >= 2  # stratified over the dominant axis


# -- training + proposals from a fixed snapshot ------------------------------

def test_store_row_shape(tmp_path, ctrl_flags):
    store = str(tmp_path / "ctrl.jsonl")
    recs = _seed_store(store, ctrl_flags)
    assert len(recs) == 3 * len(_REGIMES)
    rec = recs[0]
    assert rec["op"] == "serving.control"
    assert rec["dtype"] == "-"
    assert sv_control.parse_knobs(rec["arm"]) is not None
    assert sv_control.parse_regime(rec["shape_key"]) is not None
    # seconds per goodput token: argmin time == argmax goodput
    assert rec["median_s"] == pytest.approx(
        1.0 / (2.0 * (10.0 + _REGIMES[0]["rate"])))


def test_record_row_gating(tmp_path, ctrl_flags):
    store = str(tmp_path / "gated.jsonl")
    ctrl_flags.set_flags({"tuning_record": "off"})
    assert not sv_control.record_row(_SIG_MID, ARM_FAST, 100.0,
                                     tool=True, path=store)
    ctrl_flags.set_flags({"tuning_record": "on"})
    assert not sv_control.record_row(_SIG_MID, ARM_FAST, 0.0,
                                     tool=True, path=store)  # no goodput
    assert sv_control.record_row(_SIG_MID, ARM_FAST, 100.0,
                                 tool=True, path=store)


def test_train_is_deterministic_and_proposals_reproduce(tmp_path,
                                                        ctrl_flags):
    store = str(tmp_path / "ctrl.jsonl")
    recs = _seed_store(store, ctrl_flags)
    m1 = learned.train_model(recs, seed=0)
    m2 = learned.train_model(list(learned.iter_records(store)), seed=0)
    p1, p2 = str(tmp_path / "m1.json"), str(tmp_path / "m2.json")
    learned.save_model(m1, p1)
    learned.save_model(m2, p2)
    with open(p1, "rb") as f1, open(p2, "rb") as f2:
        assert f1.read() == f2.read()  # byte-identical retrain
    group = m1["groups"]["serving.control|cpu"]
    assert group["holdout"]["rank_acc"] >= 0.6
    ctrl_flags.set_flags({"serve_control_mode": "shadow"})
    k1, i1 = sv_control.propose(_SIG_MID, model=m1)
    k2, i2 = sv_control.propose(_SIG_MID, model=m2)
    assert (k1, i1["tier"]) == (k2, "learned")
    assert k1 == ARM_FAST  # the 2x arm wins every regime


def test_confidence_gate_fallback_ladder(tmp_path, ctrl_flags):
    store = str(tmp_path / "ctrl.jsonl")
    model = learned.train_model(_seed_store(store, ctrl_flags), seed=0)
    hand = sv_control.hand_knobs()
    ctrl_flags.set_flags({"serve_control_mode": "off"})
    k, info = sv_control.propose(_SIG_MID, model=model)
    assert (k, info["reason"]) == (hand, "off")
    ctrl_flags.set_flags({"serve_control_mode": "shadow"})
    missing = str(tmp_path / "nope.json")
    ctrl_flags.set_flags({"serve_control_model": missing})
    sv_control.invalidate_model_cache()
    k, info = sv_control.propose(_SIG_MID)
    assert (k, info["reason"]) == (hand, "no_model")
    # foreign device: regimes never transfer across device kinds
    k, info = sv_control.propose(_SIG_MID, model=model, dev="tpu")
    assert (k, info["reason"]) == (hand, "no_group")
    # a confidence floor above the group's holdout accuracy refuses
    ctrl_flags.set_flags({"serve_control_conf": 1.01})
    k, info = sv_control.propose(_SIG_MID, model=model)
    assert (k, info["reason"]) == (hand, "accuracy")


# -- the actuator's safety rails ---------------------------------------------

def test_staged_config_adopts_only_at_idle_boundary():
    eng = _engine()
    eng.warmup_decode(24)
    eng.submit([1, 2, 3], 4)
    eng.step()
    assert eng.propose_config({"mi": 4, "sq": 16}) is True
    eng.step()
    # in-flight work pins the old config: no torn reconfiguration
    assert eng.max_inflight == 2 and eng.shed_queue_depth == 0
    while eng.has_work():
        eng.step()
    eng.submit([4, 5, 6], 4)  # admit boundary: idle engine adopts
    assert eng.max_inflight == 4 and eng.shed_queue_depth == 16
    assert eng.stats["control.applies"] == 1
    assert eng.stats["control.rewarmups"] == 1  # bucket geometry moved
    while eng.has_work():
        eng.step()


def test_rewarmup_leaves_zero_compiles_on_serving_path():
    from paddle_tpu.pipeline import jit_compile_counter

    eng = _engine()
    eng.warmup_decode(24)
    eng.submit([1, 2, 3], 4)
    while eng.has_work():
        eng.step()
    eng.propose_config({"mi": 4})
    eng.submit([7, 8, 9], 4)  # adoption + re-warmup compile here
    assert eng.max_inflight == 4
    assert eng.stats["control.rewarmups"] == 1
    with jit_compile_counter() as c:
        for i in range(3):  # fill the widened batch: every bucket to 4
            eng.submit([10 + i, 2, 3, 4, 5], 4)
        while eng.has_work():
            eng.step()
    assert c.count == 0  # the actuated geometry was fully pre-warmed


def test_same_config_proposal_clears_pending():
    eng = _engine()
    assert eng.propose_config({"mi": 4}) is True
    assert eng._pending_ecfg is not None
    assert eng.propose_config({"mi": 2}) is False  # back to current
    assert eng._pending_ecfg is None
    assert eng.maybe_adopt_config() is False
    assert eng.stats["control.applies"] == 0


def test_propose_config_clamps_and_ignores_construction_knobs():
    eng = _engine()
    before = sv_control.engine_knobs(eng)
    eng.propose_config({"mi": 0, "dk": -3, "so": 250,
                        "pc": 1 - before["pc"], "sp": 1 - before["sp"]})
    assert eng.maybe_adopt_config() is True
    assert eng.max_inflight == 1  # floor, not zero
    assert eng.draft_k == 0
    assert eng.shed_occupancy == 1.0  # percent clamped into [0, 1]
    after = sv_control.engine_knobs(eng)
    # construction-only knobs never move through the actuator
    assert (after["pc"], after["sp"]) == (before["pc"], before["sp"])


def test_controller_shadow_never_applies(ctrl_flags, monkeypatch):
    ctrl_flags.set_flags({"serve_control_mode": "shadow"})
    eng = _engine()
    monkeypatch.setattr(
        sv_policy, "propose",
        lambda sig, **kw: (dict(ARM_FAST),
                           {"tier": "learned", "arm": "fake", "times": {}}))
    ctrl = sv_controller.Controller(epoch_s=1.0)
    assert ctrl.tick(eng, now=100.0) is False  # first sight opens window
    assert ctrl.tick(eng, now=100.5) is False  # not due yet
    assert ctrl.tick(eng, now=101.5) is True
    assert ctrl.last_info[id(eng)]["tier"] == "learned"
    assert eng._pending_ecfg is None  # shadow proposes, never stages
    assert eng.stats["control.applies"] == 0


def test_controller_apply_stages_then_engine_adopts(ctrl_flags,
                                                    monkeypatch):
    ctrl_flags.set_flags({"serve_control_mode": "apply"})
    eng = _engine()
    eng.warmup_decode(24)
    monkeypatch.setattr(
        sv_policy, "propose",
        lambda sig, **kw: (dict(ARM_FAST),
                           {"tier": "learned", "arm": "fake", "times": {}}))
    ctrl = sv_controller.Controller(epoch_s=1.0)
    ctrl.tick(eng, now=100.0)
    assert ctrl.tick(eng, now=101.5) is True
    assert eng._pending_ecfg is not None  # staged, not yet live
    assert eng.max_inflight == 2
    eng.submit([1, 2, 3], 2)  # idle boundary adopts the staged config
    assert eng.max_inflight == ARM_FAST["mi"]
    assert eng.shed_queue_depth == ARM_FAST["sq"]
    assert eng.degrade_after == ARM_FAST["da"]
    while eng.has_work():
        eng.step()


def test_controller_off_mode_skips_epochs(ctrl_flags):
    ctrl_flags.set_flags({"serve_control_mode": "off"})
    eng = _engine()
    ctrl = sv_controller.Controller(epoch_s=1.0)
    ctrl.tick(eng, now=100.0)
    assert ctrl.tick(eng, now=105.0) is False  # due, but the mode is off


def test_engine_config_snapshot_is_single_source():
    eng = _engine(shed_queue_depth=8, shed_occupancy=0.95, degrade_after=2)
    cfg = eng.engine_config
    assert (cfg.max_inflight, cfg.shed_queue_depth,
            cfg.shed_occupancy, cfg.degrade_after) == (2, 8, 0.95, 2)
    assert eng.max_inflight == 2 and eng.shed_queue_depth == 8
    assert cfg.bucket_geometry() == (2, 0)


# -- fleet: role prior + placement costs -------------------------------------

def _pd_row(pd, median_s, fleet_n=3):
    return {"op": "serving.control", "shape_key": "r",
            "arm": sv_control.knob_key(dict(ARM_HAND, pd=pd)),
            "median_s": median_s, "fleet_n": fleet_n}


def test_role_split_prior_picks_best_recorded_pd(ctrl_flags):
    ctrl_flags.set_flags({"serve_control_mode": "shadow"})
    rows = [_pd_row(1, 0.002), _pd_row(1, 0.002),
            _pd_row(2, 0.004), _pd_row(2, 0.005)]
    n_pre, info = sv_control.role_split_prior(3, records=rows)
    assert (n_pre, info["tier"]) == (1, "learned")
    # rows from another fleet size are not comparable work
    n_pre, info = sv_control.role_split_prior(
        3, records=[_pd_row(1, 0.001, fleet_n=4)])
    assert (n_pre, info["reason"]) == (0, "no_rows")


def test_role_split_prior_fallbacks(ctrl_flags):
    ctrl_flags.set_flags({"serve_control_mode": "shadow",
                          "disagg_prefill_replicas": 1})
    n_pre, info = sv_control.role_split_prior(3, records=[])
    assert (n_pre, info["reason"]) == (1, "no_rows")
    # the recorded best IS the hand flag: nothing to override
    rows = [_pd_row(1, 0.002), _pd_row(2, 0.004)]
    n_pre, info = sv_control.role_split_prior(3, records=rows)
    assert (n_pre, info["reason"]) == (1, "hand_best")
    # a best within the near-tie band defers to the flag
    rows = [_pd_row(1, 0.00100), _pd_row(2, 0.00097)]
    n_pre, info = sv_control.role_split_prior(3, records=rows)
    assert (n_pre, info["reason"]) == (1, "tie_band")
    ctrl_flags.set_flags({"serve_control_mode": "off"})
    n_pre, info = sv_control.role_split_prior(3, records=rows)
    assert (n_pre, info["reason"]) == (1, "off")


def test_router_placement_costs_neutral_unless_apply(ctrl_flags):
    from paddle_tpu.serving import FleetRouter

    ctrl_flags.set_flags({"serve_control_mode": "shadow"})
    with FleetRouter(lambda role=None: _engine(), 2,
                     heartbeat_s=30.0) as fr:
        costs = fr._placement_costs(fr.replicas)
        assert set(costs.values()) == {1.0}  # shadow: plain least-loaded
        ctrl_flags.set_flags({"serve_control_mode": "apply"})
        e0, e1 = fr.replicas[0].engine, fr.replicas[1].engine
        e0._ctrl.last_cost[id(e0)] = 0.002
        costs = fr._placement_costs(fr.replicas)
        assert set(costs.values()) == {1.0}  # one prediction missing
        e1._ctrl.last_cost[id(e1)] = 0.004
        costs = fr._placement_costs(fr.replicas)
        assert costs[fr.replicas[0].rid] == pytest.approx(0.002)
        assert costs[fr.replicas[1].rid] == pytest.approx(0.004)
