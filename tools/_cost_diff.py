"""Compare XLA cost analysis of the framework's bench step vs the pure-JAX
replica: flops + bytes accessed reveal double-compute / extra materialization.
Usage: python tools/_cost_diff.py
"""
import sys

import jax
import jax.numpy as jnp
import numpy as np

sys.path.insert(0, "/root/repo")


def framework_cost():
    import paddle_tpu as pt
    from paddle_tpu.models import transformer

    cfg = transformer.TransformerConfig(
        vocab_size=30522, hidden_size=768, num_layers=12, num_heads=12,
        ffn_size=3072, max_position=512, dropout=0.0, use_tp=False)
    batch, seq_len = 128, 128
    main_p, startup = pt.Program(), pt.Program()
    with pt.program_guard(main_p, startup):
        avg_loss, _ = transformer.bert_pretrain(cfg, seq_len=seq_len)
        opt = pt.contrib.mixed_precision.decorate(
            pt.optimizer.Adam(learning_rate=1e-4))
        opt.minimize(avg_loss)

    from __graft_entry__ import _example_feed
    feed = _example_feed(cfg, batch, seq_len)
    exe = pt.Executor()
    with pt.scope_guard(pt.Scope()):
        exe.run(startup)
        exe.run(main_p, feed=feed)  # compile + cache
        # grab the cached compiled fn and its arg values
        prog_cache = exe._cache[main_p]
        comp = next(iter(prog_cache.values()))
        scope = pt.global_scope()
        feed_names = sorted(feed)
        feed_vals = tuple(feed[n] for n in feed_names)
        ro_vals = tuple(exe._fetch_state(scope, n) for n in comp.ro_names)
        rw_vals = tuple(exe._fetch_state(scope, n) for n in comp.rw_names)
        key = jax.random.PRNGKey(0)
        lowered = comp.fn.lower(feed_vals, ro_vals, rw_vals, key)
        compiled = lowered.compile()
        ca = compiled.cost_analysis()
        import os
        if os.environ.get("DUMP_HLO"):
            open("/tmp/hlo_framework.txt", "w").write(compiled.as_text())
    return ca


def replica_cost():
    import importlib
    sys.argv = ["x", "model", "1"]
    mod = importlib.import_module("tools._bert_pure") if False else None
    # inline a single-step version instead (no scan) for clean cost numbers
    B, S, H, nh, dh, L, V, F = 128, 128, 768, 12, 64, 12, 30522, 3072
    sm = dh ** -0.5
    rng = np.random.default_rng(0)

    def mk(*shape, scale=0.02):
        return jnp.asarray(rng.standard_normal(shape) * scale, jnp.float32)

    params = {"emb": mk(V, H), "pos": mk(S, H), "head_w": mk(H, V),
              "head_b": jnp.zeros((V,), jnp.float32)}
    for i in range(L):
        params[f"l{i}"] = {
            "qkv_w": mk(H, 3 * H), "qkv_b": jnp.zeros((3 * H,)),
            "o_w": mk(H, H), "o_b": jnp.zeros((H,)),
            "ln1_g": jnp.ones((H,)), "ln1_b": jnp.zeros((H,)),
            "f1_w": mk(H, F), "f1_b": jnp.zeros((F,)),
            "f2_w": mk(F, H), "f2_b": jnp.zeros((H,)),
            "ln2_g": jnp.ones((H,)), "ln2_b": jnp.zeros((H,)),
        }
    ids = jnp.asarray(rng.integers(0, V, (B, S)), jnp.int32)
    labels = jnp.asarray(rng.integers(0, V, (B, S)), jnp.int32)

    def ln(x, g, b):
        x32 = x.astype(jnp.float32)
        mu = x32.mean(-1, keepdims=True)
        var = ((x32 - mu) ** 2).mean(-1, keepdims=True)
        return ((x32 - mu) * jax.lax.rsqrt(var + 1e-12) * g + b).astype(x.dtype)

    def layer(x, p):
        xb = x.astype(jnp.bfloat16)
        qkv = xb @ p["qkv_w"].astype(jnp.bfloat16) + p["qkv_b"].astype(jnp.bfloat16)
        qkv = qkv.reshape(B, S, 3, nh, dh).transpose(2, 0, 3, 1, 4)
        q, k, v = qkv[0], qkv[1], qkv[2]
        s = jnp.einsum("bhqd,bhkd->bhqk", q, k) * sm
        pr = jax.nn.softmax(s.astype(jnp.float32), -1).astype(jnp.bfloat16)
        o = jnp.einsum("bhqk,bhkd->bhqd", pr, v)
        o = o.transpose(0, 2, 1, 3).reshape(B, S, H)
        a = o @ p["o_w"].astype(jnp.bfloat16) + p["o_b"].astype(jnp.bfloat16)
        x = ln(x + a, p["ln1_g"], p["ln1_b"])
        xb = x.astype(jnp.bfloat16)
        h = jax.nn.gelu(xb @ p["f1_w"].astype(jnp.bfloat16) + p["f1_b"].astype(jnp.bfloat16))
        f = h @ p["f2_w"].astype(jnp.bfloat16) + p["f2_b"].astype(jnp.bfloat16)
        return ln(x + f, p["ln2_g"], p["ln2_b"])

    def loss_fn(params):
        x = params["emb"][ids] + params["pos"][None, :, :]
        x = x.astype(jnp.bfloat16)
        for i in range(L):
            x = layer(x, params[f"l{i}"])
        logits = (x @ params["head_w"].astype(jnp.bfloat16)).astype(jnp.float32)
        logits = logits + params["head_b"]
        lse = jax.nn.logsumexp(logits, -1)
        nll = lse - jnp.take_along_axis(logits, labels[..., None], -1)[..., 0]
        return nll.mean()

    def step(params, mom, vel):
        loss, grads = jax.value_and_grad(loss_fn)(params)
        tm = jax.tree_util.tree_map
        mom = tm(lambda g, m: 0.9 * m + 0.1 * g, grads, mom)
        vel = tm(lambda g, v: 0.999 * v + 0.001 * g * g, grads, vel)
        params = tm(lambda p, m, v: p - 1e-4 * m / (jnp.sqrt(v) + 1e-8),
                    params, mom, vel)
        return params, mom, vel, loss

    zeros = jax.tree_util.tree_map(jnp.zeros_like, params)
    lowered = jax.jit(step).lower(params, zeros, zeros)
    compiled = lowered.compile()
    import os
    if os.environ.get("DUMP_HLO"):
        open("/tmp/hlo_replica.txt", "w").write(compiled.as_text())
    return compiled.cost_analysis()


def show(tag, ca):
    keys = ["flops", "bytes accessed", "transcendentals",
            "bytes accessed output", "optimal_seconds"]
    parts = []
    for k in keys:
        if k in ca:
            parts.append(f"{k}={ca[k]:.3e}")
    print(tag, "  ".join(parts))


ca_r = replica_cost()
show("replica  :", ca_r)
ca_f = framework_cost()
show("framework:", ca_f)
for k in ("flops", "bytes accessed", "transcendentals"):
    if k in ca_r and k in ca_f and ca_r[k]:
        print(f"{k} ratio fw/replica: {ca_f[k]/ca_r[k]:.3f}")
