"""Pure-JAX ResNet-50 train step ceiling probe: bf16 NHWC, momentum SGD.

Establishes what the chip+XLA can do on this model independent of the
framework path. Usage: python tools/_rn_pure.py [batch] [nchw|nhwc] [f32|bf16]
"""
import sys, time
sys.path.insert(0, "/root/repo")
import jax, jax.numpy as jnp, numpy as np
from functools import partial

BATCH = int(sys.argv[1]) if len(sys.argv) > 1 else 128
LAYOUT = sys.argv[2] if len(sys.argv) > 2 else "nhwc"
DT = jnp.bfloat16 if (len(sys.argv) <= 3 or sys.argv[3] == "bf16") else jnp.float32

NHWC = LAYOUT == "nhwc"
DN = ("NHWC", "HWIO", "NHWC") if NHWC else ("NCHW", "OIHW", "NCHW")
CAX = 3 if NHWC else 1

rng = np.random.default_rng(0)


def conv_w(k, ci, co):
    w = rng.standard_normal((k, k, ci, co), dtype=np.float32) * np.sqrt(2.0 / (k * k * ci))
    if not NHWC:
        w = w.transpose(3, 2, 0, 1)
    return jnp.asarray(w, DT)


def conv(x, w, s=1):
    k = w.shape[0] if NHWC else w.shape[2]
    return jax.lax.conv_general_dilated(
        x, w, (s, s), [(k // 2, k // 2)] * 2, dimension_numbers=DN)


def bn(x, p):
    scale, bias = p
    xf = x.astype(jnp.float32)
    axes = tuple(i for i in range(4) if i != CAX)
    m = jnp.mean(xf, axis=axes)
    v = jnp.mean(jnp.square(xf), axis=axes) - jnp.square(m)
    sh = [1, 1, 1, 1]; sh[CAX] = -1
    y = (xf - m.reshape(sh)) / jnp.sqrt(v.reshape(sh) + 1e-5)
    return (y * scale.reshape(sh) + bias.reshape(sh)).astype(x.dtype)


def make_params():
    depths = [3, 4, 6, 3]
    chans = [64, 128, 256, 512]
    P = {"stem": (conv_w(7, 3, 64), (jnp.ones(64), jnp.zeros(64)))}
    ci = 64
    for si, (d, c) in enumerate(zip(depths, chans)):
        for bi in range(d):
            pre = f"s{si}b{bi}"
            co = c * 4
            stride = 2 if (bi == 0 and si > 0) else 1
            blk = {
                "c1": conv_w(1, ci, c), "b1": (jnp.ones(c), jnp.zeros(c)),
                "c2": conv_w(3, c, c), "b2": (jnp.ones(c), jnp.zeros(c)),
                "c3": conv_w(1, c, co), "b3": (jnp.ones(co), jnp.zeros(co)),
            }
            if ci != co:
                blk["proj"] = conv_w(1, ci, co)
                blk["bproj"] = (jnp.ones(co), jnp.zeros(co))
            blk["stride"] = stride
            P[pre] = blk
            ci = co
    P["fc"] = (jnp.asarray(rng.standard_normal((2048, 1000), dtype=np.float32) * 0.01, DT),
               jnp.zeros(1000, DT))
    return P


STRIDES = {}

def forward(P, x, labels):
    x = conv(x, P["stem"][0], 2)
    x = jax.nn.relu(bn(x, P["stem"][1]))
    window = (1, 3, 3, 1) if NHWC else (1, 1, 3, 3)
    strides = (1, 2, 2, 1) if NHWC else (1, 1, 2, 2)
    pads = [(0, 0), (1, 1), (1, 1), (0, 0)] if NHWC else [(0, 0), (0, 0), (1, 1), (1, 1)]
    x = jax.lax.reduce_window(x, -jnp.inf, jax.lax.max, window, strides, pads)
    for si, d in enumerate([3, 4, 6, 3]):
        for bi in range(d):
            blk = P[f"s{si}b{bi}"]
            stride = STRIDES[f"s{si}b{bi}"]
            idn = x
            y = jax.nn.relu(bn(conv(x, blk["c1"], 1), blk["b1"]))
            y = jax.nn.relu(bn(conv(y, blk["c2"], stride), blk["b2"]))
            y = bn(conv(y, blk["c3"], 1), blk["b3"])
            if "proj" in blk:
                idn = bn(conv(idn, blk["proj"], stride), blk["bproj"])
            x = jax.nn.relu(y + idn)
    x = jnp.mean(x.astype(jnp.float32), axis=(1, 2) if NHWC else (2, 3))
    w, b = P["fc"]
    logits = x.astype(DT) @ w + b
    lsm = jax.nn.log_softmax(logits.astype(jnp.float32))
    return -jnp.mean(jnp.take_along_axis(lsm, labels[:, None], axis=1))


def main():
    P = make_params()
    for k, v in list(P.items()):
        if isinstance(v, dict):
            STRIDES[k] = v.pop("stride")

    x = jnp.asarray(rng.standard_normal((BATCH, 224, 224, 3) if NHWC else (BATCH, 3, 224, 224),
                                        dtype=np.float32), DT)
    labels = jnp.asarray(rng.integers(0, 1000, BATCH).astype(np.int32))

    mom = jax.tree.map(jnp.zeros_like, P)

    @jax.jit
    def step(P, mom, x, labels):
        loss, g = jax.value_and_grad(forward)(P, x, labels)
        mom = jax.tree.map(lambda m, gg: 0.9 * m + gg.astype(m.dtype), mom, g)
        P = jax.tree.map(lambda p, m: p - (0.1 * m).astype(p.dtype), P, mom)
        return P, mom, loss

    _drain = jax.jit(lambda v: v.reshape(-1)[0])
    P, mom, loss = step(P, mom, x, labels)
    np.asarray(_drain(P["fc"][1]))
    N = 20
    t0 = time.perf_counter()
    for _ in range(N):
        P, mom, loss = step(P, mom, x, labels)
    np.asarray(_drain(P["fc"][1]))
    dt = (time.perf_counter() - t0) / N
    img_s = BATCH / dt
    from bench import RN50_FWD_FLOPS_PER_IMG
    mfu = 3 * RN50_FWD_FLOPS_PER_IMG * img_s / 197e12
    print(f"pure-jax RN50 {LAYOUT} {DT.__name__} batch={BATCH}: {dt*1e3:.1f} ms/step, "
          f"{img_s:.0f} img/s, MFU {mfu*100:.1f}%", flush=True)


main()
