"""Space-to-depth stem for ResNet-50: equivalence proof + full-model A/B.

VERDICT r4 #1: the measured RN50 bottleneck is narrow-channel MXU fill
(stem 7x7 conv has a 3-channel contraction; s0/s1 at 38-57 TF/s). The
standard TPU counter-move (MLPerf RN50 submissions) repacks the input
image 224x224x3 -> 112x112x12 with a 2x2 space-to-depth and folds the
7x7-stride-2 stem conv into an EXACTLY equivalent 4x4-stride-1 conv on
the repacked tensor:

  y[o] = sum_u w[u] x[2o-3+u]          (7-tap, stride 2, pad 3)
  with n = 2(o+j)+p  (j = s2d row, p = phase in {0,1})
  => 2j+p = u-3, u in [0,6]  =>  j in [-2,1]  (4 taps, pad (2,1))
  => w2[j+2, p] = w8[2(j+2)+p]  where w8 = [0, w[0..6]]  (pad 7->8 front)

The kernel repack [8,8,3,64] -> [4,2,4,2,3,64] -> [4,4,(2,2,3)=12,64]
matches the activation repack [B,112,2,112,2,3] -> [B,112,112,12].
Widens the stem contraction 3 -> 12 (folded k*k*ci: 147 -> 192) and
quarters the number of output rows the conv emitter must mask for
stride. Run: python tools/_rn_s2d.py [batch]
"""
import sys
import time

sys.path.insert(0, "/root/repo")
import jax
import jax.numpy as jnp
import numpy as np

B = int(sys.argv[1]) if len(sys.argv) > 1 else 128
DT = jnp.bfloat16
DN = ("NHWC", "HWIO", "NHWC")

rng = np.random.default_rng(0)
_drain = jax.jit(lambda v: v.reshape(-1)[0])


def conv_w(k, ci, co):
    w = rng.standard_normal((k, k, ci, co), dtype=np.float32) * \
        np.sqrt(2.0 / (k * k * ci))
    return jnp.asarray(w, DT)


def conv(x, w, s=1, pad=None):
    k = w.shape[0]
    if pad is None:
        pad = [(k // 2, k // 2)] * 2
    return jax.lax.conv_general_dilated(x, w, (s, s), pad,
                                        dimension_numbers=DN)


def space_to_depth(x):
    """[B, H, W, C] -> [B, H/2, W/2, 4C], channel = (ph, pw, c)."""
    b, h, w, c = x.shape
    x = x.reshape(b, h // 2, 2, w // 2, 2, c)
    x = x.transpose(0, 1, 3, 2, 4, 5)
    return x.reshape(b, h // 2, w // 2, 4 * c)


def fold_stem_kernel(w7):
    """[7,7,3,64] stride-2 kernel -> [4,4,12,64] stride-1 s2d kernel."""
    w8 = jnp.pad(w7.astype(jnp.float32), ((1, 0), (1, 0), (0, 0), (0, 0)))
    w8 = w8.reshape(4, 2, 4, 2, 3, 64).transpose(0, 2, 1, 3, 4, 5)
    return w8.reshape(4, 4, 12, 64).astype(w7.dtype)


def bn(x, p):
    scale, bias = p
    xf = x.astype(jnp.float32)
    m = jnp.mean(xf, axis=(0, 1, 2))
    v = jnp.mean(jnp.square(xf), axis=(0, 1, 2)) - jnp.square(m)
    y = (xf - m) / jnp.sqrt(v + 1e-5)
    return (y * scale + bias).astype(x.dtype)


def check_equivalence():
    x = jnp.asarray(rng.standard_normal((4, 224, 224, 3), dtype=np.float32),
                    DT)
    w7 = conv_w(7, 3, 64)
    ref = conv(x, w7, 2)                               # [4,112,112,64]
    xs = space_to_depth(x)                             # [4,112,112,12]
    w4 = fold_stem_kernel(w7)
    got = conv(xs, w4, 1, pad=[(2, 1), (2, 1)])        # [4,112,112,64]
    err = jnp.max(jnp.abs(ref.astype(jnp.float32) - got.astype(jnp.float32)))
    scale = jnp.max(jnp.abs(ref.astype(jnp.float32)))
    print(f"stem fold equivalence: shapes {ref.shape}=={got.shape}, "
          f"max abs err {err:.2e} (max |ref| {scale:.2f}, "
          f"rel {err/scale:.2e})", flush=True)
    assert ref.shape == got.shape
    assert err / scale < 2e-2, "s2d stem fold diverges from 7x7-s2 conv"


DEPTHS = [3, 4, 6, 3]
CHANS = [64, 128, 256, 512]
STRIDES = {}


def make_params(s2d):
    P = {"stem": (conv_w(4, 12, 64) if s2d else conv_w(7, 3, 64),
                  (jnp.ones(64), jnp.zeros(64)))}
    ci = 64
    for si, (d, c) in enumerate(zip(DEPTHS, CHANS)):
        for bi in range(d):
            pre = f"s{si}b{bi}"
            co = c * 4
            STRIDES[pre] = 2 if (bi == 0 and si > 0) else 1
            blk = {
                "c1": conv_w(1, ci, c), "b1": (jnp.ones(c), jnp.zeros(c)),
                "c2": conv_w(3, c, c), "b2": (jnp.ones(c), jnp.zeros(c)),
                "c3": conv_w(1, c, co), "b3": (jnp.ones(co), jnp.zeros(co)),
            }
            if ci != co:
                blk["proj"] = conv_w(1, ci, co)
                blk["bproj"] = (jnp.ones(co), jnp.zeros(co))
            P[pre] = blk
            ci = co
    P["fc"] = (jnp.asarray(
        rng.standard_normal((2048, 1000), dtype=np.float32) * 0.01, DT),
        jnp.zeros(1000, DT))
    return P


def forward(P, x, labels, s2d):
    if s2d:
        x = conv(x, P["stem"][0], 1, pad=[(2, 1), (2, 1)])
    else:
        x = conv(x, P["stem"][0], 2)
    x = jax.nn.relu(bn(x, P["stem"][1]))
    x = jax.lax.reduce_window(x, -jnp.inf, jax.lax.max, (1, 3, 3, 1),
                              (1, 2, 2, 1),
                              [(0, 0), (1, 1), (1, 1), (0, 0)])
    for si, d in enumerate(DEPTHS):
        for bi in range(d):
            blk = P[f"s{si}b{bi}"]
            s = STRIDES[f"s{si}b{bi}"]
            idn = x
            y = jax.nn.relu(bn(conv(x, blk["c1"], 1), blk["b1"]))
            y = jax.nn.relu(bn(conv(y, blk["c2"], s), blk["b2"]))
            y = bn(conv(y, blk["c3"], 1), blk["b3"])
            if "proj" in blk:
                idn = bn(conv(idn, blk["proj"], s), blk["bproj"])
            x = jax.nn.relu(y + idn)
    x = jnp.mean(x.astype(jnp.float32), axis=(1, 2))
    w, b = P["fc"]
    logits = x.astype(DT) @ w + b
    lsm = jax.nn.log_softmax(logits.astype(jnp.float32))
    return -jnp.mean(jnp.take_along_axis(lsm, labels[:, None], axis=1))


def timed(s2d, include_repack):
    P = make_params(s2d)
    labels = jnp.asarray(rng.integers(0, 1000, B).astype(np.int32))
    x_raw = jnp.asarray(
        rng.standard_normal((B, 224, 224, 3), dtype=np.float32), DT)
    mom = jax.tree.map(jnp.zeros_like, P)

    @jax.jit
    def step(P, mom, x, labels):
        if s2d and include_repack:
            x = space_to_depth(x)  # on-device repack inside the step
        loss, g = jax.value_and_grad(
            lambda p: forward(p, x, labels, s2d))(P)
        mom = jax.tree.map(lambda m, gg: 0.9 * m + gg.astype(m.dtype),
                           mom, g)
        P = jax.tree.map(lambda p, m: p - (0.1 * m).astype(p.dtype), P, mom)
        return P, mom, loss

    x = x_raw if (not s2d or include_repack) else space_to_depth(x_raw)
    P, mom, loss = step(P, mom, x, labels)
    np.asarray(_drain(P["fc"][1]))
    N = 20
    best = np.inf
    for _ in range(2):
        t0 = time.perf_counter()
        for _ in range(N):
            P, mom, loss = step(P, mom, x, labels)
        np.asarray(_drain(P["fc"][1]))
        best = min(best, (time.perf_counter() - t0) / N)
    return best


def main():
    check_equivalence()
    from bench import RN50_FWD_FLOPS_PER_IMG
    rn = 3 * RN50_FWD_FLOPS_PER_IMG * B
    rows = [("baseline 7x7-s2 stem", timed(False, False)),
            ("s2d stem (host repack)", timed(True, False)),
            ("s2d stem (device repack in-step)", timed(True, True))]
    print("\n| variant | ms/step | img/s | MFU |")
    print("|---|---|---|---|")
    for name, dt in rows:
        print(f"| {name} | {dt*1e3:.1f} | {B/dt:.0f} | "
              f"{rn/dt/197e12*100:.1f}% |", flush=True)


if __name__ == "__main__":
    main()
