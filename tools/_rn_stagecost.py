"""In-situ ResNet-50 stage costs by DIFFERENTIAL measurement.

The scan-chained per-conv microbench has a ~1 ms per-iteration floor (see
_conv_inner.py results: every small conv reads ~1 ms regardless of FLOPs),
so isolated timings cannot decompose a 53 ms step. Instead this times the
full pure-JAX train step of TRUNCATED models (stem only, stem+s0, ...,
full): successive differences give each stage's fwd+bwd cost inside the
real fused XLA graph — no dispatch floor, no CSE hazard.

Against each stage's analytic roofline time
  t_roof = max(FLOPs / measured_matmul_peak, bytes / measured_bw)
(x3 for train, conv bytes + one BN/ReLU/residual pass) this shows which
stages sit at their arithmetic-intensity ceiling and what the whole-model
MFU ceiling is. Run: python tools/_rn_stagecost.py
"""
import sys
import time

sys.path.insert(0, "/root/repo")
import jax
import jax.numpy as jnp
import numpy as np

B = 128
DT = jnp.bfloat16
DN = ("NHWC", "HWIO", "NHWC")

rng = np.random.default_rng(0)
_drain = jax.jit(lambda v: v.reshape(-1)[0])


def conv_w(k, ci, co):
    w = rng.standard_normal((k, k, ci, co), dtype=np.float32) * \
        np.sqrt(2.0 / (k * k * ci))
    return jnp.asarray(w, DT)


def conv(x, w, s=1):
    k = w.shape[0]
    return jax.lax.conv_general_dilated(
        x, w, (s, s), [(k // 2, k // 2)] * 2, dimension_numbers=DN)


def bn(x, p):
    scale, bias = p
    xf = x.astype(jnp.float32)
    m = jnp.mean(xf, axis=(0, 1, 2))
    v = jnp.mean(jnp.square(xf), axis=(0, 1, 2)) - jnp.square(m)
    y = (xf - m) / jnp.sqrt(v + 1e-5)
    return (y * scale + bias).astype(x.dtype)


DEPTHS = [3, 4, 6, 3]
CHANS = [64, 128, 256, 512]


def make_params(n_stages):
    P = {"stem": (conv_w(7, 3, 64), (jnp.ones(64), jnp.zeros(64)))}
    strides = {}
    ci = 64
    for si in range(n_stages):
        d, c = DEPTHS[si], CHANS[si]
        for bi in range(d):
            pre = f"s{si}b{bi}"
            co = c * 4
            strides[pre] = 2 if (bi == 0 and si > 0) else 1
            blk = {
                "c1": conv_w(1, ci, c), "b1": (jnp.ones(c), jnp.zeros(c)),
                "c2": conv_w(3, c, c), "b2": (jnp.ones(c), jnp.zeros(c)),
                "c3": conv_w(1, c, co),
                "b3": (jnp.ones(co), jnp.zeros(co)),
            }
            if ci != co:
                blk["proj"] = conv_w(1, ci, co)
                blk["bproj"] = (jnp.ones(co), jnp.zeros(co))
            P[pre] = blk
            ci = co
    return P, strides


def forward(P, strides, n_stages, x):
    x = conv(x, P["stem"][0], 2)
    x = jax.nn.relu(bn(x, P["stem"][1]))
    x = jax.lax.reduce_window(x, -jnp.inf, jax.lax.max, (1, 3, 3, 1),
                              (1, 2, 2, 1),
                              [(0, 0), (1, 1), (1, 1), (0, 0)])
    for si in range(n_stages):
        for bi in range(DEPTHS[si]):
            blk = P[f"s{si}b{bi}"]
            s = strides[f"s{si}b{bi}"]
            idn = x
            y = jax.nn.relu(bn(conv(x, blk["c1"], 1), blk["b1"]))
            y = jax.nn.relu(bn(conv(y, blk["c2"], s), blk["b2"]))
            y = bn(conv(y, blk["c3"], 1), blk["b3"])
            if "proj" in blk:
                idn = bn(conv(idn, blk["proj"], s), blk["bproj"])
            x = jax.nn.relu(y + idn)
    return jnp.mean(x.astype(jnp.float32))


def timed_step(n_stages, x):
    P, strides = make_params(n_stages)

    @jax.jit
    def step(P, x):
        loss, g = jax.value_and_grad(
            lambda p: forward(p, strides, n_stages, x))(P)
        P = jax.tree.map(lambda p, gg: p - 0.1 * gg.astype(p.dtype), P, g)
        return P, loss

    P, loss = step(P, x)
    np.asarray(_drain(P["stem"][0]))
    N = 20
    t0 = time.perf_counter()
    for _ in range(N):
        P, loss = step(P, x)
    np.asarray(_drain(P["stem"][0]))
    return (time.perf_counter() - t0) / N


def stage_roofline(si, matmul_tfs, bw):
    """Analytic fwd FLOPs and bytes for stage si (convs + one elementwise
    pass per BN/ReLU/residual tensor)."""
    d, c = DEPTHS[si], CHANS[si]
    hw_in = [56, 56, 28, 14][si]
    hw = [56, 28, 14, 7][si]
    ci = 64 if si == 0 else CHANS[si - 1] * 4
    co = c * 4
    flops = 0
    bytes_ = 0
    for bi in range(d):
        cin = ci if bi == 0 else co
        h_in = hw_in if bi == 0 else hw
        # c1 (on the input resolution), c2 (strided to hw), c3
        trio = [(1, cin, c, h_in, h_in),
                (3, c, c, h_in if bi == 0 else hw, hw),
                (1, c, co, hw, hw)]
        if bi == 0:
            trio.append((1, cin, co, h_in, hw))  # projection
        for k, a, b_, hin, hout in trio:
            flops += 2 * B * a * b_ * k * k * hout * hout
            bytes_ += 2 * (B * a * hin * hin + a * b_ * k * k
                           + B * b_ * hout * hout)
        # elementwise: BN+ReLU on c/c/co maps + residual add
        ew = B * (c * (hw if bi else h_in) ** 2 + c * hw * hw
                  + 2 * co * hw * hw)
        bytes_ += 2 * 2 * ew  # read+write, bf16
    t = max(flops / (matmul_tfs * 1e12), bytes_ / (bw * 1e9))
    return flops, bytes_, t


def main():
    from _rn_roofline import measure_matmul_peak, measure_bw

    matmul_tfs = measure_matmul_peak()
    bw = measure_bw()
    print(f"measured peaks: matmul {matmul_tfs:.1f} TF/s, HBM {bw:.0f} GB/s")

    x = jnp.asarray(rng.standard_normal((B, 224, 224, 3), dtype=np.float32),
                    DT)
    times = []
    for n in range(5):
        t = timed_step(n, x)
        times.append(t)
        print(f"prefix stem+{n} stages: {t*1e3:.1f} ms/step", flush=True)

    print("\n| stage | in-situ ms (train) | roofline ms (x3) | ratio |")
    print("|---|---|---|---|")
    total_roof = times[0]  # stem prefix cost taken as measured
    for si in range(4):
        dt = (times[si + 1] - times[si]) * 1e3
        fl, by, troof = stage_roofline(si, matmul_tfs, bw)
        print(f"| s{si} ({DEPTHS[si]} blocks) | {dt:.1f} | "
              f"{3*troof*1e3:.1f} | {dt/(3*troof*1e3):.2f}x |", flush=True)
        total_roof += 3 * troof
    print(f"\nfull-model measured: {times[4]*1e3:.1f} ms; "
          f"roofline total (stem measured + stages at roofline): "
          f"{total_roof*1e3:.1f} ms")
    from bench import RN50_FWD_FLOPS_PER_IMG
    rn = 3 * RN50_FWD_FLOPS_PER_IMG * B
    print(f"MFU: measured {rn/times[4]/197e12:.3f}, "
          f"at-roofline ceiling {rn/total_roof/197e12:.3f}")


if __name__ == "__main__":
    main()
