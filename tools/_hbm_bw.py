"""Raw HBM bandwidth probe: big elementwise scale inside scan."""
import sys, time
sys.path.insert(0, "/root/repo")
import jax, jax.numpy as jnp, numpy as np

_drain = jax.jit(lambda v: v.reshape(-1)[0])
def drain(x): return np.asarray(_drain(x))

for mb in (64, 256, 1024):
    n = mb * 1024 * 1024 // 2  # bf16 elements
    x = jnp.full((n,), 0.5, jnp.bfloat16)
    K = 20

    @jax.jit
    def f(x):
        def body(c, _):
            return c * jnp.asarray(1.000001, jnp.bfloat16), None
        y, _ = jax.lax.scan(body, x, None, length=K)
        return y

    drain(f(x))
    t0 = time.perf_counter()
    for _ in range(5):
        y = f(x)
    drain(y)
    dt = (time.perf_counter() - t0) / 5 / K
    bw = 2 * mb / 1024 / dt  # read + write, GB/s
    print(f"{mb:>5} MB scale: {dt*1e3:7.3f} ms/iter, {bw:6.0f} GB/s", flush=True)
