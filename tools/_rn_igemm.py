"""ResNet-50 conv-lever A/B: implicit-GEMM lowering x fused one-pass BN stats.

END-TO-END ONLY, per the r5 methodology: chained per-op microbenches are
twice-proven poisoned on this stack (the r3 "conv ceiling" artifact and the
r5 xent harness-pollution finding, PERF.md) — every arm here is a full
framework train step timed with bench.py's own protocol (async dispatch,
drain-synchronized windows, best-of-N).

Arms:
    off   : direct conv + two-pass batch_norm (the r5 bench configuration)
    auto  : FLAGS_conv_implicit_gemm=auto (per-shape cost model) + fused BN
    igemm : implicit GEMM forced ON for every conv, two-pass BN (isolates
            the im2col lowering, including shapes the cost model rejects)
    bnfuse: direct conv + fused one-pass BN statistics (isolates the pass)

Run on the chip:  python tools/_rn_igemm.py [--iters 50]
Prints one JSON line per arm plus a summary; feed the numbers to PERF.md r6.
"""
from __future__ import annotations

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax  # noqa: E402

import bench  # noqa: E402
from paddle_tpu import flags, tuning  # noqa: E402
from paddle_tpu.tuning.learned import store as learned_store  # noqa: E402
from tools import _timing  # noqa: E402

ARMS = {
    "off": ("off", False),
    "auto": ("auto", True),
    "igemm": ("on", False),
    "bnfuse": ("off", True),
}


def main():
    on_tpu = jax.devices()[0].platform == "tpu"
    peak = bench._peak_flops(jax.devices()[0])
    results = {}
    for name, (igemm, fuse) in ARMS.items():
        flags.set_flags({"conv_implicit_gemm": igemm, "bn_fuse_stats": fuse})
        img_s, mfu, windows = bench._resnet_arm(on_tpu, peak)
        results[name] = {"img_s": round(img_s, 1), "mfu": round(mfu, 4),
                         "windows_img_s": windows,
                         "band": round(_timing.interference_band(windows), 4)}
        print(json.dumps({"arm": name, **results[name]}), flush=True)
        if learned_store.recording_enabled(tool=True):
            # windows are images/s; store seconds-per-image so the record
            # reads like every other timing row
            learned_store.record(
                "ab.resnet50", "workload=resnet50 lever=conv", "-",
                tuning.device_kind(), name,
                windows_s=[1.0 / w for w in windows if w > 0],
                band=results[name]["band"], source="ab")
    base = results["off"]["img_s"]
    # keep-or-retire per arm on the shared verdict rule (tools/_timing.py):
    # seconds-per-image medians, band floored at gate.py's 5%
    print(json.dumps({
        "summary": {k: round(v["img_s"] / base, 4) for k, v in results.items()},
        "verdicts": {k: _timing.ab_verdict(1.0 / base, 1.0 / v["img_s"])
                     for k, v in results.items() if k != "off"},
        "note": "ratios vs the 'off' arm; >1.0 = lever wins end-to-end",
    }), flush=True)


if __name__ == "__main__":
    main()
