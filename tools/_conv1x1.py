"""1x1 conv as lax.conv vs reshaped matmul, inside one jit."""
import sys, time
sys.path.insert(0, "/root/repo")
import jax, jax.numpy as jnp, numpy as np

_drain = jax.jit(lambda v: v.reshape(-1)[0])
def drain(x): return np.asarray(_drain(x))

B = 128
K_INNER = 20
SHAPES = [(64, 64, 56, 56), (64, 256, 56, 56), (256, 64, 56, 56),
          (512, 128, 28, 28), (1024, 256, 14, 14), (2048, 512, 7, 7)]
for (ci, co, h, w) in SHAPES:
    fl = 2 * B * co * ci * h * w * K_INNER
    x = jnp.full((B, h, w, ci), 0.5, jnp.bfloat16)
    wt = jnp.full((1, 1, ci, co), 0.001, jnp.bfloat16)
    wm = jnp.full((ci, co), 0.001, jnp.bfloat16)
    wb = jnp.full((co, ci), 0.001, jnp.bfloat16)  # back-projection to keep channel count

    @jax.jit
    def f_conv(x, wt, wb):
        def body(c, _):
            y = jax.lax.conv_general_dilated(c, wt, (1, 1), [(0, 0)] * 2,
                                             dimension_numbers=("NHWC", "HWIO", "NHWC"))
            return jnp.einsum("bhwd,dc->bhwc", y, wb) * 0.01, None
        y, _ = jax.lax.scan(body, x, None, length=K_INNER)
        return y

    @jax.jit
    def f_mm(x, wm, wb):
        def body(c, _):
            y = c.reshape(-1, ci) @ wm
            return (y @ wb * 0.01).reshape(B, h, w, ci), None
        y, _ = jax.lax.scan(body, x, None, length=K_INNER)
        return y

    for name, f, args in (("conv", f_conv, (x, wt, wb)), ("mm  ", f_mm, (x, wm, wb))):
        drain(f(*args))
        t0 = time.perf_counter()
        for _ in range(5):
            y = f(*args)
        drain(y)
        dt = (time.perf_counter() - t0) / 5
        # fl counts only the forward 1x1; the back-projection doubles it
        print(f"{ci:>4}->{co:<4} {h:>2}x{w:<2} {name}: {dt/K_INNER*1e3:7.3f} ms {2*fl/dt/1e12:6.1f} TF/s", flush=True)
