"""A/B: synchronous per-step feeding vs the async feed/dispatch pipeline.

Synthetic slow-host workload, CPU-runnable: the reader sleeps `--host-ms`
per batch (standing in for file parse / decode cost) before yielding numpy
feeds. Arm A runs the classic loop — host produces a batch, Executor.run
places it, a per-step fetch drains the device. Arm B runs the pipeline —
DeviceLoader stages batches from a background thread and run_async keeps up
to FLAGS_max_inflight_steps dispatched without a host drain. When host cost
and step cost are comparable, B should approach max(host, step) per batch
while A pays host + step; the printed per-stage counters show where each
arm's wall time went.

    python tools/_pipeline_ab.py [--host-ms 4] [--batches 60] [--window 4]
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import paddle_tpu as pt
from paddle_tpu import layers as L
from paddle_tpu import profiler
from paddle_tpu.pipeline import DeviceLoader
from tools import _timing

BATCH, DIM, HIDDEN = 256, 64, 512


def build_program():
    main_p, startup = pt.Program(), pt.Program()
    with pt.program_guard(main_p, startup):
        x = L.data(name="x", shape=[DIM], dtype="float32")
        y = L.data(name="y", shape=[1], dtype="float32")
        h = L.fc(x, size=HIDDEN, act="relu")
        loss = L.reduce_mean(L.square_error_cost(L.fc(h, size=1), y))
        pt.optimizer.SGD(learning_rate=0.01).minimize(loss)
    return main_p, startup, loss


def slow_host_reader(n_batches: int, host_ms: float):
    rng = np.random.default_rng(0)

    def gen():
        for _ in range(n_batches):
            time.sleep(host_ms / 1e3)  # synthetic parse/decode cost
            yield {"x": rng.standard_normal((BATCH, DIM)).astype(np.float32),
                   "y": rng.standard_normal((BATCH, 1)).astype(np.float32)}

    return gen


def run_arm(pipelined: bool, n_batches: int, host_ms: float, window: int):
    main_p, startup, loss = build_program()
    exe = pt.Executor()
    drain = main_p.all_parameters()[-1].name
    gen = slow_host_reader(n_batches, host_ms)
    with pt.scope_guard(pt.Scope()):
        exe.run(startup)
        exe.run(main_p, feed=next(iter(gen())), fetch_list=[loss])  # compile
        np.asarray(pt.global_scope().find_var(drain))
        profiler.stage_counters(reset=True)

        def epoch():
            if pipelined:
                pt.flags.set_flags({"max_inflight_steps": window})
                for feed in DeviceLoader(gen, depth=window):
                    exe.run_async(main_p, feed=feed, fetch_list=[loss])
                exe.wait()
            else:
                for feed in gen():
                    (lv,) = exe.run(main_p, feed=feed, fetch_list=[loss])
                    float(np.asarray(lv))  # the per-step host drain
            np.asarray(pt.global_scope().find_var(drain))

        dt, _ = _timing.time_call(epoch)  # shared tools/ timing protocol
    counters = {k: round(v["seconds"], 4)
                for k, v in profiler.stage_counters(reset=True).items()}
    return n_batches * BATCH / dt, counters


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--host-ms", type=float, default=4.0)
    ap.add_argument("--batches", type=int, default=60)
    ap.add_argument("--window", type=int, default=4)
    args = ap.parse_args()

    sync_ex_s, sync_c = run_arm(False, args.batches, args.host_ms, args.window)
    pipe_ex_s, pipe_c = run_arm(True, args.batches, args.host_ms, args.window)
    print(json.dumps({
        "metric": "pipeline_ab_examples_per_sec",
        "sync_ex_s": round(sync_ex_s, 1),
        "pipelined_ex_s": round(pipe_ex_s, 1),
        "speedup": round(pipe_ex_s / sync_ex_s, 3),
        "sync_stage_seconds": sync_c,
        "pipelined_stage_seconds": pipe_c,
        "config": {"batch": BATCH, "batches": args.batches,
                   "host_ms": args.host_ms, "window": args.window},
    }))


if __name__ == "__main__":
    main()
