"""Offline A/B sweeper: populate the tuning DB with measured verdicts.

The tools/_rn_igemm.py loop made generic (ISSUE 6): for every shape in the
sweep set, each candidate implementation is timed with the shared
tools/_timing.py protocol (warmup, median-of-windows, interference band)
and the keep-or-retire verdict is written into the persistent decision DB
(paddle_tpu/tuning/) that FLAGS_tuning_mode=consult reads at minimize()/
trace time. A tie inside the band records the ANALYTIC decision — a noise
margin must never overwrite a cost model with a coin flip — and every entry
carries its measured medians + band so a later reader can re-judge it.

Sweeps:
  conv       — direct vs implicit-GEMM lowering per conv shape (default
               set: the PERF.md r6 ResNet-50 cost-table shapes; add yours
               with repeated --conv-shape n,h,w,cin,cout,kh,kw,sh,sw).
  attention  — XLA einsum composition vs the short-seq Pallas kernels
               (seq<=128 and the 128-multiple kernel) vs the bundled flash
               kernel per (batch, heads, seq, head_dim) (default: the
               bench.py BERT s128 and s512 configs). Arms a platform
               cannot run (Pallas off-TPU) are skipped.
  epilogue   — XLA composition vs the fused normalize+affine+act(+residual)
               Pallas kernel (ops/pallas_kernels/epilogue.py) over the
               PERF.md r6 cost-table conv OUTPUT shapes (the BN apply tail,
               NHWC + NCHW, with and without residual) and the bench BERT
               s128 layer-norm rows.
  embedding  — tiered-embedding cache geometry (ISSUE 10): slot-count and
               prefetch-width arms per table geometry, each arm a real
               one-table training loop (resolve + install + gather +
               scatter-add through the Executor — the resolution cost IS
               part of what the geometry trades), driven by a seeded zipf
               id stream. Verdicts land as 'embedding|table=..' keys the
               minimize()-time rewrite consults.
  candidates — every `candidate` conv2d / attention / epilogue / embedding
               entry a FLAGS_tuning_mode=sweep run recorded into the DB
               gets measured and upgraded.

These are per-shape microbenches — TVM-style schedule search, deliberately
NOT the chained-per-op instrument PERF.md retired (each arm here is one
jitted fwd+bwd of a single op, not a chain whose interactions poison the
sum). The end-to-end confirmation stays where it always was: bench.py's
`resnet50_lever_ab` and tools/_rn_igemm.py re-measure the composed effect
every round, and gate.py arbitrates.

    python tools/tune.py --db TUNING_DB.json                  # full sweep
    python tools/tune.py --db x.json --what conv --iters 20
    python tools/tune.py --db x.json --what candidates        # upgrade
"""
from __future__ import annotations

import argparse
import json
import os
import re
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402

from paddle_tpu import tuning  # noqa: E402
from paddle_tpu.ops.nn_ops import (_conv2d_igemm_f32,  # noqa: E402
                                   _igemm_predict_win)
from paddle_tpu.tuning.learned import store as learned_store  # noqa: E402
from tools import _timing  # noqa: E402

# The PERF.md r6 cost-table shapes (b128 NHWC, the bench configuration):
# raw 7x7-s2 stem, the s2d 4x4 stem, s0's 3x3 and s1's 3x3. These are the
# shapes the acceptance equivalence test replays.
RN50_CONV_SHAPES = [
    ("stem_7x7_s2_3ch", 128, 224, 224, 3, 64, 7, 7, (2, 2),
     [(3, 3), (3, 3)], (1, 1)),
    ("stem_s2d_4x4_12ch", 128, 112, 112, 12, 64, 4, 4, (1, 1),
     [(2, 1), (2, 1)], (1, 1)),
    ("s0_3x3_64ch", 128, 56, 56, 64, 64, 3, 3, (1, 1),
     [(1, 1), (1, 1)], (1, 1)),
    ("s1_3x3_128ch", 128, 28, 28, 128, 128, 3, 3, (1, 1),
     [(1, 1), (1, 1)], (1, 1)),
]

# bench.py's two BERT attention regimes: the headline s128 (XLA wins,
# BENCH_r05) and the s512 kernel-proof row (Pallas wins ~9%)
ATTENTION_SHAPES = [
    ("bert_s128", 128, 12, 128, 64, False),
    ("bert_s512", 64, 12, 512, 64, False),
]

# serving decode regimes (sq=1, long ragged sk): the shapes the serving
# engine's paged-attention lever keys on — (batch-bucket, heads,
# padded-slot-count, head_dim). The ragged kv_lens inside each arm span
# 1/4..full so the sweep times realistic occupancy, not the dense corner.
DECODE_ATTENTION_SHAPES = [
    ("decode_b8_kv1024", 8, 12, 1024, 64),
    ("decode_b32_kv512", 32, 12, 512, 64),
    ("decode_b64_kv2048", 64, 12, 2048, 64),
]

# TP-sharded serving decode (ISSUE 11): under GSPMD each tp shard executes
# nh/tp heads of the same decode shape, and paged_attention_backend keys the
# DB on that PER-SHARD shape. The per-shard shapes are recorded as
# `candidate` entries so `--what candidates` measures and upgrades them
# exactly like the PR 7 decode regimes — TP decode resolves through the DB
# like every other lever.
SERVING_TP_DEGREES = (2, 4)


# the epilogue lever's sweep set (ISSUE 9): the BN apply tail of the
# PERF.md r6 cost-table conv OUTPUT shapes — (name, batch, channels,
# spatial) — expanded over layout x residual below; plus the bench BERT
# s128 LN rows. These are the shapes bench.py's resnet/bert arms dispatch.
EPILOGUE_BN_SHAPES = [
    ("stem_7x7_out", 128, 64, 112 * 112),
    ("s0_3x3_out", 128, 64, 56 * 56),
    ("s1_3x3_out", 128, 128, 28 * 28),
]

EPILOGUE_LN_SHAPES = [
    ("bert_s128_ln", 128 * 128, 768),
]


# the embedding sweep's table geometries (name, vocab, dim, ids_per_batch):
# a CTR-scale narrow table, a wide ranker table, and a mid shape — the three
# regimes the slots-vs-hit-rate trade actually differs across. ids_per_batch
# is the per-step lookup volume (batch x fields).
EMBEDDING_GEOMETRIES = [
    ("ctr_v200k_d16", 200_000, 16, 2048),
    ("ctr_v50k_d32", 50_000, 32, 1024),
    ("ranker_v100k_d64", 100_000, 64, 512),
]


def _out_hw(h, w, kh, kw, strides, pads, d):
    hout = (h + sum(pads[0]) - ((kh - 1) * d[0] + 1)) // strides[0] + 1
    wout = (w + sum(pads[1]) - ((kw - 1) * d[1] + 1)) // strides[1] + 1
    return hout, wout


def _measure_arms(arms: dict, iters: int, passes: int) -> dict:
    """Time every runnable arm with the shared protocol; returns
    {name: measure-dict}. Arm values are zero-arg callables returning a
    device array (the drain target)."""
    out = {}
    for name, fn in arms.items():
        holder = {}

        def run_once(fn=fn, holder=holder):
            holder["v"] = fn()

        m = _timing.measure(run_once, lambda: holder["v"], iters, passes)
        out[name] = m
        print(json.dumps({"arm": name, **m}), flush=True)
    return out


def _record_store(key: str, measured: dict, source: str = "sweep") -> None:
    """Append every arm's raw windows to the measurement store
    (tuning/learned/store.py) — the learned cost model's training set grows
    as a side effect of sweeping. Gated by FLAGS_tuning_record ('auto'
    records from the tools whenever a store path resolves)."""
    if learned_store.recording_enabled(tool=True):
        learned_store.record_measured(key, measured, source=source)


def _verdict_vs_base(measured: dict, base: str, band: float):
    """Pick the winner against the conservative base arm: the fastest
    candidate that beats base's median by more than max(band, its own
    measured spread); inside the band -> tie (analytic keeps the call)."""
    base_med = measured[base]["median_s"]
    best, best_med = base, base_med
    for name, m in measured.items():
        if name != base and m["median_s"] < best_med:
            best, best_med = name, m["median_s"]
    if best == base:
        return base, "retire"
    eff_band = max(band, measured[best]["band"], measured[base]["band"])
    v = _timing.ab_verdict(base_med, best_med, eff_band)
    return (best, "keep") if v == "keep" else (base, v)


def sweep_conv(db, shapes, dtype: str, iters: int, passes: int, band: float,
               fmt: str = "NHWC"):
    key_dtype = str(jnp.dtype(dtype))
    rhs = "HWIO" if fmt == "NHWC" else "OIHW"
    for row in shapes:
        name, n, h, w, cin, cout, kh, kw, strides, pads, d = row
        hout, wout = _out_hw(h, w, kh, kw, strides, pads, d)
        rng = np.random.default_rng(0)
        x_shape = (n, h, w, cin) if fmt == "NHWC" else (n, cin, h, w)
        w_shape = (kh, kw, cin, cout) if fmt == "NHWC" \
            else (cout, cin, kh, kw)
        x = jax.device_put(rng.standard_normal(
            x_shape, dtype=np.float32).astype(dtype))
        wt = jax.device_put((rng.standard_normal(
            w_shape, dtype=np.float32) * 0.05).astype(dtype))

        def loss_direct(xx, ww):
            out = jax.lax.conv_general_dilated(
                xx, ww, window_strides=strides, padding=pads,
                rhs_dilation=d, dimension_numbers=(fmt, rhs, fmt))
            return jnp.sum(jnp.square(out.astype(jnp.float32)))

        def loss_igemm(xx, ww):
            acc = _conv2d_igemm_f32(xx, ww, strides, pads, d, fmt)
            return jnp.sum(jnp.square(acc))

        f_direct = jax.jit(jax.grad(loss_direct, argnums=(0, 1)))
        f_igemm = jax.jit(jax.grad(loss_igemm, argnums=(0, 1)))
        print(json.dumps({"sweep": "conv", "shape": name,
                          "dims": f"{n}x{h}x{w}x{cin}->{cout} "
                                  f"k{kh}x{kw}"}), flush=True)
        measured = _measure_arms(
            {"direct": lambda: f_direct(x, wt)[1],
             "igemm": lambda: f_igemm(x, wt)[1]}, iters, passes)
        winner, verdict = _verdict_vs_base(measured, "direct", band)
        analytic = "igemm" if _igemm_predict_win(
            n, hout, wout, cin, cout, kh, kw,
            jnp.dtype(dtype).itemsize) else "direct"
        lowering = winner if verdict in ("keep", "retire") else analytic
        if verdict == "tie":
            lowering = analytic
        key = tuning.canonical_key(
            "conv2d", tuning.conv_key(n, hout, wout, cin, cout, kh, kw,
                                      strides, d, fmt),
            key_dtype, tuning.device_kind())
        db.put(key, {"lowering": lowering}, source="swept",
               measured=tuning.evidence(measured),
               note=f"{name}: verdict={verdict} analytic={analytic}")
        _record_store(key, measured)
        print(json.dumps({"shape": name, "decision": lowering,
                          "verdict": verdict, "analytic": analytic}),
              flush=True)


def sweep_attention(db, shapes, dtype: str, iters: int, passes: int,
                    band: float):
    from paddle_tpu.ops.attention_ops import (_flash_bundled_ok,
                                              _pallas_short128_ok,
                                              _pallas_short_ok,
                                              _reference_attention)

    key_dtype = str(jnp.dtype(dtype))
    for name, b, nh, s, dh, causal in shapes:
        rng = np.random.default_rng(0)
        q, k, v = (jax.device_put(rng.standard_normal(
            (b, nh, s, dh), dtype=np.float32).astype(dtype))
            for _ in range(3))
        sm = dh ** -0.5

        def mk(attn_fn):
            def loss(qq, kk, vv):
                return jnp.sum(jnp.square(
                    attn_fn(qq, kk, vv).astype(jnp.float32)))
            g = jax.jit(jax.grad(loss, argnums=(0, 1, 2)))
            return lambda: g(q, k, v)[0]

        arms = {"xla": mk(lambda qq, kk, vv: _reference_attention(
            qq, kk, vv, None, causal, sm))}
        if _pallas_short_ok(q.shape, k.shape, None):
            from paddle_tpu.ops.pallas_kernels import attention as psa

            arms["pallas_short"] = mk(lambda qq, kk, vv:
                                      psa.short_seq_attention(
                                          qq, kk, vv, causal=causal,
                                          sm_scale=sm))
        if _pallas_short128_ok(q.shape, k.shape, None):
            from paddle_tpu.ops.pallas_kernels import short_attention as s128

            arms["pallas_short128"] = mk(lambda qq, kk, vv:
                                         s128.short128_attention(
                                             qq, kk, vv, causal=causal,
                                             sm_scale=sm))
        if _flash_bundled_ok(q.shape, k.shape, q.dtype):
            from jax.experimental.pallas.ops.tpu import flash_attention as fa

            arms["flash_bundled"] = mk(lambda qq, kk, vv: fa.flash_attention(
                qq, kk, vv, causal=causal, sm_scale=sm))
        print(json.dumps({"sweep": "attention", "shape": name,
                          "arms": sorted(arms)}), flush=True)
        if len(arms) < 2:
            print(json.dumps({"shape": name, "skipped":
                              "only the XLA arm runs on this platform"}),
                  flush=True)
            continue
        measured = _measure_arms(arms, iters, passes)
        backend, verdict = _verdict_vs_base(measured, "xla", band)
        key = tuning.canonical_key(
            "attention", tuning.attention_key(b, nh, s, s, dh, causal),
            key_dtype, tuning.device_kind())
        db.put(key, {"backend": backend}, source="swept",
               measured=tuning.evidence(measured),
               note=f"{name}: verdict={verdict}")
        _record_store(key, measured)
        print(json.dumps({"shape": name, "decision": backend,
                          "verdict": verdict}), flush=True)


def record_tp_decode_candidates(db, shapes, dtype: str,
                                tp_degrees=SERVING_TP_DEGREES) -> int:
    """Record the head-sharded decode shapes (nh/tp per shard) as
    `candidate` DB entries. Candidates never clobber swept verdicts and
    never count as hits (the PR 6 contract); `sweep_candidates` routes the
    sq=1 family through `sweep_decode_attention` and upgrades them to
    swept verdicts — after which a TP serving engine's per-shard dispatch
    is a DB hit like any other lever's."""
    from paddle_tpu import flags as pt_flags

    key_dtype = str(jnp.dtype(dtype))
    ps = int(pt_flags.get_flag("serving_page_size"))
    added = 0
    for _, b, nh, kv, dh in shapes:
        kv = max(ps, (kv // ps) * ps)
        for tp in tp_degrees:
            if nh % tp or nh // tp < 1:
                continue
            key = tuning.canonical_key(
                "attention", tuning.attention_key(b, nh // tp, 1, kv, dh,
                                                  True),
                key_dtype, tuning.device_kind())
            if db.lookup(key) is not None:
                continue
            db.put(key, {"backend": "xla"}, source="candidate")
            added += 1
    print(json.dumps({"sweep": "tp_decode_candidates", "recorded": added,
                      "tp_degrees": list(tp_degrees)}), flush=True)
    return added


def sweep_decode_attention(db, shapes, dtype: str, iters: int, passes: int,
                           band: float):
    """The serving lever's sweep: XLA gather-based paged attention vs the
    Pallas page-DMA kernel per (batch, heads, kv_slots, head_dim) decode
    shape. Keys are attention_key(b, nh, 1, kv, dh, causal=1) — exactly
    what ops/attention_ops.paged_attention_backend consults, so a swept
    verdict here IS the serving engine's dispatch for that bucket."""
    from paddle_tpu import flags as pt_flags
    from paddle_tpu.ops.attention_ops import (_paged_attention_reference,
                                              _pallas_paged_ok)

    key_dtype = str(jnp.dtype(dtype))
    ps = int(pt_flags.get_flag("serving_page_size"))
    for name, b, nh, kv, dh in shapes:
        kv = max(ps, (kv // ps) * ps)  # whole pages
        num_pages = b * (kv // ps) + 1
        rng = np.random.default_rng(0)
        kp, vp = (jax.device_put(rng.standard_normal(
            (num_pages, ps, nh, dh), dtype=np.float32).astype(dtype))
            for _ in range(2))
        q = jax.device_put(rng.standard_normal(
            (b, nh, dh), dtype=np.float32).astype(dtype))
        pt_ = jax.device_put(rng.permutation(num_pages - 1)[:b * (kv // ps)]
                             .reshape(b, kv // ps).astype(np.int32))
        kv_lens = jax.device_put(
            rng.integers(max(1, kv // 4), kv + 1, b).astype(np.int32))
        sm = dh ** -0.5

        arms = {"xla": lambda: jax.jit(_paged_attention_reference)(
            q, kp, vp, pt_, kv_lens, sm)}
        if _pallas_paged_ok(q.shape, kp.shape):
            from paddle_tpu.ops.pallas_kernels import paged_attention as ppa

            arms["pallas_paged"] = lambda: ppa.paged_decode_attention(
                q, kp, vp, pt_, kv_lens, sm_scale=sm)
        print(json.dumps({"sweep": "decode_attention", "shape": name,
                          "arms": sorted(arms)}), flush=True)
        if len(arms) < 2:
            print(json.dumps({"shape": name, "skipped":
                              "only the XLA arm runs on this platform"}),
                  flush=True)
            continue
        measured = _measure_arms(arms, iters, passes)
        backend, verdict = _verdict_vs_base(measured, "xla", band)
        key = tuning.canonical_key(
            "attention", tuning.attention_key(b, nh, 1, kv, dh, True),
            key_dtype, tuning.device_kind())
        db.put(key, {"backend": backend}, source="swept",
               measured=tuning.evidence(measured),
               note=f"{name}: verdict={verdict}")
        _record_store(key, measured)
        print(json.dumps({"shape": name, "decision": backend,
                          "verdict": verdict}), flush=True)


def sweep_epilogue(db, bn_shapes, ln_shapes, dtype: str, iters: int,
                   passes: int, band: float):
    """The fused-epilogue lever's sweep (ISSUE 9): XLA composition vs the
    Pallas apply kernel per canonical (rows, channels, layout, act,
    residual) problem — fwd+bwd jitted, one arm-set per BN shape over
    (NHWC no-res, NHWC res, NCHW res) plus the LN rows. Keys are exactly
    what ops/nn_ops._epilogue_backend consults, so a swept keep here IS
    the dispatch for that shape. Shapes whose Pallas arm cannot run on
    this platform are skipped, not recorded — absence of a verdict keeps
    the analytic XLA prior, which is already the off state."""
    jobs = []
    for name, n, c, hw in bn_shapes:
        jobs.append((f"{name}_nhwc", "bn", (n * hw, c), "last", "relu",
                     False))
        jobs.append((f"{name}_nhwc_res", "bn", (n * hw, c), "last", "relu",
                     True))
        jobs.append((f"{name}_nchw_res", "bn", (n, c, hw), "row", "relu",
                     True))
    for name, rows, k in ln_shapes:
        jobs.append((name, "ln", (rows, k), "last", "identity", False))
    _sweep_epilogue_jobs(db, jobs, dtype, iters, passes, band)


def _sweep_epilogue_jobs(db, jobs, dtype: str, iters: int, passes: int,
                         band: float):
    from paddle_tpu.ops.pallas_kernels import epilogue as ep
    from paddle_tpu.ops.pallas_kernels import workbench
    from paddle_tpu import tuning as _t

    key_dtype = str(jnp.dtype(dtype))
    for name, kind, shape, cpos, act, has_res in jobs:
        rng = np.random.default_rng(0)
        cl = cpos == "last"
        C = shape[-1] if cl else shape[1]
        rows = int(np.prod(shape)) // C
        x = jax.device_put(rng.standard_normal(
            shape, dtype=np.float32).astype(dtype))
        res = jax.device_put(rng.standard_normal(
            shape, dtype=np.float32).astype(dtype)) if has_res else None
        s, b = (jax.device_put(rng.standard_normal(C).astype(np.float32))
                for _ in range(2))
        m = jax.device_put(rng.standard_normal(C).astype(np.float32))
        v = jax.device_put((np.abs(rng.standard_normal(C)) + 0.5)
                           .astype(np.float32))

        def mk(fn, wants_res):
            if wants_res:
                def loss(xx, rr):
                    return jnp.sum(jnp.square(fn(xx, rr)
                                              .astype(jnp.float32)))
                g = jax.jit(jax.grad(loss, argnums=(0, 1)))
                return lambda: g(x, res)[0]

            def loss(xx):
                return jnp.sum(jnp.square(fn(xx).astype(jnp.float32)))
            g = jax.jit(jax.grad(loss))
            return lambda: g(x)

        if kind == "bn":
            arms = {"xla": mk(lambda xx, rr=None: ep.bn_apply_act_reference(
                xx, s, b, m, v, act=act, residual=rr, channel_last=cl),
                has_res)}
            if workbench.runnable(ep) and ep.epilogue_supported(
                    shape, jnp.dtype(dtype), cl, act):
                arms["pallas"] = mk(
                    lambda xx, rr=None: ep.bn_apply_act(
                        xx, s, b, m, v, act=act, residual=rr,
                        channel_last=cl), has_res)
        else:
            arms = {"xla": mk(lambda xx: ep.layer_norm_act_reference(
                xx, s, b, act=act), False)}
            if workbench.runnable(ep) and ep.epilogue_supported(
                    shape, jnp.dtype(dtype), True, act):
                arms["pallas"] = mk(lambda xx: ep.layer_norm_act(
                    xx, s, b, act=act), False)
        print(json.dumps({"sweep": "epilogue", "shape": name,
                          "arms": sorted(arms)}), flush=True)
        if len(arms) < 2:
            print(json.dumps({"shape": name, "skipped":
                              "only the XLA arm runs on this platform"}),
                  flush=True)
            continue
        measured = _measure_arms(arms, iters, passes)
        backend, verdict = _verdict_vs_base(measured, "xla", band)
        key = _t.canonical_key(
            "epilogue", _t.epilogue_key(kind, rows, C, cpos, act, has_res),
            key_dtype, _t.device_kind())
        db.put(key, {"backend": backend}, source="swept",
               measured=tuning.evidence(measured),
               note=f"{name}: verdict={verdict}")
        _record_store(key, measured)
        print(json.dumps({"shape": name, "decision": backend,
                          "verdict": verdict}), flush=True)


def _pow2(n: int) -> int:
    p = 1
    while p < n:
        p <<= 1
    return p


def _emb_arm_ex_s(vocab: int, dim: int, ids_per_batch: int, slots: int,
                  prefetch: int, steps_per_window: int, passes: int):
    """Time one cache-geometry arm end-to-end: a fresh one-table program
    (sum-pooled embedding -> sigmoid loss -> SGD) trained over a seeded
    zipf id stream through the REAL tiered stack — minimize()-time rewrite,
    host-side resolve, install/gather/scatter step. Returns (measure dict
    with per-step seconds, stats dict). Resolution runs inline (sync) so
    the measured cost includes the host work the geometry must amortize."""
    import paddle_tpu as pt
    from paddle_tpu import flags as ptf
    from paddle_tpu import layers as L
    from paddle_tpu.layers import tensor as T
    from paddle_tpu.param_attr import ParamAttr

    batch = max(1, min(128, ids_per_batch))
    fields = max(1, ids_per_batch // batch)
    rng = np.random.default_rng(7)
    feeds = []
    for _ in range(8):
        ids = (rng.zipf(1.5, (batch, fields)) - 1) % vocab
        feeds.append({
            "ids": ids.astype(np.int32),
            "label": rng.integers(0, 2, (batch, 1)).astype(np.float32)})

    saved = {k: ptf.get_flag(k) for k in (
        "emb_hbm_budget_mb", "emb_cache_slots", "emb_prefetch_rows")}
    ptf.set_flags({"emb_hbm_budget_mb": 1e-6, "emb_cache_slots": int(slots),
                   "emb_prefetch_rows": int(prefetch)})
    try:
        main, startup = pt.Program(), pt.Program()
        main.random_seed = startup.random_seed = 7
        with pt.program_guard(main, startup), pt.unique_name.guard():
            ids_v = T.data(name="ids", shape=[fields], dtype="int64")
            label = T.data(name="label", shape=[1], dtype="float32")
            emb = L.embedding(ids_v, size=[vocab, dim],
                              param_attr=ParamAttr(name="sweep_tbl"))
            pooled = L.reduce_sum(emb, dim=1)
            logit = L.fc(pooled, size=1)
            loss = L.mean(
                L.sigmoid_cross_entropy_with_logits(logit, label))
            pt.optimizer.SGD(0.1).minimize(loss)
        eng = main._tiered_engine
        assert eng is not None and "sweep_tbl" in eng.tables, \
            "sweep arm did not tier — budget/geometry wiring broke"
        exe = pt.Executor()
        step = [0]
        with pt.scope_guard(pt.Scope()):
            exe.run(startup)
            cache_name = eng.tables["sweep_tbl"].cache_var

            def run_once():
                exe.run_async(main, feed=feeds[step[0] % len(feeds)])
                step[0] += 1

            def drain():
                exe.wait()
                return pt.global_scope().find_var(cache_name)

            m = _timing.measure(run_once, drain, steps_per_window, passes)
            eng.flush_all()
            stats = eng.stats("sweep_tbl")
        return m, stats
    finally:
        ptf.set_flags(saved)


def sweep_embedding(db, geometries, dtype: str, iters: int, passes: int,
                    band: float, table_names: dict | None = None):
    """Cache-geometry sweep (ISSUE 10): per table geometry, slot-count arms
    around the working set (the budget-derived prior is the base) then
    prefetch-width arms on the winning slot count. The swept verdict is the
    decision the minimize()-time rewrite consults for that table key;
    ties keep the analytic call per the r5 rule. `table_names` maps a
    geometry name to the REAL table name to record under (candidate
    upgrades); default records under the geometry name."""
    from paddle_tpu import tuning as _t

    key_dtype = str(jnp.dtype(dtype))
    for name, vocab, dim, ids_per_batch in geometries:
        # working-set estimate: unique ids of one zipf batch
        rng = np.random.default_rng(7)
        uniq = len(np.unique((rng.zipf(1.5, ids_per_batch) - 1) % vocab))
        base_slots = min(_pow2(max(4 * uniq, 2)), max(2, vocab))
        arm_slots = sorted({min(_pow2(max(2 * uniq, 2)), max(2, vocab)),
                            base_slots,
                            min(_pow2(max(8 * uniq, 2)), max(2, vocab))})
        print(json.dumps({"sweep": "embedding", "shape": name,
                          "uniq_per_batch": uniq,
                          "arms": [f"slots{s}" for s in arm_slots]}),
              flush=True)
        measured, stats_by = {}, {}
        for s in arm_slots:
            m, st = _emb_arm_ex_s(vocab, dim, ids_per_batch, s, 0,
                                  iters, passes)
            m["hit_rate"] = st.get("hit_rate")
            measured[f"slots{s}"] = m
            print(json.dumps({"arm": f"slots{s}", **m}), flush=True)
            stats_by[f"slots{s}"] = st
        winner, verdict = _verdict_vs_base(measured, f"slots{base_slots}",
                                           band)
        best_slots = int(winner[len("slots"):])
        # prefetch-width mini-sweep on the winning slot count: auto (pow2 of
        # the first batch's miss count) vs double that, which trades padded
        # transfer bytes against overflow recompiles
        auto_pf = int(stats_by[winner].get("prefetch_rows") or 0)
        pf_measured = {f"pf{auto_pf}": measured[winner]}
        best_pf = auto_pf
        if auto_pf:
            m2, _ = _emb_arm_ex_s(vocab, dim, ids_per_batch, best_slots,
                                  2 * auto_pf, iters, passes)
            pf_measured[f"pf{2 * auto_pf}"] = m2
            print(json.dumps({"arm": f"pf{2 * auto_pf}", **m2}), flush=True)
            pw, pv = _verdict_vs_base(pf_measured, f"pf{auto_pf}", band)
            if pv == "keep":
                best_pf = int(pw[len("pf"):])
        table = (table_names or {}).get(name, name)
        key = _t.canonical_key(
            "embedding", _t.embedding_key(table, vocab, dim), key_dtype,
            _t.device_kind())
        decision = {"slots": best_slots, "prefetch_rows": best_pf}
        db.put(key, decision, source="swept",
               measured={a: {"median_s": m["median_s"], "band": m["band"],
                             "hit_rate": m.get("hit_rate")}
                         for a, m in {**measured, **pf_measured}.items()},
               note=f"{name}: verdict={verdict} base=slots{base_slots}")
        _record_store(key, {**measured, **pf_measured})
        print(json.dumps({"shape": name, "decision": decision,
                          "verdict": verdict}), flush=True)


_EMB_KEY_RE = re.compile(
    r"^embedding\|table=(\S+) vocab=(\d+) dim=(\d+)\|([\w.]+)\|")


_CONV_KEY_RE = re.compile(
    r"^conv2d\|n=(\d+) out=(\d+)x(\d+) cin=(\d+) cout=(\d+) k=(\d+)x(\d+) "
    r"s=(\d+)x(\d+) d=(\d+)x(\d+) (NHWC|NCHW)\|([\w.]+)\|")


_ATTN_KEY_RE = re.compile(
    r"^attention\|b=(\d+) nh=(\d+) sq=(\d+) sk=(\d+) dh=(\d+) "
    r"causal=(\d)\|([\w.]+)\|")


_EPI_KEY_RE = re.compile(
    r"^epilogue\|kind=(\w+) rows=(\d+) c=(\d+) ch=(last|row) act=(\w+) "
    r"res=(\d)\|([\w.]+)\|")


def sweep_candidates(db, iters, passes, band):
    """Upgrade `candidate` entries (recorded by a FLAGS_tuning_mode=sweep
    run) to measured verdicts — conv2d lowerings AND attention backends.
    Attention candidates route by shape: sq=1 keys are serving decode
    dispatches (ragged paged attention), sq==sk keys are the encoder
    self-attention regimes; anything else is skipped (no harness measures
    it honestly). Conv input extents are reconstructed pad-free from the
    output tile — the GEMM dims (M, folded K) that drive the decision are
    identical either way."""
    attn_groups: dict[str, list] = {}
    decode_groups: dict[str, list] = {}
    epi_groups: dict[str, tuple[list, list]] = {}
    emb_groups: dict[str, tuple[list, dict]] = {}
    for ckey, entry in sorted(db.entries.items()):
        if entry.get("source") != "candidate":
            continue
        gm = _EMB_KEY_RE.match(ckey)
        if gm:
            table, vocab, dim = gm.group(1), int(gm.group(2)), \
                int(gm.group(3))
            dt = gm.group(4)
            geoms, names = emb_groups.setdefault(dt, ([], {}))
            # probe the geometry with a representative per-batch lookup
            # volume — the runtime candidate records table identity + shape,
            # not the workload's batch, so the sweep supplies the load
            gname = f"candidate_{table}"
            geoms.append((gname, vocab, dim, min(2048, max(64, vocab // 8))))
            names[gname] = table
            continue
        am = _ATTN_KEY_RE.match(ckey)
        if am:
            b, nh, sq, sk, dh_, causal = map(int, am.groups()[:6])
            dt = am.group(7)
            if sq == 1:
                decode_groups.setdefault(dt, []).append(
                    (f"candidate_b{b}_kv{sk}", b, nh, sk, dh_))
            elif sq == sk:
                attn_groups.setdefault(dt, []).append(
                    (f"candidate_b{b}_s{sq}", b, nh, sq, dh_, bool(causal)))
            continue
        em = _EPI_KEY_RE.match(ckey)
        if em:
            kind, rows, c = em.group(1), int(em.group(2)), int(em.group(3))
            cpos, act, has_res = em.group(4), em.group(5), int(em.group(6))
            dt = em.group(7)
            bn_s, ln_s = epi_groups.setdefault(dt, ([], []))
            # sweep_epilogue regenerates the (layout, residual) expansion
            # from a compact shape row, so reconstruct one matching row:
            # channels-last rows collapse to (n=1, c, hw=rows); channels-row
            # keys carry rows = n (per-image spatial folded into hw)
            if kind == "ln":
                ln_s.append((f"candidate_ln_{rows}x{c}", rows, c))
            else:
                bn_s.append((f"candidate_bn_{rows}x{c}", kind, rows, c,
                             cpos, act, bool(has_res)))
            continue
    for dt, (geoms, names) in sorted(emb_groups.items()):
        sweep_embedding(db, geoms, dt, iters, passes, band,
                        table_names=names)
    for dt, shapes in sorted(attn_groups.items()):
        sweep_attention(db, shapes, dt, iters, passes, band)
    for dt, shapes in sorted(decode_groups.items()):
        sweep_decode_attention(db, shapes, dt, iters, passes, band)
    for dt, (bn_s, ln_s) in sorted(epi_groups.items()):
        # channels-row keys fold the (N, HW) split into rows = N*HW; the
        # re-measured tensor uses N=1 — total elements (what the apply cost
        # scales with) are preserved, only the param-tiling split differs
        jobs = [(nm, kind, ((rows, c) if cpos == "last" else (1, c, rows)),
                 cpos, act, has_res)
                for nm, kind, rows, c, cpos, act, has_res in bn_s]
        jobs += [(nm, "ln", (rows, c), "last", "identity", False)
                 for nm, rows, c in ln_s]
        _sweep_epilogue_jobs(db, jobs, dt, iters, passes, band)

    rows = []
    for ckey, entry in sorted(db.entries.items()):
        if entry.get("source") != "candidate":
            continue
        m = _CONV_KEY_RE.match(ckey)
        if not m:
            continue
        (n, hout, wout, cin, cout, kh, kw, sh, sw, dh_, dw_) = \
            map(int, m.groups()[:11])
        fmt, dt = m.group(12), m.group(13)
        h = (hout - 1) * sh + (kh - 1) * dh_ + 1
        w = (wout - 1) * sw + (kw - 1) * dw_ + 1
        rows.append(((dt, fmt),
                     (f"candidate_{cin}ch_{kh}x{kw}", n, h, w, cin, cout,
                      kh, kw, (sh, sw), [(0, 0), (0, 0)], (dh_, dw_))))
    if not rows:
        print(json.dumps({"sweep": "candidates", "note": "none found"}),
              flush=True)
        return
    grouped: dict[tuple, list] = {}
    for gk, row in rows:
        grouped.setdefault(gk, []).append(row)
    for (dt, fmt), shapes in sorted(grouped.items()):
        sweep_conv(db, shapes, dt, iters, passes, band, fmt=fmt)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--db", default=os.environ.get("FLAGS_tuning_db",
                                                   "TUNING_DB.json"))
    ap.add_argument("--what", default="conv,attention,epilogue",
                    help="comma list: conv, attention, epilogue, embedding, "
                         "candidates")
    on_tpu = jax.devices()[0].platform == "tpu"
    ap.add_argument("--iters", type=int, default=20 if on_tpu else 3)
    ap.add_argument("--passes", type=int, default=3 if on_tpu else 2)
    ap.add_argument("--band", type=float, default=_timing.DEFAULT_BAND)
    ap.add_argument("--dtype", default="bfloat16" if on_tpu else "float32")
    ap.add_argument("--small", action="store_true",
                    help="shrink the default shape set (batch 8, CPU smoke)")
    ap.add_argument("--measurements", default="",
                    help="measurement-store JSONL path (default: derived "
                         "from --db, see FLAGS_tuning_measurements)")
    args = ap.parse_args()

    # the measurement store derives its path from the tuning flags — point
    # them at this sweep's DB so raw windows land next to the verdicts
    from paddle_tpu import flags as pt_flags
    pt_flags.set_flags({"tuning_db": args.db,
                        "tuning_measurements": args.measurements})

    conv_shapes = RN50_CONV_SHAPES
    attn_shapes = ATTENTION_SHAPES
    decode_shapes = DECODE_ATTENTION_SHAPES
    epi_bn_shapes = EPILOGUE_BN_SHAPES
    epi_ln_shapes = EPILOGUE_LN_SHAPES
    emb_geometries = EMBEDDING_GEOMETRIES
    if args.small or not on_tpu:
        emb_geometries = [(nm, v // 8, d, max(64, b // 8))
                          for nm, v, d, b in EMBEDDING_GEOMETRIES]
    if args.small or not on_tpu:
        conv_shapes = [(nm, 8, h // 4, w // 4, ci, co, kh, kw, st, pd, d)
                       for nm, _, h, w, ci, co, kh, kw, st, pd, d
                       in RN50_CONV_SHAPES]
        attn_shapes = [(nm, 2, nh, s, dh, c)
                       for nm, _, nh, s, dh, c in ATTENTION_SHAPES]
        decode_shapes = [(nm, 2, nh, kv // 4, dh)
                         for nm, _, nh, kv, dh in DECODE_ATTENTION_SHAPES]
        epi_bn_shapes = [(nm, 2, c, hw // 16)
                         for nm, _, c, hw in EPILOGUE_BN_SHAPES]
        epi_ln_shapes = [(nm, rows // 64, k)
                         for nm, rows, k in EPILOGUE_LN_SHAPES]

    db = tuning.TuningDB(args.db)
    what = {w.strip() for w in args.what.split(",") if w.strip()}
    if "conv" in what:
        sweep_conv(db, conv_shapes, args.dtype, args.iters, args.passes,
                   args.band)
    if "attention" in what:
        sweep_attention(db, attn_shapes, args.dtype, args.iters,
                        args.passes, args.band)
        # the serving lever's decode regimes ride the attention sweep: same
        # op kind, same DB namespace, different (sq=1) shape family
        sweep_decode_attention(db, decode_shapes, args.dtype, args.iters,
                               args.passes, args.band)
        # TP-sharded serving (ISSUE 11): per-shard (nh/tp) decode shapes
        # land as candidates for `--what candidates` to measure
        record_tp_decode_candidates(db, decode_shapes, args.dtype)
    if "epilogue" in what:
        sweep_epilogue(db, epi_bn_shapes, epi_ln_shapes, args.dtype,
                       args.iters, args.passes, args.band)
    if "embedding" in what:
        sweep_embedding(db, emb_geometries, args.dtype, args.iters,
                        args.passes, args.band)
    if "candidates" in what:
        sweep_candidates(db, args.iters, args.passes, args.band)
    db.save(args.db)
    print(json.dumps({"db": os.path.abspath(args.db),
                      "entries": len(db)}), flush=True)


if __name__ == "__main__":
    main()
