"""Pure-JAX BERT-base step replica: what can this chip actually reach?

Identical shapes to the bench (B=128, S=128, 12 layers, vocab 30522),
bf16 matmuls, fp32 master weights + Adam, chained steps inside one jit.
Variants via argv[1]: model | native | pallas  (attention layout/kernel).
Usage: python tools/_bert_pure.py [variant] [chain]
"""
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np

sys.path.insert(0, "/root/repo")
from paddle_tpu.ops.pallas_kernels import attention as psa

variant = sys.argv[1] if len(sys.argv) > 1 else "model"
N = int(sys.argv[2]) if len(sys.argv) > 2 else 10
B, S, H, nh, dh, L, V, F = 128, 128, 768, 12, 64, 12, 30522, 3072
sm = dh ** -0.5
OUTER = 3

rng = np.random.default_rng(0)


def mk(*shape, scale=0.02):
    return jnp.asarray(rng.standard_normal(shape) * scale, jnp.float32)


params = {
    "emb": mk(V, H), "pos": mk(S, H),
    "head_w": mk(H, V), "head_b": jnp.zeros((V,), jnp.float32),
}
for i in range(L):
    params[f"l{i}"] = {
        "qkv_w": mk(H, 3 * H), "qkv_b": jnp.zeros((3 * H,), jnp.float32),
        "o_w": mk(H, H), "o_b": jnp.zeros((H,), jnp.float32),
        "ln1_g": jnp.ones((H,), jnp.float32), "ln1_b": jnp.zeros((H,), jnp.float32),
        "f1_w": mk(H, F), "f1_b": jnp.zeros((F,), jnp.float32),
        "f2_w": mk(F, H), "f2_b": jnp.zeros((H,), jnp.float32),
        "ln2_g": jnp.ones((H,), jnp.float32), "ln2_b": jnp.zeros((H,), jnp.float32),
    }
params = jax.device_put(params)

ids = jax.device_put(jnp.asarray(
    rng.integers(0, V, (B, S)), jnp.int32))
labels = jax.device_put(jnp.asarray(
    rng.integers(0, V, (B, S)), jnp.int32))


def ln(x, g, b):
    x32 = x.astype(jnp.float32)
    mu = x32.mean(-1, keepdims=True)
    var = ((x32 - mu) ** 2).mean(-1, keepdims=True)
    return ((x32 - mu) * jax.lax.rsqrt(var + 1e-12) * g + b).astype(x.dtype)


def attention(x, p):
    xb = x.astype(jnp.bfloat16)
    qkv = xb @ p["qkv_w"].astype(jnp.bfloat16) + p["qkv_b"].astype(jnp.bfloat16)
    if variant == "model":
        qkv = qkv.reshape(B, S, 3, nh, dh).transpose(2, 0, 3, 1, 4)
        q, k, v = qkv[0], qkv[1], qkv[2]
        s = jnp.einsum("bhqd,bhkd->bhqk", q, k) * sm
        pr = jax.nn.softmax(s.astype(jnp.float32), -1).astype(jnp.bfloat16)
        o = jnp.einsum("bhqk,bhkd->bhqd", pr, v)
        o = o.transpose(0, 2, 1, 3).reshape(B, S, H)
    elif variant == "native":
        qkv = qkv.reshape(B, S, 3, nh, dh)
        q, k, v = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]
        s = jnp.einsum("bqhd,bkhd->bhqk", q, k) * sm
        pr = jax.nn.softmax(s.astype(jnp.float32), -1).astype(jnp.bfloat16)
        o = jnp.einsum("bhqk,bkhd->bqhd", pr, v).reshape(B, S, H)
    else:  # pallas
        qkv = qkv.reshape(B, S, 3, nh, dh).transpose(2, 0, 3, 1, 4)
        q, k, v = qkv[0], qkv[1], qkv[2]
        o = psa.short_seq_attention(q, k, v, sm_scale=sm)
        o = o.transpose(0, 2, 1, 3).reshape(B, S, H)
    return o @ p["o_w"].astype(jnp.bfloat16) + p["o_b"].astype(jnp.bfloat16)


def layer(x, p):
    a = attention(x, p)
    x = ln(x + a, p["ln1_g"], p["ln1_b"])
    xb = x.astype(jnp.bfloat16)
    h = jax.nn.gelu(xb @ p["f1_w"].astype(jnp.bfloat16)
                    + p["f1_b"].astype(jnp.bfloat16))
    f = h @ p["f2_w"].astype(jnp.bfloat16) + p["f2_b"].astype(jnp.bfloat16)
    return ln(x + f, p["ln2_g"], p["ln2_b"])


def loss_fn(params):
    x = params["emb"][ids] + params["pos"][None, :, :]
    x = x.astype(jnp.bfloat16)
    for i in range(L):
        x = layer(x, params[f"l{i}"])
    logits = (x @ params["head_w"].astype(jnp.bfloat16)).astype(jnp.float32)
    logits = logits + params["head_b"]
    lse = jax.nn.logsumexp(logits, -1)
    nll = lse - jnp.take_along_axis(logits, labels[..., None], -1)[..., 0]
    return nll.mean()


FWD_ONLY = len(sys.argv) > 3 and sys.argv[3] == "fwd"


@jax.jit
def train(params, mom, vel):
    def body(c, _):
        params, mom, vel = c
        if FWD_ONLY:
            # keep the carry alive so the chain can't collapse
            loss = loss_fn(params)
            params = jax.tree_util.tree_map(
                lambda p: p + 1e-9 * loss.astype(p.dtype), params)
            return (params, mom, vel), loss
        loss, grads = jax.value_and_grad(loss_fn)(params)
        tm = jax.tree_util.tree_map
        mom = tm(lambda g, m: 0.9 * m + 0.1 * g, grads, mom)
        vel = tm(lambda g, v: 0.999 * v + 0.001 * g * g, grads, vel)
        params = tm(lambda p, m, v: p - 1e-4 * m / (jnp.sqrt(v) + 1e-8),
                    params, mom, vel)
        return (params, mom, vel), loss
    (params, mom, vel), losses = jax.lax.scan(body, (params, mom, vel),
                                              None, length=N)
    return params, mom, vel, losses


zeros = jax.tree_util.tree_map(jnp.zeros_like, params)
p, m, v, losses = train(params, zeros, zeros)
np.asarray(losses[-1])
t0 = time.perf_counter()
for _ in range(OUTER):
    p2, m2, v2, losses = train(p, m, v)
np.asarray(losses[-1])
dt = (time.perf_counter() - t0) / (OUTER * N)
tok = B * S / dt
# same honest MFU formula as bench.py: 6*N_matmul*T + attention
n_mat = (L * (H * 3 * H + H * H + H * F + F * H) + H * V)
flops = 6 * n_mat * B * S + 12 * L * B * nh * S * S * dh  # attn fwd+bwd(2.5x)
print(f"variant={variant}  {dt*1e3:.1f} ms/step  {tok:,.0f} tok/s  "
      f"MFU {flops/dt/197e12:.3f}")
