"""NCHW vs NHWC conv layout microbench on ResNet-50 shapes."""
import time
import jax, jax.numpy as jnp, numpy as np

_drain = jax.jit(lambda v: v.reshape(-1)[0])

def drain(x):
    return np.asarray(_drain(x))

B = 128
SHAPES = [
    (3, 64, 224, 224, 7, 2),    # stem
    (64, 256, 56, 56, 1, 1),
    (256, 64, 56, 56, 1, 1),
    (64, 64, 56, 56, 3, 1),     # actual RN50 stage-1 3x3
    (256, 256, 56, 56, 3, 1),
    (128, 128, 28, 28, 3, 1),
    (512, 512, 28, 28, 3, 1),
    (256, 256, 14, 14, 3, 1),
    (512, 512, 7, 7, 3, 1),
    (2048, 512, 7, 7, 1, 1),
]
N = 30
for (ci, co, h, w, k, s) in SHAPES:
    ho, wo = h // s, w // s
    fl = 2 * B * co * ci * k * k * ho * wo
    res = []
    for dn in (("NCHW", "OIHW", "NCHW"), ("NHWC", "HWIO", "NHWC")):
        if dn[0] == "NCHW":
            x = jnp.full((B, ci, h, w), 0.5, jnp.bfloat16)
            wt = jnp.full((co, ci, k, k), 0.001, jnp.bfloat16)
        else:
            x = jnp.full((B, h, w, ci), 0.5, jnp.bfloat16)
            wt = jnp.full((k, k, ci, co), 0.001, jnp.bfloat16)
        f = jax.jit(lambda x, wt, dn=dn, s=s, k=k: jax.lax.conv_general_dilated(
            x, wt, (s, s), [(k//2, k//2)]*2, dimension_numbers=dn))
        drain(f(x, wt))  # warm conv + drain for this shape
        t0 = time.perf_counter()
        for _ in range(N):
            y = f(x, wt)
        drain(y)
        res.append((time.perf_counter() - t0) / N)
    t1, t2 = res
    print(f"{ci:>4}->{co:<4} {h:>3}x{w:<3} k{k} s{s}: NCHW {t1*1e3:7.2f} ms {fl/t1/1e12:6.1f} TF/s | NHWC {t2*1e3:7.2f} ms {fl/t2/1e12:6.1f} TF/s", flush=True)
