"""A/B: BERT bench step with use_flash_attention True vs False.

Timing rides tools/_timing.py (the shared warmup + windowed protocol) so
this harness, _rn_igemm.py and tools/tune.py all report comparable numbers.
"""
import sys
sys.path.insert(0, "/root/repo")
import numpy as np  # noqa: E402

from tools import _timing  # noqa: E402


def run(use_flash):
    import paddle_tpu as pt
    from paddle_tpu.models import transformer
    cfg = transformer.TransformerConfig(
        vocab_size=30522, hidden_size=768, num_layers=12, num_heads=12,
        ffn_size=3072, max_position=512, dropout=0.0, use_tp=False,
        use_flash_attention=use_flash)
    batch, seq_len, iters = 128, 128, 50
    main_p, startup = pt.Program(), pt.Program()
    with pt.program_guard(main_p, startup):
        avg_loss, _ = transformer.bert_pretrain(cfg, seq_len=seq_len)
        opt = pt.contrib.mixed_precision.decorate(pt.optimizer.Adam(learning_rate=1e-4))
        opt.minimize(avg_loss)
    from __graft_entry__ import _example_feed
    feed = _example_feed(cfg, batch, seq_len)
    exe = pt.Executor()
    with pt.scope_guard(pt.Scope()):
        exe.run(startup)
        exe.run(main_p, feed=feed, fetch_list=[avg_loss])  # compile both sigs
        m = _timing.measure(
            lambda: exe.run(main_p, feed=feed),
            lambda: pt.global_scope().find_var("lm_head.b"),
            iters=iters, passes=2, warmup=1)
        (loss,) = exe.run(main_p, feed=feed, fetch_list=[avg_loss])
        assert np.isfinite(float(np.asarray(loss)))
        from paddle_tpu import tuning
        from paddle_tpu.tuning.learned import store as learned_store
        if learned_store.recording_enabled(tool=True):
            learned_store.record(
                "ab.bert", f"workload=bert b={batch} s={seq_len}", "-",
                tuning.device_kind(), f"flash{int(bool(use_flash))}",
                windows_s=m["windows_s"], median_s=m["median_s"],
                min_s=m["min_s"], band=m["band"], source="ab")
    dt = m["median_s"]
    tokens = batch * seq_len
    H, L_, F, V = 768, 12, 3072, 30522
    n_params = L_ * (4 * H * H + 2 * H * F) + H * V
    step_flops = 6 * n_params * tokens + 12 * L_ * H * seq_len * tokens
    mfu = (step_flops / dt) / 197e12
    print(f"use_flash={use_flash}: {dt*1e3:.1f} ms/step (band "
          f"{m['band']:.3f}), {tokens/dt:,.0f} tok/s, MFU {mfu*100:.1f}%",
          flush=True)


run(sys.argv[1] == "1")
