"""Scratch perf sweep. Usage: python _sweep.py <batch> <seq> <flash:0|1>"""
import sys, time, json
import jax, numpy as np

def run(batch, seq_len, flash):
    import paddle_tpu as pt
    from paddle_tpu.models import transformer
    cfg = transformer.TransformerConfig(
        vocab_size=30522, hidden_size=768, num_layers=12, num_heads=12,
        ffn_size=3072, max_position=max(512, seq_len), dropout=0.0, use_tp=False,
        use_flash_attention=bool(flash))
    iters = 20
    import os as _os
    main_p, startup = pt.Program(), pt.Program()
    with pt.program_guard(main_p, startup):
        avg_loss, _ = transformer.bert_pretrain(cfg, seq_len=seq_len)
        opt = pt.contrib.mixed_precision.decorate(pt.optimizer.Adam(learning_rate=1e-4))
        if _os.environ.get("SWEEP_RECOMPUTE"):
            opt = pt.optimizer.RecomputeOptimizer(opt)
            opt._set_checkpoints(list(transformer.last_layer_outputs))
        opt.minimize(avg_loss)
    from __graft_entry__ import _example_feed
    feed = _example_feed(cfg, batch, seq_len)
    exe = pt.Executor()
    with pt.scope_guard(pt.Scope()):
        exe.run(startup)
        exe.run(main_p, feed=feed, fetch_list=[avg_loss])
        exe.run(main_p, feed=feed)
        np.asarray(pt.global_scope().find_var("lm_head.b"))
        t0 = time.perf_counter()
        for _ in range(iters):
            exe.run(main_p, feed=feed)
        np.asarray(pt.global_scope().find_var("lm_head.b"))
        dt = (time.perf_counter() - t0) / iters
        (loss,) = exe.run(main_p, feed=feed, fetch_list=[avg_loss])
        assert np.isfinite(float(np.asarray(loss))), "loss not finite"
    tokens = batch * seq_len
    H, L_, F, V = cfg.hidden_size, cfg.num_layers, cfg.ffn_size, cfg.vocab_size
    n_params = L_ * (4 * H * H + 2 * H * F) + H * V
    step_flops = 6 * n_params * tokens + 12 * L_ * H * seq_len * tokens
    mfu = (step_flops / dt) / 197e12
    print(json.dumps({"batch": batch, "seq": seq_len, "flash": flash,
                      "tok_s": round(tokens / dt, 1), "mfu": round(mfu, 4)}))

if __name__ == "__main__":
    run(int(sys.argv[1]), int(sys.argv[2]), int(sys.argv[3]))
