"""Attention SUBLAYER probe: qkv-proj -> attention -> out-proj, fwd+bwd,
chained in one jit. Compares:
  a) model-style: reshape/transpose to [B,nh,S,dh], XLA einsum attention
  b) model-style with the pallas short-seq kernel
  c) layout-native: einsum directly on [B,S,nh,dh] (no transposes)
  d) layout-native pallas kernel (blocks index the head dim)
Usage: python tools/_attn_sublayer.py [B] [S] [chain]
"""
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np

sys.path.insert(0, "/root/repo")
from paddle_tpu.ops.pallas_kernels import attention as psa

B = int(sys.argv[1]) if len(sys.argv) > 1 else 128
S = int(sys.argv[2]) if len(sys.argv) > 2 else 128
N = int(sys.argv[3]) if len(sys.argv) > 3 else 24
H, nh, dh = 768, 12, 64
sm = dh ** -0.5
OUTER = 5

rng = np.random.default_rng(0)
x0 = jax.device_put(jnp.asarray(rng.standard_normal((B, S, H)), jnp.bfloat16))
wqkv = jax.device_put(jnp.asarray(
    rng.standard_normal((H, 3 * H)) * 0.02, jnp.bfloat16))
wo = jax.device_put(jnp.asarray(
    rng.standard_normal((H, H)) * 0.02, jnp.bfloat16))
ct = jax.device_put(jnp.asarray(rng.standard_normal((B, S, H)), jnp.bfloat16))


def attn_model_xla(x, wqkv, wo):
    qkv = x @ wqkv                                     # [B,S,3H]
    qkv = qkv.reshape(B, S, 3, nh, dh).transpose(2, 0, 3, 1, 4)
    q, k, v = qkv[0], qkv[1], qkv[2]                   # [B,nh,S,dh]
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k) * sm
    p = jax.nn.softmax(s.astype(jnp.float32), axis=-1).astype(x.dtype)
    o = jnp.einsum("bhqk,bhkd->bhqd", p, v)
    o = o.transpose(0, 2, 1, 3).reshape(B, S, H)
    return o @ wo


def attn_model_pallas(x, wqkv, wo):
    qkv = x @ wqkv
    qkv = qkv.reshape(B, S, 3, nh, dh).transpose(2, 0, 3, 1, 4)
    q, k, v = qkv[0], qkv[1], qkv[2]
    o = psa.short_seq_attention(q, k, v, sm_scale=sm)
    o = o.transpose(0, 2, 1, 3).reshape(B, S, H)
    return o @ wo


def attn_native_xla(x, wqkv, wo):
    qkv = (x @ wqkv).reshape(B, S, 3, nh, dh)
    q, k, v = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]  # [B,S,nh,dh]
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k) * sm
    p = jax.nn.softmax(s.astype(jnp.float32), axis=-1).astype(x.dtype)
    o = jnp.einsum("bhqk,bkhd->bqhd", p, v)
    return o.reshape(B, S, H) @ wo


def attn_native_pallas(x, wqkv, wo):
    qkv = (x @ wqkv).reshape(B, S, 3, nh, dh)
    q, k, v = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]
    o = psa.bsnd_attention(q, k, v, sm_scale=sm)        # [B,S,nh,dh]
    return o.reshape(B, S, H) @ wo


def bench(name, f):
    def loss(x, wqkv, wo):
        return jnp.sum((f(x, wqkv, wo) * ct).astype(jnp.float32))

    @jax.jit
    def run(x, wqkv, wo):
        def body(c, _):
            x, wq, wo_ = c
            dx, dwq, dwo = jax.grad(loss, argnums=(0, 1, 2))(x, wq, wo_)
            return ((x + 0.001 * dx).astype(x.dtype),
                    (wq + 0.001 * dwq).astype(wq.dtype),
                    (wo_ + 0.001 * dwo).astype(wo_.dtype)), None
        (xo, _, _), _ = jax.lax.scan(body, (x, wqkv, wo), None, length=N)
        return xo

    out = run(x0, wqkv, wo)
    np.asarray(out[0, 0, 0], np.float32)
    t0 = time.perf_counter()
    for _ in range(OUTER):
        out = run(x0, wqkv, wo)
    np.asarray(out[0, 0, 0], np.float32)
    dt = (time.perf_counter() - t0) / (OUTER * N)
    print(f"{name:22s} {dt*1e3:8.3f} ms/sublayer(fwd+bwd)")
    return dt


print(f"B={B} S={S} H={H} bf16, chain {N} x {OUTER}")
bench("model xla", attn_model_xla)
bench("model pallas", attn_model_pallas)
bench("native xla", attn_native_xla)
if hasattr(psa, "bsnd_attention"):
    bench("native pallas", attn_native_pallas)

o1 = jax.jit(attn_model_xla)(x0, wqkv, wo)
o2 = jax.jit(attn_model_pallas)(x0, wqkv, wo)
o3 = jax.jit(attn_native_xla)(x0, wqkv, wo)
print("pallas vs xla err:", float(jnp.max(jnp.abs((o1 - o2).astype(jnp.float32)))),
      "native vs model err:", float(jnp.max(jnp.abs((o1 - o3).astype(jnp.float32)))))
