"""Probe: which stage of the fused-attention fwd kernel is slow on v5e.
Variants: qk (scores only), qk_max, softmax (no PV), full, full_perhead.
Usage: python tools/_attn_probe.py [iters]
"""
import functools
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

B, nh, S, dh = 128, 12, 128, 64
gh = 12
iters = int(sys.argv[1]) if len(sys.argv) > 1 else 100
sm = dh ** -0.5

rng = np.random.default_rng(0)
q, k, v = (jax.device_put(jnp.asarray(
    rng.standard_normal((B, nh, S, dh)), jnp.bfloat16)) for _ in range(3))


def hb():
    return pl.BlockSpec((1, gh, S, dh), lambda b, h: (b, h, 0, 0))


def make(kernel, n_in=3):
    return jax.jit(lambda *a: pl.pallas_call(
        kernel,
        grid=(B, nh // gh),
        in_specs=[hb()] * n_in,
        out_specs=hb(),
        out_shape=jax.ShapeDtypeStruct(q.shape, q.dtype),
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel")),
    )(*a))


def k_qk(q_ref, k_ref, v_ref, o_ref):
    qq, kk = q_ref[0], k_ref[0]
    s = jax.lax.dot_general(qq, kk, (((2,), (2,)), ((0,), (0,))),
                            preferred_element_type=jnp.float32)
    # reduce scores back to output shape so nothing is DCE'd
    o_ref[0] = (s[:, :, :dh] * sm).astype(o_ref.dtype)


def k_qk_max(q_ref, k_ref, v_ref, o_ref):
    qq, kk = q_ref[0], k_ref[0]
    s = jax.lax.dot_general(qq, kk, (((2,), (2,)), ((0,), (0,))),
                            preferred_element_type=jnp.float32) * sm
    m = jnp.max(s, axis=-1, keepdims=True)
    o_ref[0] = (s[:, :, :dh] - m).astype(o_ref.dtype)


def k_softmax(q_ref, k_ref, v_ref, o_ref):
    qq, kk = q_ref[0], k_ref[0]
    s = jax.lax.dot_general(qq, kk, (((2,), (2,)), ((0,), (0,))),
                            preferred_element_type=jnp.float32) * sm
    m = jnp.max(s, axis=-1, keepdims=True)
    p = jnp.exp(s - m)
    l = jnp.sum(p, axis=-1, keepdims=True)
    o_ref[0] = ((p / l)[:, :, :dh]).astype(o_ref.dtype)


def k_full(q_ref, k_ref, v_ref, o_ref):
    qq, kk, vv = q_ref[0], k_ref[0], v_ref[0]
    s = jax.lax.dot_general(qq, kk, (((2,), (2,)), ((0,), (0,))),
                            preferred_element_type=jnp.float32) * sm
    m = jnp.max(s, axis=-1, keepdims=True)
    p = jnp.exp(s - m)
    l = jnp.sum(p, axis=-1, keepdims=True)
    o = jax.lax.dot_general(p.astype(vv.dtype), vv,
                            (((2,), (1,)), ((0,), (0,))),
                            preferred_element_type=jnp.float32)
    o_ref[0] = (o / l).astype(o_ref.dtype)


def k_full_perhead(q_ref, k_ref, v_ref, o_ref):
    for g in range(gh):
        qq, kk, vv = q_ref[0, g], k_ref[0, g], v_ref[0, g]
        s = jax.lax.dot_general(qq, kk, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * sm
        m = jnp.max(s, axis=-1, keepdims=True)
        p = jnp.exp(s - m)
        l = jnp.sum(p, axis=-1, keepdims=True)
        o = jax.lax.dot_general(p.astype(vv.dtype), vv,
                                (((1,), (0,)), ((), ())),
                                preferred_element_type=jnp.float32)
        o_ref[0, g] = (o / l).astype(o_ref.dtype)


def k_full_bf16sm(q_ref, k_ref, v_ref, o_ref):
    qq, kk, vv = q_ref[0], k_ref[0], v_ref[0]
    s = jax.lax.dot_general(qq, kk, (((2,), (2,)), ((0,), (0,))),
                            preferred_element_type=jnp.float32) * sm
    m = jnp.max(s, axis=-1, keepdims=True)
    p = jnp.exp((s - m).astype(jnp.bfloat16))
    l = jnp.sum(p.astype(jnp.float32), axis=-1, keepdims=True)
    o = jax.lax.dot_general(p, vv, (((2,), (1,)), ((0,), (0,))),
                            preferred_element_type=jnp.float32)
    o_ref[0] = (o / l).astype(o_ref.dtype)


def k_copy(q_ref, k_ref, v_ref, o_ref):
    o_ref[0] = q_ref[0] + v_ref[0]


def bench(name, fn):
    out = fn(q, k, v)
    np.asarray(out[0, 0, 0], np.float32)
    t0 = time.perf_counter()
    for _ in range(iters):
        out = fn(q, k, v)
    np.asarray(out[0, 0, 0], np.float32)
    dt = (time.perf_counter() - t0) / iters
    print(f"{name:16s} {dt*1e3:8.3f} ms   {dt/B*1e6:6.2f} us/step")


for name, kern in [("copy", k_copy), ("qk", k_qk), ("qk_max", k_qk_max),
                   ("softmax", k_softmax), ("full", k_full),
                   ("full_bf16sm", k_full_bf16sm),
                   ("full_perhead", k_full_perhead)]:
    bench(name, make(kern))
