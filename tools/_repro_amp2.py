import os
os.environ["XLA_FLAGS"] = os.environ.get("XLA_FLAGS","") + " --xla_force_host_platform_device_count=8"
os.environ["JAX_PLATFORMS"] = "cpu"
import jax
jax.config.update("jax_platforms", "cpu")
import numpy as np
import paddle_tpu as pt
from paddle_tpu.models import resnet

main_p, startup = pt.Program(), pt.Program()
with pt.program_guard(main_p, startup):
    loss, acc, _ = resnet.resnet_cifar10()
    opt = pt.contrib.mixed_precision.decorate(pt.optimizer.Momentum(learning_rate=0.1, momentum=0.9))
    opt.minimize(loss)

blk = main_p.blocks[0]
target = "res2.2.c2.w_0@BF16"
for i, op in enumerate(blk.ops):
    ins = [n for ns in op.inputs.values() for n in ns]
    outs = [n for ns in op.outputs.values() for n in ns]
    if target in ins or target in outs:
        print(i, op.type, "IN:", ins, "OUT:", outs)
v = blk.vars.get(target)
print("var dtype:", getattr(v, "dtype", None))
