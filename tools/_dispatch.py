"""Axon tunnel dispatch overhead: N chained no-op-ish calls, total wall time."""
import sys, time
sys.path.insert(0, "/root/repo")
import jax, jax.numpy as jnp, numpy as np

_drain = jax.jit(lambda v: v.reshape(-1)[0])
def drain(x): return np.asarray(_drain(x))

x = jnp.full((1024, 1024), 0.5, jnp.bfloat16)

f = jax.jit(lambda c: c * jnp.asarray(0.999, jnp.bfloat16) + jnp.asarray(0.001, jnp.bfloat16))
drain(f(x))
for N in (1, 5, 20, 50):
    y = x
    t0 = time.perf_counter()
    for _ in range(N):
        y = f(y)
    drain(y)
    dt = time.perf_counter() - t0
    print(f"N={N:>3}: total {dt*1e3:8.2f} ms, per-call {dt/N*1e3:7.2f} ms", flush=True)

# and: how long does a bare drain of an already-materialized array take?
t0 = time.perf_counter()
for _ in range(10):
    drain(x)
print(f"drain alone: {(time.perf_counter()-t0)/10*1e3:.2f} ms", flush=True)
