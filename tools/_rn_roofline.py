"""Per-stage ResNet-50 roofline: measured vs predicted, on the real chip.

For every distinct conv shape in RN50 (batch 128, 224x224) this measures
the sustained per-conv time inside one jit (scan-chained with a real data
dependency so XLA can neither CSE nor slice-propagate — the r3
tools/_conv_inner.py methodology), and compares it against the analytic
roofline max(FLOPs/peak_matmul, bytes/peak_bw) where both peaks are
MEASURED first on the same chip (tools/_peak.py and tools/_hbm_bw.py
patterns). Summing count-weighted times (x3 for fwd+bwd) plus the BN/ReLU/
residual elementwise traffic predicts the full train step; comparing that
with the bench-measured step answers whether 14.8% MFU is a dispatch
problem or the model's arithmetic-intensity ceiling — the committed
per-stage roofline table VERDICT r3 asked for.

Run: python tools/_rn_roofline.py   (prints a markdown table)
"""
import sys
import time

sys.path.insert(0, "/root/repo")
import jax
import jax.numpy as jnp
import numpy as np

B = 128
DT = jnp.bfloat16

_drain = jax.jit(lambda v: v.reshape(-1)[0])


def drain(x):
    return np.asarray(_drain(x))


# (name, Cin, Cout, k, stride, in_hw, count_in_model)
CONVS = [
    ("stem 7x7/2 3-64", 3, 64, 7, 2, 224, 1),
    ("s1 1x1 64-64", 64, 64, 1, 1, 56, 1),
    ("s1 3x3 64-64", 64, 64, 3, 1, 56, 3),
    ("s1 1x1 64-256", 64, 256, 1, 1, 56, 3),
    ("s1 1x1 256-64", 256, 64, 1, 1, 56, 2),
    ("s1 down 1x1 64-256", 64, 256, 1, 1, 56, 1),
    ("s2 1x1 256-128", 256, 128, 1, 1, 56, 1),
    ("s2 3x3/2 128", 128, 128, 3, 2, 56, 1),
    ("s2 1x1 128-512", 128, 512, 1, 1, 28, 4),
    ("s2 down 1x1 256-512/2", 256, 512, 1, 2, 56, 1),
    ("s2 1x1 512-128", 512, 128, 1, 1, 28, 3),
    ("s2 3x3 128", 128, 128, 3, 1, 28, 3),
    ("s3 1x1 512-256", 512, 256, 1, 1, 28, 1),
    ("s3 3x3/2 256", 256, 256, 3, 2, 28, 1),
    ("s3 1x1 256-1024", 256, 1024, 1, 1, 14, 6),
    ("s3 down 1x1 512-1024/2", 512, 1024, 1, 2, 28, 1),
    ("s3 1x1 1024-256", 1024, 256, 1, 1, 14, 5),
    ("s3 3x3 256", 256, 256, 3, 1, 14, 5),
    ("s4 1x1 1024-512", 1024, 512, 1, 1, 14, 1),
    ("s4 3x3/2 512", 512, 512, 3, 2, 14, 1),
    ("s4 1x1 512-2048", 512, 2048, 1, 1, 7, 3),
    ("s4 down 1x1 1024-2048/2", 1024, 2048, 1, 2, 14, 1),
    ("s4 1x1 2048-512", 2048, 512, 1, 1, 7, 2),
    ("s4 3x3 512", 512, 512, 3, 1, 7, 2),
]

K_INNER = 20
OUTER = 5


def measure_matmul_peak():
    N = 8192
    a = jnp.full((N, N), 0.5, DT)
    b = (jnp.eye(N, dtype=jnp.float32)).astype(DT)

    @jax.jit
    def step(s, b):
        for _ in range(5):
            s = s @ b
        return s

    s = step(a, b)
    drain(s)
    t0 = time.perf_counter()
    s2 = s
    for _ in range(20):
        s2 = step(s2, b)
    drain(s2)
    dt = (time.perf_counter() - t0) / (20 * 5)
    return 2 * N ** 3 / dt / 1e12


def measure_bw():
    n = 256 * 1024 * 1024 // 2  # 256 MB bf16
    x = jnp.full((n,), 0.5, DT)

    @jax.jit
    def f(x):
        def body(c, _):
            return c * jnp.asarray(1.000001, DT), None
        y, _ = jax.lax.scan(body, x, None, length=K_INNER)
        return y

    drain(f(x))
    t0 = time.perf_counter()
    for _ in range(OUTER):
        y = f(x)
    drain(y)
    dt = (time.perf_counter() - t0) / OUTER / K_INNER
    return 2 * n * 2 / dt / 1e9  # read+write GB/s


def conv_time(cin, cout, k, stride, hw):
    """Per-conv sustained ms. Same-shape convs chain by direct feedback;
    shape-changing convs carry the input and couple through a full-output
    reduction epilogue (forces the whole conv, adds only output-read)."""
    pad = k // 2
    x = jnp.full((B, hw, hw, cin), 0.5, DT)
    w = jnp.full((k, k, cin, cout), 0.001, DT)
    same = (cin == cout) and stride == 1

    @jax.jit
    def f(x, w):
        def body(c, _):
            y = jax.lax.conv_general_dilated(
                c, w, (stride, stride), [(pad, pad)] * 2,
                dimension_numbers=("NHWC", "HWIO", "NHWC"))
            if same:
                return y * jnp.asarray(0.01, DT), None
            eps = (jnp.mean(y).astype(jnp.float32) * 1e-9).astype(DT)
            return c * (jnp.asarray(1.0, DT) + eps), None

        y, _ = jax.lax.scan(body, x, None, length=K_INNER)
        return y

    drain(f(x, w))
    t0 = time.perf_counter()
    for _ in range(OUTER):
        y = f(x, w)
    drain(y)
    return (time.perf_counter() - t0) / OUTER / K_INNER


def main():
    matmul_tfs = measure_matmul_peak()
    bw = measure_bw()
    print(f"measured peaks: matmul {matmul_tfs:.1f} TF/s, HBM {bw:.0f} GB/s\n")
    print("| conv | n | ms meas | ms roofline | TF/s | bound | model ms (xN) |")
    print("|---|---|---|---|---|---|---|")
    total_fwd = 0.0
    total_roof = 0.0
    for name, cin, cout, k, s, hw, n in CONVS:
        out_hw = hw // s
        flops = 2 * B * cout * cin * k * k * out_hw * out_hw
        bytes_ = 2 * (B * cin * hw * hw + cin * cout * k * k
                      + B * cout * out_hw * out_hw)
        t = conv_time(cin, cout, k, s, hw)
        t_f = flops / (matmul_tfs * 1e12)
        t_b = bytes_ / (bw * 1e9)
        troof = max(t_f, t_b)
        bound = "flops" if t_f > t_b else "bw"
        total_fwd += n * t
        total_roof += n * troof
        print(f"| {name} | {n} | {t*1e3:.3f} | {troof*1e3:.3f} | "
              f"{flops/t/1e12:.1f} | {bound} | {n*t*1e3:.2f} |", flush=True)

    act_elems = (B * 64 * 112 * 112
                 + 3 * (B * (64 + 64 + 256) * 56 * 56)
                 + 4 * (B * (128 + 128 + 512) * 28 * 28)
                 + 6 * (B * (256 + 256 + 1024) * 14 * 14)
                 + 3 * (B * (512 + 512 + 2048) * 7 * 7))
    ew_bytes = act_elems * 2 * 3  # ~3 read/write passes (BN, ReLU, residual)
    ew_time = ew_bytes / (bw * 1e9)
    print(f"\nconv fwd sum: {total_fwd*1e3:.1f} ms measured, "
          f"{total_roof*1e3:.1f} ms roofline")
    print(f"elementwise (BN/ReLU/add) fwd traffic: {ew_bytes/1e9:.2f} GB "
          f"-> {ew_time*1e3:.1f} ms")
    train_meas = 3 * (total_fwd + ew_time)
    train_roof = 3 * (total_roof + ew_time)
    bench_ms = B / 2383 * 1e3
    print(f"predicted train step: {train_meas*1e3:.1f} ms from measured "
          f"convs / {train_roof*1e3:.1f} ms at pure roofline; bench "
          f"measured {bench_ms:.1f} ms")
    from bench import RN50_FWD_FLOPS_PER_IMG
    rn_flops = 3 * RN50_FWD_FLOPS_PER_IMG * B
    print(f"MFU: bench {rn_flops/(bench_ms/1e3)/197e12:.3f}, "
          f"measured-conv pred {rn_flops/train_meas/197e12:.3f}, "
          f"roofline ceiling {rn_flops/train_roof/197e12:.3f}")


if __name__ == "__main__":
    main()
