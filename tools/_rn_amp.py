"""ResNet-50 variants: fp32 vs AMP (gray batch_norm) at batch 128/256."""
import sys, time
sys.path.insert(0, "/root/repo")
import jax, numpy as np


def run(batch, amp, momentum=True):
    import paddle_tpu as pt
    from paddle_tpu.models import resnet
    from paddle_tpu import layers as L

    main_p, startup = pt.Program(), pt.Program()
    with pt.program_guard(main_p, startup):
        img = L.data(name="img", shape=[3, 224, 224], dtype="float32")
        label = L.data(name="label", shape=[1], dtype="int64")
        loss, acc, _ = resnet.resnet50(img, label)
        opt = pt.optimizer.Momentum(learning_rate=0.1, momentum=0.9)
        if amp:
            opt = pt.contrib.mixed_precision.decorate(opt)
        opt.minimize(loss)

    rng = np.random.default_rng(0)
    feed = {
        "img": jax.device_put(rng.standard_normal((batch, 3, 224, 224), dtype=np.float32)),
        "label": jax.device_put(rng.integers(0, 1000, (batch, 1)).astype(np.int32)),
    }
    drain = main_p.all_parameters()[-1].name
    exe = pt.Executor()
    iters = 20
    with pt.scope_guard(pt.Scope()):
        exe.run(startup)
        exe.run(main_p, feed=feed, fetch_list=[loss])
        exe.run(main_p, feed=feed)
        np.asarray(pt.global_scope().find_var(drain))
        t0 = time.perf_counter()
        for _ in range(iters):
            exe.run(main_p, feed=feed)
        np.asarray(pt.global_scope().find_var(drain))
        dt = (time.perf_counter() - t0) / iters
        (lv,) = exe.run(main_p, feed=feed, fetch_list=[loss])
        assert np.isfinite(float(np.asarray(lv))), "loss blew up"
    img_s = batch / dt
    from bench import RN50_FWD_FLOPS_PER_IMG
    mfu = (3 * RN50_FWD_FLOPS_PER_IMG * img_s) / 197e12
    print(f"batch={batch} amp={amp}: {dt*1e3:.1f} ms/step, {img_s:.0f} img/s, MFU {mfu*100:.1f}%", flush=True)


if __name__ == "__main__":
    batch = int(sys.argv[1]) if len(sys.argv) > 1 else 128
    amp = sys.argv[2] == "amp" if len(sys.argv) > 2 else False
    run(batch, amp)
