"""Loop-inside-jit microbench: isolates device compute from tunnel overhead."""
import time
import jax, jax.numpy as jnp, numpy as np
from functools import partial

def drain(x):
    return np.asarray(jax.jit(lambda v: v.reshape(-1)[0])(x))

ITERS = 50
B = 128
for (ci, co, h, w, k) in [(256, 256, 56, 56, 3)]:
    for dtype in (jnp.bfloat16, jnp.float32):
        x = jnp.full((B, ci, h, w), 0.5, dtype)
        wt = jnp.full((co, ci, k, k), 0.001, dtype)
        @jax.jit
        def f(x, wt):
            def body(i, v):
                return jax.lax.conv_general_dilated(
                    v, wt, (1, 1), [(k//2, k//2)]*2,
                    dimension_numbers=("NCHW", "OIHW", "NCHW")) * 0.01
            return jax.lax.fori_loop(0, ITERS, body, x)
        drain(f(x, wt))
        t0 = time.perf_counter(); drain(f(x, wt))
        dt = (time.perf_counter() - t0) / ITERS
        fl = 2 * B * co * ci * k * k * h * w
        print(f"{dtype.__name__} conv {ci}->{co} {h}x{w} k{k}: {dt*1e3:.3f} ms/conv, {fl/dt/1e12:.1f} TF/s", flush=True)

a = jnp.full((8192, 4096), 0.5, jnp.bfloat16)
b = jnp.full((4096, 4096), 0.001, jnp.bfloat16)
@jax.jit
def g(a, b):
    return jax.lax.fori_loop(0, ITERS, lambda i, v: (v @ b) * 0.001, a)
drain(g(a, b))
t0 = time.perf_counter(); drain(g(a, b))
dt = (time.perf_counter() - t0) / ITERS
print(f"matmul 8192x4096x4096 bf16: {dt*1e3:.3f} ms, {2*8192*4096*4096/dt/1e12:.1f} TF/s")
