#!/usr/bin/env python
"""Chaos smoke: train the MLP under a seeded random fault plan and prove
the resilience runtime absorbs every injected failure.

The single-process descendant of running a pod job under a preemption
storm: a `rand:` fault plan fires at the runtime's named sites
(resilience/faults.py) while a CheckpointedRunner trains; the run must
complete, and — because recovery is restore-and-replay with step-keyed
feeds/RNG — the loss trajectory must be BIT-IDENTICAL to the same run with
injection off. A seed that fails replays exactly: re-run with the printed
plan string.

    python tools/chaos.py --steps 8 --p 0.15 --seed 3
    python tools/chaos.py --plan 'collective.step:2;ckpt.write:1'
    python tools/chaos.py --stall   # hang-watchdog smoke: an injected
                                    # pipeline_stall must raise StallError
                                    # (with a state dump), never hang
    python tools/chaos.py --numeric # numeric-guardrail drill: seeded
                                    # numeric_nan/numeric_spike faults
                                    # under FLAGS_guard_numerics — the
                                    # epoch must finish finite with the
                                    # poisoned updates skipped in-graph
    python tools/chaos.py --fleet   # fleet drill: kill / hang / slow-
                                    # heartbeat waves + drain-and-retire
                                    # over the replica fleet; zero lost
                                    # requests, zero duplicate tokens,
                                    # byte-exact greedy outputs

Exit code 0 = survived + trajectory matched; 1 = divergence or crash.
The `chaos` pytest marker (tests/test_chaos.py, tests/test_liveness.py)
runs this same harness — plus the SIGKILL-trainer eviction/rejoin
scenario — fast enough for tier-1.
"""
from __future__ import annotations

import argparse
import os
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
os.environ.setdefault("JAX_PLATFORMS", "cpu")

import numpy as np  # noqa: E402


def _build(seed: int):
    import paddle_tpu as pt
    from paddle_tpu import layers as L

    main, startup = pt.Program(), pt.Program()
    main.random_seed = seed
    startup.random_seed = seed
    with pt.program_guard(main, startup):
        with pt.unique_name.guard():
            img = L.data(name="img", shape=[64], dtype="float32")
            label = L.data(name="label", shape=[1], dtype="int64")
            h = L.fc(img, size=32, act="relu")
            loss = L.mean(L.softmax_with_cross_entropy(L.fc(h, size=10),
                                                       label))
            pt.optimizer.SGD(0.1).minimize(loss)
    return main, startup, loss


def _feed_fn(step: int) -> dict:
    rng = np.random.default_rng(500 + step)
    x = rng.standard_normal((32, 64)).astype(np.float32)
    w = np.random.default_rng(9).standard_normal((64, 10)).astype(np.float32)
    y = np.argmax(x @ w, axis=1).astype(np.int64)[:, None]
    return {"img": x, "label": y}


def _train(plan_spec: str | None, steps: int, seed: int, root: str,
           save_every: int = 2):
    """One training run, optionally under a fault plan. Returns
    (losses, retries, plan_stats)."""
    import paddle_tpu as pt
    from paddle_tpu.resilience import (CheckpointManager, CheckpointedRunner,
                                       fault_scope)

    main, startup, loss = _build(seed)
    with pt.scope_guard(pt.Scope()):
        exe = pt.Executor()
        exe.run(startup)
        runner = CheckpointedRunner(
            exe, CheckpointManager(root, keep_last_k=2), main_program=main,
            save_every=save_every, max_retries=6)
        if plan_spec:
            with fault_scope(plan_spec) as plan:
                out = runner.run(_feed_fn, steps, fetch_list=[loss])
            stats = plan.stats()
        else:
            out = runner.run(_feed_fn, steps, fetch_list=[loss])
            stats = {}
    losses = [float(np.asarray(v[0]).reshape(-1)[0])
              for _, v in sorted(out["results"].items())]
    return losses, out["retries"], stats


def run_chaos(plan_spec: str, steps: int = 8, seed: int = 0,
              root: str | None = None, verbose: bool = True) -> dict:
    """Faulted run + clean baseline; raises AssertionError on divergence.
    Returns {plan, losses, retries, fired, hits}."""
    tmp = root or tempfile.mkdtemp(prefix="chaos_")
    losses, retries, stats = _train(plan_spec, steps, seed,
                                    os.path.join(tmp, "faulted"))
    base, base_retries, _ = _train(None, steps, seed,
                                   os.path.join(tmp, "baseline"))
    if verbose:
        print(f"plan      : {plan_spec}")
        print(f"fired     : {stats.get('fired', [])}")
        print(f"hits      : {stats.get('hits', {})}")
        print(f"retries   : {retries}")
        print(f"losses    : {[round(v, 5) for v in losses]}")
    assert base_retries == 0, "baseline run must be fault-free"
    assert len(losses) == steps, f"run truncated: {len(losses)}/{steps}"
    assert losses == base, (
        f"trajectory diverged under faults:\n  faulted : {losses}\n"
        f"  baseline: {base}")
    return {"plan": plan_spec, "losses": losses, "retries": retries,
            "fired": stats.get("fired", []), "hits": stats.get("hits", {})}


def run_stall_smoke(window_s: float = 0.3) -> dict:
    """Prove the hang watchdog converts a wedged async step into a
    StallError with a state dump (never an indefinite hang): inject
    `pipeline_stall` at the first Executor completion-token drain and
    assert the failure shape. Returns the StallError's state dict."""
    import paddle_tpu as pt
    from paddle_tpu import flags
    from paddle_tpu.resilience import StallError, fault_scope

    main_p, startup, loss = _build(0)
    old = flags.get_flag("watchdog_stall_s")
    flags.set_flags({"watchdog_stall_s": window_s})
    try:
        with pt.scope_guard(pt.Scope()):
            exe = pt.Executor()
            exe.run(startup)
            with fault_scope("pipeline_stall:1"):
                exe.run_async(main_p, feed=_feed_fn(0), fetch_list=[loss])
                try:
                    exe.wait()
                except StallError as e:
                    assert e.state.get("inflight_step_ids"), e.state
                    assert "profiler_stages" in e.state, e.state
                    return e.state
                raise AssertionError(
                    "injected pipeline_stall did not raise StallError")
    finally:
        flags.set_flags({"watchdog_stall_s": old})


def run_numeric_smoke(steps: int = 8, seed: int = 0) -> dict:
    """Numeric-guardrail drill (kill-free): train under seeded numeric_nan
    and numeric_spike faults with FLAGS_guard_numerics on. The in-graph
    sentinel must skip both poisoned updates (params/loss stay finite, no
    rewind needed for isolated bad steps) and the StepGuard must record the
    skip events. Returns {skips, rewinds, final_loss, events}."""
    import paddle_tpu as pt
    from paddle_tpu import flags
    from paddle_tpu.resilience import (CheckpointManager, StepGuard,
                                       fault_scope)

    old = {k: flags.get_flag(k) for k in
           ("guard_numerics", "guard_spike_factor", "max_inflight_steps")}
    flags.set_flags({"guard_numerics": True, "guard_spike_factor": 50.0,
                     "max_inflight_steps": 2})
    try:
        main_p, startup, loss = _build(seed)
        with pt.scope_guard(pt.Scope()) as scope:
            exe = pt.Executor()
            exe.run(startup)
            root = tempfile.mkdtemp(prefix="chaos_numeric_")
            mgr = CheckpointManager(root, main_program=main_p, scope=scope)
            guard = StepGuard(mgr, program=main_p, scope=scope)
            exe.set_step_guard(guard)
            # one healthy step, then the rewind anchor the guard would need
            exe.run(main_p, feed=_feed_fn(0), fetch_list=[loss])
            mgr.save(0, executor=exe)
            # hits count per _run_impl inside the scope: NaN poisons step 3,
            # the 1e4x spike hits step 5 — both isolated, so skips only
            with fault_scope("numeric_nan:3;numeric_spike:5"):
                for step in range(1, steps + 1):
                    exe.run_async(main_p, feed=_feed_fn(step),
                                  fetch_list=[loss])
                exe.wait()
            (lv,) = exe.run(main_p, feed=_feed_fn(steps + 1),
                            fetch_list=[loss])
            final = float(np.asarray(lv).reshape(-1)[0])
            w = np.asarray(scope.find_var(main_p.all_parameters()[0].name))
    finally:
        flags.set_flags(old)
    assert np.isfinite(final), f"final loss not finite: {final}"
    assert np.isfinite(w).all(), "parameters poisoned despite the guard"
    assert guard.skips >= 2, f"expected >=2 skip events, saw {guard.skips}"
    assert guard.rewinds == 0, (
        f"isolated bad steps must not exhaust the budget "
        f"(rewinds={guard.rewinds})")
    reasons = {e["reason"] for e in guard.events}
    assert "nonfinite" in reasons and "loss_spike" in reasons, reasons
    return {"skips": guard.skips, "rewinds": guard.rewinds,
            "final_loss": final, "events": guard.events}


def run_serve_drill(cycles: int = 3, n_req: int = 6, p: float = 0.08,
                    seed: int = 0, verbose: bool = False) -> dict:
    """Serving-resilience drill (ISSUE 14): drive the continuous-batching
    engine through `cycles` open-loop waves of requests under a seeded
    `rand:` plan over the three serving fault sites (step-fail at every
    compiled dispatch, pool-bookkeeping corruption, deadline collapse).
    Every cycle must drain with ZERO page/refcount leaks, a clean
    PagedKVPool.check_consistency audit, and every request in a clean
    terminal state — the engine absorbs isolated faults via retry and
    recovers from the rest via quarantine + pool rebuild + prompt replay.
    Returns per-cycle fired faults and terminal-state tallies."""
    from paddle_tpu.resilience import fault_scope
    from paddle_tpu.serving import ServingEngine
    from paddle_tpu.serving import model as sv_model

    eng = ServingEngine(sv_model.decoder_tiny(), page_size=4, pool_pages=64,
                        max_inflight=4, seed=seed, prefix_cache=True,
                        draft_k=0, audit_every=1, step_retries=2)
    rng = np.random.default_rng(seed)
    clean_terminal = ("finished", "aborted", "deadline_exceeded", "shed")
    cycles_out = []
    for cycle in range(cycles):
        rids = [eng.submit(rng.integers(
                    1, eng.cfg.vocab_size,
                    size=int(rng.integers(3, 9))).tolist(),
                    int(rng.integers(2, 6)))
                for _ in range(n_req)]
        plan = (f"rand:p={p},seed={seed * 101 + cycle},max=8,"
                f"sites=serving_step_fail|serving_pool_corrupt|"
                f"serving_deadline")
        with fault_scope(plan) as fp:
            eng.run_until_drained()
            fired = list(fp.stats()["fired"])
        states = {rid: eng.requests[rid].state for rid in rids}
        bad = {r: s for r, s in states.items() if s not in clean_terminal}
        assert not bad, f"cycle {cycle}: unclean terminal states {bad}"
        problems, _ = eng.audit_pool()
        assert not problems, f"cycle {cycle}: dirty pool audit {problems}"
        leaked = eng.leaked_pages()
        assert leaked == 0, f"cycle {cycle}: leaked {leaked} pages"
        tally: dict = {}
        for s in states.values():
            tally[s] = tally.get(s, 0) + 1
        if verbose:
            print(f"cycle {cycle}: fired={fired} states={tally}")
        cycles_out.append({"plan": plan, "fired": fired, "states": tally})
        eng.prune_finished()
    snap = eng.stats_snapshot()
    return {"cycles": cycles_out,
            "recovery_passes": snap["recovery.passes"],
            "step_retries": snap["step_retries"],
            "deadline_exceeded": snap["deadline_exceeded"],
            "leaked_pages": snap["leaked_pages"]}


def run_fleet_drill(cycles: int = 3, n_req: int = 6, seed: int = 0,
                    n_replicas: int = 3, verbose: bool = False) -> dict:
    """Fleet-resilience drill (ISSUE 16): drive the replica fleet through
    `cycles` waves of requests, each wave under a different seeded fleet
    fault scenario — kill (SIGKILL-style silent death), hang (wedged pump,
    no beats), and sparse slow-heartbeat blips the margined deadline
    must ride out without a death verdict — plus a drain-and-retire
    wave. Every wave must end with ZERO lost requests (every submit
    reaches a clean terminal state), ZERO duplicate token positions (the
    router ledger is append-only by construction, checked via
    dedup/divergence counters), greedy outputs byte-identical to the
    fault-free single-engine oracle, and zero pages leaked on every
    surviving engine. Returns per-cycle fired faults and fleet stats."""
    from paddle_tpu.resilience import fault_scope
    from paddle_tpu.serving import FleetRouter, ServingEngine
    from paddle_tpu.serving import model as sv_model

    def factory():
        return ServingEngine(sv_model.decoder_tiny(), page_size=4,
                             pool_pages=64, max_inflight=4, seed=seed,
                             prefix_cache=True, draft_k=0)

    rng = np.random.default_rng(seed)
    waves = []
    for cycle in range(cycles):
        prompts = [rng.integers(1, 97, size=int(rng.integers(3, 8))).tolist()
                   for _ in range(n_req)]
        max_new = int(rng.integers(4, 9))
        waves.append((prompts, max_new))

    # fault-free oracle: one engine, same seed — the byte-exactness pin
    oracle = factory()
    want = []
    for prompts, max_new in waves:
        rids = [oracle.submit(p, max_new) for p in prompts]
        oracle.run_until_drained()
        want.append([oracle.result(r) for r in rids])
        oracle.prune_finished()

    scenarios = ["fleet_replica_kill", "fleet_replica_hang",
                 "fleet_heartbeat_slow"]
    cycles_out = []
    fr = FleetRouter(factory, n_replicas=n_replicas, heartbeat_s=0.3,
                     affinity=False)
    # compile pass so fault timing hits warmed replicas, not XLA compiles
    warm = [fr.submit([9, 8, 7], 2) for _ in range(n_replicas)]
    fr.run_until_idle()
    assert all(fr.state(f) == "finished" for f in warm)
    fr.reset_stats()
    for cycle, (prompts, max_new) in enumerate(waves):
        site = scenarios[cycle % len(scenarios)]
        alive_before = sum(1 for r in fr.replicas if r.alive)
        if alive_before <= 1:
            fr.add_replica()  # keep a survivor to fail over onto
        # one mid-wave hit for kill/hang. Slow-beat gets SPARSE explicit
        # drops (isolated loaded-host blips, beats in between): the margined
        # deadline must ride them out with zero deaths — a total starve is
        # legitimate fleet-wide death and would (correctly) lose requests,
        # which is the sustained-starve unit test's job, not the drill's
        plan = ("fleet_heartbeat_slow:3,7,11,15"
                if site == "fleet_heartbeat_slow"
                else f"{site}:{4 + 2 * cycle}")
        fids = [fr.submit(p, max_new) for p in prompts]
        with fault_scope(plan) as fp:
            fr.run_until_idle()
            fired = list(fp.stats()["fired"])
        states = {f: fr.state(f) for f in fids}
        lost = {f: s for f, s in states.items() if s != "finished"}
        assert not lost, f"cycle {cycle} ({site}): lost requests {lost}"
        got = [fr.result(f) for f in fids]
        assert got == want[cycle], (
            f"cycle {cycle} ({site}): delivered streams diverged from the "
            f"fault-free oracle")
        assert fr.stats["replay_divergence"] == 0, \
            "greedy replay must never disagree with the delivered ledger"
        for rep in fr.replicas:
            if rep.alive:
                leaked = rep.engine.leaked_pages()
                assert leaked == 0, (
                    f"cycle {cycle}: replica {rep.rid} leaked {leaked}")
        if verbose:
            print(f"cycle {cycle}: site={site} fired={fired} "
                  f"deaths={fr.stats['deaths']} "
                  f"failovers={fr.stats['failovers']} "
                  f"dedup={fr.stats['dedup_tokens']}")
        cycles_out.append({"site": site, "plan": plan, "fired": fired,
                           "states": {"finished": len(fids)}})
    # final wave: drain-and-retire a live replica mid-traffic — zero shed
    healthy = [r.rid for r in fr.replicas if r.state == "healthy"]
    if len(healthy) < 2:
        fr.add_replica()
        healthy = [r.rid for r in fr.replicas if r.state == "healthy"]
    prompts, max_new = waves[0]
    fids = [fr.submit(p, max_new) for p in prompts]
    for _ in range(2):
        fr.step()
    fr.drain(healthy[0])
    fr.run_until_idle()
    assert all(fr.state(f) == "finished" for f in fids), \
        "drain-and-retire must shed nothing"
    assert [fr.result(f) for f in fids] == want[0]
    out = {"cycles": cycles_out, "stats": dict(fr.stats),
           "retired": sum(1 for r in fr.replicas if r.state == "retired")}
    fr.shutdown()
    return out


def run_disagg_drill(cycles: int = 3, n_req: int = 4, seed: int = 0,
                     verbose: bool = False) -> dict:
    """Disaggregation kill-wave drill (ISSUE 19): a 2-prefill + 2-decode
    fleet over ONE shared PagedKVPool serves `cycles` waves, each under a
    different seeded disagg fault — a prefill SIGKILL mid-wave, a dropped
    handoff (lease published, commit never dispatched; the reaper must
    reclaim and replay it), and the lease-expiry race at commit — plus a
    final decode SIGKILL holding adopted pages. Every wave must end with
    ZERO lost requests, greedy outputs byte-identical to the fault-free
    single-engine oracle, ZERO leaked pages on every surviving engine, a
    clean shared-pool audit, and no lease left PREPARED. Returns per-cycle
    fired faults plus the router/handoff stats."""
    from paddle_tpu.resilience import fault_scope
    from paddle_tpu.serving import FleetRouter, ServingEngine
    from paddle_tpu.serving import model as sv_model
    from paddle_tpu.serving.fleet import disagg_fleet_factory

    cfg = sv_model.decoder_tiny()
    rng = np.random.default_rng(seed)
    waves = []
    for _ in range(cycles + 1):  # +1 for the decode-kill finale
        prompts = [rng.integers(1, 97, size=int(rng.integers(3, 8))).tolist()
                   for _ in range(n_req)]
        waves.append((prompts, int(rng.integers(4, 9))))

    oracle = ServingEngine(cfg, page_size=4, pool_pages=96, max_inflight=4,
                           seed=seed, prefix_cache=True, draft_k=0)
    want = []
    for prompts, max_new in waves:
        rids = [oracle.submit(p, max_new) for p in prompts]
        oracle.run_until_drained()
        want.append([oracle.result(r) for r in rids])
        oracle.prune_finished()

    factory = disagg_fleet_factory(cfg, page_size=4, pool_pages=96,
                                   max_inflight=4, seed=seed, draft_k=0)
    fr = FleetRouter(factory, 4,
                     roles=["prefill", "prefill", "decode", "decode"],
                     heartbeat_s=0.3, affinity=False, lease_ttl_s=0.5)
    warm = [fr.submit([9, 8, 7], 2) for _ in range(2)]
    fr.run_until_idle()
    assert all(fr.state(f) == "finished" for f in warm)
    fr.reset_stats()

    def check_wave(cycle, site, fids):
        states = {f: fr.state(f) for f in fids}
        lost = {f: s for f, s in states.items() if s != "finished"}
        assert not lost, f"cycle {cycle} ({site}): lost requests {lost}"
        got = [fr.result(f) for f in fids]
        assert got == want[cycle], (
            f"cycle {cycle} ({site}): delivered streams diverged from the "
            f"fault-free oracle")
        assert fr.stats["replay_divergence"] == 0, \
            "greedy replay must never disagree with the delivered ledger"
        for rep in fr.replicas:
            if rep.alive:
                leaked = rep.engine.leaked_pages()
                assert leaked == 0, (
                    f"cycle {cycle}: replica {rep.rid} leaked {leaked}")
        problems = list(fr.handoff.pool.check_consistency(None))
        assert not problems, f"cycle {cycle}: dirty shared-pool audit " \
                             f"{problems}"
        assert fr.handoff.active() == 0, \
            f"cycle {cycle}: {fr.handoff.active()} lease(s) left PREPARED"

    scenarios = ["disagg_prefill_kill", "disagg_handoff_drop",
                 "disagg_lease_expire_race"]
    cycles_out = []
    for cycle in range(cycles):
        site = scenarios[cycle % len(scenarios)]
        # keep a prefill survivor to replay onto before each kill wave
        if sum(1 for r in fr.replicas
               if r.alive and r.role == "prefill") < 2:
            fr.add_replica("prefill")
        prompts, max_new = waves[cycle]
        fids = [fr.submit(p, max_new) for p in prompts]
        with fault_scope(f"{site}:{2 + cycle}") as fp:
            fr.run_until_idle()
            fired = list(fp.stats()["fired"])
        check_wave(cycle, site, fids)
        if verbose:
            print(f"cycle {cycle}: site={site} fired={fired} "
                  f"deaths={fr.stats['deaths']} "
                  f"reaped={fr.handoff.stats['reaped']} "
                  f"commits={fr.handoff.stats['committed']}")
        cycles_out.append({"site": site, "fired": fired,
                           "states": {"finished": len(fids)}})
    # finale: SIGKILL a decode replica HOLDING adopted pages mid-stream —
    # the forfeit returns them, the ledger dedups the replay
    prompts, max_new = waves[cycles]
    fids = [fr.submit(p, max_new) for p in prompts]
    victim = None
    for _ in range(3000):
        fr.step()
        victim = next((r for r in fr.replicas
                       if r.alive and r.role == "decode"
                       and r.engine.stats["adopts"] > 0
                       and any(q.state == "running"
                               for q in r.engine.requests.values())), None)
        if victim is not None:
            break
    assert victim is not None, "no decode replica ever held adopted work"
    fr.kill(victim.rid)
    fr.run_until_idle()
    check_wave(cycles, "decode_kill_post_adopt", fids)
    out = {"cycles": cycles_out, "stats": dict(fr.stats),
           "handoff": dict(fr.handoff.stats),
           "deaths": fr.stats["deaths"]}
    fr.shutdown()
    return out


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--steps", type=int, default=8)
    ap.add_argument("--seed", type=int, default=0,
                    help="model init seed AND the fault plan seed")
    ap.add_argument("--p", type=float, default=0.15,
                    help="per-hit fault probability for the random plan")
    ap.add_argument("--max-faults", type=int, default=6)
    ap.add_argument("--plan", default=None,
                    help="explicit plan spec (overrides --p/--seed random "
                         "plan)")
    ap.add_argument("--root", default=None,
                    help="checkpoint root (default: fresh temp dir)")
    ap.add_argument("--stall", action="store_true",
                    help="run the hang-watchdog smoke instead of the "
                         "fault-plan trajectory check")
    ap.add_argument("--numeric", action="store_true",
                    help="run the numeric-guardrail drill (seeded "
                         "numeric_nan/numeric_spike under "
                         "FLAGS_guard_numerics)")
    ap.add_argument("--serve", action="store_true",
                    help="run the serving-resilience drill: rand-plan "
                         "faults over serving_step_fail / "
                         "serving_pool_corrupt / serving_deadline; every "
                         "cycle must drain leak-free with a clean pool "
                         "audit and clean terminal states")
    ap.add_argument("--fleet", action="store_true",
                    help="run the fleet-resilience drill: kill / hang / "
                         "slow-heartbeat waves plus drain-and-retire over "
                         "the replica fleet; zero lost requests, zero "
                         "duplicate tokens, byte-exact greedy outputs")
    ap.add_argument("--disagg", action="store_true",
                    help="run the disaggregation kill-wave drill: prefill "
                         "SIGKILL / dropped handoff / lease-expiry race "
                         "waves plus a decode kill holding adopted pages "
                         "over a 2-prefill+2-decode shared-pool fleet; "
                         "zero lost requests, byte-exact outputs, zero "
                         "leaked pages, clean audit every cycle")
    args = ap.parse_args(argv)

    if args.disagg:
        try:
            out = run_disagg_drill(seed=args.seed, verbose=True)
        except Exception as e:  # noqa: BLE001 — CLI boundary
            print(f"DISAGG DRILL FAILED: {e}", file=sys.stderr)
            return 1
        h = out["handoff"]
        print(f"OK: disagg fleet served {len(out['cycles'])} faulted "
              f"wave(s) + decode kill — {out['deaths']} death(s), "
              f"{h['granted']} lease(s) granted / {h['committed']} "
              f"committed / {h['reaped']} reaped, "
              f"{out['stats']['handoff.replays']} handoff replay(s), "
              f"0 leaks, clean audit")
        return 0

    if args.fleet:
        try:
            out = run_fleet_drill(seed=args.seed, verbose=True)
        except Exception as e:  # noqa: BLE001 — CLI boundary
            print(f"FLEET DRILL FAILED: {e}", file=sys.stderr)
            return 1
        s = out["stats"]
        print(f"OK: fleet served {len(out['cycles'])} faulted wave(s) + "
              f"drain — {s['deaths']} death(s), {s['failovers']} "
              f"failover(s), {s['replayed_tokens']} replayed / "
              f"{s['dedup_tokens']} deduped token(s), 0 divergence, "
              f"{out['retired']} retired clean")
        return 0

    if args.serve:
        try:
            out = run_serve_drill(p=args.p or 0.08, seed=args.seed,
                                  verbose=True)
        except Exception as e:  # noqa: BLE001 — CLI boundary
            print(f"SERVE DRILL FAILED: {e}", file=sys.stderr)
            return 1
        fired = sum(len(c["fired"]) for c in out["cycles"])
        print(f"OK: served {len(out['cycles'])} cycle(s) through {fired} "
              f"injected fault(s) — {out['recovery_passes']} recovery "
              f"pass(es), {out['step_retries']} absorbed retries, "
              f"{out['deadline_exceeded']} deadline expiries, 0 leaks")
        return 0

    if args.numeric:
        try:
            out = run_numeric_smoke(steps=args.steps, seed=args.seed)
        except Exception as e:  # noqa: BLE001 — CLI boundary
            print(f"NUMERIC DRILL FAILED: {e}", file=sys.stderr)
            return 1
        print(f"OK: guard skipped {out['skips']} poisoned step(s) "
              f"({[e['reason'] for e in out['events']]}), 0 rewinds, "
              f"final loss {out['final_loss']:.5f} finite")
        return 0

    if args.stall:
        try:
            state = run_stall_smoke()
        except Exception as e:  # noqa: BLE001 — CLI boundary
            print(f"STALL SMOKE FAILED: {e}", file=sys.stderr)
            return 1
        print(f"OK: injected pipeline_stall raised StallError with state "
              f"dump (in-flight steps {state.get('inflight_step_ids')})")
        return 0

    # ps.send/ps.recv need a live pserver; the single-process smoke covers
    # the executor + checkpoint sites (the dist tests cover the wire)
    plan = args.plan or (
        f"rand:p={args.p},seed={args.seed},max={args.max_faults},"
        f"sites=collective.step|executor.compile|ckpt.write")
    try:
        out = run_chaos(plan, steps=args.steps, seed=args.seed,
                        root=args.root)
    except Exception as e:  # noqa: BLE001 — CLI boundary
        print(f"CHAOS FAILED: {e}", file=sys.stderr)
        return 1
    survived = len(out["fired"])
    print(f"OK: survived {survived} injected fault(s), trajectory "
          f"bit-identical to fault-free baseline")
    return 0


if __name__ == "__main__":
    sys.exit(main())
