"""What is ResNet s0/s1 time actually spent on? Differential decomposition.

Times truncated prefixes (stem vs stem+stage) with the stage's
elementwise chain varied:
  bn        — full batch norm (batch stats) + relu + residual  [production]
  scalebias — y*scale+bias + relu + residual (no batch statistics)
  convonly  — convs + residual add only
If bn >> scalebias: the BN statistic reductions (extra HBM passes) bind.
If scalebias ~~ convonly >> roofline: the convs themselves bind (MXU fill).
Run: python tools/_rn_diag.py [stage_index]
"""
import sys
import time

sys.path.insert(0, "/root/repo")
import jax
import jax.numpy as jnp
import numpy as np

B = 128
DT = jnp.bfloat16
DN = ("NHWC", "HWIO", "NHWC")
SI = int(sys.argv[1]) if len(sys.argv) > 1 else 0

rng = np.random.default_rng(0)
_drain = jax.jit(lambda v: v.reshape(-1)[0])

DEPTHS = [3, 4, 6, 3]
CHANS = [64, 128, 256, 512]


def conv_w(k, ci, co):
    w = rng.standard_normal((k, k, ci, co), dtype=np.float32) * \
        np.sqrt(2.0 / (k * k * ci))
    return jnp.asarray(w, DT)


def conv(x, w, s=1):
    k = w.shape[0]
    return jax.lax.conv_general_dilated(
        x, w, (s, s), [(k // 2, k // 2)] * 2, dimension_numbers=DN)


def norm(x, p, mode):
    scale, bias = p
    if mode == "convonly":
        return x
    if mode == "scalebias":
        return (x.astype(jnp.float32) * scale + bias).astype(x.dtype)
    xf = x.astype(jnp.float32)
    m = jnp.mean(xf, axis=(0, 1, 2))
    v = jnp.mean(jnp.square(xf), axis=(0, 1, 2)) - jnp.square(m)
    y = (xf - m) / jnp.sqrt(v + 1e-5)
    return (y * scale + bias).astype(x.dtype)


def make_params(n_stages):
    P = {"stem": (conv_w(7, 3, 64), (jnp.ones(64), jnp.zeros(64)))}
    strides = {}
    ci = 64
    for si in range(n_stages):
        d, c = DEPTHS[si], CHANS[si]
        for bi in range(d):
            pre = f"s{si}b{bi}"
            co = c * 4
            strides[pre] = 2 if (bi == 0 and si > 0) else 1
            blk = {"c1": conv_w(1, ci, c), "b1": (jnp.ones(c), jnp.zeros(c)),
                   "c2": conv_w(3, c, c), "b2": (jnp.ones(c), jnp.zeros(c)),
                   "c3": conv_w(1, c, co),
                   "b3": (jnp.ones(co), jnp.zeros(co))}
            if ci != co:
                blk["proj"] = conv_w(1, ci, co)
                blk["bproj"] = (jnp.ones(co), jnp.zeros(co))
            P[pre] = blk
            ci = co
    return P, strides


def forward(P, strides, n_stages, x, mode):
    x = conv(x, P["stem"][0], 2)
    x = jax.nn.relu(norm(x, P["stem"][1], "bn"))
    x = jax.lax.reduce_window(x, -jnp.inf, jax.lax.max, (1, 3, 3, 1),
                              (1, 2, 2, 1),
                              [(0, 0), (1, 1), (1, 1), (0, 0)])
    for si in range(n_stages):
        m = mode if si == n_stages - 1 else "bn"
        for bi in range(DEPTHS[si]):
            blk = P[f"s{si}b{bi}"]
            s = strides[f"s{si}b{bi}"]
            idn = x
            y = jax.nn.relu(norm(conv(x, blk["c1"], 1), blk["b1"], m))
            y = jax.nn.relu(norm(conv(y, blk["c2"], s), blk["b2"], m))
            y = norm(conv(y, blk["c3"], 1), blk["b3"], m)
            if "proj" in blk:
                idn = norm(conv(idn, blk["proj"], s), blk["bproj"], m)
            x = jax.nn.relu(y + idn)
    return jnp.mean(x.astype(jnp.float32))


def timed_step(n_stages, x, mode):
    P, strides = make_params(n_stages)

    @jax.jit
    def step(P, x):
        loss, g = jax.value_and_grad(
            lambda p: forward(p, strides, n_stages, x, mode))(P)
        P = jax.tree.map(lambda p, gg: p - 0.1 * gg.astype(p.dtype), P, g)
        return P, loss

    P, loss = step(P, x)
    np.asarray(_drain(P["stem"][0]))
    N = 20
    best = np.inf
    for _ in range(2):
        t0 = time.perf_counter()
        for _ in range(N):
            P, loss = step(P, x)
        np.asarray(_drain(P["stem"][0]))
        best = min(best, (time.perf_counter() - t0) / N)
    return best


def main():
    x = jnp.asarray(rng.standard_normal((B, 224, 224, 3), dtype=np.float32),
                    DT)
    t_prev = timed_step(SI, x, "bn")
    print(f"prefix through s{SI-1}: {t_prev*1e3:.1f} ms", flush=True)
    for mode in ("bn", "scalebias", "convonly"):
        t = timed_step(SI + 1, x, mode)
        print(f"s{SI} as {mode:>9}: prefix {t*1e3:.1f} ms, "
              f"stage delta {(t-t_prev)*1e3:.1f} ms", flush=True)


if __name__ == "__main__":
    main()
