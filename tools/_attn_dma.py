"""Probe: pallas DMA throughput vs block shape on v5e (copy kernels).
Usage: python tools/_attn_dma.py [iters]
"""
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

iters = int(sys.argv[1]) if len(sys.argv) > 1 else 100
B, nh, S, dh = 128, 12, 128, 64
rng = np.random.default_rng(0)
x4 = jax.device_put(jnp.asarray(
    rng.standard_normal((B, nh, S, dh)), jnp.bfloat16))
x3 = jax.device_put(jnp.asarray(
    rng.standard_normal((B, S, nh * dh)), jnp.bfloat16))


def bench(name, fn, x):
    out = fn(x)
    np.asarray(out.reshape(-1)[0], np.float32)
    t0 = time.perf_counter()
    for _ in range(iters):
        out = fn(x)
    np.asarray(out.reshape(-1)[0], np.float32)
    dt = (time.perf_counter() - t0) / iters
    gb = 2 * x.size * x.dtype.itemsize / 1e9
    print(f"{name:28s} {dt*1e3:8.3f} ms   {gb/dt:7.1f} GB/s")


def copy4(bb):
    def kern(i_ref, o_ref):
        o_ref[...] = i_ref[...] * 2.0
    return jax.jit(lambda x: pl.pallas_call(
        kern, grid=(B // bb,),
        in_specs=[pl.BlockSpec((bb, nh, S, dh), lambda b: (b, 0, 0, 0))],
        out_specs=pl.BlockSpec((bb, nh, S, dh), lambda b: (b, 0, 0, 0)),
        out_shape=jax.ShapeDtypeStruct(x.shape, x.dtype),
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel",)),
    )(x))


def copy3(bb):
    def kern(i_ref, o_ref):
        o_ref[...] = i_ref[...] * 2.0
    return jax.jit(lambda x: pl.pallas_call(
        kern, grid=(B // bb,),
        in_specs=[pl.BlockSpec((bb, S, nh * dh), lambda b: (b, 0, 0))],
        out_specs=pl.BlockSpec((bb, S, nh * dh), lambda b: (b, 0, 0)),
        out_shape=jax.ShapeDtypeStruct(x.shape, x.dtype),
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel",)),
    )(x))


def xla_copy(x):
    return x * 2.0


bench("xla copy", jax.jit(xla_copy), x4)
for bb in (1, 4, 16):
    bench(f"pallas [b,nh,S,dh] bb={bb}", copy4(bb), x4)
for bb in (1, 4, 16):
    bench(f"pallas [b,S,H] bb={bb}", copy3(bb), x3)
