import os
os.environ["XLA_FLAGS"] = os.environ.get("XLA_FLAGS","") + " --xla_force_host_platform_device_count=8"
os.environ["JAX_PLATFORMS"] = "cpu"
import jax
jax.config.update("jax_platforms", "cpu")
import numpy as np
import paddle_tpu as pt
from paddle_tpu.models import resnet

main_p, startup = pt.Program(), pt.Program()
with pt.program_guard(main_p, startup):
    loss, acc, _ = resnet.resnet_cifar10()
    opt = pt.contrib.mixed_precision.decorate(pt.optimizer.Momentum(learning_rate=0.1, momentum=0.9))
    opt.minimize(loss)

rng = np.random.default_rng(0)
feed = {"img": rng.standard_normal((4, 3, 32, 32), dtype=np.float32),
        "label": rng.integers(0, 10, (4, 1)).astype(np.int64)}
exe = pt.Executor()
with pt.scope_guard(pt.Scope()):
    exe.run(startup)
    out = exe.run(main_p, feed=feed, fetch_list=[loss])
    print("OK loss=", float(np.asarray(out[0])))
