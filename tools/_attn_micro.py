"""Microbench: short-seq Pallas attention vs XLA attention at BERT shapes.

Chains N applications inside ONE jit (per-dispatch tunnel overhead is
~1.1 ms — see tools/_attn_dma.py — so per-call timing lies).
Usage: python tools/_attn_micro.py [B] [S] [dh] [chain_len]
"""
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np

sys.path.insert(0, "/root/repo")
from paddle_tpu.ops.attention_ops import _reference_attention
from paddle_tpu.ops.pallas_kernels import attention as psa

B = int(sys.argv[1]) if len(sys.argv) > 1 else 128
S = int(sys.argv[2]) if len(sys.argv) > 2 else 128
dh = int(sys.argv[3]) if len(sys.argv) > 3 else 64
N = int(sys.argv[4]) if len(sys.argv) > 4 else 40
nh = 12
sm = dh ** -0.5
OUTER = 5

rng = np.random.default_rng(0)
q, k, v = (jax.device_put(jnp.asarray(
    rng.standard_normal((B, nh, S, dh)), jnp.bfloat16)) for _ in range(3))


def chain_fwd(attn_fn):
    @jax.jit
    def run(q, k, v):
        def body(qc, _):
            return attn_fn(qc, k, v).astype(qc.dtype), None
        out, _ = jax.lax.scan(body, q, None, length=N)
        return out
    return run


def chain_fwdbwd(attn_fn):
    def loss(qc, k, v):
        return jnp.sum(attn_fn(qc, k, v).astype(jnp.float32))

    @jax.jit
    def run(q, k, v):
        def body(qc, _):
            dq, dk, dv = jax.grad(loss, argnums=(0, 1, 2))(qc, k, v)
            return (qc + 0.001 * (dq + dk + dv)).astype(qc.dtype), None
        out, _ = jax.lax.scan(body, q, None, length=N)
        return out
    return run


def bench(name, run, flops_per_app):
    out = run(q, k, v)
    np.asarray(out[0, 0, 0], np.float32)
    t0 = time.perf_counter()
    for _ in range(OUTER):
        out = run(q, k, v)
    np.asarray(out[0, 0, 0], np.float32)
    dt = (time.perf_counter() - t0) / (OUTER * N)
    print(f"{name:24s} {dt*1e3:8.3f} ms/app  ({flops_per_app/dt/1e12:6.2f} TF/s)")
    return dt


def pallas_attn(q, k, v):
    return psa.short_seq_attention(q, k, v, sm_scale=sm)


def xla_attn(q, k, v):
    return _reference_attention(q, k, v, sm_scale=sm)


fwd_flops = 2 * 2 * B * nh * S * S * dh
print(f"B={B} nh={nh} S={S} dh={dh} bf16, chain {N} x {OUTER}")
bench("xla fwd", chain_fwd(xla_attn), fwd_flops)
bench("pallas fwd", chain_fwd(pallas_attn), fwd_flops)
bench("xla fwd+bwd", chain_fwdbwd(xla_attn), fwd_flops * 3.5)
bench("pallas fwd+bwd", chain_fwdbwd(pallas_attn), fwd_flops * 3.5)

o1 = jax.jit(pallas_attn)(q, k, v)
o2 = jax.jit(xla_attn)(q, k, v)
err = float(jnp.max(jnp.abs(o1.astype(jnp.float32) - o2.astype(jnp.float32))))
print("max fwd err:", err)
