"""Pure conv kernel time: chain K convs inside ONE jit to amortize dispatch."""
import time
import jax, jax.numpy as jnp, numpy as np

_drain = jax.jit(lambda v: v.reshape(-1)[0])
def drain(x): return np.asarray(_drain(x))

B = 128
K_INNER = 20
SHAPES = [
    (64, 64, 56, 56, 3),
    (256, 256, 56, 56, 3),
    (128, 128, 28, 28, 3),
    (512, 512, 28, 28, 3),
    (256, 256, 14, 14, 3),
    (512, 512, 7, 7, 3),
    (64, 64, 56, 56, 1),
    (512, 512, 7, 7, 1),
]
for (ci, co, h, w, k) in SHAPES:
    fl = 2 * B * co * ci * k * k * h * w * K_INNER
    x = jnp.full((B, h, w, ci), 0.5, jnp.bfloat16)
    wt = jnp.full((k, k, ci, co), 0.001, jnp.bfloat16)

    @jax.jit
    def f(x, wt):
        def body(c, _):
            y = jax.lax.conv_general_dilated(
                c, wt, (1, 1), [(k//2, k//2)]*2,
                dimension_numbers=("NHWC", "HWIO", "NHWC"))
            return y * 0.01, None
        y, _ = jax.lax.scan(body, x, None, length=K_INNER)
        return y
    drain(f(x, wt))
    t0 = time.perf_counter()
    for _ in range(5):
        y = f(x, wt)
    drain(y)
    dt = (time.perf_counter() - t0) / 5
    print(f"{ci:>4}->{co:<4} {h:>3}x{w:<3} k{k}: {dt/K_INNER*1e3:7.3f} ms/conv {fl/dt/1e12:6.1f} TF/s", flush=True)
