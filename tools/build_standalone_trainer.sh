#!/bin/sh
# Build the native standalone trainer (paddle_tpu/native/standalone_trainer.c).
set -e
DIR="$(cd "$(dirname "$0")/.." && pwd)"
SRC="$DIR/paddle_tpu/native/standalone_trainer.c"
OUT="${1:-$DIR/paddle_tpu/native/standalone_trainer}"
CFLAGS="$(python3-config --includes)"
LDFLAGS="$(python3-config --ldflags --embed 2>/dev/null || python3-config --ldflags)"
${CC:-cc} -O2 "$SRC" $CFLAGS $LDFLAGS -o "$OUT"
echo "built $OUT"
