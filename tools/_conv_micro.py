"""Raw JAX conv microbench; axon tunnel: sync via host read, not block_until_ready."""
import time
import jax, jax.numpy as jnp, numpy as np

def drain(x):
    return np.asarray(jax.jit(lambda v: v.reshape(-1)[0])(x))

B = 128
for dtype in (jnp.bfloat16, jnp.float32):
    for (ci, co, h, w, k) in [(256, 256, 56, 56, 3), (512, 512, 28, 28, 3)]:
        x = jnp.full((B, ci, h, w), 0.5, dtype)
        wt = jnp.full((co, ci, k, k), 0.001, dtype)
        f = jax.jit(lambda x, wt: jax.lax.conv_general_dilated(
            x, wt, (1, 1), [(k//2, k//2)]*2,
            dimension_numbers=("NCHW", "OIHW", "NCHW")) * 0.01)
        y = f(x, wt); drain(y)
        t0 = time.perf_counter()
        y = x
        for _ in range(20):
            y = f(y, wt)
        drain(y)
        dt = (time.perf_counter() - t0) / 20
        fl = 2 * B * co * ci * k * k * h * w
        print(f"{dtype.__name__} conv {ci}->{co} {h}x{w} k{k}: {dt*1e3:.2f} ms, {fl/dt/1e12:.1f} TF/s", flush=True)

a = jnp.full((8192, 4096), 0.5, jnp.bfloat16)
b = jnp.full((4096, 4096), 0.001, jnp.bfloat16)
f = jax.jit(lambda a, b: (a @ b))
drain(f(a, b))
t0 = time.perf_counter()
z = a
for _ in range(20):
    z = f(z, b)
drain(z)
dt = (time.perf_counter() - t0) / 20
print(f"matmul 8192x4096x4096 bf16: {dt*1e3:.2f} ms, {2*8192*4096*4096/dt/1e12:.1f} TF/s")
