"""A/B: Pallas fused bottleneck block vs XLA's fusion of the same region.

The VERDICT-r3-named lever for ResNet-50: fuse 1x1(256-64)+ReLU ->
3x3(64-64)+ReLU -> 1x1(64-256)+residual+ReLU into ONE kernel so the two
64-channel intermediates never round-trip HBM (saves ~205 MB/step of
traffic for an s1-interior block at batch 128). BN is taken in folded
scale/bias form on BOTH sides so the A/B isolates the conv-chain cost
(training-BN batch stats would add identical global reductions to both).

Grid: one image per kernel instance (the whole 56x56x256 activation is
1.6 MB — fits VMEM with all weights and intermediates). The 3x3 runs as 9
shifted [HW, 64]x[64, 64] matmuls accumulating in fp32.

Run: python tools/_rn_pallas_block.py

MEASURED RESULT (r4, v5e through axon): single-shot (one block per jit,
~3.8 ms dispatch floor included on both sides) Pallas 4.59 ms vs XLA
5.15 ms — an apparent 1.12x win. Chained 10-deep inside one jit (the
realistic in-graph setting, dispatch amortized): XLA 1.29 ms/block
(43.3 TF/s) vs Pallas 1.59 ms/block (35.1 TF/s) — XLA WINS by 1.23x,
because it fuses ACROSS block boundaries (block i's add+relu into block
i+1's 1x1) while pallas_call is an opaque fusion barrier. The r3-named
"fused conv+BN+ReLU Pallas chain" lever is therefore measured and
retired: XLA's own fusion already does this better on these shapes.
"""
import sys
import time
from functools import partial

sys.path.insert(0, "/root/repo")
import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

B, H, W, C, M = 128, 56, 56, 256, 64
DT = jnp.bfloat16
_drain = jax.jit(lambda v: v.reshape(-1)[0])


def kernel(x_ref, w1_ref, w2_ref, w3_ref, o_ref, y1p_ref):
    x = x_ref[0]                                   # [H, W, C] bf16
    xm = x.reshape(H * W, C)
    y1 = jnp.dot(xm, w1_ref[...], preferred_element_type=jnp.float32)
    y1 = jnp.maximum(y1, 0.0).astype(DT).reshape(H, W, M)
    # zero-padded copy for the 3x3 halo
    y1p_ref[...] = jnp.zeros((H + 2, W + 2, M), DT)
    y1p_ref[1:H + 1, 1:W + 1, :] = y1
    acc = jnp.zeros((H * W, M), jnp.float32)
    for dy in range(3):
        for dx in range(3):
            sh = y1p_ref[dy:dy + H, dx:dx + W, :].reshape(H * W, M)
            acc += jnp.dot(sh, w2_ref[dy, dx],
                           preferred_element_type=jnp.float32)
    y2 = jnp.maximum(acc, 0.0).astype(DT)
    y3 = jnp.dot(y2, w3_ref[...], preferred_element_type=jnp.float32)
    out = jnp.maximum(y3.reshape(H, W, C) + x.astype(jnp.float32), 0.0)
    o_ref[0] = out.astype(DT)


@jax.jit
def pallas_block(x, w1, w2, w3):
    return pl.pallas_call(
        kernel,
        grid=(B,),
        in_specs=[
            pl.BlockSpec((1, H, W, C), lambda b: (b, 0, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec(memory_space=pltpu.VMEM),
            pl.BlockSpec(memory_space=pltpu.VMEM),
            pl.BlockSpec(memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((1, H, W, C), lambda b: (b, 0, 0, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((B, H, W, C), DT),
        scratch_shapes=[pltpu.VMEM((H + 2, W + 2, M), DT)],
    )(x, w1, w2, w3)


@jax.jit
def xla_block(x, w1, w2, w3):
    y1 = jax.lax.conv_general_dilated(
        x, w1.reshape(1, 1, C, M), (1, 1), [(0, 0), (0, 0)],
        dimension_numbers=("NHWC", "HWIO", "NHWC"))
    y1 = jax.nn.relu(y1)
    y2 = jax.lax.conv_general_dilated(
        y1, w2.reshape(3, 3, M, M), (1, 1), [(1, 1), (1, 1)],
        dimension_numbers=("NHWC", "HWIO", "NHWC"))
    y2 = jax.nn.relu(y2)
    y3 = jax.lax.conv_general_dilated(
        y2, w3.reshape(1, 1, M, C), (1, 1), [(0, 0), (0, 0)],
        dimension_numbers=("NHWC", "HWIO", "NHWC"))
    return jax.nn.relu(y3 + x.astype(y3.dtype)).astype(DT)


def timeit(fn, args, n=30):
    out = fn(*args)
    np.asarray(_drain(out))
    t0 = time.perf_counter()
    for _ in range(n):
        out = fn(*args)
    np.asarray(_drain(out))
    return (time.perf_counter() - t0) / n


K_CHAIN = 10


@jax.jit
def xla_chain(x, w1, w2, w3):
    c = x
    for _ in range(K_CHAIN):
        c = xla_block(c, w1, w2, w3)
    return c


@jax.jit
def pallas_chain(x, w1, w2, w3):
    c = x
    for _ in range(K_CHAIN):
        c = pallas_block(c, w1, w2, w3)
    return c


def main():
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.standard_normal((B, H, W, C), dtype=np.float32) * .01,
                    DT)
    w1 = jnp.asarray(rng.standard_normal((C, M), dtype=np.float32) * .02, DT)
    w2 = jnp.asarray(rng.standard_normal((3, 3, M, M), dtype=np.float32) * .02,
                     DT)
    w3 = jnp.asarray(rng.standard_normal((M, C), dtype=np.float32) * .02, DT)

    ref = np.asarray(xla_block(x, w1, w2, w3), np.float32)
    got = np.asarray(pallas_block(x, w1, w2, w3), np.float32)
    err = np.abs(ref - got).max() / (np.abs(ref).max() + 1e-6)
    print(f"max rel err pallas vs xla: {err:.4f}")

    fl = 2 * B * H * W * (C * M + 9 * M * M + M * C)
    t_x = timeit(xla_block, (x, w1, w2, w3))
    t_p = timeit(pallas_block, (x, w1, w2, w3))
    print(f"single-shot (incl ~3.8 ms dispatch floor on both):")
    print(f"  XLA   : {t_x*1e3:.3f} ms  ({fl/t_x/1e12:.1f} TF/s)")
    print(f"  Pallas: {t_p*1e3:.3f} ms  ({fl/t_p/1e12:.1f} TF/s)")

    # the decisive measurement: chained in one jit, dispatch amortized —
    # this is what the block costs INSIDE a model graph
    t_xc = timeit(xla_chain, (x, w1, w2, w3), n=20) / K_CHAIN
    t_pc = timeit(pallas_chain, (x, w1, w2, w3), n=20) / K_CHAIN
    print(f"chained x{K_CHAIN} (in-graph):")
    print(f"  XLA   : {t_xc*1e3:.3f} ms/block  ({fl/t_xc/1e12:.1f} TF/s)")
    print(f"  Pallas: {t_pc*1e3:.3f} ms/block  ({fl/t_pc/1e12:.1f} TF/s)")
    print(f"  XLA advantage: {t_pc/t_xc:.2f}x")


if __name__ == "__main__":
    main()
