#!/usr/bin/env python
"""obs.py — read the telemetry the unified registry ships (ISSUE 13).

The observability layer writes two artifact kinds: the JSONL event/span
stream (FLAGS_obs_jsonl_dir/obs.jsonl, one canonical-encoded record per
line) and snapshot files (registry `snapshot()` dumped as JSON, or the
Prometheus text exposition). This CLI is the read side — no server, no
deps, works on a laptop against files scp'd off a TPU host.

Usage:
    python tools/obs.py tail FILE.jsonl [-n N] [--follow]
    python tools/obs.py summarize FILE.jsonl
        # per-name event counts by level + span count/p50/p95/total
    python tools/obs.py diff OLD.json NEW.json
        # counter deltas, gauge moves, histogram p99 shifts between two
        # registry snapshot() JSON files
    python tools/obs.py prom FILE.prom
        # strict-parse a Prometheus exposition file -> JSON on stdout;
        # exits 1 on any unparseable line (the round-trip check as a tool)

Exit status: 0 on success, 1 on malformed input, 2 on usage error.
"""
from __future__ import annotations

import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def _read_jsonl(path: str) -> list[dict]:
    """Parse a JSONL stream, skipping (but counting) malformed lines — a
    torn final line from a live writer must not kill the reader."""
    recs, bad = [], 0
    with open(path, "rb") as f:
        for ln in f:
            ln = ln.strip()
            if not ln:
                continue
            try:
                recs.append(json.loads(ln))
            except ValueError:
                bad += 1
    if bad:
        print(f"[obs] WARN: skipped {bad} malformed line(s) in {path}",
              file=sys.stderr)
    return recs


def cmd_tail(argv: list[str]) -> int:
    path = argv[0]
    n = 20
    if "-n" in argv:
        n = int(argv[argv.index("-n") + 1])
    follow = "--follow" in argv or "-f" in argv
    recs = _read_jsonl(path)
    for rec in recs[-n:]:
        sys.stdout.write(json.dumps(rec, sort_keys=True) + "\n")
    if not follow:
        return 0
    sys.stdout.flush()
    with open(path, "rb") as f:
        f.seek(0, os.SEEK_END)
        while True:
            ln = f.readline()
            if not ln:
                time.sleep(0.25)
                continue
            try:
                rec = json.loads(ln)
            except ValueError:
                continue  # torn line mid-write; the next read completes it
            sys.stdout.write(json.dumps(rec, sort_keys=True) + "\n")
            sys.stdout.flush()


def _pctl(sorted_vals: list[float], q: float) -> float:
    i = min(len(sorted_vals) - 1, int(q * len(sorted_vals)))
    return sorted_vals[i]


def cmd_summarize(argv: list[str]) -> int:
    recs = _read_jsonl(argv[0])
    events: dict[str, dict[str, int]] = {}
    spans: dict[str, list[float]] = {}
    other = 0
    for rec in recs:
        kind, name = rec.get("type"), rec.get("name", "?")
        if kind == "event":
            lv = events.setdefault(name, {})
            level = rec.get("level", "info")
            lv[level] = lv.get(level, 0) + 1
        elif kind == "span":
            spans.setdefault(name, []).append(float(rec.get("dur_s", 0.0)))
        else:
            other += 1
    print(f"{len(recs)} records "
          f"({sum(sum(v.values()) for v in events.values())} events, "
          f"{sum(len(v) for v in spans.values())} spans, {other} other)")
    if events:
        print("\nevents:")
        for name in sorted(events):
            by = events[name]
            lv = " ".join(f"{k}={by[k]}" for k in sorted(by))
            print(f"  {name:<28} {sum(by.values()):>7}  ({lv})")
    if spans:
        print("\nspans:")
        print(f"  {'name':<28} {'count':>7} {'p50_ms':>9} {'p95_ms':>9} "
              f"{'total_s':>9}")
        for name in sorted(spans):
            vs = sorted(spans[name])
            print(f"  {name:<28} {len(vs):>7} "
                  f"{_pctl(vs, 0.50) * 1e3:>9.3f} "
                  f"{_pctl(vs, 0.95) * 1e3:>9.3f} {sum(vs):>9.3f}")
    return 0


def cmd_diff(argv: list[str]) -> int:
    with open(argv[0]) as f:
        old = json.load(f)
    with open(argv[1]) as f:
        new = json.load(f)
    rows: list[str] = []
    oc, nc = old.get("counters", {}), new.get("counters", {})
    for k in sorted(set(oc) | set(nc)):
        d = nc.get(k, 0) - oc.get(k, 0)
        if d:
            rows.append(f"  counter  {k:<36} {d:+g}")
    og, ng = old.get("gauges", {}), new.get("gauges", {})
    for k in sorted(set(og) | set(ng)):
        a, b = og.get(k), ng.get(k)
        if a != b:
            rows.append(f"  gauge    {k:<36} {a} -> {b}")
    oh, nh = old.get("histograms", {}), new.get("histograms", {})
    for k in sorted(set(oh) | set(nh)):
        a = (oh.get(k) or {}).get("p99")
        b = (nh.get(k) or {}).get("p99")
        if a != b:
            fa = "-" if a is None else f"{a:.6g}"
            fb = "-" if b is None else f"{b:.6g}"
            rows.append(f"  hist p99 {k:<36} {fa} -> {fb}")
    if rows:
        print(f"{os.path.basename(argv[0])} -> {os.path.basename(argv[1])}:")
        print("\n".join(rows))
    else:
        print("no differences")
    return 0


def cmd_prom(argv: list[str]) -> int:
    from paddle_tpu.observability import parse_prometheus

    with open(argv[0]) as f:
        text = f.read()
    try:
        series = parse_prometheus(text)
    except ValueError as e:
        print(f"[obs] FAIL: {argv[0]}: {e}", file=sys.stderr)
        return 1
    json.dump(series, sys.stdout, indent=2, sort_keys=True)
    sys.stdout.write("\n")
    return 0


def main() -> int:
    cmds = {"tail": (cmd_tail, 1), "summarize": (cmd_summarize, 1),
            "diff": (cmd_diff, 2), "prom": (cmd_prom, 1)}
    if len(sys.argv) < 2 or sys.argv[1] not in cmds:
        print(__doc__.strip(), file=sys.stderr)
        return 2
    fn, min_args = cmds[sys.argv[1]]
    argv = sys.argv[2:]
    if len(argv) < min_args:
        print(f"[obs] usage error: {sys.argv[1]} needs {min_args} "
              f"file argument(s)", file=sys.stderr)
        return 2
    try:
        return fn(argv)
    except OSError as e:
        print(f"[obs] FAIL: {e}", file=sys.stderr)
        return 1


if __name__ == "__main__":
    sys.exit(main())
