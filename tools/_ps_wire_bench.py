"""PS wire-frame throughput microbench (VERDICT r4 #4 'recorded localhost
throughput number').

Measures the full client->server->client path of distributed/ps_rpc.py on
localhost: dense send MB/s, get MB/s, and small-message round-trips/s,
against a live PServerRuntime with a no-op optimize program replaced by a
buffering sink (we bench the TRANSPORT, so the server runs with sync_mode
False and a grad name that has no registered block — the frame is parsed,
buffered, and dropped). Run: python tools/_ps_wire_bench.py
"""
import os
import sys
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
import numpy as np

from paddle_tpu.distributed.ps_rpc import (PSClient, PServerRuntime, _pack,
                                           _unpack)


def codec_bench():
    arr = np.random.default_rng(0).standard_normal((64, 1 << 18)).astype(
        np.float32)  # 64 MB
    t0 = time.perf_counter()
    n = 10
    for _ in range(n):
        buf = _pack({"op": "send", "name": "w", "trainer": 0,
                     "kind": "dense"}, [arr])
    t1 = time.perf_counter()
    for _ in range(n):
        meta, (out,) = _unpack(buf)
    t2 = time.perf_counter()
    mb = arr.nbytes / 1e6
    print(f"codec: pack {mb * n / (t1 - t0):.0f} MB/s, "
          f"unpack {mb * n / (t2 - t1):.0f} MB/s "
          f"(frame overhead {len(buf) - arr.nbytes} bytes)", flush=True)
    assert np.array_equal(out, arr)


def transport_bench():
    import paddle_tpu as pt

    ep = "127.0.0.1:29517"
    scope = pt.Scope()
    big = np.random.default_rng(1).standard_normal((16, 1 << 18)).astype(
        np.float32)  # 16 MB
    scope.set_var("w", big)
    srv = PServerRuntime(ep, n_trainers=1, sync_mode=False, blocks=[],
                         scope=scope, executor=pt.Executor())
    th = threading.Thread(target=srv.serve, daemon=True)
    th.start()
    cli = PSClient([ep], trainer_id=0)

    # 16MB gets
    n = 300
    t0 = time.perf_counter()
    for _ in range(n):
        cli.get_var(ep, "w")
    dt = time.perf_counter() - t0
    # true small-message ping: get of a tiny var
    scope.set_var("tiny", np.zeros(1, np.float32))
    t0 = time.perf_counter()
    for _ in range(n):
        cli.get_var(ep, "tiny")
    small_dt = time.perf_counter() - t0
    print(f"transport: get 16MB x{n}: {16 * n / dt:.0f} MB/s; "
          f"small round-trips {n / small_dt:.0f}/s", flush=True)

    # dense send path (unregistered grad name: parsed + buffered + dropped)
    t0 = time.perf_counter()
    for _ in range(n // 3):
        cli.send_var(ep, "g", big)
    dt = time.perf_counter() - t0
    print(f"transport: send 16MB x{n // 3}: {16 * (n // 3) / dt:.0f} MB/s",
          flush=True)
    cli.send_complete()
    th.join(timeout=5)


if __name__ == "__main__":
    codec_bench()
    transport_bench()
