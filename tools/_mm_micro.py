import time
import jax, jax.numpy as jnp, numpy as np

def drain(x):
    return np.asarray(jax.jit(lambda v: v.reshape(-1)[0])(x))

a = jnp.full((8192, 4096), 0.5, jnp.bfloat16)
b = jnp.full((4096, 4096), 0.001, jnp.bfloat16)
N = 50
@jax.jit
def g(a, b):
    v = a
    for _ in range(N):
        v = v @ b
    return v
drain(g(a, b))
t0 = time.perf_counter(); drain(g(a, b))
dt = (time.perf_counter() - t0) / N
print(f"unrolled matmul chain: {dt*1e3:.3f} ms/mm, {2*8192*4096*4096/dt/1e12:.1f} TF/s")
# and the drain latency itself
t0 = time.perf_counter()
for _ in range(5):
    drain(a)
print(f"drain latency: {(time.perf_counter()-t0)/5*1e3:.1f} ms")
