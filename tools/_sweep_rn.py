"""Scratch ResNet-50 perf sweep behind PERF.md numbers.
Usage: python tools/_sweep_rn.py <batch>   (SWEEP_AMP=0 for the fp32 variant)"""
import os, sys, time, json
import jax, numpy as np

def run(batch):
    import paddle_tpu as pt
    from paddle_tpu.models import resnet
    iters = 20
    main_p, startup = pt.Program(), pt.Program()
    with pt.program_guard(main_p, startup):
        loss, acc, _ = resnet.resnet50()
        opt = pt.optimizer.Momentum(learning_rate=0.1, momentum=0.9)
        if os.environ.get("SWEEP_AMP", "1") != "0":
            opt = pt.contrib.mixed_precision.decorate(opt)
        opt.minimize(loss)
    rng = np.random.default_rng(0)
    feed = {"img": jax.device_put(rng.standard_normal((batch, 3, 224, 224), dtype=np.float32)),
            "label": jax.device_put(rng.integers(0, 1000, (batch, 1)).astype(np.int32))}
    exe = pt.Executor()
    with pt.scope_guard(pt.Scope()):
        exe.run(startup)
        exe.run(main_p, feed=feed, fetch_list=[loss])
        exe.run(main_p, feed=feed)
        np.asarray(pt.global_scope().find_var("fc_0.b_0"))
        t0 = time.perf_counter()
        for _ in range(iters):
            exe.run(main_p, feed=feed)
        np.asarray(pt.global_scope().find_var("fc_0.b_0"))
        dt = (time.perf_counter() - t0) / iters
        (lv,) = exe.run(main_p, feed=feed, fetch_list=[loss])
        assert np.isfinite(float(np.asarray(lv)))
    img_s = batch / dt
    # ResNet-50 @224: ~4.09 GFLOP fwd/image; train ~ 3x fwd
    from bench import RN50_FWD_FLOPS_PER_IMG
    mfu = (3 * RN50_FWD_FLOPS_PER_IMG * img_s) / 197e12
    print(json.dumps({"batch": batch, "img_s": round(img_s, 1), "mfu": round(mfu, 4)}))

if __name__ == "__main__":
    run(int(sys.argv[1]))
