"""Open-loop served-load driver for the serving runtime (ISSUE 7).

Open-loop means arrivals do NOT wait for the system: request i arrives at
its scheduled offset (exponential inter-arrival at `rate` req/s) whether or
not the engine is keeping up — the only honest load model for "heavy
traffic from millions of users" (a closed loop self-throttles and hides
queueing collapse). Per-request stamps (arrival, first token, completion)
feed the shared tools/_timing.py percentile protocol, so p50/p99 here and
in the bench.py `serving` block are the same arithmetic.

    python tools/_serve_ab.py                       # default rate sweep
    python tools/_serve_ab.py --rates 4,16,64 --requests 64
    python tools/_serve_ab.py --pool-pages 64       # pressure the pool

Each rate prints one JSON line; the last line is the sweep summary.
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import time
from collections import deque

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np  # noqa: E402

from tools import _timing  # noqa: E402


def synth_workload(n_requests: int, vocab_size: int, seed: int,
                   prompt_lens=(4, 24), max_new: int = 8,
                   rate: float = 8.0) -> list:
    """[(arrival_offset_s, prompt, max_new)] — seeded, so a rate's workload
    replays identically across runs/arms."""
    rng = np.random.default_rng(seed)
    arrivals = np.cumsum(rng.exponential(1.0 / rate, n_requests))
    lo, hi = prompt_lens
    out = []
    for i in range(n_requests):
        plen = int(rng.integers(lo, hi + 1))
        prompt = rng.integers(1, vocab_size, plen).tolist()
        out.append((float(arrivals[i]), prompt, int(max_new)))
    return out


def run_open_loop(engine, workload, max_steps: int = 200_000) -> dict:
    """Drive one engine through one workload; returns the serving metrics
    block (served tokens/s, p50/p99 request + first-token latency, pool
    occupancy, and the zero-leak page count)."""
    pending = deque(sorted(workload))
    rids = []
    t0 = time.perf_counter()
    steps = 0
    while pending or engine.has_work():
        now = time.perf_counter() - t0
        while pending and pending[0][0] <= now:
            _, prompt, max_new = pending.popleft()
            rids.append(engine.submit(prompt, max_new))
        if engine.has_work():
            engine.step()
        elif pending:
            time.sleep(min(0.002, max(0.0, pending[0][0] - now)))
        steps += 1
        if steps > max_steps:
            raise RuntimeError(f"open loop did not drain in {max_steps} "
                               f"iterations")
    wall = time.perf_counter() - t0

    reqs = [engine.requests[r] for r in rids]
    done = [r for r in reqs if r.state == "finished"]
    lat = [r.t_done - r.arrival_t for r in done]
    ttft = [r.t_first_token - r.arrival_t for r in done
            if r.t_first_token is not None]
    served_tokens = sum(r.n_generated for r in done)
    st = engine.stats
    occ_mean = (st["occupancy_sum"] / st["occupancy_n"]
                if st["occupancy_n"] else 0.0)
    return {
        "requests": len(reqs),
        "finished": len(done),
        "aborted": sum(1 for r in reqs if r.state == "aborted"),
        "served_tokens": served_tokens,
        "wall_s": round(wall, 4),
        "served_tokens_per_sec": round(served_tokens / wall, 2) if wall else 0.0,
        "request_latency": _timing.latency_stats(lat),
        "first_token_latency": _timing.latency_stats(ttft),
        "kv_pool_occupancy_mean": round(occ_mean, 4),
        "kv_pool_occupancy_peak": round(
            st["peak_pages_in_use"] / engine.pool.num_pages, 4),
        "kv_pages_leaked": engine.pool.num_pages - engine.pool.free_count,
        "decode_steps": st["decode_steps"],
        "prefills": st["prefills"],
        "preemptions": st["preemptions"],
        "decode_compile_buckets": len(st["decode_signatures"]),
        "prefill_compile_buckets": len(st["prefill_signatures"]),
    }


def main():
    from paddle_tpu.serving import DecoderConfig, ServingEngine, decoder_tiny

    import jax

    on_tpu = jax.devices()[0].platform == "tpu"
    ap = argparse.ArgumentParser()
    ap.add_argument("--rates", default="4,16,64" if on_tpu else "8,32",
                    help="comma list of arrival rates (req/s)")
    ap.add_argument("--requests", type=int, default=64 if on_tpu else 16)
    ap.add_argument("--max-new", type=int, default=32 if on_tpu else 6)
    ap.add_argument("--page-size", type=int, default=None)
    ap.add_argument("--pool-pages", type=int, default=None)
    ap.add_argument("--max-inflight", type=int, default=None)
    ap.add_argument("--policy", default=None)
    ap.add_argument("--seed", type=int, default=0)
    args = ap.parse_args()

    if on_tpu:
        cfg = DecoderConfig(vocab_size=30522, hidden_size=512, num_layers=6,
                            num_heads=8, ffn_size=2048, max_position=1024)
        prompt_lens = (16, 128)
    else:
        cfg = decoder_tiny()
        prompt_lens = (4, 24)

    summary = {}
    for rate in [float(r) for r in args.rates.split(",") if r]:
        engine = ServingEngine(cfg, page_size=args.page_size,
                               pool_pages=args.pool_pages,
                               max_inflight=args.max_inflight,
                               policy=args.policy, seed=args.seed)
        wl = synth_workload(args.requests, cfg.vocab_size, args.seed,
                            prompt_lens=prompt_lens, max_new=args.max_new,
                            rate=rate)
        out = run_open_loop(engine, wl)
        out["rate_req_s"] = rate
        print(json.dumps(out), flush=True)
        summary[str(rate)] = out["served_tokens_per_sec"]
    print(json.dumps({"sweep": "serve_ab", "served_tok_s_by_rate": summary}),
          flush=True)


if __name__ == "__main__":
    main()
